/*
 * tpurm internals.  Not installed; the public surface is include/tpurm/.
 *
 * Locking order (reference pattern: uvm_lock.h:31+ — order documented as
 * data, asserted at runtime in debug builds via tpuLockTrack*):
 *   1. g_rm.lock        (object model / attach state)
 *   2. UVM VA space lock
 *   3. UVM VA block lock
 *   4. UVM PMM / tier-arena lock
 *   5. cxl table lock
 *   6. pin accounting lock
 *   7. per-channel lock
 *   8. journal/counters
 */
#ifndef TPURM_INTERNAL_H
#define TPURM_INTERNAL_H

#include <pthread.h>
#include <stdatomic.h>
#include <stdbool.h>
#include <stdint.h>
#include <time.h>

#include "tpurm/abi.h"
#include "tpurm/status.h"
#include "tpurm/tpurm.h"

/* ------------------------------------------------------------ monotonic ns
 *
 * THE process clock: journal records, injection decisions, trace spans
 * and fault latencies all stamp with this, so the timelines are
 * directly comparable (previously diag.c, ici.c and uvm_tier.c each
 * carried a private copy). */
static inline uint64_t tpuNowNs(void)
{
    struct timespec ts;
    clock_gettime(CLOCK_MONOTONIC, &ts);
    return (uint64_t)ts.tv_sec * 1000000000ull + (uint64_t)ts.tv_nsec;
}

/* Crash-dump raw hooks (journal.c) read mutex-guarded fields WITHOUT
 * the lock — a signal handler cannot take it, and torn values are
 * benign by the bundle's best-effort contract.  Annotate those
 * readers so TSan doesn't demand every writer become an atomic. */
#if defined(__has_feature)
#  if __has_feature(thread_sanitizer)
#    define TPU_NO_TSAN __attribute__((no_sanitize("thread")))
#  endif
#endif
#ifndef TPU_NO_TSAN
#  if defined(__SANITIZE_THREAD__)
#    define TPU_NO_TSAN __attribute__((no_sanitize_thread))
#  else
#    define TPU_NO_TSAN
#  endif
#endif

/* ------------------------------------------------------------- histogram
 *
 * Log-linear HDR-style latency histogram (trace.c): values below
 * 2^SUB_BITS land in exact unit buckets; above that, each power of two
 * splits into 2^SUB_BITS linear sub-buckets, so the relative bucket
 * width is <= 2^-SUB_BITS (~0.8%) across the full uint64 range.
 * Recording is three relaxed atomic adds — safe on any hot path. */
#define TPU_HIST_SUB_BITS 7
#define TPU_HIST_SUB      (1u << TPU_HIST_SUB_BITS)
#define TPU_HIST_BUCKETS  ((64 - TPU_HIST_SUB_BITS + 1) * TPU_HIST_SUB)

typedef struct {
    _Atomic uint64_t count;
    _Atomic uint64_t sum;
    _Atomic uint64_t buckets[TPU_HIST_BUCKETS];
} TpuHist;

void     tpuHistRecord(TpuHist *h, uint64_t v);
/* Batched: n samples of the same value (per-tenant SLO feed). */
void     tpuHistRecordN(TpuHist *h, uint64_t v, uint64_t n);
uint64_t tpuHistQuantile(const TpuHist *h, double q);
uint64_t tpuHistBucketLow(uint32_t idx);   /* bucket lower bound value */
void     tpuHistReset(TpuHist *h);

/* The trace subsystem's per-site histogram (trace.h site ids).  The
 * fault engine feeds FAULT_LATENCY/WAKE/SERVICE unconditionally (they
 * back the UvmFaultStats ABI); other sites fill while armed. */
TpuHist *tpurmTraceHistRef(uint32_t site);

/* Bounded render cursor shared by the procfs and trace renderers
 * (appends are silently truncated at cap-1; off never exceeds it). */
typedef struct {
    char *buf;
    size_t cap, off;
} TpuCur;

void tpuCurf(TpuCur *c, const char *fmt, ...)
    __attribute__((format(printf, 2, 3)));

/* Prometheus histogram rows (bucket/sum/count; caller owns # TYPE):
 * one export-boundary table for every tpurm_*_ns family (trace.c);
 * `labels` ("tenant=\"3\"") prefixes the le label, NULL = unlabeled. */
void tpuPromHistRows(TpuCur *c, const TpuHist *h, const char *family,
                     const char *labels);

/* ------------------------------------------------------------- lock order */

enum tpu_lock_order {
    TPU_LOCK_RM = 1,
    TPU_LOCK_UVM_VASPACE = 2,
    TPU_LOCK_UVM_BLOCK = 3,
    TPU_LOCK_UVM_PMM = 4,
    TPU_LOCK_CXL = 5,
    TPU_LOCK_PIN = 6,
    TPU_LOCK_CHANNEL = 7,
    TPU_LOCK_DIAG = 8,
};

/* Debug lock-order tracker (no-ops in release builds). */
void tpuLockTrackAcquire(int order, const char *name);
void tpuLockTrackRelease(int order, const char *name);

/* ---------------------------------------------------------------- journal */

typedef enum {
    TPU_LOG_DEBUG = 0,
    TPU_LOG_INFO = 1,
    TPU_LOG_WARN = 2,
    TPU_LOG_ERROR = 3,
} TpuLogLevel;

void tpuLog(TpuLogLevel level, const char *subsys, const char *fmt, ...)
    __attribute__((format(printf, 3, 4)));

/* Minimum level tpuLog processes (TPUMEM_LOG_LEVEL, default DEBUG so
 * everything flows as before; registry-generation cached). */
TpuLogLevel tpuLogGate(void);

/* Leveled logging front end (NvLog/NV_PRINTF analog): the ONE spelling
 * for engine diagnostics.  Gated at the call site so a raised
 * TPUMEM_LOG_LEVEL skips the formatting entirely; the tpuLog sink
 * mirrors WARN+ into the tpubox binary journal, so printf debugging
 * and the black box can never disagree. */
#define TPU_LOG(level, subsys, ...)                                     \
    do {                                                                \
        if ((int)(level) >= (int)tpuLogGate())                          \
            tpuLog((level), (subsys), __VA_ARGS__);                     \
    } while (0)
void tpuCounterAdd(const char *name, uint64_t delta);
_Atomic uint64_t *tpuCounterRef(const char *name);
void tpuCounterAddScoped(const char *name, uint32_t devInst,
                         uint64_t delta);
size_t tpuCountersDump(char *buf, size_t bufSize);
/* Insertion-order iteration over every registered counter (metrics
 * exposition). */
void tpuCountersForEach(void (*fn)(const char *name, uint64_t value,
                                   void *ctx), void *ctx);

/* --------------------------------------------------------------- registry */

/* Env-backed config: TPUMEM_<KEY> (decimal or 0x hex), else default. */
uint64_t tpuRegistryGet(const char *key, uint64_t defval);

/* Hot-path registry reads go through a per-site cache: tpuRegistryGet is
 * a getenv (linear environ scan) and the fault-service path was paying
 * several per fault.  The cache re-resolves only when the registry
 * GENERATION changes; code that rewrites TPUMEM_* at runtime (in-module
 * tests flipping knobs) must call tpuRegistryBump() afterwards.  The
 * reference's registry is likewise snapshotted, not re-read per op
 * (NVreg_* parsed at module load). */
uint64_t tpuRegistryGen(void);
void tpuRegistryBump(void);
/* Runtime knob flip: setenv/unsetenv (value NULL) under the registry
 * lock + generation bump — the only safe way to rewrite TPUMEM_* once
 * background pollers (rc/reset watchdogs) exist.  NOTE the asymmetry
 * with tpuRegistryGet: Get takes the bare key and prefixes/upcases it
 * ("reset_hang_timeout_ms" -> TPUMEM_RESET_HANG_TIMEOUT_MS); Set takes
 * the FULL environment-variable name verbatim, because callers also
 * use it for non-registry env (and the bare-key spelling would
 * silently set a name no reader consults). */
void tpuRegistrySet(const char *key, const char *value);

typedef struct {
    _Atomic uint64_t gen;             /* registry gen + 1; 0 = empty */
    _Atomic uint64_t val;
} TpuRegCache;

static inline uint64_t tpuRegCacheGet(TpuRegCache *c, const char *key,
                                      uint64_t defval)
{
    uint64_t g = tpuRegistryGen() + 1;
    if (atomic_load_explicit(&c->gen, memory_order_acquire) == g)
        return atomic_load_explicit(&c->val, memory_order_relaxed);
    uint64_t v = tpuRegistryGet(key, defval);
    atomic_store_explicit(&c->val, v, memory_order_relaxed);
    atomic_store_explicit(&c->gen, g, memory_order_release);
    return v;
}

/* ----------------------------------------------------------- tpubox
 *
 * Cross-module plumbing for the black-box journal + crash dumper
 * (journal.c; public surface in tpurm/journal.h). */

/* Async-signal-safe fd-backed formatting cursor: the crash dumper and
 * the last-gasp SIGSEGV handler format through these instead of stdio
 * (no malloc, no locks; write(2) only). */
typedef struct TpuDumpCur {
    int fd;
    size_t off;
    int err;                     /* real write(2) failure             */
    int trunc;                   /* dump.write inject hit: bundle cut */
    char buf[512];
} TpuDumpCur;

void tpuDumpFlush(TpuDumpCur *c);
void tpuDumpStr(TpuDumpCur *c, const char *s);
void tpuDumpU64(TpuDumpCur *c, uint64_t v);
void tpuDumpHex(TpuDumpCur *c, uint64_t v);

/* Raw bundle sections: LOCK-FREE snapshots (atomic/plain loads only —
 * the dumper may run from a signal handler while the subsystem's own
 * mutex is held by the interrupted thread).  Benign races read torn
 * but never fault. */
void tpurmHealthDumpRaw(TpuDumpCur *c);    /* health table + open vac txns */
void tpurmMemringDumpRaw(TpuDumpCur *c);   /* per-ring frontier/claimed    */
void tpurmShieldDumpRaw(TpuDumpCur *c);    /* retirement list              */

/* Render hooks (procfs.c). */
void tpurmJournalRenderText(TpuCur *c);
void tpurmJournalRenderProm(TpuCur *c);

/* ------------------------------------------------------ broker UVM server */

/* Owner side of a forwarded remote CPU fault (broker BR_OP_UVM_RFAULT). */
TpuStatus uvmRemoteFaultService(uint64_t addr, uint64_t len, int isWrite);
/* Owner side of remote-backing resolution (BR_OP_UVM_BACKING). */
TpuStatus uvmRangeBackingForAddr(uint64_t ownerAddr, int *fdOut,
                                 uint64_t *fdOffset, uint64_t *rangeStart,
                                 uint64_t *rangeSize);

/* ------------------------------------------------------ broker UVM client */

/* Fetch the owner range's host-backing memfd + bounds for ownerAddr
 * (caller owns *fdOut).  Engine-host side resolves via
 * uvmRangeBackingForAddr. */
int tpurmBrokerUvmBacking(uint64_t ownerAddr, int *fdOut,
                          uint64_t *fdOffset, uint64_t *rangeStart,
                          uint64_t *rangeSize);
/* Forward a CPU fault on owner memory; returns the service TpuStatus
 * (engine-host side runs uvmRemoteFaultService). */
int tpurmBrokerUvmFault(uint64_t ownerAddr, uint64_t len, int isWrite);

/* ---------------------------------------------------------------- memdesc */

typedef enum {
    TPU_APERTURE_SYSMEM = 0,   /* host memory                        */
    TPU_APERTURE_HBM = 1,      /* device HBM arena                   */
    TPU_APERTURE_CXL = 2,      /* pinned CXL-tier memory             */
} TpuAperture;

/* Physical-layout descriptor (reference: MEMORY_DESCRIPTOR, mem_desc.c).
 * Pages are (addr,len)-coalesced extents so the copy loop iterates extents
 * exactly like ce_utils.c:646-661 iterates contiguous runs. */
typedef struct TpuMemDesc {
    TpuAperture aperture;
    uint64_t size;
    uint64_t pageSize;         /* 4K or 2M */
    uint32_t extentCount;
    struct { uint64_t base; uint64_t len; } *extents;
    bool contiguous;
} TpuMemDesc;

TpuStatus tpuMemdescCreateContig(TpuMemDesc **out, TpuAperture ap,
                                 uint64_t base, uint64_t size,
                                 uint64_t pageSize);
TpuStatus tpuMemdescCreatePages(TpuMemDesc **out, TpuAperture ap,
                                const uint64_t *pageAddrs, uint32_t pageCount,
                                uint64_t pageSize);
void      tpuMemdescDestroy(TpuMemDesc *md);
/* Resolve an offset into (host pointer, run length) given the device whose
 * HBM arena backs TPU_APERTURE_HBM. */
TpuStatus tpuMemdescResolve(const TpuMemDesc *md, TpurmDevice *dev,
                            uint64_t offset, void **ptr, uint64_t *runLen);

/* ----------------------------------------------------------------- device */

#define TPU_CE_POOL_MAX 8

typedef struct TpuMsgq TpuMsgq;

struct TpurmDevice {
    uint32_t inst;             /* device instance (0..n-1)      */
    uint32_t devId;            /* probed id on the wire         */
    bool attached;
    bool lost;
    void *hbmBase;             /* coherent shadow of device HBM  */
    uint64_t hbmSize;
    int hbmFd;                 /* memfd backing the arena (-1: anon) */
    TpurmChannel *ce;          /* legacy shared CE channel (== cePool[0]) */
    /* CE channel pool (reference: channel pools per CE type,
     * uvm_channel.c): large copies stripe across the pool so the
     * worker threads memcpy in parallel.  cePoolSize is atomic because
     * tpuce (ce.c) GROWS the pool at runtime while rc.c/procfs.c read
     * it locklessly — the seq_cst store publishes the cePool[i] write
     * that precedes it. */
    TpurmChannel *cePool[TPU_CE_POOL_MAX];
    _Atomic uint32_t cePoolSize;
    /* Real-arena backend (hbm.c): when registered, engine writes to the
     * shadow publish dirty ranges on mirrorq for the JAX runtime. */
    _Atomic int arenaReal;
    /* Set when a dirty range could not be queued (mirrorq full): the
     * consumer must treat the whole arena as dirty at its next
     * coherence point.  Never blocks the engine. */
    _Atomic int mirrorOverflow;
    TpuMsgq *mirrorq;
    pthread_mutex_t hbmLock;
    /* Chip-dirty page bitmap (1 bit per 4 KB arena page): set when a
     * jitted computation wrote the on-chip arena (the chip copy is
     * newer than the shadow), cleared when the consumer downloads the
     * pages back into the shadow.  chipDirtyPages gates the read-path
     * check to one atomic load when no chip writes exist. */
    _Atomic(uint64_t) *chipDirty;
    _Atomic uint64_t chipDirtyPages;
};

/* hbm.c engine hook: publish [dst, dst+bytes) as dirty if it lies in a
 * real-registered device's shadow arena. */
void tpuHbmMirrorNotify(const void *dst, uint64_t bytes);

/* hbm.c engine hook: make [src, src+bytes) coherent for a host-side
 * read.  If the span lies in a real arena and intersects chip-dirty
 * pages (a jitted computation wrote them), blocks until the consumer
 * has downloaded those pages into the shadow.  TPU_OK when there is
 * nothing to do; a non-OK status (dead consumer, queue shutdown) means
 * the shadow is STALE and the caller must fail the copy rather than
 * serve it.  Reference: direction-agnostic copies, mem_utils.c:567 /
 * ce_utils.c:571; eviction reads real vidmem,
 * kernel-open/nvidia-uvm/uvm_va_block.c:4660. */
TpuStatus tpuHbmCoherentForRead(const void *src, uint64_t bytes);

void tpuDeviceGlobalInit(void);     /* idempotent */
TpurmDevice *tpuDeviceByDevId(uint32_t devId);

/* -------------------------------------------------------------------- cxl */

typedef struct TpuCxlBuffer TpuCxlBuffer;

TpuStatus tpuCxlSystemInfo(uint32_t *numDevices, uint32_t *numMemDevices,
                           bool *linkUp, uint32_t *cxlVersion);
TpuStatus tpuCxlRegister(uint64_t baseAddress, uint64_t size,
                         uint32_t cxlVersion, uint64_t *outHandle);
TpuStatus tpuCxlUnregister(uint64_t handle);
TpuStatus tpuCxlDmaRequest(TpurmDevice *dev, uint64_t handle,
                           uint64_t gpuOffset, uint64_t cxlOffset,
                           uint64_t size, uint32_t flags,
                           uint32_t hClient, uint32_t *outTransferId);
/* Test/introspection surface. */
uint32_t  tpuCxlRegisteredCount(void);
uint64_t  tpuCxlPinnedBytes(void);

/* ---------------------------------------------------------------- uvm fd  */

/* Per-fd UVM state management for /dev/nvidia-uvm pseudo-fds
 * (implemented in uvm/uvm_ioctl.c). */
void *tpuUvmFdOpen(void);
void  tpuUvmFdClose(void *state);
int   tpuUvmFdIoctl(void *state, unsigned long request, void *argp);
/* mmap surface (reference uvm_mmap, uvm.c:792): allocate a managed
 * range through a uvm fd; the hook frees it on interposed munmap
 * (returns 1 when it consumed the call). */
int   tpuUvmFdMmap(void *state, uint64_t length, void **outBase);
int   tpuUvmMunmapHook(void *addr, uint64_t length);
void  uvmMmapRegistryOnRangeDestroy(uint64_t base);

/* -------------------------------------------------------------- transfer  */

/* memmgrMemCopy analog: copy between two memdescs through the device's
 * CE POOL (pushes stripe round-robin across the pool's channels),
 * splitting per contiguous extent and clamping each submission
 * (reference: mem_utils.c:567, ce_utils.c:571,646-661; clamp
 * p2p_cxl.c:617-621).  async records every push's dependency into
 * outTracker; sync waits them all. */
TpuStatus tpuMemCopy(TpurmDevice *dev, TpuMemDesc *dst, uint64_t dstOff,
                     TpuMemDesc *src, uint64_t srcOff, uint64_t size,
                     bool async, TpuTracker *outTracker);

/* ------------------------------------------------- RM event notification
 * (event.c — NV0005 analog; see abi.h for the wire structs.) */

TpuStatus tpurmEventCreate(uint32_t hClient, uint32_t handle,
                           uint32_t devInst, uint32_t notifyIndex,
                           uint64_t userPtr);
void      tpurmEventDestroy(uint32_t hClient, uint32_t handle);
void      tpurmEventDestroyClient(uint32_t hClient);
TpuStatus tpurmEventSetNotification(uint32_t hClient, uint32_t devInst,
                                    uint32_t notifyIndex, uint32_t action);
/* hClient scope: 0 = broadcast to every armed listener; nonzero fires
 * only that client's events (completion-style notifiers, where the
 * condition belongs to the REQUESTING client — a concurrent client's
 * identical notifier must not hear someone else's completion). */
void      tpurmEventFireScoped(uint32_t devInst, uint32_t notifyIndex,
                               uint32_t hClient, uint32_t info32,
                               uint16_t info16);
TpuStatus tpurmEventNotifyTrackerScoped(const TpuTracker *deps,
                                        uint32_t devInst,
                                        uint32_t notifyIndex,
                                        uint32_t hClient, uint32_t info32,
                                        uint16_t info16);
void      tpurmEventFire(uint32_t devInst, uint32_t notifyIndex,
                         uint32_t info32, uint16_t info16);
bool      tpurmEventArmed(uint32_t devInst, uint32_t notifyIndex);
/* True when hClient itself holds an armed listener at the notifier. */
bool      tpurmEventArmedForClient(uint32_t devInst, uint32_t notifyIndex,
                                   uint32_t hClient);
TpuStatus tpurmEventNotifyTracker(const TpuTracker *deps, uint32_t devInst,
                                  uint32_t notifyIndex, uint32_t info32,
                                  uint16_t info16);
void      tpurmEventQuiesce(void);
void      tpurmEventQuiesceChannel(TpurmChannel *ch);
void      tpurmChannelEvRef(TpurmChannel *ch);
void      tpurmChannelEvUnref(TpurmChannel *ch);
uint32_t  tpurmChannelEvRefs(TpurmChannel *ch);

/* ------------------------------------------------- multi-process broker */

TpuStatus tpurmBrokerServe(const char *path);
int  tpurmBrokerOpen(const char *path);
int  tpurmBrokerClose(int fd);
int  tpurmBrokerIoctl(int fd, unsigned long request, void *argp);
bool tpurmBrokerIsRemoteFd(int fd);
/* Heartbeat round trip (stale-client reaper: registry
 * broker_heartbeat_timeout_ms). */
int  tpurmBrokerPing(void);
/* Forward an evacuation request (BR_OP_VAC) to the engine host.
 * TPU_ERR_NOT_SUPPORTED when this process is not a broker client —
 * the caller falls back to the in-process tpurmHealthEvacRequest. */
TpuStatus tpurmBrokerVacRequest(uint32_t devInst, uint32_t target);

/* ------------------------------------------------------------- tpuvac
 *
 * Render hooks for the health subsystem (health.c; public surface in
 * tpurm/health.h). */

void tpurmHealthRenderProm(TpuCur *c);
void tpurmHealthRenderTable(TpuCur *c);

/* ------------------------------------------------------------- tpuflow
 *
 * Render hooks for the request-flow / SLO subsystem (flow.c; public
 * surface in tpurm/flow.h). */

void tpurmFlowRenderProm(TpuCur *c);
void tpurmFlowRenderTable(TpuCur *c);

/* ----------------------------------------------------------- tpushield
 *
 * Render hooks for the page-integrity subsystem (shield.c; public
 * surface in tpurm/shield.h). */

void tpurmShieldRenderProm(TpuCur *c);
void tpurmShieldRenderTable(TpuCur *c);

/* ------------------------------------------------- robust channel RC */

/* (Fault kinds TPU_RC_* live in tpurm.h beside the public notifier.) */

void tpuRcInit(void);
void tpuRcPostFault(TpurmChannel *ch, uint64_t rcId, uint64_t value,
                    uint32_t kind);
/* Reset-and-replay: clear every latched channel error (recovery loops
 * call this before re-issuing failed work); returns latches cleared.
 * Failure attribution is unaffected (tpurmChannelWaitRange history). */
uint32_t tpuRcRecoverAll(void);
/* True while ch carries a latched (unreset) error. */
bool tpurmChannelErrorPending(TpurmChannel *ch);
/* Bounded-backoff sleep for recovery retries: attempt 0,1,2... sleeps
 * base<<attempt microseconds (registry recover_backoff_us, default
 * 100). */
void tpuRecoverBackoff(uint32_t attempt);
void tpuRcChannelRegister(TpurmChannel *ch, uint64_t rcId);
void tpuRcChannelUnregister(TpurmChannel *ch);
void tpuRcForEachChannel(void (*fn)(TpurmChannel *ch, uint64_t completed,
                                    uint64_t pending, void *arg),
                         void *arg);
/* Channel-side delivery (called by the RC service under its registry
 * lock): invoke the channel's error notifier + apply recovery policy. */
void tpurmChannelRcDeliver(TpurmChannel *ch, uint64_t value,
                           uint32_t kind);
/* Watchdog probe: completed tracker value + outstanding push count. */
void tpurmChannelProgress(TpurmChannel *ch, uint64_t *completed,
                          uint64_t *pendingDepth);

/* ------------------------------------------------------------- tpuce
 *
 * The multi-channel copy-engine subsystem (ce.h / ce.c) replaced the
 * old per-callsite TpuCeStriper fan-out: every bulk copy path submits
 * through a TpuCeBatch now.  These are the cross-module hooks. */

/* Executor-side compression stage (ce.c): applied by the channel
 * executor in place of memmove for xform-tagged segments. */
void tpuCeXformExec(uint32_t xform, void *dst, const void *src,
                    uint64_t bytes);

/* Attach tpuce per-channel accounting to a DMA channel: the executor
 * adds executed bytes / busy-ns to the given counter cells and tags
 * its ce.stripe trace spans with ceIdx.  NULL counters detach. */
void tpurmChannelSetCeAcct(TpurmChannel *ch, _Atomic uint64_t *bytesCtr,
                           _Atomic uint64_t *busyCtr, uint32_t ceIdx);

/* ------------------------------------------------------------ tpureset
 *
 * Cross-module hooks the full-device reset engine (reset.c, public
 * surface in tpurm/reset.h) uses to quiesce and monitor the pools. */

/* Park every memring worker pool: no new SQE claims; waits (bounded)
 * for claimed ops to retire.  Published-but-unclaimed SQEs stay queued
 * and re-issue after unpark (idempotent replay).  TPU_OK when all
 * in-flight work drained inside timeoutNs, TPU_ERR_RETRY_EXHAUSTED
 * when something is still in flight (hung — the caller proceeds and
 * generation fencing rejects the zombie completion). */
TpuStatus tpurmMemringParkAll(uint64_t timeoutNs);
void      tpurmMemringUnparkAll(void);
/* True while the park gate is held (reset quiesce window). */
bool      tpurmMemringSpineParked(void);

/* Hung-op watchdog scan: for every ring with in-flight work and no
 * completion progress for hangNs, take the next escalation-ladder rung
 * (1 = doorbell nudge, 2 = channel RC reset, 3 = request a full device
 * reset — performed by the CALLER; the ladder saturates afterwards
 * until the ring progresses).  Returns the highest rung taken. */
uint32_t  tpurmMemringWatchdogScan(uint64_t hangNs);

/* Sharded-spine introspection (tests/bench): the live internal shard
 * count, and a shard's ring (NULL past count or when that shard failed
 * to create).  Both force spine init. */
uint32_t tpurmMemringInternalShards(void);
struct TpuMemring *tpurmMemringInternalShardRing(uint32_t shard);

/* Pin the calling thread to a distinct CPU, round-robin over the
 * process affinity mask (NUMA/CPU-aware worker placement for spine
 * workers and tpuce channel executors).  Deliberately a no-op when
 * sched_getaffinity shows <= 2 CPUs (nothing to spread over — forced
 * placement only hurts there) or registry cpu_pin=0. */
void tpuCpuPinThread(const char *role);

/* One-time CRC table + hardware-feature probe for the tpushield CRC32C
 * (idempotent; a library constructor and tpuRcInit both call it so the
 * per-seal hot path carries no once-check). */
void tpurmShieldCrcInit(void);

/* Drain every device's tpuce manager (fence semantics per manager). */
void tpuCeDrainAll(void);

/* Retrain every device's ICI links (reset phase); returns links that
 * ended ACTIVE.  Counted as ici_reset_retrains. */
uint32_t tpuIciRetrainAll(void);

#endif /* TPURM_INTERNAL_H */
