/*
 * ICI — inter-chip interconnect manager (see include/tpurm/ici.h).
 *
 * Torus topology over the enumerated devices: registry "ici_torus_x" /
 * "ici_torus_y" pick the dims (default 1-D ring).  Links are
 * bidirectional neighbor pairs with a DOWN->TRAINING->ACTIVE state
 * machine (reference: nvlink core library link init/training,
 * src/common/nvlink/), traffic accounting, fault injection, and
 * dimension-ordered routing that detours around FAILED links when the
 * other dimension offers a path (the reference's NVSwitch routing
 * tables collapse to this — no switch ASIC on ICI).
 *
 * Peer apertures implement the P2P substrate over trained links: HBM
 * window copies between devices through the local CE channel pool, with
 * per-hop traffic accounted on every traversed link.
 */
#define _GNU_SOURCE
#include "internal.h"
#include "tpurm/ce.h"
#include "tpurm/flow.h"
#include "tpurm/health.h"
#include "tpurm/ici.h"
#include "tpurm/inject.h"
#include "tpurm/journal.h"
#include "tpurm/memring.h"
#include "tpurm/shield.h"
#include "tpurm/trace.h"
#include "tpurm/uvm.h"

#include <stdlib.h>
#include <string.h>

#define MAX_ICI_DEVICES 16
#define MAX_LINKS_PER_DEV 4     /* 2 dims x 2 directions */

typedef struct {
    uint32_t peerInst;
    uint32_t state;             /* TpuIciLinkState */
    uint64_t trainedAtNs;
    uint64_t bytesTx, bytesRx;
    uint32_t errorCount;
    uint8_t dim;                /* 0 = x, 1 = y */
    int8_t dir;                 /* +1 / -1 around the torus */
    /* Flap recovery state: softFail marks failures from the injection
     * framework (transient link flaps) that the lazy retrain policy may
     * recover; admin failures via tpuIciInjectLinkFailure stay FAILED
     * until an explicit reset (tests rely on sticky detours). */
    bool softFail;
    uint64_t failedAtNs;
} IciLink;

static struct {
    pthread_mutex_t lock;
    bool ready;
    uint32_t count, dimX, dimY;
    IciLink links[MAX_ICI_DEVICES][MAX_LINKS_PER_DEV];
    uint32_t linkCount[MAX_ICI_DEVICES];
} g_ici = { .lock = PTHREAD_MUTEX_INITIALIZER };

static void train_links_locked(uint32_t devInst);
static TpuStatus next_hop_locked(uint32_t src, uint32_t dst,
                                 uint32_t *next);

static void ici_add_link(uint32_t dev, uint32_t peer, uint8_t dim, int8_t dir)
{
    uint32_t n = g_ici.linkCount[dev];
    if (n >= MAX_LINKS_PER_DEV || peer == dev)
        return;
    /* Two-device rings would create duplicate +1/-1 links. */
    for (uint32_t i = 0; i < n; i++)
        if (g_ici.links[dev][i].peerInst == peer &&
            g_ici.links[dev][i].dim == dim)
            return;
    g_ici.links[dev][n].peerInst = peer;
    g_ici.links[dev][n].state = TPU_ICI_LINK_DOWN;
    g_ici.links[dev][n].dim = dim;
    g_ici.links[dev][n].dir = dir;
    g_ici.linkCount[dev] = n + 1;
}

void tpuIciInit(void)
{
    pthread_mutex_lock(&g_ici.lock);
    if (g_ici.ready) {
        pthread_mutex_unlock(&g_ici.lock);
        return;
    }
    tpuDeviceGlobalInit();
    uint32_t n = tpurmDeviceCount();
    if (n > MAX_ICI_DEVICES)
        n = MAX_ICI_DEVICES;
    uint32_t dimX = (uint32_t)tpuRegistryGet("ici_torus_x", n);
    uint32_t dimY = (uint32_t)tpuRegistryGet("ici_torus_y", 1);
    if (dimX * dimY != n) {     /* fall back to a ring */
        dimX = n;
        dimY = 1;
    }
    g_ici.count = n;
    g_ici.dimX = dimX;
    g_ici.dimY = dimY;

    for (uint32_t d = 0; d < n; d++) {
        uint32_t x = d % dimX, y = d / dimX;
        if (dimX > 1) {
            ici_add_link(d, y * dimX + (x + 1) % dimX, 0, +1);
            ici_add_link(d, y * dimX + (x + dimX - 1) % dimX, 0, -1);
        }
        if (dimY > 1) {
            ici_add_link(d, ((y + 1) % dimY) * dimX + x, 1, +1);
            ici_add_link(d, ((y + dimY - 1) % dimY) * dimX + x, 1, -1);
        }
    }
    /* Links train at init by default (reference: boot-time link init);
     * registry ici_auto_train=0 leaves them DOWN for tests.  Training
     * happens BEFORE ready is published so no concurrent first caller
     * can route over still-DOWN links. */
    if (tpuRegistryGet("ici_auto_train", 1))
        for (uint32_t d = 0; d < n; d++)
            train_links_locked(d);
    g_ici.ready = true;
    TPU_LOG(TPU_LOG_INFO, "ici", "topology: %ux%u torus, %u device(s)",
           dimX, dimY, n);
    pthread_mutex_unlock(&g_ici.lock);
}

uint32_t tpuIciLinkCount(uint32_t devInst)
{
    tpuIciInit();
    if (devInst >= g_ici.count)
        return 0;
    return g_ici.linkCount[devInst];
}

TpuStatus tpuIciLinkInfo(uint32_t devInst, uint32_t link,
                         TpuIciLinkInfo *out)
{
    tpuIciInit();
    if (!out || devInst >= g_ici.count ||
        link >= g_ici.linkCount[devInst])
        return TPU_ERR_INVALID_ARGUMENT;
    pthread_mutex_lock(&g_ici.lock);
    IciLink *l = &g_ici.links[devInst][link];
    out->peerInst = l->peerInst;
    out->state = l->state;
    out->trainedAtNs = l->trainedAtNs;
    out->bytesTx = l->bytesTx;
    out->bytesRx = l->bytesRx;
    out->errorCount = l->errorCount;
    pthread_mutex_unlock(&g_ici.lock);
    return TPU_OK;
}

/* Find dev's link to `peer`, preferring ACTIVE; NULL if none. */
static IciLink *link_to(uint32_t dev, uint32_t peer)
{
    for (uint32_t i = 0; i < g_ici.linkCount[dev]; i++)
        if (g_ici.links[dev][i].peerInst == peer)
            return &g_ici.links[dev][i];
    return NULL;
}

static void train_links_locked(uint32_t devInst)
{
    for (uint32_t i = 0; i < g_ici.linkCount[devInst]; i++) {
        IciLink *l = &g_ici.links[devInst][i];
        if (l->state == TPU_ICI_LINK_FAILED)
            continue;
        /* DOWN -> TRAINING -> ACTIVE, and the peer's matching link
         * trains with it (links are bidirectional pairs). */
        l->state = TPU_ICI_LINK_TRAINING;
        l->state = TPU_ICI_LINK_ACTIVE;
        l->trainedAtNs = tpuNowNs();
        IciLink *back = link_to(l->peerInst, devInst);
        if (back && back->state != TPU_ICI_LINK_FAILED) {
            back->state = TPU_ICI_LINK_ACTIVE;
            back->trainedAtNs = l->trainedAtNs;
        }
        tpuCounterAdd("ici_links_trained", 1);
    }
}

TpuStatus tpuIciTrainLinks(uint32_t devInst)
{
    tpuIciInit();
    if (devInst >= g_ici.count)
        return TPU_ERR_INVALID_DEVICE;
    pthread_mutex_lock(&g_ici.lock);
    train_links_locked(devInst);
    pthread_mutex_unlock(&g_ici.lock);
    return TPU_OK;
}

/* Full-device reset hook (internal.h): retrain every device's links —
 * the reference RC path retrains NVLink after a GPU reset the same
 * way (nvlink_lib_mgmt.c re-init sequences).  Returns links ACTIVE
 * after the pass; each pass is counted so the reset MTTR can be
 * decomposed. */
uint32_t tpuIciRetrainAll(void)
{
    tpuIciInit();
    uint32_t active = 0;
    pthread_mutex_lock(&g_ici.lock);
    for (uint32_t d = 0; d < g_ici.count; d++) {
        /* Admin link failures are sticky "until reset" — this IS the
         * reset: FAILED drops to DOWN so the training pass below can
         * bring the link back (matching tpuIciResetLink per link).
         * Flap HISTORY clears too, on EVERY link: a post-reset link
         * must not inherit pre-reset softFail hysteresis (the lazy-
         * retrain backoff window, and the health scorer's flap
         * attribution) into its fresh life — the reset is the clean
         * slate the "sticky until reset" doctrine promises. */
        for (uint32_t l = 0; l < g_ici.linkCount[d]; l++) {
            IciLink *lk = &g_ici.links[d][l];
            if (lk->state == TPU_ICI_LINK_FAILED)
                lk->state = TPU_ICI_LINK_DOWN;
            lk->softFail = false;
            lk->failedAtNs = 0;
        }
    }
    for (uint32_t d = 0; d < g_ici.count; d++) {
        train_links_locked(d);
        for (uint32_t l = 0; l < g_ici.linkCount[d]; l++)
            if (g_ici.links[d][l].state == TPU_ICI_LINK_ACTIVE)
                active++;
    }
    pthread_mutex_unlock(&g_ici.lock);
    if (g_ici.count > 0)
        tpuCounterAdd("ici_reset_retrains", 1);
    return active;
}

TpuStatus tpuIciInjectLinkFailure(uint32_t devInst, uint32_t link)
{
    tpuIciInit();
    if (devInst >= g_ici.count || link >= g_ici.linkCount[devInst])
        return TPU_ERR_INVALID_ARGUMENT;
    pthread_mutex_lock(&g_ici.lock);
    IciLink *l = &g_ici.links[devInst][link];
    l->state = TPU_ICI_LINK_FAILED;
    l->softFail = false;        /* admin failure: sticky until reset */
    l->failedAtNs = tpuNowNs();
    l->errorCount++;
    IciLink *back = link_to(l->peerInst, devInst);
    if (back) {
        back->state = TPU_ICI_LINK_FAILED;
        back->softFail = false;
        back->failedAtNs = l->failedAtNs;
        back->errorCount++;
    }
    tpurmHealthNote(devInst, TPU_HEALTH_EV_LINK_FLAP);
    tpurmHealthNote(l->peerInst, TPU_HEALTH_EV_LINK_FLAP);
    TPU_LOG(TPU_LOG_WARN, "ici", "link %u.%u -> %u FAILED (injected)",
           devInst, link, l->peerInst);
    pthread_mutex_unlock(&g_ici.lock);
    return TPU_OK;
}

/* Flap the direct link along src's route toward dst (framework
 * injection site): both directions drop to FAILED with the soft flag,
 * so the lazy retrain policy recovers them.  g_ici.lock held. */
static void ici_flap_route_locked(uint32_t src, uint32_t dst)
{
    uint32_t next;
    if (next_hop_locked(src, dst, &next) != TPU_OK || next == src)
        return;
    IciLink *l = link_to(src, next);
    if (!l || l->state != TPU_ICI_LINK_ACTIVE)
        return;
    uint64_t now = tpuNowNs();
    l->state = TPU_ICI_LINK_FAILED;
    l->softFail = true;
    l->failedAtNs = now;
    l->errorCount++;
    IciLink *back = link_to(next, src);
    if (back && back->state == TPU_ICI_LINK_ACTIVE) {
        back->state = TPU_ICI_LINK_FAILED;
        back->softFail = true;
        back->failedAtNs = now;
        back->errorCount++;
    }
    tpuCounterAdd("ici_link_flaps", 1);
    tpurmJournalEmit(TPU_JREC_ICI_FLAP, src, TPU_OK, src, next);
    /* Both endpoints of a flapped link take the health hit: the scorer
     * cannot know which chip's SerDes is at fault, and evacuating
     * either end routes around the link. */
    tpurmHealthNote(src, TPU_HEALTH_EV_LINK_FLAP);
    tpurmHealthNote(next, TPU_HEALTH_EV_LINK_FLAP);
    TPU_LOG(TPU_LOG_WARN, "ici", "link flap (injected): %u -> %u FAILED",
           src, next);
}

/* Lazy retrain of soft-failed links (recovery policy: every peer copy
 * first gives flapped links a chance to come back).  `force` ignores
 * the backoff — used when a copy finds the fabric partitioned.  A
 * retrain attempt can itself fail (injection site fires again), which
 * leaves the link FAILED with a fresh backoff window.  Returns links
 * restored to ACTIVE.  g_ici.lock held. */
static uint32_t ici_retrain_soft_locked(bool force)
{
    uint64_t now = tpuNowNs();
    uint64_t tSpan = tpurmTraceBegin();
    uint64_t backoffNs = tpuRegistryGet("ici_retrain_backoff_ms", 0) *
                         1000000ull;
    uint32_t recovered = 0;
    for (uint32_t d = 0; d < g_ici.count; d++) {
        for (uint32_t i = 0; i < g_ici.linkCount[d]; i++) {
            IciLink *l = &g_ici.links[d][i];
            if (l->state != TPU_ICI_LINK_FAILED || !l->softFail)
                continue;
            if (!force && now - l->failedAtNs < backoffNs)
                continue;
            if (tpurmInjectShouldFail(TPU_INJECT_SITE_ICI_LINK)) {
                /* Retrain itself failed: stay FAILED, re-arm backoff. */
                l->failedAtNs = now;
                tpuCounterAdd("ici_retrain_failures", 1);
                tpurmJournalEmit(TPU_JREC_ICI_RETRAIN, d,
                                 TPU_ERR_RETRAIN_FAILED, d, l->peerInst);
                tpurmHealthNote(d, TPU_HEALTH_EV_RETRAIN_FAIL);
                TPU_LOG(TPU_LOG_WARN, "ici",
                       "retrain FAILED for link %u -> %u (%s)", d,
                       l->peerInst,
                       tpuStatusToString(TPU_ERR_RETRAIN_FAILED));
                continue;
            }
            l->state = TPU_ICI_LINK_ACTIVE;
            l->softFail = false;
            l->trainedAtNs = now;
            IciLink *back = link_to(l->peerInst, d);
            if (back && back->state == TPU_ICI_LINK_FAILED &&
                back->softFail) {
                back->state = TPU_ICI_LINK_ACTIVE;
                back->softFail = false;
                back->trainedAtNs = now;
            }
            recovered++;
            tpuCounterAdd("recover_link_retrains", 1);
            tpurmTraceInstant(TPU_TRACE_RECOVER_RETRAIN,
                              ((uint64_t)d << 32) | l->peerInst, 0);
            tpuCounterAdd("ici_links_trained", 1);
            TPU_LOG(TPU_LOG_WARN, "ici", "link %u -> %u retrained ACTIVE",
                   d, l->peerInst);
        }
    }
    /* Only a pass that actually restored links earns a span; the
     * common every-copy no-op stays off the rings. */
    if (tSpan && recovered)
        tpurmTraceEnd(TPU_TRACE_ICI_RETRAIN, tSpan, force, recovered);
    return recovered;
}

TpuStatus tpuIciResetLink(uint32_t devInst, uint32_t link)
{
    tpuIciInit();
    if (devInst >= g_ici.count || link >= g_ici.linkCount[devInst])
        return TPU_ERR_INVALID_ARGUMENT;
    pthread_mutex_lock(&g_ici.lock);
    IciLink *l = &g_ici.links[devInst][link];
    l->state = TPU_ICI_LINK_DOWN;
    IciLink *back = link_to(l->peerInst, devInst);
    if (back)
        back->state = TPU_ICI_LINK_DOWN;
    pthread_mutex_unlock(&g_ici.lock);
    return TPU_OK;
}

/* Shortest-path next hop over ACTIVE links (BFS from dst).  On a healthy
 * torus this reproduces dimension-ordered minimal routing; with FAILED
 * links it detours loop-free or reports a partition.  N is tiny (<=16),
 * so per-query BFS costs nothing; a routing cache would be the next step
 * if topologies grew. */
static TpuStatus next_hop_locked(uint32_t src, uint32_t dst, uint32_t *next)
{
    if (src == dst) {
        *next = dst;
        return TPU_OK;
    }
    uint8_t dist[MAX_ICI_DEVICES];
    uint32_t queue[MAX_ICI_DEVICES];
    memset(dist, 0xFF, sizeof(dist));
    uint32_t head = 0, tail = 0;
    dist[dst] = 0;
    queue[tail++] = dst;
    while (head < tail) {
        uint32_t cur = queue[head++];
        for (uint32_t i = 0; i < g_ici.linkCount[cur]; i++) {
            IciLink *l = &g_ici.links[cur][i];
            if (l->state != TPU_ICI_LINK_ACTIVE)
                continue;
            uint32_t peer = l->peerInst;
            if (dist[peer] == 0xFF) {
                dist[peer] = dist[cur] + 1;
                queue[tail++] = peer;
            }
        }
    }
    if (dist[src] == 0xFF)
        return TPU_ERR_OBJECT_NOT_FOUND;    /* partitioned */
    for (uint32_t i = 0; i < g_ici.linkCount[src]; i++) {
        IciLink *l = &g_ici.links[src][i];
        if (l->state == TPU_ICI_LINK_ACTIVE &&
            dist[l->peerInst] == dist[src] - 1) {
            *next = l->peerInst;
            return TPU_OK;
        }
    }
    return TPU_ERR_INVALID_STATE;           /* unreachable */
}

TpuStatus tpuIciRouteNextHop(uint32_t src, uint32_t dst, uint32_t *next)
{
    tpuIciInit();
    if (!next || src >= g_ici.count || dst >= g_ici.count)
        return TPU_ERR_INVALID_ARGUMENT;
    pthread_mutex_lock(&g_ici.lock);
    TpuStatus st = next_hop_locked(src, dst, next);
    pthread_mutex_unlock(&g_ici.lock);
    return st;
}

TpuStatus tpuIciRouteHops(uint32_t src, uint32_t dst, uint32_t *hops)
{
    tpuIciInit();
    if (!hops || src >= g_ici.count || dst >= g_ici.count)
        return TPU_ERR_INVALID_ARGUMENT;
    pthread_mutex_lock(&g_ici.lock);
    uint32_t cur = src, n = 0;
    TpuStatus st = TPU_OK;
    while (cur != dst && n <= g_ici.count) {
        uint32_t next;
        st = next_hop_locked(cur, dst, &next);
        if (st != TPU_OK)
            break;
        cur = next;
        n++;
    }
    if (n > g_ici.count)
        st = TPU_ERR_INVALID_STATE;     /* routing loop */
    pthread_mutex_unlock(&g_ici.lock);
    /* *hops only on success — callers keep their '~0 = unreachable'
     * sentinel on failure (abi.h busPeerIds contract). */
    if (st == TPU_OK)
        *hops = n;
    return st;
}

/* ------------------------------------------------------ peer apertures */

struct TpuIciPeerAperture {
    uint32_t srcInst, peerInst;
};

/* Account `bytes` on every link along src->dst (both directions). */
static TpuStatus account_route_locked(uint32_t src, uint32_t dst,
                                      uint64_t bytes)
{
    uint32_t cur = src, guard = 0;
    while (cur != dst) {
        uint32_t next;
        TpuStatus st = next_hop_locked(cur, dst, &next);
        if (st != TPU_OK)
            return st;
        IciLink *l = link_to(cur, next);
        IciLink *back = link_to(next, cur);
        if (l)
            l->bytesTx += bytes;
        if (back)
            back->bytesRx += bytes;
        cur = next;
        if (++guard > g_ici.count)
            return TPU_ERR_INVALID_STATE;
    }
    return TPU_OK;
}

TpuStatus tpuIciPeerApertureCreate(uint32_t srcInst, uint32_t peerInst,
                                   TpuIciPeerAperture **out)
{
    tpuIciInit();
    if (!out || srcInst >= g_ici.count || peerInst >= g_ici.count ||
        srcInst == peerInst)
        return TPU_ERR_INVALID_ARGUMENT;
    /* Route must exist over ACTIVE links. */
    uint32_t hops;
    TpuStatus st = tpuIciRouteHops(srcInst, peerInst, &hops);
    if (st != TPU_OK)
        return st;
    TpuIciPeerAperture *ap = calloc(1, sizeof(*ap));
    if (!ap)
        return TPU_ERR_NO_MEMORY;
    ap->srcInst = srcInst;
    ap->peerInst = peerInst;
    tpuCounterAdd("ici_peer_apertures", 1);
    *out = ap;
    return TPU_OK;
}

void tpuIciPeerApertureDestroy(TpuIciPeerAperture *ap)
{
    free(ap);
}

static TpuStatus ici_peer_copy_async(TpuIciPeerAperture *ap,
                                     uint64_t localOff, uint64_t peerOff,
                                     uint64_t size, int direction,
                                     TpuTracker *tracker)
{
    if (!ap || size == 0)
        return TPU_ERR_INVALID_ARGUMENT;
    TpurmDevice *local = tpurmDeviceGet(ap->srcInst);
    TpurmDevice *peer = tpurmDeviceGet(ap->peerInst);
    if (!local || !peer)
        return TPU_ERR_INVALID_DEVICE;
    if (local->lost || peer->lost)
        return TPU_ERR_GPU_IS_LOST;
    /* Overflow-safe form: localOff + size can wrap uint64. */
    uint64_t lhbm = tpurmDeviceHbmSize(local);
    uint64_t phbm = tpurmDeviceHbmSize(peer);
    if (localOff > lhbm || size > lhbm - localOff ||
        peerOff > phbm || size > phbm - peerOff)
        return TPU_ERR_INVALID_LIMIT;

    /* Recovery-first: give flapped links their lazy retrain, then let
     * the injection framework flap a link on this copy's route (chaos:
     * the copy must still complete — detour or retrain). */
    pthread_mutex_lock(&g_ici.lock);
    ici_retrain_soft_locked(false);
    if (tpurmInjectShouldFail(TPU_INJECT_SITE_ICI_LINK))
        ici_flap_route_locked(ap->srcInst, ap->peerInst);
    TpuStatus st = account_route_locked(ap->srcInst, ap->peerInst, size);
    if (st != TPU_OK) {
        /* Partitioned: force retrain of soft-failed links and retry the
         * route once.  If nothing retrains (or retrain itself failed)
         * report RETRAIN_FAILED when a flapped link is the cause. */
        bool anySoft = false;
        for (uint32_t d = 0; d < g_ici.count && !anySoft; d++)
            for (uint32_t i = 0; i < g_ici.linkCount[d]; i++)
                if (g_ici.links[d][i].state == TPU_ICI_LINK_FAILED &&
                    g_ici.links[d][i].softFail) {
                    anySoft = true;
                    break;
                }
        if (ici_retrain_soft_locked(true) > 0)
            st = account_route_locked(ap->srcInst, ap->peerInst, size);
        if (st != TPU_OK && anySoft)
            st = TPU_ERR_RETRAIN_FAILED;
    }
    pthread_mutex_unlock(&g_ici.lock);
    if (st != TPU_OK)
        return st;

    char *lp = (char *)tpurmDeviceHbmBase(local) + localOff;
    char *pp = (char *)tpurmDeviceHbmBase(peer) + peerOff;
    void *dst = direction == 0 ? pp : lp;
    const void *src = direction == 0 ? lp : pp;
    uint32_t from = direction == 0 ? ap->srcInst : ap->peerInst;
    uint32_t to = direction == 0 ? ap->peerInst : ap->srcInst;

    /* PERFORMANCE MODEL: multi-hop routes STORE-AND-FORWARD through a
     * staging chunk on each intermediate device (allocated from its
     * UVM tier PMM, like any other HBM tenant) — every hop is a real
     * channel copy on the hop's source device, so a 3-hop transfer
     * costs 3x the link work and rides 3 devices' CEs, exactly the
     * bandwidth shape real torus detours have.  Payloads stream in
     * chunk-sized segments. */
    uint32_t hops = 0;
    if (tpuIciRouteHops(from, to, &hops) != TPU_OK)
        return TPU_ERR_INVALID_STATE;
    if (hops > 1) {
        /* Multi-hop while a direct link exists but is down: the copy is
         * riding a detour (degraded routing). */
        pthread_mutex_lock(&g_ici.lock);
        IciLink *direct = link_to(from, to);
        if (direct && direct->state != TPU_ICI_LINK_ACTIVE)
            tpuCounterAdd("ici_degraded_routes", 1);
        pthread_mutex_unlock(&g_ici.lock);
    }
    if (hops <= 1) {
        /* PEER_COPY rides the hop-source device's tpuce manager:
         * stripes spread across its channel pool, and tpuce owns the
         * bounded retry + RC reset-and-replay per stripe (the bespoke
         * retry loop this replaces).  With a tracker, the stripes'
         * dependencies hand off to the caller (failures surface at its
         * range-checked wait); without one, completion is synchronous
         * with per-stripe recovery.
         *
         * tpushield wire checksum (sync path only — a tracker handoff
         * completes at the caller, where no verify hook exists): the
         * payload CRC computed at the SOURCE travels with the push and
         * is verified against the DESTINATION after the fence; a
         * mismatch is attributed to the link (both endpoints take the
         * health hit) and the copy retries once from the still-intact
         * source. */
        TpuCeMgr *mgr = tpuCeMgrGet(from);
        if (!mgr)
            return TPU_ERR_INVALID_STATE;
        bool sealed = tracker == NULL && tpurmShieldEnabled();
        /* Real-arena coherence BEFORE the seal CRC: a chip-dirty source
         * span would otherwise seal the stale host shadow while the CE
         * copy downloads + moves the fresh bytes — a deterministic
         * false mismatch (and two spurious link-flap health notes) per
         * healthy copy.  If coherence fails, skip the seal: the copy's
         * own coherence path still decides the transfer's fate. */
        if (sealed && tpuHbmCoherentForRead(src, size) != TPU_OK)
            sealed = false;
        uint32_t srcCrc = sealed ? tpurmShieldCrc32c(src, size) : 0;
        for (int attempt = 0; ; attempt++) {
            TpuCeBatch b;
            tpuCeBatchBegin(mgr, &b);
            st = tpuCeBatchCopy(&b, dst, src, size, TPU_CE_COMP_NONE);
            if (tracker && st == TPU_OK) {
                st = tpuCeBatchHandoff(&b, tracker);
            } else {
                TpuStatus ws = tpuCeBatchWait(&b);
                if (st == TPU_OK)
                    st = ws;
            }
            if (st != TPU_OK || !sealed)
                break;
            uint64_t linkScope = ((uint64_t)from << 32) | to;
            tpurmShieldInjectWire(dst, size, linkScope);
            if (tpurmShieldVerifyWire(dst, size, srcCrc, linkScope) ==
                TPU_OK)
                break;
            tpuCounterAdd("ici_wire_crc_errors", 1);
            tpurmJournalEmit(TPU_JREC_ICI_CRC, from, TPU_OK, from, to);
            tpurmHealthNote(from, TPU_HEALTH_EV_LINK_FLAP);
            tpurmHealthNote(to, TPU_HEALTH_EV_LINK_FLAP);
            TPU_LOG(TPU_LOG_WARN, "ici",
                   "wire CRC mismatch on link %u -> %u (%llu bytes), "
                   "%s", from, to, (unsigned long long)size,
                   attempt == 0 ? "re-fetching from source"
                                : "retry exhausted");
            if (attempt >= 1) {
                st = TPU_ERR_INVALID_STATE;
                break;
            }
        }
        if (st == TPU_OK)
            tpuCounterAdd("ici_peer_copy_bytes", size);
        return st;
    }

    /* Build the hop chain from..to. */
    enum { MAX_HOPS = 32 };
    uint32_t chain[MAX_HOPS + 1];
    uint32_t n = 0;
    chain[n++] = from;
    uint32_t cur = from;
    while (cur != to && n <= MAX_HOPS) {
        uint32_t next;
        if (tpuIciRouteNextHop(cur, to, &next) != TPU_OK)
            return TPU_ERR_INVALID_STATE;
        chain[n++] = next;
        cur = next;
    }
    if (cur != to)
        return TPU_ERR_INVALID_STATE;

    /* Every device ALONG the route must be healthy: routing through a
     * lost chip is as fatal as a lost endpoint. */
    TpurmDevice *chainDev[MAX_HOPS + 1];
    for (uint32_t i = 0; i < n; i++) {
        chainDev[i] = tpurmDeviceGet(chain[i]);
        if (!chainDev[i])
            return TPU_ERR_INVALID_DEVICE;
        if (chainDev[i]->lost)
            return TPU_ERR_GPU_IS_LOST;
    }

    /* Staging chunk on each INTERMEDIATE device (clamped to the PMM's
     * 2 MB chunk ceiling the way uvm_page_size clamps). */
    uint64_t seg = tpuRegistryGet("ici_staging_bytes", 1ull << 20);
    if (seg > 2ull * 1024 * 1024)
        seg = 2ull * 1024 * 1024;
    if (seg < 4096)
        seg = 4096;
    if (seg > size)
        seg = size;
    uint64_t stageOff[MAX_HOPS];
    void *stageHandle[MAX_HOPS];
    uint32_t nStage = 0;
    st = TPU_OK;
    for (uint32_t i = 1; i + 1 < n && st == TPU_OK; i++) {
        /* Staging allocation rides the same PMM as everything else, so
         * the injected allocation fault can land here too: bounded
         * retry (a transient chunk fault won't repeat), then give up. */
        for (uint32_t attempt = 0; ; attempt++) {
            st = uvmHbmChunkAlloc(chain[i], seg, &stageOff[nStage],
                                  &stageHandle[nStage]);
            if (st != TPU_ERR_INSUFFICIENT_RESOURCES || attempt >= 3)
                break;
            tpuCounterAdd("recover_retries", 1);
            tpurmTraceInstant(TPU_TRACE_RECOVER_RETRY, chain[i], attempt);
            tpuRecoverBackoff(attempt);
        }
        if (st == TPU_OK)
            nStage++;
    }
    if (st != TPU_OK)
        goto out_free;

    /* Stream segments through the chain as a SOFTWARE PIPELINE: each
     * hop is a tpuce batch on the hop-source device's manager (striped
     * across its channel pool), fencing only its two real dependencies
     * — the same segment's previous hop (the data it forwards) and the
     * PREVIOUS segment's next hop (the staging slot it overwrites).
     * Hop 0 of segment s+1 therefore overlaps the later hops of
     * segment s, which is exactly how wormhole-ish torus traffic keeps
     * every link busy.  tpuCeBatchWait is idempotent, so dependency
     * fences, slot-reuse fences and the tail drain can all hit the
     * same batch — and since PR 11 each of those waits is a DEP-JOIN
     * over the batch's (channel, value) tracker pairs: a hop's stripes
     * complete in retirement order across the channel pool, so one
     * slow channel delays only its own stripes, not the whole hop
     * fence (tpuce_ooo_completions counts the reordering). */
    {
        TpuCeMgr *hopMgr[MAX_HOPS + 1];
        for (uint32_t h = 0; h + 1 < n; h++) {
            hopMgr[h] = tpuCeMgrGet(chain[h]);
            if (!hopMgr[h]) {
                st = TPU_ERR_INVALID_STATE;
                break;
            }
        }
        /* Two batch rows (previous / current segment), heap-side and
         * sized to the ACTUAL chain: a batch embeds its stripe table,
         * so rows for the worst-case MAX_HOPS would zero megabytes per
         * detour copy for nothing. */
        TpuCeBatch *rows = st == TPU_OK ? calloc(2 * n, sizeof(*rows))
                                        : NULL;
        if (st == TPU_OK && !rows)
            st = TPU_ERR_NO_MEMORY;
        TpuCeBatch *prevB = rows, *curB = rows ? rows + n : NULL;
        if (rows)
            for (uint32_t h = 0; h + 1 < n; h++) {
                tpuCeBatchBegin(hopMgr[h], &prevB[h]);
                tpuCeBatchBegin(hopMgr[h], &curB[h]);
            }
        uint32_t lastHop = n - 2;
        /* tpushield per-hop CRC: the segment's CRC is carried with the
         * push down the store-and-forward chain and checked at every
         * hop boundary (the input of hop h is the fenced output of hop
         * h-1), so a corrupting MIDDLE hop is attributed to the exact
         * LINK that damaged the bytes — and repaired by re-running
         * just that hop from its still-intact input. */
        bool hopSeal = tpurmShieldEnabled();
        /* Real-arena coherence before any source CRC (single readback
         * covers every per-segment seal and the fallback verify). */
        if (hopSeal && tpuHbmCoherentForRead(src, size) != TPU_OK)
            hopSeal = false;
        const char *hopIn[MAX_HOPS + 1];
        /* Per-segment source CRCs are kept for the final-hop verify:
         * the destination is checked segment-by-segment against the
         * seals computed once here — no second full source pass, and
         * a final-link mismatch is attributed to the exact segment. */
        uint32_t nSegs = (uint32_t)((size + seg - 1) / seg);
        uint32_t *segCrcs = hopSeal
                                ? malloc((size_t)nSegs * sizeof(*segCrcs))
                                : NULL;
        for (uint64_t off = 0; off < size && st == TPU_OK; off += seg) {
            uint64_t len = size - off < seg ? size - off : seg;
            const char *hopSrc = (const char *)src + off;
            uint32_t segCrc = hopSeal
                                  ? tpurmShieldCrc32c(hopSrc, len) : 0;
            if (segCrcs)
                segCrcs[off / seg] = segCrc;
            for (uint32_t h = 0; h + 1 < n && st == TPU_OK; h++) {
                /* Data dependency: previous hop of THIS segment. */
                if (h > 0) {
                    st = tpuCeBatchWait(&curB[h - 1]);
                    if (st != TPU_OK)
                        break;
                    if (hopSeal) {
                        /* hopSrc is now the FENCED output of hop h-1:
                         * check it against the segment CRC before hop
                         * h forwards it.  One mem.corrupt evaluation
                         * per hop models the corrupting middle hop. */
                        uint64_t lk = ((uint64_t)chain[h - 1] << 32) |
                                      chain[h];
                        tpurmShieldInjectWire((void *)(uintptr_t)hopSrc,
                                              len, lk);
                        if (tpurmShieldVerifyWire(hopSrc, len, segCrc,
                                                  lk) != TPU_OK) {
                            tpuCounterAdd("ici_wire_crc_errors", 1);
                            tpurmJournalEmit(TPU_JREC_ICI_CRC,
                                             chain[h - 1], TPU_OK,
                                             chain[h - 1], chain[h]);
                            tpurmHealthNote(chain[h - 1],
                                            TPU_HEALTH_EV_LINK_FLAP);
                            tpurmHealthNote(chain[h],
                                            TPU_HEALTH_EV_LINK_FLAP);
                            TPU_LOG(TPU_LOG_WARN, "ici",
                                   "hop CRC mismatch on link %u -> %u "
                                   "(detour seg @%llu): re-running hop",
                                   chain[h - 1], chain[h],
                                   (unsigned long long)off);
                            /* Repair: re-run hop h-1 from its intact
                             * input (verified when IT was the hop
                             * boundary), synchronously. */
                            st = tpuCeCopySync(hopMgr[h - 1],
                                               (void *)(uintptr_t)hopSrc,
                                               hopIn[h - 1], len,
                                               TPU_CE_COMP_NONE);
                            if (st == TPU_OK &&
                                tpurmShieldVerifyWire(hopSrc, len,
                                                      segCrc, lk) !=
                                    TPU_OK)
                                st = TPU_ERR_INVALID_STATE;
                            if (st != TPU_OK)
                                break;
                        }
                    }
                }
                hopIn[h] = hopSrc;
                /* Staging reuse: the PREVIOUS segment must have been
                 * read out of the slot this copy overwrites. */
                if (h < lastHop) {
                    st = tpuCeBatchWait(&prevB[h + 1]);
                    if (st != TPU_OK)
                        break;
                }
                /* The slot we are about to refill carried the copy two
                 * segments back: fence it before reuse. */
                st = tpuCeBatchWait(&curB[h]);
                if (st != TPU_OK)
                    break;
                void *hopDst = (h == lastHop)
                                   ? (char *)dst + off
                                   : (char *)tpurmDeviceHbmBase(
                                         chainDev[h + 1]) + stageOff[h];
                /* tpuflow: each store-and-forward leg bumps the flow
                 * id's HOP field, so the per-hop ce.stripe spans of
                 * one transfer stay one arrow chain in the Perfetto
                 * export while remaining distinguishable per leg. */
                uint64_t baseFlow = tpurmTraceFlowGet();
                if (baseFlow)
                    tpurmTraceFlowSet(TPU_FLOW_WITH_HOP(
                        baseFlow, TPU_FLOW_HOP(baseFlow) + h));
                st = tpuCeBatchCopy(&curB[h], hopDst, hopSrc, len,
                                    TPU_CE_COMP_NONE);
                if (baseFlow)
                    tpurmTraceFlowSet(baseFlow);
                if (st != TPU_OK)
                    break;
                tpuCounterAdd("ici_hop_bytes", len);
                hopSrc = hopDst;
            }
            if (rows) {
                TpuCeBatch *t = prevB;
                prevB = curB;
                curB = t;
            }
        }
        /* Drain the tail (staging frees below must not race copies). */
        if (rows) {
            for (uint32_t h = 0; h + 1 < n; h++) {
                TpuStatus ws = tpuCeBatchWait(&prevB[h]);
                if (ws != TPU_OK && st == TPU_OK)
                    st = ws;
                ws = tpuCeBatchWait(&curB[h]);
                if (ws != TPU_OK && st == TPU_OK)
                    st = ws;
            }
            free(rows);
        }
        /* Final-hop verify: the payload at the destination against the
         * per-segment source CRCs computed once above (the last link's
         * per-hop check) — no second full source pass.  A mismatch
         * cannot be repaired in place — its staging inputs are already
         * recycled — so it fails the copy; the spine's bounded retry
         * re-runs the transfer from the intact source.  (segCrcs NULL
         * = malloc failed: recompute the whole-payload CRC instead.) */
        if (st == TPU_OK && hopSeal) {
            uint64_t lk = ((uint64_t)chain[n - 2] << 32) | chain[n - 1];
            tpurmShieldInjectWire(dst, size, lk);
            bool ok = true;
            if (segCrcs) {
                for (uint64_t off = 0; off < size && ok; off += seg) {
                    uint64_t len = size - off < seg ? size - off : seg;
                    ok = tpurmShieldVerifyWire((char *)dst + off, len,
                                               segCrcs[off / seg],
                                               lk) == TPU_OK;
                }
            } else {
                ok = tpurmShieldVerifyWire(
                         dst, size, tpurmShieldCrc32c(src, size),
                         lk) == TPU_OK;
            }
            if (!ok) {
                tpuCounterAdd("ici_wire_crc_errors", 1);
                tpurmJournalEmit(TPU_JREC_ICI_CRC, chain[n - 2], TPU_OK,
                                 chain[n - 2], chain[n - 1]);
                tpurmHealthNote(chain[n - 2], TPU_HEALTH_EV_LINK_FLAP);
                tpurmHealthNote(chain[n - 1], TPU_HEALTH_EV_LINK_FLAP);
                TPU_LOG(TPU_LOG_WARN, "ici",
                       "final-hop CRC mismatch on link %u -> %u: "
                       "failing the detour copy for retry",
                       chain[n - 2], chain[n - 1]);
                st = TPU_ERR_INVALID_STATE;
            }
        }
        free(segCrcs);
    }
    if (st == TPU_OK) {
        tpuCounterAdd("ici_peer_copy_bytes", size);
        tpuCounterAdd("ici_multihop_copies", 1);
    }

out_free:
    for (uint32_t i = 0; i < nStage; i++)
        uvmHbmChunkFree(chain[i + 1], stageHandle[i]);
    (void)tracker;   /* staged path drains before returning: staging
                      * chunks cannot outlive their in-flight reads */
    return st;
}

TpuStatus tpuIciPeerCopyAsync(TpuIciPeerAperture *ap, uint64_t localOff,
                              uint64_t peerOff, uint64_t size, int direction,
                              TpuTracker *tracker)
{
    /* Tracker handoff needs the direct path (a ring round-trip would
     * defeat the async contract); the sync form rides the spine. */
    if (!tracker)
        return tpuIciPeerCopy(ap, localOff, peerOff, size, direction);
    uint64_t t0 = tpurmTraceBegin();
    TpuStatus st = ici_peer_copy_async(ap, localOff, peerOff, size,
                                       direction, tracker);
    if (t0)
        tpurmTraceEnd(TPU_TRACE_ICI_COPY,
                      t0, ap ? (((uint64_t)ap->srcInst << 32) |
                                ap->peerInst) : 0, size);
    return st;
}

/* Direct engine execution — the memring spine workers' entry
 * (everything else submits through tpuIciPeerCopy). */
TpuStatus tpuIciPeerCopyExec(TpuIciPeerAperture *ap, uint64_t localOff,
                             uint64_t peerOff, uint64_t size, int direction)
{
    uint64_t t0 = tpurmTraceBegin();
    TpuStatus st = ici_peer_copy_async(ap, localOff, peerOff, size,
                                       direction, NULL);
    if (t0)
        tpurmTraceEnd(TPU_TRACE_ICI_COPY,
                      t0, ap ? (((uint64_t)ap->srcInst << 32) |
                                ap->peerInst) : 0, size);
    return st;
}

TpuStatus tpuIciPeerCopy(TpuIciPeerAperture *ap, uint64_t localOff,
                         uint64_t peerOff, uint64_t size, int direction)
{
    if (!ap || size == 0)
        return TPU_ERR_INVALID_ARGUMENT;
    /* Spine submission: one PEER_COPY SQE on the internal ring (the
     * worker resolves its own cached aperture for the pair and runs
     * the single/multi-hop pipeline via tpuIciPeerCopyExec).  All ICI
     * transfers are thereby ring-accounted and share the pool's
     * claim/coalesce machinery with fault and tier traffic. */
    TpuMemringSqe s;
    memset(&s, 0, sizeof(s));
    s.opcode = TPU_MEMRING_OP_PEER_COPY;
    s.devInst = ap->srcInst;
    s.peerInst = ap->peerInst;
    s.addr = localOff;
    s.peerOff = peerOff;
    s.len = size;
    s.arg0 = direction ? TPU_MEMRING_PEER_READ : TPU_MEMRING_PEER_WRITE;
    TpuStatus st = TPU_OK;
    TpuStatus sub = tpurmMemringSubmitInternal(NULL, &s, 1, &st,
                                               TPU_MEMRING_SUBSYS_ICI);
    return st != TPU_OK ? st : sub;
}
