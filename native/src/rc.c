/*
 * rc — robust-channel recovery: the non-replayable fault subsystem and
 * the channel watchdog.
 *
 * Reference split (SURVEY.md §5): replayable faults replay after
 * service; NON-replayable faults (Copy Engine / PBDMA) are delivered
 * through an RM SHADOW BUFFER and serviced without replay — fatal ones
 * trigger per-channel robust-channel recovery
 * (uvm_gpu_non_replayable_faults.c; rc/kernel_rc.c; watchdog
 * kernel_rc_watchdog.c).  TPU-native shape:
 *
 *   shadow buffer — a msgq (msgq.c) the channel executors post fault
 *                   records into when a push fails (the executor also
 *                   latches the channel error synchronously, so wait
 *                   semantics are unchanged — the shadow path is the
 *                   ATTRIBUTION/RECOVERY plane, exactly the reference's
 *                   split between fault delivery and RC);
 *   RC service    — drains the shadow buffer: journal + counters +
 *                   per-channel error notifier callbacks (reference:
 *                   error notifiers on every channel) + recovery policy
 *                   (registry "rc_policy": 0 = latch only, 1 =
 *                   auto-reset the channel);
 *   watchdog      — periodic scan of all live channels: pending work
 *                   with no completion progress for longer than
 *                   "rc_watchdog_timeout_ms" posts a WATCHDOG fault
 *                   into the same shadow buffer (reference:
 *                   krcWatchdogCheckChannelsDueToTimeout).
 */
#define _GNU_SOURCE
#include "internal.h"
#include "tpurm/health.h"
#include "tpurm/msgq.h"
#include "uvm/uvm_internal.h"   /* uvmMonotonicNs */
#include "tpurm/reset.h"
#include "tpurm/trace.h"

#include <stdatomic.h>
#include <stdlib.h>
#include <string.h>
#include <time.h>

/* Shadow record wire format inside a TpuMsgqCmd: dst = channel pointer,
 * src = tracker value, bytes = kind, pbEnd = channel rc id. */

/* Watchdog bookkeeping per registered channel. */
typedef struct RcChannel {
    TpurmChannel *ch;
    uint64_t rcId;
    uint64_t lastCompleted;
    uint64_t stuckSinceNs;       /* 0 = progressing */
    bool barked;                 /* one watchdog fault per stall */
    bool escalated;              /* one device-reset escalation per stall */
    struct RcChannel *next;
} RcChannel;

static struct {
    pthread_once_t once;
    TpuMsgq *shadow;             /* the non-replayable fault buffer */
    pthread_t service;
    pthread_t watchdog;
    bool ready;

    pthread_mutex_t chLock;
    RcChannel *channels;
} g_rc = { .once = PTHREAD_ONCE_INIT,
           .chLock = PTHREAD_MUTEX_INITIALIZER };

/* ------------------------------------------------------ shadow service */

static void *rc_service_thread(void *arg)
{
    (void)arg;
    TpuMsgqCmd cmd;
    while (tpuMsgqReceive(g_rc.shadow, &cmd, 1) == 1) {
        TpurmChannel *ch = (TpurmChannel *)(uintptr_t)cmd.dst;
        uint64_t value = cmd.src;
        uint32_t kind = (uint32_t)cmd.bytes;
        uint64_t rcId = cmd.pbEnd;
        TPU_LOG(TPU_LOG_ERROR, "rc",
               "non-replayable %s on channel %p at value %llu",
               kind == TPU_RC_WATCHDOG_TIMEOUT ? "watchdog timeout"
                                               : "CE fault",
               (void *)ch, (unsigned long long)value);
        tpuCounterAdd("rc_nonreplayable_faults", 1);
        if (kind == TPU_RC_WATCHDOG_TIMEOUT)
            tpuCounterAdd("rc_watchdog_timeouts", 1);
        uvmToolsEmit(NULL,
                     kind == TPU_RC_WATCHDOG_TIMEOUT ? UVM_EVENT_WATCHDOG
                                                     : UVM_EVENT_CHANNEL_RC,
                     UVM_TIER_COUNT, UVM_TIER_COUNT, 0,
                     (uint64_t)(uintptr_t)ch, value);

        /* Attribution under chLock: a racing channel destroy calls
         * tpuRcChannelUnregister (same lock) before freeing, so a LIVE
         * channel cannot vanish mid-delivery.  Notifiers therefore run
         * under the RC lock and must not create/destroy channels. */
        pthread_mutex_lock(&g_rc.chLock);
        for (RcChannel *rc = g_rc.channels; rc; rc = rc->next) {
            /* Pointer AND id must match: a recycled allocation at the
             * same address has a different id, so stale records from a
             * destroyed channel never misattribute (ABA guard). */
            if (rc->ch == ch && rc->rcId == rcId) {
                tpurmChannelRcDeliver(ch, value, kind);
                break;
            }
        }
        pthread_mutex_unlock(&g_rc.chLock);
        tpuMsgqComplete(g_rc.shadow, cmd.seq);
    }
    return NULL;
}

/* ---------------------------------------------------------- watchdog */

static void *rc_watchdog_thread(void *arg)
{
    (void)arg;
    for (;;) {
        uint64_t periodMs = tpuRegistryGet("rc_watchdog_period_ms", 100);
        uint64_t timeoutMs = tpuRegistryGet("rc_watchdog_timeout_ms", 2000);
        struct timespec ts = { .tv_sec = (time_t)(periodMs / 1000),
                               .tv_nsec = (long)(periodMs % 1000) *
                                          1000000L };
        nanosleep(&ts, NULL);
        if (!tpuRegistryGet("rc_watchdog_enable", 1))
            continue;

        /* Optional last rung above the per-channel bark: a channel
         * still frozen this long AFTER its watchdog fault escalates to
         * a FULL DEVICE RESET (tpurm/reset.h).  Off by default — the
         * bark + RC policy handle channel-scoped stalls; the ladder is
         * for operators who want the reference's "lose the channel,
         * then lose the GPU, never the process" end-to-end. */
        uint64_t escalateMs = tpuRegistryGet("rc_escalate_device_ms", 0);
        bool escalate = false;
        uint64_t now = uvmMonotonicNs();
        pthread_mutex_lock(&g_rc.chLock);
        for (RcChannel *rc = g_rc.channels; rc; rc = rc->next) {
            uint64_t completed, pendingDepth;
            tpurmChannelProgress(rc->ch, &completed, &pendingDepth);
            if (pendingDepth == 0 || completed != rc->lastCompleted) {
                rc->lastCompleted = completed;
                rc->stuckSinceNs = 0;
                rc->barked = false;
                rc->escalated = false;
                continue;
            }
            if (rc->stuckSinceNs == 0) {
                rc->stuckSinceNs = now;
                continue;
            }
            if (!rc->barked &&
                now - rc->stuckSinceNs > timeoutMs * 1000000ull) {
                rc->barked = true;
                tpuRcPostFault(rc->ch, rc->rcId, completed,
                               TPU_RC_WATCHDOG_TIMEOUT);
            }
            if (escalateMs && rc->barked && !rc->escalated &&
                now - rc->stuckSinceNs >
                    (timeoutMs + escalateMs) * 1000000ull) {
                rc->escalated = true;
                escalate = true;
            }
        }
        pthread_mutex_unlock(&g_rc.chLock);
        if (escalate) {
            /* Outside chLock: the reset's RC recovery walks channels. */
            tpuCounterAdd("rc_device_escalations", 1);
            TPU_LOG(TPU_LOG_ERROR, "rc",
                   "channel stall outlived its watchdog fault: "
                   "escalating to full-device reset");
            tpurmDeviceReset();
        }
    }
    return NULL;
}

/* --------------------------------------------------------------- init */

static void rc_init_once(void)
{
    /* Shield CRC tables: normally the library constructor already ran
     * this; repeating it here (idempotent) covers exotic static-init
     * orders before any channel executor can seal a page. */
    tpurmShieldCrcInit();
    g_rc.shadow = tpuMsgqCreate(
        (uint32_t)tpuRegistryGet("rc_shadow_entries", 256), TPU_MSGQ_MPSC);
    if (!g_rc.shadow)
        return;
    if (pthread_create(&g_rc.service, NULL, rc_service_thread, NULL) != 0) {
        TPU_LOG(TPU_LOG_ERROR, "rc", "RC service thread create failed");
        tpuMsgqDestroy(g_rc.shadow);
        g_rc.shadow = NULL;
        return;
    }
    if (pthread_create(&g_rc.watchdog, NULL, rc_watchdog_thread,
                       NULL) != 0) {
        /* Tear down cleanly: shutdown wakes the service thread out of
         * its Receive loop, then the queue can be freed. */
        TPU_LOG(TPU_LOG_ERROR, "rc", "RC watchdog thread create failed");
        tpuMsgqShutdown(g_rc.shadow);
        pthread_join(g_rc.service, NULL);
        tpuMsgqDestroy(g_rc.shadow);
        g_rc.shadow = NULL;
        return;
    }
    g_rc.ready = true;
    /* The hung-op/reset watchdog rides the same lifecycle: any process
     * that creates a channel is covered by the full ladder. */
    tpurmResetWatchdogStart();
    TPU_LOG(TPU_LOG_INFO, "rc", "robust-channel recovery ready "
           "(shadow buffer + watchdog)");
}

void tpuRcInit(void)
{
    pthread_once(&g_rc.once, rc_init_once);
}

/* Post a non-replayable fault record into the shadow buffer.  Callers
 * are channel executors (CE faults) and the watchdog; NEVER blocks —
 * on a full shadow buffer the record is dropped with a counter (the
 * channel error latch itself is synchronous, so no error is lost,
 * only its attribution). */
void tpuRcPostFault(TpurmChannel *ch, uint64_t rcId, uint64_t value,
                    uint32_t kind)
{
    tpuRcInit();
    if (!g_rc.ready)
        return;
    TpuMsgqCmd cmd = { .op = TPU_MSGQ_NOP,
                       .dst = (uint64_t)(uintptr_t)ch,
                       .src = value,
                       .bytes = kind,
                       .pbEnd = rcId };
    if (tpuMsgqTrySubmit(g_rc.shadow, &cmd, 1, NULL) != 0)
        tpuCounterAdd("rc_shadow_overflows", 1);
}

/* Exponential-backoff sleep shared by every bounded recovery loop. */
void tpuRecoverBackoff(uint32_t attempt)
{
    uint64_t us = tpuRegistryGet("recover_backoff_us", 100);
    if (attempt > 10)
        attempt = 10;
    us <<= attempt;
    struct timespec ts = { .tv_sec = (time_t)(us / 1000000ull),
                           .tv_nsec = (long)(us % 1000000ull) * 1000L };
    nanosleep(&ts, NULL);
}

/* -------------------------------------------- channel registry hooks */

void tpuRcChannelRegister(TpurmChannel *ch, uint64_t rcId)
{
    tpuRcInit();
    RcChannel *rc = calloc(1, sizeof(*rc));
    if (!rc)
        return;
    rc->ch = ch;
    rc->rcId = rcId;
    pthread_mutex_lock(&g_rc.chLock);
    rc->next = g_rc.channels;
    g_rc.channels = rc;
    pthread_mutex_unlock(&g_rc.chLock);
}

/* Iterate live channels under the registry lock (procfs renderer).
 * The callback must not create/destroy channels. */
void tpuRcForEachChannel(void (*fn)(TpurmChannel *ch, uint64_t completed,
                                    uint64_t pending, void *arg),
                         void *arg)
{
    tpuRcInit();
    pthread_mutex_lock(&g_rc.chLock);
    for (RcChannel *rc = g_rc.channels; rc; rc = rc->next) {
        uint64_t completed, pending;
        tpurmChannelProgress(rc->ch, &completed, &pending);
        fn(rc->ch, completed, pending, arg);
    }
    pthread_mutex_unlock(&g_rc.chLock);
}

/* Reset-and-replay entry point for the hardened recovery loops: clear
 * latched errors on the ENGINE-OWNED channels (every device's CE pool)
 * so the caller can re-issue (replay) its failed work.  Scope matters:
 * engine-internal waits on the shared pool all use the failed-push
 * history (tpurmChannelWaitRange), which a reset never erases, so
 * clearing the pool latches is safe against concurrent engine waiters
 * — but CLIENT-created channels keep the legacy latch contract
 * (fault -> wait fails -> explicit ResetError), so a recovery running
 * inside the engine must never touch them: clearing a client latch
 * before the client's wait observes it would turn their faulted copy
 * into silent success.  Counts one recover_rc_resets per cleared latch
 * (the acceptance counter for RC recovery). */
uint32_t tpuRcRecoverAll(void)
{
    tpuRcInit();
    uint32_t cleared = 0;
    uint32_t ndev = tpurmDeviceCount();
    for (uint32_t i = 0; i < ndev; i++) {
        TpurmDevice *dev = tpurmDeviceGet(i);
        if (!dev)
            continue;
        uint32_t devCleared = 0;
        for (uint32_t c = 0; c < dev->cePoolSize; c++) {
            if (tpurmChannelErrorPending(dev->cePool[c])) {
                tpurmChannelResetError(dev->cePool[c]);
                devCleared++;
            }
        }
        if (devCleared) {
            /* Health attribution: the latched errors happened on THIS
             * device's CE pool — one note per recovery pass (not per
             * latch: a burst of latches is one sickness episode). */
            tpurmHealthNote(i, TPU_HEALTH_EV_RC_RESET);
            cleared += devCleared;
        }
    }
    if (cleared) {
        tpuCounterAdd("recover_rc_resets", cleared);
        /* bytes carries the per-call latch count so trace-side
         * accounting can reconcile exactly with the counter delta. */
        tpurmTraceInstant(TPU_TRACE_RECOVER_RC_RESET, 0, cleared);
        TPU_LOG(TPU_LOG_WARN, "rc",
               "reset-and-replay: cleared %u latched CE-pool error(s)",
               cleared);
    }
    return cleared;
}

void tpuRcChannelUnregister(TpurmChannel *ch)
{
    pthread_mutex_lock(&g_rc.chLock);
    for (RcChannel **pp = &g_rc.channels; *pp; pp = &(*pp)->next) {
        if ((*pp)->ch == ch) {
            RcChannel *dead = *pp;
            *pp = dead->next;
            free(dead);
            break;
        }
    }
    pthread_mutex_unlock(&g_rc.chLock);
}

