/*
 * Tracker — cross-channel completion dependencies.
 *
 * Re-design of the reference's uvm_tracker.c: a tracker is a small set of
 * (channel, value) entries; work that depends on pushes spread across
 * several channels records each push here and waits once.  Entries for
 * the same channel collapse to the max value (channel tracker semaphores
 * are monotonic, reference uvm_gpu_semaphore.c), and completed entries
 * are pruned on query, so a long-lived tracker stays small.
 *
 * Used by the CE fan-out (uvm_va_block.c), ICI peer copies (ici.c), and
 * the CXL DMA quiesce path (cxl.c) — one synchronization object for all
 * three engines, replacing per-engine ad hoc waits.
 */
#include "internal.h"

#include <stdlib.h>

void tpuTrackerInit(TpuTracker *t)
{
    t->count = 0;
    t->capacity = TPU_TRACKER_INLINE;
    t->entries = t->inlineEntries;
}

void tpuTrackerDeinit(TpuTracker *t)
{
    if (t->entries != t->inlineEntries)
        free(t->entries);
    t->count = 0;
    t->capacity = TPU_TRACKER_INLINE;
    t->entries = t->inlineEntries;
}

static TpuStatus tracker_add_range(TpuTracker *t, TpurmChannel *ch,
                                   uint64_t minValue, uint64_t value);

TpuStatus tpuTrackerAdd(TpuTracker *t, TpurmChannel *ch, uint64_t value)
{
    return tracker_add_range(t, ch, value, value);
}

static TpuStatus tracker_add_range(TpuTracker *t, TpurmChannel *ch,
                                   uint64_t minValue, uint64_t value)
{
    if (!t || !ch || value == 0)
        return TPU_ERR_INVALID_ARGUMENT;
    for (uint32_t i = 0; i < t->count; i++) {
        if (t->entries[i].ch == ch) {
            if (value > t->entries[i].value)
                t->entries[i].value = value;
            if (minValue < t->entries[i].minValue)
                t->entries[i].minValue = minValue;
            return TPU_OK;
        }
    }
    if (t->count == t->capacity) {
        uint32_t ncap = t->capacity * 2;
        TpuTrackerEntry *ne = malloc(ncap * sizeof(*ne));
        if (!ne)
            return TPU_ERR_NO_MEMORY;
        for (uint32_t i = 0; i < t->count; i++)
            ne[i] = t->entries[i];
        if (t->entries != t->inlineEntries)
            free(t->entries);
        t->entries = ne;
        t->capacity = ncap;
    }
    t->entries[t->count].ch = ch;
    t->entries[t->count].value = value;
    t->entries[t->count].minValue = minValue;
    t->count++;
    return TPU_OK;
}

TpuStatus tpuTrackerAddTracker(TpuTracker *dst, const TpuTracker *src)
{
    if (!dst || !src)
        return TPU_ERR_INVALID_ARGUMENT;
    for (uint32_t i = 0; i < src->count; i++) {
        TpuStatus st = tracker_add_range(dst, src->entries[i].ch,
                                         src->entries[i].minValue,
                                         src->entries[i].value);
        if (st != TPU_OK)
            return st;
    }
    return TPU_OK;
}

bool tpuTrackerIsCompleted(TpuTracker *t)
{
    if (!t)
        return true;
    uint32_t i = 0;
    while (i < t->count) {
        if (tpurmChannelCompletedValue(t->entries[i].ch) >=
            t->entries[i].value) {
            /* Prune: swap-with-last (order is irrelevant). */
            t->entries[i] = t->entries[--t->count];
        } else {
            i++;
        }
    }
    return t->count == 0;
}

TpuStatus tpuTrackerWait(TpuTracker *t)
{
    if (!t)
        return TPU_ERR_INVALID_ARGUMENT;
    TpuStatus st = TPU_OK;
    for (uint32_t i = 0; i < t->count; i++) {
        /* Range wait: only failures within THIS tracker's window of
         * pushes fail the wait, so a concurrent RC reset-and-replay on
         * another thread can neither hide our failure nor leak its own
         * into us. */
        TpuStatus s = tpurmChannelWaitRange(t->entries[i].ch,
                                            t->entries[i].minValue,
                                            t->entries[i].value);
        if (s != TPU_OK && st == TPU_OK)
            st = s;      /* keep waiting the rest; report first failure */
    }
    t->count = 0;
    return st;
}
