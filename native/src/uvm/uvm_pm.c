/*
 * Power management — suspend/resume with device-arena save/restore.
 *
 * Re-design of the reference's checkpoint/resume capability (SURVEY.md
 * §5): system sleep saves framebuffer contents to sysmem and restores
 * them on wake (src/nvidia/src/kernel/gpu/mem_mgr/fbsr.c), while UVM
 * quiesces every entry point behind a global PM lock
 * (kernel-open/nvidia-uvm/uvm_lock.h:43-49 uvm_suspend).
 *
 * tpurm shape:
 *   uvmSuspend():
 *     1. take the PM gate exclusively — uvmMemAlloc/Free, uvmMigrate and
 *        uvmDeviceAccess enter through the shared side, so in-flight
 *        operations drain and new ones block,
 *     2. wait for the fault ring to drain (the service thread keeps
 *        running: CPU faults target HOST only and are safe while device
 *        arenas are frozen),
 *     3. save: record each block's device-side residency (tier + device)
 *        and evict it to host — the exact make_resident machinery the
 *        migration engine uses (SURVEY.md §5: "HBM save/restore == the
 *        same migration machinery pointed at host").
 *   uvmResume():
 *     4. restore: re-make-resident each saved block span on its original
 *        tier (registry uvm_resume_restore=0 keeps restore lazy — the
 *        first fault brings pages back),
 *     5. release the gate.
 *
 * After suspend returns, the HBM/CXL arenas hold no live data: the test
 * scrambles them wholesale and resume must still verify (fbsr semantics).
 */
#include "uvm_internal.h"

#include <sched.h>
#include <stdlib.h>

/* PM gate: mutex+condvar reader-count gate rather than a rwlock, so the
 * suspend/resume pair is NOT thread-owner-bound — POSIX makes unlocking a
 * rwlock from a thread that doesn't hold it UB, and suspend() and
 * resume() are free functions callable from different threads (the
 * reference's semaphore-style PM lock is owner-agnostic the same way). */
static pthread_mutex_t g_pmMutex = PTHREAD_MUTEX_INITIALIZER;
static pthread_cond_t g_pmCond = PTHREAD_COND_INITIALIZER;
static uint32_t g_pmReaders;      /* in-flight entry points */
static bool g_suspended;
static bool g_resuming;           /* resume in progress (claims g_saved) */

void uvmPmEnterShared(void)
{
    pthread_mutex_lock(&g_pmMutex);
    while (g_suspended)
        pthread_cond_wait(&g_pmCond, &g_pmMutex);
    g_pmReaders++;
    pthread_mutex_unlock(&g_pmMutex);
}

void uvmPmExitShared(void)
{
    pthread_mutex_lock(&g_pmMutex);
    if (--g_pmReaders == 0)
        pthread_cond_broadcast(&g_pmCond);
    pthread_mutex_unlock(&g_pmMutex);
}

/* Saved-residency record, one per block span that was device-resident. */
typedef struct PmSaved {
    UvmVaSpace *vs;
    UvmVaBlock *blk;
    UvmTier tier;
    uint32_t devInst;
    uint32_t firstPage, count;
    struct PmSaved *next;
} PmSaved;

static PmSaved *g_saved;          /* valid only while suspended */

static void pm_save_block(UvmVaSpace *vs, UvmVaBlock *blk)
{
    /* Record contiguous device-resident runs, then evict to host. */
    static const UvmTier tiers[] = { UVM_TIER_HBM, UVM_TIER_CXL };
    for (int t = 0; t < 2; t++) {
        UvmTier tier = tiers[t];
        uint32_t p = 0;
        while (p < blk->npages) {
            if (!uvmPageMaskTest(&blk->resident[tier], p)) {
                p++;
                continue;
            }
            uint32_t span = 1;
            while (p + span < blk->npages &&
                   uvmPageMaskTest(&blk->resident[tier], p + span))
                span++;
            PmSaved *s = malloc(sizeof(*s));
            if (s) {
                s->vs = vs;
                s->blk = blk;
                s->tier = tier;
                s->devInst = tier == UVM_TIER_HBM ? blk->hbmDevInst : 0;
                s->firstPage = p;
                s->count = span;
                s->next = g_saved;
                g_saved = s;
            }
            p += span;
        }
        UvmTierArena *arena = tier == UVM_TIER_HBM
                                  ? uvmTierArenaHbm(blk->hbmDevInst)
                                  : uvmTierArenaCxl();
        if (arena &&
            !uvmPageMaskEmpty(&blk->resident[tier], blk->npages)) {
            /* Retry contended blocks: save must be complete. */
            TpuStatus st = TPU_ERR_STATE_IN_USE;
            for (int i = 0; i < 256 && st == TPU_ERR_STATE_IN_USE; i++) {
                st = uvmBlockEvictFrom(blk, arena);
                if (st == TPU_ERR_STATE_IN_USE)
                    sched_yield();
            }
            if (st != TPU_OK)
                TPU_LOG(TPU_LOG_ERROR, "uvm_pm",
                       "suspend: block 0x%llx tier %d save failed: %s",
                       (unsigned long long)blk->start, tier,
                       tpuStatusToString(st));
        }
    }
}

TpuStatus uvmSuspend(void)
{
    /* 1. Exclusive gate: block new entry points, drain in-flight ones. */
    pthread_mutex_lock(&g_pmMutex);
    if (g_suspended) {
        pthread_mutex_unlock(&g_pmMutex);
        return TPU_ERR_INVALID_STATE;
    }
    g_suspended = true;               /* new readers now park in Enter */
    while (g_pmReaders > 0)
        pthread_cond_wait(&g_pmCond, &g_pmMutex);
    pthread_mutex_unlock(&g_pmMutex);

    /* 2. Drain the fault ring (CPU faults may still trickle in; the
     * service thread keeps consuming them — wait for quiescence). */
    uvmFaultRingDrain();

    /* 3. Save device-side residency to host. */
    uvmFaultForEachSpace(pm_save_block);

    tpuCounterAdd("uvm_suspends", 1);
    uvmToolsEmit(NULL, UVM_EVENT_PM_SUSPEND, UVM_TIER_COUNT,
                 UVM_TIER_COUNT, 0, 0, 0);
    TPU_LOG(TPU_LOG_INFO, "uvm_pm", "suspended (arenas saved to host)");
    /* Gate stays closed (g_suspended) until uvmResume — from any thread. */
    return TPU_OK;
}

TpuStatus uvmResume(void)
{
    /* Claim the saved list under the gate mutex so concurrent resumes
     * (or a racing suspend) serialize correctly. */
    pthread_mutex_lock(&g_pmMutex);
    if (!g_suspended || g_resuming) {
        pthread_mutex_unlock(&g_pmMutex);
        return TPU_ERR_INVALID_STATE;
    }
    g_resuming = true;
    PmSaved *s = g_saved;
    g_saved = NULL;
    pthread_mutex_unlock(&g_pmMutex);

    /* 4. Restore saved spans via make_resident (eager fbsr-style restore;
     * registry uvm_resume_restore=0 leaves it to first-fault). */
    bool eager = tpuRegistryGet("uvm_resume_restore", 1) != 0;
    while (s) {
        PmSaved *next = s->next;
        if (eager) {
            UvmLocation dst = { s->tier, s->devInst };
            TpuStatus st = uvmBlockMakeResident(s->blk, dst, s->firstPage,
                                                s->count, false);
            if (st != TPU_OK)
                TPU_LOG(TPU_LOG_WARN, "uvm_pm",
                       "resume: restore 0x%llx +%u failed: %s (lazy fault "
                       "will recover)",
                       (unsigned long long)s->blk->start, s->count,
                       tpuStatusToString(st));
        }
        free(s);
        s = next;
    }

    pthread_mutex_lock(&g_pmMutex);
    g_suspended = false;
    g_resuming = false;
    pthread_cond_broadcast(&g_pmCond);   /* reopen the gate */
    pthread_mutex_unlock(&g_pmMutex);
    tpuCounterAdd("uvm_resumes", 1);
    uvmToolsEmit(NULL, UVM_EVENT_PM_RESUME, UVM_TIER_COUNT,
                 UVM_TIER_COUNT, 0, 0, 0);
    TPU_LOG(TPU_LOG_INFO, "uvm_pm", "resumed");
    return TPU_OK;
}
