/*
 * uvm_hmm — the pageable-memory path: managed semantics for memory the
 * engine did not allocate.
 *
 * Reference capability (uvm_hmm.c, 3,790 LoC; uvm_ats*.c): with HMM,
 * ANY malloc'd/pageable CPU memory is GPU-accessible — device faults on
 * pageable VAs either migrate the pages into vidmem via device-private
 * pages (HMM) or access them in place through the CPU page tables
 * (ATS).  TPU-native shape, both halves:
 *
 *   ATS analog    — uvmDeviceAccess on a VA with no managed range
 *                   services IN PLACE: the span stays in host memory
 *                   (which TPU DMA engines reach anyway — our CE
 *                   consumes host pointers), pages are touched/pinned
 *                   best-effort, and access is accounted.  Gated by
 *                   registry "uvm_disable_hmm" (reference module param
 *                   uvm_disable_hmm, uvm_hmm.c:28-49).
 *   HMM adoption  — uvmPageableAdopt converts an existing anonymous
 *                   mapping into a FULL managed range in place,
 *                   preserving contents (the migrate_vma analog: the
 *                   engine takes ownership of the pages): faults,
 *                   tiering, policies, eviction all apply afterwards.
 *                   Freeing the range restores a plain anonymous
 *                   mapping with the current contents, so the caller's
 *                   allocator (e.g. malloc arena) keeps working.
 *
 * Adoption requires 2 MB block alignment: VA blocks partition fault
 * service by ABSOLUTE 2 MB windows (uvm_fault.c worker_for), so an
 * unaligned managed range would break the one-worker-per-block
 * invariant the perf state depends on.
 */
#define _GNU_SOURCE
#include "uvm_internal.h"

#include <stdio.h>
#include <stdlib.h>
#include <string.h>
#include <sys/mman.h>
#include <sys/uio.h>
#include <unistd.h>

bool uvmHmmEnabled(void)
{
    return tpuRegistryGet("uvm_disable_hmm", 0) == 0;
}

/* True when [base, base+len) lies entirely inside writable private
 * anonymous mappings (rw-p with no backing path) per /proc/self/maps. */
static bool hmm_span_is_private_anon_rw(uintptr_t base, uint64_t len)
{
    FILE *f = fopen("/proc/self/maps", "r");
    if (!f)
        return false;
    uintptr_t need = base, end = base + len;
    char line[512];
    while (need < end && fgets(line, sizeof(line), f)) {
        uintptr_t lo, hi;
        char perms[8] = "";
        uint64_t off;
        unsigned devMaj, devMin;
        uint64_t inode = 1;
        char path[256] = "";
        int n = sscanf(line, "%lx-%lx %7s %lx %x:%x %lu %255s",
                       (unsigned long *)&lo, (unsigned long *)&hi, perms,
                       (unsigned long *)&off, &devMaj, &devMin,
                       (unsigned long *)&inode, path);
        if (n < 7 || hi <= need || lo > need)
            continue;
        if (perms[0] != 'r' || perms[1] != 'w' || perms[3] != 'p' ||
            inode != 0 || (n >= 8 && path[0] == '/'))
            break;              /* wrong kind of mapping */
        need = hi;              /* covered up to here; keep walking */
    }
    fclose(f);
    return need >= end;
}

/* ----------------------------------------------------- ATS-style access */

/* Service a device access to PAGEABLE (non-managed) memory in place.
 * The bytes stay host-resident — TPU DMA reads them through the normal
 * host path — so "service" means: verify the span is readable, touch
 * the pages so they are materialized for DMA, and account the access
 * (reference: service_fault_batch_ats, uvm_ats_faults.c:1892). */
TpuStatus uvmPageableDeviceAccess(UvmVaSpace *vs, uint32_t devInst,
                                  void *base, uint64_t len, int isWrite)
{
    (void)vs;
    (void)devInst;
    if (!uvmHmmEnabled())
        return TPU_ERR_OBJECT_NOT_FOUND;    /* pre-HMM behavior */

    /* Probe + materialize every page WITHOUT risking a fault in the
     * engine: process_vm_readv on our own pid returns EFAULT/partial
     * for unmapped or PROT_NONE pages instead of delivering SIGSEGV,
     * and for writes process_vm_writev proves writability (writing a
     * byte back to itself).  The transient mlock pins the span across
     * the probe and is released (an unbounded pin over every ATS span
     * would pile toward RLIMIT_MEMLOCK). */
    uint64_t ps = (uint64_t)sysconf(_SC_PAGESIZE);
    uintptr_t start = (uintptr_t)base & ~(ps - 1);
    uintptr_t end = ((uintptr_t)base + len + ps - 1) & ~(ps - 1);
    mlock((void *)start, end - start);      /* best-effort */
    pid_t self = getpid();
    for (uintptr_t off = 0; off < end - start; off += ps) {
        uint8_t byte;
        struct iovec lv = { &byte, 1 };
        struct iovec rv = { (void *)(start + off), 1 };
        if (process_vm_readv(self, &lv, 1, &rv, 1, 0) != 1) {
            munlock((void *)start, end - start);
            return TPU_ERR_INVALID_ADDRESS;
        }
        if (isWrite &&
            process_vm_writev(self, &lv, 1, &rv, 1, 0) != 1) {
            munlock((void *)start, end - start);
            return TPU_ERR_INVALID_ADDRESS;   /* not writable */
        }
    }
    munlock((void *)start, end - start);
    tpuCounterAdd("uvm_ats_accesses", 1);
    tpuCounterAdd("uvm_ats_bytes", len);
    uvmToolsEmit(vs, UVM_EVENT_ATS_ACCESS, UVM_TIER_HOST, UVM_TIER_HOST,
                 devInst, (uintptr_t)base, len);
    return TPU_OK;
}

/* --------------------------------------------------------- HMM adoption */

TpuStatus uvmPageableAdopt(UvmVaSpace *vs, void *base, uint64_t len)
{
    if (!vs || !base || len == 0)
        return TPU_ERR_INVALID_ARGUMENT;
    if (!uvmHmmEnabled())
        return TPU_ERR_NOT_SUPPORTED;
    if (((uintptr_t)base & (UVM_BLOCK_SIZE - 1)) ||
        (len & (UVM_BLOCK_SIZE - 1)))
        return TPU_ERR_INVALID_ADDRESS;     /* block-aligned spans only */

    /* The span must be existing writable PRIVATE ANONYMOUS memory:
     * adopting a file-backed or read-only mapping would silently sever
     * file coherence / grant writability (checked against
     * /proc/self/maps — adoption is rare, the parse is cheap). */
    if (!hmm_span_is_private_anon_rw((uintptr_t)base, len))
        return TPU_ERR_INVALID_ADDRESS;

    /* Managed backing: memfd + always-RW engine alias (exactly the
     * mem_alloc layout), preloaded with the CALLER'S BYTES. */
    int memfd = memfd_create("tpurm-uvm-adopt", MFD_CLOEXEC);
    if (memfd < 0)
        return TPU_ERR_OPERATING_SYSTEM;
    if (ftruncate(memfd, (off_t)len) != 0) {
        close(memfd);
        return TPU_ERR_NO_MEMORY;
    }
    void *alias = mmap(NULL, len, PROT_READ | PROT_WRITE, MAP_SHARED,
                       memfd, 0);
    if (alias == MAP_FAILED) {
        close(memfd);
        return TPU_ERR_NO_MEMORY;
    }

    UvmVaRange *range = calloc(1, sizeof(*range));
    UvmVaBlock **blocks = calloc(len / UVM_BLOCK_SIZE, sizeof(*blocks));
    if (!range || !blocks) {
        free(range);
        free(blocks);
        munmap(alias, len);
        close(memfd);
        return TPU_ERR_NO_MEMORY;
    }

    uint64_t ps = uvmPageSize();
    uint32_t ppb = uvmPagesPerBlock();
    range->memfd = memfd;
    range->alias = alias;
    range->node.start = (uintptr_t)base;
    range->node.end = (uintptr_t)base + len - 1;
    range->vaSpace = vs;
    range->type = UVM_RANGE_TYPE_MANAGED;
    range->adopted = true;
    range->size = len;
    range->allocStart = (uintptr_t)base;
    range->allocSize = len;
    range->blockCount = (uint32_t)(len / UVM_BLOCK_SIZE);
    range->blocks = blocks;
    for (uint32_t i = 0; i < range->blockCount; i++) {
        UvmVaBlock *blk = calloc(1, sizeof(*blk));
        if (!blk) {
            for (uint32_t j = 0; j < i; j++)
                free(range->blocks[j]);
            free(blocks);
            free(range);
            munmap(alias, len);
            close(memfd);
            return TPU_ERR_NO_MEMORY;
        }
        pthread_mutex_init(&blk->lock, NULL);
        blk->range = range;
        blk->start = (uintptr_t)base + (uint64_t)i * UVM_BLOCK_SIZE;
        blk->npages = ppb;
        blk->pinnedTier = -1;
        /* Adopted pages are live host data with valid RW PTEs. */
        uvmPageMaskSetRange(&blk->resident[UVM_TIER_HOST], 0, ppb);
        uvmPageMaskSetRange(&blk->cpuMapped, 0, ppb);
        range->blocks[i] = blk;
    }
    (void)ps;

    /* Reserve the span in the tree FIRST (atomic overlap check +
     * insert, so concurrent adopters of overlapping spans cannot both
     * proceed to the MAP_FIXED swap), then swap the backing under the
     * VA: the memfd mapping replaces the anonymous pages in place
     * (contents identical, so the caller observes nothing). */
    pthread_mutex_lock(&vs->lock);
    tpuLockTrackAcquire(TPU_LOCK_UVM_VASPACE, "hmm-adopt");
    TpuStatus st = uvmRangeTreeAdd(&vs->ranges, &range->node);
    tpuLockTrackRelease(TPU_LOCK_UVM_VASPACE, "hmm-adopt");
    pthread_mutex_unlock(&vs->lock);
    if (st != TPU_OK) {
        for (uint32_t i = 0; i < range->blockCount; i++)
            free(range->blocks[i]);
        free(blocks);
        free(range);
        munmap(alias, len);
        close(memfd);
        return st == TPU_ERR_STATE_IN_USE ? TPU_ERR_INSERT_DUPLICATE_NAME
                                          : st;
    }
    /* Take ownership of the bytes immediately before the swap.  The
     * copy->swap window is not atomic: a concurrent writer to the span
     * can lose its store (same contract as the kernel's migrate_vma —
     * the caller must quiesce writers while adopting). */
    memcpy(alias, base, len);
    if (mmap(base, len, PROT_READ | PROT_WRITE,
             MAP_SHARED | MAP_FIXED, memfd, 0) == MAP_FAILED) {
        pthread_mutex_lock(&vs->lock);
        tpuLockTrackAcquire(TPU_LOCK_UVM_VASPACE, "hmm-adopt");
        uvmRangeTreeRemove(&vs->ranges, &range->node);
        tpuLockTrackRelease(TPU_LOCK_UVM_VASPACE, "hmm-adopt");
        pthread_mutex_unlock(&vs->lock);
        for (uint32_t i = 0; i < range->blockCount; i++)
            free(range->blocks[i]);
        free(blocks);
        free(range);
        munmap(alias, len);
        close(memfd);
        return TPU_ERR_OPERATING_SYSTEM;
    }
    uvmFaultSnapshotRebuild();
    tpuCounterAdd("uvm_hmm_adoptions", 1);
    uvmToolsEmit(vs, UVM_EVENT_HMM_ADOPT, UVM_TIER_HOST, UVM_TIER_HOST,
                 0, (uintptr_t)base, len);
    TPU_LOG(TPU_LOG_INFO, "uvm", "adopted pageable span %p + %llu MB",
           base, (unsigned long long)(len >> 20));
    return TPU_OK;
}

/* Called by range_destroy for adopted ranges (vs lock held): put a
 * plain anonymous mapping with the CURRENT contents back under the VA
 * so the caller's allocator keeps working.  The engine alias always
 * reflects the memfd (host tier); pages resident only device-side are
 * pulled home by the migrate in uvmMemFree's adopted pre-pass. */
void uvmHmmRestoreOnDestroy(UvmVaRange *range)
{
    void *base = (void *)(uintptr_t)range->node.start;
    if (mmap(base, range->size, PROT_READ | PROT_WRITE,
             MAP_PRIVATE | MAP_ANONYMOUS | MAP_FIXED, -1, 0) == MAP_FAILED)
        return;                 /* VA lost; nothing safe to do */
    memcpy(base, range->alias, range->size);
}
