/*
 * UVM ioctl dispatch — the /dev/nvidia-uvm surface.
 *
 * Re-design of the reference's route table (kernel-open/nvidia-uvm/
 * uvm.c:1026-1070): each pseudo-fd owns a VA space created at
 * UVM_INITIALIZE (uvm.c:144 uvm_open + UVM_INITIALIZE semantics —
 * calling other ioctls first returns NV_ERR_ILLEGAL_ACTION-equivalent
 * INVALID_STATE), raw command numbers (not _IOWR encodings), rmStatus
 * carried inside the param block with ioctl(2) returning 0.
 *
 * Processor UUID convention (uvm.h): zero = CPU, "TPU\0"+LE32(inst) =
 * device HBM, "CXL\0" = the CXL tier.  The reference addresses processors
 * by real GPU UUIDs; tpurm devices synthesize stable UUIDs from their
 * instance number (the reference's are just opaque 16-byte cookies to
 * userspace too).
 */
#include "uvm_internal.h"

#include <errno.h>
#include <stdlib.h>
#include <string.h>

typedef struct {
    /* rwlock: INITIALIZE/DEINITIALIZE take the write side; every other
     * ioctl holds the read side for its whole duration, so a racing
     * DEINITIALIZE cannot free the VA space under an in-flight migrate
     * (the rmapi fd refcount only orders against tpurm_close). */
    pthread_rwlock_t lock;
    UvmVaSpace *vs;              /* NULL until UVM_INITIALIZE */
    UvmToolsSession *tools;      /* NULL until TOOLS_INIT_EVENT_TRACKER */
    /* Pin count for paths that reach this state OUTSIDE the rmapi fd
     * table (the munmap hook): close waits for pins to drain before
     * tearing the state down. */
    pthread_mutex_t pinLock;
    pthread_cond_t pinCond;
    uint32_t pins;
} UvmFdState;

/* ------------------------------------------------------- uuid conversion */

static void uuid_for_device(uint32_t inst, UvmProcessorUuid *u)
{
    memset(u, 0, sizeof(*u));
    u->uuid[0] = 'T';
    u->uuid[1] = 'P';
    u->uuid[2] = 'U';
    memcpy(&u->uuid[4], &inst, sizeof(inst));
}

/* Returns false if the uuid encodes no known processor. */
static bool uuid_to_location(const UvmProcessorUuid *u, UvmLocation *out)
{
    static const uint8_t zeros[16];
    if (memcmp(u->uuid, zeros, 16) == 0) {
        out->tier = UVM_TIER_HOST;
        out->devInst = 0;
        return true;
    }
    if (u->uuid[0] == 'T' && u->uuid[1] == 'P' && u->uuid[2] == 'U' &&
        u->uuid[3] == 0) {
        out->tier = UVM_TIER_HBM;
        memcpy(&out->devInst, &u->uuid[4], sizeof(out->devInst));
        return true;
    }
    if (u->uuid[0] == 'C' && u->uuid[1] == 'X' && u->uuid[2] == 'L' &&
        u->uuid[3] == 0) {
        out->tier = UVM_TIER_CXL;
        out->devInst = 0;
        return true;
    }
    return false;
}

/* ------------------------------------------------------------ fd plumbing */

static void mmap_registry_purge(UvmFdState *fd);

void *tpuUvmFdOpen(void)
{
    UvmFdState *fd = calloc(1, sizeof(UvmFdState));
    if (fd) {
        pthread_rwlock_init(&fd->lock, NULL);
        pthread_mutex_init(&fd->pinLock, NULL);
        pthread_cond_init(&fd->pinCond, NULL);
    }
    return fd;
}

void tpuUvmFdClose(void *state)
{
    UvmFdState *fd = state;
    if (!fd)
        return;
    /* Purge BEFORE taking fd->lock: the munmap hook holds the registry
     * lock across its fd->lock acquisition, so close must never hold
     * fd->lock while waiting on the registry (lock-order: registry
     * first, fd->lock second, everywhere). */
    mmap_registry_purge(fd);
    /* Wait for hook-held pins: a hook that unlinked its entry before
     * our purge still owns a pin taken under the registry lock (where
     * the fd was provably alive); destruction must not race it. */
    pthread_mutex_lock(&fd->pinLock);
    while (fd->pins > 0)
        pthread_cond_wait(&fd->pinCond, &fd->pinLock);
    pthread_mutex_unlock(&fd->pinLock);
    pthread_rwlock_wrlock(&fd->lock);
    if (fd->tools)
        uvmToolsSessionDestroy(fd->tools);
    if (fd->vs)
        uvmVaSpaceDestroy(fd->vs);
    fd->tools = NULL;
    fd->vs = NULL;
    pthread_rwlock_unlock(&fd->lock);
    pthread_rwlock_destroy(&fd->lock);
    pthread_mutex_destroy(&fd->pinLock);
    pthread_cond_destroy(&fd->pinCond);
    free(fd);
}

/* ------------------------------------------------------------ mmap surface
 *
 * The reference creates managed ranges by mmap'ing /dev/nvidia-uvm
 * (uvm_mmap, reference uvm.c:792) — the vma IS the managed range and
 * munmap frees it via vm_ops.  Analog: mmap on a uvm pseudo-fd routes
 * here, allocates a managed range in the fd's VA space, and records the
 * (base -> fd) association so the interposed munmap can free it. */

typedef struct MmapRangeReg {
    uintptr_t base;
    uint64_t len;
    UvmFdState *fd;
    struct MmapRangeReg *next;
} MmapRangeReg;

static pthread_mutex_t g_mmapLock = PTHREAD_MUTEX_INITIALIZER;
static MmapRangeReg *g_mmapHead;

int tpuUvmFdMmap(void *state, uint64_t length, void **outBase)
{
    UvmFdState *fd = state;
    if (!fd || !outBase || length == 0) {
        errno = EINVAL;
        return -1;
    }
    MmapRangeReg *reg = calloc(1, sizeof(*reg));
    if (!reg) {
        errno = ENOMEM;
        return -1;
    }
    pthread_rwlock_rdlock(&fd->lock);
    if (!fd->vs) {
        pthread_rwlock_unlock(&fd->lock);
        free(reg);
        errno = EINVAL;          /* mmap before UVM_INITIALIZE */
        return -1;
    }
    void *base = NULL;
    TpuStatus st = uvmMemAlloc(fd->vs, length, &base);
    pthread_rwlock_unlock(&fd->lock);
    if (st != TPU_OK) {
        free(reg);
        errno = ENOMEM;
        return -1;
    }
    reg->base = (uintptr_t)base;
    reg->len = length;
    reg->fd = fd;
    pthread_mutex_lock(&g_mmapLock);
    reg->next = g_mmapHead;
    g_mmapHead = reg;
    pthread_mutex_unlock(&g_mmapLock);
    *outBase = base;
    return 0;
}

int tpuUvmMunmapHook(void *addr, uint64_t length)
{
    (void)length;   /* like the reference vma teardown, the whole range
                     * goes (partial munmap of a managed range is not a
                     * supported split operation here) */
    /* Unlink FIRST, free with no registry lock held: range_destroy
     * munmaps the range VA, which under the LD_PRELOAD shim re-enters
     * this hook — the entry being already gone makes that re-entry a
     * harmless miss instead of a self-deadlock on g_mmapLock. */
    pthread_mutex_lock(&g_mmapLock);
    MmapRangeReg *found = NULL;
    for (MmapRangeReg **pp = &g_mmapHead; *pp; pp = &(*pp)->next) {
        if ((*pp)->base == (uintptr_t)addr) {
            found = *pp;
            *pp = found->next;
            break;
        }
    }
    UvmFdState *fd = found ? found->fd : NULL;
    if (fd) {
        /* Pin the fd state WHILE the registry lock still proves it
         * alive (close purges the registry before freeing, under this
         * same lock): close then waits for the pin to drain. */
        pthread_mutex_lock(&fd->pinLock);
        fd->pins++;
        pthread_mutex_unlock(&fd->pinLock);
    }
    pthread_mutex_unlock(&g_mmapLock);
    if (!found)
        return 0;
    pthread_rwlock_rdlock(&fd->lock);
    if (fd->vs)
        uvmMemFree(fd->vs, addr);
    pthread_rwlock_unlock(&fd->lock);
    pthread_mutex_lock(&fd->pinLock);
    fd->pins--;
    pthread_cond_broadcast(&fd->pinCond);
    pthread_mutex_unlock(&fd->pinLock);
    free(found);
    return 1;
}

/* Called by range_destroy for EVERY managed range teardown: frees done
 * through UVM_FREE/uvmMemFree (not munmap) must still drop their
 * registry entry, or a later munmap at a recycled address would be
 * falsely consumed against a dangling fd. */
void uvmMmapRegistryOnRangeDestroy(uint64_t base)
{
    pthread_mutex_lock(&g_mmapLock);
    for (MmapRangeReg **pp = &g_mmapHead; *pp; pp = &(*pp)->next) {
        if ((*pp)->base == base) {
            MmapRangeReg *dead = *pp;
            *pp = dead->next;
            free(dead);
            break;
        }
    }
    pthread_mutex_unlock(&g_mmapLock);
}

static void mmap_registry_purge(UvmFdState *fd)
{
    pthread_mutex_lock(&g_mmapLock);
    MmapRangeReg **pp = &g_mmapHead;
    while (*pp) {
        if ((*pp)->fd == fd) {
            MmapRangeReg *dead = *pp;
            *pp = dead->next;
            free(dead);          /* ranges die with the VA space */
        } else {
            pp = &(*pp)->next;
        }
    }
    pthread_mutex_unlock(&g_mmapLock);
}

/* ---------------------------------------------------------------- dispatch */

static int uvm_fd_dispatch(UvmFdState *fd, UvmVaSpace *vs,
                           unsigned long request, void *argp);

int tpuUvmFdIoctl(void *state, unsigned long request, void *argp)
{
    UvmFdState *fd = state;
    if (!fd) {
        errno = EBADF;
        return -1;
    }

    if (request == UVM_INITIALIZE) {
        UvmInitializeParams *p = argp;
        pthread_rwlock_wrlock(&fd->lock);
        if (fd->vs)
            p->rmStatus = TPU_OK;    /* idempotent, like the reference */
        else
            p->rmStatus = uvmVaSpaceCreate(&fd->vs);
        pthread_rwlock_unlock(&fd->lock);
        return 0;
    }
    if (request == UVM_DEINITIALIZE) {
        pthread_rwlock_wrlock(&fd->lock);
        if (fd->tools) {
            uvmToolsSessionDestroy(fd->tools);
            fd->tools = NULL;
        }
        if (fd->vs) {
            uvmVaSpaceDestroy(fd->vs);
            fd->vs = NULL;
        }
        pthread_rwlock_unlock(&fd->lock);
        return 0;
    }

    pthread_rwlock_rdlock(&fd->lock);
    if (!fd->vs) {
        pthread_rwlock_unlock(&fd->lock);
        /* Reference: ioctls before UVM_INITIALIZE fail
         * (uvm_ioctl.h:1069-1084 comment). rmStatus is the first u32
         * field in some param structs but not all; INVALID_STATE via
         * errno is the transport-level contract here. */
        errno = EINVAL;
        return -1;
    }
    int rc = uvm_fd_dispatch(fd, fd->vs, request, argp);
    pthread_rwlock_unlock(&fd->lock);
    return rc;
}

/* Dispatch with fd->lock held (read side). */
static int uvm_fd_dispatch(UvmFdState *fd, UvmVaSpace *vs,
                           unsigned long request, void *argp)
{
    switch (request) {
    case UVM_REGISTER_GPU: {
        UvmRegisterGpuParams *p = argp;
        UvmLocation loc;
        static const uint8_t zeros[16];
        if (memcmp(p->gpuUuid.uuid, zeros, 16) == 0) {
            /* Unspecified: register device 0 and report its UUID. */
            loc.tier = UVM_TIER_HBM;
            loc.devInst = 0;
        } else if (!uuid_to_location(&p->gpuUuid, &loc) ||
                   loc.tier != UVM_TIER_HBM) {
            p->rmStatus = TPU_ERR_INVALID_DEVICE;
            return 0;
        }
        p->rmStatus = uvmRegisterDevice(vs, loc.devInst);
        if (p->rmStatus == TPU_OK) {
            uuid_for_device(loc.devInst, &p->gpuUuid);
            p->numaEnabled = 0;
            p->numaNodeId = -1;
        }
        return 0;
    }
    case UVM_UNREGISTER_GPU: {
        UvmUnregisterGpuParams *p = argp;
        UvmLocation loc;
        if (!uuid_to_location(&p->gpuUuid, &loc) ||
            loc.tier != UVM_TIER_HBM) {
            p->rmStatus = TPU_ERR_INVALID_DEVICE;
            return 0;
        }
        p->rmStatus = uvmUnregisterDevice(vs, loc.devInst);
        return 0;
    }
    case UVM_PAGEABLE_MEM_ACCESS: {
        /* HMM/ATS analog wired (uvm_hmm.c): pageable memory is device
         * accessible unless registry uvm_disable_hmm is set (reference
         * uvm_hmm.c:28-49 module param). */
        struct { uint8_t pageableMemAccess; } *p = argp;
        p->pageableMemAccess = uvmHmmEnabled() ? 1 : 0;
        return 0;
    }
    case UVM_TPU_ADOPT_PAGEABLE: {
        UvmAdoptPageableParams *p = argp;
        p->rmStatus = uvmPageableAdopt(vs, (void *)(uintptr_t)p->base,
                                       p->length);
        return 0;
    }
    case UVM_TPU_ALLOC_MANAGED: {
        UvmTpuAllocManagedParams *p = argp;
        void *ptr = NULL;
        p->rmStatus = uvmMemAlloc(vs, p->length, &ptr);
        p->base = (uintptr_t)ptr;
        return 0;
    }
    case UVM_FREE: {
        UvmFreeParams *p = argp;
        p->rmStatus = uvmMemFree(vs, (void *)(uintptr_t)p->base);
        return 0;
    }
    case UVM_MIGRATE: {
        UvmMigrateParams *p = argp;
        UvmLocation dst;
        if (!uuid_to_location(&p->destinationUuid, &dst)) {
            p->rmStatus = TPU_ERR_INVALID_DEVICE;
            return 0;
        }
        p->userSpaceStart = p->base;
        p->userSpaceLength = p->length;
        p->rmStatus = uvmMigrate(vs, (void *)(uintptr_t)p->base, p->length,
                                 dst, p->flags);
        /* Reference semantics: semaphore released on completion
         * (uvm_migrate.c:735); completion is synchronous here. */
        if (p->rmStatus == TPU_OK && p->semaphoreAddress)
            *(volatile uint32_t *)(uintptr_t)p->semaphoreAddress =
                p->semaphorePayload;
        return 0;
    }
    case UVM_SET_PREFERRED_LOCATION: {
        UvmSetPreferredLocationParams *p = argp;
        UvmLocation loc;
        if (!uuid_to_location(&p->preferredLocation, &loc)) {
            p->rmStatus = TPU_ERR_INVALID_DEVICE;
            return 0;
        }
        p->rmStatus = uvmSetPreferredLocation(
            vs, (void *)(uintptr_t)p->requestedBase, p->length, loc);
        return 0;
    }
    case UVM_UNSET_PREFERRED_LOCATION: {
        UvmRangeOpParams *p = argp;
        p->rmStatus = uvmUnsetPreferredLocation(
            vs, (void *)(uintptr_t)p->requestedBase, p->length);
        return 0;
    }
    case UVM_ENABLE_READ_DUPLICATION:
    case UVM_DISABLE_READ_DUPLICATION: {
        UvmRangeOpParams *p = argp;
        p->rmStatus = uvmSetReadDuplication(
            vs, (void *)(uintptr_t)p->requestedBase, p->length,
            request == UVM_ENABLE_READ_DUPLICATION);
        return 0;
    }
    case UVM_SET_ACCESSED_BY:
    case UVM_UNSET_ACCESSED_BY: {
        UvmAccessedByParams *p = argp;
        UvmLocation loc;
        if (!uuid_to_location(&p->accessedByUuid, &loc) ||
            loc.tier != UVM_TIER_HBM) {
            p->rmStatus = TPU_ERR_INVALID_DEVICE;
            return 0;
        }
        void *base = (void *)(uintptr_t)p->requestedBase;
        p->rmStatus = request == UVM_SET_ACCESSED_BY
                          ? uvmSetAccessedBy(vs, base, p->length, loc.devInst)
                          : uvmUnsetAccessedBy(vs, base, p->length,
                                               loc.devInst);
        return 0;
    }
    case UVM_CREATE_RANGE_GROUP: {
        UvmRangeGroupParams *p = argp;
        p->rmStatus = uvmRangeGroupCreate(vs, &p->rangeGroupId);
        return 0;
    }
    case UVM_DESTROY_RANGE_GROUP: {
        UvmRangeGroupParams *p = argp;
        p->rmStatus = uvmRangeGroupDestroy(vs, p->rangeGroupId);
        return 0;
    }
    case UVM_SET_RANGE_GROUP: {
        UvmSetRangeGroupParams *p = argp;
        p->rmStatus = uvmRangeGroupSet(vs, p->rangeGroupId,
                                       (void *)(uintptr_t)p->requestedBase,
                                       p->length);
        return 0;
    }
    case UVM_PREVENT_MIGRATION_RANGE_GROUPS:
    case UVM_ALLOW_MIGRATION_RANGE_GROUPS: {
        UvmRangeGroupMigrationParams *p = argp;
        const uint64_t *ids = (const uint64_t *)(uintptr_t)p->rangeGroupIds;
        if (!ids && p->numGroupIds) {
            p->rmStatus = TPU_ERR_INVALID_ARGUMENT;
            return 0;
        }
        TpuStatus st = TPU_OK;
        for (uint64_t i = 0; i < p->numGroupIds && st == TPU_OK; i++)
            st = uvmRangeGroupSetMigratable(
                vs, ids[i], request == UVM_ALLOW_MIGRATION_RANGE_GROUPS);
        p->rmStatus = st;
        return 0;
    }
    case UVM_TPU_SET_COMPRESSIBLE: {
        UvmTpuSetCompressibleParams *p = argp;
        p->rmStatus = uvmSetCompressible(
            vs, (void *)(uintptr_t)p->base, p->length, p->format);
        return 0;
    }
    case UVM_TPU_SET_TENANT: {
        /* Per-client QoS: configure the tenant and bind the calling VA
         * space to it — one call gives a broker client its quota
         * identity (the serving scheduler's admission/eviction policy
         * reads usage against these quotas). */
        UvmTpuSetTenantParams *p = argp;
        p->rmStatus = uvmTenantConfigure(p->tenantId, p->priority,
                                         p->hbmQuotaPages,
                                         p->cxlQuotaPages);
        if (p->rmStatus == TPU_OK)
            p->rmStatus = uvmVaSpaceBindTenant(vs, p->tenantId);
        return 0;
    }
    case UVM_TPU_DEVICE_ACCESS: {
        UvmTpuDeviceAccessParams *p = argp;
        UvmLocation loc;
        if (!uuid_to_location(&p->processorUuid, &loc) ||
            loc.tier != UVM_TIER_HBM) {
            p->rmStatus = TPU_ERR_INVALID_DEVICE;
            return 0;
        }
        p->rmStatus = uvmDeviceAccess(vs, loc.devInst,
                                      (void *)(uintptr_t)p->base, p->length,
                                      p->isWrite != 0);
        return 0;
    }
    case UVM_TPU_RESIDENCY_INFO: {
        UvmTpuResidencyInfoParams *p = argp;
        UvmResidencyInfo info;
        p->rmStatus = uvmResidencyInfo(vs, (void *)(uintptr_t)p->address,
                                       &info);
        if (p->rmStatus == TPU_OK) {
            p->residentHost = info.residentHost;
            p->residentHbm = info.residentHbm;
            p->residentCxl = info.residentCxl;
            p->residentRemote = info.residentRemote;
            p->remoteLenderInst = info.remoteLenderInst;
            p->hbmDeviceInst = info.hbmDeviceInst;
            p->cpuMapped = info.cpuMapped;
            p->pinnedTier = (uint32_t)info.pinnedTier;
            p->hbmOffset = info.hbmOffset;
        }
        return 0;
    }
    case UVM_RUN_TEST: {
        UvmRunTestParams *p = argp;
        p->rmStatus = uvmRunTest(vs, p->testCmd);
        return 0;
    }
    case UVM_CREATE_EXTERNAL_RANGE: {
        UvmExternalRangeParams *p = argp;
        p->rmStatus = uvmExternalRangeCreate(
            vs, (void *)(uintptr_t)p->base, p->length);
        return 0;
    }
    case UVM_MAP_EXTERNAL_ALLOCATION: {
        UvmMapExternalAllocationParams *p = argp;
        p->rmStatus = uvmMapExternal(
            vs, (void *)(uintptr_t)p->base, p->length,
            (struct TpuDmabuf *)(uintptr_t)p->dmabufHandle, p->offset);
        return 0;
    }
    case UVM_UNMAP_EXTERNAL: {
        UvmExternalRangeParams *p = argp;
        p->rmStatus = uvmUnmapExternal(
            vs, (void *)(uintptr_t)p->base, p->length);
        return 0;
    }
    case UVM_TOOLS_GET_PROCESSOR_UUID_TABLE: {
        UvmToolsGetProcessorUuidTableParams *p = argp;
        UvmProcessorUuid *table =
            (UvmProcessorUuid *)(uintptr_t)p->tablePtr;
        uint32_t ndev = tpurmDeviceCount();
        uint64_t needed = 1 + (uint64_t)ndev + 1;  /* CPU + devs + CXL */
        if (!table) {
            p->rmStatus = TPU_ERR_INVALID_ARGUMENT;
            return 0;
        }
        if (p->count < needed) {
            /* No silent truncation: report the required capacity. */
            p->count = needed;
            p->rmStatus = TPU_ERR_INVALID_LIMIT;
            return 0;
        }
        uint64_t n = 0;
        memset(&table[n++], 0, sizeof(table[0]));        /* CPU */
        for (uint32_t d = 0; d < ndev; d++)
            uuid_for_device(d, &table[n++]);
        memset(&table[n], 0, sizeof(table[0]));          /* CXL tier */
        table[n].uuid[0] = 'C';
        table[n].uuid[1] = 'X';
        table[n].uuid[2] = 'L';
        n++;
        p->count = n;
        p->rmStatus = TPU_OK;
        return 0;
    }
    case UVM_TOOLS_INIT_EVENT_TRACKER: {
        /* In-process sessions replace the reference's mmap'd queues; the
         * param block's buffer pointers are unused (uvm.h note). */
        UvmToolsInitEventTrackerParams *p = argp;
        uint32_t cap = 1024;
        if (p->queueBufferSize)
            cap = (uint32_t)(p->queueBufferSize > 1u << 20
                                 ? 1u << 20 : p->queueBufferSize);
        if (fd->tools)
            p->rmStatus = TPU_OK;          /* idempotent */
        else
            p->rmStatus = uvmToolsSessionCreate(vs, cap, &fd->tools);
        return 0;
    }
    case UVM_TOOLS_EVENT_QUEUE_ENABLE_EVENTS:
    case UVM_TOOLS_EVENT_QUEUE_DISABLE_EVENTS: {
        UvmToolsEventControlParams *p = argp;
        if (!fd->tools) {
            p->rmStatus = TPU_ERR_INVALID_STATE;   /* tracker not inited */
            return 0;
        }
        if (request == UVM_TOOLS_EVENT_QUEUE_ENABLE_EVENTS)
            uvmToolsEnableEventTypes(fd->tools, p->eventTypeFlags);
        else
            uvmToolsDisableEventTypes(fd->tools, p->eventTypeFlags);
        p->rmStatus = TPU_OK;
        return 0;
    }
    case UVM_TOOLS_ENABLE_COUNTERS:
    case UVM_TOOLS_DISABLE_COUNTERS: {
        UvmToolsCountersParams *p = argp;
        if (!fd->tools) {
            p->rmStatus = TPU_ERR_INVALID_STATE;
            return 0;
        }
        uvmToolsSetCountersEnabled(fd->tools,
                                   request == UVM_TOOLS_ENABLE_COUNTERS);
        p->rmStatus = TPU_OK;
        return 0;
    }
    case UVM_TOOLS_SET_NOTIFICATION_THRESHOLD: {
        UvmToolsSetNotificationThresholdParams *p = argp;
        if (!fd->tools) {
            p->rmStatus = TPU_ERR_INVALID_STATE;
            return 0;
        }
        uvmToolsSetNotificationThreshold(fd->tools,
                                         p->notificationThreshold);
        p->rmStatus = TPU_OK;
        return 0;
    }
    case UVM_TOOLS_FLUSH_EVENTS: {
        /* The in-process ring has no kernel-side buffering to flush:
         * everything emitted is already visible to uvmToolsReadEvents.
         * Success is therefore honest, but only with a live session. */
        UvmToolsFlushEventsParams *p = argp;
        p->rmStatus = fd->tools ? TPU_OK : TPU_ERR_INVALID_STATE;
        return 0;
    }
    default:
        errno = ENOTTY;
        return -1;
    }
}
