/*
 * VA block — the 2 MB-granularity residency state machine.
 *
 * Re-design of the reference's single biggest file (uvm_va_block.c,
 * 13,711 LoC): per-page residency masks across tiers, copy staging through
 * the DMA channel engine, host PTE management, and eviction.  The TPU
 * build collapses the reference's 8-arch HAL surface to one backing model
 * (tier arenas resolved to host-addressable windows; real-chip HBM traffic
 * is submitted by the Python runtime through XLA) and restricts a block's
 * HBM residency to one device at a time; read duplication spans
 * HOST/HBM/CXL (reference: uvm_va_block_make_resident:5086,
 * block_copy_resident_pages:4660).
 *
 * State invariants (asserted by the in-module VA_BLOCK test):
 *   - resident[t] page sets are disjoint across tiers unless the range has
 *     read duplication enabled,
 *   - cpuMapped ⊆ resident[HOST],
 *   - every page in resident[HBM] / resident[CXL] is covered by a chunk
 *     run in the matching arena,
 *   - a page resident nowhere reads as zeroes on first access (first-touch
 *     population).
 */
#define _GNU_SOURCE
#include "uvm_internal.h"
#include "tpurm/ce.h"
#include "tpurm/shield.h"
#include "tpurm/trace.h"
#include "tpurm/inject.h"

#include <sched.h>
#include <stdlib.h>
#include <string.h>
#include <sys/mman.h>

/* ------------------------------------------------------------- run utils */

static UvmChunkRun **runs_head(UvmVaBlock *blk, UvmTier tier)
{
    return tier == UVM_TIER_CXL ? &blk->cxlRuns : &blk->hbmRuns;
}

static UvmChunkRun *run_find(UvmVaBlock *blk, UvmTier tier, uint32_t page)
{
    for (UvmChunkRun *r = *runs_head(blk, tier); r; r = r->next)
        if (page >= r->firstPage && page < r->firstPage + r->numPages)
            return r;
    return NULL;
}

/* Host-addressable pointer for `page` in `tier` (NULL if no backing).
 * For HOST this is the ENGINE ALIAS, not the user VA: the alias is
 * always RW, so CE copies never depend on (or race with) user-PTE
 * protection — protection changes commit strictly after the copies
 * they order against. */
static void *tier_page_ptr(UvmVaBlock *blk, UvmTier tier, uint32_t page)
{
    uint64_t ps = uvmPageSize();
    if (tier == UVM_TIER_HOST) {
        UvmVaRange *range = blk->range;
        uint64_t off = blk->start - range->node.start + (uint64_t)page * ps;
        return (char *)range->alias + off;
    }
    UvmChunkRun *r = run_find(blk, tier, page);
    if (!r)
        return NULL;
    return (char *)r->arena->base + r->chunk->offset +
           (uint64_t)(page - r->firstPage) * ps;
}

bool uvmBlockHbmArenaOffset(UvmVaBlock *blk, uint32_t page,
                            uint64_t *outOffset)
{
    UvmChunkRun *r = run_find(blk, UVM_TIER_HBM, page);
    if (!r)
        return false;
    *outOffset = r->chunk->offset +
                 (uint64_t)(page - r->firstPage) * uvmPageSize();
    return true;
}

/* tpushield exports (shield.c runs the CRC ladder over these). */
void *uvmBlockPagePtr(UvmVaBlock *blk, UvmTier tier, uint32_t page)
{
    return tier_page_ptr(blk, tier, page);
}

bool uvmBlockTierOffset(UvmVaBlock *blk, UvmTier tier, uint32_t page,
                        uint64_t *outOffset)
{
    UvmChunkRun *r = run_find(blk, tier, page);
    if (!r)
        return false;
    *outOffset = r->chunk->offset +
                 (uint64_t)(page - r->firstPage) * uvmPageSize();
    return true;
}

/* ------------------------------------------------- device MMU wiring */

/* Arena offset of `page` in `tier` (HBM/CXL only; blk->lock held). */
static bool block_tier_offset(UvmVaBlock *blk, UvmTier tier, uint32_t page,
                              uint64_t *outOffset)
{
    UvmChunkRun *r = run_find(blk, tier, page);
    if (!r)
        return false;
    *outOffset = r->chunk->offset +
                 (uint64_t)(page - r->firstPage) * uvmPageSize();
    return true;
}

/* Install device PTEs for every page of the span resident in a device
 * aperture (HBM first, CXL second; host-resident pages carry no PTE —
 * the sysmem path flows through CE host pointers).  blk->lock held. */
void uvmBlockPtePopulate(UvmVaBlock *blk, uint32_t firstPage,
                         uint32_t count, uint32_t devInst, bool writable)
{
    uint64_t ps = uvmPageSize();
    UvmPteBatch pb;
    uvmPteBatchBegin(&pb, devInst);
    for (uint32_t p = firstPage; p < firstPage + count; p++) {
        uint64_t off;
        uint64_t va = blk->start + (uint64_t)p * ps;
        if (uvmPageMaskTest(&blk->resident[UVM_TIER_HBM], p) &&
            block_tier_offset(blk, UVM_TIER_HBM, p, &off))
            uvmPteBatchWrite(&pb, va, UVM_TIER_HBM, off, writable);
        else if (uvmPageMaskTest(&blk->resident[UVM_TIER_CXL], p) &&
                 block_tier_offset(blk, UVM_TIER_CXL, p, &off))
            uvmPteBatchWrite(&pb, va, UVM_TIER_CXL, off, writable);
    }
    uvmPteBatchEnd(&pb);
    blk->devPtesLive = true;
    /* tpushield: a WRITABLE device PTE means the device may mutate the
     * span behind the engine's back — every seal under it is stale the
     * moment the translation lands. */
    if (writable && blk->shield)
        uvmShieldUnsealRange(blk, firstPage, count, -1);
}

/* Revoke device PTEs for the span on EVERY device and issue one TLB
 * invalidate per device (uvm_tlb_batch economy).  Called on any
 * transition that moves or drops aperture residency.  blk->lock held. */
void uvmBlockPteRevoke(UvmVaBlock *blk, uint32_t firstPage, uint32_t count)
{
    /* Blocks no device ever mapped (CPU-only traffic) skip the
     * per-device table walks entirely — this runs on every fault-commit
     * and every exclusive write. */
    if (!blk->devPtesLive)
        return;
    uint64_t ps = uvmPageSize();
    uint32_t ndev = tpurmDeviceCount();
    for (uint32_t d = 0; d < ndev; d++) {
        UvmPteBatch pb;
        UvmTlbBatch tb;
        uvmPteBatchBegin(&pb, d);
        uvmTlbBatchBegin(&tb, d);
        for (uint32_t p = firstPage; p < firstPage + count; p++)
            uvmPteBatchClear(&pb, blk->start + (uint64_t)p * ps);
        uvmPteBatchEnd(&pb);
        /* Invalidate only when a LIVE translation was torn down — CPU
         * faults on host-only blocks must not thrash every device's
         * translation caches. */
        if (pb.clearedLive) {
            uvmTlbBatchAdd(&tb, blk->start + (uint64_t)firstPage * ps,
                           count);
            uvmTlbBatchEnd(&tb);
        }
    }
    if (firstPage == 0 && count == blk->npages)
        blk->devPtesLive = false;
}

/* Allocate backing runs in `arena` covering every page of [first,
 * first+count) that lacks one.  Greedy largest-pow2 chunks.  Returns
 * TPU_ERR_NO_MEMORY if the arena is exhausted (caller evicts + retries). */
static TpuStatus block_alloc_backing(UvmVaBlock *blk, UvmTierArena *arena,
                                     uint32_t first, uint32_t count)
{
    uint64_t ps = uvmPageSize();
    uint32_t p = first;
    while (p < first + count) {
        if (run_find(blk, arena->tier, p)) {
            p++;
            continue;
        }
        /* Maximal uncovered gap starting at p. */
        uint32_t gap = 1;
        while (p + gap < first + count &&
               !run_find(blk, arena->tier, p + gap))
            gap++;
        /* Cover the gap with greedy power-of-two chunks. */
        uint32_t covered = 0;
        while (covered < gap) {
            uint32_t left = gap - covered;
            uint64_t want = ps;
            while (want * 2 <= (uint64_t)left * ps &&
                   want * 2 <= UVM_BLOCK_SIZE)
                want *= 2;
            UvmPmmChunk *chunk;
            TpuStatus st = uvmPmmAlloc(&arena->pmm, want, &chunk);
            if (st != TPU_OK)
                return st;
            /* tpushield invariant detector: a fresh chunk must never
             * overlap a retired span (the retire path leaks the chunk
             * precisely so this cannot happen). */
            uvmShieldCheckAlloc(arena, chunk->offset, want);
            UvmChunkRun *run = calloc(1, sizeof(*run));
            if (!run) {
                uvmPmmFree(&arena->pmm, chunk);
                return TPU_ERR_NO_MEMORY;
            }
            run->firstPage = p + covered;
            run->numPages = (uint32_t)(want / ps);
            run->chunk = chunk;
            run->arena = arena;
            run->next = *runs_head(blk, arena->tier);
            *runs_head(blk, arena->tier) = run;
            /* QoS accounting: the run's backing pages charge to the
             * owning space's tenant; the SLO-aware victim walk reads
             * this usage against the tenant's quota. */
            uvmTenantCharge(blk->range->vaSpace, arena->tier,
                            (int64_t)run->numPages);
            covered += run->numPages;
        }
        p += gap;
    }
    return TPU_OK;
}

/* Free every run of `tier` with no remaining resident pages.  (Chunks are
 * freed whole; a run with any survivor page is kept — documented
 * simplification vs the reference's per-4K chunk splitting.) */
static void block_gc_runs(UvmVaBlock *blk, UvmTier tier)
{
    UvmChunkRun **prev = runs_head(blk, tier);
    UvmChunkRun *r = *prev;
    while (r) {
        bool live = false;
        for (uint32_t p = r->firstPage; p < r->firstPage + r->numPages; p++) {
            if (uvmPageMaskTest(&blk->resident[tier], p)) {
                live = true;
                break;
            }
        }
        if (!live) {
            *prev = r->next;
            /* Retired chunks never return to the freelist: the
             * deliberate leak IS the page retirement (PMM blacklist
             * analog) — the physical span can never be re-allocated. */
            if (!uvmShieldRunRetired(r->arena, r->chunk->offset,
                                     (uint64_t)r->numPages * uvmPageSize()))
                uvmPmmFree(&r->arena->pmm, r->chunk);
            uvmTenantCharge(blk->range->vaSpace, tier,
                            -(int64_t)r->numPages);
            UvmChunkRun *dead = r;
            r = r->next;
            free(dead);
        } else {
            prev = &r->next;
            r = r->next;
        }
    }
    if (!*runs_head(blk, tier)) {
        UvmTierArena *a = tier == UVM_TIER_CXL ? uvmTierArenaCxl()
                                               : uvmTierArenaHbm(blk->hbmDevInst);
        if (a)
            uvmLruRemove(a, blk);
    }
}

void uvmBlockSetCpuAccess(UvmVaBlock *blk, uint32_t firstPage,
                          uint32_t count, int prot)
{
    uint64_t ps = uvmPageSize();
    if (!blk->hasCancelled) {
        void *addr = (char *)(uintptr_t)blk->start +
                     (uint64_t)firstPage * ps;
        if (mprotect(addr, (uint64_t)count * ps, prot) != 0)
            TPU_LOG(TPU_LOG_ERROR, "uvm", "mprotect(%p, %u pages, %d) failed",
                   addr, count, prot);
    } else {
        /* Cancelled pages sit on poison mappings that must stay RW;
         * mprotect around them per contiguous non-cancelled span. */
        uint32_t p = firstPage;
        while (p < firstPage + count) {
            if (uvmPageMaskTest(&blk->cancelled, p)) {
                p++;
                continue;
            }
            uint32_t span = 1;
            while (p + span < firstPage + count &&
                   !uvmPageMaskTest(&blk->cancelled, p + span))
                span++;
            void *addr = (char *)(uintptr_t)blk->start + (uint64_t)p * ps;
            if (mprotect(addr, (uint64_t)span * ps, prot) != 0)
                TPU_LOG(TPU_LOG_ERROR, "uvm",
                       "mprotect(%p, %u pages, %d) failed", addr, span,
                       prot);
            p += span;
        }
    }
    /* cpuMapped tracks full RW PTEs; read-only and none both fault writes. */
    if (!(prot & PROT_WRITE))
        uvmPageMaskClearRange(&blk->cpuMapped, firstPage, count);
}

/* Block copies ride the tpuce multi-channel manager (ce.h): stripes
 * land on the least-loaded channel with per-stripe recovery at the
 * batch fence (reference: mem_mgr CE utils striping across FIFO
 * channels with per-channel trackers, uvm_channel.c pools). */
static TpuCeMgr *block_ce_mgr(UvmVaBlock *blk)
{
    TpuCeMgr *m = tpuCeMgrGet(blk->hbmDevInst);
    return m ? m : tpuCeMgrGet(0);
}

/* Compression stage selection for one copy span: ranges advised
 * COMPRESSIBLE quantize on the host->HBM upload and dequantize on the
 * HBM->host download (ce.h wire model); every other direction — and
 * every advise-free range — stays lossless. */
static uint32_t block_comp_for(UvmVaBlock *blk, UvmTier dstTier, int srcTier)
{
    uint32_t fmt = blk->range->compressFormat;
    if (!fmt)
        return TPU_CE_COMP_NONE;
    if (dstTier == UVM_TIER_HBM && srcTier == UVM_TIER_HOST)
        return fmt;
    if (dstTier == UVM_TIER_HOST && srcTier == UVM_TIER_HBM)
        return fmt | TPU_CE_COMP_DOWNLOAD;
    return TPU_CE_COMP_NONE;
}

/* cpuMapped tracks live managed RW PTEs; cancelled pages sit on poison
 * mappings and are excluded (invariant: cpuMapped implies resident[HOST]
 * candidacy, never a cancelled page). */
static void block_set_cpu_mapped(UvmVaBlock *blk, uint32_t first,
                                 uint32_t count)
{
    if (!blk->hasCancelled) {
        uvmPageMaskSetRange(&blk->cpuMapped, first, count);
        return;
    }
    for (uint32_t p = first; p < first + count; p++)
        if (!uvmPageMaskTest(&blk->cancelled, p))
            uvmPageMaskSet(&blk->cpuMapped, p);
}

/* Pick the copy source tier for a page: HBM > CXL > HOST (device copies
 * are nearest-first, like the reference's resident_id selection). */
static int page_src_tier(UvmVaBlock *blk, uint32_t page)
{
    if (uvmPageMaskTest(&blk->resident[UVM_TIER_HBM], page))
        return UVM_TIER_HBM;
    if (uvmPageMaskTest(&blk->resident[UVM_TIER_CXL], page))
        return UVM_TIER_CXL;
    if (uvmPageMaskTest(&blk->resident[UVM_TIER_HOST], page))
        return UVM_TIER_HOST;
    return -1;
}

/* Copy pages [first, first+count) into dstTier backing, coalescing
 * contiguous page spans into single channel pushes (the contiguity-split
 * loop, reference ce_utils.c:646-661).  Pages resident nowhere are
 * zero-filled.  Pushes are pipelined; one wait at the end (reference
 * pipelines block copies the same way, uvm_migrate.c:555).
 *
 * tpushield: sealed SOURCE pages (a cold HOST/CXL copy coming back
 * hot) are verified against their CRC before any mask or PTE commits —
 * and the verify is OVERLAPPED, not serialized: the copy rides the
 * executor-side CRC stage (crcOut[p] / the local capture receives the
 * CRC32C of page p's destination bytes, computed on the tpuce executor
 * threads during the copy), and the compare runs after the single
 * batch wait.  A match proves seal -> source -> copied bytes end to
 * end; a mismatch falls back to the source-side re-fetch ladder and,
 * unrecovered, fails the pass with TPU_ERR_PAGE_POISONED before
 * anything commits.  Sealed DESTINATION pages unseal before the
 * overwrite (the last verify hook a pending injected flip can be
 * caught by). */
static TpuStatus block_copy_in(UvmVaBlock *blk, UvmTier dstTier,
                               const UvmPageMask *pages, uint32_t first,
                               uint32_t count, uint64_t *bytesOut,
                               uint32_t *crcOut)
{
    /* Injected migration-copy fault: fail BEFORE any byte moves or any
     * mask commits, so the retry in make-resident re-runs the whole
     * pass losslessly. */
    if (tpurmInjectShouldFail(TPU_INJECT_SITE_MIGRATE_COPY))
        return TPU_ERR_INVALID_STATE;

    uint64_t ps = uvmPageSize();
    TpuCeBatch batch;
    /* Manager lookup is LAZY: the first-touch zero-fill path (every
     * populate fault) never pushes a copy, so it must not pay the CE
     * manager lookup. */
    bool haveCe = false, triedCe = false;
    uint64_t bytes = 0;
    /* Overlapped verify-on-promote capture: spans whose SOURCE pages
     * are sealed get per-page CRCs of the delivered bytes even when
     * the caller is not sealing the destination. */
    uint32_t localCrc[UVM_MAX_PAGES_PER_BLOCK];
    UvmPageMask verifyMask;
    uvmPageMaskZero(&verifyMask);
    bool anyVerify = false;

    /* On any failure, drain already-issued stripes before unwinding —
     * the caller may free the backing the workers are still writing. */
    uint32_t p = first;
    while (p < first + count) {
        if (!uvmPageMaskTest(pages, p)) {
            p++;
            continue;
        }
        int src = page_src_tier(blk, p);
        void *dstPtr = tier_page_ptr(blk, dstTier, p);
        if (!dstPtr) {
            if (haveCe)
                tpuCeBatchWait(&batch);
            return TPU_ERR_INVALID_STATE;
        }
        if (src < 0) {
            /* First touch: zero-fill.  Host backing is fresh anonymous
             * memory — already zero, and skipping the touch keeps the
             * fault-service path from committing pages the caller never
             * reads (big win for prefetch-expanded regions). */
            if (dstTier != UVM_TIER_HOST) {
                /* Direct shadow write: like the executor, make any
                 * chip-dirty overlap coherent first so the zero-fill's
                 * republish can't resurrect stale shadow bytes. */
                if (tpuHbmCoherentForRead(dstPtr, ps) != TPU_OK) {
                    if (haveCe)
                        tpuCeBatchWait(&batch);
                    return TPU_ERR_INVALID_STATE;
                }
                memset(dstPtr, 0, ps);
                /* Direct shadow write: publish to the real-arena mirror
                 * (every other HBM write rides the channel executor,
                 * which notifies; this one must do it itself or chip
                 * blocks keep the chunk's previous tenant's bytes). */
                tpuHbmMirrorNotify(dstPtr, ps);
            }
            if (crcOut)
                crcOut[p] = tpurmShieldCrc32c(dstPtr, ps);
            p++;
            continue;
        }
        void *srcPtr = tier_page_ptr(blk, (UvmTier)src, p);
        if (!srcPtr) {
            if (haveCe)
                tpuCeBatchWait(&batch);
            return TPU_ERR_INVALID_STATE;
        }
        /* Grow the span while pages are selected, same source tier, and
         * both sides stay contiguous. */
        uint32_t span = 1;
        while (p + span < first + count &&
               uvmPageMaskTest(pages, p + span) &&
               page_src_tier(blk, p + span) == src &&
               tier_page_ptr(blk, dstTier, p + span) ==
                   (char *)dstPtr + (uint64_t)span * ps &&
               tier_page_ptr(blk, (UvmTier)src, p + span) ==
                   (char *)srcPtr + (uint64_t)span * ps)
            span++;
        /* tpushield verify-on-promote, OVERLAPPED: a sealed cold
         * source must prove its CRC before any consumer trusts the
         * bytes — over the WHOLE grown span (verifying only its head
         * page lets a flip further in ride the copy and get
         * unseal-"detected" at commit, after the corruption already
         * moved hot).  Rather than a serialized source read up front,
         * capture per-page CRCs of the DELIVERED bytes on the
         * executor threads during the copy; the compare (and, on
         * mismatch, the ladder) runs after the batch wait, before
         * anything commits. */
        uint32_t comp = block_comp_for(blk, dstTier, src);
        uint32_t *cap = crcOut;
        if (blk->shield && (src == UVM_TIER_HOST || src == UVM_TIER_CXL) &&
            uvmShieldRangeSealed(blk, p, span)) {
            if (comp & TPU_CE_COMP_FMT_MASK) {
                /* Lossy-compressed copy: the stripe CRC covers the
                 * xform's OUTPUT, which can never reconcile with the
                 * raw-byte seal — every promote would false-mismatch
                 * and the ladder's recovery copy would bypass the
                 * xform.  Compressible spans keep the serialized
                 * source-side verify instead. */
                TpuStatus vst = uvmShieldVerifyRange(blk, p, span);
                if (vst != TPU_OK) {
                    if (haveCe)
                        tpuCeBatchWait(&batch);
                    return vst;
                }
            } else {
                if (!cap)
                    cap = localCrc;
                uvmPageMaskSetRange(&verifyMask, p, span);
                anyVerify = true;
            }
        }
        if (!triedCe) {
            triedCe = true;
            TpuCeMgr *m = block_ce_mgr(blk);
            haveCe = m && tpuCeBatchBegin(m, &batch) == TPU_OK;
        }
        if (!haveCe)
            return TPU_ERR_INVALID_STATE;
        /* Overwriting a sealed destination copy: unseal first (with
         * the pending-flip verify) so the seal bookkeeping never goes
         * stale under the copy. */
        if (blk->shield)
            uvmShieldUnsealRange(blk, p, span, (int)dstTier);
        TpuStatus st = tpuCeBatchCopyCrc(&batch, dstPtr, srcPtr,
                                         (uint64_t)span * ps, comp,
                                         cap ? cap + p : NULL,
                                         cap ? ps : 0);
        if (st != TPU_OK) {
            tpuCeBatchWait(&batch);
            return st;
        }
        bytes += (uint64_t)span * ps;
        p += span;
    }
    if (haveCe) {
        TpuStatus wst = tpuCeBatchWait(&batch);
        if (wst != TPU_OK)
            return wst;
    }
    if (anyVerify) {
        /* The overlapped compare: sealed sources must reconcile with
         * the bytes the copy delivered.  A mismatching page runs the
         * source-side ladder; a recovered source is copied again (the
         * rare path — one synchronous page copy), an unrecovered one
         * poisons and fails the pass with nothing committed. */
        for (uint32_t q = first; q < first + count && q < blk->npages;
             q++) {
            if (!uvmPageMaskTest(&verifyMask, q))
                continue;
            uint32_t *cap = crcOut ? crcOut : localCrc;
            bool recopy = false;
            TpuStatus vst = uvmShieldVerifyCopied(blk, q, cap[q],
                                                  &recopy);
            if (vst != TPU_OK)
                return vst;
            if (!recopy)
                continue;
            int src = page_src_tier(blk, q);
            void *srcPtr = src >= 0
                               ? tier_page_ptr(blk, (UvmTier)src, q)
                               : NULL;
            void *dstPtr = tier_page_ptr(blk, dstTier, q);
            if (!srcPtr || !dstPtr)
                return TPU_ERR_INVALID_STATE;
            if (src == UVM_TIER_HBM &&
                tpuHbmCoherentForRead(srcPtr, ps) != TPU_OK)
                return TPU_ERR_INVALID_STATE;
            memcpy(dstPtr, srcPtr, ps);
            if (dstTier == UVM_TIER_HBM)
                tpuHbmMirrorNotify(dstPtr, ps);
            cap[q] = tpurmShieldCrc32c(dstPtr, ps);
        }
    }
    if (bytesOut)
        *bytesOut = bytes;
    return TPU_OK;
}

/* ---------------------------------------------------------- eviction */

void uvmBlockP2pPin(UvmVaBlock *blk)
{
    pthread_mutex_lock(&blk->lock);
    tpuLockTrackAcquire(TPU_LOCK_UVM_BLOCK, "block-pin");
    blk->p2pPinCount++;
    tpuLockTrackRelease(TPU_LOCK_UVM_BLOCK, "block-pin");
    pthread_mutex_unlock(&blk->lock);
}

void uvmBlockP2pUnpin(UvmVaBlock *blk)
{
    pthread_mutex_lock(&blk->lock);
    tpuLockTrackAcquire(TPU_LOCK_UVM_BLOCK, "block-pin");
    if (blk->p2pPinCount)
        blk->p2pPinCount--;
    tpuLockTrackRelease(TPU_LOCK_UVM_BLOCK, "block-pin");
    pthread_mutex_unlock(&blk->lock);
}

TpuStatus uvmBlockEvictFrom(UvmVaBlock *blk, UvmTierArena *arena)
{
    if (pthread_mutex_trylock(&blk->lock) != 0)
        return TPU_ERR_STATE_IN_USE;
    tpuLockTrackAcquire(TPU_LOCK_UVM_BLOCK, "block-evict");
    if (blk->p2pPinCount || blk->remoteBusy) {
        /* RDMA consumers hold bus addresses into this block, or a
         * REMOTE-tier PEER_COPY window is in flight with the lock
         * dropped (its source/dest runs must not move). */
        tpuLockTrackRelease(TPU_LOCK_UVM_BLOCK, "block-evict");
        pthread_mutex_unlock(&blk->lock);
        return TPU_ERR_STATE_IN_USE;
    }

    UvmTier tier = arena->tier;
    uint32_t np = blk->npages;
    UvmPageMask toHost;
    uvmPageMaskZero(&toHost);
    uint64_t ps = uvmPageSize();

    /* tpushield verify-on-evict: pages sealed on the evicting tier must
     * prove their CRC BEFORE the copy-back — otherwise a rotted CXL
     * park is copied host-ward and RESEALED over the corrupt bytes (the
     * new HOST CRC matches the garbage, so every later verify passes),
     * and the source unseal below "detects" the flip only after it
     * became the trusted truth.  A ladder-unrecovered page poisons
     * here, dropping its residency, so the copy-back set built next
     * skips it. */
    if (blk->shield)
        for (uint32_t p = 0; p < np; p++) {
            if (uvmShieldPageSealedTier(blk, p) != (int)tier ||
                !uvmPageMaskTest(&blk->resident[tier], p))
                continue;
            /* One VerifyRange per contiguous sealed run, not per page
             * — one shield.verify span each instead of flooding the
             * trace ring with per-page records. */
            uint32_t run = 1;
            while (p + run < np &&
                   uvmShieldPageSealedTier(blk, p + run) == (int)tier &&
                   uvmPageMaskTest(&blk->resident[tier], p + run))
                run++;
            (void)uvmShieldVerifyRange(blk, p, run);
            p += run - 1;
        }

    /* Pages resident ONLY in this tier must be copied back to host;
     * read-duplicated pages just drop the copy. */
    uint32_t first = np, last = 0;
    for (uint32_t p = 0; p < np; p++) {
        if (!uvmPageMaskTest(&blk->resident[tier], p))
            continue;
        if (p < first)
            first = p;
        last = p;
        bool elsewhere = false;
        for (int t = 0; t < UVM_TIER_COUNT; t++)
            if (t != (int)tier && uvmPageMaskTest(&blk->resident[t], p))
                elsewhere = true;
        if (!elsewhere)
            uvmPageMaskSet(&toHost, p);
    }

    if (first <= last) {
        if (!uvmPageMaskEmpty(&toHost, np)) {
            TpuCeBatch batch;
            TpuCeMgr *mgr = block_ce_mgr(blk);
            bool haveCe = mgr && tpuCeBatchBegin(mgr, &batch) == TPU_OK;
            uint64_t bytes = 0;
            /* tpushield: the demoted pages SEAL — CRC32C per page,
             * computed by the tpuce executor threads as the stripe
             * transform stage (overlapped with the copy, not a second
             * pass after the fence). */
            bool sealing = uvmShieldActive();
            uint32_t crcs[UVM_MAX_PAGES_PER_BLOCK];
            if (sealing)
                /* Stale HOST seals die before the overwrite (pending
                 * flips verified there) — but ONLY on the toHost pages
                 * the copy-back actually rewrites.  A read-dup page
                 * resident elsewhere keeps its HOST copy untouched;
                 * blanket-unsealing it would drop a seal whose bytes
                 * stay live (a detected-but-unrepaired flip would
                 * become the trusted copy). */
                for (uint32_t q = first; q <= last; q++)
                    if (uvmPageMaskTest(&toHost, q))
                        uvmShieldUnsealRange(blk, q, 1, UVM_TIER_HOST);
            for (uint32_t p = first; p <= last; p++) {
                if (!uvmPageMaskTest(&toHost, p))
                    continue;
                void *src = tier_page_ptr(blk, tier, p);
                void *dst = tier_page_ptr(blk, UVM_TIER_HOST, p);
                uint32_t span = 1;
                while (p + span <= last && uvmPageMaskTest(&toHost, p + span) &&
                       tier_page_ptr(blk, tier, p + span) ==
                           (char *)src + (uint64_t)span * ps)
                    span++;
                /* Eviction saves what the DEVICE computed, not a stale
                 * shadow (reference: uvm_va_block.c:4660 copies actual
                 * GPU memory back): the channel executor downloads any
                 * chip-dirty source pages before the copy runs. */
                /* Copies land in the engine alias; user PTEs stay
                 * PROT_NONE until the data is home, so racing CPU
                 * accesses fault and queue behind this eviction rather
                 * than reading stale bytes or losing stores. */
                TpuStatus st = haveCe
                                   ? tpuCeBatchCopyCrc(&batch, dst, src,
                                                    (uint64_t)span * ps,
                                                    block_comp_for(
                                                        blk, UVM_TIER_HOST,
                                                        (int)tier),
                                                    sealing ? &crcs[p]
                                                            : NULL,
                                                    sealing ? ps : 0)
                                   : TPU_ERR_INVALID_STATE;
                if (st != TPU_OK) {
                    if (haveCe)
                        tpuCeBatchWait(&batch); /* drain in-flight stripes */
                    tpuLockTrackRelease(TPU_LOCK_UVM_BLOCK, "block-evict");
                    pthread_mutex_unlock(&blk->lock);
                    return st;
                }
                bytes += (uint64_t)span * ps;
                p += span - 1;
            }
            {
                TpuStatus st = haveCe ? tpuCeBatchWait(&batch)
                                      : TPU_ERR_INVALID_STATE;
                if (st != TPU_OK) {
                    /* Nothing committed: masks and user PTEs unchanged,
                     * so the device copy stays authoritative and CPU
                     * accesses still fault (no silent staleness). */
                    tpuLockTrackRelease(TPU_LOCK_UVM_BLOCK, "block-evict");
                    pthread_mutex_unlock(&blk->lock);
                    return st;
                }
            }
            /* Commit: masks first, then user PTEs.  Sealed pages park
             * behind PROT_NONE — the first CPU touch faults, VERIFIES
             * the seal and only then reopens RW (one extra fault per
             * evicted-then-touched span buys read-side detection);
             * with the shield off the historical RW mapping returns. */
            for (uint32_t p = 0; p < np; p++) {
                if (!uvmPageMaskTest(&toHost, p))
                    continue;
                uvmPageMaskSet(&blk->resident[UVM_TIER_HOST], p);
                if (!sealing)
                    uvmPageMaskSet(&blk->cpuMapped, p);
                uint32_t span = 1;
                while (p + span < np && uvmPageMaskTest(&toHost, p + span)) {
                    uvmPageMaskSet(&blk->resident[UVM_TIER_HOST], p + span);
                    if (!sealing)
                        uvmPageMaskSet(&blk->cpuMapped, p + span);
                    span++;
                }
                if (sealing) {
                    for (uint32_t q = p; q < p + span; q++)
                        uvmShieldSealPage(blk, q, UVM_TIER_HOST, crcs[q]);
                    uvmBlockSetCpuAccess(blk, p, span, PROT_NONE);
                } else {
                    uvmBlockSetCpuAccess(blk, p, span,
                                         PROT_READ | PROT_WRITE);
                }
                p += span - 1;
            }
            uvmFaultStatsRecordMigration(bytes);
            if (bytes) {
                tpuCounterAddScoped("uvm_bytes_xfer_dth", blk->hbmDevInst,
                                    bytes);
                /* tpuhot: an eviction copy-back is a hostward migration
                 * — half of the HBM<->host ping-pong the thrash
                 * detector watches for. */
                uvmHotMigrationNote(blk, UVM_TIER_HOST, blk->hbmDevInst);
            }
            uvmToolsEmit(blk->range->vaSpace, UVM_EVENT_EVICTION, tier,
                         UVM_TIER_HOST, blk->hbmDevInst, blk->start, bytes);
            /* REMOTE tier (tpusplit): the host copy is committed, the
             * HBM source runs still exist — replicate the demoted span
             * onto a lender chip's HBM so a later promote rides ICI
             * instead of re-reading host memory.  Write-through: HOST
             * keeps the durable copy, so every failure mode inside is
             * just "no replica".  Drops/re-takes blk->lock. */
            if (tier == UVM_TIER_HBM)
                uvmTierRemoteReplicate(blk, &toHost, first, last);
        }
        /* Still-marked speculative pages leaving the aperture untouched
         * are USELESS prefetches (blk->lock held here). */
        uvmPerfPrefetchEvictLocked(blk, first, last - first + 1);
        /* Seals of the copies this clear drops (read-dup CXL parks
         * losing their aperture copy) die with the residency. */
        if (blk->shield)
            uvmShieldUnsealRange(blk, first, last - first + 1, (int)tier);
        uvmPageMaskClearRange(&blk->resident[tier], first, last - first + 1);
        /* Evicted pages lose any accessed-by device mapping into them,
         * and their device PTEs (one TLB invalidate per device). */
        uvmPageMaskClearRange(&blk->devMapped, first, last - first + 1);
        uvmBlockPteRevoke(blk, first, last - first + 1);
    }
    block_gc_runs(blk, tier);
    uvmTierRemoteGc(blk);
    uvmFaultStatsRecordEviction();
    tpuCounterAdd("uvm_block_evictions", 1);
    tpuLockTrackRelease(TPU_LOCK_UVM_BLOCK, "block-evict");
    pthread_mutex_unlock(&blk->lock);
    return TPU_OK;
}

/* Evict LRU victims from `arena` until an alloc retry is worth making.
 * Caller must NOT hold any block lock. */
static TpuStatus arena_evict_some(UvmTierArena *arena, UvmVaBlock *self)
{
    for (int attempt = 0; attempt < 8; attempt++) {
        UvmVaBlock *victim = uvmLruPopVictim(arena, self);
        if (!victim)
            return TPU_ERR_NO_MEMORY;
        TpuStatus st = uvmBlockEvictFrom(victim, arena);
        if (st != TPU_OK)
            /* Contended or failed: re-link so the block's residency is
             * never stranded off-LRU (it still holds arena memory). */
            uvmLruTouch(arena, victim);
        uvmLruEvictDone(arena, victim);   /* release the lifetime guard */
        if (st == TPU_OK)
            return TPU_OK;
        if (st != TPU_ERR_STATE_IN_USE)
            return st;
    }
    return TPU_ERR_NO_MEMORY;
}

/* Spine hook (memring OP_TIER_EVICT — the fused evict+upload pair's
 * evict half): LRU-evict from the (tier, devInst) arena until it can
 * take `bytes` more.  Best-effort by contract — under-delivery just
 * means the linked upload runs the engine's own pressure path above.
 * Ring-worker context: no block locks held. */
uint64_t uvmTierEvictBytes(uint32_t tier, uint32_t devInst, uint64_t bytes)
{
    UvmTierArena *arena =
        tier == UVM_TIER_HBM ? uvmTierArenaHbm(devInst)
        : tier == UVM_TIER_CXL ? uvmTierArenaCxl() : NULL;
    if (!arena)
        return 0;
    uint64_t want = bytes > arena->size ? arena->size : bytes;
    tpuCounterAdd("memring_tier_evict_runs", 1);
    for (int rounds = 0; rounds < 64; rounds++) {
        uint64_t freeB = arena->size - uvmPmmAllocatedBytes(&arena->pmm);
        if (freeB >= want)
            return freeB;
        if (arena_evict_some(arena, NULL) != TPU_OK)
            break;
    }
    return arena->size - uvmPmmAllocatedBytes(&arena->pmm);
}

/* ------------------------------------------------------- make resident */

TpuStatus uvmBlockMakeResidentEx(UvmVaBlock *blk, UvmLocation dst,
                                 uint32_t firstPage, uint32_t count,
                                 bool forWrite, bool forceDup)
{
    if (firstPage + count > blk->npages)
        return TPU_ERR_INVALID_ARGUMENT;

    UvmVaRange *range = blk->range;
    bool readDup = (range->readDuplication || forceDup) && !forWrite;
    bool pteRevoked = false;    /* one PTE revoke per span, not two */
    bool hostRwCommitted = false;   /* commit already made span host-RW */
    UvmTierArena *arena = NULL;
    if (dst.tier == UVM_TIER_HBM) {
        arena = uvmTierArenaHbm(dst.devInst);
        if (!arena)
            return TPU_ERR_INVALID_DEVICE;
    } else if (dst.tier == UVM_TIER_REMOTE) {
        /* REMOTE is an eviction-side replica of HOST, never a
         * make-resident destination (tpusplit). */
        return TPU_ERR_NOT_SUPPORTED;
    } else if (dst.tier == UVM_TIER_CXL) {
        arena = uvmTierArenaCxl();
        if (!arena)
            return TPU_ERR_NOT_SUPPORTED;
    }

    pthread_mutex_lock(&blk->lock);
    tpuLockTrackAcquire(TPU_LOCK_UVM_BLOCK, "block");

    if (blk->remoteBusy) {
        /* A REMOTE-tier PEER_COPY window is in flight with blk->lock
         * dropped: residency masks and backing runs must not move
         * under it (the fault path retries on STATE_IN_USE). */
        tpuLockTrackRelease(TPU_LOCK_UVM_BLOCK, "block");
        pthread_mutex_unlock(&blk->lock);
        return TPU_ERR_STATE_IN_USE;
    }

    /* P2P-pinned blocks keep their device residency in place: CPU reads
     * are served by duplication (device copy survives), anything that
     * would move or invalidate the pinned copy is refused (reference:
     * pinned vidmem is immovable until put_pages). */
    if (blk->p2pPinCount &&
        !(dst.tier == UVM_TIER_HBM && dst.devInst == blk->hbmDevInst)) {
        if (dst.tier == UVM_TIER_HOST && !forWrite) {
            readDup = true;
        } else {
            tpuLockTrackRelease(TPU_LOCK_UVM_BLOCK, "block");
            pthread_mutex_unlock(&blk->lock);
            return TPU_ERR_STATE_IN_USE;
        }
    }

    /* Single-HBM-device rule: migrating to a different device first pulls
     * the old device's residency home.  The eviction must actually
     * complete (not merely be tolerated) before hbmDevInst flips, or the
     * old arena would keep runs and an LRU entry pointing at a block
     * whose gc now targets the new arena. */
    if (dst.tier == UVM_TIER_HBM && blk->hbmRuns &&
        blk->hbmDevInst != dst.devInst) {
        UvmTierArena *old = uvmTierArenaHbm(blk->hbmDevInst);
        tpuLockTrackRelease(TPU_LOCK_UVM_BLOCK, "block");
        pthread_mutex_unlock(&blk->lock);
        TpuStatus st = old ? TPU_ERR_STATE_IN_USE : TPU_OK;
        for (int attempt = 0; old && attempt < 64; attempt++) {
            st = uvmBlockEvictFrom(blk, old);
            if (st != TPU_ERR_STATE_IN_USE)
                break;
            sched_yield();
        }
        if (st != TPU_OK)
            return st;
        pthread_mutex_lock(&blk->lock);
        tpuLockTrackAcquire(TPU_LOCK_UVM_BLOCK, "block");
        if (blk->hbmRuns && blk->hbmDevInst != dst.devInst) {
            /* Re-populated on the old device while unlocked: give up. */
            tpuLockTrackRelease(TPU_LOCK_UVM_BLOCK, "block");
            pthread_mutex_unlock(&blk->lock);
            return TPU_ERR_STATE_IN_USE;
        }
    }
    if (dst.tier == UVM_TIER_HBM)
        blk->hbmDevInst = dst.devInst;

    /* Hardened recovery state: bounded copy retries (transient CE
     * faults recover via RC reset-and-replay + re-copy) and one-way
     * HBM/CXL -> HOST tier fallback when the aperture cannot deliver
     * backing (injected allocation fault or genuine exhaustion).  The
     * host tier is always viable — device traffic to host-resident
     * pages flows through CE host pointers — so degraded placement
     * beats a failed service. */
    uint32_t copyAttempts = 0;
    uint32_t copyLimit = (uint32_t)tpuRegistryGet("recover_copy_retries",
                                                  3);
    bool fallbackEnabled = tpuRegistryGet("recover_tier_fallback", 1) != 0;

    for (int retry = 0; ; retry++) {
        /* Pages not yet resident in dst (word ops: span & ~resident &
         * ~cancelled). */
        UvmPageMask needed;
        uvmPageMaskZero(&needed);
        uint32_t nneeded = 0;
        UVM_MASK_RANGE_WORDS(firstPage, count, w, bm, {
            uint64_t want = bm & ~blk->resident[dst.tier].bits[w] &
                            ~blk->cancelled.bits[w];
            needed.bits[w] = want;
            nneeded += (uint32_t)__builtin_popcountll(want);
        });
        if (nneeded == 0)
            break;

        TpuStatus st = TPU_OK;
        bool wantFallback = false;
        if (arena)
            st = block_alloc_backing(blk, arena, firstPage, count);
        if (st == TPU_ERR_INSUFFICIENT_RESOURCES && arena) {
            /* Injected/ECC allocation fault: eviction cannot cure a bad
             * chunk — fall back to the host tier directly.  With
             * fallback disabled the DISTINCT status surfaces (the
             * caller must not confuse a bad chunk with mere
             * exhaustion and start evicting). */
            if (!fallbackEnabled) {
                tpuLockTrackRelease(TPU_LOCK_UVM_BLOCK, "block");
                pthread_mutex_unlock(&blk->lock);
                return st;
            }
            wantFallback = true;
        } else if (st == TPU_ERR_NO_MEMORY) {
            if (retry >= 32) {
                /* Eviction churned 32 rounds without freeing enough:
                 * degrade to host rather than failing the service. */
                if (!fallbackEnabled) {
                    tpuLockTrackRelease(TPU_LOCK_UVM_BLOCK, "block");
                    pthread_mutex_unlock(&blk->lock);
                    return TPU_ERR_NO_MEMORY;
                }
                wantFallback = true;
            } else {
                /* Drop the block lock around eviction (see header note). */
                tpuLockTrackRelease(TPU_LOCK_UVM_BLOCK, "block");
                pthread_mutex_unlock(&blk->lock);
                st = arena_evict_some(arena, blk);
                if (st == TPU_ERR_INVALID_STATE &&
                    copyAttempts < copyLimit) {
                    /* Victim's copy-back hit a (possibly injected) CE
                     * fault: reset-and-replay, then retry the alloc. */
                    copyAttempts++;
                    tpuCounterAdd("recover_retries", 1);
                    tpuCounterAdd("recover_copy_retries", 1);
                    tpurmTraceInstant(TPU_TRACE_RECOVER_RETRY, blk->start,
                                      copyAttempts - 1);
                    tpuRcRecoverAll();
                    tpuRecoverBackoff(copyAttempts - 1);
                    st = TPU_OK;
                } else if (st == TPU_ERR_NO_MEMORY && fallbackEnabled) {
                    wantFallback = true;
                    st = TPU_OK;
                } else if (st != TPU_OK) {
                    return st;
                }
                pthread_mutex_lock(&blk->lock);
                tpuLockTrackAcquire(TPU_LOCK_UVM_BLOCK, "block");
                if (!wantFallback)
                    continue;
            }
        }
        if (wantFallback) {
            tpuCounterAdd("recover_tier_fallbacks", 1);
            tpurmTraceInstant(TPU_TRACE_RECOVER_TIER_FALLBACK, blk->start,
                              dst.tier);
            TPU_LOG(TPU_LOG_WARN, "uvm",
                   "tier fallback: block %llx pages [%u,+%u) %s -> HOST "
                   "(aperture allocation failed)",
                   (unsigned long long)blk->start, firstPage, count,
                   dst.tier == UVM_TIER_HBM ? "HBM" : "CXL");
            dst.tier = UVM_TIER_HOST;
            dst.devInst = 0;
            arena = NULL;
            continue;
        }
        if (st != TPU_OK) {
            tpuLockTrackRelease(TPU_LOCK_UVM_BLOCK, "block");
            pthread_mutex_unlock(&blk->lock);
            return st;
        }

        /* Copies go through the engine alias, so user PTEs need no
         * relaxation here — protection flips only AFTER the data moves
         * (commit below). */
        if (dst.tier != UVM_TIER_HOST &&
            !uvmPageMaskEmpty(&blk->resident[UVM_TIER_HOST], blk->npages))
            /* Write-protect host pages BEFORE copying device-ward so a
             * racing CPU write faults and re-services instead of being
             * silently lost (the reference unmaps before copy for the
             * same reason).  This applies under read duplication too:
             * the surviving host copy must be read-only or CPU stores
             * would silently diverge from the device duplicate. */
            uvmBlockSetCpuAccess(blk, firstPage, count, PROT_READ);

        uint64_t bytes = 0;
        uint64_t tCopy = tpurmTraceBegin();
        /* tpushield: a demotion to the far CXL tier seals the new cold
         * copy — CRCs ride the executor threads through the copy.
         * forWrite does not exempt it: the CPU side of a CXL page is
         * PROT_NONE either way, and a device that later WRITES it
         * unseals at the writable-PTE install — until then the parked
         * copy is exactly the cold data the scrubber must cover. */
        bool sealCxl = dst.tier == UVM_TIER_CXL && uvmShieldActive();
        uint32_t sealCrcs[UVM_MAX_PAGES_PER_BLOCK];
        /* REMOTE tier (tpusplit): pages with a live lease on a lender
         * chip promote over ICI into the just-allocated HBM runs
         * instead of re-reading the HOST copy.  Fetched pages are
         * masked out of the copy-in; a fence abort (lender reset,
         * revocation, unhealthy lender) leaves them UNfetched, so the
         * HOST copy-in below overwrites any partial bytes — an aborted
         * window can never leak garbage into a completed service.
         * Drops/re-takes blk->lock (remoteBusy guards the window). */
        UvmPageMask copyIn = needed;
        if (dst.tier == UVM_TIER_HBM && blk->remoteRuns) {
            UvmPageMask remoteFetched;
            uvmTierRemoteFetch(blk, dst.devInst, &needed, &remoteFetched);
            uvmPageMaskAndNot(&copyIn, &remoteFetched);
        }
        st = block_copy_in(blk, dst.tier, &copyIn, firstPage, count, &bytes,
                           sealCxl ? sealCrcs : NULL);
        if (tCopy && bytes)
            tpurmTraceEnd(TPU_TRACE_MIGRATE_COPY, tCopy, blk->start, bytes);
        if (st != TPU_OK) {
            /* Transient copy fault (CE error, chip-readback stall,
             * injection): nothing was committed — masks and user PTEs
             * are untouched and sources are intact — so RC
             * reset-and-replay plus a bounded backoff retry recovers
             * losslessly.  Exhaustion surfaces as RETRY_EXHAUSTED so
             * the fault layer can quarantine the page instead of
             * spinning. */
            if (st == TPU_ERR_INVALID_STATE && copyAttempts < copyLimit) {
                copyAttempts++;
                tpuCounterAdd("recover_retries", 1);
                tpuCounterAdd("recover_copy_retries", 1);
                tpurmTraceInstant(TPU_TRACE_RECOVER_RETRY, blk->start,
                                  copyAttempts - 1);
                tpuLockTrackRelease(TPU_LOCK_UVM_BLOCK, "block");
                pthread_mutex_unlock(&blk->lock);
                tpuRcRecoverAll();
                tpuRecoverBackoff(copyAttempts - 1);
                pthread_mutex_lock(&blk->lock);
                tpuLockTrackAcquire(TPU_LOCK_UVM_BLOCK, "block");
                continue;
            }
            tpuLockTrackRelease(TPU_LOCK_UVM_BLOCK, "block");
            pthread_mutex_unlock(&blk->lock);
            if (st == TPU_ERR_INVALID_STATE && copyAttempts)
                st = TPU_ERR_RETRY_EXHAUSTED;
            return st;
        }
        /* Transfer accounting with the reference's counter-scope split
         * (UvmCounterNameBytesXferHtD/DtH, uvm_types.h:283-284; scope
         * ProcessSingleGpu vs ProcessAllGpus): per-device lines live
         * beside the aggregate. */
        if (bytes && dst.tier == UVM_TIER_HBM)
            tpuCounterAddScoped("uvm_bytes_xfer_htd", dst.devInst, bytes);

        /* Commit masks.  Residency movement stales any accessed-by device
         * mapping to the old location; clear so the next device access
         * re-establishes it (reference revokes mappings on migration),
         * and drop the device PTEs covering the moved span. */
        uvmBlockPteRevoke(blk, firstPage, count);
        pteRevoked = true;
        uvmPageMaskOr(&blk->resident[dst.tier], &needed);
        uvmPageMaskAndNot(&blk->devMapped, &needed);
        if (!readDup) {
            for (int t = 0; t < UVM_TIER_COUNT; t++) {
                if (t == (int)dst.tier)
                    continue;
                /* Seals of source copies this exclusivity drops die
                 * with their residency (pending flips verified in the
                 * unseal hook — bytes still addressable here). */
                if (blk->shield)
                    for (uint32_t q = firstPage; q < firstPage + count;
                         q++)
                        if (uvmPageMaskTest(&needed, q))
                            uvmShieldUnsealRange(blk, q, 1, t);
                uvmPageMaskAndNot(&blk->resident[t], &needed);
            }
        }
        if (sealCxl)
            for (uint32_t q = firstPage; q < firstPage + count; q++)
                if (uvmPageMaskTest(&needed, q))
                    uvmShieldSealPage(blk, q, UVM_TIER_CXL, sealCrcs[q]);
        if (dst.tier == UVM_TIER_HOST) {
            if (readDup) {
                /* Read-duplicated pages map read-only so a CPU write
                 * faults and invalidates the duplicates (MESI-style;
                 * reference maps read-dup pages RO on every processor). */
                uvmBlockSetCpuAccess(blk, firstPage, count, PROT_READ);
            } else {
                uvmBlockSetCpuAccess(blk, firstPage, count,
                                     PROT_READ | PROT_WRITE);
                block_set_cpu_mapped(blk, firstPage, count);
                block_gc_runs(blk, UVM_TIER_HBM);
                block_gc_runs(blk, UVM_TIER_CXL);
                uvmTierRemoteGc(blk);
                hostRwCommitted = true;
            }
        } else if (!readDup) {
            /* CPU must re-fault on next touch. */
            uvmBlockSetCpuAccess(blk, firstPage, count, PROT_NONE);
            block_gc_runs(blk, dst.tier == UVM_TIER_HBM ? UVM_TIER_CXL
                                                        : UVM_TIER_HBM);
            uvmTierRemoteGc(blk);
        }
        if (bytes) {
            uvmFaultStatsRecordMigration(bytes);
            tpuCounterAddScoped("uvm_bytes_xfer_dth", blk->hbmDevInst,
                                bytes);
            /* tpuhot thrash detector: one committed migration toward
             * dst — direction alternations inside the window trip the
             * PIN/THROTTLE decision (blk->lock held here). */
            uvmHotMigrationNote(blk, dst.tier, dst.devInst);
            if (readDup)
                /* Source copies survived: this copy created duplicates
                 * (reference emits UvmEventTypeReadDuplicate from the
                 * same commit point). */
                uvmToolsEmit(range->vaSpace, UVM_EVENT_READ_DUP,
                             UVM_TIER_COUNT, dst.tier, dst.devInst,
                             blk->start + (uint64_t)firstPage * uvmPageSize(),
                             bytes);
        }
        break;
    }

    /* Write access always makes the destination exclusive (MESI): clear
     * duplicates on other tiers and restore protections — including when
     * no copy was needed (e.g. a CPU write to a page left PROT_READ by an
     * earlier device-read duplication; without this fix-up the store
     * would re-fault forever because nneeded==0 skips the commit path). */
    if (forWrite) {
        bool hadDup = false;
        for (int t = 0; t < UVM_TIER_COUNT; t++) {
            if (t != (int)dst.tier &&
                uvmPageMaskIntersectsRange(&blk->resident[t], firstPage,
                                           count))
                hadDup = true;
        }
        bool devMappedAny = uvmPageMaskIntersectsRange(&blk->devMapped,
                                                       firstPage, count);
        /* Fast path for the CPU-write populate fault: the commit loop
         * just made this exact span host-exclusive RW (protections,
         * cpuMapped, run gc and PTE revoke all done there).  With no
         * duplicate residency and no accessed-by mappings to tear down,
         * the fix-up below would only repeat that work — notably a
         * second mprotect syscall over the same span. */
        if (hostRwCommitted && !hadDup && !devMappedAny)
            goto fixup_done;
        /* Exclusive write: duplicate copies drop, so their seals die;
         * a HOST destination also opens CPU-writable, killing its own
         * seal.  A CXL destination keeps the seal the commit just laid
         * — its CPU side stays PROT_NONE, and a device write unseals
         * at the writable-PTE install (uvmBlockPtePopulate). */
        if (blk->shield) {
            for (int t = 0; t < UVM_TIER_COUNT; t++)
                if (t != (int)dst.tier || dst.tier == UVM_TIER_HOST)
                    uvmShieldUnsealRange(blk, firstPage, count, t);
        }
        for (int t = 0; t < UVM_TIER_COUNT; t++) {
            if (t != (int)dst.tier)
                uvmPageMaskClearRange(&blk->resident[t], firstPage, count);
        }
        /* Exclusive write revokes remote (accessed-by) mappings. */
        uvmPageMaskClearRange(&blk->devMapped, firstPage, count);
        if (hadDup)
            /* Duplicates dropped by the exclusive write (reference:
             * UvmEventTypeReadDuplicateInvalidate). */
            uvmToolsEmit(range->vaSpace, UVM_EVENT_READ_DUP_INVALIDATE,
                         UVM_TIER_COUNT, dst.tier, dst.devInst,
                         blk->start + (uint64_t)firstPage * uvmPageSize(),
                         (uint64_t)count * uvmPageSize());
        if (!pteRevoked)        /* commit loop may already have */
            uvmBlockPteRevoke(blk, firstPage, count);
        if (dst.tier != UVM_TIER_HOST) {
            uvmBlockSetCpuAccess(blk, firstPage, count, PROT_NONE);
        } else {
            /* Now-exclusive host pages regain full RW mapping. */
            uvmBlockSetCpuAccess(blk, firstPage, count,
                                 PROT_READ | PROT_WRITE);
            block_set_cpu_mapped(blk, firstPage, count);
        }
        block_gc_runs(blk, UVM_TIER_HBM);
        block_gc_runs(blk, UVM_TIER_CXL);
        uvmTierRemoteGc(blk);
    }

fixup_done:
    if (arena)
        uvmLruTouch(arena, blk);
    tpuLockTrackRelease(TPU_LOCK_UVM_BLOCK, "block");
    pthread_mutex_unlock(&blk->lock);
    return TPU_OK;
}

TpuStatus uvmBlockMakeResident(UvmVaBlock *blk, UvmLocation dst,
                               uint32_t firstPage, uint32_t count,
                               bool forWrite)
{
    return uvmBlockMakeResidentEx(blk, dst, firstPage, count, forWrite,
                                  false);
}

/* Accessed-by service: map [firstPage, firstPage+count) for a device
 * WITHOUT migrating — the device reads/writes the data where it resides
 * (reference: SetAccessedBy processors get mappings established on fault
 * service instead of migrations, uvm_va_policy accessed_by semantics).
 * Pages resident nowhere cannot be mapped (TPU_ERR_INVALID_STATE: the
 * caller falls back to migration).  A write access makes the mapped
 * location exclusive first (MESI), mirroring make-resident's rule. */
TpuStatus uvmBlockMapDevice(UvmVaBlock *blk, uint32_t firstPage,
                            uint32_t count, bool forWrite)
{
    if (firstPage + count > blk->npages)
        return TPU_ERR_INVALID_ARGUMENT;

    pthread_mutex_lock(&blk->lock);
    tpuLockTrackAcquire(TPU_LOCK_UVM_BLOCK, "block-map");

    for (uint32_t p = firstPage; p < firstPage + count; p++) {
        bool resident = false;
        for (int t = 0; t < UVM_TIER_COUNT; t++)
            if (uvmPageMaskTest(&blk->resident[t], p))
                resident = true;
        if (!resident) {
            tpuLockTrackRelease(TPU_LOCK_UVM_BLOCK, "block-map");
            pthread_mutex_unlock(&blk->lock);
            return TPU_ERR_INVALID_STATE;
        }
    }

    if (forWrite) {
        /* Keep one copy per page (priority HBM > CXL > HOST) and drop
         * duplicates so the remote write cannot diverge from a stale
         * duplicate; host pages the device may now write become
         * PROT_READ so CPU stores re-fault and serialize. */
        /* tpushield: the device may now WRITE the mapped copy — every
         * seal under the span is stale the moment the PTE opens. */
        if (blk->shield)
            uvmShieldUnsealRange(blk, firstPage, count, -1);
        for (uint32_t p = firstPage; p < firstPage + count; p++) {
            int keep = -1;
            const int prio[] = { UVM_TIER_HBM, UVM_TIER_CXL, UVM_TIER_HOST };
            for (int i = 0; i < 3 && keep < 0; i++)
                if (uvmPageMaskTest(&blk->resident[prio[i]], p))
                    keep = prio[i];
            bool hadHost = uvmPageMaskTest(&blk->resident[UVM_TIER_HOST], p);
            for (int t = 0; t < UVM_TIER_COUNT; t++)
                if (t != keep)
                    uvmPageMaskClear(&blk->resident[t], p);
            if (keep == UVM_TIER_HOST) {
                uvmBlockSetCpuAccess(blk, p, 1, PROT_READ);
                uvmPageMaskClear(&blk->cpuMapped, p);
            } else if (hadHost) {
                /* Host copy invalidated by the remote write: CPU loads
                 * must fault, not read the stale page (same pairing as
                 * make-resident's exclusive-write path). */
                uvmBlockSetCpuAccess(blk, p, 1, PROT_NONE);
                uvmPageMaskClear(&blk->cpuMapped, p);
            }
        }
    }
    uvmPageMaskSetRange(&blk->devMapped, firstPage, count);
    /* (The caller installs the mapping device's PTEs: this function has
     * no device identity — service_one does.) */

    tpuLockTrackRelease(TPU_LOCK_UVM_BLOCK, "block-map");
    pthread_mutex_unlock(&blk->lock);
    tpuCounterAdd("uvm_accessed_by_mappings", 1);
    return TPU_OK;
}

void uvmBlockFreeBacking(UvmVaBlock *blk)
{
    /* Fault workers pin blocks (serviceRefs, taken under vs->lock)
     * while servicing without the space lock: wait for in-flight
     * services to drain — they never re-take vs->lock, so waiting here
     * (typically under it) cannot deadlock. */
    while (atomic_load_explicit(&blk->serviceRefs, memory_order_acquire))
        sched_yield();
    /* Dying block: its device PTEs must not outlive the backing.  AFTER
     * the drain — a pinned service could otherwise re-populate PTEs
     * behind the revoke, leaving them dangling into freed chunks. */
    uvmBlockPteRevoke(blk, 0, blk->npages);
    UvmTierArena *hbm = uvmTierArenaHbm(blk->hbmDevInst);
    UvmTierArena *cxl = uvmTierArenaCxl();
    /* An evictor may have popped this block off an LRU and still hold the
     * raw pointer: wait for it to finish before tearing the block down. */
    if (hbm) {
        uvmLruAwaitEvictors(hbm, blk);
        uvmLruRemove(hbm, blk);
    }
    if (cxl) {
        uvmLruAwaitEvictors(cxl, blk);
        uvmLruRemove(cxl, blk);
    }
    /* REMOTE leases: wait out any in-flight PEER_COPY window (its
     * submitter holds a serviceRef or the migrate call; it re-locks and
     * drops remoteBusy when the spine wait returns), then give every
     * lender its chunks back. */
    while (__atomic_load_n(&blk->remoteBusy, __ATOMIC_ACQUIRE))
        sched_yield();
    uvmTierRemoteFreeAll(blk);
    for (int tier = 0; tier < UVM_TIER_COUNT; tier++) {
        if (tier == UVM_TIER_HOST || tier == UVM_TIER_REMOTE)
            continue;
        UvmChunkRun *r = *runs_head(blk, (UvmTier)tier);
        while (r) {
            UvmChunkRun *next = r->next;
            if (!uvmShieldRunRetired(r->arena, r->chunk->offset,
                                     (uint64_t)r->numPages * uvmPageSize()))
                uvmPmmFree(&r->arena->pmm, r->chunk);
            uvmTenantCharge(blk->range->vaSpace, (UvmTier)tier,
                            -(int64_t)r->numPages);
            free(r);
            r = next;
        }
        *runs_head(blk, (UvmTier)tier) = NULL;
    }
    uvmShieldBlockFree(blk);
}

/* -------------------------------------------- device-wrote invalidation
 * (chip->host direction, write side).  A jitted computation wrote HBM
 * arena [off, off+bytes) on device `devInst`: any CPU/CXL copy of a
 * managed page backed by that span is now stale and must be dropped,
 * with user PTEs revoked so the next CPU touch faults and migrates the
 * chip truth back (reference: device writes hold write exclusivity and
 * remote mappings are revoked — uvm_va_block.c make-resident unmap
 * semantics; reverse lookup plays uvm_pmm_sysmem.c's reverse-map role).
 * Caller must already have marked the span chip-dirty
 * (tpurmHbmMarkChipDirty) so engine reads of it block on readback. */

typedef struct {
    uint32_t devInst;
    uint64_t off, end;
    uint64_t invalidated;       /* pages dropped (stat/return) */
    bool pinnedOverlap;         /* span hits a P2P-pinned block */
} DeviceWroteCtx;

static void device_wrote_visit(UvmVaSpace *vs, UvmVaBlock *blk, void *ctxv)
{
    (void)vs;
    DeviceWroteCtx *ctx = ctxv;
    uint64_t ps = uvmPageSize();

    pthread_mutex_lock(&blk->lock);
    tpuLockTrackAcquire(TPU_LOCK_UVM_BLOCK, "dev-wrote");
    for (UvmChunkRun *r = blk->hbmRuns; r; r = r->next) {
        if (r->arena->tier != UVM_TIER_HBM ||
            r->arena->devInst != ctx->devInst)
            continue;
        uint64_t runLo = r->chunk->offset;
        uint64_t runHi = runLo + (uint64_t)r->numPages * ps;
        uint64_t lo = ctx->off > runLo ? ctx->off : runLo;
        uint64_t hi = ctx->end < runHi ? ctx->end : runHi;
        if (lo >= hi)
            continue;
        /* RDMA consumers hold live bus addresses into this block and
         * read the arena mapping directly — nothing on their path can
         * block on a READBACK, so the caller must download the span
         * synchronously (GPUDirect invariant: exported memory is the
         * device truth, nvidia-peermem.c dma_map semantics). */
        if (blk->p2pPinCount)
            ctx->pinnedOverlap = true;
        uint32_t firstP = r->firstPage + (uint32_t)((lo - runLo) / ps);
        uint32_t lastP = r->firstPage + (uint32_t)((hi - 1 - runLo) / ps);
        uint32_t spanStart = UINT32_MAX, spanLen = 0;
        for (uint32_t p = firstP; p <= lastP; p++) {
            if (!uvmPageMaskTest(&blk->resident[UVM_TIER_HBM], p))
                continue;
            bool hadOther = false;
            for (int t = 0; t < UVM_TIER_COUNT; t++) {
                if (t == (int)UVM_TIER_HBM)
                    continue;
                if (uvmPageMaskTest(&blk->resident[t], p)) {
                    /* The chip overwrote the authoritative copy: the
                     * stale duplicate's seal dies with it. */
                    if (blk->shield)
                        uvmShieldUnsealRange(blk, p, 1, t);
                    uvmPageMaskClear(&blk->resident[t], p);
                    hadOther = true;
                }
            }
            ctx->invalidated++;
            /* Revoke CPU access even for previously HBM-exclusive pages:
             * PTEs may be read-only-valid under read duplication. */
            (void)hadOther;
            if (spanStart == UINT32_MAX) {
                spanStart = p;
                spanLen = 1;
            } else if (p == spanStart + spanLen) {
                spanLen++;
            } else {
                uvmBlockSetCpuAccess(blk, spanStart, spanLen, PROT_NONE);
                spanStart = p;
                spanLen = 1;
            }
        }
        if (spanStart != UINT32_MAX)
            uvmBlockSetCpuAccess(blk, spanStart, spanLen, PROT_NONE);
    }
    tpuLockTrackRelease(TPU_LOCK_UVM_BLOCK, "dev-wrote");
    pthread_mutex_unlock(&blk->lock);
}

uint64_t uvmHbmDeviceWroteRange(uint32_t devInst, uint64_t off,
                                uint64_t bytes)
{
    DeviceWroteCtx ctx = { .devInst = devInst, .off = off,
                           .end = off + bytes };
    if (bytes == 0)
        return 0;
    uvmFaultForEachSpaceCtx(device_wrote_visit, &ctx);
    if (ctx.invalidated)
        tpuCounterAdd("uvm_device_wrote_invalidations", ctx.invalidated);
    /* Pinned overlap: force the chip->shadow download NOW (no engine
     * locks held here) so RDMA readers of the arena mapping see the
     * device-written bytes. */
    if (ctx.pinnedOverlap)
        (void)tpurmHbmReadback(devInst, off, bytes);
    return ctx.invalidated;
}
