/*
 * Tools — event trackers + counters (re-design of uvm_tools.c:54-70).
 *
 * The reference gives each tools fd an mmap'd lock-free queue userspace
 * drains directly.  The tpurm runtime is in-process, so a session is a
 * ring the client reads through uvmToolsReadEvents (the Python runtime
 * memoryview()s it through ctypes — same zero-copy effect as the
 * reference's mmap).  Overflow drops the oldest event and counts drops,
 * like the reference's queue wrap accounting.  Event types cover the
 * migration engine's lifecycle (fault/migration/eviction/thrashing/
 * prefetch/read-dup), fault-loop internals (replay, buffer flush,
 * remote maps), the device MMU (PTE updates, TLB invalidates), channel
 * RC + watchdog, PM suspend/resume, external mappings, and the
 * HMM/ATS pageable paths; remaining reference types map onto tpurm
 * counters (tpurmCounterGet).
 */
#define _GNU_SOURCE
#include "uvm_internal.h"

#include <stdatomic.h>
#include <stdlib.h>
#include <string.h>
#include <sys/mman.h>
#include <unistd.h>

struct UvmToolsSession {
    UvmVaSpace *vs;                   /* filter; NULL = all spaces */
    uint64_t typeMask;
    bool countersEnabled;
    uint64_t notifThreshold;          /* 0 = no threshold */
    uint64_t notifications;           /* threshold crossings */
    bool aboveThresh;                 /* latched: depth >= threshold */
    uint32_t capacity;                /* power of two */
    /* memfd-backed queue: header page + event ring, mappable by the
     * consumer (the reference's user-mmap'd queue). */
    int queueFd;
    UvmToolsQueueHeader *hdr;
    UvmEvent *ring;                   /* hdr + UVM_TOOLS_QUEUE_RING_OFFSET */
    size_t mapBytes;
    struct UvmToolsSession *next;
};

static uint64_t sess_pending(const UvmToolsSession *s)
{
    /* ridx FIRST: widx only grows and ridx never exceeds it, so this
     * order can momentarily under-count but can never wrap negative
     * (loading widx first could pair a stale widx with a newer ridx). */
    uint64_t r = atomic_load_explicit(&s->hdr->ridx, memory_order_acquire);
    uint64_t w = atomic_load_explicit(&s->hdr->widx, memory_order_acquire);
    return w - r;
}

static struct {
    pthread_mutex_t lock;             /* order TPU_LOCK_DIAG */
    struct UvmToolsSession *head;
} g_tools = { PTHREAD_MUTEX_INITIALIZER, NULL };

TpuStatus uvmToolsSessionCreate(UvmVaSpace *vs, uint32_t capacity,
                                UvmToolsSession **out)
{
    if (!out)
        return TPU_ERR_INVALID_ARGUMENT;
    if (capacity < 64)
        capacity = 64;
    /* Round up to a power of two. */
    while (capacity & (capacity - 1))
        capacity += capacity & (~capacity + 1);

    UvmToolsSession *s = calloc(1, sizeof(*s));
    if (!s)
        return TPU_ERR_NO_MEMORY;
    s->mapBytes = UVM_TOOLS_QUEUE_RING_OFFSET +
                  (size_t)capacity * sizeof(UvmEvent);
    s->queueFd = memfd_create("tpurm-tools-queue", MFD_CLOEXEC);
    if (s->queueFd < 0 ||
        ftruncate(s->queueFd, (off_t)s->mapBytes) != 0 ||
        (s->hdr = mmap(NULL, s->mapBytes, PROT_READ | PROT_WRITE,
                       MAP_SHARED, s->queueFd, 0)) == MAP_FAILED) {
        if (s->queueFd >= 0)
            close(s->queueFd);
        free(s);
        return TPU_ERR_NO_MEMORY;
    }
    memset(s->hdr, 0, sizeof(*s->hdr));
    s->hdr->capacity = capacity;
    s->hdr->eventSize = (uint32_t)sizeof(UvmEvent);
    s->ring = (UvmEvent *)((char *)s->hdr + UVM_TOOLS_QUEUE_RING_OFFSET);
    s->vs = vs;
    s->capacity = capacity;
    s->typeMask = ~0ull;

    pthread_mutex_lock(&g_tools.lock);
    tpuLockTrackAcquire(TPU_LOCK_DIAG, "tools");
    s->next = g_tools.head;
    g_tools.head = s;
    tpuLockTrackRelease(TPU_LOCK_DIAG, "tools");
    pthread_mutex_unlock(&g_tools.lock);
    *out = s;
    return TPU_OK;
}

void uvmToolsSessionDestroy(UvmToolsSession *s)
{
    if (!s)
        return;
    pthread_mutex_lock(&g_tools.lock);
    tpuLockTrackAcquire(TPU_LOCK_DIAG, "tools");
    UvmToolsSession **p = &g_tools.head;
    while (*p && *p != s)
        p = &(*p)->next;
    if (*p)
        *p = s->next;
    tpuLockTrackRelease(TPU_LOCK_DIAG, "tools");
    pthread_mutex_unlock(&g_tools.lock);
    munmap(s->hdr, s->mapBytes);
    close(s->queueFd);
    free(s);
}

int uvmToolsSessionQueueFd(UvmToolsSession *s)
{
    return s ? s->queueFd : -1;
}

void uvmToolsEnableEvents(UvmToolsSession *s, uint64_t typeMask)
{
    if (s)
        s->typeMask = typeMask;
}

/* Per-event-type enable/disable (reference: UVM_TOOLS_EVENT_QUEUE_
 * ENABLE/DISABLE_EVENTS modify the set, they don't replace it). */
void uvmToolsEnableEventTypes(UvmToolsSession *s, uint64_t typeMask)
{
    if (s)
        s->typeMask |= typeMask;
}

void uvmToolsDisableEventTypes(UvmToolsSession *s, uint64_t typeMask)
{
    if (s)
        s->typeMask &= ~typeMask;
}

void uvmToolsSetCountersEnabled(UvmToolsSession *s, bool enabled)
{
    if (s)
        s->countersEnabled = enabled;
}

/* Counter snapshot: tpurm counters are global; a session exposes them
 * only while its counters are enabled (reference: counters are per-fd
 * subscriptions over shared state). */
bool uvmToolsCounterGet(UvmToolsSession *s, const char *name, uint64_t *out)
{
    if (!s || !s->countersEnabled || !out)
        return false;
    *out = tpurmCounterGet(name);
    return true;
}

/* Count a notification whenever pending depth transitions from below to
 * >= threshold.  Latched (not equality-tested) so crossings are not
 * missed when the threshold is set with events already pending, or when
 * overflow's drop-oldest pins widx-ridx at capacity.  g_tools.lock held. */
static void tools_notify_update_locked(UvmToolsSession *s)
{
    bool above = s->notifThreshold &&
                 sess_pending(s) >= s->notifThreshold;
    if (above && !s->aboveThresh)
        s->notifications++;
    s->aboveThresh = above;
}

void uvmToolsSetNotificationThreshold(UvmToolsSession *s, uint64_t threshold)
{
    if (!s)
        return;
    pthread_mutex_lock(&g_tools.lock);
    tpuLockTrackAcquire(TPU_LOCK_DIAG, "tools");
    s->notifThreshold = threshold;
    tools_notify_update_locked(s);
    tpuLockTrackRelease(TPU_LOCK_DIAG, "tools");
    pthread_mutex_unlock(&g_tools.lock);
}

uint64_t uvmToolsPendingEvents(UvmToolsSession *s)
{
    if (!s)
        return 0;
    return sess_pending(s);
}

uint64_t uvmToolsNotificationCount(UvmToolsSession *s)
{
    if (!s)
        return 0;
    pthread_mutex_lock(&g_tools.lock);
    tpuLockTrackAcquire(TPU_LOCK_DIAG, "tools");
    uint64_t n = s->notifications;
    tpuLockTrackRelease(TPU_LOCK_DIAG, "tools");
    pthread_mutex_unlock(&g_tools.lock);
    return n;
}

void uvmToolsEmit(UvmVaSpace *vs, UvmEventType type, uint32_t srcTier,
                  uint32_t dstTier, uint32_t devInst, uint64_t address,
                  uint64_t bytes)
{
    /* No-session fast path: emit sites on hot paths (PTE batches under
     * blk->lock) must not serialize on the tools mutex when nobody is
     * listening.  A racy NULL read only delays the first events of a
     * session being created concurrently — benign for telemetry. */
    if (__atomic_load_n(&g_tools.head, __ATOMIC_ACQUIRE) == NULL)
        return;
    pthread_mutex_lock(&g_tools.lock);
    tpuLockTrackAcquire(TPU_LOCK_DIAG, "tools");
    for (UvmToolsSession *s = g_tools.head; s; s = s->next) {
        /* vs == NULL marks a GLOBAL event (RC, PM, MMU, links):
         * delivered to every session regardless of its space filter. */
        if (s->vs && vs && s->vs != vs)
            continue;
        if (!(s->typeMask & (1ull << type)))
            continue;
        uint64_t w = atomic_load_explicit(&s->hdr->widx,
                                          memory_order_relaxed);
        if (w - atomic_load_explicit(&s->hdr->ridx,
                                     memory_order_acquire) >=
            s->capacity) {
            /* Ring full: drop the NEW event (reference queue-full
             * accounting).  ridx belongs to the consumer — possibly an
             * external process mapping the queue — and is never stolen. */
            atomic_fetch_add_explicit(&s->hdr->dropped, 1,
                                      memory_order_relaxed);
            tpuCounterAdd("uvm_tools_events_dropped", 1);
            tools_notify_update_locked(s);
            continue;
        }
        UvmEvent *e = &s->ring[w % s->capacity];
        e->type = type;
        e->srcTier = srcTier;
        e->dstTier = dstTier;
        e->devInst = devInst;
        e->address = address;
        e->bytes = bytes;
        e->timestampNs = uvmMonotonicNs();
        /* Release-publish so a mapped consumer's acquire of widx sees
         * the completed event record. */
        atomic_store_explicit(&s->hdr->widx, w + 1, memory_order_release);
        /* Notification threshold: count the crossing (reference wakes
         * the queue's wait_queue when pending reaches the threshold). */
        tools_notify_update_locked(s);
    }
    tpuLockTrackRelease(TPU_LOCK_DIAG, "tools");
    pthread_mutex_unlock(&g_tools.lock);
}

size_t uvmToolsReadEvents(UvmToolsSession *s, UvmEvent *buf, size_t max)
{
    if (!s || !buf || max == 0)
        return 0;
    pthread_mutex_lock(&g_tools.lock);
    tpuLockTrackAcquire(TPU_LOCK_DIAG, "tools");
    size_t n = 0;
    uint64_t r = atomic_load_explicit(&s->hdr->ridx, memory_order_relaxed);
    uint64_t w = atomic_load_explicit(&s->hdr->widx, memory_order_acquire);
    while (n < max && r < w) {
        buf[n++] = s->ring[r % s->capacity];
        r++;
    }
    atomic_store_explicit(&s->hdr->ridx, r, memory_order_release);
    tools_notify_update_locked(s);    /* drain may re-arm the latch */
    tpuLockTrackRelease(TPU_LOCK_DIAG, "tools");
    pthread_mutex_unlock(&g_tools.lock);
    return n;
}
