/*
 * Tools — event trackers + counters (re-design of uvm_tools.c:54-70).
 *
 * The reference gives each tools fd an mmap'd lock-free queue userspace
 * drains directly.  The tpurm runtime is in-process, so a session is a
 * ring the client reads through uvmToolsReadEvents (the Python runtime
 * memoryview()s it through ctypes — same zero-copy effect as the
 * reference's mmap).  Overflow drops the oldest event and counts drops,
 * like the reference's queue wrap accounting.  Event types cover the
 * migration engine's lifecycle (fault/migration/eviction/thrashing/
 * prefetch/read-dup), fault-loop internals (replay, buffer flush,
 * remote maps), the device MMU (PTE updates, TLB invalidates), channel
 * RC + watchdog, PM suspend/resume, external mappings, and the
 * HMM/ATS pageable paths; remaining reference types map onto tpurm
 * counters (tpurmCounterGet).
 */
#include "uvm_internal.h"

#include <stdlib.h>
#include <string.h>

struct UvmToolsSession {
    UvmVaSpace *vs;                   /* filter; NULL = all spaces */
    uint64_t typeMask;
    bool countersEnabled;
    uint64_t notifThreshold;          /* 0 = no threshold */
    uint64_t notifications;           /* threshold crossings */
    bool aboveThresh;                 /* latched: depth >= threshold */
    uint32_t capacity;                /* power of two */
    uint64_t widx, ridx;
    UvmEvent *ring;
    struct UvmToolsSession *next;
};

static struct {
    pthread_mutex_t lock;             /* order TPU_LOCK_DIAG */
    struct UvmToolsSession *head;
} g_tools = { PTHREAD_MUTEX_INITIALIZER, NULL };

TpuStatus uvmToolsSessionCreate(UvmVaSpace *vs, uint32_t capacity,
                                UvmToolsSession **out)
{
    if (!out)
        return TPU_ERR_INVALID_ARGUMENT;
    if (capacity < 64)
        capacity = 64;
    /* Round up to a power of two. */
    while (capacity & (capacity - 1))
        capacity += capacity & (~capacity + 1);

    UvmToolsSession *s = calloc(1, sizeof(*s));
    if (!s)
        return TPU_ERR_NO_MEMORY;
    s->ring = calloc(capacity, sizeof(UvmEvent));
    if (!s->ring) {
        free(s);
        return TPU_ERR_NO_MEMORY;
    }
    s->vs = vs;
    s->capacity = capacity;
    s->typeMask = ~0ull;

    pthread_mutex_lock(&g_tools.lock);
    tpuLockTrackAcquire(TPU_LOCK_DIAG, "tools");
    s->next = g_tools.head;
    g_tools.head = s;
    tpuLockTrackRelease(TPU_LOCK_DIAG, "tools");
    pthread_mutex_unlock(&g_tools.lock);
    *out = s;
    return TPU_OK;
}

void uvmToolsSessionDestroy(UvmToolsSession *s)
{
    if (!s)
        return;
    pthread_mutex_lock(&g_tools.lock);
    tpuLockTrackAcquire(TPU_LOCK_DIAG, "tools");
    UvmToolsSession **p = &g_tools.head;
    while (*p && *p != s)
        p = &(*p)->next;
    if (*p)
        *p = s->next;
    tpuLockTrackRelease(TPU_LOCK_DIAG, "tools");
    pthread_mutex_unlock(&g_tools.lock);
    free(s->ring);
    free(s);
}

void uvmToolsEnableEvents(UvmToolsSession *s, uint64_t typeMask)
{
    if (s)
        s->typeMask = typeMask;
}

/* Per-event-type enable/disable (reference: UVM_TOOLS_EVENT_QUEUE_
 * ENABLE/DISABLE_EVENTS modify the set, they don't replace it). */
void uvmToolsEnableEventTypes(UvmToolsSession *s, uint64_t typeMask)
{
    if (s)
        s->typeMask |= typeMask;
}

void uvmToolsDisableEventTypes(UvmToolsSession *s, uint64_t typeMask)
{
    if (s)
        s->typeMask &= ~typeMask;
}

void uvmToolsSetCountersEnabled(UvmToolsSession *s, bool enabled)
{
    if (s)
        s->countersEnabled = enabled;
}

/* Counter snapshot: tpurm counters are global; a session exposes them
 * only while its counters are enabled (reference: counters are per-fd
 * subscriptions over shared state). */
bool uvmToolsCounterGet(UvmToolsSession *s, const char *name, uint64_t *out)
{
    if (!s || !s->countersEnabled || !out)
        return false;
    *out = tpurmCounterGet(name);
    return true;
}

/* Count a notification whenever pending depth transitions from below to
 * >= threshold.  Latched (not equality-tested) so crossings are not
 * missed when the threshold is set with events already pending, or when
 * overflow's drop-oldest pins widx-ridx at capacity.  g_tools.lock held. */
static void tools_notify_update_locked(UvmToolsSession *s)
{
    bool above = s->notifThreshold &&
                 s->widx - s->ridx >= s->notifThreshold;
    if (above && !s->aboveThresh)
        s->notifications++;
    s->aboveThresh = above;
}

void uvmToolsSetNotificationThreshold(UvmToolsSession *s, uint64_t threshold)
{
    if (!s)
        return;
    pthread_mutex_lock(&g_tools.lock);
    tpuLockTrackAcquire(TPU_LOCK_DIAG, "tools");
    s->notifThreshold = threshold;
    tools_notify_update_locked(s);
    tpuLockTrackRelease(TPU_LOCK_DIAG, "tools");
    pthread_mutex_unlock(&g_tools.lock);
}

uint64_t uvmToolsPendingEvents(UvmToolsSession *s)
{
    if (!s)
        return 0;
    pthread_mutex_lock(&g_tools.lock);
    tpuLockTrackAcquire(TPU_LOCK_DIAG, "tools");
    uint64_t n = s->widx - s->ridx;
    tpuLockTrackRelease(TPU_LOCK_DIAG, "tools");
    pthread_mutex_unlock(&g_tools.lock);
    return n;
}

uint64_t uvmToolsNotificationCount(UvmToolsSession *s)
{
    if (!s)
        return 0;
    pthread_mutex_lock(&g_tools.lock);
    tpuLockTrackAcquire(TPU_LOCK_DIAG, "tools");
    uint64_t n = s->notifications;
    tpuLockTrackRelease(TPU_LOCK_DIAG, "tools");
    pthread_mutex_unlock(&g_tools.lock);
    return n;
}

void uvmToolsEmit(UvmVaSpace *vs, UvmEventType type, uint32_t srcTier,
                  uint32_t dstTier, uint32_t devInst, uint64_t address,
                  uint64_t bytes)
{
    /* No-session fast path: emit sites on hot paths (PTE batches under
     * blk->lock) must not serialize on the tools mutex when nobody is
     * listening.  A racy NULL read only delays the first events of a
     * session being created concurrently — benign for telemetry. */
    if (__atomic_load_n(&g_tools.head, __ATOMIC_ACQUIRE) == NULL)
        return;
    pthread_mutex_lock(&g_tools.lock);
    tpuLockTrackAcquire(TPU_LOCK_DIAG, "tools");
    for (UvmToolsSession *s = g_tools.head; s; s = s->next) {
        /* vs == NULL marks a GLOBAL event (RC, PM, MMU, links):
         * delivered to every session regardless of its space filter. */
        if (s->vs && vs && s->vs != vs)
            continue;
        if (!(s->typeMask & (1ull << type)))
            continue;
        if (s->widx - s->ridx >= s->capacity) {
            s->ridx++;                /* drop oldest */
            tpuCounterAdd("uvm_tools_events_dropped", 1);
        }
        UvmEvent *e = &s->ring[s->widx % s->capacity];
        e->type = type;
        e->srcTier = srcTier;
        e->dstTier = dstTier;
        e->devInst = devInst;
        e->address = address;
        e->bytes = bytes;
        e->timestampNs = uvmMonotonicNs();
        s->widx++;
        /* Notification threshold: count the crossing (reference wakes
         * the queue's wait_queue when pending reaches the threshold). */
        tools_notify_update_locked(s);
    }
    tpuLockTrackRelease(TPU_LOCK_DIAG, "tools");
    pthread_mutex_unlock(&g_tools.lock);
}

size_t uvmToolsReadEvents(UvmToolsSession *s, UvmEvent *buf, size_t max)
{
    if (!s || !buf || max == 0)
        return 0;
    pthread_mutex_lock(&g_tools.lock);
    tpuLockTrackAcquire(TPU_LOCK_DIAG, "tools");
    size_t n = 0;
    while (n < max && s->ridx < s->widx) {
        buf[n++] = s->ring[s->ridx % s->capacity];
        s->ridx++;
    }
    tools_notify_update_locked(s);    /* drain may re-arm the latch */
    tpuLockTrackRelease(TPU_LOCK_DIAG, "tools");
    pthread_mutex_unlock(&g_tools.lock);
    return n;
}
