/*
 * Tier arenas: the physical backing stores the block state machine
 * migrates between.
 *
 * HBM tier  — one arena per TPU device, wrapping the device's HBM window
 *             (fake-device backend: host memory; real chip: the window the
 *             Python runtime registers).  Reference analog: per-GPU PMA
 *             managed by uvm_pmm_gpu.c.
 * CXL tier  — one global arena over the CXL expander window, fake mode a
 *             MAP_NORESERVE anonymous mapping sized by registry
 *             "cxl_tier_bytes" (default 1 GB).  Reference analog: the
 *             fork's CXL buffers (p2p_cxl.c) used as migration target.
 * HOST tier — the managed VA itself (no arena; unbounded).
 *
 * Each arena owns a PMM and an eviction LRU of blocks with residency in
 * it (reference: root-chunk LRU in uvm_pmm_gpu.c).
 */
#define _GNU_SOURCE
#include "uvm_internal.h"

#include <stdio.h>
#include <stdlib.h>
#include <sys/mman.h>
#include <unistd.h>

#include <time.h>

#define MAX_HBM_ARENAS 16

/* Alias of the process-wide clock (internal.h tpuNowNs): journal,
 * inject and trace timestamps are directly comparable with UVM's. */
uint64_t uvmMonotonicNs(void)
{
    return tpuNowNs();
}

static struct {
    pthread_once_t once;
    UvmTierArena hbm[MAX_HBM_ARENAS];
    uint32_t hbmCount;
    UvmTierArena cxl;
    bool cxlOk;
} g_tiers = { .once = PTHREAD_ONCE_INIT };

uint64_t uvmPageSize(void)
{
    static uint64_t cached;
    if (!cached) {
        uint64_t ps = tpuRegistryGet("uvm_page_size", UVM_PAGE_SIZE_DEFAULT);
        if (ps < 4096 || ps > UVM_BLOCK_SIZE || (ps & (ps - 1)))
            ps = UVM_PAGE_SIZE_DEFAULT;
        cached = ps;
    }
    return cached;
}

uint32_t uvmPagesPerBlock(void)
{
    return (uint32_t)(UVM_BLOCK_SIZE / uvmPageSize());
}

static TpuStatus arena_init(UvmTierArena *a, UvmTier tier, uint32_t devInst,
                            void *base, uint64_t size)
{
    a->tier = tier;
    a->devInst = devInst;
    a->base = base;
    a->size = size;
    /* LRU lock stripes share the PMM's knob: one "tier_lock_shards"
     * governs both halves of the tier locking. */
    long ncpu = sysconf(_SC_NPROCESSORS_ONLN);
    if (ncpu < 1)
        ncpu = 1;
    uint64_t dflt = (uint64_t)ncpu < UVM_TIER_LRU_SHARDS
                        ? (uint64_t)ncpu : UVM_TIER_LRU_SHARDS;
    uint64_t shards = tpuRegistryGet("tier_lock_shards", dflt);
    if (shards < 1)
        shards = 1;
    if (shards > UVM_TIER_LRU_SHARDS)
        shards = UVM_TIER_LRU_SHARDS;
    a->lruShardCount = (uint32_t)shards;
    atomic_store_explicit(&a->victimCursor, 0, memory_order_relaxed);
    for (uint32_t s = 0; s < a->lruShardCount; s++) {
        pthread_mutex_init(&a->lru[s].lock, NULL);
        pthread_cond_init(&a->lru[s].evictCond, NULL);
        a->lru[s].lruHead = a->lru[s].lruTail = NULL;
    }
    return uvmPmmInit(&a->pmm, size, uvmPageSize());
}

static void tiers_init_once(void)
{
    tpuDeviceGlobalInit();
    uint32_t n = tpurmDeviceCount();
    if (n > MAX_HBM_ARENAS)
        n = MAX_HBM_ARENAS;
    for (uint32_t i = 0; i < n; i++) {
        TpurmDevice *dev = tpurmDeviceGet(i);
        if (!dev || !tpurmDeviceHbmBase(dev))
            continue;
        if (arena_init(&g_tiers.hbm[i], UVM_TIER_HBM, i,
                       tpurmDeviceHbmBase(dev),
                       tpurmDeviceHbmSize(dev)) == TPU_OK)
            g_tiers.hbmCount = i + 1;
    }

    uint64_t cxlBytes = tpuRegistryGet("cxl_tier_bytes", 1ull << 30);
    void *cxlBase = mmap(NULL, cxlBytes, PROT_READ | PROT_WRITE,
                         MAP_PRIVATE | MAP_ANONYMOUS | MAP_NORESERVE, -1, 0);
    if (cxlBase != MAP_FAILED &&
        arena_init(&g_tiers.cxl, UVM_TIER_CXL, 0, cxlBase, cxlBytes) ==
            TPU_OK) {
        g_tiers.cxlOk = true;
        TPU_LOG(TPU_LOG_INFO, "uvm", "CXL tier arena: %llu MB",
               (unsigned long long)(cxlBytes >> 20));
    } else {
        TPU_LOG(TPU_LOG_ERROR, "uvm", "CXL tier arena init failed");
    }
}

UvmTierArena *uvmTierArenaHbm(uint32_t devInst)
{
    pthread_once(&g_tiers.once, tiers_init_once);
    if (devInst >= g_tiers.hbmCount || !g_tiers.hbm[devInst].base)
        return NULL;
    return &g_tiers.hbm[devInst];
}

UvmTierArena *uvmTierArenaCxl(void)
{
    pthread_once(&g_tiers.once, tiers_init_once);
    return g_tiers.cxlOk ? &g_tiers.cxl : NULL;
}

/* ------------------------------------- external HBM chunk allocation
 *
 * Pools that live IN device HBM but outside the managed-VA world (the
 * ICI peer-mapped KV pool, peermem exports) must share the tier's PMM
 * with the fault engine — carving arena bytes privately would collide
 * with fault-driven residency (the whole arena belongs to the PMM).
 * Reference analog: PMA serves both UVM and RM allocations from one
 * per-GPU allocator (uvm_pmm_gpu.h:27-47 external/internal types). */

TpuStatus uvmHbmChunkAllocSized(uint32_t devInst, uint64_t size,
                                uint64_t *outOffset, uint64_t *outSize,
                                void **outHandle)
{
    if (!outOffset || !outHandle || size == 0)
        return TPU_ERR_INVALID_ARGUMENT;
    UvmTierArena *a = uvmTierArenaHbm(devInst);
    if (!a)
        return TPU_ERR_INVALID_DEVICE;
    uint64_t want = uvmPageSize();
    while (want < size)
        want <<= 1;
    if (want > UVM_BLOCK_SIZE)
        return TPU_ERR_INVALID_LIMIT;
    UvmPmmChunk *chunk = NULL;
    TpuStatus st = uvmPmmAlloc(&a->pmm, want, &chunk);
    if (st != TPU_OK)
        return st;
    *outOffset = chunk->offset;
    if (outSize)
        *outSize = want;    /* the ladder's granted size — callers must
                             * not re-derive it (policy lives HERE) */
    *outHandle = chunk;
    return TPU_OK;
}

TpuStatus uvmHbmChunkAlloc(uint32_t devInst, uint64_t size,
                           uint64_t *outOffset, void **outHandle)
{
    return uvmHbmChunkAllocSized(devInst, size, outOffset, NULL,
                                 outHandle);
}

/* Arena occupancy (tpuvac target headroom check: an evacuation target
 * must have real free HBM before pages are pointed at it).  Reads the
 * PMM's allocated-bytes ledger — no lock beyond the PMM's own. */
TpuStatus uvmHbmArenaUsage(uint32_t devInst, uint64_t *freeBytes,
                           uint64_t *totalBytes)
{
    UvmTierArena *a = uvmTierArenaHbm(devInst);
    if (!a)
        return TPU_ERR_INVALID_DEVICE;
    uint64_t total = a->size;
    uint64_t used = uvmPmmAllocatedBytes(&a->pmm);
    /* Bytes this device LENT to peers' REMOTE tiers don't count as
     * used: a lease is reclaimable on demand (revoke -> borrowers fall
     * back to HOST), so charging the lender would double-count borrowed
     * pages in vac target picking (tpusplit satellite fix). */
    uint64_t lent = uvmTierRemoteLentBytes(devInst);
    used = lent > used ? 0 : used - lent;
    if (freeBytes)
        *freeBytes = used > total ? 0 : total - used;
    if (totalBytes)
        *totalBytes = total;
    return TPU_OK;
}

TpuStatus uvmHbmChunkFree(uint32_t devInst, void *handle)
{
    UvmTierArena *a = uvmTierArenaHbm(devInst);
    if (!a || !handle)
        return TPU_ERR_INVALID_ARGUMENT;
    uvmPmmFree(&a->pmm, handle);
    return TPU_OK;
}

/* ------------------------------------------------------------------ LRU */

static int lru_index(const UvmTierArena *a)
{
    return a->tier == UVM_TIER_CXL ? 1 : 0;
}

/* A block's LRU stripe is keyed by its VA block index — stable for the
 * block's life, so Touch/Remove/EvictDone/AwaitEvictors always meet on
 * the same lock and cond. */
static inline UvmTierLruShard *lru_shard_of(UvmTierArena *a,
                                            const UvmVaBlock *blk)
{
    return &a->lru[(blk->start / UVM_BLOCK_SIZE) % a->lruShardCount];
}

void uvmLruTouch(UvmTierArena *a, UvmVaBlock *blk)
{
    int ix = lru_index(a);
    UvmTierLruShard *sh = lru_shard_of(a, blk);
    /* The fault-path hot producer: trylock first so stripe contention
     * is measurable (the shards exist to keep this ~0). */
    if (pthread_mutex_trylock(&sh->lock) != 0) {
        tpuCounterAdd("tier_lock_contended", 1);
        pthread_mutex_lock(&sh->lock);
    }
    tpuLockTrackAcquire(TPU_LOCK_UVM_PMM, "arena-lru");
    if (blk->lru[ix].on) {
        /* unlink */
        if (blk->lru[ix].prev)
            blk->lru[ix].prev->lru[ix].next = blk->lru[ix].next;
        else
            sh->lruHead = blk->lru[ix].next;
        if (blk->lru[ix].next)
            blk->lru[ix].next->lru[ix].prev = blk->lru[ix].prev;
        else
            sh->lruTail = blk->lru[ix].prev;
    }
    /* append at tail (most recently used) */
    blk->lru[ix].prev = sh->lruTail;
    blk->lru[ix].next = NULL;
    if (sh->lruTail)
        sh->lruTail->lru[ix].next = blk;
    else
        sh->lruHead = blk;
    sh->lruTail = blk;
    blk->lru[ix].on = true;
    tpuLockTrackRelease(TPU_LOCK_UVM_PMM, "arena-lru");
    pthread_mutex_unlock(&sh->lock);
}

void uvmLruRemove(UvmTierArena *a, UvmVaBlock *blk)
{
    int ix = lru_index(a);
    UvmTierLruShard *sh = lru_shard_of(a, blk);
    pthread_mutex_lock(&sh->lock);
    tpuLockTrackAcquire(TPU_LOCK_UVM_PMM, "arena-lru");
    if (blk->lru[ix].on) {
        if (blk->lru[ix].prev)
            blk->lru[ix].prev->lru[ix].next = blk->lru[ix].next;
        else
            sh->lruHead = blk->lru[ix].next;
        if (blk->lru[ix].next)
            blk->lru[ix].next->lru[ix].prev = blk->lru[ix].prev;
        else
            sh->lruTail = blk->lru[ix].prev;
        blk->lru[ix].on = false;
        blk->lru[ix].prev = blk->lru[ix].next = NULL;
    }
    tpuLockTrackRelease(TPU_LOCK_UVM_PMM, "arena-lru");
    pthread_mutex_unlock(&sh->lock);
}

UvmVaBlock *uvmLruPopVictim(UvmTierArena *a, UvmVaBlock *exclude)
{
    int ix = lru_index(a);
    uint64_t now = uvmMonotonicNs();
    /* Victim scans walk the stripes round-robin from a rotating cursor
     * (concurrent evictors fan out instead of piling on one stripe).
     * Victim ORDER is per-stripe: the selection policy below — pin
     * skip, tpuhot coldness, tenant SLO classes — runs within one
     * stripe's list at a time, so cross-stripe ordering is approximate
     * (the reference's per-GPU root-chunk lists have the same shape).
     * With tier_lock_shards=1 the historical global order is exact. */
    uint32_t start = atomic_fetch_add_explicit(&a->victimCursor, 1,
                                               memory_order_relaxed);
    UvmVaBlock *blk = NULL;
    for (uint32_t k = 0; k < a->lruShardCount && !blk; k++) {
    UvmTierLruShard *sh = &a->lru[(start + k) % a->lruShardCount];
    pthread_mutex_lock(&sh->lock);
    tpuLockTrackAcquire(TPU_LOCK_UVM_PMM, "arena-lru");
    blk = sh->lruHead;
    while (blk) {
        /* Skip the allocating block itself, blocks pinned to this tier
         * by thrashing mitigation (uvm_perf_thrashing.h PIN hint), and
         * P2P-pinned blocks (RDMA holds bus addresses into them). */
        bool pinned = (blk->pinnedTier == (int32_t)a->tier &&
                       blk->pinExpiryNs > now) || blk->p2pPinCount > 0;
        if (blk != exclude && !pinned)
            break;
        blk = blk->lru[ix].next;
    }
    /* Hotness-fed victim scoring, plain-LRU path (tpuhot): the list
     * head is the oldest INSERTION, not necessarily the coldest data —
     * a released-but-hot block reinserted at the cold end would be the
     * next victim on position alone.  A bounded scan picks the
     * genuinely-coldest candidate by decayed score; ties (cold
     * tracker, uniform scores) keep the historical head-first order
     * byte-for-byte.  The reorder is a tpuhot policy decision: it runs
     * under the hot.decide inject site and degrades to the positional
     * pick. */
    if (blk && !uvmTenantsActive()) {
        uint64_t depth = uvmHotVictimScanDepth();
        if (depth) {
            UvmVaBlock *best = blk;
            uint64_t bestScore = uvmHotBlockScore(blk, now);
            uint64_t seen = 0;
            /* Every TRAVERSED candidate counts toward the depth bound
             * (not just eligible ones): a pin storm must not turn this
             * into an O(list) walk under the arena lock. */
            for (UvmVaBlock *cand = blk->lru[ix].next;
                 cand && seen < depth; cand = cand->lru[ix].next) {
                seen++;
                bool pinned = (cand->pinnedTier == (int32_t)a->tier &&
                               cand->pinExpiryNs > now) ||
                              cand->p2pPinCount > 0;
                if (cand == exclude || pinned)
                    continue;
                uint64_t s = uvmHotBlockScore(cand, now);
                if (s < bestScore) {
                    best = cand;
                    bestScore = s;
                }
            }
            if (best != blk && uvmHotDecideAllowed()) {
                blk = best;
                uvmHotVictimReorderNote();
            }
        }
    }
    /* SLO-aware victim selection (multi-tenant QoS): once tenants are
     * configured, the plain LRU-head pop becomes a scored walk — cold
     * blocks of OVER-QUOTA tenants victimize first, then lower-priority
     * tenants, and within a class the list order (coldest first) is the
     * tie-break; pinned blocks stay exempt.  An unconfigured process
     * never enters this walk, keeping the historical eviction order
     * byte-for-byte.  Reference analog: the reference's eviction also
     * consults policy before the root-chunk LRU order
     * (uvm_pmm_gpu.c chunk_free_locked policy hooks). */
    if (blk && uvmTenantsActive()) {
        UvmVaBlock *best = blk;
        UvmTenant *bt = uvmTenantOfSpace(blk->range->vaSpace);
        bool bestOver = uvmTenantOverQuota(bt, a->tier);
        uint32_t bestPrio = atomic_load_explicit(&bt->priority,
                                                 memory_order_relaxed);
        bool hotScored = uvmHotVictimScanDepth() != 0;
        uint64_t bestScore = hotScored ? uvmHotBlockScore(blk, now) : 0;
        /* The score-less lexicographic pick runs alongside: if the
         * hotness tie-break ends up CHANGING the victim, that is a
         * tpuhot policy decision — gated on hot.decide (degrade =
         * keep the positional pick) and counted like the plain-path
         * reorder. */
        UvmVaBlock *baseBest = blk;
        bool baseOver = bestOver;
        uint32_t basePrio = bestPrio;
        for (UvmVaBlock *cand = blk->lru[ix].next; cand;
             cand = cand->lru[ix].next) {
            bool pinned = (cand->pinnedTier == (int32_t)a->tier &&
                           cand->pinExpiryNs > now) ||
                          cand->p2pPinCount > 0;
            if (cand == exclude || pinned)
                continue;
            UvmTenant *ct = uvmTenantOfSpace(cand->range->vaSpace);
            bool over = uvmTenantOverQuota(ct, a->tier);
            uint32_t prio = atomic_load_explicit(&ct->priority,
                                                 memory_order_relaxed);
            if ((over && !baseOver) ||
                (over == baseOver && prio < basePrio)) {
                baseBest = cand;
                baseOver = over;
                basePrio = prio;
            }
            /* Lexicographic (overQuota desc, priority asc, decayed
             * hotness asc — the tpuhot coldness signal replaces raw
             * list position as the in-class tie-break, so eviction
             * takes genuinely-cold blocks); with the scorer disabled
             * (hot_victim_scan=0) earlier list position wins ties by
             * never replacing, the historical order. */
            uint64_t score = hotScored ? uvmHotBlockScore(cand, now) : 0;
            if ((over && !bestOver) ||
                (over == bestOver && prio < bestPrio) ||
                (hotScored && over == bestOver && prio == bestPrio &&
                 score < bestScore)) {
                best = cand;
                bestOver = over;
                bestPrio = prio;
                bestScore = score;
            }
        }
        if (best != baseBest) {
            if (uvmHotDecideAllowed()) {
                uvmHotVictimReorderNote();
            } else {
                best = baseBest;      /* injected: positional pick */
                bestOver = baseOver;
            }
        }
        if (best != blk)
            tpuCounterAdd("tier_tenant_slo_reorders", 1);
        if (bestOver)
            tpuCounterAdd("tier_tenant_over_quota_evictions", 1);
        blk = best;
        char scoped[48];
        snprintf(scoped, sizeof(scoped), "tier_tenant_evictions[t%u]",
                 uvmTenantOfSpace(blk->range->vaSpace)->id);
        tpuCounterAdd(scoped, 1);
        tpuCounterAdd("tier_tenant_evictions", 1);
    }
    if (blk) {
        if (blk->lru[ix].prev)
            blk->lru[ix].prev->lru[ix].next = blk->lru[ix].next;
        else
            sh->lruHead = blk->lru[ix].next;
        if (blk->lru[ix].next)
            blk->lru[ix].next->lru[ix].prev = blk->lru[ix].prev;
        else
            sh->lruTail = blk->lru[ix].prev;
        blk->lru[ix].on = false;
        blk->lru[ix].prev = blk->lru[ix].next = NULL;
        blk->lru[ix].evicting = true;   /* lifetime guard for the caller */
    }
    tpuLockTrackRelease(TPU_LOCK_UVM_PMM, "arena-lru");
    pthread_mutex_unlock(&sh->lock);
    }
    return blk;
}

void uvmLruEvictDone(UvmTierArena *a, UvmVaBlock *blk)
{
    int ix = lru_index(a);
    /* blk->start is immutable, so the evicting flag and its waiters
     * always meet on the same stripe's lock + condvar. */
    UvmTierLruShard *sh = lru_shard_of(a, blk);
    pthread_mutex_lock(&sh->lock);
    tpuLockTrackAcquire(TPU_LOCK_UVM_PMM, "arena-lru");
    blk->lru[ix].evicting = false;
    pthread_cond_broadcast(&sh->evictCond);
    tpuLockTrackRelease(TPU_LOCK_UVM_PMM, "arena-lru");
    pthread_mutex_unlock(&sh->lock);
}

void uvmLruAwaitEvictors(UvmTierArena *a, UvmVaBlock *blk)
{
    int ix = lru_index(a);
    UvmTierLruShard *sh = lru_shard_of(a, blk);
    pthread_mutex_lock(&sh->lock);
    tpuLockTrackAcquire(TPU_LOCK_UVM_PMM, "arena-lru");
    while (blk->lru[ix].evicting)
        pthread_cond_wait(&sh->evictCond, &sh->lock);
    tpuLockTrackRelease(TPU_LOCK_UVM_PMM, "arena-lru");
    pthread_mutex_unlock(&sh->lock);
}
