/*
 * VA space — per-client top-level UVM object.
 *
 * Re-design of the reference's uvm_va_space.c (2,703 LoC): registered
 * devices, the VA range tree, policy application, and range groups.
 * Managed ranges are created by uvmMemAlloc or by mmap of the uvm
 * pseudo-fd (reference uvm_mmap + cudaMallocManaged).
 *
 * Policy on a sub-span SPLITS the containing range at the span
 * boundaries (reference uvm_va_range.c split machinery), so different
 * halves of one allocation can carry different preferred tiers.  Split
 * points must land on 2 MB block boundaries — blocks are the residency/
 * backing unit and are not split here (the reference splits blocks too,
 * uvm_va_block_split); sub-block policy spans return INVALID_ADDRESS
 * explicitly rather than silently applying to the whole range.
 */
#define _GNU_SOURCE
#include "uvm_internal.h"
#include "tpurm/ce.h"

#include "tpurm/peermem.h"

#include <sched.h>
#include <stdlib.h>
#include <string.h>
#include <sys/mman.h>
#include <unistd.h>

static void vs_lock(UvmVaSpace *vs)
{
    pthread_mutex_lock(&vs->lock);
    tpuLockTrackAcquire(TPU_LOCK_UVM_VASPACE, "vaspace");
}

static void vs_unlock(UvmVaSpace *vs)
{
    tpuLockTrackRelease(TPU_LOCK_UVM_VASPACE, "vaspace");
    pthread_mutex_unlock(&vs->lock);
}

/* ------------------------------------------------------------- tenants
 *
 * Process-global QoS table (uvm.h tenant API; uvm_internal.h UvmTenant).
 * Slot 0 is the default tenant every space starts in.  Configuration
 * takes the table lock; the block-path charge/uncharge and the victim
 * walk read the table lock-free (slots only transition unused -> used,
 * published with a release store on `used`; usage counters are atomics).
 */

static struct {
    pthread_mutex_t lock;
    UvmTenant t[UVM_MAX_TENANTS];
    _Atomic int active;          /* nonzero once a non-default tenant
                                  * or non-default policy exists */
} g_tenants = {
    .lock = PTHREAD_MUTEX_INITIALIZER,
    .t = { [0] = { .id = 0, .priority = UVM_TENANT_PRIO_DEFAULT,
                   .used = true } },
};

bool uvmTenantsActive(void)
{
    return atomic_load_explicit(&g_tenants.active,
                                memory_order_relaxed) != 0;
}

UvmTenant *uvmTenantGet(uint32_t tenantId)
{
    for (int i = 0; i < UVM_MAX_TENANTS; i++) {
        UvmTenant *t = &g_tenants.t[i];
        if (__atomic_load_n(&t->used, __ATOMIC_ACQUIRE) &&
            t->id == tenantId)
            return t;
    }
    return NULL;
}

TpuStatus uvmTenantConfigure(uint32_t tenantId, uint32_t priority,
                             uint64_t hbmQuotaPages,
                             uint64_t cxlQuotaPages)
{
    pthread_mutex_lock(&g_tenants.lock);
    UvmTenant *t = uvmTenantGet(tenantId);
    if (!t) {
        for (int i = 0; i < UVM_MAX_TENANTS; i++) {
            if (!g_tenants.t[i].used) {
                t = &g_tenants.t[i];
                break;
            }
        }
        if (!t) {
            pthread_mutex_unlock(&g_tenants.lock);
            return TPU_ERR_INSUFFICIENT_RESOURCES;
        }
        t->id = tenantId;
    }
    atomic_store_explicit(&t->priority, priority, memory_order_relaxed);
    atomic_store_explicit(&t->quotaPages[UVM_TIER_HBM], hbmQuotaPages,
                          memory_order_relaxed);
    atomic_store_explicit(&t->quotaPages[UVM_TIER_CXL], cxlQuotaPages,
                          memory_order_relaxed);
    /* First publication AFTER the fields (release on `used`); later
     * reconfigures rely on the fields themselves being atomic. */
    __atomic_store_n(&t->used, true, __ATOMIC_RELEASE);
    atomic_store_explicit(&g_tenants.active, 1, memory_order_release);
    pthread_mutex_unlock(&g_tenants.lock);
    tpuCounterAdd("tier_tenant_configs", 1);
    TPU_LOG(TPU_LOG_INFO, "uvm",
           "tenant %u: prio=%u quota hbm=%llu cxl=%llu pages", tenantId,
           priority, (unsigned long long)hbmQuotaPages,
           (unsigned long long)cxlQuotaPages);
    return TPU_OK;
}

TpuStatus uvmTenantInfoGet(uint32_t tenantId, UvmTenantInfo *out)
{
    if (!out)
        return TPU_ERR_INVALID_ARGUMENT;
    UvmTenant *t = uvmTenantGet(tenantId);
    if (!t)
        return TPU_ERR_OBJECT_NOT_FOUND;
    out->priority = atomic_load_explicit(&t->priority,
                                         memory_order_relaxed);
    out->hbmQuotaPages = atomic_load_explicit(
        &t->quotaPages[UVM_TIER_HBM], memory_order_relaxed);
    out->cxlQuotaPages = atomic_load_explicit(
        &t->quotaPages[UVM_TIER_CXL], memory_order_relaxed);
    out->hbmPages = atomic_load_explicit(&t->usedPages[UVM_TIER_HBM],
                                         memory_order_relaxed);
    out->cxlPages = atomic_load_explicit(&t->usedPages[UVM_TIER_CXL],
                                         memory_order_relaxed);
    return TPU_OK;
}

UvmTenant *uvmTenantOfSpace(UvmVaSpace *vs)
{
    UvmTenant *t = vs ? uvmTenantGet(atomic_load_explicit(
                            &vs->tenantId, memory_order_relaxed))
                      : NULL;
    return t ? t : &g_tenants.t[0];
}

bool uvmTenantOverQuota(const UvmTenant *t, UvmTier tier)
{
    if (!t || tier >= UVM_TIER_COUNT)
        return false;
    uint64_t quota = atomic_load_explicit(&t->quotaPages[tier],
                                          memory_order_relaxed);
    if (!quota)
        return false;
    return atomic_load_explicit(&t->usedPages[tier],
                                memory_order_relaxed) > quota;
}

void uvmTenantCharge(UvmVaSpace *vs, UvmTier tier, int64_t pages)
{
    if (!vs || pages == 0 ||
        (tier != UVM_TIER_HBM && tier != UVM_TIER_CXL))
        return;
    UvmTenant *t = uvmTenantOfSpace(vs);
    atomic_fetch_add_explicit(&t->usedPages[tier], (uint64_t)pages,
                              memory_order_relaxed);
    atomic_fetch_add_explicit(&vs->tenantPages[tier], (uint64_t)pages,
                              memory_order_relaxed);
}

TpuStatus uvmVaSpaceBindTenant(UvmVaSpace *vs, uint32_t tenantId)
{
    if (!vs)
        return TPU_ERR_INVALID_ARGUMENT;
    pthread_mutex_lock(&g_tenants.lock);
    UvmTenant *to = uvmTenantGet(tenantId);
    if (!to) {
        pthread_mutex_unlock(&g_tenants.lock);
        return TPU_ERR_OBJECT_NOT_FOUND;
    }
    UvmTenant *from = uvmTenantOfSpace(vs);
    if (from != to) {
        /* Move the space's existing charge so usage stays truthful
         * across a rebind (concurrent block-path charges land on
         * whichever tenant the racing read resolves — benign: the
         * next uncharge follows the same binding). */
        for (int tier = 0; tier < UVM_TIER_COUNT; tier++) {
            uint64_t held = atomic_load_explicit(
                &vs->tenantPages[tier], memory_order_relaxed);
            if (held) {
                atomic_fetch_sub_explicit(&from->usedPages[tier], held,
                                          memory_order_relaxed);
                atomic_fetch_add_explicit(&to->usedPages[tier], held,
                                          memory_order_relaxed);
            }
        }
        atomic_store_explicit(&vs->tenantId, tenantId,
                              memory_order_release);
    }
    pthread_mutex_unlock(&g_tenants.lock);
    tpuCounterAdd("tier_tenant_binds", 1);
    return TPU_OK;
}

void uvmTenantDevCharge(uint32_t tenantId, uint32_t devInst,
                        int64_t pages)
{
    if (devInst >= UVM_TENANT_MAX_DEVS || pages == 0)
        return;
    UvmTenant *t = uvmTenantGet(tenantId);
    if (!t)
        return;
    atomic_fetch_add_explicit(&t->devPages[devInst], (uint64_t)pages,
                              memory_order_relaxed);
}

TpuStatus uvmTenantRebindDevicePages(uint32_t tenantId, uint32_t fromDev,
                                     uint32_t toDev, uint64_t pages)
{
    if (fromDev >= UVM_TENANT_MAX_DEVS || toDev >= UVM_TENANT_MAX_DEVS ||
        fromDev == toDev)
        return TPU_ERR_INVALID_ARGUMENT;
    UvmTenant *t = uvmTenantGet(tenantId);
    if (!t)
        return TPU_ERR_OBJECT_NOT_FOUND;
    /* Clamp to what the source column actually charges: a rebind must
     * never drive a gauge negative (racing releases are fine — the
     * loser of the race just moves fewer pages). */
    uint64_t have = atomic_load_explicit(&t->devPages[fromDev],
                                         memory_order_relaxed);
    if (pages > have)
        pages = have;
    if (pages) {
        atomic_fetch_sub_explicit(&t->devPages[fromDev], pages,
                                  memory_order_relaxed);
        atomic_fetch_add_explicit(&t->devPages[toDev], pages,
                                  memory_order_relaxed);
    }
    tpuCounterAdd("tpurm_tenant_rebinds", 1);
    return TPU_OK;
}

uint64_t uvmTenantDevPages(uint32_t tenantId, uint32_t devInst)
{
    if (devInst >= UVM_TENANT_MAX_DEVS)
        return 0;
    UvmTenant *t = uvmTenantGet(tenantId);
    return t ? atomic_load_explicit(&t->devPages[devInst],
                                    memory_order_relaxed)
             : 0;
}

void uvmTenantRenderProm(TpuCur *c)
{
    static const char *tierName[UVM_TIER_COUNT] = { "host", "hbm",
                                                    "cxl", "remote" };
    tpuCurf(c, "# TYPE tpurm_tenant_pages gauge\n");
    tpuCurf(c, "# TYPE tpurm_tenant_quota_pages gauge\n");
    for (int i = 0; i < UVM_MAX_TENANTS; i++) {
        UvmTenant *t = &g_tenants.t[i];
        if (!__atomic_load_n(&t->used, __ATOMIC_ACQUIRE))
            continue;
        for (int tier = UVM_TIER_HBM; tier <= UVM_TIER_CXL; tier++) {
            tpuCurf(c, "tpurm_tenant_pages{tenant=\"%u\",tier=\"%s\"} "
                    "%llu\n", t->id, tierName[tier],
                    (unsigned long long)atomic_load_explicit(
                        &t->usedPages[tier], memory_order_relaxed));
            tpuCurf(c, "tpurm_tenant_quota_pages{tenant=\"%u\","
                    "tier=\"%s\"} %llu\n", t->id, tierName[tier],
                    (unsigned long long)atomic_load_explicit(
                        &t->quotaPages[tier], memory_order_relaxed));
        }
        for (uint32_t d = 0; d < UVM_TENANT_MAX_DEVS; d++) {
            uint64_t p = atomic_load_explicit(&t->devPages[d],
                                              memory_order_relaxed);
            if (p)
                tpuCurf(c, "tpurm_tenant_dev_pages{tenant=\"%u\","
                        "dev=\"%u\"} %llu\n", t->id, d,
                        (unsigned long long)p);
        }
    }
}

void uvmTenantRenderTable(TpuCur *c)
{
    tpuCurf(c, "%-8s %-8s %-12s %-12s %-12s %-12s\n", "tenant", "prio",
            "hbm_pages", "hbm_quota", "cxl_pages", "cxl_quota");
    for (int i = 0; i < UVM_MAX_TENANTS; i++) {
        UvmTenant *t = &g_tenants.t[i];
        if (!__atomic_load_n(&t->used, __ATOMIC_ACQUIRE))
            continue;
        tpuCurf(c, "%-8u %-8u %-12llu %-12llu %-12llu %-12llu\n", t->id,
                atomic_load_explicit(&t->priority, memory_order_relaxed),
                (unsigned long long)atomic_load_explicit(
                    &t->usedPages[UVM_TIER_HBM], memory_order_relaxed),
                (unsigned long long)atomic_load_explicit(
                    &t->quotaPages[UVM_TIER_HBM], memory_order_relaxed),
                (unsigned long long)atomic_load_explicit(
                    &t->usedPages[UVM_TIER_CXL], memory_order_relaxed),
                (unsigned long long)atomic_load_explicit(
                    &t->quotaPages[UVM_TIER_CXL], memory_order_relaxed));
    }
}

TpuStatus uvmVaSpaceCreate(UvmVaSpace **out)
{
    if (!out)
        return TPU_ERR_INVALID_ARGUMENT;
    tpuDeviceGlobalInit();
    uvmFaultEngineInit();
    UvmVaSpace *vs = calloc(1, sizeof(*vs));
    if (!vs)
        return TPU_ERR_NO_MEMORY;
    pthread_mutex_init(&vs->lock, NULL);
    uvmRangeTreeInit(&vs->ranges);
    vs->nextRangeGroupId = 1;
    vs->pageSize = uvmPageSize();
    uvmFaultEngineRegisterSpace(vs);
    tpuCounterAdd("uvm_va_spaces_created", 1);
    *out = vs;
    return TPU_OK;
}

static UvmRangeDestroyHook g_rangeDestroyHook;

void uvmSetRangeDestroyHook(UvmRangeDestroyHook hook)
{
    g_rangeDestroyHook = hook;
}

static void ext_unmap_span(UvmVaRange *range, UvmExtMapping *m)
{
    /* Restore the caller's reservation over the window. */
    mmap((void *)(uintptr_t)m->start, m->len, PROT_NONE,
         MAP_PRIVATE | MAP_ANONYMOUS | MAP_NORESERVE | MAP_FIXED, -1, 0);
    (void)range;
    tpuDmabufPut(m->buf);
}

static void range_destroy(UvmVaSpace *vs, UvmVaRange *range)
{
    /* Drop any mmap-surface registry entry BEFORE the munmap below: a
     * shim-interposed munmap re-entering the hook must miss. */
    uvmMmapRegistryOnRangeDestroy(range->node.start);
    if (g_rangeDestroyHook)
        g_rangeDestroyHook(range->node.start, range->size);
    for (uint32_t i = 0; i < range->blockCount; i++) {
        UvmVaBlock *blk = range->blocks[i];
        if (!blk)
            continue;
        uvmBlockFreeBacking(blk);
        pthread_mutex_destroy(&blk->lock);
        free(blk);
    }
    free(range->blocks);
    while (range->extMappings) {
        UvmExtMapping *m = range->extMappings;
        range->extMappings = m->next;
        ext_unmap_span(range, m);
        free(m);
    }
    uvmRangeTreeRemove(&vs->ranges, &range->node);
    if (range->type == UVM_RANGE_TYPE_EXTERNAL) {
        /* The VA reservation belongs to the caller (they mmap'd it);
         * dropping the range must not yank it out from under them. */
        free(range);
        return;
    }
    if (range->adopted)
        /* Put an anonymous mapping with the current contents back under
         * the caller's VA (their allocator still owns it). */
        uvmHmmRestoreOnDestroy(range);
    else
        munmap((void *)(uintptr_t)range->node.start, range->size);
    if (range->alias)
        munmap(range->alias, range->size);
    if (range->memfd >= 0)
        close(range->memfd);
    free(range);
}

void uvmVaSpaceDestroy(UvmVaSpace *vs)
{
    if (!vs)
        return;
    /* Adopted ranges must carry their CURRENT bytes into the restored
     * anonymous mappings: pull device residency home before teardown
     * (the memFree path does the same per allocation).  No cap — every
     * adopted range is collected; a failed migrate is LOGGED loudly
     * (destroy cannot refuse like memFree does, but silent stale
     * restores are the one unacceptable outcome). */
    struct AdoptedSpan { uint64_t start, size; };
    struct AdoptedSpan *adopted = NULL;
    uint32_t nAdopted = 0, capAdopted = 0;
    vs_lock(vs);
    for (UvmRangeTreeNode *n = vs->ranges.first; n;
         n = uvmRangeTreeNext(n)) {
        UvmVaRange *r = (UvmVaRange *)n;
        if (!r->adopted)
            continue;
        if (nAdopted == capAdopted) {
            capAdopted = capAdopted ? capAdopted * 2 : 16;
            struct AdoptedSpan *grown =
                realloc(adopted, capAdopted * sizeof(*adopted));
            if (!grown)
                break;          /* OOM: remaining ranges get the log */
            adopted = grown;
        }
        adopted[nAdopted].start = n->start;
        adopted[nAdopted].size = r->size;
        nAdopted++;
    }
    vs_unlock(vs);
    UvmLocation home = { .tier = UVM_TIER_HOST, .devInst = 0 };
    for (uint32_t i = 0; i < nAdopted; i++) {
        TpuStatus ms = uvmMigrate(vs, (void *)(uintptr_t)adopted[i].start,
                                  adopted[i].size, home, 0);
        if (ms != TPU_OK)
            TPU_LOG(TPU_LOG_ERROR, "uvm",
                   "adopted range %#llx migrate-home failed (0x%x): "
                   "restored contents will be STALE",
                   (unsigned long long)adopted[i].start, ms);
    }
    free(adopted);

    uvmFaultEngineUnregisterSpace(vs);
    vs_lock(vs);
    UvmRangeTreeNode *n = vs->ranges.first;
    while (n) {
        UvmRangeTreeNode *next = uvmRangeTreeNext(n);
        range_destroy(vs, (UvmVaRange *)n);
        n = next;
    }
    UvmRangeGroup *g = vs->groups;
    while (g) {
        UvmRangeGroup *next = g->next;
        free(g);
        g = next;
    }
    vs_unlock(vs);
    uvmFaultSnapshotRebuild();
    pthread_mutex_destroy(&vs->lock);
    free(vs);
}

TpuStatus uvmRegisterDevice(UvmVaSpace *vs, uint32_t devInst)
{
    if (!vs)
        return TPU_ERR_INVALID_ARGUMENT;
    if (!tpurmDeviceGet(devInst))
        return TPU_ERR_INVALID_DEVICE;
    vs_lock(vs);
    vs->registeredDevMask |= 1ull << devInst;
    vs_unlock(vs);
    return TPU_OK;
}

TpuStatus uvmUnregisterDevice(UvmVaSpace *vs, uint32_t devInst)
{
    if (!vs)
        return TPU_ERR_INVALID_ARGUMENT;
    vs_lock(vs);
    if (!(vs->registeredDevMask & (1ull << devInst))) {
        vs_unlock(vs);
        return TPU_ERR_INVALID_DEVICE;
    }
    vs->registeredDevMask &= ~(1ull << devInst);
    vs_unlock(vs);
    /* Pull this device's residency home (reference: gpu unregister evicts
     * vidmem-resident pages).  Contended blocks are retried — returning
     * success while residency silently lingers would break the contract. */
    TpuStatus st = TPU_OK;
    UvmTierArena *arena = uvmTierArenaHbm(devInst);
    if (arena) {
        vs_lock(vs);
        for (UvmRangeTreeNode *n = vs->ranges.first; n;
             n = uvmRangeTreeNext(n)) {
            UvmVaRange *r = (UvmVaRange *)n;
            for (uint32_t i = 0; i < r->blockCount; i++) {
                UvmVaBlock *blk = r->blocks[i];
                if (!(blk->hbmRuns && blk->hbmDevInst == devInst))
                    continue;
                TpuStatus bs = TPU_ERR_STATE_IN_USE;
                for (int attempt = 0; attempt < 64 &&
                                      bs == TPU_ERR_STATE_IN_USE; attempt++) {
                    bs = uvmBlockEvictFrom(blk, arena);
                    if (bs == TPU_ERR_STATE_IN_USE)
                        sched_yield();
                }
                if (bs != TPU_OK)
                    st = bs;
            }
        }
        vs_unlock(vs);
    }
    return st;
}

static TpuStatus mem_alloc_gated(UvmVaSpace *vs, uint64_t size,
                                 void **outPtr);

TpuStatus uvmMemAlloc(UvmVaSpace *vs, uint64_t size, void **outPtr)
{
    if (!vs || !outPtr || size == 0)
        return TPU_ERR_INVALID_ARGUMENT;
    /* PM gate (shared): allocations block while suspended. */
    uvmPmEnterShared();
    TpuStatus pmSt = mem_alloc_gated(vs, size, outPtr);
    uvmPmExitShared();
    return pmSt;
}

/* Reserve an `align`-aligned VA window of `size` and place a SHARED
 * mapping of (fd, off) there (over-reserve + trim + MAP_FIXED).  Used
 * by managed alloc (2 MB alignment) and remote attach (uvm-page
 * alignment). */
static void *map_aligned_shared(int fd, uint64_t off, uint64_t size,
                                uint64_t align, int prot)
{
    uint64_t mapSize = size + align;
    char *raw = mmap(NULL, mapSize, PROT_NONE,
                     MAP_PRIVATE | MAP_ANONYMOUS | MAP_NORESERVE, -1, 0);
    if (raw == MAP_FAILED)
        return NULL;
    uintptr_t aligned = ((uintptr_t)raw + align - 1) &
                        ~((uintptr_t)align - 1);
    if (aligned > (uintptr_t)raw)
        munmap(raw, aligned - (uintptr_t)raw);
    uintptr_t tailStart = aligned + size;
    uint64_t tailLen = (uintptr_t)raw + mapSize - tailStart;
    if (tailLen)
        munmap((void *)tailStart, tailLen);
    if (mmap((void *)aligned, size, prot, MAP_SHARED | MAP_FIXED, fd,
             (off_t)off) == MAP_FAILED) {
        munmap((void *)aligned, size);
        return NULL;
    }
    return (void *)aligned;
}

static TpuStatus mem_alloc_gated(UvmVaSpace *vs, uint64_t size,
                                 void **outPtr)
{
    uint64_t ps = uvmPageSize();
    size = (size + ps - 1) & ~(ps - 1);

    /* Host backing is a memfd mapped twice (see UvmVaRange): user VA
     * below, engine alias after. */
    int memfd = memfd_create("tpurm-uvm", MFD_CLOEXEC);
    if (memfd < 0)
        return TPU_ERR_OPERATING_SYSTEM;
    if (ftruncate(memfd, (off_t)size) != 0) {
        close(memfd);
        return TPU_ERR_NO_MEMORY;
    }

    /* 2 MB-aligned reservation with the memfd fixed over it. */
    void *alignedPtr = map_aligned_shared(memfd, 0, size, UVM_BLOCK_SIZE,
                                          PROT_NONE);
    if (!alignedPtr) {
        close(memfd);
        return TPU_ERR_NO_MEMORY;
    }
    uintptr_t aligned = (uintptr_t)alignedPtr;
    void *alias = mmap(NULL, size, PROT_READ | PROT_WRITE, MAP_SHARED,
                       memfd, 0);
    if (alias == MAP_FAILED) {
        munmap((void *)aligned, size);
        close(memfd);
        return TPU_ERR_NO_MEMORY;
    }

    UvmVaRange *range = calloc(1, sizeof(*range));
    if (!range) {
        munmap(alias, size);
        munmap((void *)aligned, size);
        close(memfd);
        return TPU_ERR_NO_MEMORY;
    }
    range->memfd = memfd;
    range->alias = alias;
    range->node.start = aligned;
    range->node.end = aligned + size - 1;
    range->vaSpace = vs;
    range->type = UVM_RANGE_TYPE_MANAGED;
    range->size = size;
    range->allocStart = aligned;
    range->allocSize = size;

    uint32_t ppb = uvmPagesPerBlock();
    range->blockCount = (uint32_t)((size + UVM_BLOCK_SIZE - 1) /
                                   UVM_BLOCK_SIZE);
    range->blocks = calloc(range->blockCount, sizeof(UvmVaBlock *));
    if (!range->blocks) {
        free(range);
        munmap(alias, size);
        munmap((void *)aligned, size);
        close(memfd);
        return TPU_ERR_NO_MEMORY;
    }
    for (uint32_t i = 0; i < range->blockCount; i++) {
        UvmVaBlock *blk = calloc(1, sizeof(*blk));
        if (!blk) {
            for (uint32_t j = 0; j < i; j++)
                free(range->blocks[j]);
            free(range->blocks);
            free(range);
            munmap(alias, size);
            munmap((void *)aligned, size);
            close(memfd);
            return TPU_ERR_NO_MEMORY;
        }
        pthread_mutex_init(&blk->lock, NULL);
        blk->range = range;
        blk->start = aligned + (uint64_t)i * UVM_BLOCK_SIZE;
        uint64_t remaining = size - (uint64_t)i * UVM_BLOCK_SIZE;
        blk->npages = remaining >= UVM_BLOCK_SIZE
                          ? ppb
                          : (uint32_t)(remaining / ps);
        blk->pinnedTier = -1;
        range->blocks[i] = blk;
    }

    vs_lock(vs);
    TpuStatus st = uvmRangeTreeAdd(&vs->ranges, &range->node);
    vs_unlock(vs);
    if (st != TPU_OK) {
        for (uint32_t i = 0; i < range->blockCount; i++)
            free(range->blocks[i]);
        free(range->blocks);
        free(range);
        munmap(alias, size);
        munmap((void *)aligned, size);
        close(memfd);
        return st;
    }
    uvmFaultSnapshotRebuild();
    tpuCounterAdd("uvm_managed_bytes_allocated", size);
    *outPtr = (void *)aligned;
    return TPU_OK;
}

static TpuStatus mem_free_gated(UvmVaSpace *vs, void *ptr);

TpuStatus uvmMemFree(UvmVaSpace *vs, void *ptr)
{
    /* Adopted ranges: pull device-resident pages home FIRST so the
     * restored anonymous mapping carries the current bytes (uvm_hmm.c
     * contract).  Peek under the lock, migrate outside it. */
    if (vs && ptr) {
        vs_lock(vs);
        UvmRangeTreeNode *n = uvmRangeTreeFind(&vs->ranges,
                                               (uintptr_t)ptr);
        bool adopted = n && n->start == (uintptr_t)ptr &&
                       ((UvmVaRange *)n)->adopted;
        uint64_t asize = adopted ? ((UvmVaRange *)n)->allocSize : 0;
        vs_unlock(vs);
        if (adopted) {
            UvmLocation host = { .tier = UVM_TIER_HOST, .devInst = 0 };
            TpuStatus ms = uvmMigrate(vs, ptr, asize, host, 0);
            if (ms != TPU_OK)
                /* Restoring stale bytes would silently lose the
                 * caller's data: refuse the free instead. */
                return ms;
        }
    }
    /* PM gate (shared): frees block while suspended (saved-residency
     * records must not dangle). */
    uvmPmEnterShared();
    TpuStatus pmSt = mem_free_gated(vs, ptr);
    uvmPmExitShared();
    return pmSt;
}

static TpuStatus mem_free_gated(UvmVaSpace *vs, void *ptr)
{
    if (!vs || !ptr)
        return TPU_ERR_INVALID_ARGUMENT;
    vs_lock(vs);
    UvmRangeTreeNode *n = uvmRangeTreeFind(&vs->ranges, (uintptr_t)ptr);
    if (!n || n->start != (uintptr_t)ptr ||
        ((UvmVaRange *)n)->allocStart != (uintptr_t)ptr) {
        vs_unlock(vs);
        return TPU_ERR_OBJECT_NOT_FOUND;
    }
    /* Free the WHOLE allocation: every fragment a policy split carved
     * out of it (the reference's uvm_free tears down the full vma). */
    uint64_t allocStart = ((UvmVaRange *)n)->allocStart;
    uint64_t allocEnd = allocStart + ((UvmVaRange *)n)->allocSize - 1;
    uint64_t cursor = allocStart;
    while (cursor <= allocEnd) {
        UvmRangeTreeNode *f = uvmRangeTreeFind(&vs->ranges, cursor);
        if (!f || ((UvmVaRange *)f)->allocStart != allocStart)
            break;
        cursor = f->end + 1;
        range_destroy(vs, (UvmVaRange *)f);
        if (cursor == 0)
            break;                       /* end was UINT64_MAX */
    }
    vs_unlock(vs);
    uvmFaultSnapshotRebuild();
    return TPU_OK;
}

/* ------------------------------------------- multi-process attach (owner) */

/* Engine-host side: resolve the MANAGED range covering ownerAddr to its
 * host-backing memfd + bounds (the broker ships the fd via SCM_RIGHTS;
 * reference analog: the IPC handle resolving to the same physical
 * allocation). */
TpuStatus uvmRangeBackingForAddr(uint64_t ownerAddr, int *fdOut,
                                 uint64_t *fdOffset, uint64_t *rangeStart,
                                 uint64_t *rangeSize)
{
    UvmVaSpace *vs = uvmFaultSpaceForAddr(ownerAddr);
    if (!vs)
        return TPU_ERR_INVALID_ADDRESS;
    TpuStatus st = TPU_ERR_INVALID_ADDRESS;
    vs_lock(vs);
    tpuLockTrackAcquire(TPU_LOCK_UVM_VASPACE, "remote-backing");
    UvmRangeTreeNode *n = uvmRangeTreeFind(&vs->ranges, ownerAddr);
    if (n) {
        UvmVaRange *r = (UvmVaRange *)n;
        if (r->type == UVM_RANGE_TYPE_MANAGED && r->memfd >= 0) {
            /* dup UNDER the lock: the raw fd number could be closed
             * (range freed) and reused between unlock and the broker's
             * sendmsg — the dup pins the file.  Caller owns *fdOut. */
            int d = dup(r->memfd);
            if (d < 0) {
                st = TPU_ERR_OPERATING_SYSTEM;
            } else {
                *fdOut = d;
                /* A split-off tail range shares the ALLOCATION's memfd:
                 * its bytes start at node.start - allocStart within the
                 * file, not at 0. */
                *fdOffset = n->start - r->allocStart;
                *rangeStart = n->start;
                *rangeSize = r->size;
                st = TPU_OK;
            }
        }
    }
    tpuLockTrackRelease(TPU_LOCK_UVM_VASPACE, "remote-backing");
    vs_unlock(vs);
    return st;
}

/* Client side: window onto an owner range (see uvm.h contract). */
TpuStatus uvmRemoteAttach(UvmVaSpace *vs, uint64_t ownerAddr,
                          void **outLocalBase, uint64_t *outSize)
{
    if (!vs || !outLocalBase)
        return TPU_ERR_INVALID_ARGUMENT;
    int fd = -1;
    uint64_t fdOff = 0, start = 0, size = 0;
    int rc = tpurmBrokerUvmBacking(ownerAddr, &fd, &fdOff, &start, &size);
    if (rc != 0 || fd < 0)
        return rc > 0 ? (TpuStatus)rc : TPU_ERR_OPERATING_SYSTEM;
    /* The window must be UVM-page aligned (the fault path aligns
     * addresses down to uvm pages; a 4 KB-aligned mmap would put those
     * below the range start). */
    void *base = map_aligned_shared(fd, fdOff, size, uvmPageSize(),
                                    PROT_NONE);
    close(fd);
    if (!base)
        return TPU_ERR_NO_MEMORY;

    UvmVaRange *range = calloc(1, sizeof(*range));
    if (!range) {
        munmap(base, size);
        return TPU_ERR_NO_MEMORY;
    }
    range->node.start = (uint64_t)(uintptr_t)base;
    range->node.end = range->node.start + size - 1;
    range->vaSpace = vs;
    range->type = UVM_RANGE_TYPE_REMOTE;
    range->size = size;
    range->allocStart = range->node.start;
    range->allocSize = size;
    range->memfd = -1;
    range->remoteBase = start;

    vs_lock(vs);
    tpuLockTrackAcquire(TPU_LOCK_UVM_VASPACE, "remote-attach");
    TpuStatus st = uvmRangeTreeAdd(&vs->ranges, &range->node);
    tpuLockTrackRelease(TPU_LOCK_UVM_VASPACE, "remote-attach");
    vs_unlock(vs);
    if (st != TPU_OK) {
        munmap(base, size);
        free(range);
        return st;
    }
    /* The space registered with the fault engine at creation; only the
     * snapshot needs the new range. */
    uvmFaultSnapshotRebuild();
    *outLocalBase = base;
    if (outSize)
        *outSize = size;
    return TPU_OK;
}

TpuStatus uvmRemoteDetach(UvmVaSpace *vs, void *localBase)
{
    if (!vs || !localBase)
        return TPU_ERR_INVALID_ARGUMENT;
    vs_lock(vs);
    tpuLockTrackAcquire(TPU_LOCK_UVM_VASPACE, "remote-detach");
    UvmRangeTreeNode *n = uvmRangeTreeFind(&vs->ranges,
                                           (uint64_t)(uintptr_t)localBase);
    UvmVaRange *range = (UvmVaRange *)n;
    TpuStatus st = TPU_OK;
    if (!n || range->type != UVM_RANGE_TYPE_REMOTE ||
        n->start != (uint64_t)(uintptr_t)localBase) {
        st = TPU_ERR_INVALID_ADDRESS;
    } else {
        uvmRangeTreeRemove(&vs->ranges, n);
    }
    tpuLockTrackRelease(TPU_LOCK_UVM_VASPACE, "remote-detach");
    vs_unlock(vs);
    if (st != TPU_OK)
        return st;
    uvmFaultSnapshotRebuild();
    /* Drain in-flight forwarded faults before tearing the window down:
     * a fault worker that found this range (and pinned it under
     * vs->lock, see service_one's remoteRefs) may still be mid-forward
     * — munmap now and its mprotect could land on a recycled mapping.
     * The range left the tree above, so no NEW pins can appear; the
     * snapshot rebuild's grace period already drained handler lookups. */
    while (atomic_load_explicit(&range->remoteRefs,
                                memory_order_acquire) != 0)
        sched_yield();
    munmap(localBase, range->size);
    free(range);
    return TPU_OK;
}

UvmVaRange *uvmRangeFind(UvmVaSpace *vs, uint64_t addr, UvmVaBlock **blockOut)
{
    UvmRangeTreeNode *n = uvmRangeTreeFind(&vs->ranges, addr);
    if (!n)
        return NULL;
    UvmVaRange *range = (UvmVaRange *)n;
    if (blockOut) {
        uint32_t bi = (uint32_t)((addr - n->start) / UVM_BLOCK_SIZE);
        *blockOut = bi < range->blockCount ? range->blocks[bi] : NULL;
    }
    return range;
}

/* ------------------------------------------------------- range splitting */

/* Split `range` at splitVa (vs->lock held): the head keeps
 * [start, splitVa), a new tail range takes [splitVa, end].  splitVa
 * must be 2 MB block-aligned relative to the range start so every block
 * lands wholly in one side.  The tail inherits the head's policy
 * (reference: uvm_va_range_split preserves policy on both halves) and
 * shares the memfd backing (dup'd fd; per-range alias sub-pointers).  */
static TpuStatus range_split_locked(UvmVaSpace *vs, UvmVaRange *range,
                                    uint64_t splitVa)
{
    if (range->type != UVM_RANGE_TYPE_MANAGED)
        return TPU_ERR_INVALID_ADDRESS;
    uint64_t start = range->node.start;
    if (splitVa <= start || splitVa > range->node.end)
        return TPU_ERR_INVALID_ADDRESS;
    if ((splitVa - start) % UVM_BLOCK_SIZE)
        return TPU_ERR_INVALID_ADDRESS;   /* sub-block split unsupported */

    uint32_t headBlocks = (uint32_t)((splitVa - start) / UVM_BLOCK_SIZE);
    uint32_t tailBlocks = range->blockCount - headBlocks;

    UvmVaRange *tail = calloc(1, sizeof(*tail));
    if (!tail)
        return TPU_ERR_NO_MEMORY;
    tail->blocks = calloc(tailBlocks, sizeof(UvmVaBlock *));
    if (!tail->blocks) {
        free(tail);
        return TPU_ERR_NO_MEMORY;
    }
    int newFd = range->memfd >= 0 ? dup(range->memfd) : -1;
    if (range->memfd >= 0 && newFd < 0) {
        free(tail->blocks);
        free(tail);
        return TPU_ERR_OPERATING_SYSTEM;
    }

    tail->node.start = splitVa;
    tail->node.end = range->node.end;
    tail->vaSpace = vs;
    tail->type = UVM_RANGE_TYPE_MANAGED;
    tail->size = range->size - (splitVa - start);
    tail->allocStart = range->allocStart;
    tail->allocSize = range->allocSize;
    tail->adopted = range->adopted;    /* frees must restore, not unmap */
    tail->memfd = newFd;
    tail->alias = (char *)range->alias + (splitVa - start);
    /* Policy inheritance. */
    tail->hasPreferred = range->hasPreferred;
    tail->preferred = range->preferred;
    tail->accessedByMask = range->accessedByMask;
    tail->readDuplication = range->readDuplication;
    tail->compressFormat = range->compressFormat;
    tail->rangeGroupId = range->rangeGroupId;
    /* Move the tail's blocks over (block start addresses are absolute,
     * so only the owning-range pointer changes). */
    tail->blockCount = tailBlocks;
    for (uint32_t i = 0; i < tailBlocks; i++) {
        tail->blocks[i] = range->blocks[headBlocks + i];
        if (tail->blocks[i])
            tail->blocks[i]->range = tail;
        range->blocks[headBlocks + i] = NULL;
    }
    /* Shrink the head in place (tree order is keyed by start; end only
     * participates in containment queries). */
    range->blockCount = headBlocks;
    range->size = splitVa - start;
    range->node.end = splitVa - 1;

    TpuStatus st = uvmRangeTreeAdd(&vs->ranges, &tail->node);
    if (st != TPU_OK) {
        /* Roll back (cannot actually happen: the span was ours). */
        for (uint32_t i = 0; i < tailBlocks; i++) {
            range->blocks[headBlocks + i] = tail->blocks[i];
            if (tail->blocks[i])
                tail->blocks[i]->range = range;
        }
        range->blockCount = headBlocks + tailBlocks;
        range->size += tail->size;
        range->node.end = tail->node.end;
        if (newFd >= 0)
            close(newFd);
        free(tail->blocks);
        free(tail);
        return st;
    }
    tpuCounterAdd("uvm_range_splits", 1);
    return TPU_OK;
}

/* Ensure range edges exist at `va` (no-op when va already starts a
 * range or lies outside any range).  *didSplit reports whether the
 * tree actually changed. */
static TpuStatus split_at_locked(UvmVaSpace *vs, uint64_t va,
                                 bool *didSplit)
{
    UvmRangeTreeNode *n = uvmRangeTreeFind(&vs->ranges, va);
    if (!n || n->start == va)
        return TPU_OK;
    TpuStatus st = range_split_locked(vs, (UvmVaRange *)n, va);
    if (st == TPU_OK)
        *didSplit = true;
    return st;
}

/* ----------------------------------------------------------- policy ops */

typedef void (*RangePolicyFn)(UvmVaRange *range, void *arg);

static TpuStatus for_ranges_in(UvmVaSpace *vs, void *base, uint64_t len,
                               RangePolicyFn fn, void *arg)
{
    if (!vs || !base || len == 0)
        return TPU_ERR_INVALID_ARGUMENT;
    uint64_t start = (uintptr_t)base, end = start + len - 1;
    vs_lock(vs);
    UvmRangeTreeNode *n = uvmRangeTreeIterFirst(&vs->ranges, start, end);
    if (!n) {
        vs_unlock(vs);
        return TPU_ERR_OBJECT_NOT_FOUND;
    }
    /* Validation pre-pass: policy is a managed-range concept, and the
     * whole span must qualify BEFORE any range is mutated (the
     * reference validates types up front; failing midway would leave
     * earlier ranges silently updated under an error return). */
    for (UvmRangeTreeNode *c = n; c; c = uvmRangeTreeIterNext(c, end)) {
        if (((UvmVaRange *)c)->type != UVM_RANGE_TYPE_MANAGED) {
            vs_unlock(vs);
            return TPU_ERR_INVALID_ADDRESS;
        }
    }
    /* Split at the span edges so policy applies EXACTLY to [start, end]
     * (reference uvm_va_range.c split machinery): a sub-span of one
     * allocation gets its own range carrying its own policy. */
    bool didSplit = false;
    TpuStatus st = split_at_locked(vs, start, &didSplit);
    if (st == TPU_OK && end != UINT64_MAX)
        st = split_at_locked(vs, end + 1, &didSplit);
    if (st != TPU_OK) {
        vs_unlock(vs);
        return st;
    }
    if (didSplit)
        n = uvmRangeTreeIterFirst(&vs->ranges, start, end);
    while (n) {
        fn((UvmVaRange *)n, arg);
        n = uvmRangeTreeIterNext(n, end);
    }
    vs_unlock(vs);
    if (didSplit)
        uvmFaultSnapshotRebuild();
    return TPU_OK;
}

static void set_preferred_fn(UvmVaRange *r, void *arg)
{
    UvmLocation *loc = arg;
    if (loc) {
        r->hasPreferred = true;
        r->preferred = *loc;
    } else {
        r->hasPreferred = false;
    }
}

TpuStatus uvmSetPreferredLocation(UvmVaSpace *vs, void *base, uint64_t len,
                                  UvmLocation loc)
{
    if (loc.tier == UVM_TIER_HBM && !tpurmDeviceGet(loc.devInst))
        return TPU_ERR_INVALID_DEVICE;
    return for_ranges_in(vs, base, len, set_preferred_fn, &loc);
}

TpuStatus uvmUnsetPreferredLocation(UvmVaSpace *vs, void *base, uint64_t len)
{
    return for_ranges_in(vs, base, len, set_preferred_fn, NULL);
}

struct accessed_by_arg {
    uint32_t devInst;
    bool set;
};

static void accessed_by_fn(UvmVaRange *r, void *arg)
{
    struct accessed_by_arg *a = arg;
    if (a->set)
        r->accessedByMask |= 1ull << a->devInst;
    else
        r->accessedByMask &= ~(1ull << a->devInst);

    /* Mappings follow the policy immediately (reference: SetAccessedBy
     * establishes mappings to already-resident pages eagerly; Unset
     * revokes them).  devMapped is the union over accessed-by devices,
     * so it clears only when the policy empties. */
    for (uint32_t b = 0; b < r->blockCount; b++) {
        UvmVaBlock *blk = r->blocks[b];
        if (!blk)
            continue;
        pthread_mutex_lock(&blk->lock);
        tpuLockTrackAcquire(TPU_LOCK_UVM_BLOCK, "block-policy");
        if (a->set) {
            for (uint32_t p = 0; p < blk->npages; p++)
                for (int t = 0; t < UVM_TIER_COUNT; t++)
                    if (uvmPageMaskTest(&blk->resident[t], p)) {
                        uvmPageMaskSet(&blk->devMapped, p);
                        break;
                    }
        } else if (r->accessedByMask == 0) {
            uvmPageMaskZero(&blk->devMapped);
        }
        tpuLockTrackRelease(TPU_LOCK_UVM_BLOCK, "block-policy");
        pthread_mutex_unlock(&blk->lock);
    }
}

TpuStatus uvmSetAccessedBy(UvmVaSpace *vs, void *base, uint64_t len,
                           uint32_t devInst)
{
    if (!tpurmDeviceGet(devInst))
        return TPU_ERR_INVALID_DEVICE;
    struct accessed_by_arg a = { devInst, true };
    return for_ranges_in(vs, base, len, accessed_by_fn, &a);
}

TpuStatus uvmUnsetAccessedBy(UvmVaSpace *vs, void *base, uint64_t len,
                             uint32_t devInst)
{
    if (devInst >= 64)          /* accessedByMask is one bit per device */
        return TPU_ERR_INVALID_DEVICE;
    struct accessed_by_arg a = { devInst, false };
    return for_ranges_in(vs, base, len, accessed_by_fn, &a);
}

static void read_dup_fn(UvmVaRange *r, void *arg)
{
    r->readDuplication = *(int *)arg != 0;
}

TpuStatus uvmSetReadDuplication(UvmVaSpace *vs, void *base, uint64_t len,
                                int enable)
{
    return for_ranges_in(vs, base, len, read_dup_fn, &enable);
}

static void compressible_fn(UvmVaRange *r, void *arg)
{
    r->compressFormat = *(uint32_t *)arg;
}

/* UVM_ADVISE_COMPRESSIBLE: opt [base, base+len) into the tpuce
 * quantize-on-upload / dequantize-on-download stage (ce.h).  format is
 * a TPU_CE_COMP_* value; 0 restores lossless.  The advise is an
 * explicit precision contract — only data that tolerates fp8/int8
 * round-trips (KV-cache pages) may set it; exact data must not. */
TpuStatus uvmSetCompressible(UvmVaSpace *vs, void *base, uint64_t len,
                             uint32_t format)
{
    if (format != TPU_CE_COMP_NONE && format != TPU_CE_COMP_FP8 &&
        format != TPU_CE_COMP_INT8)
        return TPU_ERR_INVALID_ARGUMENT;
    TpuStatus st = for_ranges_in(vs, base, len, compressible_fn, &format);
    if (st == TPU_OK)
        tpuCounterAdd("uvm_compressible_advises", 1);
    return st;
}

/* ---------------------------------------------------------- range groups */

TpuStatus uvmRangeGroupCreate(UvmVaSpace *vs, uint64_t *outId)
{
    if (!vs || !outId)
        return TPU_ERR_INVALID_ARGUMENT;
    UvmRangeGroup *g = calloc(1, sizeof(*g));
    if (!g)
        return TPU_ERR_NO_MEMORY;
    vs_lock(vs);
    g->id = vs->nextRangeGroupId++;
    g->migratable = true;
    g->next = vs->groups;
    vs->groups = g;
    vs_unlock(vs);
    *outId = g->id;
    return TPU_OK;
}

static UvmRangeGroup *group_find(UvmVaSpace *vs, uint64_t id)
{
    for (UvmRangeGroup *g = vs->groups; g; g = g->next)
        if (g->id == id)
            return g;
    return NULL;
}

TpuStatus uvmRangeGroupDestroy(UvmVaSpace *vs, uint64_t id)
{
    if (!vs)
        return TPU_ERR_INVALID_ARGUMENT;
    vs_lock(vs);
    UvmRangeGroup **prev = &vs->groups;
    for (UvmRangeGroup *g = vs->groups; g; g = g->next) {
        if (g->id == id) {
            *prev = g->next;
            /* Detach member ranges. */
            for (UvmRangeTreeNode *n = vs->ranges.first; n;
                 n = uvmRangeTreeNext(n)) {
                UvmVaRange *r = (UvmVaRange *)n;
                if (r->rangeGroupId == id)
                    r->rangeGroupId = 0;
            }
            vs_unlock(vs);
            free(g);
            return TPU_OK;
        }
        prev = &g->next;
    }
    vs_unlock(vs);
    return TPU_ERR_OBJECT_NOT_FOUND;
}

struct set_group_arg {
    uint64_t id;
};

static void set_group_fn(UvmVaRange *r, void *arg)
{
    r->rangeGroupId = ((struct set_group_arg *)arg)->id;
}

TpuStatus uvmRangeGroupSet(UvmVaSpace *vs, uint64_t id, void *base,
                           uint64_t len)
{
    vs_lock(vs);
    bool ok = group_find(vs, id) != NULL;
    vs_unlock(vs);
    if (!ok && id != 0)
        return TPU_ERR_OBJECT_NOT_FOUND;
    struct set_group_arg a = { id };
    return for_ranges_in(vs, base, len, set_group_fn, &a);
}

TpuStatus uvmRangeGroupSetMigratable(UvmVaSpace *vs, uint64_t id,
                                     int migratable)
{
    if (!vs)
        return TPU_ERR_INVALID_ARGUMENT;
    vs_lock(vs);
    UvmRangeGroup *g = group_find(vs, id);
    if (g)
        g->migratable = migratable != 0;
    vs_unlock(vs);
    return g ? TPU_OK : TPU_ERR_OBJECT_NOT_FOUND;
}

bool uvmRangeGroupMigratable(UvmVaSpace *vs, uint64_t groupId)
{
    if (groupId == 0)
        return true;
    UvmRangeGroup *g = group_find(vs, groupId);
    return g ? g->migratable : true;
}

/* ------------------------------------------------------ external ranges */

TpuStatus uvmExternalRangeCreate(UvmVaSpace *vs, void *base, uint64_t length)
{
    if (!vs || !base || length == 0)
        return TPU_ERR_INVALID_ARGUMENT;
    /* External mappings work at OS-page granularity (they are real
     * mmap windows), unlike managed ranges' 64 KB UVM pages. */
    uint64_t ps = (uint64_t)sysconf(_SC_PAGESIZE);
    if (((uintptr_t)base & (ps - 1)) || (length & (ps - 1)))
        return TPU_ERR_INVALID_ADDRESS;

    UvmVaRange *range = calloc(1, sizeof(*range));
    if (!range)
        return TPU_ERR_NO_MEMORY;
    range->node.start = (uintptr_t)base;
    range->node.end = (uintptr_t)base + length - 1;
    range->vaSpace = vs;
    range->type = UVM_RANGE_TYPE_EXTERNAL;
    range->size = length;
    range->allocStart = (uintptr_t)base;
    range->allocSize = length;
    range->memfd = -1;

    vs_lock(vs);
    TpuStatus st = uvmRangeTreeAdd(&vs->ranges, &range->node);
    vs_unlock(vs);
    if (st != TPU_OK) {
        free(range);
        return st;
    }
    /* No snapshot rebuild: external ranges are intentionally NOT in the
     * fault snapshot (faults on unmapped spans are real segfaults, not
     * managed work), so the managed-only snapshot is unchanged. */
    return TPU_OK;
}

static UvmVaRange *ext_range_find(UvmVaSpace *vs, void *base, uint64_t len)
{
    UvmVaBlock *blk;
    UvmVaRange *range = uvmRangeFind(vs, (uintptr_t)base, &blk);
    if (!range || range->type != UVM_RANGE_TYPE_EXTERNAL)
        return NULL;
    if ((uintptr_t)base + len - 1 > range->node.end)
        return NULL;
    return range;
}

TpuStatus uvmMapExternal(UvmVaSpace *vs, void *base, uint64_t length,
                         struct TpuDmabuf *buf, uint64_t bufOffset)
{
    if (!vs || !base || length == 0 || !buf)
        return TPU_ERR_INVALID_ARGUMENT;
    uint64_t ps = (uint64_t)sysconf(_SC_PAGESIZE);
    if (((uintptr_t)base & (ps - 1)) || (length & (ps - 1)) ||
        (bufOffset & (ps - 1)))
        return TPU_ERR_INVALID_ADDRESS;

    uint32_t devInst;
    uint64_t dOff, dSize;
    TpuStatus st = tpuDmabufInfo(buf, &devInst, &dOff, &dSize);
    if (st != TPU_OK)
        return st;
    if (bufOffset > dSize || length > dSize - bufOffset)
        return TPU_ERR_INVALID_LIMIT;
    TpurmDevice *dev = tpurmDeviceGet(devInst);
    if (!dev)
        return TPU_ERR_INVALID_DEVICE;
    if (dev->hbmFd < 0)
        return TPU_ERR_NOT_SUPPORTED;   /* anon-arena fallback in use */

    vs_lock(vs);
    UvmVaRange *range = ext_range_find(vs, base, length);
    if (!range) {
        vs_unlock(vs);
        return TPU_ERR_OBJECT_NOT_FOUND;
    }
    /* Reject overlap with a live window (reference rejects remap). */
    for (UvmExtMapping *m = range->extMappings; m; m = m->next) {
        if ((uintptr_t)base < m->start + m->len &&
            m->start < (uintptr_t)base + length) {
            vs_unlock(vs);
            return TPU_ERR_INVALID_ADDRESS;
        }
    }
    UvmExtMapping *m = calloc(1, sizeof(*m));
    if (!m) {
        vs_unlock(vs);
        return TPU_ERR_NO_MEMORY;
    }
    uint64_t arenaOff = dOff + bufOffset;
    if (arenaOff & (ps - 1)) {
        /* The dmabuf window itself must land on an OS page boundary. */
        free(m);
        vs_unlock(vs);
        return TPU_ERR_INVALID_ADDRESS;
    }
    if (mmap(base, length, PROT_READ | PROT_WRITE,
             MAP_SHARED | MAP_FIXED, dev->hbmFd,
             (off_t)arenaOff) == MAP_FAILED) {
        free(m);
        vs_unlock(vs);
        return TPU_ERR_OPERATING_SYSTEM;
    }
    m->start = (uintptr_t)base;
    m->len = length;
    m->buf = tpuDmabufGet(buf);
    m->devInst = devInst;
    m->arenaOff = arenaOff;
    m->next = range->extMappings;
    range->extMappings = m;
    vs_unlock(vs);
    tpuCounterAdd("uvm_external_maps", 1);
    uvmToolsEmit(vs, UVM_EVENT_EXTERNAL_MAP, UVM_TIER_HBM, UVM_TIER_COUNT,
                 devInst, (uintptr_t)base, length);
    return TPU_OK;
}

TpuStatus uvmUnmapExternal(UvmVaSpace *vs, void *base, uint64_t length)
{
    if (!vs || !base || length == 0)
        return TPU_ERR_INVALID_ARGUMENT;
    vs_lock(vs);
    UvmVaRange *range = ext_range_find(vs, base, length);
    if (!range) {
        vs_unlock(vs);
        return TPU_ERR_OBJECT_NOT_FOUND;
    }
    UvmExtMapping **pp = &range->extMappings;
    while (*pp) {
        UvmExtMapping *m = *pp;
        if (m->start == (uintptr_t)base && m->len == length) {
            uint32_t mdev = m->devInst;
            *pp = m->next;
            ext_unmap_span(range, m);
            free(m);
            vs_unlock(vs);
            uvmToolsEmit(vs, UVM_EVENT_EXTERNAL_UNMAP, UVM_TIER_HBM,
                         UVM_TIER_COUNT, mdev, (uintptr_t)base, length);
            return TPU_OK;
        }
        pp = &m->next;
    }
    vs_unlock(vs);
    return TPU_ERR_OBJECT_NOT_FOUND;
}

TpuStatus uvmExternalFlush(UvmVaSpace *vs, void *base, uint64_t length)
{
    if (!vs || !base || length == 0)
        return TPU_ERR_INVALID_ARGUMENT;
    vs_lock(vs);
    UvmVaRange *range = ext_range_find(vs, base, length);
    if (!range) {
        vs_unlock(vs);
        return TPU_ERR_OBJECT_NOT_FOUND;
    }
    /* Publish every mapped window intersecting [base, base+length) to
     * the real-arena mirror (CPU writes through the alias bypass the
     * channel executors that normally notify). */
    for (UvmExtMapping *m = range->extMappings; m; m = m->next) {
        uint64_t lo = m->start > (uintptr_t)base ? m->start
                                                 : (uintptr_t)base;
        uint64_t hi = m->start + m->len < (uintptr_t)base + length
                          ? m->start + m->len
                          : (uintptr_t)base + length;
        if (lo >= hi)
            continue;
        TpurmDevice *dev = tpurmDeviceGet(m->devInst);
        if (dev && dev->hbmBase)
            tpuHbmMirrorNotify((char *)dev->hbmBase + m->arenaOff +
                                   (lo - m->start),
                               hi - lo);
    }
    vs_unlock(vs);
    return TPU_OK;
}

/* --------------------------------------------------------- introspection */

TpuStatus uvmResidencyInfo(UvmVaSpace *vs, void *addr, UvmResidencyInfo *out)
{
    if (!vs || !addr || !out)
        return TPU_ERR_INVALID_ARGUMENT;
    vs_lock(vs);
    UvmVaBlock *blk = NULL;
    UvmVaRange *range = uvmRangeFind(vs, (uintptr_t)addr, &blk);
    if (!range || !blk) {
        vs_unlock(vs);
        return TPU_ERR_OBJECT_NOT_FOUND;
    }
    pthread_mutex_lock(&blk->lock);
    tpuLockTrackAcquire(TPU_LOCK_UVM_BLOCK, "block");
    uint32_t page = (uint32_t)(((uintptr_t)addr - blk->start) / uvmPageSize());
    memset(out, 0, sizeof(*out));
    out->residentHost = uvmPageMaskTest(&blk->resident[UVM_TIER_HOST], page);
    out->residentHbm = uvmPageMaskTest(&blk->resident[UVM_TIER_HBM], page);
    out->residentCxl = uvmPageMaskTest(&blk->resident[UVM_TIER_CXL], page);
    out->residentRemote = uvmPageMaskTest(&blk->resident[UVM_TIER_REMOTE],
                                          page);
    if (out->residentRemote)
        for (UvmRemoteRun *run = blk->remoteRuns; run; run = run->next)
            if (page >= run->firstPage &&
                page < run->firstPage + run->numPages)
                out->remoteLenderInst = run->lenderInst;
    out->hbmDeviceInst = blk->hbmDevInst;
    out->cpuMapped = uvmPageMaskTest(&blk->cpuMapped, page);
    out->devMapped = uvmPageMaskTest(&blk->devMapped, page);
    out->cancelled = uvmPageMaskTest(&blk->cancelled, page);
    /* Report a LAPSED thrash pin as unpinned: the hint readers all
     * check expiry, so the raw field alone would overstate the pin. */
    out->pinnedTier = blk->pinExpiryNs > uvmMonotonicNs()
                          ? blk->pinnedTier : -1;
    if (out->residentHbm)
        uvmBlockHbmArenaOffset(blk, page, &out->hbmOffset);
    tpuLockTrackRelease(TPU_LOCK_UVM_BLOCK, "block");
    pthread_mutex_unlock(&blk->lock);
    vs_unlock(vs);
    return TPU_OK;
}
