/*
 * REMOTE tier (tpusplit): a healthy neighbor chip's HBM as another
 * chip's far memory.
 *
 * The tier ladder below local HBM gains a rung that is not a local
 * medium at all: pages evicted from a device's HBM are REPLICATED into
 * a chunk leased from a lender chip's arena (lender picked by the
 * tpuvac health/headroom scorer), and a later promote fetches them back
 * over ICI instead of re-reading host memory.  Three invariants keep
 * this safe without a coherence protocol:
 *
 *   WRITE-THROUGH — REMOTE is strictly a replica of HOST.  The demote
 *     hook runs only after eviction's host copy-back has committed, so
 *     resident[REMOTE] implies resident[HOST] and dropping a lease
 *     never loses data: the span just falls back to the durable copy.
 *
 *   GENERATION FENCE — every lease records the process-wide device
 *     generation (tpurmDeviceGeneration) and the lender's revoke epoch.
 *     ANY device reset, an unhealthy lender (EVACUATING or worse), or
 *     an explicit uvmTierRemoteRevokeLender invalidates the lease
 *     lazily on next touch; the promote path drops it and HOST serves.
 *     An invalid lease is never read.
 *
 *   SPINE-ONLY DATA PATH — bytes move exclusively as PEER_COPY SQEs
 *     through tpurmMemringSubmitInternal (SUBSYS_TIER), dep-chained
 *     into windows of REMOTE_WINDOW in-flight copies, so they inherit
 *     the spine's per-hop wire CRCs (tpushield), claim-generation
 *     fencing and inject sites.  check-spine forbids any other route.
 *
 * Concurrency: both entry points are called with blk->lock HELD but
 * must not hold it across the spine wait (TIER/FAULT exec runs on
 * spine workers that take blk->lock).  They pin the block
 * (p2pPinCount) and raise blk->remoteBusy, drop the lock, run the
 * windows, re-lock and commit.  While remoteBusy > 0, make-resident
 * and eviction refuse with STATE_IN_USE and remote-run gc defers, so
 * neither the local runs nor the lender chunks can move or free under
 * an in-flight transfer.
 *
 * Reference analog: NVLink peer-mapped vidmem used as a migration
 * target (uvm_pmm_gpu.c indirect peers), with the fork's CXL far-tier
 * plumbing supplying the eviction-ladder shape.
 */
#define _GNU_SOURCE
#include "uvm_internal.h"

#include <stdlib.h>
#include <string.h>

#include "tpurm/health.h"
#include "tpurm/journal.h"
#include "tpurm/memring.h"
#include "tpurm/reset.h"

#define REMOTE_MAX_DEVS 16
#define REMOTE_WINDOW 4           /* in-flight PEER_COPYs per window  */
#define REMOTE_BATCH_MAX 32       /* SQEs per internal submit         */

/* Per-device ledgers (atomics: touched from block paths of many
 * devices concurrently).  borrowedPages is the borrower-side gauge
 * (tpurm_tier_remote_pages); lentBytes is subtracted from the lender's
 * uvmHbmArenaUsage so vac target picking never double-counts borrowed
 * pages; leases counts live leases against a lender so RevokeLender
 * can report how many it fenced; revokeEpoch invalidates them. */
static struct {
    _Atomic uint64_t borrowedPages;
    _Atomic uint64_t lentBytes;
    _Atomic uint64_t leases;
    _Atomic uint64_t revokeEpoch;
} g_remote[REMOTE_MAX_DEVS];

bool uvmTierRemoteEnabled(void)
{
    static TpuRegCache c_en;
    if (!tpuRegCacheGet(&c_en, "remote_tier", 0))
        return false;
    return tpurmDeviceCount() >= 2;
}

static uint64_t remote_headroom_pct(void)
{
    static TpuRegCache c_pct;
    return tpuRegCacheGet(&c_pct, "remote_headroom_pct", 20);
}

uint64_t uvmTierRemoteLentBytes(uint32_t lenderInst)
{
    if (lenderInst >= REMOTE_MAX_DEVS)
        return 0;
    return atomic_load_explicit(&g_remote[lenderInst].lentBytes,
                                memory_order_relaxed);
}

TpuStatus uvmTierRemoteStats(uint32_t devInst, uint64_t *borrowedPages,
                             uint64_t *lentBytes)
{
    if (devInst >= tpurmDeviceCount() || devInst >= REMOTE_MAX_DEVS)
        return TPU_ERR_INVALID_ARGUMENT;
    if (borrowedPages)
        *borrowedPages = atomic_load(&g_remote[devInst].borrowedPages);
    if (lentBytes)
        *lentBytes = atomic_load(&g_remote[devInst].lentBytes);
    return TPU_OK;
}

uint64_t uvmTierRemoteRevokeLender(uint32_t lenderInst)
{
    if (lenderInst >= REMOTE_MAX_DEVS)
        return 0;
    atomic_fetch_add(&g_remote[lenderInst].revokeEpoch, 1);
    uint64_t n = atomic_load(&g_remote[lenderInst].leases);
    if (n) {
        tpuCounterAdd("tier_remote_revokes", n);
        tpurmJournalEmit(TPU_JREC_TIER_REMOTE, lenderInst, TPU_OK,
                         /*a0=revoked leases*/ n, /*a1=op*/ 2);
    }
    return n;
}

void uvmTierRemoteRenderProm(TpuCur *c)
{
    uint32_t n = tpurmDeviceCount();
    if (n > REMOTE_MAX_DEVS)
        n = REMOTE_MAX_DEVS;
    tpuCurf(c, "# TYPE tpurm_tier_remote_pages gauge\n");
    for (uint32_t i = 0; i < n; i++)
        tpuCurf(c, "tpurm_tier_remote_pages{dev=\"%u\"} %llu\n", i,
                (unsigned long long)atomic_load(&g_remote[i].borrowedPages));
}

/* ------------------------------------------------------------- leases */

static bool remote_lease_valid(const UvmRemoteRun *run)
{
    if (run->leaseGen != tpurmDeviceGeneration())
        return false;
    if (run->lenderInst < REMOTE_MAX_DEVS &&
        run->revokeEpoch !=
            atomic_load(&g_remote[run->lenderInst].revokeEpoch))
        return false;
    if (tpurmDeviceHealthState(run->lenderInst) >= TPU_HEALTH_EVACUATING)
        return false;
    return true;
}

/* Unlink + free one lease (blk->lock held, !remoteBusy).  Clears the
 * REMOTE residency bits and returns the lender chunk; chunk free after
 * a lender reset is harmless (the arena was rebuilt).  `prevp` is the
 * link that points at `run`. */
static void remote_run_free(UvmVaBlock *blk, UvmRemoteRun **prevp,
                            UvmRemoteRun *run, bool aborted)
{
    uvmPageMaskClearRange(&blk->resident[UVM_TIER_REMOTE], run->firstPage,
                          run->numPages);
    *prevp = run->next;
    if (run->lenderInst < REMOTE_MAX_DEVS) {
        atomic_fetch_sub(&g_remote[run->lenderInst].lentBytes,
                         run->chunkBytes);
        atomic_fetch_sub(&g_remote[run->lenderInst].leases, 1);
    }
    if (blk->hbmDevInst < REMOTE_MAX_DEVS)
        atomic_fetch_sub(&g_remote[blk->hbmDevInst].borrowedPages,
                         run->numPages);
    uvmHbmChunkFree(run->lenderInst, run->chunkHandle);
    if (aborted) {
        tpuCounterAdd("tier_remote_fence_aborts", 1);
        tpurmJournalEmit(TPU_JREC_TIER_REMOTE, run->lenderInst,
                         TPU_ERR_DEVICE_RESET, run->numPages, /*a1=op*/ 3);
    }
    free(run);
}

void uvmTierRemoteGc(UvmVaBlock *blk)
{
    if (blk->remoteBusy)
        return;                   /* window in flight: defer, chunks live */
    UvmRemoteRun **pp = &blk->remoteRuns;
    while (*pp) {
        UvmRemoteRun *run = *pp;
        bool live = false;
        for (uint32_t p = run->firstPage;
             p < run->firstPage + run->numPages; p++)
            if (uvmPageMaskTest(&blk->resident[UVM_TIER_REMOTE], p)) {
                live = true;
                break;
            }
        if (live)
            pp = &run->next;
        else
            remote_run_free(blk, pp, run, false);
    }
}

void uvmTierRemoteFreeAll(UvmVaBlock *blk)
{
    UvmRemoteRun **pp = &blk->remoteRuns;
    while (*pp)
        remote_run_free(blk, pp, *pp, false);
}

/* ---------------------------------------------------- PEER_COPY spans */

typedef struct {
    uint64_t localOff;            /* borrower HBM arena offset  */
    uint64_t peerOff;             /* lender HBM arena offset    */
    uint64_t len;
    uint64_t granted;             /* lender chunk size (>= len) */
    uint32_t firstPage, numPages;
    void *chunkHandle;            /* demote plan only           */
} RemoteSpan;

/* Submit one dep-chained window batch per REMOTE_BATCH_MAX spans and
 * wait (SubmitInternal is synchronous; nested submits from spine
 * workers run inline).  SQE i deps on i-REMOTE_WINDOW of the same
 * batch, capping copies in flight per batch at REMOTE_WINDOW while a
 * single failed hop dep-cancels its whole tail — the abort unit the
 * generation fence needs.  direction: TPU_MEMRING_PEER_WRITE pushes
 * local->lender (demote), TPU_MEMRING_PEER_READ pulls lender->local
 * (promote). */
static TpuStatus remote_copy_windows(uint32_t devInst, uint32_t lenderInst,
                                     const RemoteSpan *spans, uint32_t n,
                                     uint32_t direction)
{
    TpuStatus first = TPU_OK;
    for (uint32_t base = 0; base < n && first == TPU_OK;
         base += REMOTE_BATCH_MAX) {
        TpuMemringSqe sqes[REMOTE_BATCH_MAX];
        TpuStatus sts[REMOTE_BATCH_MAX];
        uint32_t cnt = n - base;
        if (cnt > REMOTE_BATCH_MAX)
            cnt = REMOTE_BATCH_MAX;
        memset(sqes, 0, sizeof(sqes[0]) * cnt);
        for (uint32_t i = 0; i < cnt; i++) {
            TpuMemringSqe *s = &sqes[i];
            s->opcode = TPU_MEMRING_OP_PEER_COPY;
            s->devInst = devInst;
            s->peerInst = lenderInst;
            s->addr = spans[base + i].localOff;
            s->peerOff = spans[base + i].peerOff;
            s->len = spans[base + i].len;
            s->arg0 = direction;
            if (i >= REMOTE_WINDOW)
                tpurmMemringSqeDep(s, TPU_MEMRING_DEP(TPU_MEMRING_DEP_BATCH,
                                                      i - REMOTE_WINDOW));
        }
        TpuStatus sub =
            tpurmMemringSubmitInternal(NULL, sqes, cnt, sts,
                                       TPU_MEMRING_SUBSYS_TIER);
        for (uint32_t i = 0; i < cnt && first == TPU_OK; i++)
            if (sts[i] != TPU_OK)
                first = sts[i];
        if (first == TPU_OK && sub != TPU_OK)
            first = sub;
    }
    return first;
}

/* Drop/re-take blk->lock around the spine wait.  `tag` must match the
 * caller's tpuLockTrack tag so the tracker's pairing stays coherent. */
static void remote_unlock(UvmVaBlock *blk, const char *tag)
{
    blk->p2pPinCount++;
    blk->remoteBusy++;
    tpuLockTrackRelease(TPU_LOCK_UVM_BLOCK, tag);
    pthread_mutex_unlock(&blk->lock);
}

static void remote_relock(UvmVaBlock *blk, const char *tag)
{
    pthread_mutex_lock(&blk->lock);
    tpuLockTrackAcquire(TPU_LOCK_UVM_BLOCK, tag);
    blk->p2pPinCount--;
    blk->remoteBusy--;
}

/* ------------------------------------------------------------- demote */

/* Replicate [first,last] ∩ toHost into a lease on a lender chip.
 * Called from the block-eviction path — blk->lock held, tag
 * "block-evict" —
 * AFTER the host copy-back committed and BEFORE resident[HBM] is
 * cleared — the local HBM runs are still the PEER_COPY source, and the
 * write-through invariant (REMOTE ⊆ HOST) holds by construction.
 * Best-effort: any refusal (no healthy lender, headroom, lender arena
 * full, spine error) just skips replication; eviction proceeds to HOST
 * exactly as before. */
void uvmTierRemoteReplicate(UvmVaBlock *blk, const UvmPageMask *toHost,
                            uint32_t first, uint32_t last)
{
    if (!uvmTierRemoteEnabled() || blk->hbmDevInst >= REMOTE_MAX_DEVS)
        return;

    uint32_t lender;
    if (tpurmHealthPickTarget(blk->hbmDevInst, &lender) != TPU_OK ||
        lender >= REMOTE_MAX_DEVS || lender == blk->hbmDevInst)
        return;

    uint64_t ps = uvmPageSize();

    /* Headroom gate: the lender must keep remote_headroom_pct of its
     * arena free AFTER the lease (uvmHbmArenaUsage already nets out
     * bytes it lent, which are reclaimable on demand). */
    uint64_t freeB = 0, totalB = 0, wantB = 0;
    for (uint32_t p = first; p <= last; p++)
        if (uvmPageMaskTest(toHost, p))
            wantB += ps;
    if (!wantB)
        return;
    if (uvmHbmArenaUsage(lender, &freeB, &totalB) != TPU_OK ||
        freeB < wantB || freeB - wantB < totalB * remote_headroom_pct() / 100) {
        tpuCounterAdd("tier_remote_headroom_refusals", 1);
        return;
    }

    /* Plan: coalesce contiguous (page, HBM offset) runs, one lender
     * chunk per span.  Offsets are stable while we later drop the lock:
     * pin + remoteBusy block every mover. */
    RemoteSpan *spans = calloc(last - first + 1, sizeof(*spans));
    if (!spans)
        return;
    uint32_t nspans = 0;
    uint64_t prevOff = 0;
    for (uint32_t p = first; p <= last; p++) {
        uint64_t off;
        if (!uvmPageMaskTest(toHost, p) || !uvmBlockHbmArenaOffset(blk, p, &off))
            continue;
        if (nspans && spans[nspans - 1].firstPage + spans[nspans - 1].numPages
                == p && prevOff + ps == off) {
            spans[nspans - 1].numPages++;
            spans[nspans - 1].len += ps;
        } else {
            spans[nspans].localOff = off;
            spans[nspans].len = ps;
            spans[nspans].firstPage = p;
            spans[nspans].numPages = 1;
            nspans++;
        }
        prevOff = off;
    }
    if (!nspans) {
        free(spans);
        return;
    }

    /* Lease one lender chunk per span (plain alloc, no evict ladder:
     * a full lender is a refusal, never recursive eviction). */
    uint32_t ok = 0;
    for (; ok < nspans; ok++)
        if (uvmHbmChunkAllocSized(lender, spans[ok].len, &spans[ok].peerOff,
                                  &spans[ok].granted,
                                  &spans[ok].chunkHandle) != TPU_OK)
            break;
    if (ok < nspans) {
        for (uint32_t i = 0; i < ok; i++)
            uvmHbmChunkFree(lender, spans[i].chunkHandle);
        free(spans);
        tpuCounterAdd("tier_remote_headroom_refusals", 1);
        return;
    }

    uint64_t gen = tpurmDeviceGeneration();
    uint64_t epoch = atomic_load(&g_remote[lender].revokeEpoch);

    remote_unlock(blk, "block-evict");
    TpuStatus st = remote_copy_windows(blk->hbmDevInst, lender, spans, nspans,
                                       TPU_MEMRING_PEER_WRITE);
    remote_relock(blk, "block-evict");

    if (st != TPU_OK || gen != tpurmDeviceGeneration()) {
        for (uint32_t i = 0; i < nspans; i++)
            uvmHbmChunkFree(lender, spans[i].chunkHandle);
        free(spans);
        tpuCounterAdd("tier_remote_demote_fails", 1);
        tpurmJournalEmit(TPU_JREC_TIER_REMOTE, lender,
                         st != TPU_OK ? st : TPU_ERR_DEVICE_RESET,
                         /*a0*/ 0, /*a1=op*/ 1);
        return;
    }

    uint64_t pages = 0;
    for (uint32_t i = 0; i < nspans; i++) {
        UvmRemoteRun *run = calloc(1, sizeof(*run));
        if (!run) {
            uvmHbmChunkFree(lender, spans[i].chunkHandle);
            continue;
        }
        run->firstPage = spans[i].firstPage;
        run->numPages = spans[i].numPages;
        run->lenderInst = lender;
        run->lenderOff = spans[i].peerOff;
        run->chunkBytes = spans[i].granted;
        run->chunkHandle = spans[i].chunkHandle;
        run->leaseGen = gen;
        run->revokeEpoch = epoch;
        run->next = blk->remoteRuns;
        blk->remoteRuns = run;
        uvmPageMaskSetRange(&blk->resident[UVM_TIER_REMOTE], run->firstPage,
                            run->numPages);
        atomic_fetch_add(&g_remote[lender].lentBytes, run->chunkBytes);
        atomic_fetch_add(&g_remote[lender].leases, 1);
        atomic_fetch_add(&g_remote[blk->hbmDevInst].borrowedPages,
                         run->numPages);
        pages += run->numPages;
    }
    free(spans);
    if (pages) {
        tpuCounterAdd("tier_remote_demotes", 1);
        tpuCounterAdd("tier_remote_demote_bytes", pages * ps);
        tpurmJournalEmit(TPU_JREC_TIER_REMOTE, lender, TPU_OK, pages,
                         /*a1=op*/ 0);
    }
}

/* ------------------------------------------------------------ promote */

/* Fetch `needed` pages whose REMOTE lease is still valid into the
 * block's freshly allocated HBM runs (uvmBlockMakeResidentEx, blk->lock
 * held, tag "block", called after backing alloc and before the HOST
 * copy-in; fetched pages are masked out of the copy).  Invalid or
 * failed leases are dropped — the caller's HOST copy-in serves those
 * pages, so an aborted PEER_COPY can never leave garbage behind a
 * completed read. */
void uvmTierRemoteFetch(UvmVaBlock *blk, uint32_t devInst,
                        const UvmPageMask *needed, UvmPageMask *fetched)
{
    uvmPageMaskZero(fetched);
    if (!blk->remoteRuns || devInst != blk->hbmDevInst)
        return;

    uint64_t ps = uvmPageSize();

    /* Validate every intersecting lease first; drop the dead ones so
     * the plan below only reads live leases. */
    UvmRemoteRun **pp = &blk->remoteRuns;
    while (*pp) {
        UvmRemoteRun *run = *pp;
        bool wanted = false;
        for (uint32_t p = run->firstPage;
             p < run->firstPage + run->numPages && !wanted; p++)
            wanted = uvmPageMaskTest(needed, p) &&
                     uvmPageMaskTest(&blk->resident[UVM_TIER_REMOTE], p);
        if (wanted && !remote_lease_valid(run)) {
            remote_run_free(blk, pp, run, true);
            continue;
        }
        pp = &run->next;
    }

    RemoteSpan *spans = calloc(blk->npages, sizeof(*spans));
    if (!spans)
        return;

    /* One lender at a time (multi-lender blocks submit per lender). */
    for (;;) {
        uint32_t lender = UINT32_MAX, nspans = 0;
        uint64_t gen = tpurmDeviceGeneration();
        /* Pick the first lender that still has a wanted, unfetched page. */
        for (UvmRemoteRun *run = blk->remoteRuns;
             run && lender == UINT32_MAX; run = run->next)
            for (uint32_t p = run->firstPage;
                 p < run->firstPage + run->numPages; p++)
                if (uvmPageMaskTest(needed, p) &&
                    uvmPageMaskTest(&blk->resident[UVM_TIER_REMOTE], p) &&
                    !uvmPageMaskTest(fetched, p)) {
                    lender = run->lenderInst;
                    break;
                }
        if (lender == UINT32_MAX)
            break;
        for (UvmRemoteRun *run = blk->remoteRuns; run; run = run->next) {
            if (run->lenderInst != lender)
                continue;
            for (uint32_t p = run->firstPage;
                 p < run->firstPage + run->numPages; p++) {
                uint64_t off;
                if (!uvmPageMaskTest(needed, p) ||
                    !uvmPageMaskTest(&blk->resident[UVM_TIER_REMOTE], p) ||
                    uvmPageMaskTest(fetched, p) ||
                    !uvmBlockHbmArenaOffset(blk, p, &off))
                    continue;
                spans[nspans].localOff = off;
                spans[nspans].peerOff =
                    run->lenderOff + (uint64_t)(p - run->firstPage) * ps;
                spans[nspans].len = ps;
                spans[nspans].firstPage = p;
                spans[nspans].numPages = 1;
                /* Merge with previous span when both sides extend. */
                if (nspans &&
                    spans[nspans - 1].firstPage + spans[nspans - 1].numPages
                        == p &&
                    spans[nspans - 1].localOff + spans[nspans - 1].len
                        == spans[nspans].localOff &&
                    spans[nspans - 1].peerOff + spans[nspans - 1].len
                        == spans[nspans].peerOff) {
                    spans[nspans - 1].numPages++;
                    spans[nspans - 1].len += ps;
                } else {
                    nspans++;
                }
            }
        }
        if (!nspans)
            break;

        remote_unlock(blk, "block");
        TpuStatus st = remote_copy_windows(devInst, lender, spans, nspans,
                                           TPU_MEMRING_PEER_READ);
        remote_relock(blk, "block");

        if (st == TPU_OK && gen == tpurmDeviceGeneration()) {
            uint64_t pages = 0;
            for (uint32_t i = 0; i < nspans; i++) {
                uvmPageMaskSetRange(fetched, spans[i].firstPage,
                                    spans[i].numPages);
                pages += spans[i].numPages;
            }
            tpuCounterAdd("tier_remote_promotes", 1);
            tpuCounterAdd("tier_remote_promote_bytes", pages * ps);
        } else {
            /* Fence abort: the window dep-cancelled (or the generation
             * moved under us).  Drop every lease on this lender — the
             * destination pages stay masked out of `fetched`, so the
             * caller's HOST copy-in overwrites any partial bytes. */
            UvmRemoteRun **dp = &blk->remoteRuns;
            while (*dp) {
                if ((*dp)->lenderInst == lender)
                    remote_run_free(blk, dp, *dp, true);
                else
                    dp = &(*dp)->next;
            }
        }
        /* Loop: the pick above finds the next lender with unfetched
         * pages; fetched or dropped leases cannot be re-picked. */
    }
    free(spans);
}
