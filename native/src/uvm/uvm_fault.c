/*
 * Fault engine — software replayable faults for TPU managed memory.
 *
 * The reference services GPU MMU faults from a HW fault buffer through a
 * batched loop (uvm_gpu_replayable_faults.c:2906: fetch -> coalesce ->
 * preprocess -> service -> replay).  TPUs expose no replayable-fault
 * buffer (SURVEY.md §7 hard part #1), so the TPU-native substitute keeps
 * the exact loop structure but swaps the fault *source*:
 *
 *   CPU accesses   — managed VAs are PROT_NONE until resident on host; a
 *                    SIGSEGV handler writes a fault record into a
 *                    lock-free MPSC ring (the "fault buffer") and parks
 *                    the faulting thread on a futex.  The service thread
 *                    wakes it after servicing ("replay": the faulting
 *                    instruction retries against the now-valid PTE).
 *   device accesses — DMA/copy paths call uvmDeviceAccess() before
 *                    touching managed memory; non-resident spans enter
 *                    the same ring as device-sourced faults.
 *
 * The handler is async-signal-safe: lookup uses an immutable snapshot
 * array swapped atomically (readers counted, writer waits quiescence),
 * ring slots use a Vyukov-style ticket protocol, and parking uses raw
 * futex syscalls.  Faults on the service thread itself (a real bug) fall
 * through to the default handler.
 *
 * Batching/latency stats mirror the reference's knobs: registry
 * "uvm_fault_batch_size" (reference uvm_perf_fault_batch_count) bounds a
 * batch; service latency percentiles come from the tputrace log-linear
 * histograms (full range, <=0.8% relative error — the old bounded
 * 4096-sample windows could only describe the last window).
 */
#define _GNU_SOURCE
#include "uvm_internal.h"
#include "tpurm/flow.h"
#include "tpurm/health.h"
#include "tpurm/inject.h"
#include "tpurm/journal.h"
#include "tpurm/memring.h"
#include "tpurm/trace.h"

#include <errno.h>
#include <execinfo.h>
#include <linux/futex.h>
#include <sched.h>
#include <sys/uio.h>
#include <signal.h>
#include <stdatomic.h>
#include <stdlib.h>
#include <string.h>
#include <sys/mman.h>
#include <sys/syscall.h>
#include <stdio.h>
#include <ucontext.h>
#include <unistd.h>

#define FAULT_RING_SIZE 4096          /* power of two */

static long futex_call(uint32_t *uaddr, int op, uint32_t val)
{
    return syscall(SYS_futex, uaddr, op, val, NULL, NULL, 0);
}

static long futex_wait_timeout(uint32_t *uaddr, uint32_t val, uint64_t ns)
{
    struct timespec ts = { .tv_sec = (time_t)(ns / 1000000000ull),
                           .tv_nsec = (long)(ns % 1000000000ull) };
    return syscall(SYS_futex, uaddr, FUTEX_WAIT, val, &ts, NULL, 0);
}

/* ------------------------------------------------------------- snapshot */

typedef struct {
    uint64_t start, end;
    UvmVaSpace *vs;
} SnapEntry;

typedef struct {
    uint32_t count;
    SnapEntry entries[];
} Snapshot;

/* --------------------------------------------------------------- state */

typedef struct {
    _Atomic uint64_t seq;
    UvmFaultEntry *e;
} RingSlot;

/* Per-worker service state (reference: per-GPU bottom halves on
 * dedicated kthread queues, uvm_gpu_isr.c:115,145).  Faults partition
 * by VA BLOCK — every fault on a given 2 MB block lands on the same
 * worker — which preserves the single-writer property the per-block
 * perf state (prefetch windows, thrashing, access counters) and batch
 * coalescing rely on, while different blocks service concurrently. */
#define FAULT_MAX_WORKERS 8

typedef struct {
    /* Fault ring (MPSC per worker). */
    RingSlot ring[FAULT_RING_SIZE];
    _Atomic uint64_t widx;
    uint64_t ridx;                    /* owning worker only */
    uint32_t pending;                 /* futex word */
    /* SQPOLL-style wake elision (PR 11): nonzero while the worker is
     * yield-spinning on `pending` — producers then skip the FUTEX_WAKE
     * syscall (the spin's deregister-then-recheck makes a lost wake
     * impossible), taking two syscalls out of the fault wake path. */
    uint32_t polling;

    pthread_t thread;
    /* Written once by the worker at startup, read by the SIGSEGV
     * handler's am-I-a-worker check: atomic (relaxed) so the benign
     * startup race is also a CLEAN one — TSAN runs the reset/park
     * handshakes over this path. */
    _Atomic pid_t tid;
    uint32_t index;

    /* ONCE replay policy: wakes deferred until this worker's ring
     * drains (owning worker only). */
    UvmFaultEntry *onceDeferred[FAULT_RING_SIZE];
    uint32_t onceCount;

    /* True while a batch is being serviced (PM drain barrier). */
    _Atomic bool servicing;

    uint64_t lastSweepNs;             /* owning worker only */
} FaultWorker;

static struct {
    pthread_once_t once;
    bool ready;

    /* Registered spaces (under mutex). */
    pthread_mutex_t spacesLock;
    UvmVaSpace *spacesHead;

    /* Signal-safe VA snapshot. */
    _Atomic(Snapshot *) snap;
    _Atomic uint32_t snapReaders;

    FaultWorker workers[FAULT_MAX_WORKERS];
    uint32_t nWorkers;
    _Atomic uint32_t inService;       /* workers currently in a batch */
    _Atomic uint32_t serviceHighWater;/* max simultaneous (observability) */
    /* Set once any fault delivers a nonzero x86 page-fault error code:
     * the kernel reports access types and the service can skip the
     * write-inference fallback (sandboxes zero the field). */
    _Atomic int regErrWorks;
    /* Full-device reset quiesce (reset.c): while set, workers park
     * between batches — pending faults wait (their threads are parked
     * in the SIGSEGV handler anyway) until resume.  The pause window
     * is the reset's reset phase, i.e. milliseconds. */
    _Atomic int paused;
    struct sigaction oldSegv;

    /* Stats (shared).  Latencies land in three tputrace histograms
     * that decompose the end-to-end cost: FAULT_LATENCY =
     * enqueue->replay (the headline), FAULT_WAKE = enqueue->batch-pop
     * (signal + futex + scheduler cost — on a 1-CPU box this is a
     * context switch, not engine work), FAULT_SERVICE = one
     * service_one call (the engine's own work).  The histograms record
     * unconditionally (they back the UvmFaultStats ABI); ring events
     * emit only while tracing is armed. */
    _Atomic uint64_t faultsCpu, faultsDevice, batches, migratedBytes,
        evictions;
} g_fault = { .once = PTHREAD_ONCE_INIT };

/* Block-stable worker assignment. */
static FaultWorker *worker_for(uint64_t addr)
{
    return &g_fault.workers[(addr / UVM_BLOCK_SIZE) % g_fault.nWorkers];
}

void uvmFaultStatsRecordMigration(uint64_t bytes)
{
    atomic_fetch_add(&g_fault.migratedBytes, bytes);
}

void uvmFaultStatsRecordEviction(void)
{
    atomic_fetch_add(&g_fault.evictions, 1);
    tpurmTraceInstant(TPU_TRACE_EVICT, 0, 0);
}

static void lat_record(uint64_t ns)
{
    tpuHistRecord(tpurmTraceHistRef(TPU_TRACE_FAULT_LATENCY), ns);
}

/* Restart the latency histograms (percentiles onward cover only
 * faults after this call).  Counters (faultsCpu etc.) are NOT reset —
 * only the three fault-latency histograms, so a benchmark can scope
 * its recorded p50/p95 to exactly the workload it reports. */
void uvmFaultStatsResetWindows(void)
{
    tpuHistReset(tpurmTraceHistRef(TPU_TRACE_FAULT_LATENCY));
    tpuHistReset(tpurmTraceHistRef(TPU_TRACE_FAULT_WAKE));
    tpuHistReset(tpurmTraceHistRef(TPU_TRACE_FAULT_SERVICE));
}

void uvmFaultStatsGet(UvmFaultStats *out)
{
    memset(out, 0, sizeof(*out));
    out->faultsCpu = atomic_load(&g_fault.faultsCpu);
    out->faultsDevice = atomic_load(&g_fault.faultsDevice);
    out->batches = atomic_load(&g_fault.batches);
    out->migratedBytes = atomic_load(&g_fault.migratedBytes);
    out->evictions = atomic_load(&g_fault.evictions);
    out->serviceNsP50 = tpurmTraceHistQuantileNs(TPU_TRACE_FAULT_LATENCY,
                                                 0.50);
    out->serviceNsP95 = tpurmTraceHistQuantileNs(TPU_TRACE_FAULT_LATENCY,
                                                 0.95);
    out->wakeNsP50 = tpurmTraceHistQuantileNs(TPU_TRACE_FAULT_WAKE, 0.50);
    out->wakeNsP95 = tpurmTraceHistQuantileNs(TPU_TRACE_FAULT_WAKE, 0.95);
    out->svcOneNsP50 = tpurmTraceHistQuantileNs(TPU_TRACE_FAULT_SERVICE,
                                                0.50);
    out->svcOneNsP95 = tpurmTraceHistQuantileNs(TPU_TRACE_FAULT_SERVICE,
                                                0.95);
}

/* ------------------------------------------------------ snapshot access */

/* On a hit the reader count stays held — the caller keeps the returned
 * vs alive through the whole fault (park included) and must call
 * snapshot_release() afterwards.  uvmFaultSnapshotRebuild's quiescence
 * wait therefore also drains in-flight CPU faults before a VA space can
 * be freed. */
static UvmVaSpace *snapshot_lookup_acquire(uintptr_t addr)
{
    atomic_fetch_add(&g_fault.snapReaders, 1);
    Snapshot *s = atomic_load(&g_fault.snap);
    UvmVaSpace *vs = NULL;
    if (s) {
        uint32_t lo = 0, hi = s->count;
        while (lo < hi) {
            uint32_t mid = (lo + hi) / 2;
            if (addr < s->entries[mid].start)
                hi = mid;
            else if (addr > s->entries[mid].end)
                lo = mid + 1;
            else {
                vs = s->entries[mid].vs;
                break;
            }
        }
    }
    if (!vs)
        atomic_fetch_sub(&g_fault.snapReaders, 1);
    return vs;
}

static void snapshot_release(void)
{
    atomic_fetch_sub(&g_fault.snapReaders, 1);
}

static int snap_cmp(const void *a, const void *b)
{
    const SnapEntry *x = a, *y = b;
    return x->start < y->start ? -1 : x->start > y->start;
}

void uvmFaultSnapshotRebuild(void)
{
    if (!g_fault.ready)
        return;
    pthread_mutex_lock(&g_fault.spacesLock);
    /* Count ranges. */
    uint32_t count = 0;
    for (UvmVaSpace *vs = g_fault.spacesHead; vs; vs = vs->nextSpace) {
        pthread_mutex_lock(&vs->lock);
        for (UvmRangeTreeNode *n = vs->ranges.first; n;
             n = uvmRangeTreeNext(n))
            if (((UvmVaRange *)n)->type == UVM_RANGE_TYPE_MANAGED ||
                ((UvmVaRange *)n)->type == UVM_RANGE_TYPE_REMOTE)
                count++;
        pthread_mutex_unlock(&vs->lock);
    }
    Snapshot *ns = malloc(sizeof(Snapshot) + count * sizeof(SnapEntry));
    if (!ns) {
        pthread_mutex_unlock(&g_fault.spacesLock);
        return;
    }
    uint32_t i = 0;
    for (UvmVaSpace *vs = g_fault.spacesHead; vs; vs = vs->nextSpace) {
        pthread_mutex_lock(&vs->lock);
        for (UvmRangeTreeNode *n = vs->ranges.first;
             n && i < count; n = uvmRangeTreeNext(n)) {
            /* EXTERNAL ranges take no fault service: a fault on an
             * unmapped span is a real segfault.  REMOTE windows DO
             * fault-service (forwarded to the owner engine). */
            if (((UvmVaRange *)n)->type != UVM_RANGE_TYPE_MANAGED &&
                ((UvmVaRange *)n)->type != UVM_RANGE_TYPE_REMOTE)
                continue;
            ns->entries[i].start = n->start;
            ns->entries[i].end = n->end;
            ns->entries[i].vs = vs;
            i++;
        }
        pthread_mutex_unlock(&vs->lock);
    }
    ns->count = i;
    qsort(ns->entries, i, sizeof(SnapEntry), snap_cmp);

    Snapshot *old = atomic_exchange(&g_fault.snap, ns);
    pthread_mutex_unlock(&g_fault.spacesLock);
    /* Grace period: wait for in-flight handler lookups to drain — with
     * spacesLock DROPPED.  A reader is held across the whole fault
     * (park included), and fault service can itself want spacesLock
     * (access-counter sweep, device-wrote invalidation, shield scrub
     * walk); spinning here with the lock held deadlocks rebuild ->
     * parked faulter -> blocked service thread in a 3-way cycle. */
    while (atomic_load(&g_fault.snapReaders) != 0)
        sched_yield();
    free(old);
}

/* Address -> owning VA space (registered spaces walk; NULL when no
 * managed range covers addr).  Used by subsystems that receive raw VAs
 * from outside the UVM API — e.g. the RDMA peer-memory client's
 * acquire() claims a VA exactly this way (reference nv_mem_acquire,
 * nvidia-peermem.c:198). */
UvmVaSpace *uvmFaultSpaceForAddr(uint64_t addr)
{
    UvmVaSpace *found = NULL;
    pthread_mutex_lock(&g_fault.spacesLock);
    for (UvmVaSpace *vs = g_fault.spacesHead; vs && !found;
         vs = vs->nextSpace) {
        pthread_mutex_lock(&vs->lock);
        if (uvmRangeTreeFind(&vs->ranges, addr))
            found = vs;
        pthread_mutex_unlock(&vs->lock);
    }
    pthread_mutex_unlock(&g_fault.spacesLock);
    return found;
}

void uvmFaultEngineRegisterSpace(UvmVaSpace *vs)
{
    pthread_mutex_lock(&g_fault.spacesLock);
    vs->nextSpace = g_fault.spacesHead;
    g_fault.spacesHead = vs;
    pthread_mutex_unlock(&g_fault.spacesLock);
}

void uvmFaultEngineUnregisterSpace(UvmVaSpace *vs)
{
    pthread_mutex_lock(&g_fault.spacesLock);
    UvmVaSpace **p = &g_fault.spacesHead;
    while (*p && *p != vs)
        p = &(*p)->nextSpace;
    if (*p)
        *p = vs->nextSpace;
    pthread_mutex_unlock(&g_fault.spacesLock);
    uvmFaultSnapshotRebuild();
}

/* ----------------------------------------------------------- ring (MPSC) */

/* Producer side is async-signal-safe: atomics + futex syscalls only. */
static void ring_push(FaultWorker *w, UvmFaultEntry *e)
{
    uint64_t t = atomic_fetch_add(&w->widx, 1);
    RingSlot *slot = &w->ring[t % FAULT_RING_SIZE];
    while (atomic_load_explicit(&slot->seq, memory_order_acquire) != t) {
#ifdef __x86_64__
        __builtin_ia32_pause();
#else
        __asm__ __volatile__("" ::: "memory");
#endif
    }
    slot->e = e;
    atomic_store_explicit(&slot->seq, t + 1, memory_order_release);
    __atomic_fetch_add(&w->pending, 1, __ATOMIC_SEQ_CST);
    /* Wake elision: a poller sees the pending bump on its next spin
     * check (it deregisters BEFORE its final re-check, so reading
     * polling != 0 here proves the bump will be observed).  Saves the
     * producer's syscall on the hot path — the fault wake was the
     * largest single slice of fault latency. */
    if (__atomic_load_n(&w->polling, __ATOMIC_SEQ_CST) == 0)
        futex_call(&w->pending, FUTEX_WAKE, 1);
}

/* Consumer (owning worker only).  Returns NULL when the ring is empty. */
static UvmFaultEntry *ring_pop(FaultWorker *w)
{
    RingSlot *slot = &w->ring[w->ridx % FAULT_RING_SIZE];
    if (atomic_load_explicit(&slot->seq, memory_order_acquire) !=
        w->ridx + 1)
        return NULL;
    UvmFaultEntry *e = slot->e;
    atomic_store_explicit(&slot->seq, w->ridx + FAULT_RING_SIZE,
                          memory_order_release);
    w->ridx++;
    __atomic_fetch_sub(&w->pending, 1, __ATOMIC_SEQ_CST);
    return e;
}

/* Returns true when work is pending, false on timeout (the service loop
 * uses timeouts to run the access-counter decay sweep while idle). */
static bool ring_wait_nonempty(FaultWorker *w, uint64_t timeoutNs)
{
    uint64_t deadline = uvmMonotonicNs() + timeoutNs;
    /* Adaptive spin before the futex sleep (registry
     * uvm_fault_spin_us, default 150): populate/storm patterns fault
     * back-to-back, and catching the next entry in the spin window
     * skips BOTH the producer's FUTEX_WAKE (see ring_push) and this
     * side's futex wakeup — the two syscalls that dominated fault wake
     * p50.  sched_yield in the loop keeps the producer runnable on a
     * 1-CPU box; the idle duty cycle is spin/sweep ≈ 0.3%%. */
    static TpuRegCache c_spin;
    uint64_t spinNs = tpuRegCacheGet(&c_spin, "uvm_fault_spin_us", 150) *
                      1000ull;
    if (spinNs) {
        uint64_t t0 = uvmMonotonicNs();
        __atomic_store_n(&w->polling, 1, __ATOMIC_SEQ_CST);
        while (uvmMonotonicNs() - t0 < spinNs) {
            if (__atomic_load_n(&w->pending, __ATOMIC_SEQ_CST) > 0) {
                __atomic_store_n(&w->polling, 0, __ATOMIC_SEQ_CST);
                return true;
            }
            if (atomic_load_explicit(&g_fault.paused,
                                     memory_order_acquire))
                break;             /* reset quiesce: park promptly */
            sched_yield();
        }
        __atomic_store_n(&w->polling, 0, __ATOMIC_SEQ_CST);
        /* Deregister-then-recheck: a producer that skipped its wake
         * because it read polling != 0 published `pending` before we
         * stored 0 (seq_cst total order), so this re-check sees it. */
        if (__atomic_load_n(&w->pending, __ATOMIC_SEQ_CST) > 0)
            return true;
    }
    for (;;) {
        uint32_t p = __atomic_load_n(&w->pending, __ATOMIC_SEQ_CST);
        if (p > 0)
            return true;
        uint64_t now = uvmMonotonicNs();
        if (now >= deadline)
            return false;
        futex_wait_timeout(&w->pending, 0, deadline - now);
    }
}

/* -------------------------------------------------------- fault service */

/* Access-counter promotion: move a hot span to the accessing device's
 * HBM (block pinned).  Overrides accessed-by mappings and thrash pins —
 * sustained hotness is stronger evidence than either hint. */
static void service_promote(UvmVaSpace *vs, UvmVaBlock *blk,
                            const UvmFaultEntry *e, uint32_t firstPage,
                            uint32_t count, uint32_t srcTier)
{
    UvmLocation hot = { UVM_TIER_HBM, e->devInst };
    if (uvmBlockMakeResidentEx(blk, hot, firstPage, count,
                               e->isWrite != 0, false) != TPU_OK)
        return;
    blk->acPromoted = true;
    uvmToolsEmit(vs, UVM_EVENT_ACCESS_COUNTER, srcTier, UVM_TIER_HBM,
                 e->devInst,
                 blk->start + (uint64_t)firstPage * uvmPageSize(),
                 (uint64_t)count * uvmPageSize());
}

/* Service one fault entry: resolve range/block, pick the target tier,
 * expand via prefetch, make resident.  Mirrors
 * service_fault_batch_dispatch (reference :1946).
 *
 * Locking: vs->lock covers ONLY the range/block lookup + a policy
 * snapshot; the block is pinned (serviceRefs) across the actual
 * service, which runs under the block's own lock inside
 * uvmBlockMakeResidentEx — so fault service no longer serializes
 * against every migrate/alloc in the space (reference: per-block
 * service locking, service_fault_batch_block_locked :1375). */
/* Read-duplication probe for the CPU seal-reopen path: resident on any
 * tier besides HOST.  blk->lock held. */
static bool page_read_dup(UvmVaBlock *blk, uint32_t page)
{
    for (int t = 0; t < UVM_TIER_COUNT; t++)
        if (t != (int)UVM_TIER_HOST &&
            uvmPageMaskTest(&blk->resident[t], page))
            return true;
    return false;
}

static TpuStatus service_one(UvmFaultEntry *e)
{
    UvmVaSpace *vs = e->vs;
    if (!vs)
        return TPU_ERR_OBJECT_NOT_FOUND;

    /* Injected service-loop/fence timeout: the service attempt stalls
     * and reports a transient failure; the bounded retry in
     * service_with_retry recovers it (or exhausts into quarantine). */
    if (tpurmInjectShouldFail(TPU_INJECT_SITE_FENCE_TIMEOUT))
        return TPU_ERR_INVALID_STATE;

    uint64_t ps = uvmPageSize();
    uint64_t addr = e->addr & ~(ps - 1);
    uint64_t end = e->addr + (e->len ? e->len : 1) - 1;

    TpuStatus st = TPU_OK;

    while (addr <= end && st == TPU_OK) {
        pthread_mutex_lock(&vs->lock);
        tpuLockTrackAcquire(TPU_LOCK_UVM_VASPACE, "vaspace");
        UvmVaBlock *blk = NULL;
        UvmVaRange *range = uvmRangeFind(vs, addr, &blk);
        if (range && range->type == UVM_RANGE_TYPE_REMOTE) {
            /* REMOTE window: forward to the owner engine, which makes
             * the span host-resident in the SHARED backing this window
             * maps, then open the local protection (fault-granularity
             * coherence — uvm.h uvmRemoteAttach contract). */
            uint64_t rBase = range->remoteBase;
            uint64_t lBase = range->node.start;
            uint64_t rEnd = range->node.end;
            /* Pin the window across the forward (taken under vs->lock,
             * released after the local mprotect): uvmRemoteDetach
             * drains this before munmap, so the forward can never
             * reprotect a recycled mapping. */
            atomic_fetch_add_explicit(&range->remoteRefs, 1,
                                      memory_order_acq_rel);
            tpuLockTrackRelease(TPU_LOCK_UVM_VASPACE, "vaspace");
            pthread_mutex_unlock(&vs->lock);
            /* Service whole uvm pages (windows are page-aligned). */
            uint64_t spanEnd = end < rEnd ? end : rEnd;
            spanEnd = spanEnd - (spanEnd % ps) + ps - 1;
            if (spanEnd > rEnd)
                spanEnd = rEnd;
            uint64_t len = spanEnd - addr + 1;
            /* Write-fault inference for remote windows (same sandbox
             * REG_ERR limitation as the managed branch below, but no
             * residency masks to consult here): probe the page's
             * CURRENT readability with process_vm_readv — it reports
             * EFAULT instead of faulting.  A CPU fault on a readable
             * page can only be a write (read-open windows are RO, so
             * the first store must forward as a write or it storms). */
            if (e->source == UVM_FAULT_SRC_CPU && !e->isWrite &&
                !atomic_load_explicit(&g_fault.regErrWorks,
                                      memory_order_relaxed)) {
                char probe;
                struct iovec liov = { &probe, 1 };
                struct iovec riov = { (void *)(uintptr_t)e->addr, 1 };
                if (process_vm_readv(getpid(), &liov, 1, &riov, 1, 0) ==
                    1) {
                    e->isWrite = 1;
                    tpuCounterAdd("uvm_write_faults_inferred", 1);
                }
            }
            int fst = tpurmBrokerUvmFault(rBase + (addr - lBase), len,
                                          e->isWrite != 0);
            st = (TpuStatus)fst;
            if (st == TPU_OK) {
                /* Read faults open READ-ONLY: the owner may have
                 * serviced them with read duplication (device copy
                 * survives), so the window's first WRITE must re-fault
                 * and forward as a write for the owner to invalidate
                 * its duplicates (host-exclusive) before the store
                 * lands in the shared backing. */
                int prot = e->isWrite ? (PROT_READ | PROT_WRITE)
                                      : PROT_READ;
                if (mprotect((void *)(uintptr_t)addr, len, prot) != 0)
                    st = TPU_ERR_OPERATING_SYSTEM;
                else
                    uvmToolsEmit(vs, UVM_EVENT_CPU_FAULT, UVM_TIER_COUNT,
                                 UVM_TIER_HOST, 0, addr, len);
            }
            atomic_fetch_sub_explicit(&range->remoteRefs, 1,
                                      memory_order_acq_rel);
            addr = spanEnd + 1;
            continue;
        }
        if (!range || !blk) {
            tpuLockTrackRelease(TPU_LOCK_UVM_VASPACE, "vaspace");
            pthread_mutex_unlock(&vs->lock);
            st = TPU_ERR_OBJECT_NOT_FOUND;
            break;
        }
        /* Policy snapshot + block pin, then drop the space lock: the
         * range pointer must not be used past this point (splits and
         * frees run under vs->lock; the pin keeps only the BLOCK
         * alive — uvmBlockFreeBacking waits for it to drain). */
        bool hasPreferred = range->hasPreferred;
        UvmLocation preferred = range->preferred;
        uint64_t accessedByMask = range->accessedByMask;
        atomic_fetch_add_explicit(&blk->serviceRefs, 1,
                                  memory_order_acq_rel);
        tpuLockTrackRelease(TPU_LOCK_UVM_VASPACE, "vaspace");
        pthread_mutex_unlock(&vs->lock);

        uint64_t blockEnd = blk->start + (uint64_t)blk->npages * ps - 1;
        uint64_t spanEnd = end < blockEnd ? end : blockEnd;
        uint32_t firstPage = (uint32_t)((addr - blk->start) / ps);
        uint32_t count = (uint32_t)((spanEnd - addr) / ps) + 1;

        /* Write-fault inference.  Sandboxed kernels (this container's
         * included) zero the x86 page-fault error code, so the SIGSEGV
         * handler cannot tell writes from reads and reports everything
         * as a read.  The engine itself knows better: a CPU fault on a
         * page that is host-resident and CPU-readable — mapped RO by
         * read duplication, pre-migration write protection, or an
         * accessed-by downgrade — can ONLY be a write, because reads
         * of readable pages do not fault.  Without the upgrade the
         * read-service is a no-op, the store replays into the same RO
         * page, and the fault storms forever (the long-standing
         * test_read_duplication / uvm_test_runner VA_BLOCK livelock,
         * also the serving flush path's pathological slowness). */
        if (e->source == UVM_FAULT_SRC_CPU && !e->isWrite &&
            !atomic_load_explicit(&g_fault.regErrWorks,
                                  memory_order_relaxed)) {
            pthread_mutex_lock(&blk->lock);
            tpuLockTrackAcquire(TPU_LOCK_UVM_BLOCK, "write-infer");
            bool roMapped =
                uvmPageMaskTest(&blk->resident[UVM_TIER_HOST], firstPage) &&
                !uvmPageMaskTest(&blk->cpuMapped, firstPage) &&
                !(blk->hasCancelled &&
                  uvmPageMaskTest(&blk->cancelled, firstPage));
            /* FIRST-TOUCH upgrade: a page resident NOWHERE has no copy
             * to duplicate and no owner to invalidate — servicing the
             * fault as a WRITE yields the exact same exclusive-host
             * end state as a read service except the mapping opens RW,
             * so a populate store doesn't pay a second fault + probe +
             * mprotect round trip per page (the populate pattern
             * double-faulted every page before this).  Genuine
             * first-touch reads get the same correct mapping. */
            bool fresh = !(blk->hasCancelled &&
                           uvmPageMaskTest(&blk->cancelled, firstPage));
            for (int t = 0; fresh && t < UVM_TIER_COUNT; t++)
                if (uvmPageMaskTest(&blk->resident[t], firstPage))
                    fresh = false;
            tpuLockTrackRelease(TPU_LOCK_UVM_BLOCK, "write-infer");
            pthread_mutex_unlock(&blk->lock);
            static TpuRegCache c_ftw;
            if (fresh && tpuRegCacheGet(&c_ftw, "uvm_first_touch_write",
                                        1)) {
                e->isWrite = 1;
                tpuCounterAdd("uvm_write_faults_inferred", 1);
                tpuCounterAdd("uvm_first_touch_writes", 1);
            } else if (roMapped) {
                /* Confirm the page is actually READABLE before
                 * upgrading: a host-resident page can also sit behind
                 * PROT_NONE (e.g. a surviving read-dup copy after an
                 * exclusive migrate device-ward protects the whole
                 * span), where a plain read fault is legitimate.
                 * process_vm_readv reports EFAULT instead of faulting,
                 * so the probe is safe from a service worker. */
                char probe;
                struct iovec liov = { &probe, 1 };
                struct iovec riov = { (void *)(uintptr_t)e->addr, 1 };
                if (process_vm_readv(getpid(), &liov, 1, &riov, 1, 0) ==
                    1) {
                    e->isWrite = 1;
                    tpuCounterAdd("uvm_write_faults_inferred", 1);
                }
            }
        }

        /* Fully-quarantined span: the page(s) were retired after
         * exhausting every bounded retry — report that rather than
         * re-servicing forever.  Only device accesses can land here
         * (the CPU side of a quarantined page is a RW poison mapping
         * that never faults again).  Read the cancel state under the
         * block lock: service_cancel writes it under the same lock on
         * another worker. */
        if (e->source == UVM_FAULT_SRC_DEVICE) {
            pthread_mutex_lock(&blk->lock);
            tpuLockTrackAcquire(TPU_LOCK_UVM_BLOCK, "quarantine-check");
            bool allCancelled = blk->hasCancelled;
            for (uint32_t p = firstPage;
                 allCancelled && p < firstPage + count; p++) {
                if (!uvmPageMaskTest(&blk->cancelled, p))
                    allCancelled = false;
            }
            tpuLockTrackRelease(TPU_LOCK_UVM_BLOCK, "quarantine-check");
            pthread_mutex_unlock(&blk->lock);
            if (allCancelled) {
                atomic_fetch_sub_explicit(&blk->serviceRefs, 1,
                                          memory_order_acq_rel);
                st = TPU_ERR_PAGE_QUARANTINED;
                break;
            }
        }

        /* tpushield: a span with sealed or poisoned pages crossing
         * back into service.  Poisoned pages fail the access with the
         * DISTINCT poison status — ANY poisoned page in a device span
         * fails the whole access (a partially-serviced span would
         * silently read the poison mapping's zeros); sealed pages
         * VERIFY (re-fetch ladder on mismatch) before anything trusts
         * the bytes.  CPU touches of a verified HOST-sealed page come
         * back hot: unseal + the RW mapping the eviction deferred.
         * Gate is one pointer load — unsealed traffic pays nothing. */
        if (blk->shield) {
            pthread_mutex_lock(&blk->lock);
            tpuLockTrackAcquire(TPU_LOCK_UVM_BLOCK, "shield-verify");
            TpuStatus vst = TPU_OK;
            if (uvmShieldRangePoisoned(blk, firstPage, count) ||
                uvmShieldRangeSealed(blk, firstPage, count))
                /* ALWAYS the full range verify — it walks past
                 * already-poisoned pages and still runs the ladder on
                 * every other sealed page of the span.  Short-
                 * circuiting on existing poison would let the CPU
                 * precision override below unseal + open RW sealed
                 * pages that were never verified (corrupt sealed
                 * bytes served as trusted data). */
                vst = uvmShieldVerifyRange(blk, firstPage, count);
            /* CPU containment precision: a poisoned page is already
             * parked behind its own zero mapping (cancelled mask set),
             * so a CPU access whose FAULTING page is healthy can still
             * be serviced — needed-mask construction skips cancelled
             * pages, and the reader sees zeros exactly on the poisoned
             * page.  Failing the whole span here would quarantine the
             * innocent faulting page too (data-loss amplification).
             * Device spans keep any-poison-fails: a partially-serviced
             * device access would silently read the zeros. */
            if (vst == TPU_ERR_PAGE_POISONED &&
                e->source == UVM_FAULT_SRC_CPU &&
                !(e->addr >= blk->start &&
                  uvmShieldRangePoisoned(
                      blk, (uint32_t)((e->addr - blk->start) / ps), 1)))
                vst = TPU_OK;
            if (vst == TPU_OK && e->source == UVM_FAULT_SRC_CPU) {
                uint32_t q = firstPage;
                while (q < firstPage + count) {
                    if (uvmShieldPageSealedTier(blk, q) !=
                            (int)UVM_TIER_HOST ||
                        !uvmPageMaskTest(&blk->resident[UVM_TIER_HOST],
                                         q)) {
                        q++;
                        continue;
                    }
                    /* Read-duplicated pages reopen READ-ONLY (the
                     * make-resident convention: a CPU write must
                     * fault so the device duplicates invalidate —
                     * reopening RW here would let stores land without
                     * a fault and silently diverge the copies). */
                    bool dup = page_read_dup(blk, q);
                    uint32_t span = 1;
                    while (q + span < firstPage + count &&
                           uvmShieldPageSealedTier(blk, q + span) ==
                               (int)UVM_TIER_HOST &&
                           uvmPageMaskTest(&blk->resident[UVM_TIER_HOST],
                                           q + span) &&
                           page_read_dup(blk, q + span) == dup)
                        span++;
                    uvmShieldUnsealRange(blk, q, span,
                                         (int)UVM_TIER_HOST);
                    if (dup) {
                        uvmBlockSetCpuAccess(blk, q, span, PROT_READ);
                    } else {
                        uvmBlockSetCpuAccess(blk, q, span,
                                             PROT_READ | PROT_WRITE);
                        uvmPageMaskSetRange(&blk->cpuMapped, q, span);
                    }
                    q += span;
                }
            }
            tpuLockTrackRelease(TPU_LOCK_UVM_BLOCK, "shield-verify");
            pthread_mutex_unlock(&blk->lock);
            if (vst != TPU_OK) {
                atomic_fetch_sub_explicit(&blk->serviceRefs, 1,
                                          memory_order_acq_rel);
                st = vst;
                break;
            }
        }

        /* Target selection (service_fault_batch_block analog):
         *   CPU fault    -> HOST (read faults honor a device-side
         *                   thrashing pin by duplicating instead of
         *                   invalidating),
         *   device fault -> preferred location if it names a device
         *                   tier, CXL if the block is thrash-pinned
         *                   there, else the faulting device's HBM. */
        UvmLocation dst;
        bool forceDup = false;
        if (e->source == UVM_FAULT_SRC_CPU) {
            dst.tier = UVM_TIER_HOST;
            dst.devInst = 0;
            if (!e->isWrite &&
                uvmPerfBlockPinnedAgainst(blk, UVM_TIER_HOST))
                forceDup = true;
        } else {
            dst.tier = UVM_TIER_HBM;
            dst.devInst = e->devInst;
            if (hasPreferred && preferred.tier != UVM_TIER_HOST)
                dst = preferred;
            if (uvmPerfBlockPinnedAgainst(blk, UVM_TIER_HBM)) {
                dst.tier = UVM_TIER_CXL;
                dst.devInst = 0;
            }
            /* A counter-promoted block stays in HBM: without this, the
             * next device WRITE fault would re-target the preferred CXL
             * tier and undo the promotion one access after it happened
             * (reads duplicate, so only writes regress).  Promotion
             * expires via the decay sweep, not via target selection. */
            if (blk->acPromoted && dst.tier != UVM_TIER_HBM) {
                dst.tier = UVM_TIER_HBM;
                dst.devInst = e->devInst;
            }
            /* Device READ faults duplicate instead of invalidating: the
             * device copy is then clean, so eviction under memory
             * pressure drops it without a copy-back — the streaming /
             * KV-cache read pattern pays one copy instead of two.
             * Device writes stay exclusive (host copy invalidated). */
            if (!e->isWrite)
                forceDup = true;
        }

        /* tpuhot tracker feed: ONE relaxed RMW per service (CPU demand
         * faults and device-access spans both land here) — recency and
         * decay fold lazily at the policy points. */
        uvmHotTouch(blk, count);
        /* THROTTLE hint (thrash mitigation without HBM headroom): delay
         * this stream's service so the resident side keeps its working
         * set.  Bounded by hot_throttle_us per service and the hint's
         * own hot_throttle_ms expiry — never a wedge. */
        {
            uint32_t tUs = uvmHotThrottleDelayUs(blk);
            if (tUs)
                usleep(tUs);
        }

        /* Prefetch effectiveness: this access DEMANDED [firstPage,
         * count) — pages there that an earlier expansion staged
         * speculatively count as prefetch hits (and unmark). */
        uint32_t reqFirst = firstPage, reqCount = count;
        uvmPerfPrefetchTouch(blk, reqFirst, reqCount);

        /* Prefetch growth only for single-page (CPU) faults; device spans
         * are explicit already. */
        if (e->len <= ps)
            uvmPerfPrefetchExpand(blk, firstPage, e->source ==
                                  UVM_FAULT_SRC_DEVICE, &firstPage, &count);
        else
            /* Multi-page device spans still feed the density tree the
             * expansion consults (they bypass the expand path). */
            uvmHotDensityMark(blk, firstPage, count);

        /* Accessed-by devices get a MAPPING to the data where it lives,
         * not a migration (reference: service_fault_batch services
         * accessed_by processors by map, uvm_va_policy semantics).  Falls
         * back to migration when the span isn't resident anywhere yet. */
        bool serviced = false;
        if (e->source == UVM_FAULT_SRC_DEVICE &&
            (accessedByMask >> e->devInst) & 1) {
            st = uvmBlockMapDevice(blk, firstPage, count, e->isWrite != 0);
            if (st == TPU_OK) {
                /* Install the accessed-by device's PTEs onto the data
                 * where it lives (aperture tiers only). */
                pthread_mutex_lock(&blk->lock);
                tpuLockTrackAcquire(TPU_LOCK_UVM_BLOCK, "pte-map");
                uvmBlockPtePopulate(blk, firstPage, count, e->devInst,
                                    e->isWrite != 0);
                tpuLockTrackRelease(TPU_LOCK_UVM_BLOCK, "pte-map");
                pthread_mutex_unlock(&blk->lock);
                uvmToolsEmit(vs, UVM_EVENT_MAP_REMOTE, UVM_TIER_COUNT,
                             UVM_TIER_COUNT, e->devInst, addr,
                             (uint64_t)count * ps);
                /* Remote (mapped) access: feed the access counters; a hot
                 * span gets promoted to the device's HBM anyway
                 * (reference: access counters trigger migrations even for
                 * mapped data, uvm_gpu_access_counters.c:81).  Mappings
                 * that already resolve to HBM are local — counting them
                 * would set acPromoted on deliberately-placed data and
                 * invite a spurious decay demotion later. */
                if (!uvmPageMaskTest(&blk->resident[UVM_TIER_HBM],
                                     firstPage) &&
                    uvmAccessCounterRecord(blk))
                    service_promote(vs, blk, e, firstPage, count,
                                    UVM_TIER_COUNT);
                serviced = true;
            } else if (st == TPU_ERR_INVALID_STATE) {
                st = TPU_OK;        /* not resident: migrate normally */
            }
        }

        if (!serviced && st == TPU_OK) {
            st = uvmBlockMakeResidentEx(blk, dst, firstPage, count,
                                        e->isWrite != 0, forceDup);
            if (st == TPU_OK) {
                /* Pages the expansion pulled in BEYOND the demanded
                 * span are speculative until something touches them. */
                if (firstPage != reqFirst || count != reqCount)
                    uvmPerfPrefetchMark(blk, reqFirst, reqCount,
                                        firstPage, count);
                /* Device faults install the faulting device's PTEs onto
                 * the new residency (reference: fault service writes
                 * GPU PTEs + TLB membar, uvm_pte_batch/uvm_tlb_batch). */
                if (e->source == UVM_FAULT_SRC_DEVICE) {
                    pthread_mutex_lock(&blk->lock);
                    tpuLockTrackAcquire(TPU_LOCK_UVM_BLOCK, "pte-install");
                    uvmBlockPtePopulate(blk, firstPage, count, e->devInst,
                                        e->isWrite != 0);
                    tpuLockTrackRelease(TPU_LOCK_UVM_BLOCK, "pte-install");
                    pthread_mutex_unlock(&blk->lock);
                }
                if (e->source == UVM_FAULT_SRC_CPU) {
                    /* Ref caches are _Atomic: several workers race the
                     * first resolution (idempotent, but a plain pointer
                     * would be a C11 data race). */
                    static _Atomic(_Atomic uint64_t *) cpuRef;
                    _Atomic uint64_t *r = atomic_load_explicit(
                        &cpuRef, memory_order_relaxed);
                    if (!r) {
                        r = tpuCounterRef("uvm_cpu_fault_count");
                        atomic_store_explicit(&cpuRef, r,
                                              memory_order_relaxed);
                    }
                    if (r)
                        atomic_fetch_add_explicit(r, 1,
                                                  memory_order_relaxed);
                } else {
                    /* Per-device + aggregate, refs resolved once. */
                    static _Atomic(_Atomic uint64_t *) aggRef;
                    static _Atomic(_Atomic uint64_t *) devRef[32];
                    _Atomic uint64_t *r = atomic_load_explicit(
                        &aggRef, memory_order_relaxed);
                    if (!r) {
                        r = tpuCounterRef("uvm_gpu_fault_count");
                        atomic_store_explicit(&aggRef, r,
                                              memory_order_relaxed);
                    }
                    if (r)
                        atomic_fetch_add_explicit(r, 1,
                                                  memory_order_relaxed);
                    if (e->devInst < 32) {
                        r = atomic_load_explicit(&devRef[e->devInst],
                                                 memory_order_relaxed);
                        if (!r) {
                            char nm[48];
                            snprintf(nm, sizeof(nm),
                                     "uvm_gpu_fault_count[d%u]",
                                     e->devInst);
                            r = tpuCounterRef(nm);
                            atomic_store_explicit(&devRef[e->devInst], r,
                                                  memory_order_relaxed);
                        }
                        if (r)
                            atomic_fetch_add_explicit(r, 1,
                                                      memory_order_relaxed);
                    }
                }
                uvmToolsEmit(vs, e->source == UVM_FAULT_SRC_CPU
                                     ? UVM_EVENT_CPU_FAULT
                                     : UVM_EVENT_GPU_FAULT,
                             UVM_TIER_COUNT, dst.tier, dst.devInst,
                             addr, (uint64_t)count * ps);
                /* Device access placed off-HBM (CXL preference / thrash
                 * pin): hotness accumulates; threshold promotes to HBM. */
                if (e->source == UVM_FAULT_SRC_DEVICE &&
                    dst.tier != UVM_TIER_HBM && uvmAccessCounterRecord(blk))
                    service_promote(vs, blk, e, firstPage, count, dst.tier);
            }
        }

        atomic_fetch_sub_explicit(&blk->serviceRefs, 1,
                                  memory_order_acq_rel);
        addr = blockEnd + 1;
    }

    return st;
}

/* Bounded retry around one fault service (the hardened recovery core):
 * transient failures — CE faults bubbling out of the copy layer,
 * allocation churn, injected timeouts — get RC reset-and-replay plus an
 * exponential backoff, up to registry "uvm_fault_retry_limit" attempts.
 * A fault that stays fatal through every attempt reports
 * RETRY_EXHAUSTED, which service_cancel turns into page quarantine:
 * "pages that fault fatally more than N times are retired". */
static bool status_transient(TpuStatus st)
{
    return st == TPU_ERR_INVALID_STATE || st == TPU_ERR_NO_MEMORY ||
           st == TPU_ERR_STATE_IN_USE;
}

static TpuStatus service_with_retry(UvmFaultEntry *e)
{
    TpuStatus st = service_one(e);
    if (st == TPU_OK || !status_transient(st))
        return st;
    uint32_t limit = (uint32_t)tpuRegistryGet("uvm_fault_retry_limit", 3);
    uint32_t attempt = 0;
    while (attempt < limit && status_transient(st)) {
        tpuCounterAdd("recover_retries", 1);
        tpuCounterAdd("recover_fault_retries", 1);
        tpurmTraceInstant(TPU_TRACE_RECOVER_RETRY, e->addr, attempt);
        tpuRcRecoverAll();
        tpuRecoverBackoff(attempt);
        attempt++;
        st = service_one(e);
    }
    if (st != TPU_OK && status_transient(st))
        st = TPU_ERR_RETRY_EXHAUSTED;
    return st;
}

static void service_cancel(UvmFaultEntry *e);

/* Spine execution of ONE pending fault entry (memring OP_FAULT): the
 * bounded-retry service, the cancel/quarantine pipeline on failure,
 * the per-service histogram and the cpu/device counters — everything
 * the batch loop used to do inline per primary.  Returns the entry's
 * FINAL status (service_cancel's precise mode may poison the page and
 * resolve it to TPU_OK so the waiter proceeds — a chain therefore only
 * cancels on the failures the old inline loop would also have
 * propagated to the waiter). */
TpuStatus uvmFaultServiceExec(void *entryPtr)
{
    UvmFaultEntry *e = entryPtr;
    /* tpuflow: service under the entry's request identity, so nested
     * engine spans (migrate copies, ce stripes) carry it.  Blame: CPU
     * demand faults charge the fault-service bucket; device faults
     * are the body of a staged PREFETCH whose exec layer already
     * charges the copy bucket — charging both would double-count. */
    uint64_t prevFlow = 0;
    if (e->flow) {
        prevFlow = tpurmTraceFlowGet();
        tpurmTraceFlowSet(e->flow);
    }
    uint64_t tSvc = uvmMonotonicNs();
    e->serviceStatus = service_with_retry(e);
    uint64_t tSvcEnd = uvmMonotonicNs();
    tpuHistRecord(tpurmTraceHistRef(TPU_TRACE_FAULT_SERVICE),
                  tSvcEnd - tSvc);
    tpurmTraceEventAt(TPU_TRACE_FAULT_SERVICE, tSvc, tSvcEnd, e->addr,
                      e->len);
    if (e->flow) {
        tpurmTraceFlowSet(prevFlow);
        if (e->source == UVM_FAULT_SRC_CPU)
            tpurmFlowAccount(e->flow, TPU_FLOW_B_FAULT, tSvcEnd - tSvc);
    }
    if (e->serviceStatus != TPU_OK)
        service_cancel(e);
    if (e->source == UVM_FAULT_SRC_CPU)
        atomic_fetch_add(&g_fault.faultsCpu, 1);
    else
        atomic_fetch_add(&g_fault.faultsDevice, 1);
    return e->serviceStatus;
}

static void replay_wake(UvmFaultEntry *e, uint64_t nowNs)
{
    lat_record(nowNs - e->enqueueNs);
    tpurmTraceEventAt(TPU_TRACE_FAULT_LATENCY, e->enqueueNs, nowNs,
                      e->addr, e->len);
    /* Only successfully serviced device faults REPLAY; fatal ones were
     * cancelled (FATAL_FAULT already emitted) and must not also read as
     * replayed. */
    if (e->source == UVM_FAULT_SRC_DEVICE && e->serviceStatus == TPU_OK)
        uvmToolsEmit(e->vs, UVM_EVENT_GPU_FAULT_REPLAY, UVM_TIER_COUNT,
                     UVM_TIER_COUNT, e->devInst, e->addr, e->len);
    uint32_t doneVal = e->serviceStatus == TPU_OK ? 1 : 2;
    /* The entry lives on the FAULTING thread's stack and dies the
     * instant that thread observes the done store — every read of *e
     * must precede it.  Cache the futex word: re-reading e->doneWord
     * after the store races the stack slot's reuse by the thread's
     * next fault (a stale-address FUTEX_WAKE itself is harmless). */
    uint32_t *dw = e->doneWord;
    __atomic_store_n(dw, doneVal, __ATOMIC_SEQ_CST);
    futex_call(dw, FUTEX_WAKE, 1);
}

/* Fatal-fault cancellation (reference: cancel_faults_precise,
 * uvm_gpu_replayable_faults.c:2690 — kill only the offending access,
 * not the world).  Device faults are precise by construction: the error
 * status returns to the uvmDeviceAccess caller alone.  CPU faults in
 * precise mode (registry uvm_fault_cancel_mode=1, default) detach the
 * faulting page onto an anonymous poison mapping: the offending access
 * completes against poison (reads zeros / writes discarded from the
 * managed image), the page is marked cancelled, and the process
 * survives; the failure is observable via the FATAL_FAULT event, the
 * uvm_fault_cancels counter, and residency introspection.  Mode 0
 * (fatal) keeps the legacy behavior: the waiter re-faults with the
 * default disposition and the process dies. */
static void service_cancel(UvmFaultEntry *e)
{
    tpuCounterAdd("uvm_fault_cancels", 1);
    UvmVaSpace *vs = e->vs;
    uvmToolsEmit(vs, UVM_EVENT_FATAL_FAULT, UVM_TIER_COUNT, UVM_TIER_COUNT,
                 e->devInst, e->addr, e->len ? e->len : 1);
    TPU_LOG(TPU_LOG_ERROR, "uvm",
           "fault cancel: addr=0x%llx src=%s status=%s",
           (unsigned long long)e->addr,
           e->source == UVM_FAULT_SRC_CPU ? "cpu" : "device",
           tpuStatusToString(e->serviceStatus));
    if (e->source != UVM_FAULT_SRC_CPU ||
        tpuRegistryGet("uvm_fault_cancel_mode", 1) == 0)
        return;

    uint64_t ps = uvmPageSize();
    uint64_t pageAddr = e->addr & ~(ps - 1);
    pthread_mutex_lock(&vs->lock);
    tpuLockTrackAcquire(TPU_LOCK_UVM_VASPACE, "cancel");
    UvmVaBlock *blk = NULL;
    UvmVaRange *range = uvmRangeFind(vs, pageAddr, &blk);
    if (range && blk) {
        pthread_mutex_lock(&blk->lock);
        tpuLockTrackAcquire(TPU_LOCK_UVM_BLOCK, "cancel");
        void *m = mmap((void *)(uintptr_t)pageAddr, ps,
                       PROT_READ | PROT_WRITE,
                       MAP_FIXED | MAP_PRIVATE | MAP_ANONYMOUS, -1, 0);
        if (m != MAP_FAILED) {
            uint32_t page = (uint32_t)((pageAddr - blk->start) / ps);
            uvmPageMaskSet(&blk->cancelled, page);
            blk->hasCancelled = true;
            for (int t = 0; t < UVM_TIER_COUNT; t++)
                uvmPageMaskClear(&blk->resident[t], page);
            uvmPageMaskClear(&blk->cpuMapped, page);
            uvmPageMaskClear(&blk->devMapped, page);
            e->serviceStatus = TPU_OK;   /* waiter proceeds on poison */
            /* Page retirement: it faulted fatally through every bounded
             * retry (service_with_retry) and is now quarantined on the
             * poison mapping. */
            tpuCounterAdd("recover_page_quarantines", 1);
            tpurmJournalEmit(TPU_JREC_PAGE_QUARANTINE, 0,
                             TPU_ERR_PAGE_QUARANTINED, pageAddr, ps);
            tpurmHealthNote(0, TPU_HEALTH_EV_PAGE_QUARANTINE);
            tpurmTraceInstant(TPU_TRACE_RECOVER_QUARANTINE, pageAddr, ps);
            TPU_LOG(TPU_LOG_WARN, "uvm",
                   "page 0x%llx quarantined (%s)",
                   (unsigned long long)pageAddr,
                   tpuStatusToString(TPU_ERR_PAGE_QUARANTINED));
        }
        tpuLockTrackRelease(TPU_LOCK_UVM_BLOCK, "cancel");
        pthread_mutex_unlock(&blk->lock);
    }
    tpuLockTrackRelease(TPU_LOCK_UVM_VASPACE, "cancel");
    pthread_mutex_unlock(&vs->lock);
}

/* Decay sweep: demote counter-promoted blocks that went cold (service
 * thread only; same spacesLock -> vs lock order as snapshot rebuild). */
/* Each worker sweeps ONLY its own blocks (worker_for partitioning):
 * the per-block perf/counter state stays single-writer — the sweep of
 * a block runs on the same thread that services its faults, so the two
 * can never interleave. */
static void access_counter_sweep(FaultWorker *w)
{
    static TpuRegCache c_acEnable, c_acSweep;
    if (!tpuRegCacheGet(&c_acEnable, "uvm_access_counter_enable", 1))
        return;
    uint64_t now = uvmMonotonicNs();
    uint64_t interval = tpuRegCacheGet(&c_acSweep,
                                       "uvm_access_counter_sweep_ms", 50) *
                        1000000ull;
    if (now - w->lastSweepNs < interval)
        return;

    /* TRYLOCK: the sweep runs on the fault-service thread, and a fault
     * may land the instant the idle wait times out.  Blocking here
     * behind a snapshot rebuild (or any spaces walk) stalls fault
     * service; skip and retry next idle tick instead. */
    if (pthread_mutex_trylock(&g_fault.spacesLock) != 0)
        return;
    w->lastSweepNs = now;
    for (UvmVaSpace *vs = g_fault.spacesHead; vs; vs = vs->nextSpace) {
        pthread_mutex_lock(&vs->lock);
        tpuLockTrackAcquire(TPU_LOCK_UVM_VASPACE, "ac-sweep");
        for (UvmRangeTreeNode *n = vs->ranges.first; n;
             n = uvmRangeTreeNext(n)) {
            UvmVaRange *r = (UvmVaRange *)n;
            for (uint32_t b = 0; b < r->blockCount; b++) {
                UvmVaBlock *blk = r->blocks[b];
                if (blk && worker_for(blk->start) == w)
                    uvmAccessCounterMaybeDemote(vs, blk);
            }
        }
        tpuLockTrackRelease(TPU_LOCK_UVM_VASPACE, "ac-sweep");
        pthread_mutex_unlock(&vs->lock);
    }
    pthread_mutex_unlock(&g_fault.spacesLock);
}

static void *fault_service_thread(void *arg)
{
    FaultWorker *w = arg;
    atomic_store_explicit(&w->tid, (pid_t)syscall(SYS_gettid),
                          memory_order_relaxed);
    uint32_t maxBatch = (uint32_t)tpuRegistryGet("uvm_fault_batch_size", 256);
    if (maxBatch == 0 || maxBatch > FAULT_RING_SIZE)
        maxBatch = 256;
    UvmFaultEntry **batch = malloc(maxBatch * sizeof(*batch));
    /* Spine staging: SQE scratch for the dep-ordered fault DAG, the
     * staged entries' block keys/spaces (dep-target search), and a
     * taken-mark per batch slot (all worker-private). */
    TpuMemringSqe *sqes = malloc(maxBatch * sizeof(*sqes));
    uint64_t *blockOf = malloc(maxBatch * sizeof(*blockOf));
    UvmVaSpace **vsOf = malloc(maxBatch * sizeof(*vsOf));
    uint8_t *taken = malloc(maxBatch);
    if (!batch || !sqes || !blockOf || !vsOf || !taken) {
        free(batch);
        free(sqes);
        free(blockOf);
        free(vsOf);
        free(taken);
        return NULL;
    }

    static TpuRegCache c_sweep;
    for (;;) {
        /* Reset park gate: no NEW batches while the reset engine holds
         * the pause (a 2 ms poll only while paused — resets are rare
         * and the window short; no wakeup protocol to get wrong). */
        while (atomic_load_explicit(&g_fault.paused,
                                    memory_order_acquire)) {
            atomic_store(&w->servicing, false);
            struct timespec pts = { .tv_sec = 0,
                                    .tv_nsec = 2 * 1000 * 1000 };
            nanosleep(&pts, NULL);
        }
        uint64_t sweepNs = tpuRegCacheGet(&c_sweep,
                                          "uvm_access_counter_sweep_ms",
                                          50) * 1000000ull;
        /* fetch_fault_buffer_entries (:844): block for the first fault,
         * then drain opportunistically up to the batch bound.  Timeouts
         * run the access-counter decay sweep while idle. */
        if (!ring_wait_nonempty(w, sweepNs)) {
            /* Idle: flush any ONCE-deferred wakes (covers transient
             * pending-counter skew and a policy change away from ONCE)
             * and run the decay sweep (worker 0 only — it walks every
             * space and needs no per-block affinity). */
            atomic_store(&w->servicing, false);
            if (w->onceCount) {
                uint64_t tn = uvmMonotonicNs();
                for (uint32_t i = 0; i < w->onceCount; i++)
                    replay_wake(w->onceDeferred[i], tn);
                w->onceCount = 0;
            }
            access_counter_sweep(w);
            continue;
        }
        if (atomic_load_explicit(&g_fault.paused, memory_order_acquire))
            continue;   /* entries stay pending; park at the loop top */
        atomic_store(&w->servicing, true);
        uint32_t n = 0;
        while (n < maxBatch) {
            UvmFaultEntry *e = ring_pop(w);
            if (!e)
                break;
            batch[n++] = e;
        }
        if (n == 0)
            continue;
        uint64_t tBatch0 = uvmMonotonicNs();
        {
            /* Wake-latency histogram: enqueue -> batch pop.  What
             * remains after subtracting this from the headline is
             * engine work.  Armed tracing additionally emits each wake
             * as a span (enqueue on the faulting thread, pop here). */
            TpuHist *wakeHist = tpurmTraceHistRef(TPU_TRACE_FAULT_WAKE);
            bool traced = tpurmTraceIsArmed();
            for (uint32_t i = 0; i < n; i++) {
                tpuHistRecord(wakeHist, tBatch0 - batch[i]->enqueueNs);
                if (traced)
                    tpurmTraceEventAt(TPU_TRACE_FAULT_WAKE,
                                      batch[i]->enqueueNs, tBatch0,
                                      batch[i]->addr, batch[i]->len);
            }
        }
        /* Cross-worker concurrency high-water (observability for the
         * multi-worker module test and procfs): counted only once a
         * real batch is in hand — an empty wake must not inflate the
         * concurrency the test asserts. */
        uint32_t now = atomic_fetch_add_explicit(&g_fault.inService, 1,
                                                 memory_order_acq_rel) + 1;
        uint32_t hw = atomic_load_explicit(&g_fault.serviceHighWater,
                                           memory_order_relaxed);
        while (now > hw &&
               !atomic_compare_exchange_weak_explicit(
                   &g_fault.serviceHighWater, &hw, now,
                   memory_order_acq_rel, memory_order_relaxed)) { }

        /* preprocess_fault_batch (:1134): coalesce duplicates — entries
         * whose page span is covered by an earlier entry of the same
         * space/target ride on that entry's make_resident and only need
         * the replay wake.  (Simple O(n^2) over a small batch.) */
        uint64_t ps = uvmPageSize();
        int32_t dupOf[FAULT_RING_SIZE];
        for (uint32_t i = 0; i < n; i++) {
            dupOf[i] = -1;
            UvmFaultEntry *e = batch[i];
            if (!e)
                continue;
            for (uint32_t j = 0; j < i; j++) {
                UvmFaultEntry *f = batch[j];
                if (f && dupOf[j] < 0 && f->vs == e->vs &&
                    f->source == e->source && f->devInst == e->devInst &&
                    (e->addr & ~(ps - 1)) == (f->addr & ~(ps - 1)) &&
                    e->len <= ps && f->len <= ps) {
                    dupOf[i] = (int32_t)j;
                    /* Upgrade the primary to a write fault if needed. */
                    if (e->isWrite && !f->isWrite)
                        f->isWrite = 1;
                    break;
                }
            }
        }

        /* service_fault_batch (:2232).  Replay policy decides WHEN waiters
         * wake (reference: 4 policies at uvm_gpu_replayable_faults.c:3053):
         *   0 BLOCK       — wake each fault (and its coalesced dups) as
         *                   soon as it is serviced (lowest latency),
         *   1 BATCH       — wake after the whole batch (default),
         *   2 BATCH_FLUSH — like BATCH, but a duplicate-heavy batch first
         *                   drains newly-arrived entries (buffer flush)
         *                   so the re-fault storm collapses into one pass,
         *   3 ONCE        — defer wakes until the ring is fully drained. */
        static TpuRegCache c_policy, c_flushRatio;
        uint32_t policy =
            (uint32_t)tpuRegCacheGet(&c_policy, "uvm_fault_replay_policy",
                                     1);

        /* SPINE SERVICE: the batch's primaries go down the internal
         * memring as a dependency DAG of OP_FAULT SQEs — per-VA-block
         * ordering is an intra-batch dep on the PREVIOUS same-block
         * entry (tracker semantics), not a claimed-whole LINK chain,
         * so different blocks' entries interleave freely across spine
         * workers while a block still never has two entries in flight
         * (the dependent claims only after its predecessor RETIRED —
         * the single-writer perf-state discipline holds).  One
         * submission per batch; only block-CROSSING spans still go
         * down alone in follow-up passes (they could alias other
         * entries' blocks from either side, and the group drain
         * between passes is the ordering barrier).  On an idle ring
         * the submitter claims its own work right back
         * (submit-and-help), so the added cost over the old inline
         * loop is one claim + CQE post per entry. */
        {
            memset(taken, 0, n);
            uint32_t ns = 0;
            for (uint32_t i = 0; i < n; i++) {
                UvmFaultEntry *e = batch[i];
                if (!e || dupOf[i] >= 0 || ns >= maxBatch)
                    continue;
                uint64_t blockIdx = e->addr / UVM_BLOCK_SIZE;
                if ((e->addr + (e->len ? e->len : 1) - 1) /
                        UVM_BLOCK_SIZE != blockIdx)
                    continue;          /* block-crossing: later pass */
                memset(&sqes[ns], 0, sizeof(sqes[ns]));
                sqes[ns].opcode = TPU_MEMRING_OP_FAULT;
                sqes[ns].addr = (uint64_t)(uintptr_t)e;
                sqes[ns].len = e->len ? e->len : 1;
                sqes[ns].userData = e->addr;
                sqes[ns].flowId = e->flow;   /* request identity rides
                                              * the spine SQE */
                for (uint32_t j = ns; j-- > 0;) {
                    if (blockOf[j] == blockIdx && vsOf[j] == e->vs) {
                        tpurmMemringSqeDep(
                            &sqes[ns],
                            TPU_MEMRING_DEP(TPU_MEMRING_DEP_BATCH, j));
                        break;
                    }
                }
                blockOf[ns] = blockIdx;
                vsOf[ns] = e->vs;
                taken[i] = 1;
                ns++;
            }
            if (ns)
                tpurmMemringSubmitInternal(NULL, sqes, ns, NULL,
                                           TPU_MEMRING_SUBSYS_FAULT);
            /* Follow-up passes: each block-crossing span alone (the
             * prior group drained, so nothing it could alias is in
             * flight). */
            for (uint32_t i = 0; i < n; i++) {
                UvmFaultEntry *e = batch[i];
                if (!e || dupOf[i] >= 0 || taken[i])
                    continue;
                memset(&sqes[0], 0, sizeof(sqes[0]));
                sqes[0].opcode = TPU_MEMRING_OP_FAULT;
                sqes[0].addr = (uint64_t)(uintptr_t)e;
                sqes[0].len = e->len ? e->len : 1;
                sqes[0].userData = e->addr;
                sqes[0].flowId = e->flow;
                tpurmMemringSubmitInternal(NULL, sqes, 1, NULL,
                                           TPU_MEMRING_SUBSYS_FAULT);
            }
            /* Dep-cancel leftovers (an upstream same-block entry's
             * failure cancelled its dependents): service inline — the
             * old loop serviced every primary independently, so these
             * must not surface as never-serviced. */
            for (uint32_t i = 0; i < n; i++) {
                UvmFaultEntry *e = batch[i];
                if (e && dupOf[i] < 0 &&
                    e->serviceStatus == (TpuStatus)~0u)
                    uvmFaultServiceExec(e);
            }
        }

        uint32_t dups = 0;
        for (uint32_t i = 0; i < n; i++) {
            UvmFaultEntry *e = batch[i];
            if (!e)
                continue;
            if (dupOf[i] >= 0) {
                dups++;
                continue;
            }
            if (policy == 0) {
                /* BLOCK: replay this fault + its dups immediately.  The
                 * primary's entry lives on the waiter's stack and dies
                 * the moment it wakes — propagate status to dups FIRST,
                 * wake the primary LAST. */
                uint64_t tb = uvmMonotonicNs();
                for (uint32_t j = i + 1; j < n; j++) {
                    if (batch[j] && dupOf[j] == (int32_t)i) {
                        batch[j]->serviceStatus = e->serviceStatus;
                        replay_wake(batch[j], tb);
                        batch[j] = NULL;
                    }
                }
                replay_wake(e, tb);
                batch[i] = NULL;
            }
        }
        /* Duplicates inherit their primary's outcome — including failure,
         * so a failed service propagates to every coalesced waiter. */
        for (uint32_t i = 0; i < n; i++) {
            if (batch[i] && dupOf[i] >= 0)
                batch[i]->serviceStatus = batch[dupOf[i]]->serviceStatus;
        }

        /* BATCH_FLUSH: a duplicate-heavy batch signals a re-fault storm;
         * drain and service what arrived meanwhile before replaying. */
        if (policy == 2 && n > 0 &&
            dups * 100 >= n * tpuRegCacheGet(&c_flushRatio,
                                             "uvm_fault_flush_ratio", 50)) {
            UvmFaultEntry *extra;
            while (n < maxBatch && (extra = ring_pop(w)) != NULL) {
                /* The storm re-faults the just-serviced pages: inherit a
                 * serviced primary's outcome instead of a second full
                 * service pass (the reference's flush replays storms as
                 * duplicates). */
                bool inherited = false;
                for (uint32_t j = 0; j < n; j++) {
                    UvmFaultEntry *f = batch[j];
                    if (f && dupOf[j] < 0 && f->vs == extra->vs &&
                        f->source == extra->source &&
                        f->devInst == extra->devInst &&
                        (extra->addr & ~(ps - 1)) == (f->addr & ~(ps - 1)) &&
                        extra->len <= ps && f->len <= ps &&
                        (!extra->isWrite || f->isWrite)) {
                        extra->serviceStatus = f->serviceStatus;
                        inherited = true;
                        break;
                    }
                }
                if (!inherited) {
                    /* Spine-accounted like every other service: one
                     * single-op FAULT submission (the prior group
                     * already drained, so per-block ordering holds). */
                    TpuMemringSqe fs;
                    memset(&fs, 0, sizeof(fs));
                    fs.opcode = TPU_MEMRING_OP_FAULT;
                    fs.addr = (uint64_t)(uintptr_t)extra;
                    fs.len = extra->len ? extra->len : 1;
                    fs.userData = extra->addr;
                    fs.flowId = extra->flow;
                    tpurmMemringSubmitInternal(NULL, &fs, 1, NULL,
                                               TPU_MEMRING_SUBSYS_FAULT);
                    if (extra->serviceStatus == (TpuStatus)~0u)
                        uvmFaultServiceExec(extra);
                } else {
                    /* Inherited outcomes skip execution; count them
                     * here as the exec path would have. */
                    if (extra->source == UVM_FAULT_SRC_CPU)
                        atomic_fetch_add(&g_fault.faultsCpu, 1);
                    else
                        atomic_fetch_add(&g_fault.faultsDevice, 1);
                }
                dupOf[n] = -1;       /* extras are primaries, never dups */
                batch[n++] = extra;
                tpuCounterAdd("uvm_fault_flush_serviced", 1);
            }
            uvmToolsEmit(NULL, UVM_EVENT_FAULT_BUFFER_FLUSH,
                         UVM_TIER_COUNT, UVM_TIER_COUNT, 0, 0, n);
        }

        uint64_t t1 = uvmMonotonicNs();
        if (policy == 3) {
            /* ONCE: stash wakes until the ring drains (one replay for the
             * whole storm).  The deferred set is bounded by the ring. */
            for (uint32_t i = 0; i < n; i++) {
                if (!batch[i])
                    continue;
                if (w->onceCount < FAULT_RING_SIZE)
                    w->onceDeferred[w->onceCount++] = batch[i];
                else
                    replay_wake(batch[i], t1);   /* overflow: wake now */
            }
            if (__atomic_load_n(&w->pending, __ATOMIC_SEQ_CST) == 0) {
                for (uint32_t i = 0; i < w->onceCount; i++)
                    replay_wake(w->onceDeferred[i], t1);
                w->onceCount = 0;
            }
        } else {
            /* Policy moved off ONCE with wakes still deferred: flush. */
            for (uint32_t i = 0; i < w->onceCount; i++)
                replay_wake(w->onceDeferred[i], t1);
            w->onceCount = 0;
            /* replay (:2986): wake every parked waiter. */
            for (uint32_t i = 0; i < n; i++) {
                if (batch[i])
                    replay_wake(batch[i], t1);
            }
        }
        atomic_fetch_add(&g_fault.batches, 1);
        tpurmTraceEventAt(TPU_TRACE_FAULT_BATCH, tBatch0,
                          uvmMonotonicNs(), w->index, n);
        {
            static _Atomic(_Atomic uint64_t *) ref;
            _Atomic uint64_t *r = atomic_load_explicit(
                &ref, memory_order_relaxed);
            if (!r) {
                r = tpuCounterRef("uvm_fault_batches");
                atomic_store_explicit(&ref, r, memory_order_relaxed);
            }
            if (r)
                atomic_fetch_add_explicit(r, 1, memory_order_relaxed);
        }
        atomic_fetch_sub_explicit(&g_fault.inService, 1,
                                  memory_order_acq_rel);
        atomic_store(&w->servicing, false);
        access_counter_sweep(w);
    }
    return NULL;
}

/* Reset quiesce (reset.c): park the service loop between batches.
 * Pending and newly-arriving faults WAIT (their threads are parked in
 * the SIGSEGV handler / device-fault sync path) until resume — the
 * pause covers only the reset's generation-bump window, so the added
 * latency is the reset itself.  Bounded: gives up waiting for an
 * in-flight batch after timeoutNs (the batch services to HOST under
 * the already-held PM gate, which is safe — same argument as
 * uvmSuspend's trickle faults). */
void uvmFaultServicePause(uint64_t timeoutNs)
{
    if (!g_fault.ready)
        return;
    atomic_store_explicit(&g_fault.paused, 1, memory_order_release);
    uint64_t deadline = uvmMonotonicNs() + timeoutNs;
    while (atomic_load(&g_fault.inService) > 0 &&
           uvmMonotonicNs() < deadline)
        sched_yield();
}

void uvmFaultServiceResume(void)
{
    if (!g_fault.ready)
        return;
    atomic_store_explicit(&g_fault.paused, 0, memory_order_release);
}

/* PM drain barrier: returns once everything enqueued before the call has
 * been serviced (the ring observed empty with no batch in flight).  New
 * CPU faults may arrive afterwards; while suspended they service to the
 * HOST tier only, which is safe with frozen device arenas. */
void uvmFaultRingDrain(void)
{
    if (!g_fault.ready)
        return;
    uint64_t parkedSinceNs = 0;
    for (;;) {
        bool anyBusy = false;
        for (uint32_t i = 0; i < g_fault.nWorkers; i++) {
            FaultWorker *w = &g_fault.workers[i];
            if (atomic_load(&w->servicing) ||
                __atomic_load_n(&w->pending, __ATOMIC_SEQ_CST) != 0) {
                anyBusy = true;
                break;
            }
        }
        if (!anyBusy)
            return;
        /* Reset-park escape: a worker whose spine chains were
         * published just before the pools parked cannot progress until
         * unpark — and unpark needs THIS drain (inside uvmSuspend,
         * inside the reset quiesce) to return.  Its chains execute
         * after resume, to HOST or the restored arenas, which is the
         * same safety argument as the quiesce's trickle faults; waiting
         * here would deadlock the reset.  The plain operator-suspend
         * path never parks, so its drain contract is untouched. */
        if (tpurmMemringSpineParked()) {
            uint64_t now = uvmMonotonicNs();
            if (!parkedSinceNs)
                parkedSinceNs = now;
            else if (now - parkedSinceNs > 100ull * 1000 * 1000) {
                tpuCounterAdd("uvm_fault_drain_park_bails", 1);
                TPU_LOG(TPU_LOG_WARN, "uvm",
                       "fault ring drain: bailing out under reset park "
                       "(queued spine chains service after resume)");
                return;
            }
        } else {
            parkedSinceNs = 0;
        }
        sched_yield();
    }
}

/* Iterate every block of every registered space (spacesLock -> vs lock,
 * the snapshot-rebuild order) calling fn(vs, blk, ctx). */
void uvmFaultForEachSpaceCtx(void (*fn)(UvmVaSpace *vs, UvmVaBlock *blk,
                                        void *ctx), void *ctx)
{
    pthread_mutex_lock(&g_fault.spacesLock);
    for (UvmVaSpace *vs = g_fault.spacesHead; vs; vs = vs->nextSpace) {
        pthread_mutex_lock(&vs->lock);
        tpuLockTrackAcquire(TPU_LOCK_UVM_VASPACE, "pm-iter");
        for (UvmRangeTreeNode *n = vs->ranges.first; n;
             n = uvmRangeTreeNext(n)) {
            UvmVaRange *r = (UvmVaRange *)n;
            for (uint32_t b = 0; b < r->blockCount; b++) {
                if (r->blocks[b])
                    fn(vs, r->blocks[b], ctx);
            }
        }
        tpuLockTrackRelease(TPU_LOCK_UVM_VASPACE, "pm-iter");
        pthread_mutex_unlock(&vs->lock);
    }
    pthread_mutex_unlock(&g_fault.spacesLock);
}

static void foreach_nullctx_tramp(UvmVaSpace *vs, UvmVaBlock *blk,
                                  void *ctx)
{
    void (*fn)(UvmVaSpace *, UvmVaBlock *) =
        (void (*)(UvmVaSpace *, UvmVaBlock *))(uintptr_t)ctx;
    fn(vs, blk);
}

void uvmFaultForEachSpace(void (*fn)(UvmVaSpace *vs, UvmVaBlock *blk))
{
    uvmFaultForEachSpaceCtx(foreach_nullctx_tramp, (void *)(uintptr_t)fn);
}

/* ------------------------------------------------------- SIGSEGV handler */

static void fault_fallback(int sig, siginfo_t *si, void *uctx)
{
    /* Not ours: chain to the previously-installed disposition WITHOUT
     * uninstalling the UVM handler.  Swapping dispositions here would be
     * (a) racy against other threads taking managed faults concurrently
     * and (b) permanent — if the old handler absorbs the fault, all later
     * managed faults would bypass the engine and crash.  Only when the old
     * disposition is SIG_DFL/SIG_IGN do we reinstall default and return:
     * the instruction re-faults and the process dies with the real fault
     * (we are on the way down anyway). */
    struct sigaction *old = &g_fault.oldSegv;
    /* sa_handler/sa_sigaction share a union: screen out SIG_DFL/SIG_IGN
     * before treating either field as a callable pointer (SIG_IGN is
     * (void *)1 and can legally appear even with SA_SIGINFO set). */
    if (old->sa_handler != SIG_DFL && old->sa_handler != SIG_IGN) {
        if (old->sa_flags & SA_SIGINFO)
            old->sa_sigaction(sig, si, uctx);
        else
            old->sa_handler(sig);
        return;
    }
    /* Last gasp before the process dies on the re-fault.  Order
     * matters and every step degrades independently:
     *
     *   1. tpubox crash bundle — the whole point of the black box.
     *      Emit + dump are async-signal-safe by construction (atomics,
     *      write/rename, pre-resolved counter cells).  If the fault
     *      happened INSIDE the dumper, its recursion guard returns
     *      TPU_ERR_STATE_IN_USE instead of re-entering — we fall
     *      through to the legacy stderr path rather than recurse.
     *   2. One stderr line via the signal-safe tpuDump formatters
     *      (no snprintf: glibc's printf family takes locks and can
     *      malloc for wide output).
     *   3. A native backtrace — backtrace_symbols_fd is technically
     *      async-signal-unsafe (first call can dlopen libgcc), so
     *      fault_engine_init_once warms it at startup; by here the
     *      alternative is dying silently. */
    {
        tpurmJournalCrashDump("sigsegv");
        TpuDumpCur c = { .fd = 2 };
        tpuDumpStr(&c, "tpurm FATAL: unhandled SIGSEGV at ");
        tpuDumpHex(&c, (uint64_t)(uintptr_t)(si ? si->si_addr : NULL));
        tpuDumpStr(&c, "\n");
        tpuDumpFlush(&c);
        void *frames[32];
        int nf = backtrace(frames, 32);
        backtrace_symbols_fd(frames, nf, 2);
    }
    signal(sig, SIG_DFL);
}

static void segv_handler(int sig, siginfo_t *si, void *uctx)
{
    uintptr_t addr = (uintptr_t)si->si_addr;
    UvmVaSpace *vs = addr ? snapshot_lookup_acquire(addr) : NULL;
    pid_t tid = (pid_t)syscall(SYS_gettid);
    if (!vs) {
        fault_fallback(sig, si, uctx);
        return;
    }
    /* A fault ON a service worker is a real bug (it would deadlock its
     * own ring): fall through.  Worker tids are written once at thread
     * start; a reader racing that assignment just misses the match,
     * which is the pre-existing window for any brand-new thread. */
    for (uint32_t i = 0; i < g_fault.nWorkers; i++) {
        if (tid == atomic_load_explicit(&g_fault.workers[i].tid,
                                        memory_order_relaxed)) {
            snapshot_release();
            fault_fallback(sig, si, uctx);
            return;
        }
    }

    int isWrite = 1;
#ifdef __x86_64__
    /* Page-fault error code bit 1 = write access.  Sandboxed kernels
     * zero REG_ERR entirely; real kernels always set the USER bit for
     * user-space faults, so ANY nonzero value proves the field works
     * and lets the service skip its write-inference fallback. */
    ucontext_t *uc = uctx;
    uint64_t err = (uint64_t)uc->uc_mcontext.gregs[REG_ERR];
    if (err)
        atomic_store_explicit(&g_fault.regErrWorks, 1,
                              memory_order_relaxed);
    isWrite = (err & 0x2) != 0;
#else
    (void)uctx;
#endif

    /* Per-fault state on the (signal) stack — the thread parks here until
     * the service loop replays it, so the storage stays live. */
    uint32_t done = 0;
    UvmFaultEntry entry = {
        .addr = addr,
        .len = 1,
        .isWrite = (uint8_t)isWrite,
        .source = UVM_FAULT_SRC_CPU,
        .devInst = 0,
        .vs = vs,
        .enqueueNs = uvmMonotonicNs(),
        /* Faulting thread's request identity (initial-exec TLS: no
         * lazy allocation inside the signal handler). */
        .flow = tpurmTraceFlowGet(),
        .serviceStatus = (TpuStatus)~0u,
        .doneWord = &done,
    };
    ring_push(worker_for(addr), &entry);
    for (;;) {
        uint32_t v = __atomic_load_n(&done, __ATOMIC_SEQ_CST);
        if (v != 0) {
            snapshot_release();
            if (v == 2)
                fault_fallback(sig, si, uctx); /* unserviceable */
            return;
        }
        futex_call(&done, FUTEX_WAIT, 0);
    }
}

/* ---------------------------------------------------------------- init */

static void fault_engine_init_once(void)
{
    pthread_mutex_init(&g_fault.spacesLock, NULL);
    /* Worker count (reference: one bottom half per GPU): default scales
     * with the device count but never past the online CPUs — extra
     * workers on a starved box only add preemption to the tail
     * latency.  Registry uvm_fault_service_threads overrides. */
    uint32_t ndev = tpurmDeviceCount();
    long ncpu = sysconf(_SC_NPROCESSORS_ONLN);
    uint32_t dflt = ndev < 2 ? 2 : ndev;
    if (ncpu > 0 && dflt > (uint32_t)ncpu)
        dflt = (uint32_t)ncpu;
    uint32_t nw = (uint32_t)tpuRegistryGet("uvm_fault_service_threads",
                                           dflt);
    if (nw < 1)
        nw = 1;
    if (nw > FAULT_MAX_WORKERS)
        nw = FAULT_MAX_WORKERS;
    g_fault.nWorkers = nw;
    for (uint32_t wi = 0; wi < nw; wi++) {
        FaultWorker *w = &g_fault.workers[wi];
        w->index = wi;
        for (uint64_t i = 0; i < FAULT_RING_SIZE; i++)
            atomic_store(&w->ring[i].seq, i);
        if (pthread_create(&w->thread, NULL, fault_service_thread, w) != 0) {
            TPU_LOG(TPU_LOG_ERROR, "uvm",
                   "fault service worker %u create failed", wi);
            if (wi == 0)
                return;          /* no engine without at least one */
            g_fault.nWorkers = wi;
            break;
        }
    }
    /* Warm libgcc's unwinder outside signal context: the FIRST
     * backtrace() call may dlopen/malloc, which the last-gasp handler
     * must never do.  After this, in-signal backtrace only walks
     * frames. */
    {
        void *warm[4];
        (void)backtrace(warm, 4);
    }
    struct sigaction sa;
    memset(&sa, 0, sizeof(sa));
    sa.sa_sigaction = segv_handler;
    sa.sa_flags = SA_SIGINFO;
    sigemptyset(&sa.sa_mask);
    if (sigaction(SIGSEGV, &sa, &g_fault.oldSegv) != 0) {
        TPU_LOG(TPU_LOG_ERROR, "uvm", "SIGSEGV handler install failed");
        return;
    }
    g_fault.ready = true;
    TPU_LOG(TPU_LOG_INFO, "uvm",
           "fault engine ready (software replayable faults, ring=%d, "
           "workers=%u)", FAULT_RING_SIZE, g_fault.nWorkers);
}

void uvmFaultEngineInit(void)
{
    pthread_once(&g_fault.once, fault_engine_init_once);
}

/* Wait one entry's doneWord; returns its resolved status. */
static TpuStatus sync_wait_entry(UvmFaultEntry *e, uint32_t *done)
{
    for (;;) {
        uint32_t v = __atomic_load_n(done, __ATOMIC_SEQ_CST);
        if (v != 0)
            return e->serviceStatus == (TpuStatus)~0u
                       ? (v == 1 ? TPU_OK : TPU_ERR_INVALID_STATE)
                       : e->serviceStatus;
        futex_call(done, FUTEX_WAIT, 0);
    }
}

/* Enqueue-and-wait protocol for one entry on its block's worker. */
static TpuStatus sync_push_and_wait(UvmFaultEntry *e)
{
    uint32_t done = 0;
    e->doneWord = &done;
    e->enqueueNs = uvmMonotonicNs();
    /* tpuflow: callers that built the entry without an identity
     * inherit the submitting thread's flow context (device accesses
     * issued under a flow-scoped PREFETCH exec, sched-side touches). */
    if (!e->flow)
        e->flow = tpurmTraceFlowGet();
    e->serviceStatus = (TpuStatus)~0u;
    ring_push(worker_for(e->addr), e);
    return sync_wait_entry(e, &done);
}

TpuStatus uvmFaultServiceSync(UvmFaultEntry *e)
{
    uvmFaultEngineInit();
    if (!g_fault.ready)
        return TPU_ERR_INVALID_STATE;
    /* tpuflow: stamp the submitting thread's identity HERE so the
     * multi-block split below inherits it too (subs copy *e). */
    if (!e->flow)
        e->flow = tpurmTraceFlowGet();

    /* Worker assignment is per 2 MB block; a span crossing blocks that
     * hash to different workers is SPLIT into per-block sub-entries so
     * each worker only ever touches its own blocks' perf state (and the
     * sub-services run concurrently — the parallel win for large
     * device_access spans). */
    uint64_t start = e->addr;
    uint64_t end = e->addr + (e->len ? e->len : 1) - 1;
    uint64_t firstBlock = start / UVM_BLOCK_SIZE;
    uint64_t lastBlock = end / UVM_BLOCK_SIZE;

    if (firstBlock == lastBlock || g_fault.nWorkers == 1)
        return sync_push_and_wait(e);

    uint64_t nsub = lastBlock - firstBlock + 1;
    UvmFaultEntry *subs = malloc(nsub * (sizeof(UvmFaultEntry) +
                                         sizeof(uint32_t)));
    if (!subs) {
        /* Degrade: service block-by-block SEQUENTIALLY, each sub-span
         * on its own block's worker — slower, but the single-writer
         * per-block invariant (perf state) is preserved. */
        TpuStatus st = TPU_OK;
        for (uint64_t b = firstBlock; b <= lastBlock; b++) {
            uint64_t bStart = b * UVM_BLOCK_SIZE;
            uint64_t bEnd = bStart + UVM_BLOCK_SIZE - 1;
            uint64_t lo = start > bStart ? start : bStart;
            uint64_t hi = end < bEnd ? end : bEnd;
            UvmFaultEntry sub = *e;
            sub.addr = lo;
            sub.len = hi - lo + 1;
            TpuStatus s = sync_push_and_wait(&sub);
            if (s != TPU_OK && st == TPU_OK)
                st = s;
        }
        return st;
    }
    uint32_t *dones = (uint32_t *)(subs + nsub);
    uint64_t now = uvmMonotonicNs();
    for (uint64_t i = 0; i < nsub; i++) {
        uint64_t bStart = (firstBlock + i) * UVM_BLOCK_SIZE;
        uint64_t bEnd = bStart + UVM_BLOCK_SIZE - 1;
        uint64_t lo = start > bStart ? start : bStart;
        uint64_t hi = end < bEnd ? end : bEnd;
        subs[i] = *e;
        subs[i].addr = lo;
        subs[i].len = hi - lo + 1;
        subs[i].enqueueNs = now;
        subs[i].serviceStatus = (TpuStatus)~0u;
        dones[i] = 0;
        subs[i].doneWord = &dones[i];
        ring_push(worker_for(lo), &subs[i]);
    }
    TpuStatus st = TPU_OK;
    for (uint64_t i = 0; i < nsub; i++) {
        TpuStatus s = sync_wait_entry(&subs[i], &dones[i]);
        if (s != TPU_OK && st == TPU_OK)
            st = s;
    }
    free(subs);
    return st;
}

/* Owner-engine side of a forwarded remote CPU fault: service the span
 * in the OWNING space (host target — device-resident pages migrate
 * home into the shared backing the remote window maps). */
TpuStatus uvmRemoteFaultService(uint64_t addr, uint64_t len, int isWrite)
{
    uvmFaultEngineInit();
    UvmVaSpace *vs = uvmFaultSpaceForAddr(addr);
    if (!vs)
        return TPU_ERR_INVALID_ADDRESS;
    UvmFaultEntry e = {
        .addr = addr,
        .len = len ? len : 1,
        .isWrite = (uint8_t)(isWrite != 0),
        .source = UVM_FAULT_SRC_CPU,
        .devInst = 0,
        .vs = vs,
    };
    uvmPmEnterShared();
    TpuStatus st = uvmFaultServiceSync(&e);
    uvmPmExitShared();
    return st;
}

TpuStatus uvmDeviceAccess(UvmVaSpace *vs, uint32_t devInst, void *base,
                          uint64_t len, int isWrite)
{
    if (!vs || !base || len == 0)
        return TPU_ERR_INVALID_ARGUMENT;
    if (!tpurmDeviceGet(devInst))
        return TPU_ERR_INVALID_DEVICE;
    /* Non-managed span: the pageable/ATS path (uvm_hmm.c) services it
     * in place when HMM is enabled (reference: pageable faults route to
     * HMM/ATS, service_fault_batch_dispatch). */
    pthread_mutex_lock(&vs->lock);
    tpuLockTrackAcquire(TPU_LOCK_UVM_VASPACE, "dev-access");
    bool managed = uvmRangeTreeIterFirst(&vs->ranges, (uintptr_t)base,
                                         (uintptr_t)base + len - 1) != NULL;
    tpuLockTrackRelease(TPU_LOCK_UVM_VASPACE, "dev-access");
    pthread_mutex_unlock(&vs->lock);
    if (!managed) {
        uvmPmEnterShared();
        TpuStatus ps = uvmPageableDeviceAccess(vs, devInst, base, len,
                                               isWrite);
        uvmPmExitShared();
        return ps;
    }

    UvmFaultEntry e = {
        .addr = (uintptr_t)base,
        .len = len,
        .isWrite = (uint8_t)(isWrite != 0),
        .source = UVM_FAULT_SRC_DEVICE,
        .devInst = devInst,
        .vs = vs,
    };
    /* PM gate: device accesses block while suspended (uvm_lock.h:43-49
     * global power management lock, shared side). */
    uvmPmEnterShared();
    TpuStatus st = uvmFaultServiceSync(&e);
    uvmPmExitShared();
    return st;
}

/* Multi-worker observability (module test + procfs). */
uint32_t uvmFaultWorkerCount(void)
{
    return g_fault.nWorkers;
}

uint32_t uvmFaultServiceHighWater(void)
{
    return atomic_load_explicit(&g_fault.serviceHighWater,
                                memory_order_acquire);
}
