/*
 * VA range tree: non-overlapping [start, end] intervals in an AVL tree
 * keyed by start, with a threaded in-order list for O(1) neighbor walks.
 *
 * Re-design of the reference's uvm_range_tree
 * (kernel-open/nvidia-uvm/uvm_range_tree.{c,h} — rbtree-based there); the
 * API shape (add returns error on overlap, find by address, bounded
 * iteration) is preserved because the VA space and HBM-window bookkeeping
 * are written against it.  Page masks live here too: they are the other
 * core container of the block state machine (reference: uvm_page_mask_*
 * in uvm_va_block_types.h).
 */
#include "uvm_internal.h"

#include <string.h>

/* ------------------------------------------------------------ page masks */

/* Single-bit and range primitives are inline in uvm_internal.h; only the
 * search helpers stay out of line. */

uint32_t uvmPageMaskFindSet(const UvmPageMask *m, uint32_t npages,
                            uint32_t from)
{
    for (uint32_t p = from; p < npages; p++)
        if (uvmPageMaskTest(m, p))
            return p;
    return npages;
}

uint32_t uvmPageMaskFindClear(const UvmPageMask *m, uint32_t npages,
                              uint32_t from)
{
    for (uint32_t p = from; p < npages; p++)
        if (!uvmPageMaskTest(m, p))
            return p;
    return npages;
}

/* ---------------------------------------------------------- AVL plumbing */

static int node_height(UvmRangeTreeNode *n)
{
    return n ? n->height : 0;
}

static void node_fix(UvmRangeTreeNode *n)
{
    int hl = node_height(n->left), hr = node_height(n->right);
    n->height = 1 + (hl > hr ? hl : hr);
}

static int node_balance(UvmRangeTreeNode *n)
{
    return node_height(n->left) - node_height(n->right);
}

static void replace_child(UvmRangeTree *t, UvmRangeTreeNode *parent,
                          UvmRangeTreeNode *oldc, UvmRangeTreeNode *newc)
{
    if (!parent)
        t->root = newc;
    else if (parent->left == oldc)
        parent->left = newc;
    else
        parent->right = newc;
    if (newc)
        newc->parent = parent;
}

static UvmRangeTreeNode *rotate_left(UvmRangeTree *t, UvmRangeTreeNode *n)
{
    UvmRangeTreeNode *r = n->right;
    replace_child(t, n->parent, n, r);
    n->right = r->left;
    if (n->right)
        n->right->parent = n;
    r->left = n;
    n->parent = r;
    node_fix(n);
    node_fix(r);
    return r;
}

static UvmRangeTreeNode *rotate_right(UvmRangeTree *t, UvmRangeTreeNode *n)
{
    UvmRangeTreeNode *l = n->left;
    replace_child(t, n->parent, n, l);
    n->left = l->right;
    if (n->left)
        n->left->parent = n;
    l->right = n;
    n->parent = l;
    node_fix(n);
    node_fix(l);
    return l;
}

static void rebalance_up(UvmRangeTree *t, UvmRangeTreeNode *n)
{
    while (n) {
        node_fix(n);
        int b = node_balance(n);
        if (b > 1) {
            if (node_balance(n->left) < 0)
                rotate_left(t, n->left);
            n = rotate_right(t, n);
        } else if (b < -1) {
            if (node_balance(n->right) > 0)
                rotate_right(t, n->right);
            n = rotate_left(t, n);
        }
        n = n->parent;
    }
}

/* -------------------------------------------------------------- tree API */

void uvmRangeTreeInit(UvmRangeTree *t)
{
    t->root = NULL;
    t->first = NULL;
}

TpuStatus uvmRangeTreeAdd(UvmRangeTree *t, UvmRangeTreeNode *n)
{
    if (n->end < n->start)
        return TPU_ERR_INVALID_ARGUMENT;

    UvmRangeTreeNode *parent = NULL, *cur = t->root;
    UvmRangeTreeNode *pred = NULL, *succ = NULL;
    while (cur) {
        parent = cur;
        if (n->start < cur->start) {
            succ = cur;
            cur = cur->left;
        } else {
            pred = cur;
            cur = cur->right;
        }
    }
    /* Overlap check against in-order neighbors. */
    if (pred && pred->end >= n->start)
        return TPU_ERR_STATE_IN_USE;
    if (succ && succ->start <= n->end)
        return TPU_ERR_STATE_IN_USE;

    n->left = n->right = NULL;
    n->parent = parent;
    n->height = 1;
    if (!parent)
        t->root = n;
    else if (n->start < parent->start)
        parent->left = n;
    else
        parent->right = n;

    /* Thread the in-order list. */
    n->prev = pred;
    n->next = succ;
    if (pred)
        pred->next = n;
    else
        t->first = n;
    if (succ)
        succ->prev = n;

    rebalance_up(t, parent);
    return TPU_OK;
}

void uvmRangeTreeRemove(UvmRangeTree *t, UvmRangeTreeNode *n)
{
    /* Unthread the list first. */
    if (n->prev)
        n->prev->next = n->next;
    else
        t->first = n->next;
    if (n->next)
        n->next->prev = n->prev;

    UvmRangeTreeNode *rebalance_from;
    if (!n->left || !n->right) {
        UvmRangeTreeNode *child = n->left ? n->left : n->right;
        rebalance_from = n->parent;
        replace_child(t, n->parent, n, child);
    } else {
        /* Splice the in-order successor (leftmost of right subtree). */
        UvmRangeTreeNode *s = n->next;   /* guaranteed inside right subtree */
        if (s->parent == n) {
            rebalance_from = s;
        } else {
            rebalance_from = s->parent;
            replace_child(t, s->parent, s, s->right);
            s->right = n->right;
            s->right->parent = s;
        }
        s->left = n->left;
        s->left->parent = s;
        replace_child(t, n->parent, n, s);
        node_fix(s);
    }
    rebalance_up(t, rebalance_from);
    n->left = n->right = n->parent = n->prev = n->next = NULL;
}

UvmRangeTreeNode *uvmRangeTreeFind(UvmRangeTree *t, uint64_t addr)
{
    UvmRangeTreeNode *cur = t->root;
    while (cur) {
        if (addr < cur->start)
            cur = cur->left;
        else if (addr > cur->end)
            cur = cur->right;
        else
            return cur;
    }
    return NULL;
}

UvmRangeTreeNode *uvmRangeTreeIterFirst(UvmRangeTree *t, uint64_t start,
                                        uint64_t end)
{
    /* Smallest node with node->end >= start, then check window. */
    UvmRangeTreeNode *cur = t->root, *best = NULL;
    while (cur) {
        if (cur->end >= start) {
            best = cur;
            cur = cur->left;
        } else {
            cur = cur->right;
        }
    }
    if (best && best->start <= end)
        return best;
    return NULL;
}

UvmRangeTreeNode *uvmRangeTreeIterNext(UvmRangeTreeNode *n, uint64_t end)
{
    UvmRangeTreeNode *nx = n->next;
    if (nx && nx->start <= end)
        return nx;
    return NULL;
}

UvmRangeTreeNode *uvmRangeTreeNext(UvmRangeTreeNode *n)
{
    return n->next;
}
