/*
 * Explicit migration — the UVM_MIGRATE path.
 *
 * Re-design of the reference's uvm_migrate.c (uvm_migrate:635 →
 * uvm_migrate_ranges:555 → uvm_va_range_migrate:504 → per-2MB
 * uvm_va_block_migrate_locked): iterate ranges intersecting the span,
 * honor range-group migration fences, and drive each covered block's
 * make_resident.  Copies pipeline inside a block (channel pushes with one
 * tracker wait); the ASYNC flag is accepted and currently serviced
 * synchronously (a synchronous completion is a valid strengthening of the
 * reference's async contract — its semaphore-release path,
 * uvm_migrate.c:735, fires on completion, which here is at return).
 */
#include "uvm_internal.h"
#include "tpurm/memring.h"
#include "tpurm/trace.h"

#include <string.h>

TpuStatus uvmMigrateExec(UvmVaSpace *vs, void *base, uint64_t len,
                         UvmLocation dst, uint32_t flags)
{
    (void)flags;
    if (!vs || !base || len == 0)
        return TPU_ERR_INVALID_ARGUMENT;
    if (dst.tier >= UVM_TIER_COUNT)
        return TPU_ERR_INVALID_ARGUMENT;
    if (dst.tier == UVM_TIER_HBM && !tpurmDeviceGet(dst.devInst))
        return TPU_ERR_INVALID_DEVICE;

    uint64_t ps = uvmPageSize();
    uint64_t start = (uintptr_t)base & ~(ps - 1);
    uint64_t end = ((uintptr_t)base + len - 1) | (ps - 1);

    uint64_t tSpan = tpurmTraceBegin();
    /* PM gate (shared): migrations block while suspended
     * (uvm_lock.h:43-49 global power management lock). */
    uvmPmEnterShared();
    pthread_mutex_lock(&vs->lock);
    tpuLockTrackAcquire(TPU_LOCK_UVM_VASPACE, "vaspace");

    UvmRangeTreeNode *n = uvmRangeTreeIterFirst(&vs->ranges, start, end);
    if (!n) {
        tpuLockTrackRelease(TPU_LOCK_UVM_VASPACE, "vaspace");
        pthread_mutex_unlock(&vs->lock);
        uvmPmExitShared();
        return TPU_ERR_OBJECT_NOT_FOUND;
    }

    TpuStatus st = TPU_OK;
    while (n) {
        UvmVaRange *range = (UvmVaRange *)n;
        if (range->type != UVM_RANGE_TYPE_MANAGED) {
            /* External ranges have no migration state (reference:
             * uvm_migrate rejects non-managed VA with INVALID_ADDRESS). */
            st = TPU_ERR_INVALID_ADDRESS;
            break;
        }
        if (!uvmRangeGroupMigratable(vs, range->rangeGroupId)) {
            /* Fenced by UvmPreventMigrationRangeGroups: skip, not error
             * (reference returns success and leaves pages in place). */
            n = uvmRangeTreeIterNext(n, end);
            continue;
        }
        uint64_t rStart = start > n->start ? start : n->start;
        uint64_t rEnd = end < n->end ? end : n->end;
        uint32_t firstBlock = (uint32_t)((rStart - n->start) / UVM_BLOCK_SIZE);
        uint32_t lastBlock = (uint32_t)((rEnd - n->start) / UVM_BLOCK_SIZE);
        for (uint32_t bi = firstBlock; bi <= lastBlock && st == TPU_OK; bi++) {
            UvmVaBlock *blk = range->blocks[bi];
            uint64_t bStart = blk->start;
            uint64_t bEnd = blk->start + (uint64_t)blk->npages * ps - 1;
            uint64_t cStart = rStart > bStart ? rStart : bStart;
            uint64_t cEnd = rEnd < bEnd ? rEnd : bEnd;
            if (cStart > cEnd)
                continue;
            uint32_t firstPage = (uint32_t)((cStart - bStart) / ps);
            uint32_t count = (uint32_t)((cEnd - cStart) / ps) + 1;
            st = uvmBlockMakeResident(blk, dst, firstPage, count,
                                      /*forWrite=*/true);
        }
        if (st != TPU_OK)
            break;
        uvmToolsEmit(vs, UVM_EVENT_MIGRATION, UVM_TIER_COUNT /* mixed */,
                     dst.tier, dst.devInst, rStart, rEnd - rStart + 1);
        n = uvmRangeTreeIterNext(n, end);
    }

    tpuLockTrackRelease(TPU_LOCK_UVM_VASPACE, "vaspace");
    pthread_mutex_unlock(&vs->lock);
    uvmPmExitShared();
    tpuCounterAdd("uvm_migrate_calls", 1);
    if (tSpan)
        tpurmTraceEnd(TPU_TRACE_MIGRATE, tSpan, (uintptr_t)base, len);
    return st;
}

/* Bytes of [start, end] NOT already resident at dst — the fused-evict
 * trigger keys on the span's actual allocation NEED, not raw arena
 * occupancy: re-migrating an already-resident span under a full arena
 * must not demote LRU victims for a no-op.  Approximate by design
 * (masks read under the vs lock only; concurrent per-block service can
 * skew a snapshot) — this is a pressure heuristic, the engine's own
 * pressure path stays the correctness backstop. */
static uint64_t span_nonresident_bytes(UvmVaSpace *vs, uint64_t start,
                                       uint64_t end, UvmLocation dst)
{
    uint64_t ps = uvmPageSize();
    uint64_t need = 0;
    pthread_mutex_lock(&vs->lock);
    for (UvmRangeTreeNode *n = uvmRangeTreeIterFirst(&vs->ranges, start,
                                                     end);
         n; n = uvmRangeTreeIterNext(n, end)) {
        UvmVaRange *range = (UvmVaRange *)n;
        if (range->type != UVM_RANGE_TYPE_MANAGED)
            continue;
        uint64_t rStart = start > n->start ? start : n->start;
        uint64_t rEnd = end < n->end ? end : n->end;
        uint32_t firstBlock =
            (uint32_t)((rStart - n->start) / UVM_BLOCK_SIZE);
        uint32_t lastBlock =
            (uint32_t)((rEnd - n->start) / UVM_BLOCK_SIZE);
        for (uint32_t bi = firstBlock; bi <= lastBlock; bi++) {
            UvmVaBlock *blk = range->blocks[bi];
            if (!blk)
                continue;
            uint64_t bEnd = blk->start + (uint64_t)blk->npages * ps - 1;
            uint64_t lo = rStart > blk->start ? rStart : blk->start;
            uint64_t hi = rEnd < bEnd ? rEnd : bEnd;
            if (lo > hi)
                continue;
            /* A block homed on a different HBM device re-migrates
             * wholesale (single-device rule). */
            bool wrongDev = dst.tier == UVM_TIER_HBM &&
                            blk->hbmRuns && blk->hbmDevInst != dst.devInst;
            uint32_t p0 = (uint32_t)((lo - blk->start) / ps);
            uint32_t p1 = (uint32_t)((hi - blk->start) / ps);
            for (uint32_t p = p0; p <= p1; p++)
                if (wrongDev ||
                    !uvmPageMaskTest(&blk->resident[dst.tier], p))
                    need += ps;
        }
    }
    pthread_mutex_unlock(&vs->lock);
    return need;
}

/* The public entry is a SUBMISSION-SPINE wrapper: the span goes down
 * as one MIGRATE SQE on the internal memring (the worker that claims
 * it runs uvmMigrateExec, coalescing virtually-contiguous sibling
 * submissions into one engine walk), prefixed — when the destination
 * arena cannot take the span — by a TIER_EVICT the MIGRATE carries a
 * DEPENDENCY on (tracker semantics, not a claimed-whole LINK chain):
 * the upload still starts only after the demote retired, but OTHER
 * workers stream past the pair instead of queueing behind one
 * worker's two-op claim.  The evict is best-effort and always retires
 * OK, so the dep can never cancel the upload; interleaved traffic
 * stealing the evicted space before the upload lands just re-enters
 * the engine's own pressure path (same contract as PR 10's fused
 * chain).  Semantics match the old direct call: synchronous, same
 * status; argument validation stays up front so obvious misuse fails
 * without a ring round-trip. */
TpuStatus uvmMigrate(UvmVaSpace *vs, void *base, uint64_t len,
                     UvmLocation dst, uint32_t flags)
{
    if (!vs || !base || len == 0)
        return TPU_ERR_INVALID_ARGUMENT;
    if (dst.tier >= UVM_TIER_COUNT)
        return TPU_ERR_INVALID_ARGUMENT;
    if (dst.tier == UVM_TIER_HBM && !tpurmDeviceGet(dst.devInst))
        return TPU_ERR_INVALID_DEVICE;

    TpuMemringSqe sqes[2];
    TpuStatus sts[2] = { TPU_OK, TPU_OK };
    uint32_t n = 0;
    memset(sqes, 0, sizeof(sqes));

    static TpuRegCache c_fused;
    if (tpuRegCacheGet(&c_fused, "memring_fused_evict", 1) &&
        (dst.tier == UVM_TIER_HBM || dst.tier == UVM_TIER_CXL)) {
        UvmTierArena *arena = dst.tier == UVM_TIER_HBM
                                  ? uvmTierArenaHbm(dst.devInst)
                                  : uvmTierArenaCxl();
        if (arena) {
            uint64_t ps = uvmPageSize();
            uint64_t start = (uintptr_t)base & ~(ps - 1);
            uint64_t end = ((uintptr_t)base + len - 1) | (ps - 1);
            uint64_t need = span_nonresident_bytes(vs, start, end, dst);
            if (need &&
                arena->size - uvmPmmAllocatedBytes(&arena->pmm) < need) {
                sqes[n].opcode = TPU_MEMRING_OP_TIER_EVICT;
                sqes[n].dstTier = (uint16_t)dst.tier;
                sqes[n].devInst = dst.devInst;
                sqes[n].len = need;
                n++;
                tpuCounterAdd("memring_fused_evictions", 1);
            }
        }
    }
    sqes[n].opcode = TPU_MEMRING_OP_MIGRATE;
    sqes[n].dstTier = (uint16_t)dst.tier;
    sqes[n].devInst = dst.devInst;
    sqes[n].addr = (uint64_t)(uintptr_t)base;
    sqes[n].len = len;
    sqes[n].arg1 = flags;
    if (n > 0)
        /* Fused pair as a DAG edge: upload-after-demote, expressed as
         * an intra-batch dep on the evict half (index 0). */
        tpurmMemringSqeDep(&sqes[n],
                           TPU_MEMRING_DEP(TPU_MEMRING_DEP_BATCH, 0));
    n++;

    tpurmMemringSubmitInternal(vs, sqes, n, sts,
                               TPU_MEMRING_SUBSYS_MIGRATE);
    /* The MIGRATE's own status is the call's result (the fused evict
     * half is best-effort by contract, and a cancelled chain already
     * lands INVALID_STATE in the migrate's slot). */
    return sts[n - 1];
}
