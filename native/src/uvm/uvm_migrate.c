/*
 * Explicit migration — the UVM_MIGRATE path.
 *
 * Re-design of the reference's uvm_migrate.c (uvm_migrate:635 →
 * uvm_migrate_ranges:555 → uvm_va_range_migrate:504 → per-2MB
 * uvm_va_block_migrate_locked): iterate ranges intersecting the span,
 * honor range-group migration fences, and drive each covered block's
 * make_resident.  Copies pipeline inside a block (channel pushes with one
 * tracker wait); the ASYNC flag is accepted and currently serviced
 * synchronously (a synchronous completion is a valid strengthening of the
 * reference's async contract — its semaphore-release path,
 * uvm_migrate.c:735, fires on completion, which here is at return).
 */
#include "uvm_internal.h"
#include "tpurm/trace.h"

TpuStatus uvmMigrate(UvmVaSpace *vs, void *base, uint64_t len,
                     UvmLocation dst, uint32_t flags)
{
    (void)flags;
    if (!vs || !base || len == 0)
        return TPU_ERR_INVALID_ARGUMENT;
    if (dst.tier >= UVM_TIER_COUNT)
        return TPU_ERR_INVALID_ARGUMENT;
    if (dst.tier == UVM_TIER_HBM && !tpurmDeviceGet(dst.devInst))
        return TPU_ERR_INVALID_DEVICE;

    uint64_t ps = uvmPageSize();
    uint64_t start = (uintptr_t)base & ~(ps - 1);
    uint64_t end = ((uintptr_t)base + len - 1) | (ps - 1);

    uint64_t tSpan = tpurmTraceBegin();
    /* PM gate (shared): migrations block while suspended
     * (uvm_lock.h:43-49 global power management lock). */
    uvmPmEnterShared();
    pthread_mutex_lock(&vs->lock);
    tpuLockTrackAcquire(TPU_LOCK_UVM_VASPACE, "vaspace");

    UvmRangeTreeNode *n = uvmRangeTreeIterFirst(&vs->ranges, start, end);
    if (!n) {
        tpuLockTrackRelease(TPU_LOCK_UVM_VASPACE, "vaspace");
        pthread_mutex_unlock(&vs->lock);
        uvmPmExitShared();
        return TPU_ERR_OBJECT_NOT_FOUND;
    }

    TpuStatus st = TPU_OK;
    while (n) {
        UvmVaRange *range = (UvmVaRange *)n;
        if (range->type != UVM_RANGE_TYPE_MANAGED) {
            /* External ranges have no migration state (reference:
             * uvm_migrate rejects non-managed VA with INVALID_ADDRESS). */
            st = TPU_ERR_INVALID_ADDRESS;
            break;
        }
        if (!uvmRangeGroupMigratable(vs, range->rangeGroupId)) {
            /* Fenced by UvmPreventMigrationRangeGroups: skip, not error
             * (reference returns success and leaves pages in place). */
            n = uvmRangeTreeIterNext(n, end);
            continue;
        }
        uint64_t rStart = start > n->start ? start : n->start;
        uint64_t rEnd = end < n->end ? end : n->end;
        uint32_t firstBlock = (uint32_t)((rStart - n->start) / UVM_BLOCK_SIZE);
        uint32_t lastBlock = (uint32_t)((rEnd - n->start) / UVM_BLOCK_SIZE);
        for (uint32_t bi = firstBlock; bi <= lastBlock && st == TPU_OK; bi++) {
            UvmVaBlock *blk = range->blocks[bi];
            uint64_t bStart = blk->start;
            uint64_t bEnd = blk->start + (uint64_t)blk->npages * ps - 1;
            uint64_t cStart = rStart > bStart ? rStart : bStart;
            uint64_t cEnd = rEnd < bEnd ? rEnd : bEnd;
            if (cStart > cEnd)
                continue;
            uint32_t firstPage = (uint32_t)((cStart - bStart) / ps);
            uint32_t count = (uint32_t)((cEnd - cStart) / ps) + 1;
            st = uvmBlockMakeResident(blk, dst, firstPage, count,
                                      /*forWrite=*/true);
        }
        if (st != TPU_OK)
            break;
        uvmToolsEmit(vs, UVM_EVENT_MIGRATION, UVM_TIER_COUNT /* mixed */,
                     dst.tier, dst.devInst, rStart, rEnd - rStart + 1);
        n = uvmRangeTreeIterNext(n, end);
    }

    tpuLockTrackRelease(TPU_LOCK_UVM_VASPACE, "vaspace");
    pthread_mutex_unlock(&vs->lock);
    uvmPmExitShared();
    tpuCounterAdd("uvm_migrate_calls", 1);
    if (tSpan)
        tpurmTraceEnd(TPU_TRACE_MIGRATE, tSpan, (uintptr_t)base, len);
    return st;
}
