/*
 * In-module UVM tests, dispatched by UVM_RUN_TEST (reference pattern:
 * uvm_test.c:241-312 routes ~90 test commands into *_test.c files built
 * into the production module).  Tests that need no device run on bare
 * data structures; the VA-block and fault tests run against the fake
 * device backend.  Fault injection mirrors the reference's error
 * injection ioctls (UVM_TEST_VA_BLOCK_INJECT_ERROR, uvm_test.c:286).
 */
#include "uvm_internal.h"

#include "tpurm/inject.h"
#include "tpurm/peermem.h"

#include <pthread.h>
#include <stdatomic.h>
#include <stdlib.h>
#include <string.h>
#include <unistd.h>
#include <sys/mman.h>
#include <time.h>

#define CHECK(cond)                                                      \
    do {                                                                 \
        if (!(cond)) {                                                   \
            TPU_LOG(TPU_LOG_ERROR, "uvm_test", "CHECK failed %s:%d: %s",  \
                   __FILE__, __LINE__, #cond);                           \
            return TPU_ERR_INVALID_STATE;                                \
        }                                                                \
    } while (0)

/* -------------------------------------------------------- range tree */

static TpuStatus test_range_tree_directed(void)
{
    UvmRangeTree t;
    uvmRangeTreeInit(&t);
    enum { N = 16 };
    UvmRangeTreeNode nodes[N];
    memset(nodes, 0, sizeof(nodes));

    /* Insert disjoint ranges [i*100, i*100+49]. */
    for (int i = 0; i < N; i++) {
        nodes[i].start = (uint64_t)i * 100;
        nodes[i].end = (uint64_t)i * 100 + 49;
        CHECK(uvmRangeTreeAdd(&t, &nodes[i]) == TPU_OK);
    }
    /* Overlap rejected. */
    UvmRangeTreeNode bad = { .start = 120, .end = 130 };
    CHECK(uvmRangeTreeAdd(&t, &bad) == TPU_ERR_STATE_IN_USE);
    bad.start = 49;
    bad.end = 50;
    CHECK(uvmRangeTreeAdd(&t, &bad) == TPU_ERR_STATE_IN_USE);
    /* Find hits and misses. */
    CHECK(uvmRangeTreeFind(&t, 125) == &nodes[1]);
    CHECK(uvmRangeTreeFind(&t, 50) == NULL);
    /* Ordered iteration over a window: [149,420] catches [100,149] at its
     * last byte; [150,420] starts at [200,249]. */
    UvmRangeTreeNode *it = uvmRangeTreeIterFirst(&t, 149, 420);
    CHECK(it == &nodes[1]);
    it = uvmRangeTreeIterFirst(&t, 150, 420);
    CHECK(it == &nodes[2]);
    int seen = 0;
    while (it) {
        seen++;
        it = uvmRangeTreeIterNext(it, 420);
    }
    CHECK(seen == 3);         /* [200,249] [300,349] [400,449] */
    /* Remove middle, re-check neighbors. */
    uvmRangeTreeRemove(&t, &nodes[3]);
    CHECK(uvmRangeTreeFind(&t, 320) == NULL);
    CHECK(uvmRangeTreeFind(&t, 220) == &nodes[2]);
    CHECK(uvmRangeTreeFind(&t, 420) == &nodes[4]);
    /* Re-insert into the hole. */
    nodes[3].start = 300;
    nodes[3].end = 349;
    CHECK(uvmRangeTreeAdd(&t, &nodes[3]) == TPU_OK);
    return TPU_OK;
}

static TpuStatus test_range_tree_random(void)
{
    enum { N = 512, ROUNDS = 4096 };
    UvmRangeTree t;
    uvmRangeTreeInit(&t);
    static UvmRangeTreeNode nodes[N];
    static bool present[N];
    memset(nodes, 0, sizeof(nodes));
    memset(present, 0, sizeof(present));
    unsigned seed = 12345;

    for (int r = 0; r < ROUNDS; r++) {
        int i = rand_r(&seed) % N;
        if (!present[i]) {
            nodes[i].start = (uint64_t)i * 1000;
            nodes[i].end = nodes[i].start + 1 +
                           (uint64_t)(rand_r(&seed) % 900);
            CHECK(uvmRangeTreeAdd(&t, &nodes[i]) == TPU_OK);
            present[i] = true;
        } else {
            uvmRangeTreeRemove(&t, &nodes[i]);
            present[i] = false;
        }
        /* Spot-check integrity. */
        int j = rand_r(&seed) % N;
        UvmRangeTreeNode *f = uvmRangeTreeFind(&t, (uint64_t)j * 1000);
        CHECK((f != NULL) == present[j]);
        if (f)
            CHECK(f == &nodes[j]);
    }
    /* In-order list must be sorted and complete. */
    uint64_t prev = 0;
    int count = 0;
    for (UvmRangeTreeNode *n = t.first; n; n = uvmRangeTreeNext(n)) {
        CHECK(count == 0 || n->start > prev);
        prev = n->start;
        count++;
    }
    int expect = 0;
    for (int i = 0; i < N; i++)
        expect += present[i];
    CHECK(count == expect);
    return TPU_OK;
}

/* --------------------------------------------------------------- pmm */

static TpuStatus test_pmm_basic(void)
{
    UvmPmm pmm;
    CHECK(uvmPmmInit(&pmm, 8 * UVM_BLOCK_SIZE, 64 * 1024) == TPU_OK);

    UvmPmmChunk *a, *b, *c;
    CHECK(uvmPmmAlloc(&pmm, UVM_BLOCK_SIZE, &a) == TPU_OK);
    CHECK(uvmPmmChunkSize(&pmm, a) == UVM_BLOCK_SIZE);
    CHECK(uvmPmmAlloc(&pmm, 64 * 1024, &b) == TPU_OK);
    CHECK(uvmPmmAlloc(&pmm, 512 * 1024, &c) == TPU_OK);
    /* Distinct, non-overlapping offsets. */
    CHECK(a->offset + UVM_BLOCK_SIZE <= b->offset ||
          b->offset + 64 * 1024 <= a->offset);
    CHECK(uvmPmmAllocatedBytes(&pmm) ==
          UVM_BLOCK_SIZE + 64 * 1024 + 512 * 1024);
    uvmPmmFree(&pmm, b);
    uvmPmmFree(&pmm, c);
    uvmPmmFree(&pmm, a);
    CHECK(uvmPmmAllocatedBytes(&pmm) == 0);

    /* Buddy merge: after freeing everything, a full-arena worth of root
     * chunks must be allocatable again. */
    UvmPmmChunk *roots[8];
    for (int i = 0; i < 8; i++)
        CHECK(uvmPmmAlloc(&pmm, UVM_BLOCK_SIZE, &roots[i]) == TPU_OK);
    UvmPmmChunk *extra;
    CHECK(uvmPmmAlloc(&pmm, 64 * 1024, &extra) == TPU_ERR_NO_MEMORY);
    for (int i = 0; i < 8; i++)
        uvmPmmFree(&pmm, roots[i]);
    uvmPmmDeinit(&pmm);
    return TPU_OK;
}

static TpuStatus test_pmm_eviction(UvmVaSpace *vs)
{
    /* Oversubscribe the HBM arena 2x via managed allocs and migrate
     * them all to HBM: later migrations must evict earlier blocks. */
    UvmTierArena *arena = uvmTierArenaHbm(0);
    CHECK(arena != NULL);
    uint64_t arenaBytes = arena->size;
    uint64_t allocBytes = arenaBytes / 4;
    enum { ALLOCS = 8 };            /* 2x oversubscription */

    void *ptrs[ALLOCS];
    UvmLocation hbm = { UVM_TIER_HBM, 0 };
    UvmFaultStats before, after;
    uvmFaultStatsGet(&before);

    for (int i = 0; i < ALLOCS; i++) {
        TpuStatus st = uvmMemAlloc(vs, allocBytes, &ptrs[i]);
        if (st != TPU_OK)
            TPU_LOG(TPU_LOG_ERROR, "uvm_test", "eviction alloc[%d]: 0x%x",
                   i, st);
        CHECK(st == TPU_OK);
        /* Touch to populate host, with a recognizable pattern. */
        memset(ptrs[i], 0x40 + i, allocBytes);
        st = uvmMigrate(vs, ptrs[i], allocBytes, hbm, 0);
        if (st != TPU_OK)
            TPU_LOG(TPU_LOG_ERROR, "uvm_test", "eviction migrate[%d]: 0x%x",
                   i, st);
        CHECK(st == TPU_OK);
    }
    uvmFaultStatsGet(&after);
    CHECK(after.evictions > before.evictions);

    /* Every allocation must read back intact (evicted ones from host). */
    for (int i = 0; i < ALLOCS; i++) {
        volatile uint8_t *bytes = ptrs[i];
        CHECK(bytes[0] == 0x40 + i);
        CHECK(bytes[allocBytes / 2] == 0x40 + i);
        CHECK(bytes[allocBytes - 1] == 0x40 + i);
    }
    for (int i = 0; i < ALLOCS; i++)
        CHECK(uvmMemFree(vs, ptrs[i]) == TPU_OK);
    return TPU_OK;
}

/* ---------------------------------------------------------- va block */

static TpuStatus test_va_block(UvmVaSpace *vs)
{
    uint64_t ps = uvmPageSize();
    uint64_t size = 4 * UVM_BLOCK_SIZE;
    void *ptr;
    CHECK(uvmMemAlloc(vs, size, &ptr) == TPU_OK);
    uint8_t *bytes = ptr;

    /* First touch populates host. */
    bytes[0] = 0xAA;
    bytes[UVM_BLOCK_SIZE] = 0xBB;
    UvmResidencyInfo info;
    CHECK(uvmResidencyInfo(vs, ptr, &info) == TPU_OK);
    CHECK(info.residentHost && info.cpuMapped);

    /* Migrate block 0 to HBM: host PTE must drop, data must survive. */
    UvmLocation hbm = { UVM_TIER_HBM, 0 };
    CHECK(uvmMigrate(vs, ptr, UVM_BLOCK_SIZE, hbm, 0) == TPU_OK);
    CHECK(uvmResidencyInfo(vs, ptr, &info) == TPU_OK);
    CHECK(info.residentHbm && !info.residentHost && !info.cpuMapped);

    /* CPU read faults it back. */
    CHECK(bytes[0] == 0xAA);
    CHECK(uvmResidencyInfo(vs, ptr, &info) == TPU_OK);
    CHECK(info.residentHost);

    /* Migrate to CXL tier and back. */
    UvmLocation cxl = { UVM_TIER_CXL, 0 };
    CHECK(uvmMigrate(vs, ptr, size, cxl, 0) == TPU_OK);
    CHECK(uvmResidencyInfo(vs, ptr, &info) == TPU_OK);
    CHECK(info.residentCxl && !info.residentHost);
    CHECK(bytes[UVM_BLOCK_SIZE] == 0xBB);   /* fault from CXL */

    /* Read duplication: after enabling, a read fault keeps the CXL copy. */
    CHECK(uvmSetReadDuplication(vs, ptr, size, 1) == TPU_OK);
    CHECK(uvmMigrate(vs, ptr, UVM_BLOCK_SIZE, cxl, 0) == TPU_OK);
    CHECK(bytes[1] == 0xAA || bytes[1] == 0);  /* fault back (read) */
    CHECK(uvmResidencyInfo(vs, ptr, &info) == TPU_OK);
    CHECK(info.residentHost && info.residentCxl);
    /* A write invalidates the duplicate. */
    bytes[0] = 0xCC;
    CHECK(uvmResidencyInfo(vs, ptr, &info) == TPU_OK);
    CHECK(info.residentHost && !info.residentCxl);

    /* Device access fault path. */
    CHECK(uvmSetReadDuplication(vs, ptr, size, 0) == TPU_OK);
    CHECK(uvmDeviceAccess(vs, 0, (char *)ptr + 2 * UVM_BLOCK_SIZE,
                          UVM_BLOCK_SIZE, 1) == TPU_OK);
    CHECK(uvmResidencyInfo(vs, (char *)ptr + 2 * UVM_BLOCK_SIZE, &info) ==
          TPU_OK);
    CHECK(info.residentHbm);

    /* Partial-block migration at page granularity. */
    CHECK(uvmMigrate(vs, (char *)ptr + 3 * UVM_BLOCK_SIZE + ps, 2 * ps,
                     hbm, 0) == TPU_OK);
    CHECK(uvmResidencyInfo(vs, (char *)ptr + 3 * UVM_BLOCK_SIZE + ps,
                           &info) == TPU_OK);
    CHECK(info.residentHbm);
    CHECK(uvmResidencyInfo(vs, (char *)ptr + 3 * UVM_BLOCK_SIZE, &info) ==
          TPU_OK);
    CHECK(!info.residentHbm);

    CHECK(uvmMemFree(vs, ptr) == TPU_OK);
    return TPU_OK;
}

/* -------------------------------------------------------- lock sanity */

static TpuStatus test_lock_sanity(void)
{
    /* In-order acquisition must pass the tracker (out-of-order aborts
     * the process by design, so only the legal direction is testable
     * in-process — the reference's lock test runs illegal orders in a
     * sacrificial context it can catch; here the tracker is fatal). */
    tpuLockTrackAcquire(TPU_LOCK_UVM_VASPACE, "t-vaspace");
    tpuLockTrackAcquire(TPU_LOCK_UVM_BLOCK, "t-block");
    tpuLockTrackAcquire(TPU_LOCK_UVM_PMM, "t-pmm");
    tpuLockTrackAcquire(TPU_LOCK_CHANNEL, "t-channel");
    tpuLockTrackRelease(TPU_LOCK_CHANNEL, "t-channel");
    tpuLockTrackRelease(TPU_LOCK_UVM_PMM, "t-pmm");
    tpuLockTrackRelease(TPU_LOCK_UVM_BLOCK, "t-block");
    tpuLockTrackRelease(TPU_LOCK_UVM_VASPACE, "t-vaspace");
    return TPU_OK;
}

/* ------------------------------------------------------ fault inject */

static TpuStatus test_fault_inject(UvmVaSpace *vs)
{
    /* Hardened recovery: a ONE-SHOT injected CE error under a migrate
     * is recovered transparently — RC reset-and-replay + bounded copy
     * retry — so the client sees success, data stays intact, and the
     * recovery counters record what happened.  With retries disabled
     * (registry recover_copy_retries=0) the failure surfaces to the
     * caller, the legacy contract (reference uvm_test.c:286 inject
     * pattern). */
    void *ptr;
    CHECK(uvmMemAlloc(vs, UVM_BLOCK_SIZE, &ptr) == TPU_OK);
    memset(ptr, 0x5A, UVM_BLOCK_SIZE);

    TpurmDevice *dev = tpurmDeviceGet(0);
    CHECK(dev != NULL);
    UvmLocation hbm = { UVM_TIER_HBM, 0 };
    UvmLocation host = { UVM_TIER_HOST, 0 };

    uint64_t retriesBefore = tpurmCounterGet("recover_retries");
    uint64_t resetsBefore = tpurmCounterGet("recover_rc_resets");
    tpurmChannelInjectError(dev->ce);
    CHECK(uvmMigrate(vs, ptr, UVM_BLOCK_SIZE, hbm, 0) == TPU_OK);
    CHECK(tpurmCounterGet("recover_retries") > retriesBefore);
    CHECK(tpurmCounterGet("recover_rc_resets") > resetsBefore);
    volatile uint8_t *bytes = ptr;
    CHECK(bytes[17] == 0x5A);   /* faults back from HBM intact */

    /* Retries off: the injected failure is the caller's problem. */
    CHECK(uvmMigrate(vs, ptr, UVM_BLOCK_SIZE, hbm, 0) == TPU_OK);
    setenv("TPUMEM_RECOVER_COPY_RETRIES", "0", 1);
    setenv("TPUMEM_UVM_FAULT_RETRY_LIMIT", "0", 1);
    tpuRegistryBump();
    tpurmChannelInjectError(dev->ce);
    TpuStatus st = uvmMigrate(vs, ptr, UVM_BLOCK_SIZE, host, 0);
    CHECK(st != TPU_OK);
    unsetenv("TPUMEM_RECOVER_COPY_RETRIES");
    unsetenv("TPUMEM_UVM_FAULT_RETRY_LIMIT");
    tpuRegistryBump();

    /* Explicit RC reset, then the same migrate succeeds losslessly. */
    tpurmChannelResetError(dev->ce);
    CHECK(uvmMigrate(vs, ptr, UVM_BLOCK_SIZE, host, 0) == TPU_OK);
    CHECK(bytes[17] == 0x5A);

    CHECK(uvmMemFree(vs, ptr) == TPU_OK);
    return TPU_OK;
}

/* ---------------------------------------------------- accessed-by map */

static TpuStatus test_accessed_by(UvmVaSpace *vs)
{
    /* SET_ACCESSED_BY services device faults by MAPPING, not migration:
     * data stays where it is and the device gets a mapping to it
     * (reference: uvm_va_policy accessed_by + fault-service map path). */
    void *ptr;
    CHECK(uvmMemAlloc(vs, UVM_BLOCK_SIZE, &ptr) == TPU_OK);
    uint8_t *bytes = ptr;
    memset(bytes, 0x42, UVM_BLOCK_SIZE);          /* host resident */

    UvmResidencyInfo info;
    CHECK(uvmResidencyInfo(vs, ptr, &info) == TPU_OK);
    CHECK(info.residentHost && !info.devMapped);

    /* Policy set eagerly maps resident pages. */
    CHECK(uvmSetAccessedBy(vs, ptr, UVM_BLOCK_SIZE, 0) == TPU_OK);
    CHECK(uvmResidencyInfo(vs, ptr, &info) == TPU_OK);
    CHECK(info.devMapped);

    /* Device read: serviced by the mapping — NO migration to HBM. */
    CHECK(uvmDeviceAccess(vs, 0, ptr, UVM_BLOCK_SIZE, 0) == TPU_OK);
    CHECK(uvmResidencyInfo(vs, ptr, &info) == TPU_OK);
    CHECK(info.residentHost && !info.residentHbm && info.devMapped);

    /* Explicit migration still moves the data and stales the mapping;
     * the next device fault re-maps to the new location. */
    UvmLocation cxl = { UVM_TIER_CXL, 0 };
    CHECK(uvmMigrate(vs, ptr, UVM_BLOCK_SIZE, cxl, 0) == TPU_OK);
    CHECK(uvmResidencyInfo(vs, ptr, &info) == TPU_OK);
    CHECK(info.residentCxl && !info.devMapped);
    CHECK(uvmDeviceAccess(vs, 0, ptr, UVM_BLOCK_SIZE, 0) == TPU_OK);
    CHECK(uvmResidencyInfo(vs, ptr, &info) == TPU_OK);
    CHECK(info.residentCxl && !info.residentHbm && info.devMapped);

    /* Unset drops the policy AND the mapping; the next device access
     * migrates to HBM like any unmapped fault. */
    CHECK(uvmUnsetAccessedBy(vs, ptr, UVM_BLOCK_SIZE, 0) == TPU_OK);
    CHECK(uvmResidencyInfo(vs, ptr, &info) == TPU_OK);
    CHECK(!info.devMapped);
    CHECK(uvmDeviceAccess(vs, 0, ptr, UVM_BLOCK_SIZE, 0) == TPU_OK);
    CHECK(uvmResidencyInfo(vs, ptr, &info) == TPU_OK);
    CHECK(info.residentHbm);

    /* Data survived the host->CXL->HBM trip: fault back and verify. */
    CHECK(bytes[12345] == 0x42);

    /* Accessed-by WRITE on a read-duplicated page: the mapping write
     * keeps one copy (HBM), invalidates the host duplicate, AND revokes
     * the CPU PTE so a CPU load faults instead of reading stale data. */
    CHECK(uvmSetReadDuplication(vs, ptr, UVM_BLOCK_SIZE, 1) == TPU_OK);
    UvmLocation hbm0 = { UVM_TIER_HBM, 0 };
    CHECK(uvmMigrate(vs, ptr, UVM_BLOCK_SIZE, hbm0, 0) == TPU_OK);
    volatile uint8_t sink = bytes[0];   /* CPU read dup -> host + HBM */
    (void)sink;
    UvmResidencyInfo dup;
    CHECK(uvmResidencyInfo(vs, ptr, &dup) == TPU_OK);
    CHECK(dup.residentHost && dup.residentHbm);
    CHECK(uvmSetAccessedBy(vs, ptr, UVM_BLOCK_SIZE, 0) == TPU_OK);
    CHECK(uvmDeviceAccess(vs, 0, ptr, UVM_BLOCK_SIZE, 1) == TPU_OK);
    CHECK(uvmResidencyInfo(vs, ptr, &dup) == TPU_OK);
    CHECK(dup.residentHbm && !dup.residentHost && !dup.cpuMapped);
    /* CPU load re-faults and pulls the written copy home. */
    sink = bytes[0];
    CHECK(uvmResidencyInfo(vs, ptr, &dup) == TPU_OK);
    CHECK(dup.residentHost);

    CHECK(uvmMemFree(vs, ptr) == TPU_OK);
    return TPU_OK;
}

/* ------------------------------------------------------ tools control */

static TpuStatus test_tools_control(UvmVaSpace *vs)
{
    UvmToolsSession *s = NULL;
    CHECK(uvmToolsSessionCreate(vs, 128, &s) == TPU_OK);

    /* Enable only READ_DUP + MIGRATION; other events must be filtered. */
    uvmToolsEnableEvents(s, 0);
    uvmToolsEnableEventTypes(s, (1ull << UVM_EVENT_READ_DUP) |
                                (1ull << UVM_EVENT_MIGRATION));
    uvmToolsDisableEventTypes(s, 1ull << UVM_EVENT_MIGRATION);

    void *ptr;
    CHECK(uvmMemAlloc(vs, UVM_BLOCK_SIZE, &ptr) == TPU_OK);
    memset(ptr, 1, UVM_BLOCK_SIZE);

    /* Read-duplicated device fault emits READ_DUP (dup copy created). */
    CHECK(uvmSetReadDuplication(vs, ptr, UVM_BLOCK_SIZE, 1) == TPU_OK);
    CHECK(uvmDeviceAccess(vs, 0, ptr, UVM_BLOCK_SIZE, 0) == TPU_OK);
    UvmResidencyInfo info;
    CHECK(uvmResidencyInfo(vs, ptr, &info) == TPU_OK);
    CHECK(info.residentHost && info.residentHbm);   /* duplicated */

    UvmEvent evs[64];
    size_t n = uvmToolsReadEvents(s, evs, 64);
    CHECK(n >= 1);
    bool sawReadDup = false;
    for (size_t i = 0; i < n; i++) {
        CHECK(evs[i].type == UVM_EVENT_READ_DUP);   /* filter honored */
        sawReadDup = true;
    }
    CHECK(sawReadDup);

    /* Counters gate on enable. */
    uint64_t v = 0;
    CHECK(!uvmToolsCounterGet(s, "uvm_fault_batches", &v));
    uvmToolsSetCountersEnabled(s, true);
    CHECK(uvmToolsCounterGet(s, "uvm_fault_batches", &v));
    CHECK(v > 0);

    /* Notification threshold counts crossings. */
    uvmToolsSetNotificationThreshold(s, 1);
    uvmToolsEnableEvents(s, ~0ull);
    CHECK(uvmMigrate(vs, ptr, UVM_BLOCK_SIZE,
                     (UvmLocation){ UVM_TIER_HOST, 0 }, 0) == TPU_OK);
    CHECK(uvmToolsPendingEvents(s) >= 1);
    CHECK(uvmToolsNotificationCount(s) >= 1);

    CHECK(uvmMemFree(vs, ptr) == TPU_OK);
    uvmToolsSessionDestroy(s);
    return TPU_OK;
}

/* ---------------------------------------------------- access counters */

static TpuStatus test_access_counters(UvmVaSpace *vs)
{
    /* Hot CXL-preferred data promotes to HBM without explicit migrates;
     * cold data stays put; decayed promotions demote back
     * (uvm_gpu_access_counters.c:81 capability). */
    setenv("TPUMEM_UVM_ACCESS_COUNTER_THRESHOLD", "4", 1);
    setenv("TPUMEM_UVM_ACCESS_COUNTER_WINDOW_MS", "10000", 1);
    setenv("TPUMEM_UVM_ACCESS_COUNTER_DECAY_MS", "30", 1);
    setenv("TPUMEM_UVM_ACCESS_COUNTER_SWEEP_MS", "10", 1);
    tpuRegistryBump();          /* hot-path caches re-resolve */

    void *hot, *cold;
    CHECK(uvmMemAlloc(vs, UVM_BLOCK_SIZE, &hot) == TPU_OK);
    CHECK(uvmMemAlloc(vs, UVM_BLOCK_SIZE, &cold) == TPU_OK);
    memset(hot, 0x11, UVM_BLOCK_SIZE);
    memset(cold, 0x22, UVM_BLOCK_SIZE);
    UvmLocation cxl = { UVM_TIER_CXL, 0 };
    CHECK(uvmSetPreferredLocation(vs, hot, UVM_BLOCK_SIZE, cxl) == TPU_OK);
    CHECK(uvmSetPreferredLocation(vs, cold, UVM_BLOCK_SIZE, cxl) == TPU_OK);

    /* One access each: both land in the preferred CXL tier. */
    CHECK(uvmDeviceAccess(vs, 0, hot, UVM_BLOCK_SIZE, 0) == TPU_OK);
    CHECK(uvmDeviceAccess(vs, 0, cold, UVM_BLOCK_SIZE, 0) == TPU_OK);
    UvmResidencyInfo info;
    CHECK(uvmResidencyInfo(vs, hot, &info) == TPU_OK);
    CHECK(info.residentCxl && !info.residentHbm);

    /* Hammer the hot buffer: the counter threshold (4) promotes it to
     * HBM with no migrate call. */
    for (int i = 0; i < 8; i++)
        CHECK(uvmDeviceAccess(vs, 0, hot, UVM_BLOCK_SIZE, 0) == TPU_OK);
    CHECK(uvmResidencyInfo(vs, hot, &info) == TPU_OK);
    CHECK(info.residentHbm);
    CHECK(uvmResidencyInfo(vs, cold, &info) == TPU_OK);
    CHECK(info.residentCxl && !info.residentHbm);   /* cold stayed */

    /* Decay: stop touching the hot buffer; the sweeper demotes it from
     * HBM back toward its preferred CXL tier.  Probe a mid-block page no
     * CPU access has pulled host-side. */
    void *probe = (char *)hot + UVM_BLOCK_SIZE / 2;
    CHECK(uvmResidencyInfo(vs, probe, &info) == TPU_OK);
    CHECK(info.residentHbm);
    for (int i = 0; i < 100; i++) {
        struct timespec ts = { 0, 10 * 1000 * 1000 };
        nanosleep(&ts, NULL);
        if (uvmResidencyInfo(vs, probe, &info) == TPU_OK &&
            !info.residentHbm)
            break;
    }
    CHECK(!info.residentHbm && info.residentCxl);

    /* Data integrity through promotion + demotion. */
    CHECK(((volatile uint8_t *)hot)[999] == 0x11);
    CHECK(((volatile uint8_t *)hot)[UVM_BLOCK_SIZE / 2 + 7] == 0x11);
    CHECK(tpurmCounterGet("uvm_access_counter_promotions") >= 1);
    CHECK(tpurmCounterGet("uvm_access_counter_demotions") >= 1);

    unsetenv("TPUMEM_UVM_ACCESS_COUNTER_THRESHOLD");
    unsetenv("TPUMEM_UVM_ACCESS_COUNTER_WINDOW_MS");
    unsetenv("TPUMEM_UVM_ACCESS_COUNTER_DECAY_MS");
    unsetenv("TPUMEM_UVM_ACCESS_COUNTER_SWEEP_MS");
    tpuRegistryBump();
    CHECK(uvmMemFree(vs, hot) == TPU_OK);
    CHECK(uvmMemFree(vs, cold) == TPU_OK);
    return TPU_OK;
}

/* --------------------------------------------- replay policies + cancel */

static TpuStatus test_replay_cancel(UvmVaSpace *vs)
{
    /* All four replay policies service faults correctly (reference:
     * uvm_gpu_replayable_faults.c:3053 BLOCK/BATCH/BATCH_FLUSH/ONCE). */
    static const char *policies[] = { "0", "1", "2", "3" };
    for (int pi = 0; pi < 4; pi++) {
        setenv("TPUMEM_UVM_FAULT_REPLAY_POLICY", policies[pi], 1);
        tpuRegistryBump();
        void *p;
        CHECK(uvmMemAlloc(vs, UVM_BLOCK_SIZE, &p) == TPU_OK);
        volatile uint8_t *b = p;
        b[0] = (uint8_t)(0x50 + pi);              /* CPU write fault */
        UvmLocation hbm = { UVM_TIER_HBM, 0 };
        CHECK(uvmMigrate(vs, p, UVM_BLOCK_SIZE, hbm, 0) == TPU_OK);
        CHECK(b[0] == (uint8_t)(0x50 + pi));      /* CPU read fault */
        CHECK(uvmMemFree(vs, p) == TPU_OK);
    }
    unsetenv("TPUMEM_UVM_FAULT_REPLAY_POLICY");
    tpuRegistryBump();

    /* Precise fatal-fault cancel (reference :2690): a CPU fault whose
     * service fails (injected CE error under it) is cancelled precisely —
     * the faulting access detaches onto a poison page and the process
     * SURVIVES; the failure is observable via counter + residency. */
    uint64_t cancelsBefore = tpurmCounterGet("uvm_fault_cancels");
    void *p;
    CHECK(uvmMemAlloc(vs, UVM_BLOCK_SIZE, &p) == TPU_OK);
    memset(p, 0x6D, UVM_BLOCK_SIZE);
    UvmLocation hbm = { UVM_TIER_HBM, 0 };
    CHECK(uvmMigrate(vs, p, UVM_BLOCK_SIZE, hbm, 0) == TPU_OK);

    /* A PERSISTENT CE fault (framework channel-CE site, every push)
     * makes the copy-back fail through every bounded retry while the
     * CPU read is being serviced: retry exhaustion quarantines the
     * page (retirement after N fatal faults). */
    TpurmDevice *dev = tpurmDeviceGet(0);
    CHECK(dev != NULL);
    uint64_t quarantinesBefore = tpurmCounterGet("recover_page_quarantines");
    CHECK(tpurmInjectConfigure(TPU_INJECT_SITE_CHANNEL_CE, TPU_INJECT_NTH,
                               1, 1, 0) == TPU_OK);
    volatile uint8_t *b = p;
    uint8_t got = b[3];                    /* survives via poison page */
    (void)got;
    tpurmInjectDisable(TPU_INJECT_SITE_CHANNEL_CE);
    tpuRcRecoverAll();                     /* clear chaos-latched errors */

    CHECK(tpurmCounterGet("uvm_fault_cancels") > cancelsBefore);
    CHECK(tpurmCounterGet("recover_page_quarantines") > quarantinesBefore);
    UvmResidencyInfo info;
    CHECK(uvmResidencyInfo(vs, p, &info) == TPU_OK);
    CHECK(info.cancelled);
    /* The poison page stays writable; the rest of the block still works
     * through the normal engine. */
    b[5] = 0x77;
    CHECK(b[5] == 0x77);
    volatile uint8_t *other = (volatile uint8_t *)p + UVM_BLOCK_SIZE / 2;
    CHECK(*other == 0x6D);                 /* normal fault path intact */
    CHECK(uvmResidencyInfo(vs, (void *)other, &info) == TPU_OK);
    CHECK(!info.cancelled);

    CHECK(uvmMemFree(vs, p) == TPU_OK);
    return TPU_OK;
}

/* ------------------------------------------------------ suspend/resume */

struct pm_gate_arg {
    UvmVaSpace *vs;
    void *ptr;
    TpuStatus st;
    _Atomic int done;
};

static void *pm_gate_thread(void *argp)
{
    struct pm_gate_arg *a = argp;
    UvmLocation hbm = { UVM_TIER_HBM, 0 };
    a->st = uvmMigrate(a->vs, a->ptr, UVM_BLOCK_SIZE, hbm, 0);
    atomic_store(&a->done, 1);
    return NULL;
}

static TpuStatus test_suspend_resume(UvmVaSpace *vs)
{
    /* populate -> suspend -> scramble arenas -> resume -> verify
     * (reference: fbsr.c FB save/restore + uvm_suspend quiesce). */
    void *a, *b;
    CHECK(uvmMemAlloc(vs, 2 * UVM_BLOCK_SIZE, &a) == TPU_OK);
    CHECK(uvmMemAlloc(vs, UVM_BLOCK_SIZE, &b) == TPU_OK);
    memset(a, 0x5A, 2 * UVM_BLOCK_SIZE);
    memset(b, 0xA5, UVM_BLOCK_SIZE);
    UvmLocation hbm = { UVM_TIER_HBM, 0 };
    UvmLocation cxl = { UVM_TIER_CXL, 0 };
    CHECK(uvmMigrate(vs, a, 2 * UVM_BLOCK_SIZE, hbm, 0) == TPU_OK);
    CHECK(uvmMigrate(vs, b, UVM_BLOCK_SIZE, cxl, 0) == TPU_OK);

    CHECK(uvmSuspend() == TPU_OK);

    /* All device-side residency was saved home. */
    UvmResidencyInfo info;
    CHECK(uvmResidencyInfo(vs, a, &info) == TPU_OK);
    CHECK(info.residentHost && !info.residentHbm);
    CHECK(uvmResidencyInfo(vs, b, &info) == TPU_OK);
    CHECK(info.residentHost && !info.residentCxl);

    /* Entry points are gated: a migrate from another thread must block
     * until resume. */
    struct pm_gate_arg ga = { vs, a, TPU_OK, 0 };
    pthread_t th;
    CHECK(pthread_create(&th, NULL, pm_gate_thread, &ga) == 0);
    struct timespec ts = { 0, 50 * 1000 * 1000 };
    nanosleep(&ts, NULL);
    CHECK(atomic_load(&ga.done) == 0);      /* still blocked */

    /* Scramble both arenas wholesale — the power-loss analog. */
    TpurmDevice *dev = tpurmDeviceGet(0);
    CHECK(dev != NULL);
    memset(tpurmDeviceHbmBase(dev), 0xFF, tpurmDeviceHbmSize(dev));
    tpuHbmMirrorNotify(tpurmDeviceHbmBase(dev), tpurmDeviceHbmSize(dev));
    UvmTierArena *cx = uvmTierArenaCxl();
    if (cx)
        memset(cx->base, 0xEE, cx->size);

    CHECK(uvmResume() == TPU_OK);
    pthread_join(th, NULL);
    CHECK(atomic_load(&ga.done) == 1 && ga.st == TPU_OK);

    /* Eager restore put the spans back on their original tiers. */
    CHECK(uvmResidencyInfo(vs, b, &info) == TPU_OK);
    CHECK(info.residentCxl);
    CHECK(uvmResidencyInfo(vs, a, &info) == TPU_OK);
    CHECK(info.residentHbm);

    /* Data survives the scramble (verify faults it back page by page). */
    volatile uint8_t *pa = a, *pb = b;
    CHECK(pa[123] == 0x5A);
    CHECK(pa[UVM_BLOCK_SIZE + 4567] == 0x5A);
    CHECK(pb[789] == 0xA5);

    /* Resume without suspend is rejected. */
    CHECK(uvmResume() == TPU_ERR_INVALID_STATE);

    CHECK(uvmMemFree(vs, a) == TPU_OK);
    CHECK(uvmMemFree(vs, b) == TPU_OK);
    return TPU_OK;
}

/* -------------------------------------------------- external ranges */

static TpuStatus test_external_range(UvmVaSpace *vs)
{
    uint64_t ps = uvmPageSize();
    uint64_t len = 4 * ps;

    TpurmDevice *dev = tpurmDeviceGet(0);
    CHECK(dev != NULL);
    if (dev->hbmFd < 0)
        return TPU_OK;            /* anon-arena fallback: nothing to map */

    /* Caller-reserved VA, as the reference's user mmap provides. */
    void *base = mmap(NULL, len, PROT_NONE,
                      MAP_PRIVATE | MAP_ANONYMOUS | MAP_NORESERVE, -1, 0);
    CHECK(base != MAP_FAILED);
    CHECK(uvmExternalRangeCreate(vs, base, len) == TPU_OK);
    /* Double registration collides. */
    CHECK(uvmExternalRangeCreate(vs, base, len) != TPU_OK);

    /* Policy/migration ops reject the external type. */
    UvmLocation cxl = { .tier = UVM_TIER_CXL, .devInst = 0 };
    CHECK(uvmSetPreferredLocation(vs, base, len, cxl) ==
          TPU_ERR_INVALID_ADDRESS);
    CHECK(uvmMigrate(vs, base, len, cxl, 0) == TPU_ERR_INVALID_ADDRESS);

    /* Export a device-HBM window as a dmabuf; map it into the range. */
    uint64_t arenaOff = 16 * ps;     /* arbitrary in-arena spot */
    TpuDmabuf *buf = NULL;
    CHECK(tpuDmabufExport(0, arenaOff, 2 * ps, &buf) == TPU_OK);
    CHECK(uvmMapExternal(vs, base, 2 * ps, buf, 0) == TPU_OK);
    /* Overlapping second window is rejected. */
    CHECK(uvmMapExternal(vs, (char *)base + ps, ps, buf, 0) ==
          TPU_ERR_INVALID_ADDRESS);

    /* The window is a live alias of the arena bytes: writes through one
     * side are visible through the other, and the channel engine sees
     * them (this is the property external mappings exist for). */
    volatile uint8_t *win = base;
    uint8_t *arena = (uint8_t *)tpurmDeviceHbmBase(dev) + arenaOff;
    win[7] = 0xBE;
    CHECK(arena[7] == 0xBE);
    arena[ps + 3] = 0xEF;
    CHECK(win[ps + 3] == 0xEF);
    uint8_t probe = 0;
    uint64_t v = tpurmChannelPushCopy(dev->ce, &probe,
                                      (const void *)&win[7], 1);
    CHECK(v != 0 && tpurmChannelWait(dev->ce, v) == TPU_OK);
    CHECK(probe == 0xBE);

    /* Flush publishes the span to the mirror without error. */
    CHECK(uvmExternalFlush(vs, base, 2 * ps) == TPU_OK);

    /* Unmap restores PROT_NONE over the window... */
    CHECK(uvmUnmapExternal(vs, base, 2 * ps) == TPU_OK);
    /* ...and unknown windows fail. */
    CHECK(uvmUnmapExternal(vs, base, 2 * ps) == TPU_ERR_OBJECT_NOT_FOUND);

    /* Re-map, then free the whole range: mappings die with it and the
     * caller's reservation survives (we can still munmap it). */
    CHECK(uvmMapExternal(vs, base, ps, buf, ps) == TPU_OK);
    CHECK(arena[ps + 3] == 0xEF);
    CHECK(((volatile uint8_t *)base)[3] == 0xEF);  /* bufOffset=ps view */
    CHECK(uvmMemFree(vs, base) == TPU_OK);
    tpuDmabufPut(buf);
    CHECK(munmap(base, len) == 0);
    return TPU_OK;
}

/* ---------------------------------------------------- range splitting */

static TpuStatus test_range_split(UvmVaSpace *vs)
{
    uint64_t half = 2 * UVM_BLOCK_SIZE;        /* 2 blocks per half */
    void *ptr = NULL;
    CHECK(uvmMemAlloc(vs, 2 * half, &ptr) == TPU_OK);
    uint8_t *p = ptr;

    /* Populate host-side. */
    memset(p, 0x11, 2 * half);

    /* Different tiers on the two halves of ONE allocation. */
    UvmLocation cxl = { .tier = UVM_TIER_CXL, .devInst = 0 };
    UvmLocation hbm = { .tier = UVM_TIER_HBM, .devInst = 0 };
    CHECK(uvmSetPreferredLocation(vs, p, half, cxl) == TPU_OK);
    CHECK(uvmSetPreferredLocation(vs, p + half, half, hbm) == TPU_OK);

    /* A sub-block policy span is rejected, not silently widened. */
    CHECK(uvmSetPreferredLocation(vs, p, uvmPageSize(), hbm) ==
          TPU_ERR_INVALID_ADDRESS);

    /* Device access migrates each half to ITS preferred tier. */
    CHECK(uvmDeviceAccess(vs, 0, p, 2 * half, /*write=*/1) == TPU_OK);
    UvmResidencyInfo info;
    CHECK(uvmResidencyInfo(vs, p, &info) == TPU_OK);
    CHECK(info.residentCxl && !info.residentHbm);
    CHECK(uvmResidencyInfo(vs, p + half - 1, &info) == TPU_OK);
    CHECK(info.residentCxl && !info.residentHbm);
    CHECK(uvmResidencyInfo(vs, p + half, &info) == TPU_OK);
    CHECK(info.residentHbm && !info.residentCxl);
    CHECK(uvmResidencyInfo(vs, p + 2 * half - 1, &info) == TPU_OK);
    CHECK(info.residentHbm && !info.residentCxl);

    /* Data integrity across the split boundary (CPU re-faults back). */
    volatile uint8_t *vp = p;
    CHECK(vp[half - 1] == 0x11 && vp[half] == 0x11);

    /* Freeing the allocation base frees every fragment. */
    CHECK(uvmMemFree(vs, ptr) == TPU_OK);
    CHECK(uvmMemFree(vs, ptr) == TPU_ERR_OBJECT_NOT_FOUND);
    return TPU_OK;
}

/* --------------------------------------------------- pageable (HMM) */

static TpuStatus test_hmm_pageable(UvmVaSpace *vs)
{
    /* ATS path: device access to plain malloc'd memory services in
     * place (no managed range anywhere near it). */
    uint64_t before = tpurmCounterGet("uvm_ats_accesses");
    size_t sz = 256 * 1024;
    uint8_t *p = malloc(sz);
    CHECK(p != NULL);
    memset(p, 0x31, sz);
    CHECK(uvmDeviceAccess(vs, 0, p, sz, 0) == TPU_OK);
    CHECK(tpurmCounterGet("uvm_ats_accesses") > before);
    CHECK(p[100] == 0x31);               /* untouched, in place */
    free(p);

    /* Adoption: an aligned span becomes fully managed IN PLACE. */
    void *a = NULL;
    CHECK(posix_memalign(&a, UVM_BLOCK_SIZE, 2 * UVM_BLOCK_SIZE) == 0);
    memset(a, 0x77, 2 * UVM_BLOCK_SIZE);
    CHECK(uvmPageableAdopt(vs, a, 2 * UVM_BLOCK_SIZE) == TPU_OK);
    volatile uint8_t *va = a;
    CHECK(va[123] == 0x77);              /* contents preserved */
    CHECK(va[2 * UVM_BLOCK_SIZE - 1] == 0x77);

    /* Misaligned spans are rejected. */
    uint8_t *mis = malloc(3 * UVM_BLOCK_SIZE);
    CHECK(mis != NULL);
    uintptr_t misAligned = ((uintptr_t)mis + UVM_BLOCK_SIZE) &
                           ~(UVM_BLOCK_SIZE - 1);
    CHECK(uvmPageableAdopt(vs, (void *)(misAligned + 4096),
                           UVM_BLOCK_SIZE) == TPU_ERR_INVALID_ADDRESS);
    free(mis);

    /* Full managed semantics on adopted memory: device write fault
     * migrates to HBM; CPU read faults it home with the data intact. */
    CHECK(uvmDeviceAccess(vs, 0, a, UVM_BLOCK_SIZE, 1) == TPU_OK);
    UvmResidencyInfo info;
    CHECK(uvmResidencyInfo(vs, a, &info) == TPU_OK);
    CHECK(info.residentHbm);
    CHECK(va[123] == 0x77);              /* CPU fault pulls it back */
    va[7] = 0x42;

    /* Freeing restores a plain anonymous mapping with CURRENT bytes:
     * the caller's allocator keeps working. */
    CHECK(uvmMemFree(vs, a) == TPU_OK);
    CHECK(va[7] == 0x42 && va[123] == 0x77);
    va[8] = 1;                           /* still writable anon memory */
    free(a);
    return TPU_OK;
}

/* ----------------------------------------------------- device MMU */

static TpuStatus test_dev_mmu(UvmVaSpace *vs)
{
    uint64_t ps = uvmPageSize();
    void *ptr = NULL;
    CHECK(uvmMemAlloc(vs, 2 * UVM_BLOCK_SIZE, &ptr) == TPU_OK);
    memset(ptr, 0x21, 2 * UVM_BLOCK_SIZE);

    /* Unmapped VA: no translation. */
    UvmTier tier;
    uint64_t off;
    bool writable;
    CHECK(uvmDevMmuTranslate(0, (uintptr_t)ptr, &tier, &off, &writable) ==
          TPU_ERR_INVALID_ADDRESS);

    /* Device write fault installs PTEs pointing at the HBM backing. */
    CHECK(uvmDeviceAccess(vs, 0, ptr, UVM_BLOCK_SIZE, 1) == TPU_OK);
    CHECK(uvmDevMmuTranslate(0, (uintptr_t)ptr, &tier, &off, &writable) ==
          TPU_OK);
    CHECK(tier == UVM_TIER_HBM && writable);
    UvmResidencyInfo info;
    CHECK(uvmResidencyInfo(vs, ptr, &info) == TPU_OK);
    CHECK(info.residentHbm && off == info.hbmOffset);
    /* Page-offset bits carry through the translation. */
    uint64_t off2;
    CHECK(uvmDevMmuTranslate(0, (uintptr_t)ptr + ps + 123, &tier, &off2,
                             NULL) == TPU_OK);
    CHECK((off2 & (ps - 1)) == 123);

    /* Migration home revokes the PTEs and bumps the TLB generation. */
    uint64_t gen = uvmDevMmuTlbGeneration(0);
    UvmLocation home = { .tier = UVM_TIER_HOST, .devInst = 0 };
    CHECK(uvmMigrate(vs, ptr, UVM_BLOCK_SIZE, home, 0) == TPU_OK);
    CHECK(uvmDevMmuTranslate(0, (uintptr_t)ptr, &tier, &off, NULL) ==
          TPU_ERR_INVALID_ADDRESS);
    CHECK(uvmDevMmuTlbGeneration(0) > gen);

    /* CXL-preferred data: device read fault maps the CXL aperture. */
    UvmLocation cxl = { .tier = UVM_TIER_CXL, .devInst = 0 };
    CHECK(uvmSetPreferredLocation(vs, ptr, 2 * UVM_BLOCK_SIZE, cxl) ==
          TPU_OK);
    CHECK(uvmDeviceAccess(vs, 0, (char *)ptr + UVM_BLOCK_SIZE,
                          UVM_BLOCK_SIZE, 0) == TPU_OK);
    CHECK(uvmDevMmuTranslate(0, (uintptr_t)ptr + UVM_BLOCK_SIZE, &tier,
                             &off, &writable) == TPU_OK);
    CHECK(tier == UVM_TIER_CXL && !writable);

    /* PTE/TLB batch accounting moved. */
    uint64_t w, c, inv;
    uvmDevMmuStats(0, &w, &c, &inv);
    CHECK(w >= 2 && c >= 1 && inv >= 1);

    CHECK(uvmMemFree(vs, ptr) == TPU_OK);
    return TPU_OK;
}

/* Multi-worker fault service: with uvm_fault_service_threads >= 2 on a
 * multi-core host, concurrent faults on blocks that hash to different
 * workers must be IN SERVICE simultaneously (the per-block worker
 * partitioning actually runs in parallel, VERDICT r3 weak #6).  Skips
 * cleanly (OK + journal note) when only one worker/CPU is online. */
typedef struct {
    UvmVaSpace *vs;
    char *base;
    uint64_t span;
    int rounds;
} MwArg;

static void *mw_faulter(void *arg)
{
    MwArg *a = arg;
    for (int r = 0; r < a->rounds; r++) {
        if (uvmDeviceAccess(a->vs, 0, a->base, a->span, 0) != TPU_OK)
            return (void *)1;
        /* Bounce residency so every round re-faults. */
        volatile char sink = 0;
        for (uint64_t off = 0; off < a->span; off += 4096)
            sink += a->base[off];
        (void)sink;
    }
    return NULL;
}

static TpuStatus test_multi_worker(UvmVaSpace *vs)
{
    long ncpu = sysconf(_SC_NPROCESSORS_ONLN);
    if (uvmFaultWorkerCount() < 2 || ncpu < 2) {
        TPU_LOG(TPU_LOG_INFO, "uvm-test",
               "multi_worker: skipped (%u workers, %ld cpus)",
               uvmFaultWorkerCount(), ncpu);
        return TPU_OK;
    }
    enum { NTHREADS = 4, ROUNDS = 64 };
    uint64_t span = 2 * UVM_BLOCK_SIZE;
    void *ptr = NULL;
    CHECK(uvmMemAlloc(vs, NTHREADS * span, &ptr) == TPU_OK);
    memset(ptr, 0x33, NTHREADS * span);

    pthread_t tids[NTHREADS];
    MwArg args[NTHREADS];
    for (int i = 0; i < NTHREADS; i++) {
        /* Distinct block spans -> distinct workers (addr/BLOCK % n). */
        args[i] = (MwArg){ .vs = vs, .base = (char *)ptr + i * span,
                           .span = span, .rounds = ROUNDS };
        CHECK(pthread_create(&tids[i], NULL, mw_faulter, &args[i]) == 0);
    }
    bool failed = false;
    for (int i = 0; i < NTHREADS; i++) {
        void *ret;
        pthread_join(tids[i], &ret);
        failed |= ret != NULL;
    }
    CHECK(!failed);
    /* The whole point: more than one worker was mid-batch at once. */
    CHECK(uvmFaultServiceHighWater() >= 2);
    CHECK(uvmMemFree(vs, ptr) == TPU_OK);
    return TPU_OK;
}

/* ----------------------------------------------------------- dispatch */

TpuStatus uvmRunTest(UvmVaSpace *vs, uint32_t testCmd)
{
    switch (testCmd) {
    case UVM_TPU_TEST_RANGE_TREE_DIRECTED:
        return test_range_tree_directed();
    case UVM_TPU_TEST_RANGE_TREE_RANDOM:
        return test_range_tree_random();
    case UVM_TPU_TEST_PMM_BASIC:
        return test_pmm_basic();
    case UVM_TPU_TEST_PMM_EVICTION:
        return vs ? test_pmm_eviction(vs) : TPU_ERR_INVALID_ARGUMENT;
    case UVM_TPU_TEST_VA_BLOCK:
        return vs ? test_va_block(vs) : TPU_ERR_INVALID_ARGUMENT;
    case UVM_TPU_TEST_LOCK_SANITY:
        return test_lock_sanity();
    case UVM_TPU_TEST_FAULT_INJECT:
        return vs ? test_fault_inject(vs) : TPU_ERR_INVALID_ARGUMENT;
    case UVM_TPU_TEST_ACCESSED_BY:
        return vs ? test_accessed_by(vs) : TPU_ERR_INVALID_ARGUMENT;
    case UVM_TPU_TEST_TOOLS:
        return vs ? test_tools_control(vs) : TPU_ERR_INVALID_ARGUMENT;
    case UVM_TPU_TEST_ACCESS_COUNTERS:
        return vs ? test_access_counters(vs) : TPU_ERR_INVALID_ARGUMENT;
    case UVM_TPU_TEST_REPLAY_CANCEL:
        return vs ? test_replay_cancel(vs) : TPU_ERR_INVALID_ARGUMENT;
    case UVM_TPU_TEST_SUSPEND_RESUME:
        return vs ? test_suspend_resume(vs) : TPU_ERR_INVALID_ARGUMENT;
    case UVM_TPU_TEST_EXTERNAL_RANGE:
        return vs ? test_external_range(vs) : TPU_ERR_INVALID_ARGUMENT;
    case UVM_TPU_TEST_RANGE_SPLIT:
        return vs ? test_range_split(vs) : TPU_ERR_INVALID_ARGUMENT;
    case UVM_TPU_TEST_HMM_PAGEABLE:
        return vs ? test_hmm_pageable(vs) : TPU_ERR_INVALID_ARGUMENT;
    case UVM_TPU_TEST_DEV_MMU:
        return vs ? test_dev_mmu(vs) : TPU_ERR_INVALID_ARGUMENT;
    case UVM_TPU_TEST_MULTI_WORKER:
        return vs ? test_multi_worker(vs) : TPU_ERR_INVALID_ARGUMENT;
    default:
        return TPU_ERR_INVALID_COMMAND;
    }
}
