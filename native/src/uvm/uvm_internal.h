/*
 * UVM internals.  Lock order extends internal.h's table (reference pattern:
 * uvm_lock.h:31+ — order documented as data, asserted in debug builds):
 *
 *   1. g_rm.lock
 *   2. VA space lock          (TPU_LOCK_UVM_VASPACE)
 *   3. VA block lock          (TPU_LOCK_UVM_BLOCK)
 *   4. PMM / tier-arena lock  (TPU_LOCK_UVM_PMM)
 *   5. CXL table lock
 *   6. pin accounting lock
 *   7. per-channel lock
 *   8. journal/counters
 *
 * The fault service thread acquires VA space (read side) -> block -> PMM,
 * exactly the reference's uvm_va_space read lock -> block lock -> PMM order.
 */
#ifndef TPURM_UVM_INTERNAL_H
#define TPURM_UVM_INTERNAL_H

#include <pthread.h>
#include <stdbool.h>
#include <stdint.h>

#include "../internal.h"
#include "tpurm/uvm.h"

/* ------------------------------------------------------------ geometry */

#define UVM_BLOCK_SIZE        (2ull * 1024 * 1024)   /* uvm_pmm_gpu.h:60-85 */
#define UVM_PAGE_SHIFT_MIN    12
/* Default UVM page size: 64 KB — the TPU-native granule (XLA tiles and HBM
 * transfers favor >=32 KB lines); registry "uvm_page_size" can lower it to
 * 4 KB for reference-equivalent granularity.  32 pages/block at 64 KB. */
#define UVM_PAGE_SIZE_DEFAULT (64ull * 1024)
#define UVM_MAX_PAGES_PER_BLOCK 512                  /* 2 MB / 4 KB */

typedef struct {
    uint64_t bits[UVM_MAX_PAGES_PER_BLOCK / 64];
} UvmPageMask;

/* Mask primitives are inline word ops (reference: uvm_page_mask_* are
 * bitmap.h wrappers, uvm_va_block_types.h) — the fault-service commit
 * path runs hundreds of these per fault, so they must not be calls. */
#include <string.h>

static inline void uvmPageMaskZero(UvmPageMask *m)
{
    memset(m->bits, 0, sizeof(m->bits));
}

static inline bool uvmPageMaskTest(const UvmPageMask *m, uint32_t page)
{
    return (m->bits[page / 64] >> (page % 64)) & 1;
}

static inline void uvmPageMaskSet(UvmPageMask *m, uint32_t page)
{
    m->bits[page / 64] |= 1ull << (page % 64);
}

static inline void uvmPageMaskClear(UvmPageMask *m, uint32_t page)
{
    m->bits[page / 64] &= ~(1ull << (page % 64));
}

/* Word-at-a-time range walker: invokes op(wordIndex, mask) for each
 * 64-bit word the range touches, with mask covering the in-range bits. */
#define UVM_MASK_RANGE_WORDS(first, count, wvar, mvar, body)               \
    do {                                                                   \
        uint32_t _p = (first), _left = (count);                            \
        while (_left) {                                                    \
            uint32_t wvar = _p / 64, _b = _p % 64;                         \
            uint32_t _span = 64 - _b;                                      \
            if (_span > _left)                                             \
                _span = _left;                                             \
            uint64_t mvar = _span == 64 ? ~0ull                            \
                                        : (((1ull << _span) - 1) << _b);   \
            body;                                                          \
            _p += _span;                                                   \
            _left -= _span;                                                \
        }                                                                  \
    } while (0)

static inline void uvmPageMaskSetRange(UvmPageMask *m, uint32_t first,
                                       uint32_t count)
{
    UVM_MASK_RANGE_WORDS(first, count, w, bm, m->bits[w] |= bm);
}

static inline void uvmPageMaskClearRange(UvmPageMask *m, uint32_t first,
                                         uint32_t count)
{
    UVM_MASK_RANGE_WORDS(first, count, w, bm, m->bits[w] &= ~bm);
}

static inline void uvmPageMaskFill(UvmPageMask *m, uint32_t npages)
{
    uvmPageMaskZero(m);
    uvmPageMaskSetRange(m, 0, npages);
}

/* dst |= src / dst &= ~src over the whole mask. */
static inline void uvmPageMaskOr(UvmPageMask *dst, const UvmPageMask *src)
{
    for (uint32_t i = 0; i < UVM_MAX_PAGES_PER_BLOCK / 64; i++)
        dst->bits[i] |= src->bits[i];
}

static inline void uvmPageMaskAndNot(UvmPageMask *dst,
                                     const UvmPageMask *src)
{
    for (uint32_t i = 0; i < UVM_MAX_PAGES_PER_BLOCK / 64; i++)
        dst->bits[i] &= ~src->bits[i];
}

/* Any set bit inside [first, first+count)? */
static inline bool uvmPageMaskIntersectsRange(const UvmPageMask *m,
                                              uint32_t first, uint32_t count)
{
    UVM_MASK_RANGE_WORDS(first, count, w, bm,
                         if (m->bits[w] & bm) return true);
    return false;
}

static inline uint32_t uvmPageMaskWeight(const UvmPageMask *m,
                                         uint32_t npages)
{
    uint32_t w = 0;
    for (uint32_t i = 0; i < (npages + 63) / 64; i++) {
        uint64_t word = m->bits[i];
        if ((i + 1) * 64 > npages && npages % 64)
            word &= (1ull << (npages % 64)) - 1;
        w += (uint32_t)__builtin_popcountll(word);
    }
    return w;
}

static inline bool uvmPageMaskEmpty(const UvmPageMask *m, uint32_t npages)
{
    return uvmPageMaskWeight(m, npages) == 0;
}

static inline bool uvmPageMaskFull(const UvmPageMask *m, uint32_t npages)
{
    return uvmPageMaskWeight(m, npages) == npages;
}

/* First set/clear bit at or after `from`; returns npages if none. */
uint32_t uvmPageMaskFindSet(const UvmPageMask *m, uint32_t npages,
                            uint32_t from);
uint32_t uvmPageMaskFindClear(const UvmPageMask *m, uint32_t npages,
                              uint32_t from);

/* ----------------------------------------------------------- range tree */

/* Non-overlapping [start, end] interval tree (reference: uvm_range_tree.c),
 * an AVL tree keyed by start with linked in-order iteration. */
typedef struct UvmRangeTreeNode {
    uint64_t start, end;              /* inclusive end, like the reference */
    struct UvmRangeTreeNode *left, *right, *parent;
    struct UvmRangeTreeNode *prev, *next;   /* in-order list */
    int height;
} UvmRangeTreeNode;

typedef struct {
    UvmRangeTreeNode *root;
    UvmRangeTreeNode *first;
} UvmRangeTree;

void uvmRangeTreeInit(UvmRangeTree *t);
/* Fails with TPU_ERR_STATE_IN_USE on overlap. */
TpuStatus uvmRangeTreeAdd(UvmRangeTree *t, UvmRangeTreeNode *n);
void uvmRangeTreeRemove(UvmRangeTree *t, UvmRangeTreeNode *n);
UvmRangeTreeNode *uvmRangeTreeFind(UvmRangeTree *t, uint64_t addr);
/* First node intersecting [start,end], or NULL. */
UvmRangeTreeNode *uvmRangeTreeIterFirst(UvmRangeTree *t, uint64_t start,
                                        uint64_t end);
UvmRangeTreeNode *uvmRangeTreeIterNext(UvmRangeTreeNode *n, uint64_t end);
UvmRangeTreeNode *uvmRangeTreeNext(UvmRangeTreeNode *n);

/* ----------------------------------------------------------------- PMM */

/* Buddy chunk allocator over a byte arena (reference: uvm_pmm_gpu.c).
 * Chunk sizes: 64 KB ... 2 MB powers of two (root = 2 MB, 6 levels);
 * with 4 KB uvm_page_size the leaf level extends to 4 KB (10 levels). */
#define UVM_PMM_MAX_LEVELS 10

typedef struct UvmPmmChunk {
    uint64_t offset;                  /* byte offset into the arena */
    uint8_t level;                    /* 0 = root (2 MB) */
    bool allocated;
    struct UvmPmmChunk *buddyParent;
    struct UvmPmmChunk *next, *prev;  /* freelist links */
} UvmPmmChunk;

/* Free-list lock striping: each 2 MB root (and every chunk split from
 * it) is owned by shard (rootIndex % shardCount) — buddies never cross
 * a root, so merges stay intra-shard and a chunk's shard is stable for
 * life.  Allocation tries the caller's home shard first (trylock;
 * tier_lock_contended on a miss), then walks the siblings before
 * reporting exhaustion.  Shard count: registry "tier_lock_shards",
 * default min(online CPUs, 8), clamped to the root count. */
#define UVM_PMM_MAX_SHARDS 8

typedef struct UvmPmmShard {
    pthread_mutex_t lock;             /* order TPU_LOCK_UVM_PMM */
    UvmPmmChunk *freelist[UVM_PMM_MAX_LEVELS];
} UvmPmmShard;

typedef struct UvmPmm {
    uint32_t shardCount;
    UvmPmmShard shards[UVM_PMM_MAX_SHARDS];
    uint64_t arenaSize;
    uint64_t chunkMin;                /* leaf chunk size */
    uint32_t levels;                  /* root..leaf inclusive */
    _Atomic uint64_t allocatedBytes;
    struct UvmPmmChunk **rootChunks;  /* lazily created roots (slot i
                                       * written under shard i%count) */
    uint64_t rootCount;
} UvmPmm;

TpuStatus uvmPmmInit(UvmPmm *pmm, uint64_t arenaSize, uint64_t chunkMin);
void      uvmPmmDeinit(UvmPmm *pmm);
/* size must be a power-of-two chunk size in [chunkMin, 2MB].  Returns
 * TPU_ERR_NO_MEMORY when the arena is exhausted (caller evicts, retries). */
TpuStatus uvmPmmAlloc(UvmPmm *pmm, uint64_t size, UvmPmmChunk **out);
void      uvmPmmFree(UvmPmm *pmm, UvmPmmChunk *chunk);
uint64_t  uvmPmmChunkSize(const UvmPmm *pmm, const UvmPmmChunk *c);
uint64_t  uvmPmmAllocatedBytes(UvmPmm *pmm);

/* ------------------------------------------------------------ tier arena */

/* A physical tier: byte arena + PMM + eviction LRU of blocks with
 * residency in it.  HBM tiers wrap a device arena; the CXL tier wraps the
 * CXL expander window (fake mode: private mmap sized by registry
 * "cxl_tier_bytes"). */
struct UvmVaBlock;

/* LRU lock striping: a block's shard is (blk->start / UVM_BLOCK_SIZE)
 * % shardCount — stable for the block's life, so Touch/Remove and the
 * evicting-flag handshake always meet on the same lock.  Victim scans
 * walk the shards round-robin from a rotating cursor; global LRU order
 * is per-shard only (approximate across shards, like the reference's
 * per-GPU root-chunk lists). */
#define UVM_TIER_LRU_SHARDS 8

typedef struct UvmTierLruShard {
    pthread_mutex_t lock;             /* order TPU_LOCK_UVM_PMM */
    pthread_cond_t evictCond;         /* evicting-flag handshake */
    /* Eviction LRU: blocks with residency in this arena, oldest first
     * (reference: root-chunk LRU, uvm_pmm_gpu.c). */
    struct UvmVaBlock *lruHead, *lruTail;
} UvmTierLruShard;

typedef struct UvmTierArena {
    UvmTier tier;
    uint32_t devInst;                 /* HBM only */
    void *base;
    uint64_t size;
    UvmPmm pmm;
    uint32_t lruShardCount;
    _Atomic uint32_t victimCursor;    /* rotating scan start */
    UvmTierLruShard lru[UVM_TIER_LRU_SHARDS];
} UvmTierArena;

/* --------------------------------------------------------------- blocks */

typedef struct UvmChunkRun {
    uint32_t firstPage, numPages;
    UvmPmmChunk *chunk;
    UvmTierArena *arena;
    struct UvmChunkRun *next;
} UvmChunkRun;

/* REMOTE-tier lease: a chunk of a LENDER chip's HBM arena holding a
 * replica of this block's pages (tpusplit).  The lease is valid only
 * while (a) the process-wide device generation still equals leaseGen —
 * ANY device reset fences every lease, conservative by design — and
 * (b) the lender is healthy and not marked revoked.  An invalid lease
 * is never read: the promote path drops it and HOST serves. */
typedef struct UvmRemoteRun {
    uint32_t firstPage, numPages;
    uint32_t lenderInst;
    uint64_t lenderOff;               /* offset in the lender HBM arena */
    uint64_t chunkBytes;              /* granted (pow2-rounded) size —
                                       * the lender's lent-bytes ledger
                                       * uses this, not pages*ps        */
    void *chunkHandle;                /* uvmHbmChunkAlloc handle        */
    uint64_t leaseGen;                /* tpurmDeviceGeneration at lease */
    uint64_t revokeEpoch;             /* lender revoke epoch at lease   */
    struct UvmRemoteRun *next;
} UvmRemoteRun;

struct UvmVaRange;

typedef struct UvmVaBlock {
    pthread_mutex_t lock;             /* order TPU_LOCK_UVM_BLOCK */
    struct UvmVaRange *range;
    uint64_t start;                   /* VA, block-aligned */
    uint32_t npages;
    /* Held by fault workers across a service (taken under vs->lock, so
     * the space lock is NOT held during block work); uvmBlockFreeBacking
     * waits for it to drain before teardown. */
    _Atomic uint32_t serviceRefs;
    UvmPageMask resident[UVM_TIER_COUNT];
    UvmPageMask cpuMapped;            /* pages with valid (RW) host PTEs */
    UvmPageMask devMapped;            /* pages device may access directly */
    UvmChunkRun *hbmRuns;             /* HBM backing (per-run chunks) */
    UvmChunkRun *cxlRuns;             /* CXL backing */
    uint32_t hbmDevInst;              /* single-HBM-device-per-block rule */
    /* Eviction LRU links: index 0 = HBM arena, 1 = CXL arena (a block can
     * have residency in both tiers at once under read duplication).
     * `evicting` is set while an evictor popped this block off the list
     * and still holds its raw pointer; uvmBlockFreeBacking waits for it
     * to clear before tearing the block down (lifetime guard). */
    struct {
        struct UvmVaBlock *prev, *next;
        bool on;
        bool evicting;
    } lru[2];
    /* Prefetch effectiveness: pages made resident by prefetch region
     * growth that no access has touched yet.  A later fault/device
     * access landing on a marked page counts uvm_prefetch_hits; an
     * eviction that drops a still-marked page counts
     * uvm_prefetch_useless (the feedback signal the ROADMAP prefetch
     * item needs).  Mutated under blk->lock. */
    UvmPageMask prefetched;
    /* Perf state (prefetch window, uvm_perf_prefetch.c analog).
     * Single-writer: the spine's per-block fault ordering (OP_FAULT
     * dep DAG) serializes services of one block, so these are plain. */
    uint32_t faultCount;
    uint64_t lastFaultNs;
    uint64_t windowStartNs;
    uint32_t windowFaults;
    /* Thrashing PIN hint (tpuhot, uvm_perf_thrashing.h:33-46 analog):
     * while pinExpiryNs is in the future the block is exempt from
     * uvmLruPopVictim (and therefore uvmTierEvictBytes) for the pinned
     * tier, and CPU read faults duplicate against the pinned copy
     * instead of invalidating it.  Atomics: written by the thrash
     * detector under blk->lock but read lock-free by the victim walk
     * (arena lock only) and the fault target selection. */
    _Atomic int32_t pinnedTier;       /* -1 = not pinned */
    _Atomic uint64_t pinExpiryNs;
    /* tpuhot per-block tracker (native/src/hot.c).  `touches` is the
     * fault-service feed: ONE relaxed fetch_add per service; the
     * decayed score/recency fold happens lazily at policy points.
     * Atomics are read/folded lock-free from the victim walks;
     * the plain fields are serialized by blk->lock (thrash detector,
     * precision feedback) or by the per-block fault ordering
     * (density mask, mutated only from prefetch expansion). */
    struct {
        _Atomic uint64_t touches;     /* pages accessed (lifetime)      */
        _Atomic uint64_t seen;        /* touches already folded         */
        _Atomic uint64_t score;       /* decayed hotness, <<10 fixpoint */
        _Atomic uint64_t scoreNs;     /* last decay fold                */
        _Atomic uint64_t lastTouchNs; /* recency (stamped at fold)      */
        _Atomic uint64_t throttleUntilNs; /* THROTTLE hint expiry       */
        /* Thrash detector (under blk->lock: migration commit paths). */
        uint64_t thrashWinNs;
        uint32_t thrashMoves;         /* direction alternations         */
        int8_t lastDir;               /* +1 deviceward, -1 hostward     */
        /* Prefetch governor. */
        _Atomic uint32_t pfCap;       /* speculation cap, 0 = uninit    */
        uint32_t pfHits, pfUseless;   /* decaying precision window      */
        UvmPageMask accessed;         /* density bitmap (20ms window)   */
    } hot;
    /* P2P pins: while >0 the block's device residency is locked in place
     * (no eviction, no migration away) — RDMA consumers hold bus
     * addresses into it (reference: vidmem pinned by p2p get_pages). */
    uint32_t p2pPinCount;
    /* REMOTE-tier backing (tpusplit): leases on lender chips' HBM.
     * remoteBusy > 0 while a PEER_COPY window is in flight with
     * blk->lock DROPPED (the spine wait cannot hold it): make-resident
     * and eviction refuse with STATE_IN_USE, and remote-run gc defers,
     * so neither the local source/dest runs nor the lender chunks can
     * move or free under an in-flight transfer. */
    UvmRemoteRun *remoteRuns;
    uint32_t remoteBusy;
    /* Access-counter state (reference: uvm_gpu_access_counters.c:81 —
     * sampled hotness that triggers migrations).  acCount counts device
     * accesses serviced WITHOUT HBM placement inside the window; crossing
     * the threshold promotes the span to the device's HBM.  acPromoted
     * marks counter-promoted blocks as candidates for decay demotion. */
    uint64_t acWindowStartNs;
    uint32_t acCount;
    bool acPromoted;
    /* Precisely-cancelled pages (fatal-fault cancel): user VA detached
     * onto a poison mapping; excluded from residency/migration. */
    UvmPageMask cancelled;
    bool hasCancelled;
    /* tpushield per-page integrity metadata (native/src/shield.c),
     * stored beside the residency masks: CRC32C seal + generation +
     * poison state of the page's COLD copy.  NULL until the first
     * seal — the fault path's shield gate is this one pointer load.
     * The POINTER is atomic (lazy publish under blk->lock races the
     * scrubber's lock-free pre-check; a plain x86 mov either way);
     * the metadata it points to is mutated under blk->lock only. */
    struct UvmShieldPage *_Atomic shield;
    /* True once uvmBlockPtePopulate wrote any device PTE for this block;
     * lets uvmBlockPteRevoke skip the per-device table walks on blocks
     * no device ever mapped (the CPU-fault-only hot path).  Cleared only
     * by a whole-block revoke — partial revokes may leave live PTEs. */
    bool devPtesLive;
} UvmVaBlock;

typedef enum {
    UVM_RANGE_TYPE_MANAGED = 0,
    UVM_RANGE_TYPE_EXTERNAL = 1,
    /* Local window onto ANOTHER process's managed range (the engine
     * host's), attached over the broker: the window maps the owner
     * range's host-backing memfd, CPU faults forward to the owner for
     * service, and protections open at fault granularity.  Reference:
     * per-fd VA spaces with IPC-shared allocations (uvm.c:144,792 +
     * the CUDA IPC model). */
    UVM_RANGE_TYPE_REMOTE = 2,
} UvmRangeType;

typedef struct UvmVaRange {
    UvmRangeTreeNode node;            /* start/end in the space tree */
    UvmVaSpace *vaSpace;
    UvmRangeType type;
    uint64_t size;
    /* Original allocation extent, preserved across splits: uvmMemFree
     * on the allocation base frees every fragment. */
    uint64_t allocStart, allocSize;
    /* HMM adoption (uvm_hmm.c): the VA belongs to the caller; destroy
     * restores an anonymous mapping with the current contents. */
    bool adopted;
    /* Managed host backing: a memfd mapped twice — the user VA (node
     * start; protection-controlled, faults drive migration) and an
     * engine alias that is always RW.  The copy engine reads/writes the
     * alias so user-PTE protection can never race an in-flight CE copy
     * (the reference's equivalent: the kernel touches physical pages,
     * not user PTEs). */
    int memfd;
    void *alias;
    /* REMOTE ranges: owner-process VA of the range start (fault
     * forwarding translates local addr -> remoteBase + delta). */
    uint64_t remoteBase;
    /* REMOTE ranges: forwarded-fault pin (serviceRefs analog).  The
     * fault worker increments under vs->lock before forwarding over
     * the broker; uvmRemoteDetach removes the range from the tree and
     * then waits for this to drain before munmap/free, so an in-flight
     * forward can never mprotect a recycled mapping. */
    _Atomic uint32_t remoteRefs;
    /* Policy (reference: uvm_va_policy.c). */
    bool hasPreferred;
    UvmLocation preferred;
    uint64_t accessedByMask;          /* bit per device inst */
    bool readDuplication;
    /* UVM_ADVISE_COMPRESSIBLE: TPU_CE_COMP_* format (0 = lossless).
     * Host<->HBM copies of this range ride the tpuce quantize stage —
     * only safe for data that tolerates reduced precision (KV-cache
     * pages); exact ranges must never set it. */
    uint32_t compressFormat;
    uint64_t rangeGroupId;            /* 0 = none */
    /* Blocks, one per 2 MB span. */
    UvmVaBlock **blocks;
    uint32_t blockCount;
    /* EXTERNAL ranges: list of live dmabuf windows mapped into the
     * range (uvm_map_external.c analog). */
    struct UvmExtMapping *extMappings;
} UvmVaRange;

typedef struct UvmExtMapping {
    uint64_t start, len;              /* VA span within the range */
    struct TpuDmabuf *buf;            /* referenced while mapped */
    uint32_t devInst;
    uint64_t arenaOff;                /* dmabuf offset + map offset */
    struct UvmExtMapping *next;
} UvmExtMapping;

struct UvmVaSpace {
    pthread_mutex_t lock;             /* order TPU_LOCK_UVM_VASPACE */
    UvmRangeTree ranges;
    uint64_t registeredDevMask;
    uint64_t nextRangeGroupId;
    /* Range groups: simple table id -> migratable flag. */
    struct UvmRangeGroup *groups;
    struct UvmVaSpace *nextSpace;     /* global list for fault lookup */
    uint64_t pageSize;
    struct UvmToolsSession *toolsHead;/* sessions (under vs lock) */
    /* Tenant binding (QoS).  tenantId 0 = the default tenant; the
     * per-space page charge mirrors what this space contributed to its
     * tenant so a rebind can move the charge without walking blocks.
     * Atomics: charged from block paths without the vs lock. */
    _Atomic uint32_t tenantId;
    _Atomic uint64_t tenantPages[UVM_TIER_COUNT];
};

typedef struct UvmRangeGroup {
    uint64_t id;
    bool migratable;
    struct UvmRangeGroup *next;
} UvmRangeGroup;

/* ------------------------------------------------------------- tenants */

/* Process-global tenant table (uvm.h tenant QoS API).  Slot 0 is the
 * default tenant (always live).  Usage counters are atomics: the block
 * paths charge without taking the table lock. */
#define UVM_MAX_TENANTS 64

#define UVM_TENANT_MAX_DEVS 16

typedef struct UvmTenant {
    uint32_t id;
    /* priority/quotas are _Atomic because reconfiguration is allowed
     * while traffic runs: the victim walk and the over-quota test read
     * them lock-free (relaxed — a racing reconfigure simply lands on
     * the next decision, but never as a torn value). */
    _Atomic uint32_t priority;        /* higher = keep longer */
    _Atomic uint64_t quotaPages[UVM_TIER_COUNT];   /* 0 = unlimited */
    _Atomic uint64_t usedPages[UVM_TIER_COUNT];
    /* Per-DEVICE HBM page charge (tpuvac): which chip's arena holds
     * this tenant's pages.  Charged explicitly by the pools that place
     * pages on a specific device (the ICI KV pool via
     * uvmTenantDevCharge / uvmTenantRebindDevicePages) — a live
     * migration rebinds the charge from the source chip to the target
     * without touching the per-tier totals. */
    _Atomic uint64_t devPages[UVM_TENANT_MAX_DEVS];
    bool used;
} UvmTenant;

/* Lookup (NULL when the id was never configured). */
UvmTenant *uvmTenantGet(uint32_t tenantId);
/* The tenant a block's pages charge to (never NULL: default tenant). */
UvmTenant *uvmTenantOfSpace(UvmVaSpace *vs);
/* True once any tenant beyond the default has been configured — the
 * SLO-aware victim walk is gated on this so an unconfigured process
 * keeps the exact historical LRU eviction order. */
bool uvmTenantsActive(void);
/* Over-quota test for an aperture tier (always false for quota 0). */
bool uvmTenantOverQuota(const UvmTenant *t, UvmTier tier);
/* Charge/uncharge `pages` backing pages of `tier` to vs's tenant
 * (negative delta uncharges).  HBM/CXL only; HOST is unbounded. */
void uvmTenantCharge(UvmVaSpace *vs, UvmTier tier, int64_t pages);
/* Render per-tenant usage/quota gauges (Prometheus exposition) and the
 * human procfs table (TpuCur from internal.h). */
void uvmTenantRenderProm(TpuCur *c);
void uvmTenantRenderTable(TpuCur *c);

/* ------------------------------------------------------- block services */

uint64_t uvmPageSize(void);
uint32_t uvmPagesPerBlock(void);

UvmTierArena *uvmTierArenaHbm(uint32_t devInst);   /* NULL if no device */
UvmTierArena *uvmTierArenaCxl(void);

/* Make [first, first+count) pages of the block resident in dst, copying
 * from wherever they are now through the device CE channel; updates masks
 * and host PTE protection.  Takes the block lock internally and may drop
 * it to run eviction when the destination arena is full (the reference
 * drops block locks around PMA eviction the same way, uvm_pmm_gpu.c).
 * (reference: uvm_va_block_make_resident, uvm_va_block.c:5086.) */
TpuStatus uvmBlockMakeResident(UvmVaBlock *blk, UvmLocation dst,
                               uint32_t firstPage, uint32_t count,
                               bool forWrite);
/* forceDup keeps source copies even when the range policy has read
 * duplication off — used by thrashing mitigation (PIN hint) so a pinned
 * device copy survives CPU read faults. */
TpuStatus uvmBlockMakeResidentEx(UvmVaBlock *blk, UvmLocation dst,
                                 uint32_t firstPage, uint32_t count,
                                 bool forWrite, bool forceDup);
/* Evict all of blk's residency in `arena` back to host.  Uses trylock on
 * the block (returns TPU_ERR_STATE_IN_USE if contended) so cross-eviction
 * between two allocating threads cannot deadlock.
 * (reference eviction: uvm_pmm_gpu.c root-chunk eviction.) */
TpuStatus uvmBlockEvictFrom(UvmVaBlock *blk, UvmTierArena *arena);
void uvmBlockFreeBacking(UvmVaBlock *blk);
/* Arena offset of `page`'s HBM backing (blk->lock held); false if the
 * page has no HBM run. */
bool uvmBlockHbmArenaOffset(UvmVaBlock *blk, uint32_t page,
                            uint64_t *outOffset);
/* Device-MMU wiring (blk->lock held): install PTEs for aperture-resident
 * pages of the span / revoke the span's PTEs on every device. */
void uvmBlockPtePopulate(UvmVaBlock *blk, uint32_t firstPage,
                         uint32_t count, uint32_t devInst, bool writable);
void uvmBlockPteRevoke(UvmVaBlock *blk, uint32_t firstPage,
                       uint32_t count);

/* Accessed-by mapping: map pages for a device where they currently
 * reside, without migration (fails TPU_ERR_INVALID_STATE if any page is
 * resident nowhere).  See uvm_va_block.c. */
TpuStatus uvmBlockMapDevice(UvmVaBlock *blk, uint32_t firstPage,
                            uint32_t count, bool forWrite);

/* Host PTE control over the managed VA (mprotect). */
void uvmBlockSetCpuAccess(UvmVaBlock *blk, uint32_t firstPage,
                          uint32_t count, int prot);

/* LRU maintenance (arena lock taken inside). */
void uvmLruTouch(UvmTierArena *a, UvmVaBlock *blk);
void uvmLruRemove(UvmTierArena *a, UvmVaBlock *blk);
/* Pop the least-recently-used unpinned block (never `exclude`), or NULL.
 * The returned block has its `evicting` guard set; the caller MUST call
 * uvmLruEvictDone once it no longer holds the pointer. */
UvmVaBlock *uvmLruPopVictim(UvmTierArena *a, UvmVaBlock *exclude);
void uvmLruEvictDone(UvmTierArena *a, UvmVaBlock *blk);
/* Wait until no evictor holds blk for this arena (called before free). */
void uvmLruAwaitEvictors(UvmTierArena *a, UvmVaBlock *blk);

/* Range/block lookup: returns range and block covering addr (vs lock must
 * be held); blockOut may be NULL. */
UvmVaRange *uvmRangeFind(UvmVaSpace *vs, uint64_t addr, UvmVaBlock **blockOut);
/* True if the range group (0 = ungrouped) currently allows migration
 * (UvmPreventMigrationRangeGroups semantics; vs lock must be held). */
bool uvmRangeGroupMigratable(UvmVaSpace *vs, uint64_t groupId);

/* P2P pin management (peermem substrate). */
void uvmBlockP2pPin(UvmVaBlock *blk);
void uvmBlockP2pUnpin(UvmVaBlock *blk);

/* ------------------------------------------------ REMOTE tier (tpusplit)
 *
 * uvm_tier_remote.c: leases on lender chips' HBM as this chip's far
 * memory.  All data movement is dep-chained PEER_COPY windows on the
 * submission spine; both entry points take blk->lock HELD, drop it
 * around the spine wait (remoteBusy + p2pPin guard the window) and
 * re-acquire before returning. */

/* True when the "remote_tier" knob is on and >= 2 devices exist. */
bool uvmTierRemoteEnabled(void);
/* Demote hook (uvmBlockEvictFrom, after the host copy-back commits and
 * BEFORE residency clears): replicate the toHost pages of [first,last]
 * to a lender picked by the health scorer.  Best-effort — on any
 * failure the eviction proceeds as a plain HOST demote. */
void uvmTierRemoteReplicate(UvmVaBlock *blk, const UvmPageMask *toHost,
                            uint32_t first, uint32_t last);
/* Promote fast path (uvmBlockMakeResidentEx, dst == HBM, after
 * block_alloc_backing): fetch needed & resident[REMOTE] pages from
 * their lenders straight into the local HBM runs.  Pages fetched are
 * set in *fetched (caller excludes them from the HOST copy_in); an
 * invalid lease (generation fence, sick lender, revocation) is dropped
 * and its pages fall back to HOST. */
void uvmTierRemoteFetch(UvmVaBlock *blk, uint32_t devInst,
                        const UvmPageMask *needed, UvmPageMask *fetched);
/* Free remote runs whose pages no longer have resident[REMOTE] bits
 * (blk->lock held).  Defers while remoteBusy — an in-flight window may
 * still read the lender chunks; later gc calls collect. */
void uvmTierRemoteGc(UvmVaBlock *blk);
/* Teardown: drop ALL remote runs unconditionally (blk->lock held,
 * remoteBusy must be 0 — uvmBlockFreeBacking drains it first). */
void uvmTierRemoteFreeAll(UvmVaBlock *blk);
/* Prometheus render (procfs metrics): tpurm_tier_remote_pages{dev=}. */
void uvmTierRemoteRenderProm(TpuCur *c);
/* Lender-side lent-bytes ledger (uvmHbmArenaUsage subtracts this). */
uint64_t uvmTierRemoteLentBytes(uint32_t lenderInst);

/* Range-destroy notification: peermem registers one hook; it fires for
 * every managed range torn down (uvmMemFree / VaSpaceDestroy) BEFORE the
 * backing is freed, so RDMA registrations can be revoked (reference:
 * nv_get_p2p_free_callback flow, nvidia-peermem.c:134). */
typedef void (*UvmRangeDestroyHook)(uint64_t start, uint64_t size);
void uvmSetRangeDestroyHook(UvmRangeDestroyHook hook);

/* --------------------------------------------------------- fault engine */

typedef enum {
    UVM_FAULT_SRC_CPU = 0,
    UVM_FAULT_SRC_DEVICE = 1,
} UvmFaultSource;

typedef struct UvmFaultEntry {
    uint64_t addr;
    uint64_t len;                     /* device faults may span a range */
    uint8_t isWrite;
    uint8_t source;                   /* UvmFaultSource */
    uint32_t devInst;                 /* device faults */
    UvmVaSpace *vs;                   /* NULL: resolved via snapshot */
    uint64_t enqueueNs;
    /* tpuflow identity captured from the FAULTING thread
     * (tpurmTraceFlowGet; initial-exec TLS, so the signal handler may
     * read it).  Carried into the OP_FAULT SQE's flowId, set as the
     * service worker's thread flow around execution, and — for CPU
     * demand faults — accounted into the flow's fault-service blame
     * bucket. */
    uint64_t flow;
    TpuStatus serviceStatus;
    /* Waiter futex word (0 pending, 1 done, 2 failed). */
    uint32_t *doneWord;
} UvmFaultEntry;

void uvmFaultEngineInit(void);        /* idempotent */
void uvmFaultEngineRegisterSpace(UvmVaSpace *vs);
UvmVaSpace *uvmFaultSpaceForAddr(uint64_t addr);

/* ------------------------------------------------------ device MMU */

/* Per-device page tables + batched PTE/TLB operations (reference:
 * uvm_mmu.c, uvm_pte_batch.c, uvm_tlb_batch.c).  The device VA is the
 * managed VA (identity, like the reference's UVM mapping); a PTE
 * resolves to (tier, offset-in-tier-arena). */
#define UVM_PTE_BATCH_MAX 64

typedef struct {
    uint32_t devInst;
    uint32_t count;
    uint32_t clearedLive;       /* clears that hit a VALID pte */
    struct { uint64_t va, pte; } entries[UVM_PTE_BATCH_MAX];
} UvmPteBatch;

typedef struct {
    uint32_t devInst;
    uint64_t pendingPages;
} UvmTlbBatch;

void uvmPteBatchBegin(UvmPteBatch *b, uint32_t devInst);
void uvmPteBatchWrite(UvmPteBatch *b, uint64_t va, UvmTier tier,
                      uint64_t tierOff, bool writable);
void uvmPteBatchClear(UvmPteBatch *b, uint64_t va);
void uvmPteBatchEnd(UvmPteBatch *b);
void uvmTlbBatchBegin(UvmTlbBatch *b, uint32_t devInst);
void uvmTlbBatchAdd(UvmTlbBatch *b, uint64_t va, uint32_t npages);
void uvmTlbBatchEnd(UvmTlbBatch *b);
TpuStatus uvmDevMmuTranslate(uint32_t devInst, uint64_t va, UvmTier *tier,
                             uint64_t *tierOff, bool *writable);
uint64_t uvmDevMmuTlbGeneration(uint32_t devInst);
void uvmDevMmuStats(uint32_t devInst, uint64_t *pteWrites,
                    uint64_t *pteClears, uint64_t *tlbInvalidates);

/* ------------------------------------------------------ pageable (HMM) */

bool uvmHmmEnabled(void);
TpuStatus uvmPageableDeviceAccess(UvmVaSpace *vs, uint32_t devInst,
                                  void *base, uint64_t len, int isWrite);
void uvmHmmRestoreOnDestroy(UvmVaRange *range);
void uvmFaultEngineUnregisterSpace(UvmVaSpace *vs);
/* Rebuild the signal-safe VA lookup snapshot after range add/remove. */
void uvmFaultSnapshotRebuild(void);
/* Enqueue + wait (device faults call this synchronously). */
TpuStatus uvmFaultServiceSync(UvmFaultEntry *e);
void uvmFaultStatsRecordMigration(uint64_t bytes);
void uvmFaultStatsRecordEviction(void);
/* PM drain barrier + space/block iteration (uvm_pm.c consumers). */
void uvmFaultRingDrain(void);
/* Reset quiesce (reset.c): park/resume the fault-service loop between
 * batches (pending faults wait; bounded in-flight-batch drain). */
void uvmFaultServicePause(uint64_t timeoutNs);
void uvmFaultServiceResume(void);
uint32_t uvmFaultWorkerCount(void);
uint32_t uvmFaultServiceHighWater(void);
void uvmFaultForEachSpace(void (*fn)(UvmVaSpace *vs, UvmVaBlock *blk));
void uvmFaultForEachSpaceCtx(void (*fn)(UvmVaSpace *vs, UvmVaBlock *blk,
                                        void *ctx), void *ctx);
/* Global PM gate (reference: uvm_lock.h:43-49).  Entry points enter the
 * shared side; uvmSuspend holds it exclusively until uvmResume. */
void uvmPmEnterShared(void);
void uvmPmExitShared(void);

/* ----------------------------------------------------------- perf hooks */

/* Returns the expanded [firstPage,count) to service for a fault at page
 * (prefetch region growth, uvm_perf_prefetch.c analog). */
void uvmPerfPrefetchExpand(UvmVaBlock *blk, uint32_t page, bool deviceFault,
                           uint32_t *firstPage, uint32_t *count);
/* Prefetch-effectiveness accounting (all take blk->lock internally):
 * Touch — an access landed on [first,count): marked pages count as
 * prefetch HITS and unmark.  Mark — a service expanded by prefetch
 * made [first,count) resident; every page OUTSIDE the requested
 * [reqFirst,reqCount) span is marked speculative.  Evict — the span is
 * losing aperture residency; still-marked pages count as USELESS
 * prefetches and unmark (caller already holds blk->lock). */
void uvmPerfPrefetchTouch(UvmVaBlock *blk, uint32_t first, uint32_t count);
void uvmPerfPrefetchMark(UvmVaBlock *blk, uint32_t reqFirst,
                         uint32_t reqCount, uint32_t first,
                         uint32_t count);
void uvmPerfPrefetchEvictLocked(UvmVaBlock *blk, uint32_t first,
                                uint32_t count);
bool uvmPerfBlockPinnedAgainst(UvmVaBlock *blk, UvmTier targetTier);

/* --------------------------------------------------------------- tpuhot
 *
 * Hotness-driven placement (native/src/hot.c; see tpurm/hot.h for the
 * subsystem contract).  Everything here is engine-internal: the feed,
 * the three policies, and the render hooks. */

#include <stdatomic.h>

/* Tracker feed: ONE relaxed RMW — the only cost on the fault-service
 * critical path (recency/decay fold happens lazily at policy points). */
static inline void uvmHotTouch(UvmVaBlock *blk, uint32_t pages)
{
    atomic_fetch_add_explicit(&blk->hot.touches, pages,
                              memory_order_relaxed);
}

/* Decayed hotness score (lazy fold of touches + decay; safe lock-free,
 * racing folds lose at most a touch delta). */
uint64_t uvmHotBlockScore(UvmVaBlock *blk, uint64_t now);

/* Prefetch governor: the governed region size (pages) for a fault at
 * `page` — tree-density bottom-up growth clamped by the block's
 * precision-driven speculation cap.  maxPages already folds the
 * registry cap and block geometry. */
uint32_t uvmHotPrefetchGovern(UvmVaBlock *blk, uint32_t page,
                              bool deviceFault, uint32_t maxPages);
/* Mark [first,count) recently-accessed in the density bitmap (called
 * from the expansion with the final serviced region). */
void uvmHotDensityMark(UvmVaBlock *blk, uint32_t first, uint32_t count);
void uvmHotDensityReset(UvmVaBlock *blk);
/* Precision feedback (blk->lock held): hits/useless deltas from the
 * PR-7 effectiveness counters grow/shrink the speculation cap. */
void uvmHotPrefetchFeedback(UvmVaBlock *blk, uint32_t hits,
                            uint32_t useless);

/* Thrash detector: note one committed migration of blk's pages toward
 * `dstTier` (blk->lock held — called from the make-resident and
 * eviction commit points).  Direction alternations inside the window
 * trip PIN or THROTTLE. */
void uvmHotMigrationNote(UvmVaBlock *blk, UvmTier dstTier,
                         uint32_t devInst);
/* THROTTLE hint: microseconds to delay this service (0 = none);
 * counts and emits the hot.throttle instant when nonzero. */
uint32_t uvmHotThrottleDelayUs(UvmVaBlock *blk);

/* Victim scorer: bounded coldness scan over the plain-LRU path
 * (returns the colder candidate to evict, possibly `head` itself;
 * caller holds the arena lock, candidates are walked via lru links).
 * Registry "hot_victim_scan" bounds the scan (0 disables). */
uint64_t uvmHotVictimScanDepth(void);
void uvmHotVictimReorderNote(void);
/* One hot.decide inject evaluation wrapping a policy decision: false
 * means an injected hit degraded this decision to a no-op (counted
 * hot_inject_skips — EXACT: hits == skips). */
bool uvmHotDecideAllowed(void);
bool uvmHotEnabled(void);

void tpurmHotRenderProm(TpuCur *c);
void tpurmHotRenderTable(TpuCur *c);

/* -------------------------------------------------------------- tpushield
 *
 * Page-integrity engine (native/src/shield.c; tpurm/shield.h for the
 * subsystem contract).  Everything here is engine-internal: the
 * per-page seal metadata and the hooks the block/fault paths call.
 * All page-granular entry points expect blk->lock HELD. */

typedef struct UvmShieldPage {
    uint32_t crc;               /* CRC32C of the sealed copy           */
    uint16_t gen;               /* seal generation (reseals bump it)   */
    uint8_t state;              /* 0 unsealed; 1+tier sealed; 0xFF
                                 * poisoned (sticky)                   */
    uint8_t pending;            /* injected flips awaiting detection   */
} UvmShieldPage;

bool uvmShieldActive(void);     /* registry shield_enable */
/* Seal `page`'s copy in `tier` with the CRC the copy path computed
 * (tpuce executor stripe transform); evaluates mem.corrupt once. */
void uvmShieldSealPage(UvmVaBlock *blk, uint32_t page, UvmTier tier,
                       uint32_t crc);
/* Drop seals in [first,first+count) (tier < 0: any) — the last verify
 * hook before a sealed copy is overwritten or dropped. */
void uvmShieldUnsealRange(UvmVaBlock *blk, uint32_t first, uint32_t count,
                          int tier);
/* Verify every sealed page of the span, running the re-fetch ladder
 * on mismatch (recompute -> sibling copy -> poison+retire).  TPU_OK or
 * TPU_ERR_PAGE_POISONED when any page of the span is/became poisoned. */
TpuStatus uvmShieldVerifyRange(UvmVaBlock *blk, uint32_t first,
                               uint32_t count);
/* Overlapped verify-on-promote: compare the copied bytes' CRC (tpuce
 * stripe-transform stage, computed during the copy) against the seal;
 * mismatch falls back to the source-side ladder.  *recopy set when the
 * caller must redo the page's copy from the now-proven source. */
TpuStatus uvmShieldVerifyCopied(UvmVaBlock *blk, uint32_t page,
                                uint32_t crc, bool *recopy);
bool uvmShieldRangeSealed(UvmVaBlock *blk, uint32_t first, uint32_t count);
bool uvmShieldRangePoisoned(UvmVaBlock *blk, uint32_t first,
                            uint32_t count);
bool uvmShieldPagePoisoned(UvmVaBlock *blk, uint32_t page);
/* Sealed tier of `page` (-1 when unsealed/poisoned).  blk->lock held. */
int uvmShieldPageSealedTier(UvmVaBlock *blk, uint32_t page);
void uvmShieldBlockFree(UvmVaBlock *blk);
/* Retirement gates for the PMM paths: RunRetired true => the chunk
 * must NOT return to the freelist (the leak IS the retirement);
 * CheckAlloc counts shield_retired_realloc if a fresh chunk overlaps a
 * retired span (invariant detector, must stay 0). */
bool uvmShieldRunRetired(UvmTierArena *arena, uint64_t chunkOff,
                         uint64_t bytes);
void uvmShieldCheckAlloc(UvmTierArena *arena, uint64_t off,
                         uint64_t bytes);

/* Host-addressable pointer for `page`'s copy in `tier` (NULL when the
 * tier holds no backing for it); arena byte offset of an aperture
 * page.  blk->lock held.  (uvm_va_block.c internals, exported for the
 * shield engine.) */
void *uvmBlockPagePtr(UvmVaBlock *blk, UvmTier tier, uint32_t page);
bool uvmBlockTierOffset(UvmVaBlock *blk, UvmTier tier, uint32_t page,
                        uint64_t *outOffset);

/* Access counters (uvm_gpu_access_counters.c:81 analog).  Record returns
 * true when the block crossed the hotness threshold and should be
 * promoted to the accessing device's HBM.  MaybeDemote (called from the
 * sweeper with the vs lock held) demotes a counter-promoted block whose
 * hotness decayed, returning true if it demoted. */
bool uvmAccessCounterRecord(UvmVaBlock *blk);
bool uvmAccessCounterMaybeDemote(UvmVaSpace *vs, UvmVaBlock *blk);

/* ---------------------------------------------------------- tools hooks */

void uvmToolsEmit(UvmVaSpace *vs, UvmEventType type, uint32_t srcTier,
                  uint32_t dstTier, uint32_t devInst, uint64_t address,
                  uint64_t bytes);

uint64_t uvmMonotonicNs(void);

#endif /* TPURM_UVM_INTERNAL_H */
