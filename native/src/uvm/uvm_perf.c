/*
 * Perf heuristics: prefetch region growth + access-counter promotion.
 *
 * Prefetch — the region a fault service expands to is picked by the
 * tpuhot governor (native/src/hot.c, uvm_perf_prefetch.c analog):
 * bottom-up TREE-DENSITY growth (the candidate region doubles only
 * while the enclosing aligned region's recently-accessed density stays
 * above hot_prefetch_density_pct) clamped by a per-block speculation
 * cap that MEASURED PRECISION — uvm_prefetch_hits/(hits+useless) from
 * the effectiveness counters below — grows and shrinks around
 * hot_prefetch_min_precision.  With tpuhot disabled (hot_enable=0) the
 * pre-governor heuristic remains: the region doubles with the block's
 * fault count inside a time window.  Registry knobs:
 *   uvm_prefetch_enable   (default 1)
 *   uvm_prefetch_max_pages(default 32 = whole 2 MB block at 64 KB pages)
 *
 * Thrashing detection + PIN/THROTTLE hints live in tpuhot
 * (uvmHotMigrationNote, fed from the migration commit points);
 * uvmPerfBlockPinnedAgainst below is the placement-side reader of the
 * PIN hint (uvm_perf_thrashing.h:33-46).
 *
 * These run from the fault-service workers without the block lock; the
 * spine's per-block ordering makes them single-writer per block, and
 * the counters are heuristic state tolerating benign races (the
 * reference's perf modules are similarly advisory).
 */
#include "uvm_internal.h"

void uvmPerfPrefetchExpand(UvmVaBlock *blk, uint32_t page, bool deviceFault,
                           uint32_t *firstPage, uint32_t *count)
{
    *firstPage = page;
    *count = 1;
    static TpuRegCache c_pfEnable;
    if (!tpuRegCacheGet(&c_pfEnable, "uvm_prefetch_enable", 1))
        return;

    uint64_t now = uvmMonotonicNs();
    static TpuRegCache c_pfWindow;
    uint64_t windowNs = tpuRegCacheGet(&c_pfWindow,
                                       "uvm_prefetch_window_ms", 20) *
                        1000000ull;
    if (now - blk->windowStartNs > windowNs) {
        blk->windowStartNs = now;
        blk->windowFaults = 0;
        /* The density tree observes one window at a time: a stale
         * bitmap would let last epoch's pattern keep inflating
         * regions the current access pattern no longer earns. */
        uvmHotDensityReset(blk);
    }
    blk->windowFaults++;
    blk->faultCount++;
    blk->lastFaultNs = now;

    static TpuRegCache c_pfMax;
    uint32_t maxPages = (uint32_t)tpuRegCacheGet(&c_pfMax,
                                                 "uvm_prefetch_max_pages",
                                                 32);
    uint32_t ppb = blk->npages;
    if (maxPages > ppb)
        maxPages = ppb;
    uint32_t want;
    if (uvmHotEnabled()) {
        want = uvmHotPrefetchGovern(blk, page, deviceFault, maxPages);
    } else {
        /* Legacy lookahead: 2^(faults-1) pages, aligned. */
        want = 1;
        uint32_t f = blk->windowFaults;
        while (f > 1 && want < maxPages) {
            want <<= 1;
            f >>= 1;
        }
        if (deviceFault && want < maxPages)
            want <<= 1;
    }
    if (want > ppb)
        want = ppb;

    uint32_t first = (page / want) * want;   /* aligned region */
    uint32_t cnt = want;
    if (first + cnt > ppb)
        cnt = ppb - first;
    *firstPage = first;
    *count = cnt;
    /* Feed the density tree with the whole serviced region: prefetched
     * pages do not re-fault, so counting only demanded pages would
     * starve the bottom-up growth the moment speculation works. */
    uvmHotDensityMark(blk, first, cnt);
    if (cnt > 1) {
        tpuCounterAdd("uvm_prefetch_pages", cnt - 1);
        uvmToolsEmit(blk->range->vaSpace, UVM_EVENT_PREFETCH, UVM_TIER_COUNT,
                     UVM_TIER_COUNT, 0, blk->start + (uint64_t)first *
                     uvmPageSize(), (uint64_t)cnt * uvmPageSize());
    }
}

/* ------------------------------------- prefetch effectiveness counters
 *
 * The ROADMAP prefetch item's feedback signal: every speculative page
 * the region growth pulls in is tracked until either an access lands
 * on it (uvm_prefetch_hits — the prefetch saved a fault) or an
 * eviction drops it untouched (uvm_prefetch_useless — the prefetch
 * wasted transport and arena space).  hits/(hits+useless) is the
 * prefetcher's measured precision.
 */

static uint32_t prefetch_count_and_clear(UvmVaBlock *blk, uint32_t first,
                                         uint32_t count)
{
    uint32_t n = 0;
    UVM_MASK_RANGE_WORDS(first, count, w, bm, {
        n += (uint32_t)__builtin_popcountll(blk->prefetched.bits[w] & bm);
        blk->prefetched.bits[w] &= ~bm;
    });
    return n;
}

void uvmPerfPrefetchTouch(UvmVaBlock *blk, uint32_t first, uint32_t count)
{
    if (!uvmPageMaskIntersectsRange(&blk->prefetched, first, count))
        return;                  /* common case: no lock, no counters */
    pthread_mutex_lock(&blk->lock);
    tpuLockTrackAcquire(TPU_LOCK_UVM_BLOCK, "prefetch-touch");
    uint32_t n = prefetch_count_and_clear(blk, first, count);
    if (n)
        uvmHotPrefetchFeedback(blk, n, 0);   /* precision: hits */
    tpuLockTrackRelease(TPU_LOCK_UVM_BLOCK, "prefetch-touch");
    pthread_mutex_unlock(&blk->lock);
    if (n)
        tpuCounterAdd("uvm_prefetch_hits", n);
}

void uvmPerfPrefetchMark(UvmVaBlock *blk, uint32_t reqFirst,
                         uint32_t reqCount, uint32_t first, uint32_t count)
{
    pthread_mutex_lock(&blk->lock);
    tpuLockTrackAcquire(TPU_LOCK_UVM_BLOCK, "prefetch-mark");
    uvmPageMaskSetRange(&blk->prefetched, first, count);
    /* The requested span was DEMANDED, not speculated. */
    uvmPageMaskClearRange(&blk->prefetched, reqFirst, reqCount);
    tpuLockTrackRelease(TPU_LOCK_UVM_BLOCK, "prefetch-mark");
    pthread_mutex_unlock(&blk->lock);
}

void uvmPerfPrefetchEvictLocked(UvmVaBlock *blk, uint32_t first,
                                uint32_t count)
{
    uint32_t n = prefetch_count_and_clear(blk, first, count);
    if (n) {
        uvmHotPrefetchFeedback(blk, 0, n);   /* precision: useless */
        tpuCounterAdd("uvm_prefetch_useless", n);
    }
}

/* PIN-hint reader (target selection + victim exemption).  The hint is
 * written by tpuhot's thrash detector under blk->lock but read
 * lock-free here — the fields are relaxed atomics; a racing lapse or
 * re-pin lands on the next decision, never as a torn value. */
bool uvmPerfBlockPinnedAgainst(UvmVaBlock *blk, UvmTier targetTier)
{
    int32_t pinned = atomic_load_explicit(&blk->pinnedTier,
                                          memory_order_relaxed);
    if (pinned < 0)
        return false;
    if (atomic_load_explicit(&blk->pinExpiryNs, memory_order_relaxed) <=
        uvmMonotonicNs())
        return false;
    return pinned != (int32_t)targetTier;
}

/* ------------------------------------------------------ access counters */

/* Hotness sampling (re-design of uvm_gpu_access_counters.c:81: HW
 * notifications of remote-access hotness become candidate migrations).
 * The TPU engine sees every device access span (uvmDeviceAccess), so the
 * "counter notification" is synthesized in the service loop: accesses
 * serviced WITHOUT HBM placement (accessed-by mappings, CXL-preferred or
 * thrash-pinned targets) count here; crossing the threshold inside the
 * window promotes the block to the device's HBM.  Registry knobs:
 *   uvm_access_counter_enable     (default 1)
 *   uvm_access_counter_threshold  (default 8 remote accesses)
 *   uvm_access_counter_window_ms  (default 100)
 *   uvm_access_counter_decay_ms   (default 250 — cold promoted blocks
 *                                  demote back to CXL/host)
 */
bool uvmAccessCounterRecord(UvmVaBlock *blk)
{
    static TpuRegCache c_acEnable;
    if (!tpuRegCacheGet(&c_acEnable, "uvm_access_counter_enable", 1))
        return false;
    uint64_t now = uvmMonotonicNs();
    static TpuRegCache c_acWindow;
    uint64_t windowNs = tpuRegCacheGet(&c_acWindow,
                                       "uvm_access_counter_window_ms",
                                       100) * 1000000ull;
    if (now - blk->acWindowStartNs > windowNs) {
        blk->acWindowStartNs = now;
        blk->acCount = 0;
    }
    blk->acCount++;
    /* Multi-page device spans skip prefetch (which owns lastFaultNs for
     * CPU faults), so refresh the decay clock here too — otherwise a
     * device hammering a block reads as idle and the sweeper demotes
     * still-hot data. */
    blk->lastFaultNs = now;
    static TpuRegCache c_acThresh;
    uint32_t threshold =
        (uint32_t)tpuRegCacheGet(&c_acThresh,
                                 "uvm_access_counter_threshold", 8);
    if (blk->acCount >= threshold) {
        blk->acCount = 0;
        tpuCounterAdd("uvm_access_counter_promotions", 1);
        return true;
    }
    return false;
}

bool uvmAccessCounterMaybeDemote(UvmVaSpace *vs, UvmVaBlock *blk)
{
    if (!blk->acPromoted)
        return false;
    uint64_t now = uvmMonotonicNs();
    static TpuRegCache c_acDecay;
    uint64_t decayNs = tpuRegCacheGet(&c_acDecay,
                                      "uvm_access_counter_decay_ms", 250) *
                       1000000ull;
    if (now - blk->lastFaultNs < decayNs)
        return false;
    if (uvmPageMaskEmpty(&blk->resident[UVM_TIER_HBM], blk->npages)) {
        blk->acPromoted = false;       /* already moved elsewhere */
        return false;
    }

    /* Demote target: the range's preferred device-side tier if it names
     * CXL, else CXL when an arena exists, else host. */
    UvmVaRange *range = blk->range;
    UvmLocation dst = { UVM_TIER_CXL, 0 };
    if (range->hasPreferred && range->preferred.tier == UVM_TIER_CXL)
        dst.tier = UVM_TIER_CXL;
    else if (!uvmTierArenaCxl())
        dst.tier = UVM_TIER_HOST;

    /* Move only HBM-resident runs (a whole-block make-resident would drag
     * host-resident pages along). */
    uint32_t p = 0;
    bool demoted = false;
    while (p < blk->npages) {
        if (!uvmPageMaskTest(&blk->resident[UVM_TIER_HBM], p)) {
            p++;
            continue;
        }
        uint32_t span = 1;
        while (p + span < blk->npages &&
               uvmPageMaskTest(&blk->resident[UVM_TIER_HBM], p + span))
            span++;
        /* forWrite=true makes the demotion exclusive: a read-duplicated
         * HBM copy must actually drop, or the demote frees nothing. */
        if (uvmBlockMakeResident(blk, dst, p, span, true) == TPU_OK)
            demoted = true;
        p += span;
    }
    blk->acPromoted = false;
    if (demoted) {
        tpuCounterAdd("uvm_access_counter_demotions", 1);
        uvmToolsEmit(vs, UVM_EVENT_ACCESS_COUNTER, UVM_TIER_HBM, dst.tier,
                     blk->hbmDevInst, blk->start,
                     (uint64_t)blk->npages * uvmPageSize());
    }
    return demoted;
}
