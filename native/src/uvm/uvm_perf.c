/*
 * Perf heuristics: prefetch region growth + thrashing detection.
 *
 * Prefetch — re-design of the reference's tree-based region growth
 * (uvm_perf_prefetch.c: faults within a va_block grow power-of-two
 * aligned prefetch regions when the fault density crosses a threshold).
 * Here: the serviced region around a faulting page doubles with the
 * block's fault count inside a time window — 1 page on a cold block, up
 * to the whole block when faults are streaming.  Registry knobs:
 *   uvm_prefetch_enable   (default 1)
 *   uvm_prefetch_max_pages(default 32 = whole 2 MB block at 64 KB pages)
 *
 * Thrashing — re-design of uvm_perf_thrashing.c's detection + PIN/THROTTLE
 * hints (uvm_perf_thrashing.h:33-46): when a block's migration target
 * alternates tiers more than uvm_thrash_threshold times within
 * uvm_thrash_window_ms, the block is PINNED to the last device-side tier
 * for uvm_thrash_pin_ms; CPU read faults then duplicate instead of
 * invalidating (uvmBlockMakeResidentEx forceDup) and the eviction LRU
 * skips pinned blocks.  THROTTLE is implicit in batching.
 *
 * These run from the single fault-service thread without the block lock;
 * the counters are heuristic state and tolerate benign races (the
 * reference's perf modules are similarly advisory).
 */
#include "uvm_internal.h"

void uvmPerfPrefetchExpand(UvmVaBlock *blk, uint32_t page, bool deviceFault,
                           uint32_t *firstPage, uint32_t *count)
{
    *firstPage = page;
    *count = 1;
    static TpuRegCache c_pfEnable;
    if (!tpuRegCacheGet(&c_pfEnable, "uvm_prefetch_enable", 1))
        return;

    uint64_t now = uvmMonotonicNs();
    static TpuRegCache c_pfWindow;
    uint64_t windowNs = tpuRegCacheGet(&c_pfWindow,
                                       "uvm_prefetch_window_ms", 20) *
                        1000000ull;
    if (now - blk->windowStartNs > windowNs) {
        blk->windowStartNs = now;
        blk->windowFaults = 0;
    }
    blk->windowFaults++;
    blk->faultCount++;
    blk->lastFaultNs = now;

    /* Region doubles with fault pressure: 2^(faults-1) pages, aligned. */
    static TpuRegCache c_pfMax;
    uint32_t maxPages = (uint32_t)tpuRegCacheGet(&c_pfMax,
                                                 "uvm_prefetch_max_pages",
                                                 32);
    uint32_t ppb = blk->npages;
    uint32_t want = 1;
    uint32_t f = blk->windowFaults;
    while (f > 1 && want < maxPages && want < ppb) {
        want <<= 1;
        f >>= 1;
    }
    /* Device faults stream sequentially; give them one extra doubling. */
    if (deviceFault && want < maxPages && want < ppb)
        want <<= 1;
    if (want > ppb)
        want = ppb;

    uint32_t first = (page / want) * want;   /* aligned region */
    uint32_t cnt = want;
    if (first + cnt > ppb)
        cnt = ppb - first;
    *firstPage = first;
    *count = cnt;
    if (cnt > 1) {
        tpuCounterAdd("uvm_prefetch_pages", cnt - 1);
        uvmToolsEmit(blk->range->vaSpace, UVM_EVENT_PREFETCH, UVM_TIER_COUNT,
                     UVM_TIER_COUNT, 0, blk->start + (uint64_t)first *
                     uvmPageSize(), (uint64_t)cnt * uvmPageSize());
    }
}

/* ------------------------------------- prefetch effectiveness counters
 *
 * The ROADMAP prefetch item's feedback signal: every speculative page
 * the region growth pulls in is tracked until either an access lands
 * on it (uvm_prefetch_hits — the prefetch saved a fault) or an
 * eviction drops it untouched (uvm_prefetch_useless — the prefetch
 * wasted transport and arena space).  hits/(hits+useless) is the
 * prefetcher's measured precision.
 */

static uint32_t prefetch_count_and_clear(UvmVaBlock *blk, uint32_t first,
                                         uint32_t count)
{
    uint32_t n = 0;
    UVM_MASK_RANGE_WORDS(first, count, w, bm, {
        n += (uint32_t)__builtin_popcountll(blk->prefetched.bits[w] & bm);
        blk->prefetched.bits[w] &= ~bm;
    });
    return n;
}

void uvmPerfPrefetchTouch(UvmVaBlock *blk, uint32_t first, uint32_t count)
{
    if (!uvmPageMaskIntersectsRange(&blk->prefetched, first, count))
        return;                  /* common case: no lock, no counters */
    pthread_mutex_lock(&blk->lock);
    tpuLockTrackAcquire(TPU_LOCK_UVM_BLOCK, "prefetch-touch");
    uint32_t n = prefetch_count_and_clear(blk, first, count);
    tpuLockTrackRelease(TPU_LOCK_UVM_BLOCK, "prefetch-touch");
    pthread_mutex_unlock(&blk->lock);
    if (n)
        tpuCounterAdd("uvm_prefetch_hits", n);
}

void uvmPerfPrefetchMark(UvmVaBlock *blk, uint32_t reqFirst,
                         uint32_t reqCount, uint32_t first, uint32_t count)
{
    pthread_mutex_lock(&blk->lock);
    tpuLockTrackAcquire(TPU_LOCK_UVM_BLOCK, "prefetch-mark");
    uvmPageMaskSetRange(&blk->prefetched, first, count);
    /* The requested span was DEMANDED, not speculated. */
    uvmPageMaskClearRange(&blk->prefetched, reqFirst, reqCount);
    tpuLockTrackRelease(TPU_LOCK_UVM_BLOCK, "prefetch-mark");
    pthread_mutex_unlock(&blk->lock);
}

void uvmPerfPrefetchEvictLocked(UvmVaBlock *blk, uint32_t first,
                                uint32_t count)
{
    uint32_t n = prefetch_count_and_clear(blk, first, count);
    if (n)
        tpuCounterAdd("uvm_prefetch_useless", n);
}

void uvmPerfThrashingRecord(UvmVaBlock *blk, UvmTier targetTier)
{
    static TpuRegCache c_thEnable;
    if (!tpuRegCacheGet(&c_thEnable, "uvm_thrash_enable", 1))
        return;
    uint64_t now = uvmMonotonicNs();
    static TpuRegCache c_thWindow;
    uint64_t windowNs = tpuRegCacheGet(&c_thWindow,
                                       "uvm_thrash_window_ms", 100) *
                        1000000ull;

    if (blk->pinnedTier >= 0 && blk->pinExpiryNs <= now) {
        blk->pinnedTier = -1;
        blk->windowSwitches = 0;
    }

    if (blk->lastTargetTier >= 0 &&
        blk->lastTargetTier != (int32_t)targetTier) {
        /* Dedicated window (prefetch owns windowStartNs on its own 20 ms
         * cadence; sharing it would keep this window forever fresh). */
        if (now - blk->thrashWindowStartNs > windowNs) {
            blk->thrashWindowStartNs = now;
            blk->windowSwitches = 0;
        }
        blk->windowSwitches++;
        static TpuRegCache c_thThresh;
        uint32_t threshold =
            (uint32_t)tpuRegCacheGet(&c_thThresh, "uvm_thrash_threshold", 3);
        if (blk->windowSwitches >= threshold && blk->pinnedTier < 0) {
            /* Pin to the device-side tier of the ping-pong pair so the
             * device copy survives; CPU reads duplicate against it. */
            UvmTier pinTo = targetTier != UVM_TIER_HOST
                                ? targetTier
                                : (UvmTier)blk->lastTargetTier;
            if (pinTo == UVM_TIER_HOST)
                pinTo = UVM_TIER_HBM;
            blk->pinnedTier = (int32_t)pinTo;
            static TpuRegCache c_thPin;
            blk->pinExpiryNs = now + tpuRegCacheGet(&c_thPin,
                                                    "uvm_thrash_pin_ms",
                                                    300) * 1000000ull;
            blk->windowSwitches = 0;
            tpuCounterAdd("uvm_thrash_pins", 1);
            uvmToolsEmit(blk->range->vaSpace, UVM_EVENT_THRASHING,
                         UVM_TIER_COUNT, pinTo, blk->hbmDevInst, blk->start,
                         (uint64_t)blk->npages * uvmPageSize());
        }
    }
    blk->lastTargetTier = (int32_t)targetTier;
}

bool uvmPerfBlockPinnedAgainst(UvmVaBlock *blk, UvmTier targetTier)
{
    if (blk->pinnedTier < 0)
        return false;
    if (blk->pinExpiryNs <= uvmMonotonicNs())
        return false;
    return blk->pinnedTier != (int32_t)targetTier;
}

/* ------------------------------------------------------ access counters */

/* Hotness sampling (re-design of uvm_gpu_access_counters.c:81: HW
 * notifications of remote-access hotness become candidate migrations).
 * The TPU engine sees every device access span (uvmDeviceAccess), so the
 * "counter notification" is synthesized in the service loop: accesses
 * serviced WITHOUT HBM placement (accessed-by mappings, CXL-preferred or
 * thrash-pinned targets) count here; crossing the threshold inside the
 * window promotes the block to the device's HBM.  Registry knobs:
 *   uvm_access_counter_enable     (default 1)
 *   uvm_access_counter_threshold  (default 8 remote accesses)
 *   uvm_access_counter_window_ms  (default 100)
 *   uvm_access_counter_decay_ms   (default 250 — cold promoted blocks
 *                                  demote back to CXL/host)
 */
bool uvmAccessCounterRecord(UvmVaBlock *blk)
{
    static TpuRegCache c_acEnable;
    if (!tpuRegCacheGet(&c_acEnable, "uvm_access_counter_enable", 1))
        return false;
    uint64_t now = uvmMonotonicNs();
    static TpuRegCache c_acWindow;
    uint64_t windowNs = tpuRegCacheGet(&c_acWindow,
                                       "uvm_access_counter_window_ms",
                                       100) * 1000000ull;
    if (now - blk->acWindowStartNs > windowNs) {
        blk->acWindowStartNs = now;
        blk->acCount = 0;
    }
    blk->acCount++;
    /* Multi-page device spans skip prefetch (which owns lastFaultNs for
     * CPU faults), so refresh the decay clock here too — otherwise a
     * device hammering a block reads as idle and the sweeper demotes
     * still-hot data. */
    blk->lastFaultNs = now;
    static TpuRegCache c_acThresh;
    uint32_t threshold =
        (uint32_t)tpuRegCacheGet(&c_acThresh,
                                 "uvm_access_counter_threshold", 8);
    if (blk->acCount >= threshold) {
        blk->acCount = 0;
        tpuCounterAdd("uvm_access_counter_promotions", 1);
        return true;
    }
    return false;
}

bool uvmAccessCounterMaybeDemote(UvmVaSpace *vs, UvmVaBlock *blk)
{
    if (!blk->acPromoted)
        return false;
    uint64_t now = uvmMonotonicNs();
    static TpuRegCache c_acDecay;
    uint64_t decayNs = tpuRegCacheGet(&c_acDecay,
                                      "uvm_access_counter_decay_ms", 250) *
                       1000000ull;
    if (now - blk->lastFaultNs < decayNs)
        return false;
    if (uvmPageMaskEmpty(&blk->resident[UVM_TIER_HBM], blk->npages)) {
        blk->acPromoted = false;       /* already moved elsewhere */
        return false;
    }

    /* Demote target: the range's preferred device-side tier if it names
     * CXL, else CXL when an arena exists, else host. */
    UvmVaRange *range = blk->range;
    UvmLocation dst = { UVM_TIER_CXL, 0 };
    if (range->hasPreferred && range->preferred.tier == UVM_TIER_CXL)
        dst.tier = UVM_TIER_CXL;
    else if (!uvmTierArenaCxl())
        dst.tier = UVM_TIER_HOST;

    /* Move only HBM-resident runs (a whole-block make-resident would drag
     * host-resident pages along). */
    uint32_t p = 0;
    bool demoted = false;
    while (p < blk->npages) {
        if (!uvmPageMaskTest(&blk->resident[UVM_TIER_HBM], p)) {
            p++;
            continue;
        }
        uint32_t span = 1;
        while (p + span < blk->npages &&
               uvmPageMaskTest(&blk->resident[UVM_TIER_HBM], p + span))
            span++;
        /* forWrite=true makes the demotion exclusive: a read-duplicated
         * HBM copy must actually drop, or the demote frees nothing. */
        if (uvmBlockMakeResident(blk, dst, p, span, true) == TPU_OK)
            demoted = true;
        p += span;
    }
    blk->acPromoted = false;
    if (demoted) {
        tpuCounterAdd("uvm_access_counter_demotions", 1);
        uvmToolsEmit(vs, UVM_EVENT_ACCESS_COUNTER, UVM_TIER_HBM, dst.tier,
                     blk->hbmDevInst, blk->start,
                     (uint64_t)blk->npages * uvmPageSize());
    }
    return demoted;
}
