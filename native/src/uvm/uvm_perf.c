/*
 * Perf heuristics: prefetch region growth + thrashing detection.
 *
 * Prefetch — re-design of the reference's tree-based region growth
 * (uvm_perf_prefetch.c: faults within a va_block grow power-of-two
 * aligned prefetch regions when the fault density crosses a threshold).
 * Here: the serviced region around a faulting page doubles with the
 * block's fault count inside a time window — 1 page on a cold block, up
 * to the whole block when faults are streaming.  Registry knobs:
 *   uvm_prefetch_enable   (default 1)
 *   uvm_prefetch_max_pages(default 32 = whole 2 MB block at 64 KB pages)
 *
 * Thrashing — re-design of uvm_perf_thrashing.c's detection + PIN/THROTTLE
 * hints (uvm_perf_thrashing.h:33-46): when a block's migration target
 * alternates tiers more than uvm_thrash_threshold times within
 * uvm_thrash_window_ms, the block is PINNED to the last device-side tier
 * for uvm_thrash_pin_ms; CPU read faults then duplicate instead of
 * invalidating (uvmBlockMakeResidentEx forceDup) and the eviction LRU
 * skips pinned blocks.  THROTTLE is implicit in batching.
 *
 * These run from the single fault-service thread without the block lock;
 * the counters are heuristic state and tolerate benign races (the
 * reference's perf modules are similarly advisory).
 */
#include "uvm_internal.h"

void uvmPerfPrefetchExpand(UvmVaBlock *blk, uint32_t page, bool deviceFault,
                           uint32_t *firstPage, uint32_t *count)
{
    *firstPage = page;
    *count = 1;
    if (!tpuRegistryGet("uvm_prefetch_enable", 1))
        return;

    uint64_t now = uvmMonotonicNs();
    uint64_t windowNs = tpuRegistryGet("uvm_prefetch_window_ms", 20) *
                        1000000ull;
    if (now - blk->windowStartNs > windowNs) {
        blk->windowStartNs = now;
        blk->windowFaults = 0;
    }
    blk->windowFaults++;
    blk->faultCount++;
    blk->lastFaultNs = now;

    /* Region doubles with fault pressure: 2^(faults-1) pages, aligned. */
    uint32_t maxPages = (uint32_t)tpuRegistryGet("uvm_prefetch_max_pages", 32);
    uint32_t ppb = blk->npages;
    uint32_t want = 1;
    uint32_t f = blk->windowFaults;
    while (f > 1 && want < maxPages && want < ppb) {
        want <<= 1;
        f >>= 1;
    }
    /* Device faults stream sequentially; give them one extra doubling. */
    if (deviceFault && want < maxPages && want < ppb)
        want <<= 1;
    if (want > ppb)
        want = ppb;

    uint32_t first = (page / want) * want;   /* aligned region */
    uint32_t cnt = want;
    if (first + cnt > ppb)
        cnt = ppb - first;
    *firstPage = first;
    *count = cnt;
    if (cnt > 1) {
        tpuCounterAdd("uvm_prefetch_pages", cnt - 1);
        uvmToolsEmit(blk->range->vaSpace, UVM_EVENT_PREFETCH, UVM_TIER_COUNT,
                     UVM_TIER_COUNT, 0, blk->start + (uint64_t)first *
                     uvmPageSize(), (uint64_t)cnt * uvmPageSize());
    }
}

void uvmPerfThrashingRecord(UvmVaBlock *blk, UvmTier targetTier)
{
    if (!tpuRegistryGet("uvm_thrash_enable", 1))
        return;
    uint64_t now = uvmMonotonicNs();
    uint64_t windowNs = tpuRegistryGet("uvm_thrash_window_ms", 100) *
                        1000000ull;

    if (blk->pinnedTier >= 0 && blk->pinExpiryNs <= now) {
        blk->pinnedTier = -1;
        blk->windowSwitches = 0;
    }

    if (blk->lastTargetTier >= 0 &&
        blk->lastTargetTier != (int32_t)targetTier) {
        /* Dedicated window (prefetch owns windowStartNs on its own 20 ms
         * cadence; sharing it would keep this window forever fresh). */
        if (now - blk->thrashWindowStartNs > windowNs) {
            blk->thrashWindowStartNs = now;
            blk->windowSwitches = 0;
        }
        blk->windowSwitches++;
        uint32_t threshold =
            (uint32_t)tpuRegistryGet("uvm_thrash_threshold", 3);
        if (blk->windowSwitches >= threshold && blk->pinnedTier < 0) {
            /* Pin to the device-side tier of the ping-pong pair so the
             * device copy survives; CPU reads duplicate against it. */
            UvmTier pinTo = targetTier != UVM_TIER_HOST
                                ? targetTier
                                : (UvmTier)blk->lastTargetTier;
            if (pinTo == UVM_TIER_HOST)
                pinTo = UVM_TIER_HBM;
            blk->pinnedTier = (int32_t)pinTo;
            blk->pinExpiryNs = now + tpuRegistryGet("uvm_thrash_pin_ms",
                                                    300) * 1000000ull;
            blk->windowSwitches = 0;
            tpuCounterAdd("uvm_thrash_pins", 1);
            uvmToolsEmit(blk->range->vaSpace, UVM_EVENT_THRASHING,
                         UVM_TIER_COUNT, pinTo, blk->hbmDevInst, blk->start,
                         (uint64_t)blk->npages * uvmPageSize());
        }
    }
    blk->lastTargetTier = (int32_t)targetTier;
}

bool uvmPerfBlockPinnedAgainst(UvmVaBlock *blk, UvmTier targetTier)
{
    if (blk->pinnedTier < 0)
        return false;
    if (blk->pinExpiryNs <= uvmMonotonicNs())
        return false;
    return blk->pinnedTier != (int32_t)targetTier;
}
