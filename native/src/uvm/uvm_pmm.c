/*
 * PMM — physical memory manager for a tier arena.
 *
 * Re-design of the reference's GPU chunk allocator (uvm_pmm_gpu.c): 2 MB
 * root chunks split down a power-of-two ladder (reference chunk sizes,
 * uvm_pmm_gpu.h:60-85), freelists per level, buddy merge on free.  The
 * reference tracks USER/KERNEL chunk types and PMA callbacks; here the
 * arena is a flat byte range (device HBM window or the CXL expander
 * window) and eviction is orchestrated by the block-LRU layer above
 * (uvm_va_block.c / uvm_tier.c in this tree), matching the reference's
 * split between PMM chunk bookkeeping and va_block eviction logic.
 *
 * Roots are materialized lazily so a 96 GB HBM arena costs no metadata
 * until used.
 */
#include "uvm_internal.h"
#include "tpurm/trace.h"
#include "tpurm/inject.h"

#include <stdlib.h>

static uint64_t level_size(const UvmPmm *pmm, uint8_t level)
{
    (void)pmm;
    return UVM_BLOCK_SIZE >> level;
}

static uint8_t size_to_level(const UvmPmm *pmm, uint64_t size)
{
    uint8_t level = 0;
    uint64_t s = UVM_BLOCK_SIZE;
    while (s > size && (uint32_t)(level + 1) < pmm->levels) {
        s >>= 1;
        level++;
    }
    return level;
}

static void freelist_push(UvmPmm *pmm, UvmPmmChunk *c)
{
    c->allocated = false;
    c->prev = NULL;
    c->next = pmm->freelist[c->level];
    if (c->next)
        c->next->prev = c;
    pmm->freelist[c->level] = c;
}

static void freelist_unlink(UvmPmm *pmm, UvmPmmChunk *c)
{
    if (c->prev)
        c->prev->next = c->next;
    else
        pmm->freelist[c->level] = c->next;
    if (c->next)
        c->next->prev = c->prev;
    c->prev = c->next = NULL;
}

TpuStatus uvmPmmInit(UvmPmm *pmm, uint64_t arenaSize, uint64_t chunkMin)
{
    if (arenaSize < UVM_BLOCK_SIZE || chunkMin < 4096 ||
        (chunkMin & (chunkMin - 1)) != 0 || chunkMin > UVM_BLOCK_SIZE)
        return TPU_ERR_INVALID_ARGUMENT;

    pthread_mutex_init(&pmm->lock, NULL);
    pmm->arenaSize = arenaSize & ~(UVM_BLOCK_SIZE - 1);
    pmm->chunkMin = chunkMin;
    pmm->levels = 1;
    for (uint64_t s = UVM_BLOCK_SIZE; s > chunkMin; s >>= 1)
        pmm->levels++;
    if (pmm->levels > UVM_PMM_MAX_LEVELS)
        return TPU_ERR_INVALID_ARGUMENT;
    pmm->allocatedBytes = 0;
    for (uint32_t i = 0; i < UVM_PMM_MAX_LEVELS; i++)
        pmm->freelist[i] = NULL;
    pmm->rootCount = pmm->arenaSize / UVM_BLOCK_SIZE;
    pmm->rootChunks = calloc(pmm->rootCount, sizeof(UvmPmmChunk *));
    if (!pmm->rootChunks)
        return TPU_ERR_NO_MEMORY;
    return TPU_OK;
}

void uvmPmmDeinit(UvmPmm *pmm)
{
    /* Frees all chunk metadata; the caller guarantees no chunks are in
     * use.  Child chunks are reachable from freelists only. */
    for (uint32_t lvl = 1; lvl < pmm->levels; lvl++) {
        UvmPmmChunk *c = pmm->freelist[lvl];
        while (c) {
            UvmPmmChunk *next = c->next;
            free(c);
            c = next;
        }
        pmm->freelist[lvl] = NULL;
    }
    for (uint64_t i = 0; i < pmm->rootCount; i++)
        free(pmm->rootChunks[i]);
    free(pmm->rootChunks);
    pmm->rootChunks = NULL;
    pthread_mutex_destroy(&pmm->lock);
}

/* Materialize the next unused root chunk, if any. */
static UvmPmmChunk *pmm_new_root(UvmPmm *pmm)
{
    for (uint64_t i = 0; i < pmm->rootCount; i++) {
        if (!pmm->rootChunks[i]) {
            UvmPmmChunk *c = calloc(1, sizeof(*c));
            if (!c)
                return NULL;
            c->offset = i * UVM_BLOCK_SIZE;
            c->level = 0;
            pmm->rootChunks[i] = c;
            return c;
        }
    }
    return NULL;
}

TpuStatus uvmPmmAlloc(UvmPmm *pmm, uint64_t size, UvmPmmChunk **out)
{
    if (size < pmm->chunkMin || size > UVM_BLOCK_SIZE ||
        (size & (size - 1)) != 0)
        return TPU_ERR_INVALID_ARGUMENT;

    /* Injected allocation fault (ECC-retired-chunk analog).  A distinct
     * status from genuine exhaustion: eviction cannot cure a bad chunk,
     * so the caller goes straight to tier fallback instead of churning
     * the LRU. */
    if (tpurmInjectShouldFail(TPU_INJECT_SITE_PMM_ALLOC))
        return TPU_ERR_INSUFFICIENT_RESOURCES;

    uint64_t tSpan = tpurmTraceBegin();
    pthread_mutex_lock(&pmm->lock);
    tpuLockTrackAcquire(TPU_LOCK_UVM_PMM, "pmm");
    uint8_t want = size_to_level(pmm, size);

    /* Find the deepest level <= want with a free chunk, splitting down. */
    int lvl = want;
    UvmPmmChunk *c = NULL;
    while (lvl >= 0) {
        if (pmm->freelist[lvl]) {
            c = pmm->freelist[lvl];
            freelist_unlink(pmm, c);
            break;
        }
        lvl--;
    }
    if (!c) {
        c = pmm_new_root(pmm);
        lvl = 0;
    }
    if (!c) {
        tpuLockTrackRelease(TPU_LOCK_UVM_PMM, "pmm");
        pthread_mutex_unlock(&pmm->lock);
        return TPU_ERR_NO_MEMORY;
    }

    /* Split down to the wanted level, pushing right buddies free. */
    while ((uint8_t)lvl < want) {
        UvmPmmChunk *right = calloc(1, sizeof(*right));
        if (!right) {
            freelist_push(pmm, c);
            tpuLockTrackRelease(TPU_LOCK_UVM_PMM, "pmm");
            pthread_mutex_unlock(&pmm->lock);
            return TPU_ERR_NO_MEMORY;
        }
        lvl++;
        c->level = (uint8_t)lvl;
        right->level = (uint8_t)lvl;
        right->offset = c->offset + level_size(pmm, (uint8_t)lvl);
        right->buddyParent = c->buddyParent;  /* same root lineage */
        freelist_push(pmm, right);
    }

    c->allocated = true;
    pmm->allocatedBytes += size;
    tpuCounterAdd("pmm_chunk_allocs", 1);
    tpuLockTrackRelease(TPU_LOCK_UVM_PMM, "pmm");
    pthread_mutex_unlock(&pmm->lock);
    if (tSpan)
        tpurmTraceEnd(TPU_TRACE_PMM_ALLOC, tSpan, c->offset, size);
    *out = c;
    return TPU_OK;
}

void uvmPmmFree(UvmPmm *pmm, UvmPmmChunk *chunk)
{
    if (!chunk)
        return;
    pthread_mutex_lock(&pmm->lock);
    tpuLockTrackAcquire(TPU_LOCK_UVM_PMM, "pmm");
    pmm->allocatedBytes -= level_size(pmm, chunk->level);
    tpuCounterAdd("pmm_chunk_frees", 1);

    /* Buddy merge: coalesce while the sibling chunk is free at the same
     * level.  Siblings are identified by offset parity at the level. */
    UvmPmmChunk *c = chunk;
    while (c->level > 0) {
        uint64_t sz = level_size(pmm, c->level);
        uint64_t buddyOff = c->offset ^ sz;
        UvmPmmChunk *buddy = NULL;
        for (UvmPmmChunk *f = pmm->freelist[c->level]; f; f = f->next) {
            if (f->offset == buddyOff) {
                buddy = f;
                break;
            }
        }
        if (!buddy)
            break;
        freelist_unlink(pmm, buddy);
        /* Keep the lower-offset chunk as the merged parent. */
        UvmPmmChunk *keep = c->offset < buddy->offset ? c : buddy;
        UvmPmmChunk *drop = keep == c ? buddy : c;
        /* Root chunks are owned by rootChunks[]; never free those. */
        keep->level = c->level - 1;
        if (pmm->rootChunks[drop->offset / UVM_BLOCK_SIZE] == drop &&
            drop->level == 0) {
            /* unreachable: roots are level 0 and loop requires level>0 */
        }
        free(drop);
        c = keep;
    }
    if (c->level == 0) {
        /* Fully merged root: return its slot so metadata stays bounded. */
        uint64_t slot = c->offset / UVM_BLOCK_SIZE;
        if (pmm->rootChunks[slot] == c) {
            pmm->rootChunks[slot] = NULL;
            free(c);
        } else {
            /* A split descendant merged back to root size but the slot
             * holds the original root pointer: adopt the slot. */
            free(pmm->rootChunks[slot]);
            pmm->rootChunks[slot] = NULL;
            free(c);
        }
    } else {
        freelist_push(pmm, c);
    }
    tpuLockTrackRelease(TPU_LOCK_UVM_PMM, "pmm");
    pthread_mutex_unlock(&pmm->lock);
}

uint64_t uvmPmmChunkSize(const UvmPmm *pmm, const UvmPmmChunk *c)
{
    return level_size(pmm, c->level);
}

uint64_t uvmPmmAllocatedBytes(UvmPmm *pmm)
{
    pthread_mutex_lock(&pmm->lock);
    uint64_t b = pmm->allocatedBytes;
    pthread_mutex_unlock(&pmm->lock);
    return b;
}
