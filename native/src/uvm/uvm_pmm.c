/*
 * PMM — physical memory manager for a tier arena.
 *
 * Re-design of the reference's GPU chunk allocator (uvm_pmm_gpu.c): 2 MB
 * root chunks split down a power-of-two ladder (reference chunk sizes,
 * uvm_pmm_gpu.h:60-85), freelists per level, buddy merge on free.  The
 * reference tracks USER/KERNEL chunk types and PMA callbacks; here the
 * arena is a flat byte range (device HBM window or the CXL expander
 * window) and eviction is orchestrated by the block-LRU layer above
 * (uvm_va_block.c / uvm_tier.c in this tree), matching the reference's
 * split between PMM chunk bookkeeping and va_block eviction logic.
 *
 * Roots are materialized lazily so a 96 GB HBM arena costs no metadata
 * until used.
 *
 * LOCK STRIPING (the sharded-spine companion): the freelists are split
 * across UvmPmmShard stripes, each root chunk owned by shard
 * (rootIndex % shardCount).  Buddies never cross a 2 MB root
 * (buddyOff = offset ^ size stays inside the root), so every merge is
 * intra-shard and a chunk's shard is stable for its whole life.
 * Allocation tries the caller's home stripe with a trylock (a miss
 * counts tier_lock_contended) and walks the siblings before reporting
 * exhaustion, so striping never manufactures NO_MEMORY.
 */
#include "uvm_internal.h"
#include "tpurm/trace.h"
#include "tpurm/inject.h"

#include <stdlib.h>
#include <unistd.h>

static uint64_t level_size(const UvmPmm *pmm, uint8_t level)
{
    (void)pmm;
    return UVM_BLOCK_SIZE >> level;
}

static uint8_t size_to_level(const UvmPmm *pmm, uint64_t size)
{
    uint8_t level = 0;
    uint64_t s = UVM_BLOCK_SIZE;
    while (s > size && (uint32_t)(level + 1) < pmm->levels) {
        s >>= 1;
        level++;
    }
    return level;
}

static inline UvmPmmShard *pmm_shard_of(UvmPmm *pmm, uint64_t offset)
{
    return &pmm->shards[(offset / UVM_BLOCK_SIZE) % pmm->shardCount];
}

/* The caller's home stripe: sticky per thread, dealt round-robin — a
 * stable home keeps one fault worker's splits and merges on one lock. */
static uint32_t pmm_home_shard(const UvmPmm *pmm)
{
    static _Atomic uint32_t cursor;
    static __thread uint32_t home = UINT32_MAX;
    if (home == UINT32_MAX)
        home = atomic_fetch_add_explicit(&cursor, 1,
                                         memory_order_relaxed);
    return home % pmm->shardCount;
}

static void freelist_push(UvmPmmShard *sh, UvmPmmChunk *c)
{
    c->allocated = false;
    c->prev = NULL;
    c->next = sh->freelist[c->level];
    if (c->next)
        c->next->prev = c;
    sh->freelist[c->level] = c;
}

static void freelist_unlink(UvmPmmShard *sh, UvmPmmChunk *c)
{
    if (c->prev)
        c->prev->next = c->next;
    else
        sh->freelist[c->level] = c->next;
    if (c->next)
        c->next->prev = c->prev;
    c->prev = c->next = NULL;
}

TpuStatus uvmPmmInit(UvmPmm *pmm, uint64_t arenaSize, uint64_t chunkMin)
{
    if (arenaSize < UVM_BLOCK_SIZE || chunkMin < 4096 ||
        (chunkMin & (chunkMin - 1)) != 0 || chunkMin > UVM_BLOCK_SIZE)
        return TPU_ERR_INVALID_ARGUMENT;

    pmm->arenaSize = arenaSize & ~(UVM_BLOCK_SIZE - 1);
    pmm->chunkMin = chunkMin;
    pmm->levels = 1;
    for (uint64_t s = UVM_BLOCK_SIZE; s > chunkMin; s >>= 1)
        pmm->levels++;
    if (pmm->levels > UVM_PMM_MAX_LEVELS)
        return TPU_ERR_INVALID_ARGUMENT;
    atomic_store_explicit(&pmm->allocatedBytes, 0, memory_order_relaxed);
    pmm->rootCount = pmm->arenaSize / UVM_BLOCK_SIZE;
    long ncpu = sysconf(_SC_NPROCESSORS_ONLN);
    if (ncpu < 1)
        ncpu = 1;
    uint64_t dflt = (uint64_t)ncpu < UVM_PMM_MAX_SHARDS ? (uint64_t)ncpu
                                                        : UVM_PMM_MAX_SHARDS;
    uint64_t shards = tpuRegistryGet("tier_lock_shards", dflt);
    if (shards < 1)
        shards = 1;
    if (shards > UVM_PMM_MAX_SHARDS)
        shards = UVM_PMM_MAX_SHARDS;
    if (shards > pmm->rootCount)
        shards = pmm->rootCount;      /* a stripe needs a root to own */
    pmm->shardCount = (uint32_t)shards;
    for (uint32_t s = 0; s < pmm->shardCount; s++) {
        pthread_mutex_init(&pmm->shards[s].lock, NULL);
        for (uint32_t i = 0; i < UVM_PMM_MAX_LEVELS; i++)
            pmm->shards[s].freelist[i] = NULL;
    }
    pmm->rootChunks = calloc(pmm->rootCount, sizeof(UvmPmmChunk *));
    if (!pmm->rootChunks)
        return TPU_ERR_NO_MEMORY;
    return TPU_OK;
}

void uvmPmmDeinit(UvmPmm *pmm)
{
    /* Frees all chunk metadata; the caller guarantees no chunks are in
     * use.  Child chunks are reachable from freelists only. */
    for (uint32_t s = 0; s < pmm->shardCount; s++) {
        for (uint32_t lvl = 1; lvl < pmm->levels; lvl++) {
            UvmPmmChunk *c = pmm->shards[s].freelist[lvl];
            while (c) {
                UvmPmmChunk *next = c->next;
                free(c);
                c = next;
            }
            pmm->shards[s].freelist[lvl] = NULL;
        }
        pthread_mutex_destroy(&pmm->shards[s].lock);
    }
    for (uint64_t i = 0; i < pmm->rootCount; i++)
        free(pmm->rootChunks[i]);
    free(pmm->rootChunks);
    pmm->rootChunks = NULL;
}

/* Materialize the next unused root chunk OWNED BY `shard`, if any
 * (that shard's lock held: root slot i belongs to shard i % count). */
static UvmPmmChunk *pmm_new_root(UvmPmm *pmm, uint32_t shard)
{
    for (uint64_t i = shard; i < pmm->rootCount; i += pmm->shardCount) {
        if (!pmm->rootChunks[i]) {
            UvmPmmChunk *c = calloc(1, sizeof(*c));
            if (!c)
                return NULL;
            c->offset = i * UVM_BLOCK_SIZE;
            c->level = 0;
            pmm->rootChunks[i] = c;
            return c;
        }
    }
    return NULL;
}

TpuStatus uvmPmmAlloc(UvmPmm *pmm, uint64_t size, UvmPmmChunk **out)
{
    if (size < pmm->chunkMin || size > UVM_BLOCK_SIZE ||
        (size & (size - 1)) != 0)
        return TPU_ERR_INVALID_ARGUMENT;

    /* Injected allocation fault (ECC-retired-chunk analog).  A distinct
     * status from genuine exhaustion: eviction cannot cure a bad chunk,
     * so the caller goes straight to tier fallback instead of churning
     * the LRU. */
    if (tpurmInjectShouldFail(TPU_INJECT_SITE_PMM_ALLOC))
        return TPU_ERR_INSUFFICIENT_RESOURCES;

    uint64_t tSpan = tpurmTraceBegin();
    uint8_t want = size_to_level(pmm, size);
    uint32_t home = pmm_home_shard(pmm);

    /* Home stripe first, then the siblings: striping must never turn a
     * non-empty arena into NO_MEMORY. */
    for (uint32_t k = 0; k < pmm->shardCount; k++) {
        uint32_t si = (home + k) % pmm->shardCount;
        UvmPmmShard *sh = &pmm->shards[si];
        if (k == 0 && pthread_mutex_trylock(&sh->lock) != 0) {
            tpuCounterAdd("tier_lock_contended", 1);
            pthread_mutex_lock(&sh->lock);
        } else if (k > 0) {
            pthread_mutex_lock(&sh->lock);
        }
        tpuLockTrackAcquire(TPU_LOCK_UVM_PMM, "pmm");

        /* Find the deepest level <= want with a free chunk, splitting
         * down. */
        int lvl = want;
        UvmPmmChunk *c = NULL;
        while (lvl >= 0) {
            if (sh->freelist[lvl]) {
                c = sh->freelist[lvl];
                freelist_unlink(sh, c);
                break;
            }
            lvl--;
        }
        if (!c) {
            c = pmm_new_root(pmm, si);
            lvl = 0;
        }
        if (!c) {
            /* This stripe is exhausted; try the next one. */
            tpuLockTrackRelease(TPU_LOCK_UVM_PMM, "pmm");
            pthread_mutex_unlock(&sh->lock);
            continue;
        }

        /* Split down to the wanted level, pushing right buddies free. */
        while ((uint8_t)lvl < want) {
            UvmPmmChunk *right = calloc(1, sizeof(*right));
            if (!right) {
                freelist_push(sh, c);
                tpuLockTrackRelease(TPU_LOCK_UVM_PMM, "pmm");
                pthread_mutex_unlock(&sh->lock);
                return TPU_ERR_NO_MEMORY;
            }
            lvl++;
            c->level = (uint8_t)lvl;
            right->level = (uint8_t)lvl;
            right->offset = c->offset + level_size(pmm, (uint8_t)lvl);
            right->buddyParent = c->buddyParent;  /* same root lineage */
            freelist_push(sh, right);
        }

        c->allocated = true;
        atomic_fetch_add_explicit(&pmm->allocatedBytes, size,
                                  memory_order_relaxed);
        tpuCounterAdd("pmm_chunk_allocs", 1);
        tpuLockTrackRelease(TPU_LOCK_UVM_PMM, "pmm");
        pthread_mutex_unlock(&sh->lock);
        if (tSpan)
            tpurmTraceEnd(TPU_TRACE_PMM_ALLOC, tSpan, c->offset, size);
        *out = c;
        return TPU_OK;
    }
    return TPU_ERR_NO_MEMORY;
}

void uvmPmmFree(UvmPmm *pmm, UvmPmmChunk *chunk)
{
    if (!chunk)
        return;
    /* The chunk's stripe is derived from its offset — the same shard
     * that allocated it, so merge candidates are all here. */
    UvmPmmShard *sh = pmm_shard_of(pmm, chunk->offset);
    pthread_mutex_lock(&sh->lock);
    tpuLockTrackAcquire(TPU_LOCK_UVM_PMM, "pmm");
    atomic_fetch_sub_explicit(&pmm->allocatedBytes,
                              level_size(pmm, chunk->level),
                              memory_order_relaxed);
    tpuCounterAdd("pmm_chunk_frees", 1);

    /* Buddy merge: coalesce while the sibling chunk is free at the same
     * level.  Siblings are identified by offset parity at the level. */
    UvmPmmChunk *c = chunk;
    while (c->level > 0) {
        uint64_t sz = level_size(pmm, c->level);
        uint64_t buddyOff = c->offset ^ sz;
        UvmPmmChunk *buddy = NULL;
        for (UvmPmmChunk *f = sh->freelist[c->level]; f; f = f->next) {
            if (f->offset == buddyOff) {
                buddy = f;
                break;
            }
        }
        if (!buddy)
            break;
        freelist_unlink(sh, buddy);
        /* Keep the lower-offset chunk as the merged parent. */
        UvmPmmChunk *keep = c->offset < buddy->offset ? c : buddy;
        UvmPmmChunk *drop = keep == c ? buddy : c;
        /* Root chunks are owned by rootChunks[]; never free those. */
        keep->level = c->level - 1;
        if (pmm->rootChunks[drop->offset / UVM_BLOCK_SIZE] == drop &&
            drop->level == 0) {
            /* unreachable: roots are level 0 and loop requires level>0 */
        }
        free(drop);
        c = keep;
    }
    if (c->level == 0) {
        /* Fully merged root: return its slot so metadata stays bounded
         * (slot i is owned by this stripe: i % shardCount == stripe). */
        uint64_t slot = c->offset / UVM_BLOCK_SIZE;
        if (pmm->rootChunks[slot] == c) {
            pmm->rootChunks[slot] = NULL;
            free(c);
        } else {
            /* A split descendant merged back to root size but the slot
             * holds the original root pointer: adopt the slot. */
            free(pmm->rootChunks[slot]);
            pmm->rootChunks[slot] = NULL;
            free(c);
        }
    } else {
        freelist_push(sh, c);
    }
    tpuLockTrackRelease(TPU_LOCK_UVM_PMM, "pmm");
    pthread_mutex_unlock(&sh->lock);
}

uint64_t uvmPmmChunkSize(const UvmPmm *pmm, const UvmPmmChunk *c)
{
    return level_size(pmm, c->level);
}

uint64_t uvmPmmAllocatedBytes(UvmPmm *pmm)
{
    return atomic_load_explicit(&pmm->allocatedBytes,
                                memory_order_relaxed);
}
