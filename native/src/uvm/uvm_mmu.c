/*
 * uvm_mmu — the device-side MMU: per-device page tables over the
 * managed VA, with batched PTE writes and batched TLB invalidates.
 *
 * Re-design of the reference trio (uvm_mmu.c — GPU page-table tree over
 * the portable walker lib; uvm_pte_batch.c — PTE writes coalesced into
 * batches; uvm_tlb_batch.c — invalidates accumulated per operation and
 * issued once with a membar).  TPU-native shape: the device VA equals
 * the managed CPU VA (the reference's UVM identity mapping), and a PTE
 * resolves it to (tier, arena offset) — the address a DMA engine needs.
 * "TLB" state is a per-device invalidate generation: consumers caching
 * translations revalidate when the generation moves, and every batch
 * flush is one generation bump + one release fence, exactly the
 * one-invalidate-per-batch economy the reference's batch exists for.
 *
 * Tables: 3-level radix over the 48-bit VA at uvm-page granularity
 * (VPN split 13/13/10 — covers the full 36-bit VPN at the 4 KB page
 * floor; at the 64 KB default the top bits are simply zero).
 * Directories install
 * with CAS so concurrent faults on different blocks never lock; PTE
 * stores are release so a translate acquiring the PTE sees the mapped
 * bytes.
 */
#define _GNU_SOURCE
#include "uvm_internal.h"

#include <stdatomic.h>
#include <stdlib.h>
#include <string.h>

/* 13/13/10 covers a 36-bit VPN — the full 48-bit VA even at the 4 KB
 * registry page size (uvm_page_size floor). */
#define MMU_TOP_BITS 13
#define MMU_MID_BITS 13
#define MMU_LEAF_BITS 10
#define MMU_TOP_N (1u << MMU_TOP_BITS)
#define MMU_MID_N (1u << MMU_MID_BITS)
#define MMU_LEAF_N (1u << MMU_LEAF_BITS)

/* PTE layout: [63:pageShift] offset (page-aligned by construction —
 * the mask is derived from the RUNTIME uvm page size, which the
 * registry may lower to 4 KB), [3:2] tier, [1] writable, [0] valid. */
#define PTE_VALID 0x1ull
#define PTE_WRITE 0x2ull
#define PTE_TIER_SHIFT 2
#define PTE_TIER_MASK (0x3ull << PTE_TIER_SHIFT)

static uint64_t pte_off_mask(void)
{
    return ~(uvmPageSize() - 1);
}

typedef struct {
    _Atomic uint64_t pte[MMU_LEAF_N];
} MmuLeaf;

typedef struct {
    _Atomic(MmuLeaf *) leaves[MMU_MID_N];
} MmuMid;

typedef struct {
    _Atomic(MmuMid *) mids[MMU_TOP_N];
    _Atomic uint64_t tlbGeneration;
    _Atomic uint64_t pteWrites, pteClears, tlbInvalidates;
} DevMmu;

static struct {
    pthread_once_t once;
    DevMmu *mmus;               /* one per enumerated device */
    uint32_t count;
} g_mmu = { .once = PTHREAD_ONCE_INIT };

static void mmu_init_once(void)
{
    tpuDeviceGlobalInit();
    g_mmu.count = tpurmDeviceCount();
    g_mmu.mmus = calloc(g_mmu.count, sizeof(DevMmu));
}

static DevMmu *mmu_get(uint32_t devInst)
{
    pthread_once(&g_mmu.once, mmu_init_once);
    if (!g_mmu.mmus || devInst >= g_mmu.count)
        return NULL;
    return &g_mmu.mmus[devInst];
}

/* Leaf for `va`, creating directories on demand (NULL = no table and
 * create not requested, or OOM). */
static MmuLeaf *mmu_leaf(DevMmu *m, uint64_t va, bool create,
                         uint32_t *leafIdx)
{
    uint64_t vpn = va >> __builtin_ctzll(uvmPageSize());
    uint32_t li = (uint32_t)(vpn & (MMU_LEAF_N - 1));
    uint32_t mi = (uint32_t)((vpn >> MMU_LEAF_BITS) & (MMU_MID_N - 1));
    uint32_t ti = (uint32_t)((vpn >> (MMU_LEAF_BITS + MMU_MID_BITS)) &
                             (MMU_TOP_N - 1));
    *leafIdx = li;

    MmuMid *mid = atomic_load_explicit(&m->mids[ti], memory_order_acquire);
    if (!mid) {
        if (!create)
            return NULL;
        MmuMid *fresh = calloc(1, sizeof(*fresh));
        if (!fresh)
            return NULL;
        MmuMid *expect = NULL;
        if (atomic_compare_exchange_strong(&m->mids[ti], &expect, fresh))
            mid = fresh;
        else {
            free(fresh);
            mid = expect;
        }
    }
    MmuLeaf *leaf = atomic_load_explicit(&mid->leaves[mi],
                                         memory_order_acquire);
    if (!leaf) {
        if (!create)
            return NULL;
        MmuLeaf *fresh = calloc(1, sizeof(*fresh));
        if (!fresh)
            return NULL;
        MmuLeaf *expect = NULL;
        if (atomic_compare_exchange_strong(&mid->leaves[mi], &expect,
                                           fresh))
            leaf = fresh;
        else {
            free(fresh);
            leaf = expect;
        }
    }
    return leaf;
}

/* ----------------------------------------------------------- PTE batch */

void uvmPteBatchBegin(UvmPteBatch *b, uint32_t devInst)
{
    b->devInst = devInst;
    b->count = 0;
    b->clearedLive = 0;
}

static void pte_batch_flush(UvmPteBatch *b)
{
    DevMmu *m = mmu_get(b->devInst);
    if (m) {
        for (uint32_t i = 0; i < b->count; i++) {
            uint32_t li;
            MmuLeaf *leaf = mmu_leaf(m, b->entries[i].va,
                                     /*create=*/b->entries[i].pte != 0,
                                     &li);
            if (!leaf)
                continue;       /* clear of a never-mapped page */
            uint64_t old = atomic_exchange_explicit(
                &leaf->pte[li], b->entries[i].pte, memory_order_release);
            if (b->entries[i].pte) {
                atomic_fetch_add_explicit(&m->pteWrites, 1,
                                          memory_order_relaxed);
            } else if (old & PTE_VALID) {
                atomic_fetch_add_explicit(&m->pteClears, 1,
                                          memory_order_relaxed);
                b->clearedLive++;
            }
        }
        tpuCounterAdd("uvm_mmu_pte_batches", 1);
        uvmToolsEmit(NULL, UVM_EVENT_PTE_UPDATE, UVM_TIER_COUNT,
                     UVM_TIER_COUNT, b->devInst,
                     b->count ? b->entries[0].va : 0, b->count);
    }
    b->count = 0;
}

static void pte_batch_add(UvmPteBatch *b, uint64_t va, uint64_t pte)
{
    if (b->count == UVM_PTE_BATCH_MAX)
        pte_batch_flush(b);
    b->entries[b->count].va = va;
    b->entries[b->count].pte = pte;
    b->count++;
}

void uvmPteBatchWrite(UvmPteBatch *b, uint64_t va, UvmTier tier,
                      uint64_t tierOff, bool writable)
{
    pte_batch_add(b, va, (tierOff & pte_off_mask()) |
                         ((uint64_t)tier << PTE_TIER_SHIFT) |
                         (writable ? PTE_WRITE : 0) | PTE_VALID);
}

void uvmPteBatchClear(UvmPteBatch *b, uint64_t va)
{
    pte_batch_add(b, va, 0);
}

void uvmPteBatchEnd(UvmPteBatch *b)
{
    if (b->count)
        pte_batch_flush(b);
}

/* ----------------------------------------------------------- TLB batch */

void uvmTlbBatchBegin(UvmTlbBatch *b, uint32_t devInst)
{
    b->devInst = devInst;
    b->pendingPages = 0;
}

void uvmTlbBatchAdd(UvmTlbBatch *b, uint64_t va, uint32_t npages)
{
    (void)va;                   /* ranges fold into one invalidate */
    b->pendingPages += npages;
}

/* One invalidate for the whole batch (uvm_tlb_batch economy): a release
 * fence orders the preceding PTE stores, then the generation bump tells
 * translation caches to revalidate. */
void uvmTlbBatchEnd(UvmTlbBatch *b)
{
    if (b->pendingPages == 0)
        return;
    DevMmu *m = mmu_get(b->devInst);
    if (!m)
        return;
    atomic_thread_fence(memory_order_release);
    atomic_fetch_add_explicit(&m->tlbGeneration, 1, memory_order_acq_rel);
    atomic_fetch_add_explicit(&m->tlbInvalidates, 1, memory_order_relaxed);
    tpuCounterAdd("uvm_mmu_tlb_invalidates", 1);
    tpuCounterAdd("uvm_mmu_tlb_pages", b->pendingPages);
    uvmToolsEmit(NULL, UVM_EVENT_TLB_INVALIDATE, UVM_TIER_COUNT,
                 UVM_TIER_COUNT, b->devInst, 0, b->pendingPages);
    b->pendingPages = 0;
}

/* ----------------------------------------------------------- translate */

TpuStatus uvmDevMmuTranslate(uint32_t devInst, uint64_t va, UvmTier *tier,
                             uint64_t *tierOff, bool *writable)
{
    DevMmu *m = mmu_get(devInst);
    if (!m)
        return TPU_ERR_INVALID_DEVICE;
    uint32_t li;
    MmuLeaf *leaf = mmu_leaf(m, va, /*create=*/false, &li);
    if (!leaf)
        return TPU_ERR_INVALID_ADDRESS;
    uint64_t pte = atomic_load_explicit(&leaf->pte[li],
                                        memory_order_acquire);
    if (!(pte & PTE_VALID))
        return TPU_ERR_INVALID_ADDRESS;
    uint64_t ps = uvmPageSize();
    if (tier)
        *tier = (UvmTier)((pte & PTE_TIER_MASK) >> PTE_TIER_SHIFT);
    if (tierOff)
        *tierOff = (pte & ~(ps - 1)) | (va & (ps - 1));
    if (writable)
        *writable = (pte & PTE_WRITE) != 0;
    return TPU_OK;
}

uint64_t uvmDevMmuTlbGeneration(uint32_t devInst)
{
    DevMmu *m = mmu_get(devInst);
    return m ? atomic_load_explicit(&m->tlbGeneration,
                                    memory_order_acquire)
             : 0;
}

void uvmDevMmuStats(uint32_t devInst, uint64_t *pteWrites,
                    uint64_t *pteClears, uint64_t *tlbInvalidates)
{
    DevMmu *m = mmu_get(devInst);
    if (!m) {
        if (pteWrites)
            *pteWrites = 0;
        if (pteClears)
            *pteClears = 0;
        if (tlbInvalidates)
            *tlbInvalidates = 0;
        return;
    }
    if (pteWrites)
        *pteWrites = atomic_load(&m->pteWrites);
    if (pteClears)
        *pteClears = atomic_load(&m->pteClears);
    if (tlbInvalidates)
        *tlbInvalidates = atomic_load(&m->tlbInvalidates);
}
