/*
 * tpuvac health — per-device health scoring, the evacuation
 * rendezvous, and transactional migration manifests (model and
 * contracts in include/tpurm/health.h).
 *
 * Concurrency: per-device STATE is an atomic (hot readers — the
 * Prometheus render, the scheduler poll, PickTarget — never take the
 * lock); everything else (score mutation, rendezvous fields, the
 * transaction table) sits under one mutex.  tpurmHealthNote is called
 * from inside other subsystems' locks (g_ici.lock, memring popLock),
 * so nothing here may call back into ici/memring while holding
 * g_health.lock — the two places that need route queries
 * (PickTarget, VacBegin/Commit) run them UNLOCKED and tolerate the
 * benign races (the single watchdog thread is the only ladder/tick
 * caller; operator requests race it at worst into an INVALID_STATE
 * "already pending" result).
 */
#define _GNU_SOURCE
#include "tpurm/health.h"

#include <pthread.h>
#include <stdatomic.h>
#include <string.h>

#include "internal.h"
#include "tpurm/ici.h"
#include "tpurm/journal.h"
#include "tpurm/reset.h"
#include "tpurm/trace.h"
#include "uvm/uvm_internal.h"

#define HEALTH_MAX_DEVICES 16
#define VAC_MAX_TXNS 16

/* Event weights (score points added per note).  Chosen so a single
 * transient (one flap, one nudge) never leaves HEALTHY at the default
 * thresholds, while a burst of real trouble (quarantine + RC resets,
 * repeated flaps) crosses DEGRADED fast and sustained trouble crosses
 * EVACUATING. */
static const uint32_t g_weights[TPU_HEALTH_EV_COUNT] = {
    [TPU_HEALTH_EV_RC_RESET] = 300,
    [TPU_HEALTH_EV_WD_NUDGE] = 60,
    [TPU_HEALTH_EV_LINK_FLAP] = 200,
    [TPU_HEALTH_EV_RETRAIN_FAIL] = 260,
    [TPU_HEALTH_EV_PAGE_QUARANTINE] = 400,
    [TPU_HEALTH_EV_STALE_COMPLETION] = 150,
    [TPU_HEALTH_EV_DEADLINE_EXPIRED] = 120,
    [TPU_HEALTH_EV_DEVICE_RESET] = 500,
};

static const char *const g_eventNames[TPU_HEALTH_EV_COUNT] = {
    "rc_reset",
    "wd_nudge",
    "link_flap",
    "retrain_fail",
    "page_quarantine",
    "stale_completion",
    "deadline_expired",
    "device_reset",
};

static const char *const g_stateNames[] = {
    "HEALTHY", "DEGRADED", "EVACUATING"
};

typedef struct {
    _Atomic uint32_t state;         /* TPU_HEALTH_* (lock-free readers) */
    uint64_t score;                 /* decayed points; lock held        */
    uint64_t lastDecayNs;
    uint64_t lastEventNs;
    uint64_t transitions;
    uint64_t events[TPU_HEALTH_EV_COUNT];
    /* Evacuation rendezvous. */
    bool evacPending;
    uint32_t evacTarget;
    uint64_t evacReqId;
    uint64_t evacPostedNs;
    uint64_t evacCooldownNs;        /* no re-post before this           */
} HealthDev;

typedef struct {
    uint64_t id;                    /* 0 = slot free                    */
    uint32_t src, dst;
    uint64_t gen;                   /* device generation at begin       */
    uint64_t startNs;
} VacTxn;

static struct {
    pthread_mutex_t lock;
    HealthDev dev[HEALTH_MAX_DEVICES];
    uint64_t nextReqId;
    uint64_t nextTxnId;
    VacTxn txns[VAC_MAX_TXNS];
    _Atomic uint32_t txnsActive;
} g_health = { .lock = PTHREAD_MUTEX_INITIALIZER,
               .nextReqId = 1, .nextTxnId = 1 };

const char *tpurmHealthEventName(uint32_t event)
{
    return event < TPU_HEALTH_EV_COUNT ? g_eventNames[event] : NULL;
}

const char *tpurmHealthStateName(uint32_t state)
{
    return state <= TPU_HEALTH_EVACUATING ? g_stateNames[state] : "?";
}

/* Lazy exponential decay: one halving per elapsed half-life, plus a
 * linear interpolation of the partial half-life — integer-only and
 * monotone, which is all the hysteresis needs. */
static void health_decay_locked(HealthDev *d, uint64_t now)
{
    uint64_t halflifeNs =
        tpuRegistryGet("vac_health_halflife_ms", 2000) * 1000000ull;
    if (!halflifeNs || now <= d->lastDecayNs) {
        d->lastDecayNs = now;
        return;
    }
    uint64_t dt = now - d->lastDecayNs;
    uint64_t halvings = dt / halflifeNs;
    d->score = halvings >= 64 ? 0 : d->score >> halvings;
    /* Partial half-life: score -= score * frac / 2 (frac in [0,1)). */
    uint64_t rem = dt % halflifeNs;
    d->score -= (d->score >> 1) / halflifeNs * rem +
                (((d->score >> 1) % halflifeNs) * rem) / halflifeNs;
    d->lastDecayNs = now;
}

static void health_set_state_locked(uint32_t devInst, HealthDev *d,
                                    uint32_t newState)
{
    uint32_t old = atomic_load_explicit(&d->state, memory_order_relaxed);
    if (old == newState)
        return;
    atomic_store_explicit(&d->state, newState, memory_order_release);
    d->transitions++;
    tpuCounterAdd("tpurm_health_transitions", 1);
    tpurmJournalEmit(TPU_JREC_HEALTH_TRANSITION, devInst, TPU_OK,
                     old, newState);
    tpurmTraceInstantLabel(TPU_TRACE_HEALTH_TRANSITION, devInst,
                           newState, "health.transition");
    TPU_LOG(newState > old ? TPU_LOG_WARN : TPU_LOG_INFO, "health",
           "device %u health %s -> %s (score=%llu)", devInst,
           g_stateNames[old], g_stateNames[newState],
           (unsigned long long)d->score);
}

/* Promotion is immediate; demotion needs half-threshold score AND a
 * quiet hold window — both evaluated here after a decay or a note. */
static void health_update_state_locked(uint32_t devInst, HealthDev *d,
                                       uint64_t now)
{
    uint64_t degrade = tpuRegistryGet("vac_degrade_score", 500);
    uint64_t evac = tpuRegistryGet("vac_evac_score", 1000);
    uint64_t holdNs = tpuRegistryGet("vac_health_hold_ms", 1000) *
                      1000000ull;
    uint32_t st = atomic_load_explicit(&d->state, memory_order_relaxed);
    if (d->score >= evac) {
        health_set_state_locked(devInst, d, TPU_HEALTH_EVACUATING);
        return;
    }
    if (d->score >= degrade && st < TPU_HEALTH_DEGRADED) {
        health_set_state_locked(devInst, d, TPU_HEALTH_DEGRADED);
        return;
    }
    bool quiet = now - d->lastEventNs >= holdNs;
    if (st == TPU_HEALTH_EVACUATING && quiet && d->score < evac / 2)
        health_set_state_locked(devInst, d, TPU_HEALTH_DEGRADED);
    else if (st == TPU_HEALTH_DEGRADED && quiet && d->score < degrade / 2)
        health_set_state_locked(devInst, d, TPU_HEALTH_HEALTHY);
}

void tpurmHealthNote(uint32_t devInst, uint32_t event)
{
    if (devInst >= HEALTH_MAX_DEVICES || event >= TPU_HEALTH_EV_COUNT)
        return;
    uint64_t now = tpuNowNs();
    pthread_mutex_lock(&g_health.lock);
    HealthDev *d = &g_health.dev[devInst];
    health_decay_locked(d, now);
    d->score += g_weights[event];
    d->events[event]++;
    d->lastEventNs = now;
    /* Black box: one health.note record per note, carrying the event
     * kind and the post-decay score (emit is lock-free: safe under
     * g_health.lock AND under whatever engine lock the caller holds). */
    tpurmJournalEmit(TPU_JREC_HEALTH_NOTE, devInst, TPU_OK, event,
                     d->score);
    health_update_state_locked(devInst, d, now);
    pthread_mutex_unlock(&g_health.lock);
}

uint32_t tpurmDeviceHealthState(uint32_t devInst)
{
    if (devInst >= HEALTH_MAX_DEVICES)
        return TPU_HEALTH_HEALTHY;
    return atomic_load_explicit(&g_health.dev[devInst].state,
                                memory_order_acquire);
}

uint64_t tpurmDeviceHealthScore(uint32_t devInst)
{
    if (devInst >= HEALTH_MAX_DEVICES)
        return 0;
    uint64_t now = tpuNowNs();
    pthread_mutex_lock(&g_health.lock);
    HealthDev *d = &g_health.dev[devInst];
    health_decay_locked(d, now);
    uint64_t s = d->score;
    pthread_mutex_unlock(&g_health.lock);
    return s;
}

TpuStatus tpurmHealthInfo(uint32_t devInst, TpuHealthInfo *out)
{
    if (!out || devInst >= HEALTH_MAX_DEVICES)
        return TPU_ERR_INVALID_ARGUMENT;
    uint64_t now = tpuNowNs();
    pthread_mutex_lock(&g_health.lock);
    HealthDev *d = &g_health.dev[devInst];
    health_decay_locked(d, now);
    health_update_state_locked(devInst, d, now);
    memset(out, 0, sizeof(*out));
    out->state = atomic_load_explicit(&d->state, memory_order_relaxed);
    out->score = d->score;
    out->transitions = d->transitions;
    out->lastEventNs = d->lastEventNs;
    memcpy(out->events, d->events, sizeof(out->events));
    out->evacPending = d->evacPending ? 1 : 0;
    out->evacTarget = d->evacTarget;
    out->evacReqId = d->evacReqId;
    pthread_mutex_unlock(&g_health.lock);
    return TPU_OK;
}

void tpurmHealthClear(uint32_t devInst)
{
    if (devInst >= HEALTH_MAX_DEVICES)
        return;
    pthread_mutex_lock(&g_health.lock);
    HealthDev *d = &g_health.dev[devInst];
    uint64_t now = tpuNowNs();
    d->score = 0;
    d->lastDecayNs = now;
    d->lastEventNs = 0;
    memset(d->events, 0, sizeof(d->events));
    d->evacPending = false;
    d->evacCooldownNs = 0;
    health_set_state_locked(devInst, d, TPU_HEALTH_HEALTHY);
    pthread_mutex_unlock(&g_health.lock);
}

/* ------------------------------------------------- evacuation rendezvous */

TpuStatus tpurmHealthPickTarget(uint32_t srcInst, uint32_t *targetOut)
{
    if (!targetOut)
        return TPU_ERR_INVALID_ARGUMENT;
    uint32_t n = tpurmDeviceCount();
    if (n > HEALTH_MAX_DEVICES)
        n = HEALTH_MAX_DEVICES;
    uint64_t headroomPct = tpuRegistryGet("vac_headroom_pct", 10);
    uint32_t best = ~0u, bestHops = ~0u;
    for (uint32_t d = 0; d < n; d++) {
        if (d == srcInst)
            continue;
        TpurmDevice *dev = tpurmDeviceGet(d);
        if (!dev || dev->lost)
            continue;
        if (tpurmDeviceHealthState(d) != TPU_HEALTH_HEALTHY)
            continue;
        uint64_t freeB = 0, totalB = 0;
        if (uvmHbmArenaUsage(d, &freeB, &totalB) != TPU_OK || !totalB)
            continue;
        if (freeB * 100 < totalB * headroomPct)
            continue;               /* no quota headroom */
        uint32_t hops;
        if (tpuIciRouteHops(srcInst, d, &hops) != TPU_OK)
            continue;               /* partitioned from the source */
        if (hops < bestHops) {
            best = d;
            bestHops = hops;
        }
    }
    if (best == ~0u)
        return TPU_ERR_OBJECT_NOT_FOUND;
    *targetOut = best;
    return TPU_OK;
}

/* Post a request (lock held, target already resolved).  The
 * tpurm_watchdog_evacuations rung counter is NOT bumped here — only
 * the watchdog call sites (tick, ladder) count it, so operator planned
 * moves never read as phantom ladder escalations. */
static void evac_post_locked(uint32_t devInst, HealthDev *d,
                             uint32_t target, uint64_t now)
{
    d->evacPending = true;
    d->evacTarget = target;
    d->evacReqId = g_health.nextReqId++;
    d->evacPostedNs = now;
    tpuCounterAdd("vac_requests", 1);
    tpurmJournalEmit(TPU_JREC_HEALTH_EVAC, devInst, TPU_OK,
                     d->evacReqId, target);
    TPU_LOG(TPU_LOG_WARN, "health",
           "EVACUATE requested: device %u -> %u (req %llu, state %s)",
           devInst, target, (unsigned long long)d->evacReqId,
           g_stateNames[atomic_load_explicit(&d->state,
                                             memory_order_relaxed)]);
}

TpuStatus tpurmHealthEvacRequest(uint32_t devInst, uint32_t target)
{
    if (devInst >= HEALTH_MAX_DEVICES || devInst >= tpurmDeviceCount())
        return TPU_ERR_INVALID_DEVICE;
    if (target == ~0u) {
        TpuStatus st = tpurmHealthPickTarget(devInst, &target);
        if (st != TPU_OK)
            return st;
    } else if (target >= tpurmDeviceCount() || target == devInst) {
        return TPU_ERR_INVALID_ARGUMENT;
    }
    uint64_t now = tpuNowNs();
    pthread_mutex_lock(&g_health.lock);
    HealthDev *d = &g_health.dev[devInst];
    if (d->evacPending) {
        pthread_mutex_unlock(&g_health.lock);
        return TPU_ERR_INVALID_STATE;
    }
    evac_post_locked(devInst, d, target, now);
    tpuCounterAdd("vac_operator_requests", 1);
    pthread_mutex_unlock(&g_health.lock);
    return TPU_OK;
}

bool tpurmHealthEvacPending(uint32_t devInst, uint32_t *targetOut,
                            uint64_t *reqIdOut)
{
    if (devInst >= HEALTH_MAX_DEVICES)
        return false;
    uint64_t graceNs = tpuRegistryGet("vac_grace_ms", 1500) * 1000000ull;
    uint64_t now = tpuNowNs();
    bool pending = false;
    pthread_mutex_lock(&g_health.lock);
    HealthDev *d = &g_health.dev[devInst];
    if (d->evacPending && now - d->evacPostedNs <= graceNs) {
        pending = true;
        if (targetOut)
            *targetOut = d->evacTarget;
        if (reqIdOut)
            *reqIdOut = d->evacReqId;
    }
    pthread_mutex_unlock(&g_health.lock);
    return pending;
}

TpuStatus tpurmHealthEvacAck(uint32_t devInst, uint64_t reqId,
                             bool success)
{
    if (devInst >= HEALTH_MAX_DEVICES)
        return TPU_ERR_INVALID_ARGUMENT;
    pthread_mutex_lock(&g_health.lock);
    HealthDev *d = &g_health.dev[devInst];
    if (!d->evacPending || d->evacReqId != reqId) {
        pthread_mutex_unlock(&g_health.lock);
        return TPU_ERR_INVALID_ARGUMENT;
    }
    d->evacPending = false;
    if (!success) {
        /* Failed evacuation: cool down so the watchdog does not storm
         * re-posts at tick rate; the ladder may escalate meanwhile. */
        d->evacCooldownNs = tpuNowNs() +
            tpuRegistryGet("vac_grace_ms", 1500) * 1000000ull;
        tpuCounterAdd("vac_failed_acks", 1);
    }
    pthread_mutex_unlock(&g_health.lock);
    if (success) {
        tpuCounterAdd("vac_acks", 1);
        /* The tenant left the chip; its error history predicts nothing
         * about the NEXT tenant — start the score clean (the state
         * machine will re-degrade in one note burst if the chip is
         * genuinely sick). */
        tpurmHealthClear(devInst);
        /* An evacuated chip is leaving service: REMOTE-tier leases it
         * was lending become invalid NOW, not at the next health-state
         * read — borrowers fall back to their HOST copies lazily. */
        uvmTierRemoteRevokeLender(devInst);
    }
    TPU_LOG(TPU_LOG_WARN, "health", "evacuation of device %u %s (req %llu)",
           devInst, success ? "ACKED" : "FAILED",
           (unsigned long long)reqId);
    return TPU_OK;
}

/* Broker-aware operator entry (uvm/vac.py planned moves): forward to
 * the engine host when this process is a broker client. */
TpuStatus tpurmHealthEvacRequestClient(uint32_t devInst, uint32_t target)
{
    TpuStatus st = tpurmBrokerVacRequest(devInst, target);
    if (st != TPU_ERR_NOT_SUPPORTED)
        return st;                  /* brokered (or broker-side error) */
    return tpurmHealthEvacRequest(devInst, target);
}

/* Consume requests whose grace expired (no serving layer picked them
 * up).  Returns true when one expired THIS pass — the ladder treats
 * that as "evacuation was offered and declined". */
static bool evac_expire_locked(uint32_t devInst, HealthDev *d,
                               uint64_t now, uint64_t graceNs)
{
    if (!d->evacPending || now - d->evacPostedNs <= graceNs)
        return false;
    d->evacPending = false;
    d->evacCooldownNs = now + 4 * graceNs;
    tpuCounterAdd("vac_grace_expired", 1);
    TPU_LOG(TPU_LOG_WARN, "health",
           "evacuation request for device %u expired un-acked (req %llu)",
           devInst, (unsigned long long)d->evacReqId);
    return true;
}

void tpurmHealthTick(void)
{
    if (!tpuRegistryGet("vac_enable", 1))
        return;
    uint32_t n = tpurmDeviceCount();
    if (n > HEALTH_MAX_DEVICES)
        n = HEALTH_MAX_DEVICES;
    uint64_t graceNs = tpuRegistryGet("vac_grace_ms", 1500) * 1000000ull;
    uint64_t now = tpuNowNs();

    /* Decay + demotion + grace expiry under the lock... */
    uint32_t wantEvac[HEALTH_MAX_DEVICES];
    uint32_t nWant = 0;
    pthread_mutex_lock(&g_health.lock);
    for (uint32_t i = 0; i < n; i++) {
        HealthDev *d = &g_health.dev[i];
        health_decay_locked(d, now);
        health_update_state_locked(i, d, now);
        evac_expire_locked(i, d, now, graceNs);
        if (atomic_load_explicit(&d->state, memory_order_relaxed) ==
                TPU_HEALTH_EVACUATING &&
            !d->evacPending && now >= d->evacCooldownNs)
            wantEvac[nWant++] = i;
    }
    pthread_mutex_unlock(&g_health.lock);

    /* ...then target picking (route queries) OUTSIDE it.  The posting
     * re-checks pending under the lock, so an operator request racing
     * this tick cannot be double-posted. */
    for (uint32_t k = 0; k < nWant; k++) {
        uint32_t dev = wantEvac[k], target;
        if (tpurmHealthPickTarget(dev, &target) != TPU_OK)
            continue;               /* nowhere to go: the ladder decides */
        pthread_mutex_lock(&g_health.lock);
        HealthDev *d = &g_health.dev[dev];
        if (!d->evacPending && now >= d->evacCooldownNs) {
            evac_post_locked(dev, d, target, now);
            tpuCounterAddScoped("tpurm_watchdog_evacuations", dev, 1);
            tpurmJournalEmit(TPU_JREC_WD_RUNG, dev, TPU_OK, 25,
                             d->evacReqId);
        }
        pthread_mutex_unlock(&g_health.lock);
    }
}

bool tpurmHealthEvacLadderRung(void)
{
    if (!tpuRegistryGet("vac_enable", 1))
        return false;
    uint32_t n = tpurmDeviceCount();
    if (n > HEALTH_MAX_DEVICES)
        n = HEALTH_MAX_DEVICES;
    uint64_t graceNs = tpuRegistryGet("vac_grace_ms", 1500) * 1000000ull;
    uint64_t now = tpuNowNs();

    /* A pending request inside its grace window absorbs the rung (the
     * serving layer is being given its chance to drain).  An expired
     * one is consumed here and the rung FALLS THROUGH to the device
     * reset — recovery never waits on an absent scheduler. */
    uint32_t sick = ~0u;
    uint64_t sickScore = 0;
    pthread_mutex_lock(&g_health.lock);
    for (uint32_t i = 0; i < n; i++) {
        HealthDev *d = &g_health.dev[i];
        if (d->evacPending) {
            if (now - d->evacPostedNs <= graceNs) {
                pthread_mutex_unlock(&g_health.lock);
                return true;
            }
            evac_expire_locked(i, d, now, graceNs);
            pthread_mutex_unlock(&g_health.lock);
            return false;
        }
        uint32_t st = atomic_load_explicit(&d->state,
                                           memory_order_relaxed);
        if (st >= TPU_HEALTH_DEGRADED && now >= d->evacCooldownNs &&
            (sick == ~0u || d->score > sickScore)) {
            sick = i;
            sickScore = d->score;
        }
    }
    pthread_mutex_unlock(&g_health.lock);
    if (sick == ~0u)
        return false;               /* nothing attributable: reset */

    uint32_t target;
    if (tpurmHealthPickTarget(sick, &target) != TPU_OK)
        return false;               /* no healthy peer with headroom */
    pthread_mutex_lock(&g_health.lock);
    HealthDev *d = &g_health.dev[sick];
    bool posted = false;
    if (!d->evacPending && now >= d->evacCooldownNs) {
        evac_post_locked(sick, d, target, now);
        tpuCounterAddScoped("tpurm_watchdog_evacuations", sick, 1);
        tpurmJournalEmit(TPU_JREC_WD_RUNG, sick, TPU_OK, 25,
                         d->evacReqId);
        posted = true;
    }
    pthread_mutex_unlock(&g_health.lock);
    return posted;
}

/* ---------------------------------------------------- vac transactions */

TpuStatus tpurmVacBegin(uint32_t srcInst, uint32_t dstInst,
                        uint64_t *txnOut)
{
    if (!txnOut || srcInst == dstInst)
        return TPU_ERR_INVALID_ARGUMENT;
    TpurmDevice *src = tpurmDeviceGet(srcInst);
    TpurmDevice *dst = tpurmDeviceGet(dstInst);
    if (!src || !dst)
        return TPU_ERR_INVALID_DEVICE;
    if (src->lost || dst->lost)
        return TPU_ERR_GPU_IS_LOST;
    uint32_t hops;
    if (tpuIciRouteHops(srcInst, dstInst, &hops) != TPU_OK)
        return TPU_ERR_RETRAIN_FAILED;      /* partitioned */
    uint64_t gen = tpurmDeviceGeneration();

    pthread_mutex_lock(&g_health.lock);
    VacTxn *t = NULL;
    for (int i = 0; i < VAC_MAX_TXNS; i++)
        if (g_health.txns[i].id == 0) {
            t = &g_health.txns[i];
            break;
        }
    if (!t) {
        pthread_mutex_unlock(&g_health.lock);
        return TPU_ERR_INSUFFICIENT_RESOURCES;
    }
    t->id = g_health.nextTxnId++;
    t->src = srcInst;
    t->dst = dstInst;
    t->gen = gen;
    t->startNs = tpuNowNs();
    *txnOut = t->id;
    atomic_fetch_add(&g_health.txnsActive, 1);
    pthread_mutex_unlock(&g_health.lock);
    tpuCounterAdd("vac_txn_begins", 1);
    tpurmJournalEmit(TPU_JREC_VAC_BEGIN, srcInst, TPU_OK, *txnOut,
                     ((uint64_t)srcInst << 32) | dstInst);
    return TPU_OK;
}

static VacTxn *vac_find_locked(uint64_t txn)
{
    for (int i = 0; i < VAC_MAX_TXNS; i++)
        if (g_health.txns[i].id == txn)
            return &g_health.txns[i];
    return NULL;
}

TpuStatus tpurmVacCommit(uint64_t txn)
{
    pthread_mutex_lock(&g_health.lock);
    VacTxn *t = vac_find_locked(txn);
    if (!t) {
        pthread_mutex_unlock(&g_health.lock);
        return TPU_ERR_OBJECT_NOT_FOUND;
    }
    uint32_t src = t->src, dst = t->dst;
    uint64_t gen = t->gen, startNs = t->startNs;
    pthread_mutex_unlock(&g_health.lock);

    /* Validation runs UNLOCKED (route query takes g_ici.lock): the
     * transaction is single-owner by contract — only its creator
     * commits/aborts it. */
    TpuStatus st = TPU_OK;
    if (tpurmDeviceGeneration() != gen) {
        /* A full-device reset ran under the migration: in-flight page
         * state on BOTH ends predates the reset's save/restore — the
         * manifest is invalid by definition. */
        st = TPU_ERR_DEVICE_RESET;
    } else {
        TpurmDevice *dstDev = tpurmDeviceGet(dst);
        if (!dstDev || dstDev->lost)
            st = TPU_ERR_GPU_IS_LOST;       /* target died mid-move */
        else {
            uint32_t hops;
            if (tpuIciRouteHops(src, dst, &hops) != TPU_OK)
                st = TPU_ERR_RETRAIN_FAILED; /* fabric partitioned */
        }
    }
    if (st != TPU_OK) {
        /* The transaction STAYS OPEN: the caller must abort — its
         * source copy is still the only truth. */
        tpuCounterAdd("vac_commit_rejected", 1);
        TPU_LOG(TPU_LOG_WARN, "health",
               "vac commit REJECTED (txn %llu %u->%u): %s",
               (unsigned long long)txn, src, dst, tpuStatusToString(st));
        return st;
    }

    pthread_mutex_lock(&g_health.lock);
    t = vac_find_locked(txn);
    if (t) {
        t->id = 0;
        atomic_fetch_sub(&g_health.txnsActive, 1);
    }
    pthread_mutex_unlock(&g_health.lock);
    tpuCounterAdd("vac_commits", 1);
    tpuCounterAdd("vac_commit_ns", tpuNowNs() - startNs);
    tpurmJournalEmit(TPU_JREC_VAC_COMMIT, src, TPU_OK, txn,
                     ((uint64_t)src << 32) | dst);
    return TPU_OK;
}

TpuStatus tpurmVacAbort(uint64_t txn)
{
    pthread_mutex_lock(&g_health.lock);
    VacTxn *t = vac_find_locked(txn);
    if (!t) {
        pthread_mutex_unlock(&g_health.lock);
        return TPU_ERR_OBJECT_NOT_FOUND;
    }
    uint32_t src = t->src, dst = t->dst;
    t->id = 0;
    atomic_fetch_sub(&g_health.txnsActive, 1);
    pthread_mutex_unlock(&g_health.lock);
    tpuCounterAdd("vac_aborts", 1);
    tpurmJournalEmit(TPU_JREC_VAC_ABORT, src, TPU_OK, txn,
                     ((uint64_t)src << 32) | dst);
    TPU_LOG(TPU_LOG_WARN, "health",
           "vac ABORT (txn %llu %u->%u): source remains authoritative",
           (unsigned long long)txn, src, dst);
    /* Fatal-path black box: an aborted manifest means a migration's
     * work was thrown away — capture the why while it is still hot. */
    tpurmJournalCrashDump("vac.abort");
    return TPU_OK;
}

uint32_t tpurmVacActive(void)
{
    return atomic_load_explicit(&g_health.txnsActive,
                                memory_order_acquire);
}

/* ------------------------------------------------------------ raw dump
 *
 * Crash-bundle section (journal.c dumper): LOCK-FREE snapshot of the
 * health table and the open vac transactions.  The dumper may run
 * from a signal handler while the interrupted thread holds
 * g_health.lock, so this reads the fields directly — torn values are
 * possible and benign (the bundle is diagnostic, not transactional). */
TPU_NO_TSAN void tpurmHealthDumpRaw(TpuDumpCur *c)
{
    uint32_t n = tpurmDeviceCount();
    if (n > HEALTH_MAX_DEVICES)
        n = HEALTH_MAX_DEVICES;
    for (uint32_t i = 0; i < n; i++) {
        HealthDev *d = &g_health.dev[i];
        tpuDumpStr(c, "H dev ");
        tpuDumpU64(c, i);
        tpuDumpStr(c, " state ");
        tpuDumpU64(c, atomic_load_explicit(&d->state,
                                           memory_order_relaxed));
        tpuDumpStr(c, " score ");
        tpuDumpU64(c, d->score);
        tpuDumpStr(c, " trans ");
        tpuDumpU64(c, d->transitions);
        tpuDumpStr(c, " evac ");
        tpuDumpU64(c, d->evacPending ? d->evacTarget + 1 : 0);
        tpuDumpStr(c, " ev");
        for (uint32_t e = 0; e < TPU_HEALTH_EV_COUNT; e++) {
            tpuDumpStr(c, " ");
            tpuDumpU64(c, d->events[e]);
        }
        tpuDumpStr(c, "\n");
    }
    for (int i = 0; i < VAC_MAX_TXNS; i++) {
        VacTxn *t = &g_health.txns[i];
        uint64_t id = t->id;
        if (!id)
            continue;
        tpuDumpStr(c, "V txn ");
        tpuDumpU64(c, id);
        tpuDumpStr(c, " src ");
        tpuDumpU64(c, t->src);
        tpuDumpStr(c, " dst ");
        tpuDumpU64(c, t->dst);
        tpuDumpStr(c, " gen ");
        tpuDumpU64(c, t->gen);
        tpuDumpStr(c, " start_ns ");
        tpuDumpU64(c, t->startNs);
        tpuDumpStr(c, "\n");
    }
}

/* -------------------------------------------------------------- render */

/* Prometheus gauges (procfs render_metrics appends this after the
 * counter exposition).  States render numerically (0/1/2) so alerting
 * thresholds are a plain comparison. */
void tpurmHealthRenderProm(TpuCur *c)
{
    uint32_t n = tpurmDeviceCount();
    if (n > HEALTH_MAX_DEVICES)
        n = HEALTH_MAX_DEVICES;
    tpuCurf(c, "# TYPE tpurm_device_health gauge\n");
    for (uint32_t i = 0; i < n; i++)
        tpuCurf(c, "tpurm_device_health{dev=\"%u\"} %u\n", i,
                tpurmDeviceHealthState(i));
    tpuCurf(c, "# TYPE tpurm_device_health_score gauge\n");
    for (uint32_t i = 0; i < n; i++)
        tpuCurf(c, "tpurm_device_health_score{dev=\"%u\"} %llu\n", i,
                (unsigned long long)tpurmDeviceHealthScore(i));
}

/* /proc/driver/tpurm/health table. */
void tpurmHealthRenderTable(TpuCur *c)
{
    uint32_t n = tpurmDeviceCount();
    if (n > HEALTH_MAX_DEVICES)
        n = HEALTH_MAX_DEVICES;
    tpuCurf(c, "%-4s %-11s %-8s %-6s %-6s  %s\n", "dev", "state",
            "score", "trans", "evac", "events");
    for (uint32_t i = 0; i < n; i++) {
        TpuHealthInfo hi;
        if (tpurmHealthInfo(i, &hi) != TPU_OK)
            continue;
        tpuCurf(c, "%-4u %-11s %-8llu %-6llu ", i,
                tpurmHealthStateName(hi.state),
                (unsigned long long)hi.score,
                (unsigned long long)hi.transitions);
        if (hi.evacPending)
            tpuCurf(c, "->%-4u ", hi.evacTarget);
        else
            tpuCurf(c, "%-6s ", "-");
        for (uint32_t e = 0; e < TPU_HEALTH_EV_COUNT; e++)
            if (hi.events[e])
                tpuCurf(c, " %s=%llu", g_eventNames[e],
                        (unsigned long long)hi.events[e]);
        tpuCurf(c, "\n");
    }
    tpuCurf(c, "\nvac: txns_active=%u requests=%llu acks=%llu "
            "grace_expired=%llu commits=%llu aborts=%llu "
            "pages_moved=%llu\n",
            tpurmVacActive(),
            (unsigned long long)tpurmCounterGet("vac_requests"),
            (unsigned long long)tpurmCounterGet("vac_acks"),
            (unsigned long long)tpurmCounterGet("vac_grace_expired"),
            (unsigned long long)tpurmCounterGet("vac_commits"),
            (unsigned long long)tpurmCounterGet("vac_aborts"),
            (unsigned long long)tpurmCounterGet("vac_pages_moved"));
}
