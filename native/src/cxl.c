/*
 * CXL.mem tier: device enumeration, buffer registration/pinning, P2P DMA.
 *
 * Re-design of the fork's CXL stack (SURVEY.md §2.1):
 *   - enumeration by PCI class 0x0502 + link-speed version heuristic
 *     (reference: kernel-open/nvidia/nv-p2p.c:1556-1609),
 *   - buffer registry with 256-buffer/1 TB limits, pinned-bytes accounting
 *     under its own lock (reference: p2p_cxl.c:137,140; nv-p2p.c
 *     cxl_check_pin_limits:1102, cxl_track_pin:1114),
 *   - 2 MB huge-page path when base+size are 2 MB aligned, else 4 K
 *     (reference: p2p_cxl.c:150,283-335),
 *   - persistent memdesc built on first DMA use (_cxlP2PCreateMemDesc:167),
 *   - DMA request = throwaway HBM memdesc at the device offset + transfer
 *     engine copy with the 4 GB clamp (p2p_cxl.c:517-678).
 *
 * Userspace pinning: the kernel reference pins with pin_user_pages; the
 * user-level TPU runtime pins with mlock(2) — best-effort (RLIMIT_MEMLOCK
 * may cap it), tracked identically.
 */
#define _GNU_SOURCE
#include "internal.h"

#include <dirent.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>
#include <sys/mman.h>

struct TpuCxlBuffer {
    bool registered;
    uint16_t generation;
    uint64_t baseAddress;
    uint64_t size;
    uint32_t cxlVersion;
    uint64_t pageSize;
    bool hugePages;
    bool mlocked;
    TpuMemDesc *memdesc;       /* persistent, built on first DMA */
    uint32_t activeDma;        /* in-flight synchronous DMA sections */
    /* Async submissions against this buffer, as (channel, value) deps —
     * multiple devices' channels tracked together (uvm_tracker.c). */
    TpuTracker pending;
};

static struct {
    pthread_mutex_t lock;
    struct TpuCxlBuffer buffers[TPU_CXL_MAX_BUFFERS];
    uint32_t count;
    uint64_t pinnedBytes;
} g_cxl = { .lock = PTHREAD_MUTEX_INITIALIZER };

/* Handle encoding: (generation << 16) | (slot + 1), kept in the low 32 bits
 * so truncating userspace still round-trips.  Never 0. */
static uint64_t handle_make(uint32_t slot, uint16_t gen)
{
    return ((uint64_t)gen << 16) | (slot + 1);
}

static struct TpuCxlBuffer *handle_lookup(uint64_t handle, uint32_t *outSlot)
{
    uint32_t slot = (uint32_t)(handle & 0xffff);
    uint16_t gen = (uint16_t)((handle >> 16) & 0xffff);
    if (slot == 0 || slot > TPU_CXL_MAX_BUFFERS)
        return NULL;
    struct TpuCxlBuffer *buf = &g_cxl.buffers[slot - 1];
    if (!buf->registered || buf->generation != gen)
        return NULL;
    if (outSlot)
        *outSlot = slot - 1;
    return buf;
}

/* ------------------------------------------------------------ enumeration */

/* PCI class scan for CXL devices (class 0x0502: CXL memory device).
 * Reference heuristic: PCIe Gen5 link -> CXL 2.0, Gen4 -> CXL 1.x
 * (nv-p2p.c:1592-1597). */
TpuStatus tpuCxlSystemInfo(uint32_t *numDevices, uint32_t *numMemDevices,
                           bool *linkUp, uint32_t *cxlVersion)
{
    uint32_t devices = 0, memDevices = 0, version = 2;

    uint64_t fake = tpuRegistryGet("fake_cxl_devices", 0);
    if (fake > 0) {
        devices = memDevices = (uint32_t)fake;
        version = (uint32_t)tpuRegistryGet("fake_cxl_version", 2);
    } else {
        DIR *dir = opendir("/sys/bus/pci/devices");
        if (dir) {
            struct dirent *de;
            while ((de = readdir(dir)) != NULL) {
                if (de->d_name[0] == '.')
                    continue;
                char path[300];
                snprintf(path, sizeof(path),
                         "/sys/bus/pci/devices/%s/class", de->d_name);
                FILE *f = fopen(path, "r");
                if (!f)
                    continue;
                unsigned int cls = 0;
                if (fscanf(f, "%x", &cls) == 1 && (cls >> 8) == 0x0502) {
                    devices++;
                    memDevices++;
                    snprintf(path, sizeof(path),
                             "/sys/bus/pci/devices/%s/current_link_speed",
                             de->d_name);
                    FILE *ls = fopen(path, "r");
                    if (ls) {
                        float gts = 0;
                        if (fscanf(ls, "%f", &gts) == 1)
                            version = gts >= 32.0f ? 2 : 1;
                        fclose(ls);
                    }
                }
                fclose(f);
            }
            closedir(dir);
        }
    }

    if (numDevices)
        *numDevices = devices;
    if (numMemDevices)
        *numMemDevices = memDevices;
    if (linkUp)
        *linkUp = devices > 0;
    if (cxlVersion)
        *cxlVersion = version;
    return TPU_OK;
}

/* ------------------------------------------------------------- register */

static bool can_use_huge_pages(uint64_t base, uint64_t size)
{
    return (base & (TPU_CXL_PAGE_SIZE_2M - 1)) == 0 &&
           (size & (TPU_CXL_PAGE_SIZE_2M - 1)) == 0 &&
           size >= TPU_CXL_PAGE_SIZE_2M;
}

TpuStatus tpuCxlRegister(uint64_t baseAddress, uint64_t size,
                         uint32_t cxlVersion, uint64_t *outHandle)
{
    if (baseAddress == 0 || size == 0 || outHandle == NULL ||
        cxlVersion < 1 || cxlVersion > 3)
        return TPU_ERR_INVALID_ARGUMENT;
    if (size > TPU_CXL_MAX_BUFFER_BYTES)
        return TPU_ERR_INVALID_LIMIT;

    uint64_t pageSize = can_use_huge_pages(baseAddress, size)
                            ? TPU_CXL_PAGE_SIZE_2M : TPU_CXL_PAGE_SIZE_4K;
    uint64_t pageCount = (size + pageSize - 1) / pageSize;
    if (pageCount > TPU_CXL_MAX_PIN_PAGES)
        return TPU_ERR_INVALID_LIMIT;

    pthread_mutex_lock(&g_cxl.lock);
    tpuLockTrackAcquire(TPU_LOCK_CXL, "cxl");

    if (g_cxl.count >= TPU_CXL_MAX_BUFFERS) {
        tpuLockTrackRelease(TPU_LOCK_CXL, "cxl");
        pthread_mutex_unlock(&g_cxl.lock);
        return TPU_ERR_INSUFFICIENT_RESOURCES;
    }
    uint64_t pinLimit = tpuRegistryGet("pin_limit_mb", 1ull << 30) << 20;
    if (g_cxl.pinnedBytes + size > pinLimit) {
        tpuLockTrackRelease(TPU_LOCK_CXL, "cxl");
        pthread_mutex_unlock(&g_cxl.lock);
        TPU_LOG(TPU_LOG_ERROR, "cxl",
               "pin limit exceeded: %llu + %llu > %llu",
               (unsigned long long)g_cxl.pinnedBytes,
               (unsigned long long)size, (unsigned long long)pinLimit);
        return TPU_ERR_INSUFFICIENT_RESOURCES;
    }

    uint32_t slot;
    for (slot = 0; slot < TPU_CXL_MAX_BUFFERS; slot++)
        if (!g_cxl.buffers[slot].registered)
            break;

    struct TpuCxlBuffer *buf = &g_cxl.buffers[slot];
    buf->registered = true;
    buf->generation++;
    buf->baseAddress = baseAddress;
    buf->size = size;
    buf->cxlVersion = cxlVersion;
    buf->pageSize = pageSize;
    buf->hugePages = pageSize == TPU_CXL_PAGE_SIZE_2M;
    buf->memdesc = NULL;
    tpuTrackerInit(&buf->pending);
    /* Pin: mlock is best-effort in userspace (RLIMIT_MEMLOCK); failure is
     * logged, accounting proceeds — matching the reference test's tolerant
     * mlock handling, while kernel-grade pinning stays a deploy concern. */
    buf->mlocked = mlock((void *)(uintptr_t)baseAddress, size) == 0;
    if (!buf->mlocked)
        TPU_LOG(TPU_LOG_WARN, "cxl", "mlock failed for %llu bytes (RLIMIT?)",
               (unsigned long long)size);
    g_cxl.count++;
    g_cxl.pinnedBytes += size;
    tpuCounterAdd("cxl_buffers_registered", 1);

    *outHandle = handle_make(slot, buf->generation);
    TPU_LOG(TPU_LOG_INFO, "cxl",
           "registered buffer slot=%u base=0x%llx size=0x%llx pages=%s",
           slot, (unsigned long long)baseAddress, (unsigned long long)size,
           buf->hugePages ? "2M" : "4K");

    tpuLockTrackRelease(TPU_LOCK_CXL, "cxl");
    pthread_mutex_unlock(&g_cxl.lock);
    return TPU_OK;
}

TpuStatus tpuCxlUnregister(uint64_t handle)
{
    if (handle == 0)
        return TPU_ERR_INVALID_ARGUMENT;
    pthread_mutex_lock(&g_cxl.lock);
    tpuLockTrackAcquire(TPU_LOCK_CXL, "cxl");
    struct TpuCxlBuffer *buf = handle_lookup(handle, NULL);
    if (!buf) {
        tpuLockTrackRelease(TPU_LOCK_CXL, "cxl");
        pthread_mutex_unlock(&g_cxl.lock);
        return TPU_ERR_OBJECT_NOT_FOUND;
    }
    if (buf->activeDma > 0) {
        /* A DMA section holds a reference outside the lock; refuse rather
         * than free under it (reference frees are likewise refused while
         * mappings are live). */
        tpuLockTrackRelease(TPU_LOCK_CXL, "cxl");
        pthread_mutex_unlock(&g_cxl.lock);
        return TPU_ERR_STATE_IN_USE;
    }
    /* Quiesce async submissions before teardown: waiting the tracker
     * retires every copy (on any device's channel) that still
     * reads/writes this buffer. */
    tpuTrackerWait(&buf->pending);
    tpuTrackerDeinit(&buf->pending);
    if (buf->mlocked)
        munlock((void *)(uintptr_t)buf->baseAddress, buf->size);
    tpuMemdescDestroy(buf->memdesc);
    buf->memdesc = NULL;
    buf->registered = false;
    g_cxl.count--;
    g_cxl.pinnedBytes -= buf->size;
    tpuCounterAdd("cxl_buffers_unregistered", 1);
    TPU_LOG(TPU_LOG_INFO, "cxl", "unregistered buffer handle=0x%llx",
           (unsigned long long)handle);
    tpuLockTrackRelease(TPU_LOCK_CXL, "cxl");
    pthread_mutex_unlock(&g_cxl.lock);
    return TPU_OK;
}

uint32_t tpuCxlRegisteredCount(void)
{
    pthread_mutex_lock(&g_cxl.lock);
    uint32_t n = g_cxl.count;
    pthread_mutex_unlock(&g_cxl.lock);
    return n;
}

uint64_t tpuCxlPinnedBytes(void)
{
    pthread_mutex_lock(&g_cxl.lock);
    uint64_t n = g_cxl.pinnedBytes;
    pthread_mutex_unlock(&g_cxl.lock);
    return n;
}

/* ---------------------------------------------------------------- DMA */

TpuStatus tpuCxlDmaRequest(TpurmDevice *dev, uint64_t handle,
                           uint64_t gpuOffset, uint64_t cxlOffset,
                           uint64_t size, uint32_t flags,
                           uint32_t hClient, uint32_t *outTransferId)
{
    if (!dev)
        return TPU_ERR_INVALID_ARGUMENT;
    if (handle == 0 || size == 0)
        return TPU_ERR_INVALID_ARGUMENT;
    if (dev->lost)
        return TPU_ERR_GPU_IS_LOST;

    bool cxlToDev = (flags & TPU_CXL_DMA_FLAG_CXL_TO_DEV) != 0;
    bool async = (flags & TPU_CXL_DMA_FLAG_ASYNC) != 0;

    pthread_mutex_lock(&g_cxl.lock);
    tpuLockTrackAcquire(TPU_LOCK_CXL, "cxl");
    struct TpuCxlBuffer *buf = handle_lookup(handle, NULL);
    TpuStatus st = TPU_OK;
    TpuMemDesc *cxlMd = NULL;

    if (!buf) {
        st = TPU_ERR_OBJECT_NOT_FOUND;
    } else if (cxlOffset > buf->size || size > buf->size - cxlOffset) {
        st = TPU_ERR_INVALID_ARGUMENT;  /* OOB (p2p_cxl.c:563) */
    } else {
        if (buf->memdesc == NULL) {
            /* Persistent memdesc on first use (_cxlP2PCreateMemDesc). */
            st = tpuMemdescCreateContig(&buf->memdesc, TPU_APERTURE_CXL,
                                        buf->baseAddress, buf->size,
                                        buf->pageSize);
        }
        cxlMd = buf->memdesc;
        if (st == TPU_OK)
            buf->activeDma++;   /* blocks unregister while we copy */
    }
    tpuLockTrackRelease(TPU_LOCK_CXL, "cxl");
    pthread_mutex_unlock(&g_cxl.lock);
    if (st != TPU_OK)
        return st;

    /* The reference clamps each CE push to 4 GB but loops the request to
     * completion (p2p_cxl.c:617-656 copies transferSize fully); here the
     * per-push clamp lives in tpuMemCopy's contiguity-split loop, so the
     * full size is handed down — never truncated. */
    uint64_t hbmSize = tpurmDeviceHbmSize(dev);
    TpuTracker dmaTracker;
    tpuTrackerInit(&dmaTracker);
    TpuMemDesc *devMd = NULL;
    /* Overflow-safe bounds check (a wrapped gpuOffset must not pass). */
    if (size > hbmSize || gpuOffset > hbmSize - size) {
        st = TPU_ERR_INVALID_LIMIT;
    } else {
        /* Throwaway device-side memdesc describing HBM at gpuOffset
         * (memdescCreate+memdescDescribe analog). */
        st = tpuMemdescCreateContig(&devMd, TPU_APERTURE_HBM, gpuOffset,
                                    size, 0);
    }
    if (st == TPU_OK) {
        if (cxlToDev)
            st = tpuMemCopy(dev, devMd, 0, cxlMd, cxlOffset, size,
                            async, &dmaTracker);
        else
            st = tpuMemCopy(dev, cxlMd, cxlOffset, devMd, 0, size,
                            async, &dmaTracker);
        tpuMemdescDestroy(devMd);
    }

    /* Record async dependencies into the buffer's tracker (pushes may
     * span the whole CE pool) so unregister can quiesce every involved
     * channel, THEN drop the DMA reference: the activeDma>0 guard must
     * keep covering any copy whose dependency could not be merged — a
     * fallback wait after the decrement would race unregister's
     * teardown. */
    pthread_mutex_lock(&g_cxl.lock);
    bool merged = true;
    if (st == TPU_OK && async)
        merged = tpuTrackerAddTracker(&buf->pending, &dmaTracker) == TPU_OK;
    if (!merged) {
        /* Deps could not be recorded: complete them now (still holding
         * the DMA reference) rather than let unregister's quiesce miss
         * an in-flight copy. */
        pthread_mutex_unlock(&g_cxl.lock);
        tpuTrackerWait(&dmaTracker);
        pthread_mutex_lock(&g_cxl.lock);
    }
    buf->activeDma--;
    pthread_mutex_unlock(&g_cxl.lock);
    /* RM event delivery (NV0005 analog): clients that armed
     * TPU_NOTIFIER_CXL_DMA hear the completion without polling the
     * tracker — the event worker waits the copy's dependencies and
     * fires.  A sync request's tracker is already complete, so the
     * event fires immediately. */
    /* Completion notification is SCOPED to the requesting client: a
     * second client armed on the same notifier must not hear someone
     * else's transfer complete (its own copy-back ordering depends on
     * its own completions).  When the requesting client has NO armed
     * listener of its own, fall back to BROADCAST delivery so a pure
     * observer (a monitor client armed on the notifier without issuing
     * DMA) still hears the completion instead of it being silently
     * dropped — see the TPU_NOTIFIER_CXL_DMA contract in abi.h. */
    if (st == TPU_OK) {
        uint32_t evScope = hClient;
        if (evScope && !tpurmEventArmedForClient(dev->inst,
                                                 TPU_NOTIFIER_CXL_DMA,
                                                 evScope))
            evScope = 0;
        tpurmEventNotifyTrackerScoped(&dmaTracker, dev->inst,
                                      TPU_NOTIFIER_CXL_DMA, evScope,
                                      /*info32=*/1,
                                      (uint16_t)(cxlToDev ? 1 : 0));
    }
    tpuTrackerDeinit(&dmaTracker);

    if (st != TPU_OK) {
        TPU_LOG(TPU_LOG_ERROR, "cxl", "DMA %s failed: %s",
               cxlToDev ? "CXL->DEV" : "DEV->CXL", tpuStatusToString(st));
        return st;
    }
    tpuCounterAdd("cxl_dma_requests", 1);
    tpuCounterAdd("cxl_dma_bytes", size);
    if (outTransferId)
        *outTransferId = 1;     /* opaque non-zero id (completion rides
                                 * the buffer's pending tracker) */
    return TPU_OK;
}
