/*
 * peermem — TPU-direct RDMA export (see include/tpurm/peermem.h).
 *
 * Reference flow (nvidia-peermem.c + nv-p2p.c): ibv_reg_mr ->
 * acquire -> get_pages (pins vidmem, registers free callback) ->
 * dma_map (per-NIC sg_table) -> ... -> free callback revokes on
 * underlying free.  Implemented here over the UVM engine: get_pages
 * migrates the span to the device HBM tier and pins every covered
 * block; bus addresses are the backing chunks' offsets into the device
 * HBM window.  A global registration table drives callback revocation
 * from the UVM range-destroy hook.
 *
 * The dma-buf analog (tpuDmabufExport/Import, reference nv-dmabuf.c) is
 * a refcounted handle over an HBM window for in-process subsystem
 * handoff.
 */
#define _GNU_SOURCE
#include "internal.h"
#include "uvm/uvm_internal.h"
#include "tpurm/peermem.h"

#include <pthread.h>
#include <stdlib.h>
#include <string.h>

typedef struct Registration {
    TpuP2pPageTable *pt;
    UvmVaSpace *vs;
    uint64_t va, size;
    UvmVaBlock **blocks;
    uint32_t blockCount;
    TpuP2pFreeCallback cb;
    void *cbData;
    bool revoked;
    struct Registration *next;
} Registration;

static struct {
    pthread_mutex_t lock;
    Registration *head;
    bool hookInstalled;
} g_peermem = { PTHREAD_MUTEX_INITIALIZER, NULL, false };

/* Range teardown: revoke every registration overlapping [start, start+size).
 * Runs before the backing is freed; consumers must stop using bus
 * addresses from their callback (reference invalidation contract). */
static void peermem_range_destroy_hook(uint64_t start, uint64_t size)
{
    /* Mark + unpin under the lock; invoke consumer callbacks AFTER
     * releasing it — the reference contract lets a free callback call
     * put_pages, which takes g_peermem.lock (self-deadlock otherwise). */
    enum { MAX_FIRE = 64 };
    TpuP2pFreeCallback cbs[MAX_FIRE];
    void *cbData[MAX_FIRE];
    uint32_t nfire = 0;

    pthread_mutex_lock(&g_peermem.lock);
    for (Registration *r = g_peermem.head; r; r = r->next) {
        if (r->revoked || r->va >= start + size || start >= r->va + r->size)
            continue;
        r->revoked = true;
        /* Blocks are about to be freed wholesale; drop our pins now. */
        for (uint32_t i = 0; i < r->blockCount; i++)
            uvmBlockP2pUnpin(r->blocks[i]);
        if (r->cb && nfire < MAX_FIRE) {
            cbs[nfire] = r->cb;
            cbData[nfire] = r->cbData;
            nfire++;
        }
        tpuCounterAdd("peermem_revocations", 1);
    }
    pthread_mutex_unlock(&g_peermem.lock);

    for (uint32_t i = 0; i < nfire; i++)
        cbs[i](cbData[i]);
}

static void peermem_init(void)
{
    pthread_mutex_lock(&g_peermem.lock);
    if (!g_peermem.hookInstalled) {
        uvmSetRangeDestroyHook(peermem_range_destroy_hook);
        g_peermem.hookInstalled = true;
    }
    pthread_mutex_unlock(&g_peermem.lock);
}

TpuStatus tpuP2pGetPages(UvmVaSpace *vs, uint32_t devInst, uint64_t va,
                         uint64_t size, TpuP2pPageTable **out,
                         TpuP2pFreeCallback cb, void *cbData)
{
    if (!vs || !out || size == 0)
        return TPU_ERR_INVALID_ARGUMENT;
    TpurmDevice *dev = tpurmDeviceGet(devInst);
    if (!dev)
        return TPU_ERR_INVALID_DEVICE;
    peermem_init();

    uint64_t ps = uvmPageSize();
    uint64_t start = va & ~(ps - 1);
    uint64_t end = (va + size - 1) | (ps - 1);

    /* Make the span device-resident (exclusive; like the reference this
     * is vidmem being exported, not a duplicate). */
    UvmLocation hbm = { UVM_TIER_HBM, devInst };
    TpuStatus st = uvmMigrate(vs, (void *)(uintptr_t)start,
                              end - start + 1, hbm, 0);
    if (st != TPU_OK)
        return st;

    uint32_t entries = (uint32_t)((end - start + 1) / ps);
    TpuP2pPageTable *pt = calloc(1, sizeof(*pt));
    TpuP2pPage *pages = calloc(entries, sizeof(*pages));
    Registration *reg = calloc(1, sizeof(*reg));
    UvmVaBlock **blocks = calloc((entries * ps + UVM_BLOCK_SIZE - 1) /
                                 UVM_BLOCK_SIZE + 1, sizeof(*blocks));
    if (!pt || !pages || !reg || !blocks) {
        free(pt);
        free(pages);
        free(reg);
        free(blocks);
        return TPU_ERR_NO_MEMORY;
    }

    /* Walk blocks: pin each one UNDER ITS LOCK while resolving its run
     * list — a concurrent evictor takes only blk->lock, so resolving
     * first and pinning later would race run frees (bus addresses into
     * reallocated chunks).  Pin-then-resolve under the lock closes it;
     * pins roll back on failure. */
    pthread_mutex_lock(&vs->lock);
    tpuLockTrackAcquire(TPU_LOCK_UVM_VASPACE, "vaspace");
    uint32_t nblocks = 0, pageIx = 0;
    uint64_t addr = start;
    st = TPU_OK;
    while (addr <= end && st == TPU_OK) {
        UvmVaBlock *blk = NULL;
        if (!uvmRangeFind(vs, addr, &blk) || !blk) {
            st = TPU_ERR_OBJECT_NOT_FOUND;
            break;
        }
        pthread_mutex_lock(&blk->lock);
        tpuLockTrackAcquire(TPU_LOCK_UVM_BLOCK, "peermem");
        blk->p2pPinCount++;
        blocks[nblocks++] = blk;
        uint64_t blockEnd = blk->start + (uint64_t)blk->npages * ps - 1;
        uint64_t spanEnd = end < blockEnd ? end : blockEnd;
        for (uint64_t a = addr; a <= spanEnd && st == TPU_OK; a += ps) {
            uint32_t page = (uint32_t)((a - blk->start) / ps);
            void *ptr = NULL;
            /* Resolve backing through the block's HBM runs. */
            for (UvmChunkRun *run = blk->hbmRuns; run; run = run->next) {
                if (page >= run->firstPage &&
                    page < run->firstPage + run->numPages) {
                    pages[pageIx].busAddress =
                        run->chunk->offset +
                        (uint64_t)(page - run->firstPage) * ps;
                    ptr = (char *)run->arena->base;
                    /* The NIC reads the arena mapping directly, so any
                     * chip-computed bytes must be downloaded into the
                     * shadow before the bus address is handed out
                     * (GPUDirect pins real vidmem, not a host mirror).
                     * Failure = stale shadow: refuse the registration. */
                    if (tpuHbmCoherentForRead(
                            (char *)ptr + pages[pageIx].busAddress,
                            ps) != TPU_OK) {
                        st = TPU_ERR_INVALID_STATE;
                        ptr = NULL;
                    }
                    break;
                }
            }
            if (!ptr)
                st = TPU_ERR_INVALID_STATE;   /* evicted before we pinned */
            pageIx++;
        }
        tpuLockTrackRelease(TPU_LOCK_UVM_BLOCK, "peermem");
        pthread_mutex_unlock(&blk->lock);
        addr = blockEnd + 1;
    }
    tpuLockTrackRelease(TPU_LOCK_UVM_VASPACE, "vaspace");
    pthread_mutex_unlock(&vs->lock);

    if (st != TPU_OK) {
        for (uint32_t i = 0; i < nblocks; i++)
            uvmBlockP2pUnpin(blocks[i]);
        free(pt);
        free(pages);
        free(reg);
        free(blocks);
        return st;
    }

    pt->version = TPU_P2P_PAGE_TABLE_VERSION;
    pt->pageSize = (uint32_t)ps;
    pt->devInst = devInst;
    pt->entries = entries;
    pt->pages = pages;

    reg->pt = pt;
    reg->vs = vs;
    reg->va = start;
    reg->size = end - start + 1;
    reg->blocks = blocks;
    reg->blockCount = nblocks;
    reg->cb = cb;
    reg->cbData = cbData;
    pthread_mutex_lock(&g_peermem.lock);
    reg->next = g_peermem.head;
    g_peermem.head = reg;
    pthread_mutex_unlock(&g_peermem.lock);

    tpuCounterAdd("peermem_get_pages", 1);
    *out = pt;
    return TPU_OK;
}

TpuStatus tpuP2pPutPages(TpuP2pPageTable *pt)
{
    if (!pt)
        return TPU_ERR_INVALID_ARGUMENT;
    pthread_mutex_lock(&g_peermem.lock);
    Registration **pp = &g_peermem.head;
    Registration *reg = NULL;
    while (*pp) {
        if ((*pp)->pt == pt) {
            reg = *pp;
            *pp = reg->next;
            break;
        }
        pp = &(*pp)->next;
    }
    pthread_mutex_unlock(&g_peermem.lock);
    if (!reg)
        return TPU_ERR_OBJECT_NOT_FOUND;
    if (!reg->revoked) {
        for (uint32_t i = 0; i < reg->blockCount; i++)
            uvmBlockP2pUnpin(reg->blocks[i]);
    }
    free(reg->blocks);
    free(reg);
    free(pt->pages);
    free(pt);
    tpuCounterAdd("peermem_put_pages", 1);
    return TPU_OK;
}

TpuStatus tpuP2pDmaMapPages(TpuP2pPageTable *pt, uint32_t nicId,
                            TpuP2pDmaMapping **out)
{
    if (!pt || !out)
        return TPU_ERR_INVALID_ARGUMENT;
    TpuP2pDmaMapping *map = calloc(1, sizeof(*map));
    if (!map)
        return TPU_ERR_NO_MEMORY;
    map->iova = calloc(pt->entries, sizeof(uint64_t));
    if (!map->iova) {
        free(map);
        return TPU_ERR_NO_MEMORY;
    }
    map->version = TPU_P2P_PAGE_TABLE_VERSION;
    map->nicId = nicId;
    map->entries = pt->entries;
    /* IOVA model: identity within the device window, tagged by NIC in
     * the top byte (each NIC has its own IOMMU domain in the reference;
     * the tag keeps mappings from different NICs distinguishable). */
    for (uint32_t i = 0; i < pt->entries; i++)
        map->iova[i] = ((uint64_t)nicId << 56) | pt->pages[i].busAddress;
    tpuCounterAdd("peermem_dma_maps", 1);
    *out = map;
    return TPU_OK;
}

TpuStatus tpuP2pDmaUnmapPages(TpuP2pDmaMapping *map)
{
    if (!map)
        return TPU_ERR_INVALID_ARGUMENT;
    free(map->iova);
    free(map);
    return TPU_OK;
}

void *tpuP2pBusToPtr(uint32_t devInst, uint64_t busAddress)
{
    TpurmDevice *dev = tpurmDeviceGet(devInst);
    if (!dev)
        return NULL;
    uint64_t size = tpurmDeviceHbmSize(dev);
    if (busAddress >= size)
        return NULL;
    return (char *)tpurmDeviceHbmBase(dev) + busAddress;
}

/* ------------------------------------------------------ dma-buf analog */

struct TpuDmabuf {
    uint32_t devInst;
    uint64_t offset, size;
    _Atomic uint32_t refs;
};

TpuStatus tpuDmabufExport(uint32_t devInst, uint64_t offset, uint64_t size,
                          TpuDmabuf **out)
{
    if (!out || size == 0)
        return TPU_ERR_INVALID_ARGUMENT;
    TpurmDevice *dev = tpurmDeviceGet(devInst);
    if (!dev)
        return TPU_ERR_INVALID_DEVICE;
    /* Overflow-safe form: offset + size can wrap uint64. */
    uint64_t hbm = tpurmDeviceHbmSize(dev);
    if (offset > hbm || size > hbm - offset)
        return TPU_ERR_INVALID_LIMIT;
    TpuDmabuf *buf = calloc(1, sizeof(*buf));
    if (!buf)
        return TPU_ERR_NO_MEMORY;
    buf->devInst = devInst;
    buf->offset = offset;
    buf->size = size;
    buf->refs = 1;
    tpuCounterAdd("dmabuf_exports", 1);
    *out = buf;
    return TPU_OK;
}

TpuStatus tpuDmabufImport(TpuDmabuf *buf, void **ptr, uint64_t *size)
{
    if (!buf || !ptr)
        return TPU_ERR_INVALID_ARGUMENT;
    void *base = tpuP2pBusToPtr(buf->devInst, buf->offset);
    if (!base)
        return TPU_ERR_INVALID_STATE;
    *ptr = base;
    if (size)
        *size = buf->size;
    return TPU_OK;
}

TpuStatus tpuDmabufInfo(TpuDmabuf *buf, uint32_t *devInst, uint64_t *offset,
                        uint64_t *size)
{
    if (!buf)
        return TPU_ERR_INVALID_ARGUMENT;
    if (devInst)
        *devInst = buf->devInst;
    if (offset)
        *offset = buf->offset;
    if (size)
        *size = buf->size;
    return TPU_OK;
}

TpuDmabuf *tpuDmabufGet(TpuDmabuf *buf)
{
    if (buf)
        __atomic_fetch_add(&buf->refs, 1, __ATOMIC_SEQ_CST);
    return buf;
}

void tpuDmabufPut(TpuDmabuf *buf)
{
    if (!buf)
        return;
    if (__atomic_fetch_sub(&buf->refs, 1, __ATOMIC_SEQ_CST) == 1)
        free(buf);
}
