/*
 * procfs — the /proc/driver observability tree.
 *
 * Re-design of the reference's procfs surface (nv-procfs.c:
 * /proc/driver/nvidia/gpus/<id>/information, version;
 * uvm_procfs.c:36-49: /proc/driver/nvidia-uvm with debug gating).
 * Userspace engine shape: a virtual node table rendered on demand —
 * tpurmProcfsRead() fills a caller buffer, and the LD_PRELOAD shim
 * serves open("/proc/driver/tpurm...") (also accepting the reference's
 * /proc/driver/nvidia spellings) by rendering into a memfd, so plain
 * cat/read works against the synthetic tree.
 *
 * Debug gating (uvm_procfs.c:36-49): nodes marked dbg render only when
 * registry "procfs_debug" is set, mirroring uvm_enable_debug_procfs.
 */
#define _GNU_SOURCE
#include "internal.h"
#include "tpurm/reset.h"
#include "tpurm/trace.h"
#include "uvm/uvm_internal.h"

#include <stdarg.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>

/* Render helpers append into the shared bounded cursor (internal.h
 * TpuCur; implementation in trace.c). */

/* ------------------------------------------------------------ renderers */

static void render_version(TpuCur *c)
{
    tpuCurf(c, "tpurm version: 1.0 (round 3)\n");
    tpuCurf(c, "engine: userspace RM + UVM over libtpu/XLA\n");
}

static void render_gpu_info(TpuCur *c, uint32_t inst)
{
    TpurmDevice *dev = tpurmDeviceGet(inst);
    if (!dev)
        return;
    tpuCurf(c, "Device Instance:     %u\n", inst);
    tpuCurf(c, "Probed Id:           0x%x\n", dev->devId);
    tpuCurf(c, "HBM Arena:           %llu MB\n",
         (unsigned long long)(tpurmDeviceHbmSize(dev) >> 20));
    tpuCurf(c, "Arena Backend:       %s\n",
         tpurmDeviceArenaIsReal(inst) ? "real (mirror stream open)"
                                      : "fake (host shadow only)");
    tpuCurf(c, "CE Channels:         %u\n", dev->cePoolSize);
    tpuCurf(c, "Device Lost:         %s\n", dev->lost ? "yes" : "no");
}

static void render_gpus(TpuCur *c)
{
    uint32_t n = tpurmDeviceCount();
    for (uint32_t i = 0; i < n; i++) {
        tpuCurf(c, "[gpu %u]\n", i);
        render_gpu_info(c, i);
        tpuCurf(c, "\n");
    }
}

static void render_fault_stats(TpuCur *c)
{
    UvmFaultStats st;
    uvmFaultStatsGet(&st);
    tpuCurf(c, "cpu_faults:          %llu\n",
         (unsigned long long)st.faultsCpu);
    tpuCurf(c, "device_faults:       %llu\n",
         (unsigned long long)st.faultsDevice);
    tpuCurf(c, "batches:             %llu\n",
         (unsigned long long)st.batches);
    tpuCurf(c, "migrated_bytes:      %llu\n",
         (unsigned long long)st.migratedBytes);
    tpuCurf(c, "evictions:           %llu\n",
         (unsigned long long)st.evictions);
    tpuCurf(c, "service_p50_ns:      %llu\n",
         (unsigned long long)st.serviceNsP50);
    tpuCurf(c, "service_p95_ns:      %llu\n",
         (unsigned long long)st.serviceNsP95);
}

static void channel_row(TpurmChannel *ch, uint64_t completed,
                        uint64_t pending, void *arg)
{
    tpuCurf((TpuCur *)arg, "%-18p completed=%-12llu pending=%llu\n",
         (void *)ch, (unsigned long long)completed,
         (unsigned long long)pending);
}

static void render_channels(TpuCur *c)
{
    tpuCurf(c, "%-18s %-22s %s\n", "channel", "tracker", "fifo");
    tpuRcForEachChannel(channel_row, c);
}

static void render_counters(TpuCur *c)
{
    if (c->off + 1 >= c->cap)
        return;
    c->off += tpuCountersDump(c->buf + c->off, c->cap - c->off);
}

/* Tools event-type coverage vs the reference's UvmEventType enum
 * (reference kernel-open/nvidia-uvm/uvm_types.h:361-391): every
 * reference type with the tpurm event that plays its role, or the
 * design reason there is none.  VERDICT r3 missing #4. */
static void render_tools_events(TpuCur *c)
{
    static const struct { const char *ref, *ours, *note; } rows[] = {
        { "CpuFault/MemoryViolation", "CPU_FAULT", "" },
        { "Migration",            "MIGRATION", "" },
        { "GpuFault",             "GPU_FAULT", "" },
        { "GpuFaultReplay",       "GPU_FAULT_REPLAY", "" },
        { "FaultBufferOverflow",  "FAULT_BUFFER_FLUSH", "flush==overflow service" },
        { "FatalFault",           "FATAL_FAULT", "" },
        { "ReadDuplicate",        "READ_DUP", "" },
        { "ReadDuplicateInvalidate", "READ_DUP_INVALIDATE", "" },
        { "PageSizeChange",       "-", "one page size per run (registry)" },
        { "ThrashingDetected",    "THRASHING", "" },
        { "ThrottlingStart/End",  "THRASHING", "tpuhot THROTTLE hint (hot.throttle)" },
        { "MapRemote",            "MAP_REMOTE", "" },
        { "Eviction",             "EVICTION", "" },
        { "(counters)Prefetch",   "PREFETCH", "" },
        { "TestAccessCounter",    "ACCESS_COUNTER", "" },
        { "(fork)PteUpdate",      "PTE_UPDATE", "dev MMU batch commit" },
        { "(fork)TlbInvalidate",  "TLB_INVALIDATE", "" },
        { "(fork)ChannelRc",      "CHANNEL_RC", "" },
        { "(fork)Watchdog",       "WATCHDOG", "" },
        { "(fork)PmSuspend/Resume", "PM_SUSPEND/PM_RESUME", "" },
        { "(fork)ExternalMap/Unmap", "EXTERNAL_MAP/EXTERNAL_UNMAP", "" },
        { "(fork)HmmAdopt",       "HMM_ADOPT", "" },
        { "(fork)AtsAccess",      "ATS_ACCESS", "" },
    };
    tpuCurf(c, "%-28s %-26s %s\n", "reference(UvmEventType)", "tpurm",
         "note");
    for (size_t i = 0; i < sizeof(rows) / sizeof(rows[0]); i++)
        tpuCurf(c, "%-28s %-26s %s\n", rows[i].ref, rows[i].ours,
             rows[i].note);
}

/* RDMA/peermem surface: registrations + traffic counters, with the
 * transport honestly labeled — per-NIC IOVA spaces are process-local
 * emulations (no NIC exists in this environment); the cross-process
 * consumer, pin lifetime and mid-MR revocation semantics are real
 * (VERDICT r3 missing #5: say so in the procfs surface). */
static void render_rdma(TpuCur *c)
{
    tpuCurf(c, "transport: EMULATED (no NIC in environment; IOVA spaces are\n"
            "  process-local; consumer attaches cross-process via the\n"
            "  arena memfd over SCM_RIGHTS)\n");
    static const char *names[] = {
        "ib_mr_registrations", "ib_mr_invalidations",
        "peermem_get_pages", "peermem_put_pages",
        "peermem_dma_maps", "peermem_revocations", "dmabuf_exports",
    };
    for (size_t i = 0; i < sizeof(names) / sizeof(names[0]); i++)
        tpuCurf(c, "%-24s %llu\n", names[i],
             (unsigned long long)tpurmCounterGet(names[i]));
}

static void render_journal(TpuCur *c)
{
    /* tpubox structured records first (the machine-parsed surface —
     * tools/tpubox.py scrapes this node live), then the legacy text
     * log ring under a marker for human eyes. */
    tpurmJournalRenderText(c);
    tpuCurf(c, "# textlog\n");
    if (c->off + 1 >= c->cap)
        return;
    c->off += tpurmJournalDump(c->buf + c->off, c->cap - c->off);
}

/* Prometheus text exposition (trace.c): named counters + the tputrace
 * site latency histograms, plus the per-tenant QoS usage/quota gauges
 * (uvm_va_space.c).  `cat /proc/driver/tpurm/metrics` under the
 * LD_PRELOAD shim is a scrape. */
static void render_metrics(TpuCur *c)
{
    if (c->off + 1 >= c->cap)
        return;
    c->off += tpurmTraceRenderProm(c->buf + c->off, c->cap - c->off);
    uvmTenantRenderProm(c);
    tpurmHealthRenderProm(c);
    tpurmHotRenderProm(c);
    tpurmFlowRenderProm(c);
    tpurmShieldRenderProm(c);
    tpurmJournalRenderProm(c);
    uvmTierRemoteRenderProm(c);
}

/* Hotness-driven placement (tpuhot): policy stats, per-device hotness
 * gauges, and the top-K hot blocks with their PIN/THROTTLE state. */
static void render_hotness(TpuCur *c)
{
    tpurmHotRenderTable(c);
}

/* Live top-K slow flows by blame (tpuflow), with per-bucket ms. */
static void render_flows(TpuCur *c)
{
    tpurmFlowRenderTable(c);
}

/* Per-device health table (tpuvac): state machine, decayed score,
 * event breakdown, pending evacuations, manifest counters. */
static void render_health(TpuCur *c)
{
    tpurmHealthRenderTable(c);
}

/* Page integrity (tpushield): seal/verify/scrub stats, the inject
 * reconciliation, and the retired-span quarantine list. */
static void render_shield(TpuCur *c)
{
    tpurmShieldRenderTable(c);
}

/* Tenant QoS table: id, priority, per-tier usage vs quota. */
static void render_tenants(TpuCur *c)
{
    uvmTenantRenderTable(c);
}

/* Reset & recovery: device generation, reset totals/MTTR, the hung-op
 * escalation-ladder counters, and client-death reclamation. */
static void render_reset(TpuCur *c)
{
    TpuResetStats st;
    tpurmResetStats(&st);
    tpuCurf(c, "device_generation:        %llu\n",
            (unsigned long long)st.generation);
    tpuCurf(c, "resets_total:             %llu\n",
            (unsigned long long)st.resets);
    tpuCurf(c, "resets_failed:            %llu\n",
            (unsigned long long)st.failedResets);
    tpuCurf(c, "resets_injected:          %llu\n",
            (unsigned long long)st.injectedResets);
    tpuCurf(c, "last_mttr_us:             %llu\n",
            (unsigned long long)(st.lastMttrNs / 1000));
    tpuCurf(c, "last_quiesce_us:          %llu\n",
            (unsigned long long)(st.lastQuiesceNs / 1000));
    tpuCurf(c, "last_restore_us:          %llu\n",
            (unsigned long long)(st.lastRestoreNs / 1000));
    tpuCurf(c, "mttr_sum_us:              %llu\n",
            (unsigned long long)(st.mttrSumNs / 1000));
    tpuCurf(c, "stale_completions:        %llu\n",
            (unsigned long long)st.staleCompletions);
    tpuCurf(c, "watchdog_nudges:          %llu\n",
            (unsigned long long)st.watchdogNudges);
    tpuCurf(c, "watchdog_rc_resets:       %llu\n",
            (unsigned long long)st.watchdogRcResets);
    tpuCurf(c, "watchdog_device_resets:   %llu\n",
            (unsigned long long)st.watchdogDeviceResets);
    tpuCurf(c, "rc_device_escalations:    %llu\n",
            (unsigned long long)tpurmCounterGet("rc_device_escalations"));
    tpuCurf(c, "client_deaths:            %llu\n",
            (unsigned long long)tpurmCounterGet("broker_client_deaths"));
    tpuCurf(c, "heartbeat_reaps:          %llu\n",
            (unsigned long long)tpurmCounterGet("broker_heartbeat_reaps"));
    tpuCurf(c, "reclaimed_cxl_pins:       %llu\n",
            (unsigned long long)tpurmCounterGet("broker_reclaimed_pins"));
    tpuCurf(c, "reclaimed_clients:        %llu\n",
            (unsigned long long)
                tpurmCounterGet("broker_reclaimed_clients"));
    tpuCurf(c, "watchdog_evacuations:     %llu\n",
            (unsigned long long)st.watchdogEvacuations);
}

/* ---------------------------------------------------------- node table */

typedef struct {
    const char *path;
    void (*render)(TpuCur *c);
    bool dbg;                    /* gated by registry procfs_debug */
} ProcNode;

static const ProcNode g_nodes[] = {
    { "driver/tpurm/version", render_version, false },
    { "driver/tpurm/gpus", render_gpus, false },
    { "driver/tpurm-uvm/fault_stats", render_fault_stats, false },
    { "driver/tpurm/channels", render_channels, false },
    { "driver/tpurm-uvm/counters", render_counters, true },
    { "driver/tpurm-uvm/tools_events", render_tools_events, false },
    { "driver/tpurm/rdma", render_rdma, false },
    { "driver/tpurm/journal", render_journal, true },
    { "driver/tpurm/metrics", render_metrics, false },
    { "driver/tpurm/tenants", render_tenants, false },
    { "driver/tpurm/reset", render_reset, false },
    { "driver/tpurm/health", render_health, false },
    { "driver/tpurm/hotness", render_hotness, false },
    { "driver/tpurm/flows", render_flows, false },
    { "driver/tpurm/shield", render_shield, false },
};

#define N_NODES (sizeof(g_nodes) / sizeof(g_nodes[0]))

/* Accept the reference's spellings too: /proc/driver/nvidia/... and
 * /proc/driver/nvidia-uvm/... alias the tpurm trees, and per-gpu
 * information paths (gpus/<id>/information) alias the gpus listing. */
static const char *normalize(const char *path, char *tmp, size_t tmpSize)
{
    if (strncmp(path, "/proc/", 6) == 0)
        path += 6;
    snprintf(tmp, tmpSize, "%s", path);
    char *p;
    if ((p = strstr(tmp, "driver/nvidia-uvm")) != NULL)
        memcpy(p, "driver/tpurm-uvm/", 17),
            memmove(p + 16, p + 17, strlen(p + 17) + 1);
    else if ((p = strstr(tmp, "driver/nvidia")) != NULL)
        memcpy(p, "driver/tpurm/", 13),
            memmove(p + 12, p + 13, strlen(p + 13) + 1);
    /* gpus/<id>/information -> gpus */
    if ((p = strstr(tmp, "/gpus/")) != NULL)
        p[5] = '\0';
    return tmp;
}

size_t tpurmProcfsRead(const char *path, char *buf, size_t bufSize)
{
    if (!path || !buf || bufSize == 0)
        return 0;
    tpuDeviceGlobalInit();
    char tmp[256];
    const char *norm = normalize(path, tmp, sizeof(tmp));
    for (size_t i = 0; i < N_NODES; i++) {
        if (strcmp(g_nodes[i].path, norm) != 0)
            continue;
        if (g_nodes[i].dbg && !tpuRegistryGet("procfs_debug", 0))
            return 0;            /* gated (uvm_enable_debug_procfs) */
        TpuCur c = { buf, bufSize, 0 };
        g_nodes[i].render(&c);
        return c.off;
    }
    return 0;
}

size_t tpurmProcfsList(char *buf, size_t bufSize)
{
    if (!buf || bufSize == 0)
        return 0;
    TpuCur c = { buf, bufSize, 0 };
    bool dbg = tpuRegistryGet("procfs_debug", 0) != 0;
    for (size_t i = 0; i < N_NODES; i++) {
        if (!g_nodes[i].dbg || dbg)
            tpuCurf(&c, "%s\n", g_nodes[i].path);
    }
    return c.off;
}

int tpurmProcfsIsNode(const char *path)
{
    char tmp[256];
    const char *norm = normalize(path, tmp, sizeof(tmp));
    for (size_t i = 0; i < N_NODES; i++) {
        if (strcmp(g_nodes[i].path, norm) == 0)
            return !g_nodes[i].dbg || tpuRegistryGet("procfs_debug", 0);
    }
    return 0;
}
