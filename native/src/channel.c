/*
 * DMA channels: the submission/completion engine.
 *
 * Re-design of the reference's UVM channel/pushbuffer/tracker trio
 * (reference: kernel-open/nvidia-uvm/uvm_channel.c — GPFIFO ring + tracking
 * semaphore per channel, uvm_channel.h:33-49 with 1,024-entry default;
 * uvm_push.c; uvm_tracker.c).  TPU-native shape: the "copy engine" behind a
 * channel is a worker thread doing memcpy for the fake-device/host tiers —
 * real HBM traffic is submitted by the Python runtime through XLA, which
 * plays the role the GSP-owned CE plays in the reference (SURVEY.md §1
 * layer map: libtpu/XLA ≈ firmware).
 *
 * Semantics preserved from the reference:
 *   - fixed-depth ring with blocking back-pressure when full,
 *   - a monotonically increasing tracker value per channel; a push's
 *     completion is "completed value >= push value" (uvm_gpu_semaphore.c),
 *   - channel error latches and fails subsequent waits (robust-channel
 *     recovery surface, SURVEY.md §5),
 *   - error injection for tests (uvm_test.c error-injection ioctls).
 */
#define _GNU_SOURCE
#include "internal.h"

#include <stdlib.h>
#include <string.h>

typedef struct {
    void *dst;
    const void *src;
    uint64_t bytes;
    uint64_t trackerValue;
    bool injectError;
} PushEntry;

struct TpurmChannel {
    TpurmDevice *dev;
    TpurmCeType ce;
    uint32_t entries;
    PushEntry *ring;
    uint64_t put;              /* producer index (monotonic) */
    uint64_t get;              /* consumer index (monotonic) */
    uint64_t submittedValue;   /* last tracker value handed out */
    uint64_t completedValue;   /* tracker semaphore */
    bool stop;
    bool injectNext;
    bool error;                /* latched channel error */
    pthread_mutex_t lock;
    pthread_cond_t cond;       /* any state change */
    pthread_t worker;
};

static void *channel_worker(void *arg)
{
    TpurmChannel *ch = arg;

    pthread_mutex_lock(&ch->lock);
    for (;;) {
        while (!ch->stop && ch->get == ch->put)
            pthread_cond_wait(&ch->cond, &ch->lock);
        if (ch->stop)
            break;

        PushEntry entry = ch->ring[ch->get % ch->entries];
        pthread_mutex_unlock(&ch->lock);

        bool failed = entry.injectError;
        if (!failed && entry.bytes > 0)
            memmove(entry.dst, entry.src, entry.bytes);

        pthread_mutex_lock(&ch->lock);
        ch->get++;
        ch->completedValue = entry.trackerValue;
        if (failed) {
            ch->error = true;
            tpuLog(TPU_LOG_ERROR, "channel",
                   "injected CE fault at tracker value %llu",
                   (unsigned long long)entry.trackerValue);
        }
        tpuCounterAdd("channel_copies_completed", 1);
        tpuCounterAdd("channel_bytes_copied", failed ? 0 : entry.bytes);
        pthread_cond_broadcast(&ch->cond);
    }
    pthread_mutex_unlock(&ch->lock);
    return NULL;
}

TpurmChannel *tpurmChannelCreate(TpurmDevice *dev, TpurmCeType ce,
                                 uint32_t ring_entries)
{
    if (ring_entries == 0)
        ring_entries = (uint32_t)tpuRegistryGet("channel_num_gpfifo_entries",
                                                1024);
    /* Reference bounds: min 32, max 1M (uvm_channel.h:49-51). */
    if (ring_entries < 32)
        ring_entries = 32;
    if (ring_entries > (1u << 20))
        ring_entries = 1u << 20;

    TpurmChannel *ch = calloc(1, sizeof(*ch));
    if (!ch)
        return NULL;
    ch->ring = calloc(ring_entries, sizeof(PushEntry));
    if (!ch->ring) {
        free(ch);
        return NULL;
    }
    ch->dev = dev;
    ch->ce = ce;
    ch->entries = ring_entries;
    pthread_mutex_init(&ch->lock, NULL);
    pthread_cond_init(&ch->cond, NULL);
    if (pthread_create(&ch->worker, NULL, channel_worker, ch) != 0) {
        free(ch->ring);
        free(ch);
        return NULL;
    }
    return ch;
}

void tpurmChannelDestroy(TpurmChannel *ch)
{
    if (!ch)
        return;
    pthread_mutex_lock(&ch->lock);
    ch->stop = true;
    pthread_cond_broadcast(&ch->cond);
    pthread_mutex_unlock(&ch->lock);
    pthread_join(ch->worker, NULL);
    pthread_cond_destroy(&ch->cond);
    pthread_mutex_destroy(&ch->lock);
    free(ch->ring);
    free(ch);
}

uint64_t tpurmChannelPushCopy(TpurmChannel *ch, void *dst, const void *src,
                              uint64_t bytes)
{
    if (!ch || (!dst && bytes) || (!src && bytes))
        return 0;

    pthread_mutex_lock(&ch->lock);
    tpuLockTrackAcquire(TPU_LOCK_CHANNEL, "channel");
    /* Back-pressure: block while the GPFIFO ring is full (the reference
     * spins/waits for ring space in uvm_channel_reserve). */
    while (!ch->stop && ch->put - ch->get >= ch->entries)
        pthread_cond_wait(&ch->cond, &ch->lock);
    if (ch->stop) {
        tpuLockTrackRelease(TPU_LOCK_CHANNEL, "channel");
        pthread_mutex_unlock(&ch->lock);
        return 0;
    }

    PushEntry *entry = &ch->ring[ch->put % ch->entries];
    entry->dst = dst;
    entry->src = src;
    entry->bytes = bytes;
    entry->trackerValue = ++ch->submittedValue;
    entry->injectError = ch->injectNext;
    ch->injectNext = false;
    ch->put++;
    uint64_t value = entry->trackerValue;
    tpuCounterAdd("channel_pushes", 1);
    pthread_cond_broadcast(&ch->cond);
    tpuLockTrackRelease(TPU_LOCK_CHANNEL, "channel");
    pthread_mutex_unlock(&ch->lock);
    return value;
}

TpuStatus tpurmChannelWait(TpurmChannel *ch, uint64_t value)
{
    if (!ch)
        return TPU_ERR_INVALID_ARGUMENT;
    pthread_mutex_lock(&ch->lock);
    while (!ch->stop && ch->completedValue < value && !ch->error)
        pthread_cond_wait(&ch->cond, &ch->lock);
    TpuStatus st = TPU_OK;
    if (ch->error)
        st = TPU_ERR_INVALID_STATE;
    else if (ch->stop && ch->completedValue < value)
        st = TPU_ERR_INVALID_STATE;
    pthread_mutex_unlock(&ch->lock);
    return st;
}

uint64_t tpurmChannelCompletedValue(TpurmChannel *ch)
{
    if (!ch)
        return 0;
    pthread_mutex_lock(&ch->lock);
    uint64_t v = ch->completedValue;
    pthread_mutex_unlock(&ch->lock);
    return v;
}

void tpurmChannelInjectError(TpurmChannel *ch)
{
    if (!ch)
        return;
    pthread_mutex_lock(&ch->lock);
    ch->injectNext = true;
    pthread_mutex_unlock(&ch->lock);
}

void tpurmChannelResetError(TpurmChannel *ch)
{
    /* Robust-channel recovery surface (reference: per-channel RC resets
     * the channel and re-arms it, src/nvidia/src/kernel/gpu/rc/): clear
     * the latched error so new work can proceed. */
    if (!ch)
        return;
    pthread_mutex_lock(&ch->lock);
    if (ch->error) {
        ch->error = false;
        tpuCounterAdd("channel_rc_resets", 1);
        tpuLog(TPU_LOG_WARN, "channel", "RC reset: error cleared at value %llu",
               (unsigned long long)ch->completedValue);
    }
    pthread_cond_broadcast(&ch->cond);
    pthread_mutex_unlock(&ch->lock);
}

/* ------------------------------------------------------- transfer engine */

TpuStatus tpuMemCopy(TpurmDevice *dev, TpuMemDesc *dst, uint64_t dstOff,
                     TpuMemDesc *src, uint64_t srcOff, uint64_t size,
                     bool async, uint64_t *outTrackerValue)
{
    if (!dev || !dst || !src || size == 0)
        return TPU_ERR_INVALID_ARGUMENT;
    if (dstOff + size > dst->size || srcOff + size > src->size)
        return TPU_ERR_INVALID_LIMIT;
    if (dev->lost)
        return TPU_ERR_GPU_IS_LOST;

    TpurmChannel *ch = dev->ce;
    uint64_t clamp = tpuRegistryGet("ce_copy_clamp_bytes", TPU_CE_COPY_CLAMP);
    uint64_t remaining = size;
    uint64_t lastValue = 0;

    /* Contiguity-split loop (reference: ce_utils.c:646-661): each push
     * covers the largest run contiguous in BOTH surfaces, clamped. */
    while (remaining > 0) {
        void *dptr, *sptr;
        uint64_t drun, srun;
        TpuStatus st = tpuMemdescResolve(dst, dev, dstOff, &dptr, &drun);
        if (st != TPU_OK)
            return st;
        st = tpuMemdescResolve(src, dev, srcOff, &sptr, &srun);
        if (st != TPU_OK)
            return st;
        uint64_t len = remaining;
        if (len > drun)
            len = drun;
        if (len > srun)
            len = srun;
        if (len > clamp)
            len = clamp;
        uint64_t value = tpurmChannelPushCopy(ch, dptr, sptr, len);
        if (value == 0)
            return TPU_ERR_INVALID_STATE;
        lastValue = value;
        dstOff += len;
        srcOff += len;
        remaining -= len;
    }

    if (outTrackerValue)
        *outTrackerValue = lastValue;
    if (async)
        return TPU_OK;
    return tpurmChannelWait(ch, lastValue);
}
