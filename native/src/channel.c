/*
 * DMA channels: the submission/completion engine.
 *
 * Re-design of the reference's UVM channel/pushbuffer/tracker trio
 * (reference: kernel-open/nvidia-uvm/uvm_channel.c — GPFIFO ring + tracking
 * semaphore per channel, uvm_channel.h:33-49 with 1,024-entry default;
 * uvm_push.c; uvm_tracker.c).  Structure, faithfully mapped:
 *
 *   pushbuffer  — per-channel ring holding the copy "methods" (CopySeg
 *                 arrays), reserved with cpu_put/gpu_get semantics
 *                 (uvm_pushbuffer.h:33-90);
 *   GPFIFO      — a lockless msgq (msgq.c, the GSP-msgq analog): each
 *                 entry is ONE submitted push pointing at its methods in
 *                 the pushbuffer, published with a release-store + futex
 *                 doorbell.  The msgq's capacity IS the GPFIFO depth and
 *                 its back-pressure is the reference's GPFIFO-full wait;
 *   CE          — an executor thread consuming the msgq across the queue
 *                 boundary (channel work is *submitted to* the runtime,
 *                 never executed inline in the caller).  Fake arena: the
 *                 executor memmoves into the host shadow.  Real arena:
 *                 the same memmoves hit the shadow and publish dirty
 *                 ranges to the per-device HBM mirror stream (hbm.c),
 *                 which the JAX runtime applies to chip HBM;
 *   tracker     — the msgq sequence doubles as the channel's monotonic
 *                 tracker value; "completed value >= push value" is the
 *                 completion predicate (uvm_gpu_semaphore.c).
 *
 * Preserved semantics: fixed-depth ring with blocking back-pressure,
 * latched channel errors failing subsequent waits (robust-channel
 * recovery surface), error injection for tests.
 */
#define _GNU_SOURCE
#include "internal.h"
#include "tpurm/ce.h"
#include "tpurm/inject.h"
#include "tpurm/msgq.h"
#include "tpurm/shield.h"
#include "tpurm/trace.h"

#include <stdatomic.h>
#include <stdlib.h>
#include <string.h>

/* Failed-push history depth per channel (see errSeqs below). */
#define CH_ERR_RING 64

/* A copy method within a push (the reference encodes CE methods into
 * pushbuffer space; here a segment IS the method).  xform selects an
 * executor-side transform (TPU_CE_COMP_* from ce.h; 0 = plain copy) —
 * the tpuce compression stage quantizes through it. */
typedef struct {
    void *dst;
    const void *src;
    uint64_t bytes;
    uint32_t xform;
    uint32_t pad;
    /* tpuflow identity stamped from the SUBMITTING thread's flow
     * context at tpuPushCopySegEx time: the executor thread re-enters
     * it around the memmove so ce.stripe spans carry the request the
     * stripe moves bytes for (cross-thread propagation, same shape as
     * the memring SQE flowId). */
    uint64_t flow;
    /* tpushield seal stage: when crcOut != NULL the executor computes
     * one CRC32C per crcStride bytes of the DESTINATION (post-xform —
     * the seal covers what is actually stored) into consecutive cells
     * — the sealing work rides the executor thread, overlapped with
     * the copy pipeline instead of serialized after the fence. */
    uint32_t *crcOut;
    uint64_t crcStride;
} CopySeg;

/* Outstanding pushbuffer chunk, in allocation order.  gpu_get advances
 * over the done-prefix only, so out-of-order submission between Begin and
 * End never releases space still being written (the reference tracks
 * per-chunk completion the same way, uvm_pushbuffer.c). */
typedef struct PbChunk {
    uint64_t end;              /* monotonic end offset (incl. leading pad) */
    bool done;
    struct PbChunk *next;
} PbChunk;

struct TpurmChannel {
    TpurmDevice *dev;
    TpurmCeType ce;
    TpuMsgq *fifo;             /* the GPFIFO: one cmd per push; its
                                * capacity is the GPFIFO depth          */
    pthread_t executor;
    bool executorStarted;
    /* Pushbuffer ring (uvm_pushbuffer.h:33-90 semantics): cpu_put grows
     * on reservation, gpu_get follows retired chunks. */
    uint8_t *pbBase;
    uint64_t pbSize;
    uint64_t pbCpuPut, pbGpuGet;   /* monotonic byte offsets */
    PbChunk *pbChunks, *pbChunksTail;
    PbChunk *pbChunkFree;          /* recycled chunk nodes */
    bool stop;
    bool injectNext;           /* legacy latch (arm-table-full fallback) */
    _Atomic int error;         /* latched channel error */
    /* Failed-push attribution, immune to RC resets: the executor
     * records every faulted push's tracker value here (monotonic
     * append; the latch above can be cleared by recovery while another
     * thread still owes a wait on the faulted push, but this history
     * cannot).  tpurmChannelWaitRange checks it so a concurrent
     * RC reset-and-replay never turns a faulted copy into a silent
     * success. */
    _Atomic uint64_t errSeqs[CH_ERR_RING];
    _Atomic uint32_t errSeqCount;   /* total failures (write cursor)   */
    _Atomic uint64_t errEvictedMax; /* highest seq aged out of the ring */
    _Atomic uint32_t evRefs;   /* live event-worker jobs referencing us
                                * (event.c); destroy waits for zero */
    /* tpuce per-channel accounting (ce.c): executed bytes / busy-ns
     * land in these counter cells; ceIdx tags ce.stripe trace spans. */
    _Atomic(_Atomic uint64_t *) ceBytesCtr;
    _Atomic(_Atomic uint64_t *) ceBusyCtr;
    uint32_t ceIdx;
    _Atomic uint32_t stallMs;  /* test injection: executor stall */
    uint64_t rcId;             /* unique id for RC attribution (ABA) */
    TpurmChannelErrorNotifier errNotifier;   /* under lock */
    void *errNotifierCtx;
    pthread_mutex_t lock;      /* pushbuffer + inject latch */
    pthread_cond_t cond;       /* pushbuffer space freed */
};

/* Mark the chunk ending at `end` done and advance gpu_get over the done
 * prefix (ch->lock held). */
static void pb_release_locked(TpurmChannel *ch, uint64_t end)
{
    for (PbChunk *c = ch->pbChunks; c; c = c->next) {
        if (c->end == end) {
            c->done = true;
            break;
        }
    }
    while (ch->pbChunks && ch->pbChunks->done) {
        PbChunk *c = ch->pbChunks;
        ch->pbGpuGet = c->end;
        ch->pbChunks = c->next;
        if (!ch->pbChunks)
            ch->pbChunksTail = NULL;
        c->next = ch->pbChunkFree;     /* recycle (freed at destroy) */
        ch->pbChunkFree = c;
    }
}

/* The CE: drains GPFIFO entries, executes their methods against the
 * shadow arena, publishes real-HBM dirty ranges, retires the push.
 * Shutdown drains whatever is already queued, then exits. */
static void *channel_executor(void *arg)
{
    TpurmChannel *ch = arg;
    TpuMsgqCmd cmd;

    /* Executors spread over distinct CPUs alongside the spine workers
     * (no-op on <=2 CPU hosts — see tpuCpuPinThread). */
    tpuCpuPinThread("ce-executor");

    while (tpuMsgqReceive(ch->fifo, &cmd, 1) == 1) {
        uint32_t stall = atomic_exchange_explicit(&ch->stallMs, 0,
                                                  memory_order_acq_rel);
        if (stall) {
            struct timespec ts = { .tv_sec = stall / 1000,
                                   .tv_nsec = (long)(stall % 1000) *
                                              1000000L };
            nanosleep(&ts, NULL);
        }
        bool failed = (cmd.flags & TPU_MSGQ_FLAG_INJECT_ERROR) != 0;
        bool readbackFailed = false;
        uint64_t bytes = 0;
        _Atomic uint64_t *ceBytes = atomic_load_explicit(
            &ch->ceBytesCtr, memory_order_acquire);
        _Atomic uint64_t *ceBusy = atomic_load_explicit(
            &ch->ceBusyCtr, memory_order_acquire);
        uint64_t tExec = ceBusy ? tpuNowNs() : 0;
        if (!failed && cmd.op == TPU_MSGQ_CE_PUSH) {
            const CopySeg *segs = (const CopySeg *)(uintptr_t)cmd.src;
            /* tpuflow: a push is one stripe (one request): enter its
             * identity for the exec window so the ce.stripe span below
             * carries it across the executor-thread boundary. */
            if (cmd.bytes > 0 && segs[0].flow)
                tpurmTraceFlowSet(segs[0].flow);
            for (uint64_t i = 0; i < cmd.bytes; i++) {
                if (segs[i].bytes > 0) {
                    /* Direction-agnostic device boundary (reference
                     * mem_utils.c:567): if either side overlaps pages
                     * a jitted computation wrote on-chip, download
                     * them into the shadow first — the src so we copy
                     * chip truth, the dst so untouched bytes of
                     * partially-overwritten pages aren't lost when the
                     * write republishes the (otherwise stale) span.
                     * Failure means the shadow is STALE: fail the push
                     * (CE fault) rather than copy — an eviction that
                     * committed a stale read would free the only copy
                     * of chip-computed data. */
                    if (tpuHbmCoherentForRead(segs[i].src,
                                              segs[i].bytes) != TPU_OK ||
                        tpuHbmCoherentForRead(segs[i].dst,
                                              segs[i].bytes) != TPU_OK) {
                        failed = true;
                        readbackFailed = true;
                        break;
                    }
                    if (segs[i].xform)
                        tpuCeXformExec(segs[i].xform, segs[i].dst,
                                       segs[i].src, segs[i].bytes);
                    else
                        memmove(segs[i].dst, segs[i].src, segs[i].bytes);
                    tpuHbmMirrorNotify(segs[i].dst, segs[i].bytes);
                    if (segs[i].crcOut && segs[i].crcStride) {
                        /* Seal stage: CRC the just-written destination
                         * while it is cache-hot.  The caller's fence
                         * (tracker-value wait) publishes the cells. */
                        uint64_t st = segs[i].crcStride;
                        uint32_t *out = segs[i].crcOut;
                        const uint8_t *d = segs[i].dst;
                        for (uint64_t off = 0; off + st <= segs[i].bytes;
                             off += st)
                            *out++ = tpurmShieldCrc32c(d + off, st);
                    }
                }
                bytes += segs[i].bytes;
            }
        }
        /* tpuce accounting: executed bytes + executor busy time on the
         * channel's counter cells (Prometheus tpuce_ch{N}_* series),
         * plus a per-channel ce.stripe span while tracing is armed. */
        if (ceBusy) {
            uint64_t tDone = tpuNowNs();
            atomic_fetch_add_explicit(ceBusy, tDone - tExec,
                                      memory_order_relaxed);
            if (!failed && bytes && ceBytes)
                atomic_fetch_add_explicit(ceBytes, bytes,
                                          memory_order_relaxed);
            if (bytes && tpurmTraceIsArmed())
                tpurmTraceSpanAt(TPU_TRACE_CE_STRIPE, tExec, tDone,
                                 ch->ceIdx, bytes);
        }
        tpurmTraceFlowSet(0);          /* stripe flow scope ends */

        pthread_mutex_lock(&ch->lock);
        pb_release_locked(ch, cmd.pbEnd);
        pthread_cond_broadcast(&ch->cond);
        pthread_mutex_unlock(&ch->lock);

        if (failed) {
            /* Record the faulted value in the failed-push history
             * BEFORE retiring the command: a waiter that observes
             * completion of this seq is then guaranteed to see the
             * record (release via the msgq's completedSeq store). */
            uint32_t n = atomic_load_explicit(&ch->errSeqCount,
                                              memory_order_relaxed);
            if (n >= CH_ERR_RING) {
                uint64_t old = atomic_load_explicit(
                    &ch->errSeqs[n % CH_ERR_RING], memory_order_relaxed);
                uint64_t evicted = atomic_load_explicit(
                    &ch->errEvictedMax, memory_order_relaxed);
                if (old > evicted)
                    atomic_store_explicit(&ch->errEvictedMax, old,
                                          memory_order_release);
            }
            atomic_store_explicit(&ch->errSeqs[n % CH_ERR_RING], cmd.seq,
                                  memory_order_release);
            atomic_store_explicit(&ch->errSeqCount, n + 1,
                                  memory_order_release);
            /* Latch synchronously (wait semantics) AND post to the
             * non-replayable shadow buffer for attribution/recovery
             * (rc.c — the reference's CE-fault delivery split). */
            atomic_store_explicit(&ch->error, 1, memory_order_release);
            TPU_LOG(TPU_LOG_ERROR, "channel",
                   readbackFailed
                       ? "CE fault: chip readback unavailable at tracker "
                         "value %llu"
                       : "injected CE fault at tracker value %llu",
                   (unsigned long long)cmd.seq);
            tpuRcPostFault(ch, ch->rcId, cmd.seq, TPU_RC_CE_FAULT);
        }
        tpuCounterAdd("channel_copies_completed", 1);
        tpuCounterAdd("channel_bytes_copied", failed ? 0 : bytes);
        tpuMsgqComplete(ch->fifo, cmd.seq);
    }
    return NULL;
}

TpurmChannel *tpurmChannelCreate(TpurmDevice *dev, TpurmCeType ce,
                                 uint32_t ring_entries)
{
    if (ring_entries == 0)
        ring_entries = (uint32_t)tpuRegistryGet("channel_num_gpfifo_entries",
                                                1024);
    /* Reference bounds: min 32, max 1M (uvm_channel.h:49-51). */
    if (ring_entries < 32)
        ring_entries = 32;
    if (ring_entries > (1u << 20))
        ring_entries = 1u << 20;

    TpurmChannel *ch = calloc(1, sizeof(*ch));
    if (!ch)
        return NULL;
    /* The GPFIFO: msgq capacity = ring depth; MPSC because any engine
     * thread may submit pushes. */
    ch->fifo = tpuMsgqCreate(ring_entries, TPU_MSGQ_MPSC);
    if (!ch->fifo) {
        free(ch);
        return NULL;
    }
    /* Pushbuffer sized by registry (reference: UVM_PUSHBUFFER_SIZE). */
    ch->pbSize = tpuRegistryGet("pushbuffer_size_bytes", 1ull << 20);
    if (ch->pbSize < 4096)
        ch->pbSize = 4096;
    ch->pbBase = malloc(ch->pbSize);
    if (!ch->pbBase) {
        tpuMsgqDestroy(ch->fifo);
        free(ch);
        return NULL;
    }
    ch->dev = dev;
    ch->ce = ce;
    pthread_mutex_init(&ch->lock, NULL);
    pthread_cond_init(&ch->cond, NULL);
    if (pthread_create(&ch->executor, NULL, channel_executor, ch) != 0) {
        tpuMsgqDestroy(ch->fifo);
        free(ch->pbBase);
        free(ch);
        return NULL;
    }
    ch->executorStarted = true;
    /* Unique id guards RC attribution against pointer reuse (a stale
     * shadow record must not land on a recycled channel address). */
    static _Atomic uint64_t nextRcId;
    ch->rcId = atomic_fetch_add_explicit(&nextRcId, 1,
                                         memory_order_relaxed) + 1;
    tpuRcChannelRegister(ch, ch->rcId);
    return ch;
}

void tpurmChannelDestroy(TpurmChannel *ch)
{
    if (!ch)
        return;
    /* Leave the RC registry first: the RC service delivers under the
     * registry lock, so after this returns no delivery can hold ch. */
    tpuRcChannelUnregister(ch);
    /* Event-worker jobs hold (channel, seq) dependencies pinned by a
     * per-channel refcount taken while the submitter still held the
     * channel live; the executor is still draining here, so their
     * waits complete.  Wait for THIS channel's jobs only — a global
     * drain would block on unrelated (possibly wedged) channels. */
    tpurmEventQuiesceChannel(ch);
    pthread_mutex_lock(&ch->lock);
    ch->stop = true;
    pthread_cond_broadcast(&ch->cond);
    pthread_mutex_unlock(&ch->lock);
    /* Shutdown lets the executor drain already-queued pushes first. */
    tpuMsgqShutdown(ch->fifo);
    if (ch->executorStarted)
        pthread_join(ch->executor, NULL);
    tpuMsgqDestroy(ch->fifo);
    pthread_cond_destroy(&ch->cond);
    pthread_mutex_destroy(&ch->lock);
    while (ch->pbChunks) {
        PbChunk *c = ch->pbChunks;
        ch->pbChunks = c->next;
        free(c);
    }
    while (ch->pbChunkFree) {
        PbChunk *c = ch->pbChunkFree;
        ch->pbChunkFree = c->next;
        free(c);
    }
    free(ch->pbBase);
    free(ch);
}

/* ---------------------------------------------------------- push objects */

TpuStatus tpuPushBegin(TpurmChannel *ch, uint32_t maxSegs, TpuPush *p)
{
    if (!ch || !p || maxSegs == 0)
        return TPU_ERR_INVALID_ARGUMENT;
    uint64_t need = (uint64_t)maxSegs * sizeof(CopySeg);
    /* A reservation that wraps pads the unusable tail, so worst case it
     * consumes pad + need < need + need bytes.  Anything over pbSize/2
     * could deadlock the back-pressure wait on an idle channel (pad+need
     * can exceed the whole ring with nothing left to retire). */
    if (need * 2 > ch->pbSize)
        return TPU_ERR_INVALID_LIMIT;

    pthread_mutex_lock(&ch->lock);
    tpuLockTrackAcquire(TPU_LOCK_CHANNEL, "push-begin");
    for (;;) {
        if (ch->stop) {
            tpuLockTrackRelease(TPU_LOCK_CHANNEL, "push-begin");
            pthread_mutex_unlock(&ch->lock);
            return TPU_ERR_INVALID_STATE;
        }
        uint64_t pos = ch->pbCpuPut % ch->pbSize;
        uint64_t pad = pos + need > ch->pbSize ? ch->pbSize - pos : 0;
        /* Reservation back-pressure: wait for gpu_get to free space
         * (reference blocks reserving pushbuffer space the same way). */
        if (ch->pbCpuPut + pad + need - ch->pbGpuGet > ch->pbSize) {
            pthread_cond_wait(&ch->cond, &ch->lock);
            continue;
        }
        ch->pbCpuPut += pad;          /* skip unusable tail */
        p->segs = ch->pbBase + (ch->pbCpuPut % ch->pbSize);
        ch->pbCpuPut += need;
        p->pbEndOffset = ch->pbCpuPut;
        break;
    }
    /* Track the chunk (in allocation order) so gpu_get only advances
     * over completed prefixes.  Nodes come from the recycle list in
     * steady state; malloc only grows the pool (bounded by outstanding
     * pushes, itself bounded by the GPFIFO depth). */
    PbChunk *c = ch->pbChunkFree;
    if (c) {
        ch->pbChunkFree = c->next;
    } else {
        c = malloc(sizeof(*c));
        if (!c) {
            /* Roll back the reservation (lock held since we advanced). */
            ch->pbCpuPut = p->pbEndOffset - ((uint64_t)maxSegs *
                                             sizeof(CopySeg));
            tpuLockTrackRelease(TPU_LOCK_CHANNEL, "push-begin");
            pthread_mutex_unlock(&ch->lock);
            return TPU_ERR_NO_MEMORY;
        }
    }
    c->end = p->pbEndOffset;
    c->done = false;
    c->next = NULL;
    if (ch->pbChunksTail)
        ch->pbChunksTail->next = c;
    else
        ch->pbChunks = c;
    ch->pbChunksTail = c;
    tpuLockTrackRelease(TPU_LOCK_CHANNEL, "push-begin");
    pthread_mutex_unlock(&ch->lock);

    p->ch = ch;
    p->nsegs = 0;
    p->maxSegs = maxSegs;
    return TPU_OK;
}

TpuStatus tpuPushCopySegEx(TpuPush *p, void *dst, const void *src,
                           uint64_t bytes, uint32_t xform)
{
    return tpuPushCopySegCrc(p, dst, src, bytes, xform, NULL, 0);
}

TpuStatus tpuPushCopySegCrc(TpuPush *p, void *dst, const void *src,
                            uint64_t bytes, uint32_t xform,
                            uint32_t *crcOut, uint64_t crcStride)
{
    if (!p || !p->ch || p->nsegs >= p->maxSegs)
        return TPU_ERR_INVALID_ARGUMENT;
    if (bytes && (!dst || !src))
        return TPU_ERR_INVALID_ARGUMENT;
    if (crcOut && (crcStride == 0 || bytes % crcStride))
        return TPU_ERR_INVALID_ARGUMENT;
    CopySeg *s = &((CopySeg *)p->segs)[p->nsegs++];
    s->dst = dst;
    s->src = src;
    s->bytes = bytes;
    s->xform = xform;
    s->pad = 0;
    s->flow = tpurmTraceFlowGet();
    s->crcOut = crcOut;
    s->crcStride = crcStride;
    return TPU_OK;
}

TpuStatus tpuPushCopySeg(TpuPush *p, void *dst, const void *src,
                         uint64_t bytes)
{
    return tpuPushCopySegEx(p, dst, src, bytes, 0);
}

uint64_t tpuPushEnd(TpuPush *p, TpuTracker *t)
{
    if (!p || !p->ch)
        return 0;
    TpurmChannel *ch = p->ch;
    uint64_t tSpan = tpurmTraceBegin();

    pthread_mutex_lock(&ch->lock);
    tpuLockTrackAcquire(TPU_LOCK_CHANNEL, "push-end");
    bool stopped = ch->stop;
    bool inject = ch->injectNext;
    ch->injectNext = false;
    tpuLockTrackRelease(TPU_LOCK_CHANNEL, "push-end");
    pthread_mutex_unlock(&ch->lock);
    /* Framework channel-CE site: a global arming (ppm chaos) or a
     * scoped one-shot (the tpurmChannelInjectError shim, keyed by this
     * channel's rc id) fails this push exactly like the legacy latch. */
    if (!inject &&
        tpurmInjectShouldFailScoped(TPU_INJECT_SITE_CHANNEL_CE, ch->rcId))
        inject = true;
    if (stopped) {
        tpuPushAbort(p);
        return 0;
    }

    /* Submit ONE GPFIFO entry pointing at the methods in the pushbuffer
     * (the reference's GPFIFO entries likewise point at pushbuffer
     * chunks).  The msgq assigns the monotonic sequence — the tracker
     * value — under its tx lock, so value order == queue order.  Submit
     * blocks while the GPFIFO is full (back-pressure); the executor
     * retires entries without taking the msgq tx lock, so this cannot
     * deadlock. */
    TpuMsgqCmd cmd = {
        .op = TPU_MSGQ_CE_PUSH,
        .flags = inject ? TPU_MSGQ_FLAG_INJECT_ERROR : 0,
        .src = (uint64_t)(uintptr_t)p->segs,
        .bytes = p->nsegs,
        .pbEnd = p->pbEndOffset,
    };
    /* Sum BEFORE submit: once the executor retires the push its
     * pushbuffer chunk recycles and another producer may rewrite it. */
    uint64_t pushBytes = 0;
    if (tSpan)
        for (uint32_t i = 0; i < p->nsegs; i++)
            pushBytes += ((const CopySeg *)p->segs)[i].bytes;
    uint64_t value = 0;
    if (tpuMsgqSubmit(ch->fifo, &cmd, 1, &value) != 0) {
        tpuPushAbort(p);
        return 0;
    }
    tpuCounterAdd("channel_pushes", 1);
    if (tSpan)
        tpurmTraceEnd(TPU_TRACE_CHANNEL_PUSH, tSpan, ch->rcId, pushBytes);

    p->ch = NULL;
    if (t && tpuTrackerAdd(t, ch, value) != TPU_OK)
        /* Dependency could not be recorded (tracker growth OOM): degrade
         * to synchronous completion so no dependency is silently lost. */
        tpurmChannelWait(ch, value);
    return value;
}

void tpuPushAbort(TpuPush *p)
{
    if (!p || !p->ch)
        return;
    TpurmChannel *ch = p->ch;
    pthread_mutex_lock(&ch->lock);
    pb_release_locked(ch, p->pbEndOffset);
    pthread_cond_broadcast(&ch->cond);   /* space freed: wake reservers */
    pthread_mutex_unlock(&ch->lock);
    p->ch = NULL;
}

uint64_t tpurmChannelPushCopy(TpurmChannel *ch, void *dst, const void *src,
                              uint64_t bytes)
{
    if (!ch || (!dst && bytes) || (!src && bytes))
        return 0;
    TpuPush p;
    if (tpuPushBegin(ch, 1, &p) != TPU_OK)
        return 0;
    if (tpuPushCopySeg(&p, dst, src, bytes) != TPU_OK) {
        tpuPushAbort(&p);
        return 0;
    }
    return tpuPushEnd(&p, NULL);
}

TpuStatus tpurmChannelWait(TpurmChannel *ch, uint64_t value)
{
    if (!ch)
        return TPU_ERR_INVALID_ARGUMENT;
    uint64_t tSpan = tpurmTraceBegin();
    /* The executor always drains (even through shutdown), so waiting on
     * the sequence either succeeds or the queue was shut down with the
     * value never reached. */
    bool reached = value == 0 || tpuMsgqWaitSeq(ch->fifo, value);
    if (tSpan)
        tpurmTraceEnd(TPU_TRACE_CHANNEL_FENCE, tSpan, ch->rcId, value);
    if (atomic_load_explicit(&ch->error, memory_order_acquire))
        return TPU_ERR_INVALID_STATE;
    return reached ? TPU_OK : TPU_ERR_INVALID_STATE;
}

uint64_t tpurmChannelCompletedValue(TpurmChannel *ch)
{
    return ch ? tpuMsgqCompletedSeq(ch->fifo) : 0;
}

/* Range wait: completion of `value`, failing only if a push whose
 * tracker value lies in [minValue, value] faulted.  Unlike the latch
 * check in tpurmChannelWait, this attributes failures to the caller's
 * own window of pushes — a concurrent RC reset (recovery on another
 * thread) cannot hide them, and another client's later fault cannot
 * leak in.  Used by trackers and every engine retry loop. */
TpuStatus tpurmChannelWaitRange(TpurmChannel *ch, uint64_t minValue,
                                uint64_t value)
{
    if (!ch)
        return TPU_ERR_INVALID_ARGUMENT;
    if (value == 0)
        return TPU_OK;
    uint64_t tSpan = tpurmTraceBegin();
    bool reached = tpuMsgqWaitSeq(ch->fifo, value);
    if (tSpan)
        tpurmTraceEnd(TPU_TRACE_CHANNEL_FENCE, tSpan, ch->rcId, value);
    if (!reached)
        return TPU_ERR_INVALID_STATE;
    uint32_t n = atomic_load_explicit(&ch->errSeqCount,
                                      memory_order_acquire);
    if (n) {
        uint32_t scan = n < CH_ERR_RING ? n : CH_ERR_RING;
        for (uint32_t i = 0; i < scan; i++) {
            uint64_t s = atomic_load_explicit(&ch->errSeqs[i],
                                              memory_order_acquire);
            if (s >= minValue && s <= value)
                return TPU_ERR_INVALID_STATE;
        }
        /* History aged out past our window: cannot prove the window
         * clean, so fail conservatively (caller retries). */
        if (atomic_load_explicit(&ch->errEvictedMax,
                                 memory_order_acquire) >= minValue)
            return TPU_ERR_INVALID_STATE;
    }
    return TPU_OK;
}

bool tpurmChannelErrorPending(TpurmChannel *ch)
{
    return ch && atomic_load_explicit(&ch->error,
                                      memory_order_acquire) != 0;
}

/* Thin shim over the injection framework's channel-CE site: arm a
 * one-shot scoped to this channel's rc id — consumed by this channel's
 * next push, which then carries TPU_MSGQ_FLAG_INJECT_ERROR exactly as
 * the old latch did.  The legacy latch survives only as the fallback
 * when the arm table is full. */
void tpurmChannelInjectError(TpurmChannel *ch)
{
    if (!ch)
        return;
    if (tpurmInjectArmOneShot(TPU_INJECT_SITE_CHANNEL_CE, ch->rcId) ==
        TPU_OK)
        return;
    pthread_mutex_lock(&ch->lock);
    ch->injectNext = true;
    pthread_mutex_unlock(&ch->lock);
}

void tpurmChannelSetErrorNotifier(TpurmChannel *ch,
                                  TpurmChannelErrorNotifier cb, void *ctx)
{
    if (!ch)
        return;
    pthread_mutex_lock(&ch->lock);
    ch->errNotifier = cb;
    ch->errNotifierCtx = ctx;
    pthread_mutex_unlock(&ch->lock);
}

void tpurmChannelInjectStall(TpurmChannel *ch, uint32_t ms)
{
    if (ch)
        atomic_store_explicit(&ch->stallMs, ms, memory_order_release);
}

/* RC-service delivery (rc.c, under the RC registry lock): notifier +
 * recovery policy (registry rc_policy: 0 = latch only, 1 = auto-reset
 * so subsequent work flows without an explicit ResetError). */
void tpurmChannelRcDeliver(TpurmChannel *ch, uint64_t value, uint32_t kind)
{
    pthread_mutex_lock(&ch->lock);
    TpurmChannelErrorNotifier cb = ch->errNotifier;
    void *ctx = ch->errNotifierCtx;
    pthread_mutex_unlock(&ch->lock);
    if (cb)
        cb(ctx, value, kind);
    /* RM event path (NV0005 analog, NV2080_NOTIFIERS_RC_ERROR): armed
     * clients hear channel RC without registering a per-channel
     * callback — the reference's krcEvent notification. */
    if (ch->dev)
        tpurmEventFire(ch->dev->inst, TPU_NOTIFIER_RC_ERROR,
                       (uint32_t)value, (uint16_t)kind);
    if (kind == TPU_RC_CE_FAULT && tpuRegistryGet("rc_policy", 0) == 1) {
        tpurmChannelResetError(ch);
        tpuCounterAdd("rc_auto_resets", 1);
    }
}

void tpurmChannelProgress(TpurmChannel *ch, uint64_t *completed,
                          uint64_t *pendingDepth)
{
    *completed = tpuMsgqCompletedSeq(ch->fifo);
    *pendingDepth = tpuMsgqDepth(ch->fifo);
}

void tpurmChannelResetError(TpurmChannel *ch)
{
    /* Robust-channel recovery surface (reference: per-channel RC resets
     * the channel and re-arms it, src/nvidia/src/kernel/gpu/rc/): clear
     * the latched error so new work can proceed. */
    if (!ch)
        return;
    if (atomic_exchange_explicit(&ch->error, 0, memory_order_acq_rel)) {
        tpuCounterAdd("channel_rc_resets", 1);
        TPU_LOG(TPU_LOG_WARN, "channel", "RC reset: error cleared at value %llu",
               (unsigned long long)tpuMsgqCompletedSeq(ch->fifo));
    }
}

/* ------------------------------------------------------- transfer engine */

TpuStatus tpuMemCopy(TpurmDevice *dev, TpuMemDesc *dst, uint64_t dstOff,
                     TpuMemDesc *src, uint64_t srcOff, uint64_t size,
                     bool async, TpuTracker *outTracker)
{
    if (!dev || !dst || !src || size == 0)
        return TPU_ERR_INVALID_ARGUMENT;
    if (dstOff + size > dst->size || srcOff + size > src->size)
        return TPU_ERR_INVALID_LIMIT;
    if (dev->lost)
        return TPU_ERR_GPU_IS_LOST;
    TpuCeMgr *mgr = tpuCeMgrGet(dev->inst);
    if (!mgr)
        return TPU_ERR_INVALID_STATE;

    uint64_t clamp = tpuRegistryGet("ce_copy_clamp_bytes", TPU_CE_COPY_CLAMP);
    uint64_t remaining = size;
    TpuCeBatch batch;
    TpuStatus st = tpuCeBatchBegin(mgr, &batch);
    if (st != TPU_OK)
        return st;

    /* Contiguity-split loop (reference: ce_utils.c:646-661): each copy
     * covers the largest run contiguous in BOTH surfaces, clamped, and
     * rides the tpuce scheduler — stripes land on the least-loaded
     * channel with per-stripe recovery at the fence.  Fragmented
     * surfaces (page-list memdescs split into 4 KB runs) GATHER up to
     * TPUCE_GATHER_SEGS runs per stripe, keeping the old
     * many-segments-per-push submission economy. */
    TpuCeSeg gather[TPUCE_GATHER_SEGS];
    uint32_t ngather = 0;
    uint64_t gatherMax = 64 * 1024;     /* runs below this batch up */
    while (remaining > 0) {
        void *dptr, *sptr;
        uint64_t drun, srun;
        st = tpuMemdescResolve(dst, dev, dstOff, &dptr, &drun);
        if (st != TPU_OK)
            goto fail;
        st = tpuMemdescResolve(src, dev, srcOff, &sptr, &srun);
        if (st != TPU_OK)
            goto fail;
        uint64_t len = remaining;
        if (len > drun)
            len = drun;
        if (len > srun)
            len = srun;
        if (len > clamp)
            len = clamp;
        if (len < gatherMax) {
            gather[ngather].dst = dptr;
            gather[ngather].src = sptr;
            gather[ngather].len = len;
            if (++ngather == TPUCE_GATHER_SEGS) {
                st = tpuCeBatchCopySegs(&batch, gather, ngather);
                ngather = 0;
                if (st != TPU_OK)
                    goto fail;
            }
        } else {
            st = tpuCeBatchCopy(&batch, dptr, sptr, len,
                                TPU_CE_COMP_NONE);
            if (st != TPU_OK)
                goto fail;
        }
        dstOff += len;
        srcOff += len;
        remaining -= len;
    }
    if (ngather) {
        st = tpuCeBatchCopySegs(&batch, gather, ngather);
        if (st != TPU_OK)
            goto fail;
    }

    if (async && outTracker)
        /* Hand the dependencies to the caller (unregister quiesce etc.);
         * failures then surface at the caller's range-checked wait. */
        return tpuCeBatchHandoff(&batch, outTracker);
    return tpuCeBatchWait(&batch);

fail:
    /* Drain stripes already submitted: the caller may free/unpin the
     * surfaces on error while workers are still writing them (same rule
     * as block_copy_in's drain-before-unwind). */
    tpuCeBatchWait(&batch);
    return st;
}

/* ---------------------------------------------------- tpuce accounting */

void tpurmChannelSetCeAcct(TpurmChannel *ch, _Atomic uint64_t *bytesCtr,
                           _Atomic uint64_t *busyCtr, uint32_t ceIdx)
{
    if (!ch)
        return;
    ch->ceIdx = ceIdx;
    atomic_store_explicit(&ch->ceBytesCtr, bytesCtr, memory_order_release);
    atomic_store_explicit(&ch->ceBusyCtr, busyCtr, memory_order_release);
}

/* ---- event-job pinning (event.c) ---- */

void tpurmChannelEvRef(TpurmChannel *ch)
{
    atomic_fetch_add_explicit(&ch->evRefs, 1, memory_order_acq_rel);
}

void tpurmChannelEvUnref(TpurmChannel *ch)
{
    atomic_fetch_sub_explicit(&ch->evRefs, 1, memory_order_acq_rel);
}

uint32_t tpurmChannelEvRefs(TpurmChannel *ch)
{
    return atomic_load_explicit(&ch->evRefs, memory_order_acquire);
}
