/*
 * DMA channels: the submission/completion engine.
 *
 * Re-design of the reference's UVM channel/pushbuffer/tracker trio
 * (reference: kernel-open/nvidia-uvm/uvm_channel.c — GPFIFO ring + tracking
 * semaphore per channel, uvm_channel.h:33-49 with 1,024-entry default;
 * uvm_push.c; uvm_tracker.c).  TPU-native shape: the "copy engine" behind a
 * channel is a worker thread doing memcpy for the fake-device/host tiers —
 * real HBM traffic is submitted by the Python runtime through XLA, which
 * plays the role the GSP-owned CE plays in the reference (SURVEY.md §1
 * layer map: libtpu/XLA ≈ firmware).
 *
 * Semantics preserved from the reference:
 *   - fixed-depth ring with blocking back-pressure when full,
 *   - a monotonically increasing tracker value per channel; a push's
 *     completion is "completed value >= push value" (uvm_gpu_semaphore.c),
 *   - channel error latches and fails subsequent waits (robust-channel
 *     recovery surface, SURVEY.md §5),
 *   - error injection for tests (uvm_test.c error-injection ioctls).
 */
#define _GNU_SOURCE
#include "internal.h"

#include <stdlib.h>
#include <string.h>

/* A copy method within a push (the reference encodes CE methods into
 * pushbuffer space; here a segment IS the method). */
typedef struct {
    void *dst;
    const void *src;
    uint64_t bytes;
} CopySeg;

typedef struct {
    CopySeg *segs;             /* points into the pushbuffer */
    uint32_t nsegs;
    uint64_t pbEnd;            /* monotonic pushbuffer offset to release */
    uint64_t trackerValue;
    bool injectError;
} PushEntry;

/* Outstanding pushbuffer chunk, in allocation order.  gpu_get advances
 * over the done-prefix only, so out-of-order submission between Begin and
 * End never releases space still being written (the reference tracks
 * per-chunk completion the same way, uvm_pushbuffer.c). */
typedef struct PbChunk {
    uint64_t end;              /* monotonic end offset (incl. leading pad) */
    bool done;
    struct PbChunk *next;
} PbChunk;

struct TpurmChannel {
    TpurmDevice *dev;
    TpurmCeType ce;
    uint32_t entries;
    PushEntry *ring;
    uint64_t put;              /* producer index (monotonic) */
    uint64_t get;              /* consumer index (monotonic) */
    uint64_t submittedValue;   /* last tracker value handed out */
    uint64_t completedValue;   /* tracker semaphore */
    /* Pushbuffer ring (uvm_pushbuffer.h:33-90 semantics): cpu_put grows
     * on reservation, gpu_get follows retired chunks. */
    uint8_t *pbBase;
    uint64_t pbSize;
    uint64_t pbCpuPut, pbGpuGet;   /* monotonic byte offsets */
    PbChunk *pbChunks, *pbChunksTail;
    PbChunk *pbChunkFree;          /* recycled chunk nodes */
    bool stop;
    bool injectNext;
    bool error;                /* latched channel error */
    pthread_mutex_t lock;
    pthread_cond_t cond;       /* any state change */
    pthread_t worker;
};

/* Mark the chunk ending at `end` done and advance gpu_get over the done
 * prefix (ch->lock held). */
static void pb_release_locked(TpurmChannel *ch, uint64_t end)
{
    for (PbChunk *c = ch->pbChunks; c; c = c->next) {
        if (c->end == end) {
            c->done = true;
            break;
        }
    }
    while (ch->pbChunks && ch->pbChunks->done) {
        PbChunk *c = ch->pbChunks;
        ch->pbGpuGet = c->end;
        ch->pbChunks = c->next;
        if (!ch->pbChunks)
            ch->pbChunksTail = NULL;
        c->next = ch->pbChunkFree;     /* recycle (freed at destroy) */
        ch->pbChunkFree = c;
    }
}

static void *channel_worker(void *arg)
{
    TpurmChannel *ch = arg;

    pthread_mutex_lock(&ch->lock);
    for (;;) {
        while (!ch->stop && ch->get == ch->put)
            pthread_cond_wait(&ch->cond, &ch->lock);
        if (ch->stop)
            break;

        PushEntry entry = ch->ring[ch->get % ch->entries];
        pthread_mutex_unlock(&ch->lock);

        bool failed = entry.injectError;
        uint64_t bytes = 0;
        if (!failed) {
            for (uint32_t i = 0; i < entry.nsegs; i++) {
                CopySeg *s = &entry.segs[i];
                if (s->bytes > 0)
                    memmove(s->dst, s->src, s->bytes);
                bytes += s->bytes;
            }
        }

        pthread_mutex_lock(&ch->lock);
        ch->get++;
        ch->completedValue = entry.trackerValue;
        pb_release_locked(ch, entry.pbEnd);
        if (failed) {
            ch->error = true;
            tpuLog(TPU_LOG_ERROR, "channel",
                   "injected CE fault at tracker value %llu",
                   (unsigned long long)entry.trackerValue);
        }
        tpuCounterAdd("channel_copies_completed", 1);
        tpuCounterAdd("channel_bytes_copied", failed ? 0 : bytes);
        pthread_cond_broadcast(&ch->cond);
    }
    pthread_mutex_unlock(&ch->lock);
    return NULL;
}

TpurmChannel *tpurmChannelCreate(TpurmDevice *dev, TpurmCeType ce,
                                 uint32_t ring_entries)
{
    if (ring_entries == 0)
        ring_entries = (uint32_t)tpuRegistryGet("channel_num_gpfifo_entries",
                                                1024);
    /* Reference bounds: min 32, max 1M (uvm_channel.h:49-51). */
    if (ring_entries < 32)
        ring_entries = 32;
    if (ring_entries > (1u << 20))
        ring_entries = 1u << 20;

    TpurmChannel *ch = calloc(1, sizeof(*ch));
    if (!ch)
        return NULL;
    ch->ring = calloc(ring_entries, sizeof(PushEntry));
    if (!ch->ring) {
        free(ch);
        return NULL;
    }
    /* Pushbuffer sized by registry (reference: UVM_PUSHBUFFER_SIZE). */
    ch->pbSize = tpuRegistryGet("pushbuffer_size_bytes", 1ull << 20);
    if (ch->pbSize < 4096)
        ch->pbSize = 4096;
    ch->pbBase = malloc(ch->pbSize);
    if (!ch->pbBase) {
        free(ch->ring);
        free(ch);
        return NULL;
    }
    ch->dev = dev;
    ch->ce = ce;
    ch->entries = ring_entries;
    pthread_mutex_init(&ch->lock, NULL);
    pthread_cond_init(&ch->cond, NULL);
    if (pthread_create(&ch->worker, NULL, channel_worker, ch) != 0) {
        free(ch->pbBase);
        free(ch->ring);
        free(ch);
        return NULL;
    }
    return ch;
}

void tpurmChannelDestroy(TpurmChannel *ch)
{
    if (!ch)
        return;
    pthread_mutex_lock(&ch->lock);
    ch->stop = true;
    pthread_cond_broadcast(&ch->cond);
    pthread_mutex_unlock(&ch->lock);
    pthread_join(ch->worker, NULL);
    pthread_cond_destroy(&ch->cond);
    pthread_mutex_destroy(&ch->lock);
    while (ch->pbChunks) {
        PbChunk *c = ch->pbChunks;
        ch->pbChunks = c->next;
        free(c);
    }
    while (ch->pbChunkFree) {
        PbChunk *c = ch->pbChunkFree;
        ch->pbChunkFree = c->next;
        free(c);
    }
    free(ch->pbBase);
    free(ch->ring);
    free(ch);
}

/* ---------------------------------------------------------- push objects */

TpuStatus tpuPushBegin(TpurmChannel *ch, uint32_t maxSegs, TpuPush *p)
{
    if (!ch || !p || maxSegs == 0)
        return TPU_ERR_INVALID_ARGUMENT;
    uint64_t need = (uint64_t)maxSegs * sizeof(CopySeg);
    /* A reservation that wraps pads the unusable tail, so worst case it
     * consumes pad + need < need + need bytes.  Anything over pbSize/2
     * could deadlock the back-pressure wait on an idle channel (pad+need
     * can exceed the whole ring with nothing left to retire). */
    if (need * 2 > ch->pbSize)
        return TPU_ERR_INVALID_LIMIT;

    pthread_mutex_lock(&ch->lock);
    tpuLockTrackAcquire(TPU_LOCK_CHANNEL, "push-begin");
    for (;;) {
        if (ch->stop) {
            tpuLockTrackRelease(TPU_LOCK_CHANNEL, "push-begin");
            pthread_mutex_unlock(&ch->lock);
            return TPU_ERR_INVALID_STATE;
        }
        uint64_t pos = ch->pbCpuPut % ch->pbSize;
        uint64_t pad = pos + need > ch->pbSize ? ch->pbSize - pos : 0;
        /* Reservation back-pressure: wait for gpu_get to free space
         * (reference blocks reserving pushbuffer space the same way). */
        if (ch->pbCpuPut + pad + need - ch->pbGpuGet > ch->pbSize) {
            pthread_cond_wait(&ch->cond, &ch->lock);
            continue;
        }
        ch->pbCpuPut += pad;          /* skip unusable tail */
        p->segs = ch->pbBase + (ch->pbCpuPut % ch->pbSize);
        ch->pbCpuPut += need;
        p->pbEndOffset = ch->pbCpuPut;
        break;
    }
    /* Track the chunk (in allocation order) so gpu_get only advances
     * over completed prefixes.  Nodes come from the recycle list in
     * steady state; malloc only grows the pool (bounded by outstanding
     * pushes, itself bounded by the GPFIFO depth). */
    PbChunk *c = ch->pbChunkFree;
    if (c) {
        ch->pbChunkFree = c->next;
    } else {
        c = malloc(sizeof(*c));
        if (!c) {
            /* Roll back the reservation (lock held since we advanced). */
            ch->pbCpuPut = p->pbEndOffset - ((uint64_t)maxSegs *
                                             sizeof(CopySeg));
            tpuLockTrackRelease(TPU_LOCK_CHANNEL, "push-begin");
            pthread_mutex_unlock(&ch->lock);
            return TPU_ERR_NO_MEMORY;
        }
    }
    c->end = p->pbEndOffset;
    c->done = false;
    c->next = NULL;
    if (ch->pbChunksTail)
        ch->pbChunksTail->next = c;
    else
        ch->pbChunks = c;
    ch->pbChunksTail = c;
    tpuLockTrackRelease(TPU_LOCK_CHANNEL, "push-begin");
    pthread_mutex_unlock(&ch->lock);

    p->ch = ch;
    p->nsegs = 0;
    p->maxSegs = maxSegs;
    return TPU_OK;
}

TpuStatus tpuPushCopySeg(TpuPush *p, void *dst, const void *src,
                         uint64_t bytes)
{
    if (!p || !p->ch || p->nsegs >= p->maxSegs)
        return TPU_ERR_INVALID_ARGUMENT;
    if (bytes && (!dst || !src))
        return TPU_ERR_INVALID_ARGUMENT;
    CopySeg *s = &((CopySeg *)p->segs)[p->nsegs++];
    s->dst = dst;
    s->src = src;
    s->bytes = bytes;
    return TPU_OK;
}

uint64_t tpuPushEnd(TpuPush *p, TpuTracker *t)
{
    if (!p || !p->ch)
        return 0;
    TpurmChannel *ch = p->ch;

    pthread_mutex_lock(&ch->lock);
    tpuLockTrackAcquire(TPU_LOCK_CHANNEL, "push-end");
    while (!ch->stop && ch->put - ch->get >= ch->entries)
        pthread_cond_wait(&ch->cond, &ch->lock);
    if (ch->stop) {
        pb_release_locked(ch, p->pbEndOffset);
        tpuLockTrackRelease(TPU_LOCK_CHANNEL, "push-end");
        pthread_mutex_unlock(&ch->lock);
        p->ch = NULL;
        return 0;
    }

    PushEntry *entry = &ch->ring[ch->put % ch->entries];
    entry->segs = p->segs;
    entry->nsegs = p->nsegs;
    entry->pbEnd = p->pbEndOffset;
    entry->trackerValue = ++ch->submittedValue;
    entry->injectError = ch->injectNext;
    ch->injectNext = false;
    ch->put++;
    uint64_t value = entry->trackerValue;
    tpuCounterAdd("channel_pushes", 1);
    pthread_cond_broadcast(&ch->cond);
    tpuLockTrackRelease(TPU_LOCK_CHANNEL, "push-end");
    pthread_mutex_unlock(&ch->lock);

    p->ch = NULL;
    if (t && tpuTrackerAdd(t, ch, value) != TPU_OK)
        /* Dependency could not be recorded (tracker growth OOM): degrade
         * to synchronous completion so no dependency is silently lost. */
        tpurmChannelWait(ch, value);
    return value;
}

void tpuPushAbort(TpuPush *p)
{
    if (!p || !p->ch)
        return;
    TpurmChannel *ch = p->ch;
    pthread_mutex_lock(&ch->lock);
    pb_release_locked(ch, p->pbEndOffset);
    pthread_cond_broadcast(&ch->cond);   /* space freed: wake reservers */
    pthread_mutex_unlock(&ch->lock);
    p->ch = NULL;
}

uint64_t tpurmChannelPushCopy(TpurmChannel *ch, void *dst, const void *src,
                              uint64_t bytes)
{
    if (!ch || (!dst && bytes) || (!src && bytes))
        return 0;
    TpuPush p;
    if (tpuPushBegin(ch, 1, &p) != TPU_OK)
        return 0;
    if (tpuPushCopySeg(&p, dst, src, bytes) != TPU_OK) {
        tpuPushAbort(&p);
        return 0;
    }
    return tpuPushEnd(&p, NULL);
}

TpuStatus tpurmChannelWait(TpurmChannel *ch, uint64_t value)
{
    if (!ch)
        return TPU_ERR_INVALID_ARGUMENT;
    pthread_mutex_lock(&ch->lock);
    while (!ch->stop && ch->completedValue < value && !ch->error)
        pthread_cond_wait(&ch->cond, &ch->lock);
    TpuStatus st = TPU_OK;
    if (ch->error)
        st = TPU_ERR_INVALID_STATE;
    else if (ch->stop && ch->completedValue < value)
        st = TPU_ERR_INVALID_STATE;
    pthread_mutex_unlock(&ch->lock);
    return st;
}

uint64_t tpurmChannelCompletedValue(TpurmChannel *ch)
{
    if (!ch)
        return 0;
    pthread_mutex_lock(&ch->lock);
    uint64_t v = ch->completedValue;
    pthread_mutex_unlock(&ch->lock);
    return v;
}

void tpurmChannelInjectError(TpurmChannel *ch)
{
    if (!ch)
        return;
    pthread_mutex_lock(&ch->lock);
    ch->injectNext = true;
    pthread_mutex_unlock(&ch->lock);
}

void tpurmChannelResetError(TpurmChannel *ch)
{
    /* Robust-channel recovery surface (reference: per-channel RC resets
     * the channel and re-arms it, src/nvidia/src/kernel/gpu/rc/): clear
     * the latched error so new work can proceed. */
    if (!ch)
        return;
    pthread_mutex_lock(&ch->lock);
    if (ch->error) {
        ch->error = false;
        tpuCounterAdd("channel_rc_resets", 1);
        tpuLog(TPU_LOG_WARN, "channel", "RC reset: error cleared at value %llu",
               (unsigned long long)ch->completedValue);
    }
    pthread_cond_broadcast(&ch->cond);
    pthread_mutex_unlock(&ch->lock);
}

/* ------------------------------------------------------- transfer engine */

TpuStatus tpuMemCopy(TpurmDevice *dev, TpuMemDesc *dst, uint64_t dstOff,
                     TpuMemDesc *src, uint64_t srcOff, uint64_t size,
                     bool async, uint64_t *outTrackerValue)
{
    if (!dev || !dst || !src || size == 0)
        return TPU_ERR_INVALID_ARGUMENT;
    if (dstOff + size > dst->size || srcOff + size > src->size)
        return TPU_ERR_INVALID_LIMIT;
    if (dev->lost)
        return TPU_ERR_GPU_IS_LOST;

    TpurmChannel *ch = dev->ce;
    uint64_t clamp = tpuRegistryGet("ce_copy_clamp_bytes", TPU_CE_COPY_CLAMP);
    uint64_t remaining = size;
    uint64_t lastValue = 0;

    /* Contiguity-split loop (reference: ce_utils.c:646-661): each segment
     * covers the largest run contiguous in BOTH surfaces, clamped.
     * Segments batch into push objects (up to 64 per push) so one tracker
     * value completes a whole request chunk. */
    enum { SEGS_PER_PUSH = 64 };
    TpuPush push;
    TpuStatus st = tpuPushBegin(ch, SEGS_PER_PUSH, &push);
    if (st != TPU_OK)
        return st;
    while (remaining > 0) {
        void *dptr, *sptr;
        uint64_t drun, srun;
        st = tpuMemdescResolve(dst, dev, dstOff, &dptr, &drun);
        if (st != TPU_OK)
            goto fail;
        st = tpuMemdescResolve(src, dev, srcOff, &sptr, &srun);
        if (st != TPU_OK)
            goto fail;
        uint64_t len = remaining;
        if (len > drun)
            len = drun;
        if (len > srun)
            len = srun;
        if (len > clamp)
            len = clamp;
        if (push.nsegs == SEGS_PER_PUSH) {
            uint64_t v = tpuPushEnd(&push, NULL);
            if (v == 0) {
                st = TPU_ERR_INVALID_STATE;
                if (lastValue)
                    tpurmChannelWait(ch, lastValue);
                return st;
            }
            lastValue = v;
            st = tpuPushBegin(ch, SEGS_PER_PUSH, &push);
            if (st != TPU_OK) {
                /* Drain submitted work before unwinding (drain rule). */
                tpurmChannelWait(ch, lastValue);
                return st;
            }
        }
        st = tpuPushCopySeg(&push, dptr, sptr, len);
        if (st != TPU_OK)
            goto fail;
        dstOff += len;
        srcOff += len;
        remaining -= len;
    }
    if (push.nsegs > 0) {
        uint64_t v = tpuPushEnd(&push, NULL);
        if (v == 0) {
            if (lastValue)
                tpurmChannelWait(ch, lastValue);
            return TPU_ERR_INVALID_STATE;
        }
        lastValue = v;
    } else {
        tpuPushAbort(&push);
    }

    if (outTrackerValue)
        *outTrackerValue = lastValue;
    if (async)
        return TPU_OK;
    return lastValue ? tpurmChannelWait(ch, lastValue) : TPU_OK;

fail:
    tpuPushAbort(&push);
    /* Drain pushes already submitted: the caller may free/unpin the
     * surfaces on error while workers are still writing them (same rule
     * as block_copy_in's drain-before-unwind). */
    if (lastValue)
        tpurmChannelWait(ch, lastValue);
    return st;
}

/* ------------------------------------------------------- CE pool striper */

bool tpuCeStriperInit(TpuCeStriper *s, TpurmDevice *dev)
{
    if (!dev || dev->cePoolSize == 0)
        return false;
    s->dev = dev;
    s->next = 0;
    s->stripe = tpuRegistryGet("uvm_ce_stripe_bytes", 512 * 1024);
    if (s->stripe < 4096)
        s->stripe = 4096;
    return true;
}

TpuStatus tpuCeStriperPush(TpuCeStriper *s, void *dst, const void *src,
                           uint64_t len, TpuTracker *t)
{
    uint64_t off = 0;
    while (off < len) {
        uint64_t piece = len - off;
        if (piece > s->stripe)
            piece = s->stripe;
        TpurmChannel *ch = s->dev->cePool[s->next];
        s->next = (s->next + 1) % s->dev->cePoolSize;
        uint64_t v = tpurmChannelPushCopy(ch, (char *)dst + off,
                                          (const char *)src + off, piece);
        if (v == 0)
            return TPU_ERR_INVALID_STATE;
        if (t && tpuTrackerAdd(t, ch, v) != TPU_OK)
            /* Can't record the dep: complete it now instead of losing it. */
            tpurmChannelWait(ch, v);
        off += piece;
    }
    return TPU_OK;
}
