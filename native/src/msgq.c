/*
 * msgq — lockless shared-memory command queue (see include/tpurm/msgq.h).
 *
 * Layout mirrors the reference's msgq library (src/common/uproc/): one
 * region holding a tx header (writePtr), an rx header (readPtr +
 * completedSeq), and a power-of-two element ring.  Pointers are
 * monotonic u64 counters; ring index = ptr & (n-1).  Publication uses
 * release stores, observation acquire loads — the same protocol the
 * reference uses across the CPU/GSP shared-memory boundary
 * (message_queue_cpu.c:446,568), here across producer/consumer threads
 * (and, for the HBM mirror instance, across the C-engine/Python-runtime
 * boundary).
 */
#define _GNU_SOURCE
#include "tpurm/msgq.h"
#include "tpurm/inject.h"
#include "tpurm/trace.h"

#include <errno.h>
#include <time.h>
#include <limits.h>
#include <linux/futex.h>
#include <pthread.h>
#include <stdatomic.h>
#include <stdlib.h>
#include <string.h>
#include <sys/syscall.h>
#include <unistd.h>

/* Futex on the low 32 bits of a monotonic counter: wake whenever the
 * counter changes.  Wait keys re-check the predicate after every wake so
 * ABA on the truncated value only costs a spurious retry. */
static void futex_wake_all(_Atomic uint32_t *addr)
{
    syscall(SYS_futex, addr, FUTEX_WAKE_PRIVATE, INT_MAX, NULL, NULL, NULL);
}

static void futex_wait(_Atomic uint32_t *addr, uint32_t expected)
{
    syscall(SYS_futex, addr, FUTEX_WAIT_PRIVATE, expected, NULL, NULL, NULL);
}

struct TpuMsgq {
    uint32_t n;                      /* ring size, power of two          */
    uint32_t flags;
    TpuMsgqCmd *ring;

    /* tx header */
    _Atomic uint64_t writePtr;       /* next slot to write (monotonic)   */
    _Atomic uint32_t writeSeqLow;    /* futex doorbell for the consumer  */

    /* rx header */
    _Atomic uint64_t readPtr;        /* next slot to read (monotonic)    */
    _Atomic uint64_t completedSeq;   /* last retired command sequence    */
    _Atomic uint32_t completeLow;    /* futex for producers + waiters    */

    _Atomic uint64_t nextSeq;        /* sequence allocator (1-based)     */
    _Atomic int shutdown;

    pthread_mutex_t txLock;          /* only used with TPU_MSGQ_MPSC     */
};

TpuMsgq *tpuMsgqCreate(uint32_t nElems, uint32_t flags)
{
    uint32_t n = 16;
    while (n < nElems && n < (1u << 20))
        n <<= 1;

    TpuMsgq *q = calloc(1, sizeof(*q));
    if (!q)
        return NULL;
    q->ring = calloc(n, sizeof(TpuMsgqCmd));
    if (!q->ring) {
        free(q);
        return NULL;
    }
    q->n = n;
    q->flags = flags;
    pthread_mutex_init(&q->txLock, NULL);
    return q;
}

void tpuMsgqDestroy(TpuMsgq *q)
{
    if (!q)
        return;
    tpuMsgqShutdown(q);
    pthread_mutex_destroy(&q->txLock);
    free(q->ring);
    free(q);
}

void tpuMsgqShutdown(TpuMsgq *q)
{
    atomic_store_explicit(&q->shutdown, 1, memory_order_release);
    /* Bump the futex words BEFORE waking: a waiter that checked the
     * shutdown flag before this store but has not yet parked would
     * otherwise miss the wake entirely (its expected value still
     * matches) and sleep until the next submit — a lost-wakeup hang
     * the chaos soak exposed in the channel destroy path.  With the
     * bump, its FUTEX_WAIT fails value-changed and it re-checks
     * shutdown.  The words are pure doorbell counters; no reader
     * interprets their value. */
    atomic_fetch_add_explicit(&q->writeSeqLow, 1, memory_order_release);
    atomic_fetch_add_explicit(&q->completeLow, 1, memory_order_release);
    futex_wake_all(&q->writeSeqLow);
    futex_wake_all(&q->completeLow);
}

static int msgq_submit(TpuMsgq *q, TpuMsgqCmd *cmds, uint32_t n,
                       uint64_t *outLastSeq, bool block)
{
    if (!q || !cmds || n == 0 || n > q->n)
        return -EINVAL;
    uint64_t tSpan = tpurmTraceBegin();
    if (q->flags & TPU_MSGQ_MPSC) {
        if (block) {
            pthread_mutex_lock(&q->txLock);
        } else if (pthread_mutex_trylock(&q->txLock) != 0) {
            /* A blocking producer may hold txLock through its futex
             * back-pressure wait; a non-blocking caller must not queue
             * behind it (TrySubmit's contract is NEVER to stall). */
            return -EAGAIN;
        }
    }
    if (atomic_load_explicit(&q->shutdown, memory_order_acquire)) {
        if (q->flags & TPU_MSGQ_MPSC)
            pthread_mutex_unlock(&q->txLock);
        return -ESHUTDOWN;
    }

    /* Injected publish fault.  Non-blocking producers see -EAGAIN and
     * take their documented overflow recovery (HBM mirror: latch +
     * whole-arena resync; RC shadow: drop + counter).  Blocking
     * producers model retry-after-transient-failure: one bounded
     * backoff, then the publish proceeds — counted as a recovery
     * retry. */
    if (tpurmInjectShouldFail(TPU_INJECT_SITE_MSGQ_PUBLISH)) {
        if (!block) {
            if (q->flags & TPU_MSGQ_MPSC)
                pthread_mutex_unlock(&q->txLock);
            return -EAGAIN;
        }
        extern void tpuCounterAdd(const char *name, uint64_t delta);
        tpuCounterAdd("recover_retries", 1);
        tpuCounterAdd("recover_msgq_retries", 1);
        tpurmTraceInstant(TPU_TRACE_RECOVER_RETRY, (uintptr_t)q, 0);
        struct timespec ts = { .tv_sec = 0, .tv_nsec = 50000L };
        nanosleep(&ts, NULL);
    }

    /* Back-pressure: wait for ring space.  readPtr only grows, so the
     * check is monotonic-safe. */
    uint64_t w = atomic_load_explicit(&q->writePtr, memory_order_relaxed);
    for (;;) {
        uint64_t r = atomic_load_explicit(&q->readPtr, memory_order_acquire);
        if (w + n - r <= q->n)
            break;
        if (!block) {
            if (q->flags & TPU_MSGQ_MPSC)
                pthread_mutex_unlock(&q->txLock);
            return -EAGAIN;
        }
        uint32_t c = atomic_load_explicit(&q->completeLow,
                                          memory_order_acquire);
        /* Re-check after loading the futex word (avoid lost wakeup). */
        if (atomic_load_explicit(&q->readPtr, memory_order_acquire) != r)
            continue;
        if (atomic_load_explicit(&q->shutdown, memory_order_acquire)) {
            if (q->flags & TPU_MSGQ_MPSC)
                pthread_mutex_unlock(&q->txLock);
            return -ESHUTDOWN;
        }
        futex_wait(&q->completeLow, c);
    }

    uint64_t last = 0;
    for (uint32_t i = 0; i < n; i++) {
        cmds[i].seq = atomic_fetch_add_explicit(&q->nextSeq, 1,
                                                memory_order_relaxed) + 1;
        last = cmds[i].seq;
        q->ring[(w + i) & (q->n - 1)] = cmds[i];
    }
    /* Publish: release so the consumer's acquire load of writePtr sees
     * the ring contents (msgqTxSubmitBuffers analog). */
    atomic_store_explicit(&q->writePtr, w + n, memory_order_release);
    atomic_fetch_add_explicit(&q->writeSeqLow, 1, memory_order_release);
    futex_wake_all(&q->writeSeqLow);

    if (q->flags & TPU_MSGQ_MPSC)
        pthread_mutex_unlock(&q->txLock);
    if (tSpan)
        tpurmTraceEnd(TPU_TRACE_MSGQ_PUBLISH, tSpan, (uintptr_t)q, n);
    if (outLastSeq)
        *outLastSeq = last;
    return 0;
}

int tpuMsgqSubmit(TpuMsgq *q, TpuMsgqCmd *cmds, uint32_t n,
                  uint64_t *outLastSeq)
{
    return msgq_submit(q, cmds, n, outLastSeq, true);
}

int tpuMsgqTrySubmit(TpuMsgq *q, TpuMsgqCmd *cmds, uint32_t n,
                     uint64_t *outLastSeq)
{
    return msgq_submit(q, cmds, n, outLastSeq, false);
}

void tpuMsgqReopen(TpuMsgq *q)
{
    if (!q)
        return;
    /* Discard unconsumed commands: they count as retired so stale fence
     * waits from the previous epoch complete rather than hang. */
    uint64_t w = atomic_load_explicit(&q->writePtr, memory_order_acquire);
    atomic_store_explicit(&q->readPtr, w, memory_order_release);
    uint64_t s = atomic_load_explicit(&q->nextSeq, memory_order_acquire);
    atomic_store_explicit(&q->completedSeq, s, memory_order_release);
    atomic_store_explicit(&q->shutdown, 0, memory_order_release);
    atomic_fetch_add_explicit(&q->completeLow, 1, memory_order_release);
    futex_wake_all(&q->completeLow);
}

uint32_t tpuMsgqReceive(TpuMsgq *q, TpuMsgqCmd *out, uint32_t max)
{
    if (!q || !out || max == 0)
        return 0;
    for (;;) {
        uint64_t r = atomic_load_explicit(&q->readPtr, memory_order_relaxed);
        uint64_t w = atomic_load_explicit(&q->writePtr, memory_order_acquire);
        if (w > r) {
            uint32_t avail = (uint32_t)(w - r);
            if (avail > max)
                avail = max;
            for (uint32_t i = 0; i < avail; i++)
                out[i] = q->ring[(r + i) & (q->n - 1)];
            /* readPtr is advanced by tpuMsgqComplete (after execution),
             * not here: ring slots stay owned until retired, exactly as
             * the reference frees tx space only when rx acknowledges. */
            return avail;
        }
        if (atomic_load_explicit(&q->shutdown, memory_order_acquire))
            return 0;
        uint32_t dv = atomic_load_explicit(&q->writeSeqLow,
                                           memory_order_acquire);
        if (atomic_load_explicit(&q->writePtr, memory_order_acquire) != w)
            continue;
        futex_wait(&q->writeSeqLow, dv);
    }
}

void tpuMsgqComplete(TpuMsgq *q, uint64_t seq)
{
    if (!q)
        return;
    /* Retire every ring slot whose command sequence is <= seq.  The
     * consumer processes in order, so this is a prefix. */
    uint64_t r = atomic_load_explicit(&q->readPtr, memory_order_relaxed);
    uint64_t w = atomic_load_explicit(&q->writePtr, memory_order_acquire);
    while (r < w && q->ring[r & (q->n - 1)].seq <= seq)
        r++;
    atomic_store_explicit(&q->readPtr, r, memory_order_release);

    uint64_t prev = atomic_load_explicit(&q->completedSeq,
                                         memory_order_relaxed);
    if (seq > prev)
        atomic_store_explicit(&q->completedSeq, seq, memory_order_release);
    atomic_fetch_add_explicit(&q->completeLow, 1, memory_order_release);
    futex_wake_all(&q->completeLow);
}

uint64_t tpuMsgqCompletedSeq(TpuMsgq *q)
{
    return q ? atomic_load_explicit(&q->completedSeq, memory_order_acquire)
             : 0;
}

bool tpuMsgqWaitSeq(TpuMsgq *q, uint64_t seq)
{
    if (!q)
        return false;
    for (;;) {
        if (atomic_load_explicit(&q->completedSeq, memory_order_acquire) >=
            seq)
            return true;
        if (atomic_load_explicit(&q->shutdown, memory_order_acquire))
            return false;
        uint32_t c = atomic_load_explicit(&q->completeLow,
                                          memory_order_acquire);
        if (atomic_load_explicit(&q->completedSeq, memory_order_acquire) >=
            seq)
            return true;
        futex_wait(&q->completeLow, c);
    }
}

uint64_t tpuMsgqSubmittedSeq(TpuMsgq *q)
{
    return q ? atomic_load_explicit(&q->nextSeq, memory_order_acquire) : 0;
}

uint32_t tpuMsgqDepth(TpuMsgq *q)
{
    if (!q)
        return 0;
    uint64_t r = atomic_load_explicit(&q->readPtr, memory_order_acquire);
    uint64_t w = atomic_load_explicit(&q->writePtr, memory_order_acquire);
    return (uint32_t)(w - r);
}
