/*
 * tpushield — page integrity engine (see include/tpurm/shield.h for
 * the model; uvm_internal.h for the per-page metadata contract).
 *
 * Layering: uvm_va_block.c / uvm_fault.c own WHERE seal/verify happen
 * (the demote commit, the promote copy, the first CPU touch); this
 * file owns the metadata, the CRC, the re-fetch ladder, the poison /
 * retirement machinery, the background scrubber, and the mem.corrupt
 * bookkeeping that keeps the reconciliation invariant exact:
 *
 *     mem.corrupt hits == shield_detected + shield_inject_misses
 *
 * Every flip is tagged where it lands (per-page `pending` count, or
 * the process-global wire-pending count for ICI/vac buffers); every
 * verify that catches one converts it to shield_detected; a flip that
 * escapes every verify hook surfaces as shield_inject_misses — the
 * coverage-hole detector both chaos soaks assert to zero.
 */
#define _GNU_SOURCE
#include "tpurm/shield.h"

#include <pthread.h>
#include <stdatomic.h>
#include <stdlib.h>
#include <string.h>
#include <sys/mman.h>
#include <time.h>
#if defined(__aarch64__)
#include <asm/hwcap.h>
#include <sys/auxv.h>
#endif

#include "internal.h"
#include "tpurm/health.h"
#include "tpurm/inject.h"
#include "tpurm/journal.h"
#include "tpurm/trace.h"
#include "uvm/uvm_internal.h"

/* ------------------------------------------------------------- CRC32C */

static uint32_t g_crcTable[8][256];
static pthread_once_t g_crcOnce = PTHREAD_ONCE_INIT;
static bool g_crcHw;

bool tpurmShieldCrcSelftest(void);   /* runs inside crc_init_once */

static void crc_init_once(void)
{
    for (uint32_t i = 0; i < 256; i++) {
        uint32_t c = i;
        for (int k = 0; k < 8; k++)
            c = (c & 1) ? 0x82F63B78u ^ (c >> 1) : c >> 1;   /* CRC32C */
        g_crcTable[0][i] = c;
    }
    for (uint32_t i = 0; i < 256; i++)
        for (int t = 1; t < 8; t++)
            g_crcTable[t][i] =
                (g_crcTable[t - 1][i] >> 8) ^
                g_crcTable[0][g_crcTable[t - 1][i] & 0xFF];
#if defined(__x86_64__) || defined(__i386__)
    g_crcHw = __builtin_cpu_supports("sse4.2");
#elif defined(__aarch64__)
    /* ARMv8 CRC32 extension is optional below v8.1: gate on the kernel
     * hwcap, not just the compile-time feature macro. */
    g_crcHw = (getauxval(AT_HWCAP) & HWCAP_CRC32) != 0;
#endif
    tpurmShieldCrcSelftest();
}

#if defined(__x86_64__)
__attribute__((target("sse4.2")))
static uint32_t crc32c_hw(uint32_t state, const uint8_t *p, uint64_t len)
{
    uint64_t c = state;
    while (len >= 8) {
        uint64_t v;
        memcpy(&v, p, 8);
        c = __builtin_ia32_crc32di(c, v);
        p += 8;
        len -= 8;
    }
    uint32_t c32 = (uint32_t)c;
    while (len--)
        c32 = __builtin_ia32_crc32qi(c32, *p++);
    return c32;
}
#elif defined(__aarch64__)
/* push_options so arm_acle's CRC intrinsics resolve without requiring
 * -march=armv8-a+crc globally; the getauxval probe above keeps the
 * call runtime-safe on cores without the extension. */
#pragma GCC push_options
#pragma GCC target("+crc")
#include <arm_acle.h>
static uint32_t crc32c_hw(uint32_t state, const uint8_t *p, uint64_t len)
{
    uint32_t c = state;
    while (len >= 8) {
        uint64_t v;
        memcpy(&v, p, 8);
        c = __crc32cd(c, v);
        p += 8;
        len -= 8;
    }
    while (len--)
        c = __crc32cb(c, *p++);
    return c;
}
#pragma GCC pop_options
#endif

static uint32_t crc32c_sw(uint32_t state, const uint8_t *p, uint64_t len)
{
    uint32_t c = state;
    while (len >= 8) {
        uint32_t lo, hi;
        memcpy(&lo, p, 4);
        memcpy(&hi, p + 4, 4);
        lo ^= c;
        c = g_crcTable[7][lo & 0xFF] ^ g_crcTable[6][(lo >> 8) & 0xFF] ^
            g_crcTable[5][(lo >> 16) & 0xFF] ^ g_crcTable[4][lo >> 24] ^
            g_crcTable[3][hi & 0xFF] ^ g_crcTable[2][(hi >> 8) & 0xFF] ^
            g_crcTable[1][(hi >> 16) & 0xFF] ^ g_crcTable[0][hi >> 24];
        p += 8;
        len -= 8;
    }
    while (len--)
        c = g_crcTable[0][(c ^ *p++) & 0xFF] ^ (c >> 8);
    return c;
}

/* At-load CRC32C self-test: both dispatch paths must produce the
 * canonical CRC32C("123456789") = 0xE3069283 before the first seal is
 * trusted.  The SW table is checked first (a miscomputed table would
 * corrupt every seal AND mask a bad HW path); then the HW instruction
 * path, which until now had only ever been exercised on the silicon it
 * was compiled for — a hwcap that lies, a qemu/TCG gap, or a
 * miscompiled +crc pragma all surface here as a journaled fallback to
 * the table instead of a fleet of false CRC faults.  Returns true when
 * the dispatched path is trustworthy.  Idempotent; runs in the library
 * constructor (counters and the journal are ctor-safe: lazy init). */
bool tpurmShieldCrcSelftest(void)
{
    static const uint8_t vec[] = "123456789";
    const uint32_t want = 0xE3069283u;

    tpuCounterAdd("shield_crc_selftests", 1);
    uint32_t sw = ~crc32c_sw(~0u, vec, 9);
    if (sw != want) {
        /* Table construction is broken: nothing to fall back to.  Keep
         * whatever dispatch we have but make the failure loud. */
        tpurmJournalEmit(TPU_JREC_CRC_SELFTEST, 0, TPU_ERR_INVALID_STATE,
                         sw, want);
        tpuCounterAdd("shield_crc_selftest_fallbacks", 1);
        return false;
    }
#if defined(__x86_64__) || defined(__aarch64__)
    if (g_crcHw) {
        uint32_t hw = ~crc32c_hw(~0u, vec, 9);
        if (hw != want) {
            g_crcHw = false;    /* dispatch the table from now on */
            tpurmJournalEmit(TPU_JREC_CRC_SELFTEST, 0,
                             TPU_ERR_INVALID_STATE, hw, want);
            tpuCounterAdd("shield_crc_selftest_fallbacks", 1);
        }
    }
#endif
    return true;
}

/* One-time init, HOISTED off the per-seal hot path: the old per-call
 * pthread_once ran an acquire-fenced once-check on every CRC the copy
 * executor sealed.  A library constructor covers every normal load
 * order; tpuRcInit repeats the call (idempotent) as the belt for
 * exotic static-init orders. */
void tpurmShieldCrcInit(void)
{
    pthread_once(&g_crcOnce, crc_init_once);
}

__attribute__((constructor))
static void shield_crc_ctor(void)
{
    tpurmShieldCrcInit();
}

uint32_t tpurmShieldCrc32cExtend(uint32_t crc, const void *data,
                                 uint64_t len)
{
    uint32_t state = ~crc;
#if defined(__x86_64__) || defined(__aarch64__)
    if (g_crcHw)
        return ~crc32c_hw(state, data, len);
#endif
    return ~crc32c_sw(state, data, len);
}

uint32_t tpurmShieldCrc32c(const void *data, uint64_t len)
{
    return tpurmShieldCrc32cExtend(0, data, len);
}

/* -------------------------------------------------------------- knobs */

bool tpurmShieldEnabled(void)
{
    static TpuRegCache c_en;
    return tpuRegCacheGet(&c_en, "shield_enable", 1) != 0;
}

bool uvmShieldActive(void)
{
    return tpurmShieldEnabled();
}

/* ------------------------------------------------------- reconciliation
 *
 * Wire flips (ICI hop buffers, vac records) are always paired with an
 * immediate verify in the same code path; the pending count bridges
 * the two so concurrent wires reconcile globally. */

static _Atomic uint64_t g_wirePending;

/* --------------------------------------------------------- retire list */

#define SHIELD_RETIRE_MAX 4096
#define SHIELD_MAX_DEVS 16

static struct {
    pthread_mutex_t lock;
    struct {
        uint8_t tier;
        uint8_t dev;
        uint64_t off, bytes;
    } s[SHIELD_RETIRE_MAX];
    _Atomic uint32_t n;             /* entries published (release)     */
    _Atomic uint32_t dropped;       /* retirements past the table cap  */
    _Atomic uint64_t perDev[SHIELD_MAX_DEVS];
    _Atomic uint64_t total;
} g_retire = { .lock = PTHREAD_MUTEX_INITIALIZER };

static void retire_add(uint32_t tier, uint32_t dev, uint64_t off,
                       uint64_t bytes)
{
    pthread_mutex_lock(&g_retire.lock);
    uint32_t n = atomic_load_explicit(&g_retire.n, memory_order_relaxed);
    if (n < SHIELD_RETIRE_MAX) {
        g_retire.s[n].tier = (uint8_t)tier;
        g_retire.s[n].dev = (uint8_t)dev;
        g_retire.s[n].off = off;
        g_retire.s[n].bytes = bytes;
        /* Entries are immutable once published: lock-free readers scan
         * up to the release-stored count. */
        atomic_store_explicit(&g_retire.n, n + 1, memory_order_release);
    } else {
        /* Table saturated: the span cannot be recorded, so the free
         * gate below FAILS CLOSED (uvmShieldRunRetired returns true
         * for everything — no chunk returns to the freelist once the
         * table can no longer prove a span clean).  Counted + logged:
         * 4096 retired spans means the device is dying, not the
         * quarantine. */
        atomic_fetch_add(&g_retire.dropped, 1);
        tpuCounterAdd("shield_retire_overflow", 1);
        TPU_LOG(TPU_LOG_ERROR, "shield",
               "retire table FULL (%u spans): tier %u dev %u off 0x%llx "
               "unrecorded — chunk frees now fail closed",
               SHIELD_RETIRE_MAX, tier, dev, (unsigned long long)off);
    }
    atomic_fetch_add(&g_retire.total, 1);
    if (dev < SHIELD_MAX_DEVS)
        atomic_fetch_add(&g_retire.perDev[dev], 1);
    pthread_mutex_unlock(&g_retire.lock);
}

bool tpurmShieldSpanRetired(uint32_t tier, uint32_t devInst,
                            uint64_t offset, uint64_t bytes)
{
    uint32_t n = atomic_load_explicit(&g_retire.n, memory_order_acquire);
    for (uint32_t i = 0; i < n; i++) {
        if (g_retire.s[i].tier != tier)
            continue;
        if (tier == UVM_TIER_HBM && g_retire.s[i].dev != devInst)
            continue;
        if (offset < g_retire.s[i].off + g_retire.s[i].bytes &&
            g_retire.s[i].off < offset + bytes)
            return true;
    }
    return false;
}

uint64_t tpurmShieldRetiredPages(uint32_t devInst)
{
    if (devInst >= SHIELD_MAX_DEVS)
        return 0;
    return atomic_load(&g_retire.perDev[devInst]);
}

uint64_t tpurmShieldRetiredTotal(void)
{
    return atomic_load(&g_retire.total);
}

/* Chunk-free gate (block_gc_runs / uvmBlockFreeBacking): a run whose
 * span overlaps a retired page must NOT return to the PMM freelist —
 * the deliberate leak IS the retirement (reference: PMM blacklist,
 * dynamic page retirement). */
bool uvmShieldRunRetired(UvmTierArena *arena, uint64_t chunkOff,
                         uint64_t bytes)
{
    /* Saturated table = fail closed: unrecorded retired spans exist,
     * so no chunk can be proven clean — nothing returns to the
     * freelist (the deliberate leak IS the retirement). */
    if (atomic_load_explicit(&g_retire.dropped, memory_order_acquire))
        return true;
    if (atomic_load_explicit(&g_retire.n, memory_order_acquire) == 0)
        return false;
    return tpurmShieldSpanRetired(arena->tier, arena->devInst, chunkOff,
                                  bytes);
}

/* Allocation-side invariant detector: a fresh chunk overlapping a
 * retired span means retirement leaked back into circulation.  Counted
 * (must stay 0), never fails the alloc — the counter is the alarm. */
void uvmShieldCheckAlloc(UvmTierArena *arena, uint64_t off, uint64_t bytes)
{
    if (atomic_load_explicit(&g_retire.n, memory_order_acquire) == 0)
        return;
    if (tpurmShieldSpanRetired(arena->tier, arena->devInst, off, bytes)) {
        tpuCounterAdd("shield_retired_realloc", 1);
        TPU_LOG(TPU_LOG_ERROR, "shield",
               "retired span re-allocated: tier %u dev %u off 0x%llx",
               arena->tier, arena->devInst, (unsigned long long)off);
    }
}

/* ------------------------------------------------------ page metadata */

/* meta.state: 0 unsealed, 1 + tier sealed, 0xFF poisoned. */
#define SHIELD_POISONED 0xFF

static inline bool meta_sealed(const UvmShieldPage *m)
{
    return m->state != 0 && m->state != SHIELD_POISONED;
}

static inline UvmTier meta_tier(const UvmShieldPage *m)
{
    return (UvmTier)(m->state - 1);
}

static void shield_scrub_start(void);

static UvmShieldPage *shield_meta(UvmVaBlock *blk)
{
    if (!blk->shield)
        blk->shield = calloc(blk->npages, sizeof(UvmShieldPage));
    return blk->shield;
}

void uvmShieldBlockFree(UvmVaBlock *blk)
{
    free(blk->shield);
    blk->shield = NULL;
}

bool uvmShieldPagePoisoned(UvmVaBlock *blk, uint32_t page)
{
    return blk->shield && blk->shield[page].state == SHIELD_POISONED;
}

int uvmShieldPageSealedTier(UvmVaBlock *blk, uint32_t page)
{
    if (!blk->shield || !meta_sealed(&blk->shield[page]))
        return -1;
    return (int)meta_tier(&blk->shield[page]);
}

bool uvmShieldRangePoisoned(UvmVaBlock *blk, uint32_t first, uint32_t count)
{
    if (!blk->shield)
        return false;
    for (uint32_t p = first; p < first + count && p < blk->npages; p++)
        if (blk->shield[p].state == SHIELD_POISONED)
            return true;
    return false;
}

bool uvmShieldRangeSealed(UvmVaBlock *blk, uint32_t first, uint32_t count)
{
    if (!blk->shield)
        return false;
    for (uint32_t p = first; p < first + count && p < blk->npages; p++)
        if (meta_sealed(&blk->shield[p]))
            return true;
    return false;
}

/* Seal one page's `tier` copy with the CRC the copy path computed
 * (executor-side stripe transform).  blk->lock held.  Evaluates the
 * mem.corrupt site once per sealed page (scope = the page's VA) — a
 * hit flips one bit in the freshly sealed copy, which is exactly what
 * a cold-storage bit flip looks like to every consumer. */
void uvmShieldSealPage(UvmVaBlock *blk, uint32_t page, UvmTier tier,
                       uint32_t crc)
{
    if (!uvmShieldActive())
        return;
    UvmShieldPage *m = shield_meta(blk);
    if (!m)
        return;
    m += page;
    if (m->state == SHIELD_POISONED)
        return;                     /* poison is sticky */
    if (m->pending) {
        /* A pending flip survived to a reseal: some overwrite path
         * skipped its unseal-verify hook — a coverage hole, surfaced
         * rather than silently re-zeroed. */
        tpuCounterAdd("shield_inject_misses", m->pending);
        m->pending = 0;
    }
    m->crc = crc;
    m->gen++;
    m->state = (uint8_t)(1 + tier);
    tpuCounterAdd("tpurm_shield_seals", 1);

    uint64_t ps = uvmPageSize();
    uint64_t va = blk->start + (uint64_t)page * ps;
    if (tpurmInjectShouldFailScoped(TPU_INJECT_SITE_MEM_CORRUPT, va)) {
        uint8_t *ptr = uvmBlockPagePtr(blk, tier, page);
        if (ptr) {
            /* One deterministic bit, mid-page: CRC32C detects every
             * single-bit error, so the verify side is exact. */
            ptr[ps / 2] ^= 0x20;
            if (m->pending < 0xFF)
                m->pending++;
            tpuCounterAdd("shield_inject_corrupts", 1);
        }
    }
    shield_scrub_start();
}

/* Drop the seal of every matching page in [first, first+count)
 * (tier < 0 matches any sealed tier).  Called wherever a sealed copy
 * is about to be overwritten or its residency dropped — the LAST
 * verify hook a pending injected flip can be caught by, which is what
 * keeps hits == detected + misses exact.  blk->lock held. */
void uvmShieldUnsealRange(UvmVaBlock *blk, uint32_t first, uint32_t count,
                          int tier)
{
    if (!blk->shield)
        return;
    uint64_t ps = uvmPageSize();
    for (uint32_t p = first; p < first + count && p < blk->npages; p++) {
        UvmShieldPage *m = &blk->shield[p];
        if (!meta_sealed(m))
            continue;
        if (tier >= 0 && meta_tier(m) != (UvmTier)tier)
            continue;
        if (m->pending) {
            uint8_t *ptr = uvmBlockPagePtr(blk, meta_tier(m), p);
            if (ptr && tpurmShieldCrc32c(ptr, ps) != m->crc) {
                tpuCounterAdd("tpurm_shield_mismatches", 1);
                tpurmJournalEmit(TPU_JREC_SHIELD_VERDICT,
                                 blk->hbmDevInst, TPU_OK,
                                 blk->start + (uint64_t)p * ps, 1);
                tpuCounterAdd("shield_detected", m->pending);
            } else {
                tpuCounterAdd("shield_inject_misses", m->pending);
            }
            m->pending = 0;
        }
        m->state = 0;
    }
}

/* --------------------------------------------------------- poisoning */

static void shield_poison_page(UvmVaBlock *blk, uint32_t page,
                               UvmTier tier)
{
    UvmShieldPage *m = &blk->shield[page];
    uint64_t ps = uvmPageSize();
    uint64_t va = blk->start + (uint64_t)page * ps;

    m->state = SHIELD_POISONED;
    m->pending = 0;
    tpuCounterAdd("tpurm_shield_pages_poisoned", 1);
    tpurmJournalEmit(TPU_JREC_PAGE_POISON, blk->hbmDevInst,
                     TPU_ERR_PAGE_POISONED, va, tier);

    /* Retire the backing page: arena-backed pages enter the quarantine
     * list (their PMM chunk is never freed, so the physical span can
     * never be handed to another tenant); host pages retire onto the
     * poison mapping below.  Either way the gauge moves. */
    if (tier != UVM_TIER_HOST) {
        uint64_t off;
        if (uvmBlockTierOffset(blk, tier, page, &off))
            retire_add(tier, tier == UVM_TIER_HBM ? blk->hbmDevInst : 0,
                       off, ps);
        else
            retire_add(tier, blk->hbmDevInst, 0, 0);
    } else {
        atomic_fetch_add(&g_retire.total, 1);
        if (blk->hbmDevInst < SHIELD_MAX_DEVS)
            atomic_fetch_add(&g_retire.perDev[blk->hbmDevInst], 1);
    }
    /* Aggregate + per-device [dN] line (renders as a dev label). */
    tpuCounterAddScoped("tpurm_shield_pages_retired", blk->hbmDevInst, 1);

    /* Containment: the page leaves the residency state machine (no
     * tier holds a trusted copy), its device PTEs are revoked, and the
     * user VA detaches onto an anonymous poison mapping exactly like
     * the fatal-fault cancel path — the process survives; only the
     * owning sequence sees TPU_ERR_PAGE_POISONED.  Never a device
     * reset. */
    for (int t = 0; t < UVM_TIER_COUNT; t++)
        uvmPageMaskClear(&blk->resident[t], page);
    uvmPageMaskClear(&blk->cpuMapped, page);
    uvmPageMaskClear(&blk->devMapped, page);
    uvmBlockPteRevoke(blk, page, 1);
    uvmPageMaskSet(&blk->cancelled, page);
    blk->hasCancelled = true;
    void *pm = mmap((void *)(uintptr_t)va, ps, PROT_READ | PROT_WRITE,
                    MAP_FIXED | MAP_PRIVATE | MAP_ANONYMOUS, -1, 0);
    (void)pm;

    tpurmHealthNote(blk->hbmDevInst, TPU_HEALTH_EV_PAGE_QUARANTINE);
    tpurmTraceInstantLabel(TPU_TRACE_SHIELD_VERIFY, va, ps,
                           "shield.poison");
    TPU_LOG(TPU_LOG_ERROR, "shield",
           "page 0x%llx POISONED (tier %u seal mismatch, no recovery "
           "source) — backing retired, owning sequence gets %s",
           (unsigned long long)va, tier,
           tpuStatusToString(TPU_ERR_PAGE_POISONED));
    /* Containment is the tpubox black-box moment: snapshot the journal
     * and engine state while the poisoned page's story is still in the
     * ring.  blk->lock is held — the dumper only calls the lock-free
     * raw hooks, so this cannot deadlock. */
    tpurmJournalCrashDump("shield.poison");
}

/* Verify one sealed page, running the re-fetch ladder on mismatch.
 * blk->lock held.  Returns 0 clean, 1 mismatch-recovered (refetch
 * save), 2 poisoned. */
static int shield_verify_page(UvmVaBlock *blk, uint32_t page)
{
    UvmShieldPage *m = &blk->shield[page];
    if (!meta_sealed(m))
        return m->state == SHIELD_POISONED ? 2 : 0;
    UvmTier tier = meta_tier(m);
    uint64_t ps = uvmPageSize();

    if (!uvmPageMaskTest(&blk->resident[tier], page)) {
        /* Orphaned seal: residency dropped without the unseal hook —
         * defensive (the hooks should cover every clear path). */
        if (m->pending)
            tpuCounterAdd("shield_inject_misses", m->pending);
        m->pending = 0;
        m->state = 0;
        return 0;
    }
    uint8_t *ptr = uvmBlockPagePtr(blk, tier, page);
    if (!ptr) {
        if (m->pending)
            tpuCounterAdd("shield_inject_misses", m->pending);
        m->pending = 0;
        m->state = 0;
        return 0;
    }
    tpuCounterAdd("tpurm_shield_verifies", 1);
    if (tpurmShieldCrc32c(ptr, ps) == m->crc) {
        if (m->pending) {
            /* Flip recorded but CRC matches — cannot happen for a real
             * single-bit flip; surface rather than hide. */
            tpuCounterAdd("shield_inject_misses", m->pending);
            m->pending = 0;
        }
        return 0;
    }

    /* Mismatch: the cold copy does not match its seal. */
    tpuCounterAdd("tpurm_shield_mismatches", 1);
    tpurmJournalEmit(TPU_JREC_SHIELD_VERDICT, blk->hbmDevInst, TPU_OK,
                     blk->start + (uint64_t)page * ps, 2);
    if (m->pending) {
        tpuCounterAdd("shield_detected", m->pending);
        m->pending = 0;
    }
    uint64_t va = blk->start + (uint64_t)page * ps;
    tpurmTraceInstantLabel(TPU_TRACE_SHIELD_VERIFY, va, ps,
                           "shield.mismatch");

    /* Ladder rung 1 — retry from the sealing source: recompute once
     * (a transiently torn read, not rotted storage, passes here). */
    if (tpurmShieldCrc32c(ptr, ps) == m->crc) {
        tpuCounterAdd("tpurm_shield_refetch_saves", 1);
        return 1;
    }

    /* Ladder rung 2 — re-fetch from a read-duplicated sibling copy. */
    for (int t = 0; t < UVM_TIER_COUNT; t++) {
        if (t == (int)tier ||
            !uvmPageMaskTest(&blk->resident[t], page))
            continue;
        uint8_t *src = uvmBlockPagePtr(blk, (UvmTier)t, page);
        if (!src)
            continue;
        if (t == UVM_TIER_HBM &&
            tpuHbmCoherentForRead(src, ps) != TPU_OK)
            continue;
        memcpy(ptr, src, ps);
        if (tier == UVM_TIER_HBM)
            tpuHbmMirrorNotify(ptr, ps);
        m->crc = tpurmShieldCrc32c(ptr, ps);
        m->gen++;
        tpuCounterAdd("tpurm_shield_seals", 1);        /* reseal */
        tpuCounterAdd("tpurm_shield_refetch_saves", 1);
        TPU_LOG(TPU_LOG_WARN, "shield",
               "page 0x%llx: tier %u seal mismatch re-fetched from "
               "tier %d sibling", (unsigned long long)va, tier, t);
        return 1;
    }

    /* Ladder rung 3 — no recovery source: poison + retire. */
    shield_poison_page(blk, page, tier);
    return 2;
}

/* Resolve an OVERLAPPED verify-on-promote: `crc` is the CRC32C of the
 * bytes the copy actually delivered, computed by the tpuce executor
 * threads riding the copy — the promote-side twin of the seal's
 * stripe-transform stage, so the sealed fast path pays no separate
 * serialized source read.  A match proves the whole chain seal ->
 * source -> copied bytes end-to-end (it even covers corruption in
 * flight, which a pre-copy source verify cannot see).  On mismatch,
 * fall back to the authoritative source-side verify:
 * shield_verify_page re-reads the sealing source and runs the full
 * re-fetch ladder (transient re-read, sibling re-fetch, poison).
 * *recopy is set when the source is now proven or recovered and the
 * caller must copy the page again before anything commits.
 * blk->lock held. */
TpuStatus uvmShieldVerifyCopied(UvmVaBlock *blk, uint32_t page,
                                uint32_t crc, bool *recopy)
{
    *recopy = false;
    if (!blk->shield)
        return TPU_OK;
    UvmShieldPage *m = &blk->shield[page];
    if (m->state == SHIELD_POISONED)
        return TPU_ERR_PAGE_POISONED;
    if (!meta_sealed(m))
        return TPU_OK;
    tpuCounterAdd("tpurm_shield_verifies", 1);
    if (crc == m->crc) {
        if (m->pending) {
            /* A recorded flip whose copied bytes still match the seal
             * cannot happen for a real single-bit flip; surface the
             * coverage hole rather than hide it. */
            tpuCounterAdd("shield_inject_misses", m->pending);
            m->pending = 0;
        }
        return TPU_OK;
    }
    int rc = shield_verify_page(blk, page);
    if (rc == 2)
        return TPU_ERR_PAGE_POISONED;
    *recopy = true;
    return TPU_OK;
}

TpuStatus uvmShieldVerifyRange(UvmVaBlock *blk, uint32_t first,
                               uint32_t count)
{
    if (!blk->shield)
        return TPU_OK;
    uint64_t tSpan = tpurmTraceBegin();
    TpuStatus st = TPU_OK;
    uint64_t bytes = 0;
    for (uint32_t p = first; p < first + count && p < blk->npages; p++) {
        if (blk->shield[p].state == SHIELD_POISONED) {
            st = TPU_ERR_PAGE_POISONED;
            continue;
        }
        if (!meta_sealed(&blk->shield[p]))
            continue;
        bytes += uvmPageSize();
        if (shield_verify_page(blk, p) == 2)
            st = TPU_ERR_PAGE_POISONED;
    }
    if (tSpan && bytes)
        tpurmTraceEnd(TPU_TRACE_SHIELD_VERIFY, tSpan,
                      blk->start + (uint64_t)first * uvmPageSize(), bytes);
    return st;
}

/* --------------------------------------------------------------- wire */

bool tpurmShieldInjectWire(void *buf, uint64_t len, uint64_t scope)
{
    if (!tpurmShieldEnabled() || !buf || !len)
        return false;
    if (!tpurmInjectShouldFailScoped(TPU_INJECT_SITE_MEM_CORRUPT, scope))
        return false;
    ((uint8_t *)buf)[len / 2] ^= 0x20;
    atomic_fetch_add(&g_wirePending, 1);
    tpuCounterAdd("shield_inject_corrupts", 1);
    return true;
}

TpuStatus tpurmShieldVerifyWire(const void *buf, uint64_t len,
                                uint32_t expectCrc, uint64_t scope)
{
    if (!buf || !len)
        return TPU_ERR_INVALID_ARGUMENT;
    tpuCounterAdd("tpurm_shield_verifies", 1);
    tpuCounterAdd("shield_wire_verifies", 1);
    if (tpurmShieldCrc32c(buf, len) == expectCrc)
        return TPU_OK;
    tpuCounterAdd("tpurm_shield_mismatches", 1);
    tpurmJournalEmit(TPU_JREC_SHIELD_VERDICT, 0, TPU_OK, scope, 3);
    tpuCounterAdd("shield_wire_mismatches", 1);
    /* Resolve the inject bookkeeping: an outstanding wire flip this
     * verify caught converts to a detection. */
    uint64_t pend = atomic_load(&g_wirePending);
    while (pend > 0 &&
           !atomic_compare_exchange_weak(&g_wirePending, &pend, pend - 1))
        ;
    if (pend > 0)
        tpuCounterAdd("shield_detected", 1);
    tpurmTraceInstantLabel(TPU_TRACE_SHIELD_VERIFY, scope, len,
                           "shield.wire_mismatch");
    return TPU_ERR_INVALID_STATE;
}

/* ------------------------------------------------------ span poisoned */

uint32_t tpurmShieldSpanPoisoned(uint64_t addr, uint64_t len)
{
    UvmVaSpace *vs = uvmFaultSpaceForAddr(addr);
    if (!vs || !len)
        return 0;
    uint64_t ps = uvmPageSize();
    uint32_t n = 0;
    pthread_mutex_lock(&vs->lock);
    tpuLockTrackAcquire(TPU_LOCK_UVM_VASPACE, "shield-span");
    uint64_t a = addr & ~(UVM_BLOCK_SIZE - 1);
    for (; a < addr + len; a += UVM_BLOCK_SIZE) {
        UvmVaBlock *blk = NULL;
        if (!uvmRangeFind(vs, a, &blk) || !blk || !blk->shield)
            continue;
        uint64_t lo = addr > blk->start ? addr : blk->start;
        uint64_t blkEnd = blk->start + (uint64_t)blk->npages * ps;
        uint64_t hi = addr + len < blkEnd ? addr + len : blkEnd;
        pthread_mutex_lock(&blk->lock);
        tpuLockTrackAcquire(TPU_LOCK_UVM_BLOCK, "shield-span");
        for (uint64_t v = lo & ~(ps - 1); v < hi; v += ps) {
            uint32_t page = (uint32_t)((v - blk->start) / ps);
            if (blk->shield[page].state == SHIELD_POISONED)
                n++;
        }
        tpuLockTrackRelease(TPU_LOCK_UVM_BLOCK, "shield-span");
        pthread_mutex_unlock(&blk->lock);
    }
    tpuLockTrackRelease(TPU_LOCK_UVM_VASPACE, "shield-span");
    pthread_mutex_unlock(&vs->lock);
    return n;
}

/* ------------------------------------------------------------ scrubber */

/* One bounded pass: walk sealed cold pages (round-robin cursor across
 * passes) and verify up to `budget` of them, catching corruption
 * BEFORE a demand fault does.  Block locks are TRYLOCKED — the
 * scrubber never contends with the fault path, which is half of how
 * the fault p50 budget holds (the other half is the bounded budget). */
typedef struct {
    uint32_t budget;
    uint32_t scrubbed, hits;
    uint64_t cursor;                /* resume after this block VA */
    uint64_t nextCursor;
    bool resumed;                   /* passed the cursor yet */
} ScrubCtx;

static _Atomic uint64_t g_scrubCursor;

static void scrub_visit(UvmVaSpace *vs, UvmVaBlock *blk, void *ctxp)
{
    (void)vs;
    ScrubCtx *ctx = ctxp;
    if (ctx->budget == 0 || !blk->shield)
        return;
    uint64_t ps = uvmPageSize();
    uint64_t blkEnd = blk->start + (uint64_t)blk->npages * ps;
    uint32_t startPage = 0;
    if (!ctx->resumed) {
        /* PAGE-granular resume (the cursor is the next VA to scan):
         * blocks wholly below it are done this wrap; the cursor's own
         * block resumes at the cursor page.  A block holding more
         * sealed pages than one tick's budget would otherwise restart
         * at page 0 every visit and its tail would NEVER scrub. */
        if (ctx->cursor && blkEnd <= ctx->cursor)
            return;
        if (ctx->cursor && blk->start < ctx->cursor)
            startPage = (uint32_t)((ctx->cursor - blk->start) / ps);
        ctx->resumed = true;
    }
    if (pthread_mutex_trylock(&blk->lock) != 0)
        return;
    tpuLockTrackAcquire(TPU_LOCK_UVM_BLOCK, "shield-scrub");
    uint32_t p = startPage;
    for (; p < blk->npages && ctx->budget; p++) {
        if (!meta_sealed(&blk->shield[p]))
            continue;
        ctx->budget--;
        ctx->scrubbed++;
        if (shield_verify_page(blk, p) != 0)
            ctx->hits++;
    }
    tpuLockTrackRelease(TPU_LOCK_UVM_BLOCK, "shield-scrub");
    pthread_mutex_unlock(&blk->lock);
    /* Resume point: the first page NOT scanned — mid-block when the
     * budget ran out, the block end otherwise. */
    ctx->nextCursor = p < blk->npages ? blk->start + (uint64_t)p * ps
                                      : blkEnd;
}

static uint32_t scrub_pass(uint32_t budget)
{
    ScrubCtx ctx = { .budget = budget, .scrubbed = 0, .hits = 0,
                     .cursor = atomic_load(&g_scrubCursor),
                     .nextCursor = 0, .resumed = false };
    uint64_t tSpan = tpurmTraceBegin();
    uvmFaultForEachSpaceCtx(scrub_visit, &ctx);
    if (ctx.budget > 0 && ctx.cursor) {
        /* Budget left after the cursor: wrap to the start this pass so
         * a single hot block at the end cannot starve the rest. */
        ctx.cursor = 0;
        ctx.resumed = false;
        uint32_t before = ctx.scrubbed;
        uvmFaultForEachSpaceCtx(scrub_visit, &ctx);
        if (ctx.scrubbed == before)
            ctx.nextCursor = 0;
    }
    atomic_store(&g_scrubCursor, ctx.budget > 0 ? 0 : ctx.nextCursor);
    tpuCounterAdd("tpurm_scrub_ticks", 1);
    if (ctx.scrubbed)
        tpuCounterAdd("tpurm_scrub_pages", ctx.scrubbed);
    if (ctx.hits)
        tpuCounterAdd("tpurm_scrub_hits", ctx.hits);
    if (tSpan && ctx.scrubbed)
        tpurmTraceEnd(TPU_TRACE_SHIELD_SCRUB, tSpan, ctx.hits,
                      (uint64_t)ctx.scrubbed * uvmPageSize());
    return ctx.scrubbed;
}

uint32_t tpurmShieldScrubNow(uint32_t maxPages)
{
    return scrub_pass(maxPages ? maxPages : 1);
}

static void *shield_scrub_thread(void *arg)
{
    (void)arg;
    static TpuRegCache c_ms, c_pages;
    for (;;) {
        uint64_t ms = tpuRegCacheGet(&c_ms, "shield_scrub_ms", 50);
        /* 0 disables scrubbing (README knob contract) — keep polling
         * at the default cadence so a runtime re-enable via
         * tpuRegistrySet takes effect without a new thread. */
        bool off = ms == 0;
        if (off)
            ms = 50;
        struct timespec ts = { .tv_sec = (time_t)(ms / 1000),
                               .tv_nsec = (long)(ms % 1000) * 1000000L };
        nanosleep(&ts, NULL);
        if (off || !tpurmShieldEnabled())
            continue;
        uint32_t budget = (uint32_t)tpuRegCacheGet(&c_pages,
                                                   "shield_scrub_pages",
                                                   32);
        if (budget)
            scrub_pass(budget);
    }
    return NULL;
}

static pthread_once_t g_scrubOnce = PTHREAD_ONCE_INIT;

static void scrub_start_once(void)
{
    pthread_t t;
    if (pthread_create(&t, NULL, shield_scrub_thread, NULL) == 0) {
        pthread_detach(t);
        TPU_LOG(TPU_LOG_INFO, "shield",
               "background scrubber ready (shield_scrub_ms cadence, "
               "shield_scrub_pages pages/tick)");
    }
}

static void shield_scrub_start(void)
{
    pthread_once(&g_scrubOnce, scrub_start_once);
}

/* ---------------------------------------------------------- stats/obs */

void tpurmShieldStatsGet(TpuShieldStats *out)
{
    if (!out)
        return;
    out->seals = tpurmCounterGet("tpurm_shield_seals");
    out->verifies = tpurmCounterGet("tpurm_shield_verifies");
    out->mismatches = tpurmCounterGet("tpurm_shield_mismatches");
    out->refetchSaves = tpurmCounterGet("tpurm_shield_refetch_saves");
    out->pagesPoisoned = tpurmCounterGet("tpurm_shield_pages_poisoned");
    out->pagesRetired = tpurmCounterGet("tpurm_shield_pages_retired");
    out->scrubTicks = tpurmCounterGet("tpurm_scrub_ticks");
    out->scrubPages = tpurmCounterGet("tpurm_scrub_pages");
    out->scrubHits = tpurmCounterGet("tpurm_scrub_hits");
    out->injectCorrupts = tpurmCounterGet("shield_inject_corrupts");
    out->injectDetected = tpurmCounterGet("shield_detected");
    /* In-flight wire flips read as misses only once traffic drains —
     * the soaks reconcile at quiescence. */
    out->injectMisses = tpurmCounterGet("shield_inject_misses") +
                        atomic_load(&g_wirePending);
    out->wireVerifies = tpurmCounterGet("shield_wire_verifies");
    out->wireMismatches = tpurmCounterGet("shield_wire_mismatches");
}

void tpurmShieldStatsReset(void)
{
    /* Counters are monotonic (tests snapshot deltas); only the
     * in-flight wire bookkeeping resets. */
    atomic_store(&g_wirePending, 0);
}

void tpurmShieldRenderProm(TpuCur *c)
{
    tpuCurf(c, "# TYPE tpurm_pages_retired gauge\n");
    uint32_t n = tpurmDeviceCount();
    if (n > SHIELD_MAX_DEVS)
        n = SHIELD_MAX_DEVS;
    for (uint32_t d = 0; d < n; d++)
        tpuCurf(c, "tpurm_pages_retired{dev=\"%u\"} %llu\n", d,
                (unsigned long long)atomic_load(&g_retire.perDev[d]));
}

void tpurmShieldRenderTable(TpuCur *c)
{
    TpuShieldStats st;
    tpurmShieldStatsGet(&st);
    tpuCurf(c, "enabled:            %u\n", tpurmShieldEnabled());
    tpuCurf(c, "scrub_ms:           %llu\n",
            (unsigned long long)tpuRegistryGet("shield_scrub_ms", 50));
    tpuCurf(c, "scrub_pages:        %llu\n",
            (unsigned long long)tpuRegistryGet("shield_scrub_pages", 32));
    tpuCurf(c, "seals:              %llu\n", (unsigned long long)st.seals);
    tpuCurf(c, "verifies:           %llu\n",
            (unsigned long long)st.verifies);
    tpuCurf(c, "mismatches:         %llu\n",
            (unsigned long long)st.mismatches);
    tpuCurf(c, "refetch_saves:      %llu\n",
            (unsigned long long)st.refetchSaves);
    tpuCurf(c, "pages_poisoned:     %llu\n",
            (unsigned long long)st.pagesPoisoned);
    tpuCurf(c, "pages_retired:      %llu\n",
            (unsigned long long)st.pagesRetired);
    tpuCurf(c, "scrub_ticks:        %llu\n",
            (unsigned long long)st.scrubTicks);
    tpuCurf(c, "scrub_pages_done:   %llu\n",
            (unsigned long long)st.scrubPages);
    tpuCurf(c, "scrub_hits:         %llu\n",
            (unsigned long long)st.scrubHits);
    tpuCurf(c, "wire_verifies:      %llu\n",
            (unsigned long long)st.wireVerifies);
    tpuCurf(c, "wire_mismatches:    %llu\n",
            (unsigned long long)st.wireMismatches);
    tpuCurf(c, "inject_corrupts:    %llu\n",
            (unsigned long long)st.injectCorrupts);
    tpuCurf(c, "inject_detected:    %llu\n",
            (unsigned long long)st.injectDetected);
    tpuCurf(c, "inject_misses:      %llu\n",
            (unsigned long long)st.injectMisses);
    uint32_t nret = atomic_load_explicit(&g_retire.n,
                                         memory_order_acquire);
    tpuCurf(c, "retired spans (%u):\n", nret);
    for (uint32_t i = 0; i < nret && i < 32; i++)
        tpuCurf(c, "  tier=%u dev=%u off=0x%llx bytes=%llu\n",
                g_retire.s[i].tier, g_retire.s[i].dev,
                (unsigned long long)g_retire.s[i].off,
                (unsigned long long)g_retire.s[i].bytes);
}

/* ------------------------------------------------------ tpubox dump */

/* Crash-bundle section: the retirement list, scanned lock-free up to
 * the release-stored count (entries are immutable once published).
 * Async-signal-safe by the raw-hook contract — no locks, no
 * allocation, bounded work. */
void tpurmShieldDumpRaw(TpuDumpCur *c)
{
    uint32_t n = atomic_load_explicit(&g_retire.n, memory_order_acquire);
    tpuDumpStr(c, "S total ");
    tpuDumpU64(c, atomic_load_explicit(&g_retire.total,
                                       memory_order_relaxed));
    tpuDumpStr(c, " listed ");
    tpuDumpU64(c, n);
    tpuDumpStr(c, " overflow ");
    tpuDumpU64(c, atomic_load_explicit(&g_retire.dropped,
                                       memory_order_relaxed));
    tpuDumpStr(c, "\n");
    for (uint32_t i = 0; i < n && i < SHIELD_RETIRE_MAX; i++) {
        tpuDumpStr(c, "S retire tier ");
        tpuDumpU64(c, g_retire.s[i].tier);
        tpuDumpStr(c, " dev ");
        tpuDumpU64(c, g_retire.s[i].dev);
        tpuDumpStr(c, " off ");
        tpuDumpHex(c, g_retire.s[i].off);
        tpuDumpStr(c, " bytes ");
        tpuDumpHex(c, g_retire.s[i].bytes);
        tpuDumpStr(c, "\n");
    }
}
