/*
 * inject — seeded, site-addressable fault injection (see
 * include/tpurm/inject.h for the model).
 *
 * Concurrency: evaluations are lock-free (atomics only; the armed-mask
 * fast path is one relaxed load).  Configuration takes a mutex but only
 * flips atomics, so it can race evaluations safely — a torn config is
 * at worst one spurious or missed hit during the transition, which
 * chaos tests tolerate by design.
 */
#define _GNU_SOURCE
#include "internal.h"
#include "tpurm/inject.h"
#include "tpurm/journal.h"
#include "tpurm/trace.h"

#include <stdatomic.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>

#define INJECT_ARM_SLOTS 16

/* Scope sentinel stored in an arm slot meaning "any scope". */
#define ARM_ANY UINT64_MAX

typedef struct {
    _Atomic uint32_t mode;
    _Atomic uint64_t arg;
    _Atomic uint32_t burst;                 /* >= 1 */
    _Atomic uint64_t scope;                 /* 0 = any */
    _Atomic uint64_t calls, hits;
    _Atomic uint64_t rng;                   /* xorshift64 state, never 0 */
    _Atomic uint64_t nth;                   /* NTH evaluation counter */
    _Atomic int32_t burstLeft;
    _Atomic uint64_t arms[INJECT_ARM_SLOTS];/* scoped one-shots; 0 empty */
} InjectSiteState;

static struct {
    pthread_mutex_t lock;                   /* configuration only */
    _Atomic uint32_t activeMask;            /* bit per armed site */
    uint64_t seed;
    InjectSiteState sites[TPU_INJECT_SITE_COUNT];
} g_inject = { .lock = PTHREAD_MUTEX_INITIALIZER };

static const char *const g_siteNames[TPU_INJECT_SITE_COUNT] = {
    "pmm.alloc",
    "migrate.copy",
    "msgq.publish",
    "ici.link",
    "rdma.completion",
    "channel.ce",
    "fence.timeout",
    "memring.submit",
    "ce.copy",
    "sched.admit",
    "reset.device",
    "vac.migrate",
    "hot.decide",
    "mem.corrupt",
    "dump.write",
};

/* Env key suffix per site (TPUMEM_INJECT_<suffix>). */
static const char *const g_siteEnv[TPU_INJECT_SITE_COUNT] = {
    "PMM_ALLOC",
    "MIGRATE_COPY",
    "MSGQ_PUBLISH",
    "ICI_LINK",
    "RDMA_COMPLETION",
    "CHANNEL_CE",
    "FENCE_TIMEOUT",
    "MEMRING_SUBMIT",
    "CE_COPY",
    "SCHED_ADMIT",
    "RESET_DEVICE",
    "VAC_MIGRATE",
    "HOT_DECIDE",
    "MEM_CORRUPT",
    "DUMP_WRITE",
};

const char *tpurmInjectSiteName(uint32_t site)
{
    return site < TPU_INJECT_SITE_COUNT ? g_siteNames[site] : NULL;
}

/* splitmix64: turns (seed, site) into a well-mixed nonzero PRNG state. */
static uint64_t mix64(uint64_t x)
{
    x += 0x9E3779B97F4A7C15ull;
    x = (x ^ (x >> 30)) * 0xBF58476D1CE4E5B9ull;
    x = (x ^ (x >> 27)) * 0x94D049BB133111EBull;
    x = x ^ (x >> 31);
    return x ? x : 1;
}

static void mask_set(uint32_t site)
{
    atomic_fetch_or_explicit(&g_inject.activeMask, 1u << site,
                             memory_order_acq_rel);
}

static void mask_clear(uint32_t site)
{
    atomic_fetch_and_explicit(&g_inject.activeMask, ~(1u << site),
                              memory_order_acq_rel);
}

void tpurmInjectSetSeed(uint64_t seed)
{
    pthread_mutex_lock(&g_inject.lock);
    g_inject.seed = seed;
    for (uint32_t s = 0; s < TPU_INJECT_SITE_COUNT; s++) {
        atomic_store(&g_inject.sites[s].rng, mix64(seed ^ (0x51ull + s)));
        atomic_store(&g_inject.sites[s].nth, 0);
    }
    pthread_mutex_unlock(&g_inject.lock);
}

TpuStatus tpurmInjectConfigure(uint32_t site, uint32_t mode, uint64_t arg,
                               uint32_t burst, uint64_t scope)
{
    if (site >= TPU_INJECT_SITE_COUNT || mode > TPU_INJECT_PPM)
        return TPU_ERR_INVALID_ARGUMENT;
    if (mode == TPU_INJECT_NTH && arg == 0)
        return TPU_ERR_INVALID_ARGUMENT;
    InjectSiteState *st = &g_inject.sites[site];
    pthread_mutex_lock(&g_inject.lock);
    atomic_store(&st->arg, arg);
    atomic_store(&st->burst, burst ? burst : 1);
    atomic_store(&st->scope, scope);
    atomic_store(&st->nth, 0);
    atomic_store(&st->burstLeft, 0);
    if (!atomic_load(&st->rng))
        atomic_store(&st->rng, mix64(g_inject.seed ^ (0x51ull + site)));
    atomic_store(&st->mode, mode);
    if (mode == TPU_INJECT_OFF) {
        bool armed = false;
        for (int i = 0; i < INJECT_ARM_SLOTS; i++)
            if (atomic_load(&st->arms[i]))
                armed = true;
        if (!armed)
            mask_clear(site);
    } else {
        mask_set(site);
        TPU_LOG(TPU_LOG_INFO, "inject", "site %s armed: mode=%u arg=%llu "
               "burst=%u scope=%llu", g_siteNames[site], mode,
               (unsigned long long)arg, burst ? burst : 1,
               (unsigned long long)scope);
    }
    pthread_mutex_unlock(&g_inject.lock);
    return TPU_OK;
}

TpuStatus tpurmInjectArmOneShot(uint32_t site, uint64_t scope)
{
    if (site >= TPU_INJECT_SITE_COUNT)
        return TPU_ERR_INVALID_ARGUMENT;
    InjectSiteState *st = &g_inject.sites[site];
    uint64_t key = scope ? scope : ARM_ANY;
    for (int i = 0; i < INJECT_ARM_SLOTS; i++) {
        uint64_t expect = 0;
        if (atomic_compare_exchange_strong(&st->arms[i], &expect, key)) {
            mask_set(site);
            return TPU_OK;
        }
    }
    return TPU_ERR_INSUFFICIENT_RESOURCES;
}

void tpurmInjectDisable(uint32_t site)
{
    if (site >= TPU_INJECT_SITE_COUNT)
        return;
    InjectSiteState *st = &g_inject.sites[site];
    pthread_mutex_lock(&g_inject.lock);
    atomic_store(&st->mode, TPU_INJECT_OFF);
    atomic_store(&st->burstLeft, 0);
    for (int i = 0; i < INJECT_ARM_SLOTS; i++)
        atomic_store(&st->arms[i], 0);
    mask_clear(site);
    pthread_mutex_unlock(&g_inject.lock);
}

void tpurmInjectDisableAll(void)
{
    for (uint32_t s = 0; s < TPU_INJECT_SITE_COUNT; s++)
        tpurmInjectDisable(s);
}

void tpurmInjectCounts(uint32_t site, uint64_t *evals, uint64_t *hits)
{
    if (site >= TPU_INJECT_SITE_COUNT) {
        if (evals)
            *evals = 0;
        if (hits)
            *hits = 0;
        return;
    }
    if (evals)
        *evals = atomic_load(&g_inject.sites[site].calls);
    if (hits)
        *hits = atomic_load(&g_inject.sites[site].hits);
}

/* ----------------------------------------------------------- evaluation */

/* A hit lands in the tpubox journal and (except for dump.write, which
 * is evaluated from the async-signal-safe dumper — no trace ring
 * acquisition, no logging allowed there) in the trace stream. */
static void inject_hit_note(uint32_t site, uint64_t scopeKey)
{
    atomic_fetch_add_explicit(&g_inject.sites[site].hits, 1,
                              memory_order_relaxed);
    tpurmJournalEmit(TPU_JREC_INJECT_HIT, 0, TPU_OK, site, scopeKey);
    if (site != TPU_INJECT_SITE_DUMP_WRITE)
        tpurmTraceInstantLabel(TPU_TRACE_INJECT_HIT, scopeKey, site,
                               g_siteNames[site]);
}

static bool inject_eval(uint32_t site, uint64_t scopeKey)
{
    InjectSiteState *st = &g_inject.sites[site];
    atomic_fetch_add_explicit(&st->calls, 1, memory_order_relaxed);

    /* Scoped one-shot arms (the tpurmChannelInjectError shim): consume
     * the first slot matching this evaluation's scope. */
    for (int i = 0; i < INJECT_ARM_SLOTS; i++) {
        uint64_t arm = atomic_load_explicit(&st->arms[i],
                                            memory_order_acquire);
        if (!arm)
            continue;
        if (arm != ARM_ANY && scopeKey != arm)
            continue;
        if (atomic_compare_exchange_strong(&st->arms[i], &arm, 0)) {
            inject_hit_note(site, scopeKey);
            return true;
        }
    }

    /* Burst tail of a previous hit fails regardless of mode. */
    if (atomic_load_explicit(&st->burstLeft, memory_order_acquire) > 0 &&
        atomic_fetch_sub(&st->burstLeft, 1) > 0) {
        inject_hit_note(site, scopeKey);
        return true;
    }

    uint32_t mode = atomic_load_explicit(&st->mode, memory_order_acquire);
    if (mode == TPU_INJECT_OFF) {
        /* Nothing armed anymore: drop the mask bit opportunistically so
         * the fast path goes quiet again (benign if raced). */
        bool armed = false;
        for (int i = 0; i < INJECT_ARM_SLOTS; i++)
            if (atomic_load(&st->arms[i]))
                armed = true;
        if (!armed && atomic_load(&st->burstLeft) <= 0)
            mask_clear(site);
        return false;
    }

    uint64_t scope = atomic_load_explicit(&st->scope, memory_order_relaxed);
    if (scope != 0 && scopeKey != scope)
        return false;

    bool hit = false;
    switch (mode) {
    case TPU_INJECT_ONESHOT: {
        uint32_t expect = TPU_INJECT_ONESHOT;
        hit = atomic_compare_exchange_strong(&st->mode, &expect,
                                             TPU_INJECT_OFF);
        break;
    }
    case TPU_INJECT_NTH: {
        uint64_t n = atomic_fetch_add(&st->nth, 1) + 1;
        uint64_t arg = atomic_load(&st->arg);
        hit = arg && (n % arg) == 0;
        break;
    }
    case TPU_INJECT_PPM: {
        /* xorshift64 step (racing threads may reuse a state — the rate
         * is preserved; exact sequences are per-thread-interleaving). */
        uint64_t x = atomic_load_explicit(&st->rng, memory_order_relaxed);
        x ^= x << 13;
        x ^= x >> 7;
        x ^= x << 17;
        if (!x)
            x = 1;
        atomic_store_explicit(&st->rng, x, memory_order_relaxed);
        hit = (x % 1000000ull) < atomic_load(&st->arg);
        break;
    }
    default:
        break;
    }
    if (hit) {
        inject_hit_note(site, scopeKey);
        uint32_t burst = atomic_load(&st->burst);
        if (burst > 1)
            atomic_store(&st->burstLeft, (int32_t)burst - 1);
        if (site != TPU_INJECT_SITE_DUMP_WRITE)
            TPU_LOG(TPU_LOG_DEBUG, "inject", "site %s fired (scope=%llu)",
                   g_siteNames[site], (unsigned long long)scopeKey);
    }
    return hit;
}

bool tpurmInjectShouldFailScoped(uint32_t site, uint64_t scopeKey)
{
    /* Bounds first (the shift below would be UB for site >= 32), then
     * the disarmed fast path: one relaxed load, nothing else —
     * injection must not tax fault-path latency when off. */
    if (site >= TPU_INJECT_SITE_COUNT)
        return false;
    uint32_t mask = atomic_load_explicit(&g_inject.activeMask,
                                         memory_order_relaxed);
    if (!(mask & (1u << site)))
        return false;
    return inject_eval(site, scopeKey);
}

bool tpurmInjectShouldFail(uint32_t site)
{
    return tpurmInjectShouldFailScoped(site, 0);
}

/* --------------------------------------------------------------- env */

static void inject_parse_spec(uint32_t site, const char *spec)
{
    uint32_t mode = TPU_INJECT_OFF;
    uint64_t arg = 0, scope = 0;
    uint32_t burst = 1;

    if (strncmp(spec, "once", 4) == 0) {
        mode = TPU_INJECT_ONESHOT;
    } else if (strncmp(spec, "nth=", 4) == 0) {
        mode = TPU_INJECT_NTH;
        arg = strtoull(spec + 4, NULL, 0);
    } else if (strncmp(spec, "ppm=", 4) == 0) {
        mode = TPU_INJECT_PPM;
        arg = strtoull(spec + 4, NULL, 0);
    } else {
        TPU_LOG(TPU_LOG_WARN, "inject", "bad spec for site %s: '%s'",
               g_siteNames[site], spec);
        return;
    }
    const char *p = strchr(spec, ',');
    while (p) {
        p++;
        if (strncmp(p, "burst=", 6) == 0)
            burst = (uint32_t)strtoul(p + 6, NULL, 0);
        else if (strncmp(p, "scope=", 6) == 0)
            scope = strtoull(p + 6, NULL, 0);
        p = strchr(p, ',');
    }
    if ((mode == TPU_INJECT_NTH && arg == 0) ||
        tpurmInjectConfigure(site, mode, arg, burst, scope) != TPU_OK)
        TPU_LOG(TPU_LOG_WARN, "inject", "bad spec for site %s: '%s'",
               g_siteNames[site], spec);
}

void tpurmInjectReloadEnv(void)
{
    tpurmInjectSetSeed(tpuRegistryGet("inject_seed", 0));
    for (uint32_t s = 0; s < TPU_INJECT_SITE_COUNT; s++) {
        char key[64];
        snprintf(key, sizeof(key), "TPUMEM_INJECT_%s", g_siteEnv[s]);
        const char *spec = getenv(key);
        if (spec && *spec)
            inject_parse_spec(s, spec);
    }
}

__attribute__((constructor)) static void inject_ctor(void)
{
    tpurmInjectReloadEnv();
}
