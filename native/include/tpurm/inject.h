/*
 * inject — seeded, site-addressable fault injection.
 *
 * Every recovery path in the engine must be exercisable on demand
 * (reference: UVM error-injection ioctls, uvm_test.c:286,308; the
 * channel layer's old one-shot latch generalized here).  A SITE is a
 * named point in a critical path where the engine asks "should this
 * operation fail now?".  Sites are armed per-process with a mode:
 *
 *   ONESHOT — fail exactly one evaluation (optionally scoped to one
 *             object, e.g. one channel), then disarm;
 *   NTH     — fail every Nth evaluation (deterministic cadence);
 *   PPM     — fail with probability arg/1,000,000 per evaluation,
 *             driven by a per-site xorshift PRNG seeded from the
 *             global seed (same seed => same hit sequence).
 *
 * An optional BURST makes every hit fail the next burst-1 evaluations
 * too — long enough bursts defeat bounded retry and drive the
 * retry-exhausted / quarantine recovery paths.
 *
 * Configuration: C API below, ctypes (open_gpu_kernel_modules_tpu/
 * uvm/inject.py), or environment at load:
 *
 *   TPUMEM_INJECT_SEED=<u64>
 *   TPUMEM_INJECT_<SITE>=once | nth=<N> | ppm=<P>[,burst=<B>][,scope=<S>]
 *
 * where <SITE> is the enum name (PMM_ALLOC, MIGRATE_COPY, ...).
 *
 * The disarmed fast path is a single relaxed atomic load of a global
 * mask — no counters, no locks — so fault-path latency is unchanged
 * while injection is off.
 */
#ifndef TPURM_INJECT_H
#define TPURM_INJECT_H

#include <stdbool.h>
#include <stdint.h>

#include "status.h"

#ifdef __cplusplus
extern "C" {
#endif

/* Injection sites (keep tpurmInjectSiteName in sync). */
typedef enum {
    TPU_INJECT_SITE_PMM_ALLOC = 0,   /* PMM chunk allocation (HBM/CXL)   */
    TPU_INJECT_SITE_MIGRATE_COPY,    /* block migration copy pass        */
    TPU_INJECT_SITE_MSGQ_PUBLISH,    /* msgq submit (mirror/shadow/fifo) */
    TPU_INJECT_SITE_ICI_LINK,        /* ICI link flap / retrain failure  */
    TPU_INJECT_SITE_RDMA_COMPLETION, /* MR pin/map completion error      */
    TPU_INJECT_SITE_CHANNEL_CE,      /* channel CE push fault            */
    TPU_INJECT_SITE_FENCE_TIMEOUT,   /* fault-service / fence timeout    */
    TPU_INJECT_SITE_MEMRING_SUBMIT,  /* memring op execution (run)       */
    TPU_INJECT_SITE_CE_COPY,         /* tpuce stripe submission          */
    TPU_INJECT_SITE_SCHED_ADMIT,     /* tpusched admission decision      */
    TPU_INJECT_SITE_RESET_DEVICE,    /* forced full-device reset (the
                                      * reset watchdog evaluates this
                                      * once per tick; a hit injects a
                                      * device-level fatal fault whose
                                      * recovery IS tpurmDeviceReset)   */
    TPU_INJECT_SITE_VAC_MIGRATE,     /* tpuvac page-record shipping
                                      * (one evaluation per record copy
                                      * attempt; recovery is bounded
                                      * retry, then transactional abort
                                      * back to the source — exact
                                      * invariant: hits ==
                                      * vac_inject_retries +
                                      * vac_inject_aborts)             */
    TPU_INJECT_SITE_HOT_DECIDE,      /* tpuhot policy decision (one
                                      * evaluation per pin-or-throttle
                                      * choice, prefetch-cap adjust, or
                                      * victim reorder; recovery is
                                      * bounded degrade-to-no-op — the
                                      * decision is skipped, placement
                                      * keeps the undecided default —
                                      * exact invariant: hits ==
                                      * hot_inject_skips)             */
    TPU_INJECT_SITE_MEM_CORRUPT,     /* tpushield silent-corruption
                                      * injection — the first site that
                                      * CORRUPTS instead of failing: a
                                      * hit flips one bit in a freshly
                                      * sealed page (one evaluation per
                                      * page seal, scope = page VA) or
                                      * a shipped ICI/vac wire buffer
                                      * (one per hop/record); recovery
                                      * is the shield verify + re-fetch
                                      * ladder — exact invariant:
                                      * hits == shield_detected +
                                      * shield_inject_misses, and
                                      * misses stay 0 while the hooks
                                      * cover every consumption path  */
    TPU_INJECT_SITE_DUMP_WRITE,      /* tpubox crash-bundle serialization
                                      * (one evaluation per bundle
                                      * SECTION boundary; a hit chops
                                      * the bundle there — recovery is
                                      * graceful degrade: remaining
                                      * sections are skipped, the
                                      * trailer still marks the bundle
                                      * `truncated` so it parses, never
                                      * a hang or recursive fatal —
                                      * exact invariant: hits ==
                                      * journal_dump_errors)           */
    TPU_INJECT_SITE_COUNT
} TpuInjectSite;

/* Site modes. */
enum {
    TPU_INJECT_OFF = 0,
    TPU_INJECT_ONESHOT = 1,
    TPU_INJECT_NTH = 2,              /* arg = N: every Nth evaluation    */
    TPU_INJECT_PPM = 3,              /* arg = parts-per-million          */
};

/* Reseed every site PRNG (deterministic: same seed => same hit
 * sequence per site, counted by evaluation index). */
void tpurmInjectSetSeed(uint64_t seed);

/* Arm a site.  burst >= 1 (a hit fails burst consecutive evaluations);
 * scope 0 matches every evaluation, nonzero only evaluations carrying
 * the same scope key.  Mode TPU_INJECT_OFF disarms. */
TpuStatus tpurmInjectConfigure(uint32_t site, uint32_t mode, uint64_t arg,
                               uint32_t burst, uint64_t scope);

/* Queue one scoped one-shot without disturbing the site's main mode
 * (several may be armed at once; each is consumed by exactly one
 * matching evaluation).  TPU_ERR_INSUFFICIENT_RESOURCES when the arm
 * table is full. */
TpuStatus tpurmInjectArmOneShot(uint32_t site, uint64_t scope);

void tpurmInjectDisable(uint32_t site);
void tpurmInjectDisableAll(void);

/* Re-parse TPUMEM_INJECT_* from the environment (also done once at
 * library load). */
void tpurmInjectReloadEnv(void);

/* Observability: evaluations and hits since process start. */
void tpurmInjectCounts(uint32_t site, uint64_t *evals, uint64_t *hits);
const char *tpurmInjectSiteName(uint32_t site);

/* Engine-side checks (exported so tests can drive them directly).
 * The scoped variant carries an object key (e.g. channel rc id). */
bool tpurmInjectShouldFail(uint32_t site);
bool tpurmInjectShouldFailScoped(uint32_t site, uint64_t scopeKey);

#ifdef __cplusplus
}
#endif

#endif /* TPURM_INJECT_H */
