/*
 * tpurm rdma — the ib_core analog: peer-memory-client registration and
 * MR lifecycle for TPU-direct RDMA that LEAVES THE PROCESS.
 *
 * Re-design of the reference's ib_peer_memory_client contract
 * (reference kernel-open/nvidia-peermem/nvidia-peermem.c):
 *   ib_register_peer_memory_client (:515)  -> tpuIbRegisterPeerMemoryClient
 *   nv_mem_acquire (:198)                  -> client->acquire
 *   nv_mem_get_pages (:216)               -> client->getPages
 *   nv_dma_map (:245)                     -> client->dmaMap
 *   free-callback revocation (:134)       -> ib invalidate_peer_memory
 *
 * The "NIC" side is a SEPARATE PROCESS: device arenas are memfd-backed,
 * so an MR is described to the consumer as (arena memfd + IOVA list)
 * shipped over a unix socket (SCM_RIGHTS), and NIC "DMA" is the
 * consumer process mapping the memfd and reading/writing at the IOVAs —
 * genuinely crossing the process boundary the way BAR-mapped GPU memory
 * crosses to a NIC.  Mid-MR invalidation (the hard case: the underlying
 * allocation is freed while the MR is live) is published to the
 * consumer through a shared control page (its own memfd) the ib core
 * flips on the peer client's free callback.
 */
#ifndef TPURM_RDMA_H
#define TPURM_RDMA_H

#include <stdint.h>

#include "status.h"

#ifdef __cplusplus
extern "C" {
#endif

/* Faithful peer_memory_client vtable.  acquire() claims a VA range
 * (returns nonzero + clientCtx when this client owns it); the remaining
 * ops run against the returned context.  getPages receives the ib
 * core's per-MR context, which the client hands back through the
 * invalidate callback when the underlying memory dies mid-MR (the
 * reference's core_context / invalidate_peer_memory contract). */
typedef struct TpuPeerMemoryClient {
    const char *name;
    int (*acquire)(uint64_t addr, uint64_t size, void **clientCtx);
    TpuStatus (*getPages)(void *clientCtx, void *coreContext);
    TpuStatus (*dmaMap)(void *clientCtx, uint32_t nicId,
                        uint32_t *outDevInst, uint32_t *outPageSize,
                        uint32_t *outEntries, const uint64_t **outIova);
    TpuStatus (*dmaUnmap)(void *clientCtx, uint32_t nicId);
    void (*putPages)(void *clientCtx);
    void (*release)(void *clientCtx);
} TpuPeerMemoryClient;

/* The ib core's invalidation entry point: the peer client calls it with
 * the coreContext from getPages when the backing goes away (reference:
 * invalidate_peer_memory returned by ib_register_peer_memory_client,
 * called from the free callback at nvidia-peermem.c:134). */
typedef void (*TpuIbInvalidateCallback)(void *coreContext);

/* Register/unregister a client with the ib core (reference :515/:546).
 * outInvalidate receives the core's invalidation callback for this
 * registration.  Returns a handle (NULL on failure). */
typedef struct TpuIbPeerReg TpuIbPeerReg;
TpuIbPeerReg *tpuIbRegisterPeerMemoryClient(
    const TpuPeerMemoryClient *c, TpuIbInvalidateCallback *outInvalidate);
void tpuIbUnregisterPeerMemoryClient(TpuIbPeerReg *reg);

/* Register the built-in UVM peer client (managed-memory VAs; pins pages
 * device-side via tpuP2pGetPages).  Idempotent. */
void tpuIbRegisterUvmPeerClient(void);

/* ------------------------------------------------------------ MR API */

/* Shared control page the consumer process maps (its own memfd). */
typedef struct {
    _Atomic uint32_t revoked;    /* ib core sets 1 on peer invalidation */
    _Atomic uint32_t consumerAck;/* consumer sets 1 when it stopped    */
} TpuIbMrControl;

typedef struct TpuIbMr TpuIbMr;

/* ibv_reg_mr analog: walk registered peer clients, claim the VA, pin,
 * dma-map for nicId. */
TpuStatus tpuIbRegMr(uint64_t va, uint64_t size, uint32_t nicId,
                     TpuIbMr **out);
TpuStatus tpuIbDeregMr(TpuIbMr *mr);
/* 0 after peer invalidation (free-callback fired mid-MR). */
int tpuIbMrValid(TpuIbMr *mr);

/* Full-device reset hook (tpurm/reset.h): re-establish every live MR's
 * DMA mapping against the post-reset device state — the peer client's
 * dmaMap is re-run per MR (counted rdma_mrs_revalidated).  An MR whose
 * pin cannot re-establish is REVOKED through its control page exactly
 * like a mid-MR free (counted rdma_reset_revocations) — a reset must
 * never leave a valid-looking MR over unverified backing.  Returns the
 * number of MRs that revalidated. */
uint32_t tpuIbMrRevalidateAll(void);

/* IOVAs carry the NIC id in the top byte (per-NIC IOMMU domains); the
 * consumer's "IOMMU translation" to an arena offset is masking it off. */
#define TPU_IB_IOVA_OFFSET_MASK ((1ull << 56) - 1)

/* Consumer-side description: the device arena memfd to map, the control
 * memfd, and the per-page IOVAs.  The fds are owned by the MR (dup
 * before shipping cross-process). */
TpuStatus tpuIbMrDescribe(TpuIbMr *mr, int *outArenaFd, int *outCtrlFd,
                          uint32_t *outPageSize, uint32_t *outEntries,
                          const uint64_t **outIova);

#ifdef __cplusplus
}
#endif

#endif /* TPURM_RDMA_H */
