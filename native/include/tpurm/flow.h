/*
 * tpuflow — request-flow causal tracing with per-tenant SLO
 * attribution.
 *
 * A FLOW is one serving request's identity, minted at scheduler
 * admission and carried through every layer the request's bytes
 * touch: the 128-byte memring SQE (spare-byte flowId field), tpuce
 * stripes (CopySeg flow stamp), ICI PEER_COPY hops (hop counter
 * bumped per store-and-forward hop), fault-service entries
 * (UvmFaultEntry.flow, captured from the faulting thread), and tpuvac
 * migration windows.  Reference analog: the channel-tracked causal
 * state uvm_tracker.c threads through every push — a (channel, value)
 * pair IS a causal edge; tpuflow gives the same edge a serving-level
 * identity so a p99 token stall can be attributed to queueing vs
 * preemption vs fault service vs copy stripes vs an evacuation
 * window.
 *
 * Flow-id ABI (one u64):
 *
 *      63            48 47                    16 15            0
 *     +----------------+------------------------+---------------+
 *     |   tenant (16)  |      request (32)      |    hop (16)   |
 *     +----------------+------------------------+---------------+
 *
 * The hop field counts propagation hops (ICI store-and-forward legs,
 * vac shipping windows); every table/SLO keying masks it off
 * (TPU_FLOW_KEY), so hops of one request land on one ledger while
 * staying distinguishable in the Perfetto export.
 *
 * Two ledgers hang off the flow:
 *
 *   blame buckets — wall time split into queued / preempted /
 *       fault-service / copy / ici-ship / reset-blackout, accumulated
 *       as spans close: the memring exec layer accounts copy/ici per
 *       executed SQE (merged runs split by each SQE's len share), the
 *       fault engine accounts CPU demand-fault service, and the
 *       scheduler accounts the states only it can see (queued wait,
 *       preemption parks, reset blackouts) through tpurmFlowAccount.
 *       Invariant (chaos-soak-checked): a closed flow's bucket sum
 *       never exceeds its wall time beyond executor concurrency (two
 *       workers of one flow can overlap; the scheduler's flows are
 *       seconds against milliseconds of buckets).
 *
 *   per-tenant SLO histograms — TTFT (submit -> first token) and ITL
 *       (inter-token latency), fed from sched.py through the existing
 *       trace-hist machinery (log-linear, <= ~0.8% relative error).
 *       Exposed as tpurm_slo_ttft_ns{tenant=} /
 *       tpurm_slo_itl_ns{tenant=} histogram families and
 *       tpurm_slo_blame_ns{tenant=,bucket=} counters in the
 *       Prometheus exposition, /proc/driver/tpurm/flows (live top-K
 *       slow flows), and utils.flow_report() on the Python side.
 *
 * Fast-path discipline: a zero flow id costs one register test at
 * every instrumented site (the SQE field is zero-initialized); only
 * flow-carrying work pays the (relaxed-atomic) ledger adds.
 */
#ifndef TPURM_FLOW_H
#define TPURM_FLOW_H

#include <stddef.h>
#include <stdint.h>

#include "status.h"

#ifdef __cplusplus
extern "C" {
#endif

/* ------------------------------------------------------------- flow id */

#define TPU_FLOW_HOP_BITS 16
#define TPU_FLOW_REQ_SHIFT 16
#define TPU_FLOW_TENANT_SHIFT 48
#define TPU_FLOW_KEY_MASK (~0xFFFFull)

#define TPU_FLOW_MAKE(tenant, request)                                   \
    ((((uint64_t)(tenant) & 0xFFFFull) << TPU_FLOW_TENANT_SHIFT) |       \
     (((uint64_t)(request) & 0xFFFFFFFFull) << TPU_FLOW_REQ_SHIFT))
#define TPU_FLOW_TENANT(f) ((uint32_t)((f) >> TPU_FLOW_TENANT_SHIFT))
#define TPU_FLOW_REQUEST(f) ((uint32_t)(((f) >> TPU_FLOW_REQ_SHIFT) & \
                                        0xFFFFFFFFull))
#define TPU_FLOW_HOP(f) ((uint32_t)((f) & 0xFFFFull))
#define TPU_FLOW_KEY(f) ((f) & TPU_FLOW_KEY_MASK)
#define TPU_FLOW_WITH_HOP(f, h) (TPU_FLOW_KEY(f) | ((uint64_t)(h) & 0xFFFFull))

/* Mint a hop-0 flow id (pure arithmetic; no table side effects). */
uint64_t tpurmFlowMint(uint32_t tenant, uint32_t request);

/* --------------------------------------------------------- blame buckets */

enum {
    TPU_FLOW_B_QUEUED = 0,    /* submit -> admission (scheduler)        */
    TPU_FLOW_B_PREEMPTED,     /* swapped-out parks (scheduler)          */
    TPU_FLOW_B_FAULT,         /* CPU demand-fault service (fault engine)*/
    TPU_FLOW_B_COPY,          /* staged moves: PREFETCH/MIGRATE/EVICT/
                               * TIER_EVICT exec on the spine           */
    TPU_FLOW_B_ICI,           /* PEER_COPY shipping (incl. vac windows) */
    TPU_FLOW_B_RESET,         /* full-device-reset blackout parks       */
    TPU_FLOW_B_COUNT
};

const char *tpurmFlowBucketName(uint32_t bucket);

/* ------------------------------------------------------------ flow table */

/* Open a flow's ledger (idempotent for an already-open key; a table
 * with no free or recyclable slot drops, counted tpurm_flow_drops). */
TpuStatus tpurmFlowOpen(uint64_t flow);

/* Accumulate ns into one blame bucket (and the per-tenant blame
 * counter).  Unopened keys drop (counted tpurm_flow_unmatched) — the
 * ledger never invents entries for stray ids. */
void tpurmFlowAccount(uint64_t flow, uint32_t bucket, uint64_t ns);

/* Bump the flow's emitted-token count (display/reconciliation). */
void tpurmFlowTokens(uint64_t flow, uint64_t tokens);

/* Close the ledger: stamps wall = now - open.  *wallNsOut optional. */
TpuStatus tpurmFlowClose(uint64_t flow, uint64_t *wallNsOut);

/* One report row (ctypes surface — keep field order in sync with
 * utils.flow_report). */
typedef struct {
    uint64_t flow;                       /* hop-0 key                  */
    uint32_t tenant;
    uint32_t state;                      /* 1 = open, 2 = closed       */
    uint64_t openNs;                     /* tpuNowNs clock             */
    uint64_t wallNs;                     /* closed: final; open: so far */
    uint64_t tokens;
    uint64_t bucketNs[TPU_FLOW_B_COUNT];
} TpuFlowRec;

/* Fill out[] with up to max rows, most-blamed first (the "top-K slow
 * flows" ordering /proc/driver/tpurm/flows renders).  Returns rows. */
uint32_t tpurmFlowReport(TpuFlowRec *out, uint32_t max);

/* Clear the table, the SLO histograms and the per-tenant blame
 * counters (tests / bench isolation). */
void tpurmFlowResetAll(void);

/* --------------------------------------------------- per-tenant SLO hists */

#define TPU_FLOW_TENANTS 64       /* == UVM_MAX_TENANTS */

enum {
    TPU_SLO_TTFT = 0,             /* submit -> first token             */
    TPU_SLO_ITL = 1,              /* inter-token latency (per token)   */
    TPU_SLO_KIND_COUNT
};

void tpurmSloRecord(uint32_t tenant, uint32_t kind, uint64_t ns);
/* Batched feed: `count` samples of the same value (sched.py records a
 * decode round's amortized per-token latency once per stream). */
void tpurmSloRecordN(uint32_t tenant, uint32_t kind, uint64_t ns,
                     uint64_t count);
uint64_t tpurmSloQuantileNs(uint32_t tenant, uint32_t kind, double q);
uint64_t tpurmSloCount(uint32_t tenant, uint32_t kind);
/* Accumulated per-tenant blame (ns) for one bucket. */
uint64_t tpurmSloBlameNs(uint32_t tenant, uint32_t bucket);

#ifdef __cplusplus
}
#endif

#endif /* TPURM_FLOW_H */
