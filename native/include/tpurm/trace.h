/*
 * tputrace — unified cross-engine tracing + metrics.
 *
 * One observability spine for every engine (fault, migrate, pmm, tier,
 * channel, rc, ici, rdma, msgq) replacing the three disconnected
 * surfaces the port inherited (journal ring, tools event queues, fixed
 * two-point percentile windows):
 *
 *   span rings  — per-THREAD lock-free rings of fixed 64-byte records.
 *                 Spans carry (site, start ns, duration ns, object id,
 *                 bytes); instants (duration 0) mark point events:
 *                 every injected fault and every hardened-recovery
 *                 action from the fault-injection framework.  Rings
 *                 overwrite oldest (flight-recorder); overwritten and
 *                 table-full records are counted, never silently lost.
 *   histograms  — per-site log-linear HDR-style latency histograms
 *                 (128 sub-buckets per power of two: <= 0.8% relative
 *                 error over the full uint64 range).  The fault
 *                 engine's UvmFaultStats percentiles derive from these
 *                 (ABI unchanged); all other sites accumulate while
 *                 tracing is armed.
 *
 * Export three ways:
 *   - tpurmTraceExportJson: Chrome trace-event / Perfetto JSON
 *     ({"traceEvents":[...]}, "X" spans + "i" instants, ts/dur in us);
 *   - /proc/driver/tpurm/metrics: Prometheus text exposition (named
 *     counters + histogram buckets), served through procfs.c so plain
 *     `cat` works under the LD_PRELOAD shim;
 *   - Python: utils.trace_start/stop/export, utils.span() app spans.
 *
 * Fast-path discipline (same as inject.h): with tracing DISARMED a
 * site costs ONE relaxed atomic load (tpurmTraceBegin returns 0) — no
 * lock, no allocation, no histogram traffic.  Timestamps share
 * tpuNowNs() with the journal and injection framework so all three
 * timelines are directly comparable.
 *
 * Environment (parsed at library load):
 *   TPUMEM_TRACE=1            arm tracing at load
 *   TPUMEM_TRACE_RING=<N>     per-thread ring capacity in records
 *                             (rounded up to a power of two; default
 *                             8192, 64 B per record)
 */
#ifndef TPURM_TRACE_H
#define TPURM_TRACE_H

#include <stddef.h>
#include <stdint.h>

#ifdef __cplusplus
extern "C" {
#endif

/* Trace sites (keep tpurmTraceSiteName / site table in trace.c in
 * sync).  Span sites come first; the tail block is instant-only. */
typedef enum {
    TPU_TRACE_FAULT_LATENCY = 0, /* enqueue -> replay (headline)       */
    TPU_TRACE_FAULT_WAKE,        /* enqueue -> batch pop               */
    TPU_TRACE_FAULT_SERVICE,     /* one service_one call               */
    TPU_TRACE_FAULT_BATCH,       /* whole service-loop batch           */
    TPU_TRACE_MIGRATE,           /* explicit UVM_MIGRATE call          */
    TPU_TRACE_MIGRATE_COPY,      /* block residency copy pass          */
    TPU_TRACE_PMM_ALLOC,         /* PMM chunk allocation               */
    TPU_TRACE_EVICT,             /* block eviction                     */
    TPU_TRACE_CHANNEL_PUSH,      /* push submit (begin -> GPFIFO)      */
    TPU_TRACE_CHANNEL_FENCE,     /* tracker-value wait                 */
    TPU_TRACE_ICI_COPY,          /* ICI peer copy (direct or detour)   */
    TPU_TRACE_ICI_RETRAIN,       /* soft-link retrain pass             */
    TPU_TRACE_RDMA_PIN,          /* MR pin + DMA map                   */
    TPU_TRACE_MSGQ_PUBLISH,      /* msgq submit                        */
    TPU_TRACE_MEMRING_SUBMIT,    /* memring batch publish + doorbell   */
    TPU_TRACE_MEMRING_OP,        /* one memring run (coalesced span)   */
    TPU_TRACE_MEMRING_CHAIN,     /* internal-spine chain LENGTH (the
                                  * histogram holds chain sizes, not
                                  * ns — fault batches feed it one
                                  * record per submitted chain)        */
    TPU_TRACE_MEMRING_DEPWAIT,   /* ns an SQE sat dep-blocked in the
                                  * claim scan before its wait-on-
                                  * (ring,seq) set retired             */
    TPU_TRACE_CE_COPY,           /* tpuce batch copy (split + submit)  */
    TPU_TRACE_CE_STRIPE,         /* executor stripe run (obj = channel) */
    TPU_TRACE_SCHED_ROUND,       /* tpusched decode round (obj = round) */
    TPU_TRACE_SCHED_ADMIT,       /* tpusched admission pass            */
    TPU_TRACE_SCHED_PREEMPT,     /* tpusched preempt + swap-out        */
    TPU_TRACE_RESET_DEVICE,      /* full-device reset (quiesce->resume) */
    TPU_TRACE_RESET_QUIESCE,     /* reset quiesce phase alone          */
    TPU_TRACE_VAC_MIGRATE,       /* tpuvac tenant migration (whole
                                  * drain->ship->commit window; obj =
                                  * src<<32|dst, bytes = bytes moved)  */
    TPU_TRACE_SHIELD_VERIFY,     /* tpushield seal verification span
                                  * (obj = VA, bytes = span); mismatch/
                                  * poison/wire events ride it as
                                  * labeled instants                   */
    TPU_TRACE_SHIELD_SCRUB,      /* one background scrub pass (obj =
                                  * hits, bytes = bytes scrubbed)      */
    TPU_TRACE_APP,               /* application span (Python utils.span) */
    /* Instant-only sites. */
    TPU_TRACE_INJECT_HIT,        /* injection framework fired          */
    TPU_TRACE_RECOVER_RETRY,     /* bounded-backoff retry taken        */
    TPU_TRACE_RECOVER_TIER_FALLBACK,
    TPU_TRACE_RECOVER_QUARANTINE,
    TPU_TRACE_RECOVER_RC_RESET,
    TPU_TRACE_RECOVER_RETRAIN,
    TPU_TRACE_HOT_PIN,           /* tpuhot thrash PIN decision (obj =
                                  * block VA, aux = pinned tier)       */
    TPU_TRACE_HOT_THROTTLE,      /* tpuhot THROTTLE decision (aux 0) or
                                  * applied service delay (aux 1)      */
    TPU_TRACE_HEALTH_TRANSITION, /* device health state change (obj =
                                  * dev, bytes = new TPU_HEALTH_*)     */
    TPU_TRACE_SITE_COUNT
} TpuTraceSite;

/* ---------------------------------------------------------- arm control */

void tpurmTraceStart(void);
void tpurmTraceStop(void);
/* Clear every ring, the drop accounting, and every SITE histogram.
 * (The three fault-stats histograms also reset — they are the
 * FAULT_LATENCY/WAKE/SERVICE sites; uvmFaultStatsResetWindows resets
 * only those three.) */
void tpurmTraceReset(void);
int  tpurmTraceIsArmed(void);

/* --------------------------------------------------------------- emission */

/* Begin a span: returns tpuNowNs(), or 0 when tracing is disarmed (the
 * single-relaxed-load fast path).  Pass the token to tpurmTraceEnd,
 * which is a no-op for token 0. */
uint64_t tpurmTraceBegin(void);
void tpurmTraceEnd(uint32_t site, uint64_t t0, uint64_t obj,
                   uint64_t bytes);
/* Span with explicit endpoints (cross-thread phases, e.g. fault wake:
 * enqueue happened on the faulting thread, pop on the worker).  Ring
 * record + site histogram, armed check inside. */
void tpurmTraceSpanAt(uint32_t site, uint64_t t0, uint64_t t1,
                      uint64_t obj, uint64_t bytes);
/* Ring-only span record (no histogram) — for sites whose histogram is
 * fed separately (the always-on fault-stats windows). */
void tpurmTraceEventAt(uint32_t site, uint64_t t0, uint64_t t1,
                       uint64_t obj, uint64_t bytes);
/* Instant event ("i" phase).  The labeled variant overrides the
 * rendered name (app spans, injection site names). */
void tpurmTraceInstant(uint32_t site, uint64_t obj, uint64_t bytes);
void tpurmTraceInstantLabel(uint32_t site, uint64_t obj, uint64_t bytes,
                            const char *label);
/* Application span (Python utils.span): t0 from tpurmTraceNowNs(). */
void tpurmTraceAppSpan(const char *name, uint64_t t0, uint64_t obj,
                       uint64_t bytes);
uint64_t tpurmTraceNowNs(void);

/* ------------------------------------------------------------ flow context
 *
 * tpuflow (tpurm/flow.h): the CURRENT thread's flow id.  Every ring
 * record stamps it, so spans emitted while a flow is set carry the
 * request identity into the Perfetto export (flow events "s"/"f" link
 * a sched.admit span to the worker spans that executed its ops,
 * across threads).  Memring workers set it from the claimed SQE's
 * flowId around execution; the fault engine sets it from the entry's
 * captured flow; 0 clears.  One relaxed TLS store — safe on every hot
 * path (initial-exec TLS: no lazy allocation, so the CPU-fault signal
 * handler may read it). */
void tpurmTraceFlowSet(uint64_t flow);
uint64_t tpurmTraceFlowGet(void);

/* ----------------------------------------------------------------- export */

/* Chrome trace-event JSON into buf; always a complete, parseable
 * document (truncation drops whole trailing events, counted in
 * "args.exportDropped" on the final metadata event).  Returns bytes
 * written (excluding NUL). */
size_t tpurmTraceExportJson(char *buf, size_t bufSize);

/* Prometheus text exposition (the /proc/driver/tpurm/metrics body):
 * every named counter + every non-empty site histogram. */
size_t tpurmTraceRenderProm(char *buf, size_t bufSize);

/* Ring accounting: records ever emitted, records lost (overwritten by
 * ring wrap or dropped with no ring slot), live per-thread rings. */
void tpurmTraceStats(uint64_t *outRecorded, uint64_t *outDropped,
                     uint32_t *outRings);

/* Site histogram readout: q in [0,1]; 0 when the histogram is empty. */
uint64_t tpurmTraceHistQuantileNs(uint32_t site, double q);
uint64_t tpurmTraceHistCountNs(uint32_t site);

const char *tpurmTraceSiteName(uint32_t site);
/* Perfetto category for a site (NULL past the table end) — exposed so
 * the site-table self-check (trace_test.c) can assert every site id
 * added by later subsystems is named AND categorized: an unnamed site
 * would export anonymous spans. */
const char *tpurmTraceSiteCat(uint32_t site);

#ifdef __cplusplus
}
#endif

#endif /* TPURM_TRACE_H */
