/*
 * tpubox — black-box error journal + crash-dump bundles.
 *
 * An always-on, lock-free, fixed-size binary journal of structured
 * error/recovery records (reference: the RCDB error-journal ring in
 * src/nvidia/src/kernel/diagnostics/journal.c, the NvLog binary logger
 * in diagnostics/nvlog.c, and the mmap'd per-client event queues of
 * nvidia-uvm/uvm_tools.c).  Every engine that reports an error today —
 * health notes, RC resets, watchdog rungs, generation bumps, stale /
 * deadline completions, ICI flaps / retrains / per-hop CRC errors,
 * page quarantine / poison verdicts, vac manifest lifecycle, inject
 * hits, scheduler shed/preempt/retire — appends one 64-byte record.
 *
 * The journal lives in a single memfd-backed mapping:
 *
 *   offset 0                    TpuJournalHdr  (one 4 KiB page)
 *   offset TPU_JOURNAL_HDR_BYTES  TpuJournalRec[cap]   (cap power of two)
 *
 * Producers claim a slot with one fetch_add on hdr->widx and commit it
 * by release-storing rec->seq = claim + 1 LAST (seqlock discipline: a
 * reader that sees rec->seq == claim + 1 before AND after copying the
 * record got a consistent snapshot; anything else is torn or lapped).
 * Wrap overwrites the oldest record (flight-recorder semantics) and is
 * accounted in hdr->dropped, exactly like the tputrace span rings.
 * Emission is async-signal-safe by construction: atomics and plain
 * stores only, a futex *wake* (never a wait) on the doorbell when
 * subscribers exist, no locks, no malloc, no stdio.
 *
 * External agents tail the journal uvm_tools-style: dup the region fd
 * (tpurmJournalRegionFd), mmap it SHARED, keep a private consumer
 * cursor, and FUTEX_WAIT on hdr->doorbell (the low 32 bits of the
 * commit count) instead of polling procfs — the memring wakeup
 * discipline applied to diagnostics.
 *
 * On any fatal path (watchdog device reset, poison containment, vac
 * abort, broker client death, the last-gasp SIGSEGV handler) an
 * async-signal-safe dumper serializes a self-contained crash bundle —
 * journal tail + per-type emit counts + counter snapshot + health
 * table + per-ring frontier/claimed state + open vac manifests +
 * shield retirement list — atomically (write temp, rename) into
 * $TPUMEM_DUMP_DIR.  tools/tpubox.py turns a bundle (or a live
 * /proc/driver/tpurm/journal scrape) back into the ordered causal
 * timeline and cross-checks record counts against the counter
 * snapshot.
 */
#ifndef TPURM_JOURNAL_H
#define TPURM_JOURNAL_H

#include <stddef.h>
#include <stdint.h>

#include "tpurm/status.h"

#ifdef __cplusplus
extern "C" {
#endif

#define TPU_JOURNAL_MAGIC     0x31424a54u   /* "TJB1" little-endian */
#define TPU_JOURNAL_VERSION   1u
#define TPU_JOURNAL_HDR_BYTES 4096u
#define TPU_JOURNAL_REC_BYTES 64u

/* Record types.  The dotted names (tpurmJournalTypeName) are the
 * stable spelling used by the bundle format, the procfs scrape, the
 * JOURNAL_INVENTORY lint and the analyzer's reconciliation map.  Each
 * type's emit site sits adjacent to the counter(s) it reconciles
 * against (see tools/tpubox.py RECONCILE). */
typedef enum {
    TPU_JREC_NONE = 0,             /* empty slot marker, never emitted  */
    TPU_JREC_HEALTH_NOTE = 1,      /* a0 = TpuHealthEvent, a1 = score   */
    TPU_JREC_HEALTH_TRANSITION = 2,/* a0 = old state, a1 = new state    */
    TPU_JREC_HEALTH_EVAC = 3,      /* evac posted: a0 = reqId, a1 = tgt */
    TPU_JREC_WD_RUNG = 4,          /* a0 = rung (1/2/25/3), a1 = detail */
    TPU_JREC_RESET_GEN = 5,        /* generation bump: a0 = new gen     */
    TPU_JREC_RESET_DEVICE = 6,     /* reset done: a0 = gen, a1 = mttrNs */
    TPU_JREC_RING_STALE = 7,       /* a0 = ring/chan id, a1 = seq       */
    TPU_JREC_RING_DEADLINE = 8,    /* a0 = opcode, a1 = deadline ns     */
    TPU_JREC_ICI_FLAP = 9,         /* a0 = src chip, a1 = dst chip      */
    TPU_JREC_ICI_RETRAIN = 10,     /* retrain FAILED: a0=src, a1=dst    */
    TPU_JREC_ICI_CRC = 11,         /* per-hop wire CRC: a0=src, a1=dst  */
    TPU_JREC_PAGE_QUARANTINE = 12, /* a0 = va                           */
    TPU_JREC_PAGE_POISON = 13,     /* a0 = va, a1 = tier                */
    TPU_JREC_SHIELD_VERDICT = 14,  /* re-fetch ladder: a0=va, a1=verdict*/
    TPU_JREC_VAC_BEGIN = 15,       /* a0 = txn id, a1 = src<<32 | dst   */
    TPU_JREC_VAC_COMMIT = 16,      /* a0 = txn id, a1 = pages           */
    TPU_JREC_VAC_ABORT = 17,       /* a0 = txn id, a1 = src<<32 | dst   */
    TPU_JREC_INJECT_HIT = 18,      /* a0 = site, a1 = scope             */
    TPU_JREC_SCHED_SHED = 19,      /* a0 = tenant, a1 = queued (python) */
    TPU_JREC_SCHED_PREEMPT = 20,   /* a0 = seq slot, a1 = pages (python)*/
    TPU_JREC_SCHED_RETIRE = 21,    /* poison retire: a0 = seq (python)  */
    TPU_JREC_CLIENT_DEATH = 22,    /* a0 = pid, a1 = reclaimed pins     */
    TPU_JREC_LOG = 23,             /* WARN+ tpuLog mirror: a0 = level,
                                    * a1 = subsys packed as <=8 chars   */
    TPU_JREC_DUMP = 24,            /* bundle written: a0 = reason packed
                                    * <=8 chars, a1 = 1 ok / 0 truncated*/
    TPU_JREC_CRC_SELFTEST = 25,    /* HW CRC32C mismatch vs table at
                                    * dispatch: a0 = hw crc, a1 = want  */
    TPU_JREC_TIER_REMOTE = 26,     /* REMOTE-tier lease event: a0=pages
                                    * (or leases), a1 = op (0 demote,
                                    * 1 demote-fail, 2 revoke, 3 fence
                                    * abort); dev = lender              */
    TPU_JREC_TYPE_COUNT = 27
} TpuJournalRecType;

/* One journal record — 64 bytes, the stable on-disk/in-mmap ABI.
 * `seq` is the commit stamp (claim index + 1; 0 = slot never written
 * or mid-write); producers release-store it last, readers
 * acquire-load it before and after copying. */
typedef struct {
    uint64_t seq;        /* commit stamp (claim + 1), stored LAST      */
    uint64_t tsNs;       /* tpuNowNs() at emit                         */
    uint64_t flow;       /* tpuflow id from thread context (0 = none)  */
    uint64_t a0;         /* site-specific payload                      */
    uint64_t a1;         /* site-specific payload                      */
    uint32_t status;     /* TpuStatus at the site (TPU_OK = info)      */
    uint16_t type;       /* TpuJournalRecType                          */
    uint16_t dev;        /* device instance (0 when global)            */
    uint64_t pad[2];     /* reserved, zero                             */
} TpuJournalRec;

/* Region header (one page).  Fixed field offsets — uvm/journal.py
 * parses the mmap with these:
 *   magic @0  version @4  cap @8  recSize @12
 *   widx @16  dropped @24  doorbell @32  nsubs @36  emitted @40 */
typedef struct {
    uint32_t magic;
    uint32_t version;
    uint32_t cap;        /* record slots, power of two                 */
    uint32_t recSize;    /* == TPU_JOURNAL_REC_BYTES                   */
    uint64_t widx;       /* claim counter == records ever emitted      */
    uint64_t dropped;    /* records overwritten by wrap (flight rec)   */
    uint32_t doorbell;   /* futex word: low 32 bits of commit count    */
    uint32_t nsubs;      /* live subscribers (gates the futex wake)    */
    uint64_t emitted[TPU_JREC_TYPE_COUNT];  /* per-type emit counts    */
} TpuJournalHdr;

/* ------------------------------------------------------------- emission */

/* Append one record (async-signal-safe; flow id is read from the
 * tpuflow thread context).  No-op counting a drop when the journal is
 * disabled (TPUMEM_JOURNAL_ENABLE=0) or failed to initialize. */
void tpurmJournalEmit(uint32_t type, uint32_t dev, TpuStatus status,
                      uint64_t a0, uint64_t a1);
/* Same with an explicit flow id (python-side emitters carry their own). */
void tpurmJournalEmitFlow(uint32_t type, uint32_t dev, TpuStatus status,
                          uint64_t a0, uint64_t a1, uint64_t flow);

/* Canonical dotted record-type name ("ici.flap"); NULL for out of
 * range. */
const char *tpurmJournalTypeName(uint32_t type);

/* ------------------------------------------------------------ inspection */

/* emitted = records ever claimed, dropped = overwritten by wrap (plus
 * emits refused while disabled), cap = ring slots. */
void tpurmJournalStats(uint64_t *emitted, uint64_t *dropped,
                       uint32_t *cap);
uint64_t tpurmJournalTypeCount(uint32_t type);

/* ----------------------------------------------------------- subscription */

/* Dup of the journal region memfd for external mmap'd tailing (caller
 * owns the fd; -1 when the region is not fd-backed). */
int tpurmJournalRegionFd(void);
/* Current claim counter (a consumer cursor's upper bound). */
uint64_t tpurmJournalHead(void);
/* Register/unregister a live subscriber: while nsubs > 0 every commit
 * FUTEX_WAKEs the doorbell. */
void tpurmJournalSubscribe(void);
void tpurmJournalUnsubscribe(void);
/* Copy committed records from *cursor forward (at most max).  Advances
 * *cursor; adds records lost to wrap (cursor lapped) into *lost.
 * Returns records copied. */
size_t tpurmJournalConsume(uint64_t *cursor, TpuJournalRec *out,
                           size_t max, uint64_t *lost);
/* Block on the doorbell futex until the journal advances past cursor
 * (1) or timeoutNs elapses (0). */
int tpurmJournalWait(uint64_t cursor, uint64_t timeoutNs);

/* ------------------------------------------------------------ crash dumps */

/* Async-signal-safe bundle dump into $TPUMEM_DUMP_DIR (cached at
 * init).  Returns TPU_ERR_NOT_SUPPORTED when no dump dir is
 * configured, TPU_ERR_STATE_IN_USE when a dump is already in flight on
 * this or another thread (the recursion guard — a crash inside the
 * dumper must fall back to the plain backtrace path, not recurse),
 * TPU_ERR_OPERATING_SYSTEM on write errors, TPU_OK otherwise (also
 * when the bundle was truncated by the dump.write inject site — the
 * bundle says so in its trailer). */
TpuStatus tpurmJournalCrashDump(const char *reason);
/* Path of the most recently completed bundle ("" when none). */
size_t tpurmJournalLastBundle(char *buf, size_t cap);

/* Render the structured journal as text (the same "R ..." / "E ..."
 * line format the bundle's [journal]/[emitted] sections use; the
 * procfs node and the python live scrape both come through here). */
size_t tpurmJournalRenderTextBuf(char *buf, size_t cap);

#ifdef __cplusplus
}
#endif

#endif /* TPURM_JOURNAL_H */
