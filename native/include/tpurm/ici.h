/*
 * tpurm ICI — inter-chip interconnect topology, link management, and
 * peer HBM apertures.
 *
 * Re-design of the reference's NVLink/NVSwitch substrate (SURVEY.md
 * §2.7): the nvlink core library's link state machine
 * (src/common/nvlink/ — discovery/init/training) collapses to a small
 * per-link DOWN->TRAINING->ACTIVE machine, and the NVSwitch fabric
 * (src/common/nvswitch/, routing tables) collapses to a torus
 * neighbor/route table — TPUs have point-to-point ICI with no switch
 * ASIC, so routing is dimension-ordered over the torus.
 *
 * Peer apertures are the P2P substrate (reference: p2p_api.c P2P objects
 * + UVM peer identity mappings): once links are ACTIVE, a device can map
 * a neighbor's HBM window and DMA to/from it through its CE channels
 * (BASELINE config #5, ICI peer-mapped HBM pool).
 */
#ifndef TPURM_ICI_H
#define TPURM_ICI_H

#include <stdint.h>

#include "status.h"
#include "tpurm.h"

#ifdef __cplusplus
extern "C" {
#endif

typedef enum {
    TPU_ICI_LINK_DOWN = 0,
    TPU_ICI_LINK_TRAINING = 1,
    TPU_ICI_LINK_ACTIVE = 2,
    TPU_ICI_LINK_FAILED = 3,
} TpuIciLinkState;

typedef struct {
    uint32_t peerInst;          /* device at the other end */
    uint32_t state;             /* TpuIciLinkState */
    uint64_t trainedAtNs;
    uint64_t bytesTx, bytesRx;
    uint32_t errorCount;
} TpuIciLinkInfo;

/* Topology init: arranges the enumerated devices in a torus.  Dims come
 * from registry "ici_torus_x" / "ici_torus_y" (default: 1-D ring over
 * all devices).  Idempotent; called lazily by every other entry point. */
void tpuIciInit(void);

/* Number of ICI links on a device (2 per torus dimension with >2 nodes). */
uint32_t tpuIciLinkCount(uint32_t devInst);
TpuStatus tpuIciLinkInfo(uint32_t devInst, uint32_t link,
                         TpuIciLinkInfo *out);

/* Train a link (DOWN -> TRAINING -> ACTIVE) or all links of a device.
 * Reference: nvlink_lib_mgmt.c init sequences. */
TpuStatus tpuIciTrainLinks(uint32_t devInst);

/* Fault injection: fail a link; routes avoid FAILED links where the
 * torus offers an alternative dimension. */
TpuStatus tpuIciInjectLinkFailure(uint32_t devInst, uint32_t link);
TpuStatus tpuIciResetLink(uint32_t devInst, uint32_t link);

/* Dimension-ordered next hop from src toward dst; TPU_ERR_* when no
 * route (e.g. partitioned by failures).  next==dst on the last hop. */
TpuStatus tpuIciRouteNextHop(uint32_t src, uint32_t dst, uint32_t *next);
/* Hop count src -> dst along the routed path (0 when src == dst). */
TpuStatus tpuIciRouteHops(uint32_t src, uint32_t dst, uint32_t *hops);

/* ------------------------------------------------------ peer apertures */

/* Map peer HBM into src's reachable address space.  Requires every link
 * along the route ACTIVE.  The returned aperture is the substrate for
 * peer DMA: tpuIciPeerCopy moves bytes between devices' HBM windows,
 * accounting traffic on the traversed links. */
typedef struct TpuIciPeerAperture TpuIciPeerAperture;

TpuStatus tpuIciPeerApertureCreate(uint32_t srcInst, uint32_t peerInst,
                                   TpuIciPeerAperture **out);
void      tpuIciPeerApertureDestroy(TpuIciPeerAperture *ap);
/* Copy between local HBM offset and peer HBM offset over the aperture
 * (direction: 0 = local->peer write, 1 = peer->local read).
 * SUBMISSION SPINE: publishes the copy as a PEER_COPY SQE on the
 * process-global internal memring and waits — ICI transfers land in
 * the same worker pool as every other memory op (single observable
 * dispatch path; the multi-hop store-and-forward pipeline runs inside
 * the op's execution). */
TpuStatus tpuIciPeerCopy(TpuIciPeerAperture *ap, uint64_t localOff,
                         uint64_t peerOff, uint64_t size, int direction);
/* Async variant: records the push in `tracker` instead of waiting, so ICI
 * peer copies synchronize with CE and CXL work through one dependency
 * object (reference: uvm_tracker.c).  tracker == NULL waits — via the
 * memring spine, exactly tpuIciPeerCopy. */
TpuStatus tpuIciPeerCopyAsync(TpuIciPeerAperture *ap, uint64_t localOff,
                              uint64_t peerOff, uint64_t size, int direction,
                              TpuTracker *tracker);
/* The synchronous copy ENGINE entry (direct single/multi-hop execution).
 * Only the memring spine workers may call this (`make -C native
 * check-spine`); everyone else goes through tpuIciPeerCopy. */
TpuStatus tpuIciPeerCopyExec(TpuIciPeerAperture *ap, uint64_t localOff,
                             uint64_t peerOff, uint64_t size,
                             int direction);

#ifdef __cplusplus
}
#endif

#endif /* TPURM_ICI_H */
