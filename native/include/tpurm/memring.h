/*
 * tpumemring — io_uring-style asynchronous memory-op submission and
 * completion rings (the paper's namesake capability: CXLMemUring's
 * ring-based asynchronous offload of far-memory operations, shaped
 * after Linux io_uring / liburing).
 *
 * A ring is a fixed-size SHARED-MEMORY region (one memfd: header page,
 * then the SQ array, then the CQ array) holding two power-of-two rings
 * of cacheline-sized entries:
 *
 *   SQ — submission queue.  The producer fills TpuMemringSqe slots
 *        (tpurmMemringPrep), then publishes a whole batch with ONE
 *        tpurmMemringSubmit: a release store of sqTail plus a futex
 *        wake on the doorbell word.  No locks on the producer side.
 *   CQ — completion queue.  The worker pool posts one TpuMemringCqe
 *        per SQE (status, bytes moved, the user_data cookie echoed
 *        back) and futex-wakes the cqReady word; the consumer reaps
 *        with tpurmMemringReap / parks with tpurmMemringWait.
 *
 * A small worker pool (registry "memring_workers", default 2) drains
 * SQEs into the existing engines: MIGRATE/PREFETCH/EVICT/ADVISE run
 * against the ring's UVM VA space, PEER_COPY against the ICI peer
 * aperture substrate.  Workers BATCH: a popped run of compatible
 * non-linked ops (same opcode/destination, virtually contiguous) is
 * coalesced into one engine call — one VA-space lock acquisition and
 * one block-granular make_resident walk instead of one per span.
 * That coalescing is where the async ring beats the synchronous
 * uvmMigrate loop (bench.py memring microbench), exactly the paper's
 * batched-offload claim.
 *
 * Ordering (weakest to strongest):
 *   DEPENDENCY SETS — the reference's uvm_tracker_t shape (a tracker
 *        is a SET of (channel, value) pairs, not a linear chain): every
 *        SQE carries up to TPU_MEMRING_SQE_NDEPS wait-on-(ring, seq)
 *        dependencies (tpurmMemringSqeDep).  A worker claims an SQE
 *        only once every dep has RETIRED; everything else in the ring
 *        streams past it out of order.  Each ring keeps a retirement
 *        frontier — hdr.seqRetired is the watermark below which every
 *        seq has retired, and a sparse done-set covers the holes that
 *        out-of-order retirement opens above it — which dep checks
 *        read lock-free.  A dep whose target retired with an ERROR
 *        cancels the dependent (TPU_ERR_INVALID_STATE CQE, counted
 *        memring_dep_cancelled), mirroring chain-cancel semantics.
 *        An ORDERED dep (TPU_MEMRING_DEP_ORDERED) waits for the
 *        frontier itself — every seq <= target retired — the per-SQE
 *        IO_DRAIN used as the wide-join fallback when 4 dep slots are
 *        not enough.
 *   TPU_MEMRING_SQE_LINK — io_uring IOSQE_LINK analog: the next SQE
 *        starts only after this one completes; a failure cancels every
 *        remaining entry of the chain (their CQEs post
 *        TPU_ERR_INVALID_STATE with bytes = 0).  A chain must be
 *        published by a single tpurmMemringSubmit call; the publication
 *        boundary terminates a chain.  Chains are claimed WHOLE by one
 *        worker — everything queued behind a long chain waits for that
 *        claim — so new code should prefer dep sets and reserve LINK
 *        for spans that genuinely need single-claimant execution
 *        (make -C native check-spine enforces the allowlist).
 *   TPU_MEMRING_OP_FENCE — completes only after every previously
 *        submitted SQE has posted its CQE (io_uring IOSQE_IO_DRAIN
 *        analog: later SQEs do not begin until the fence retires).
 *
 * Failure recovery: every op execution evaluates the memring.submit
 * injection site (inject.h) and wraps the engine call in a bounded
 * backoff retry (registry "memring_retry_max", default 3).  Retry
 * exhaustion posts an ERROR CQE carrying the failing TpuStatus —
 * errors surface per-op through the CQ instead of tearing down the
 * ring.  Recovery is counted (memring_retries / memring_error_cqes /
 * memring_inject_retries / memring_inject_error_runs) and traced
 * (memring.submit + memring.op spans, recover.retry instants).
 *
 * CQ overflow: when the consumer leaves the CQ full, new CQEs are
 * DROPPED and counted (hdr.cqOverflows / "memring_cq_overflows") —
 * fences and completion accounting still advance, so a slow reaper
 * can never deadlock the pool (io_uring's overflow accounting).
 *
 * Reset integration (tpurm/reset.h): a full-device reset PARKS the
 * worker pools (claimed ops drain bounded; published-but-unclaimed
 * SQEs stay queued and replay after resume — every opcode is
 * idempotent by design), and every claim records the device
 * generation it executed under: a completion that crosses a
 * generation bump (possible only when quiesce timed out on a hung op)
 * posts TPU_ERR_DEVICE_RESET instead of its result and is counted
 * (memring_stale_completions) — a zombie's late completion can never
 * masquerade as valid post-reset state.
 *
 * THE SUBMISSION SPINE (kernel-internal submission): memring is the
 * single dispatch path for ALL internal memory traffic, not just
 * userspace rings.  In-process subsystems — the fault-service batches
 * (uvm_fault.c), explicit migrations (uvmMigrate), the tier manager's
 * fused evict+upload pairs, and ICI peer transfers (tpuIciPeerCopy) —
 * prep SQE chains and publish them on one process-global INTERNAL ring
 * via tpurmMemringSubmitInternal, with no memfd round-trip.  The
 * internal ring defaults to ZERO dedicated workers: the submitter
 * publishes, then HELPS DRAIN the ring (claiming batches like any
 * worker) until its own group completes — on an idle ring this is the
 * old synchronous call plus one claim/post, while under load the
 * claims interleave with other submitters' chains and the worker-side
 * coalescer merges cross-subsystem runs to the same destination.
 * Accounting invariant (chaos-soak-checked): memring_internal_sqes ==
 * sum over subsystems of memring_internal_sqes[<subsys>].
 *
 * SQPOLL (io_uring SQPOLL idiom): registry "memring_sqpoll" != 0 puts
 * ring workers into an always-polling mode — an idle worker registers
 * in hdr.sqPollers and spins on sqTail for "memring_sqpoll_idle_us"
 * (default 500) before falling back to the futex sleep, so hot-path
 * submitters publish with a single release store and ZERO doorbell
 * futex syscalls (tpurmMemringSubmit skips the FUTEX_WAKE whenever a
 * poller is registered; the poller's deregister-then-recheck protocol
 * makes a lost wakeup impossible).  The idle timeout exists because an
 * always-spinning worker on a 1-2 CPU container would starve the very
 * engines it drains — memring_sqpoll_polls / memring_sqpoll_sleeps
 * count the duty cycle.  With sqpoll armed the internal ring also gets
 * dedicated polling workers (registry "memring_sqpoll_workers",
 * default 1) so internal submitters need not help-drain at all.
 */
#ifndef TPURM_MEMRING_H
#define TPURM_MEMRING_H

#include <stdint.h>

#include "status.h"

#ifdef __cplusplus
extern "C" {
#endif

struct UvmVaSpace;

/* ------------------------------------------------------------- opcodes */

enum {
    TPU_MEMRING_OP_NOP = 0,       /* completes immediately (testing)    */
    TPU_MEMRING_OP_MIGRATE = 1,   /* uvmMigrate(addr, len) -> dst tier  */
    TPU_MEMRING_OP_PREFETCH = 2,  /* uvmDeviceAccess: fault span onto
                                   * devInst's HBM (read unless WRITE)  */
    TPU_MEMRING_OP_EVICT = 3,     /* tier demote: migrate to dstTier
                                   * (HOST or CXL only)                 */
    TPU_MEMRING_OP_ADVISE = 4,    /* policy op, subcode in arg0         */
    TPU_MEMRING_OP_PEER_COPY = 5, /* ICI peer copy local<->peer HBM     */
    TPU_MEMRING_OP_FENCE = 6,     /* completes after all prior CQEs     */
    /* Internal-only opcodes (rejected by tpurmMemringPrep on userspace
     * rings; reachable only through tpurmMemringSubmitInternal): */
    TPU_MEMRING_OP_FAULT = 7,     /* service one UvmFaultEntry (addr =
                                   * entry pointer; fault batches chain
                                   * one of these per pending fault)    */
    TPU_MEMRING_OP_TIER_EVICT = 8,/* free >= len bytes from the (dstTier,
                                   * devInst) arena by LRU eviction —
                                   * best-effort, the fused half of an
                                   * EVICT->MIGRATE chain               */
    TPU_MEMRING_OP_COUNT
};

#define TPU_MEMRING_OP_INTERNAL_BASE TPU_MEMRING_OP_FAULT

/* SQE flags.  LINK chains are capped at 64 entries (one worker claim,
 * so claimed-whole execution holds); a longer chain fails prep with
 * TPU_ERR_INVALID_LIMIT. */
#define TPU_MEMRING_SQE_LINK  0x1u  /* chain with the NEXT sqe          */
#define TPU_MEMRING_SQE_WRITE 0x2u  /* PREFETCH faults for write        */

/* --------------------------------------------------- dependency handles
 *
 * A dep is one u64: the target ring's id (tpurmMemringId) in the top
 * 16 bits, the target SQE's submission seq (assigned by prep, read
 * back from TpuMemringSqe.seq) in the low 47, and the ORDERED flag at
 * bit 47.  Seqs count SQEs per ring from 0 and never wrap in practice
 * (2^47 per ring).
 *
 *   plain dep    — satisfied when THAT seq has retired (holes in the
 *                  retirement frontier count: out-of-order retirement
 *                  satisfies it as early as possible);
 *   ORDERED dep  — satisfied when EVERY seq <= target has retired
 *                  (frontier watermark passed it): the per-SQE drain
 *                  used to join a wide set with one dep slot;
 *   BATCH ring id — the pseudo-target for intra-batch edges: seq is an
 *                  INDEX into the current batch (must point backwards)
 *                  and is rewritten to the absolute (ring, seq) pair at
 *                  stage time, by tpurmMemringPrep for userspace rings
 *                  (index relative to the first SQE prepped after the
 *                  last submit) and by tpurmMemringSubmitInternal for
 *                  spine batches.
 *
 * Deps must be written through tpurmMemringSqeDep BEFORE the SQE is
 * prepped: prep copies the SQE into the shared SQ and submit's release
 * store of sqTail is the publish barrier that makes the dep set
 * visible to workers (check-spine lints direct .deps[] writes). */
#define TPU_MEMRING_SQE_NDEPS 4
#define TPU_MEMRING_DEP_SEQ_BITS 47
#define TPU_MEMRING_DEP_SEQ_MASK ((1ull << TPU_MEMRING_DEP_SEQ_BITS) - 1)
#define TPU_MEMRING_DEP_ORDERED  (1ull << TPU_MEMRING_DEP_SEQ_BITS)
#define TPU_MEMRING_DEP_RING_SHIFT 48
#define TPU_MEMRING_DEP_BATCH 0xFFFFu   /* intra-batch index pseudo-ring */

#define TPU_MEMRING_DEP(ringId, seq)                                     \
    (((uint64_t)(uint16_t)(ringId) << TPU_MEMRING_DEP_RING_SHIFT) |      \
     ((uint64_t)(seq) & TPU_MEMRING_DEP_SEQ_MASK))
#define TPU_MEMRING_DEP_RING(d) ((uint32_t)((d) >> TPU_MEMRING_DEP_RING_SHIFT))
#define TPU_MEMRING_DEP_SEQ(d)  ((d) & TPU_MEMRING_DEP_SEQ_MASK)

/* ADVISE subcodes (sqe.arg0). */
enum {
    TPU_MEMRING_ADVISE_PREFERRED = 1,        /* dstTier / devInst       */
    TPU_MEMRING_ADVISE_UNSET_PREFERRED = 2,
    TPU_MEMRING_ADVISE_ACCESSED_BY = 3,      /* devInst                 */
    TPU_MEMRING_ADVISE_UNSET_ACCESSED_BY = 4,
    TPU_MEMRING_ADVISE_READ_DUP = 5,         /* arg1: 0 off / 1 on      */
    TPU_MEMRING_ADVISE_COMPRESSIBLE = 6,     /* arg1: UVM_ADVISE_
                                              * COMPRESSIBLE_* format   */
};

/* PEER_COPY direction (sqe.arg0): 0 local->peer, 1 peer->local. */
#define TPU_MEMRING_PEER_WRITE 0u
#define TPU_MEMRING_PEER_READ  1u

/* --------------------------------------------------------- ring entries */

/* Submission entry — exactly two cachelines (io_uring SQE128 shape:
 * the dependency set did not fit the original 64; hdr.sqeSize carries
 * the size for external mappers). */
typedef struct {
    uint8_t  opcode;              /* TPU_MEMRING_OP_*                   */
    uint8_t  flags;               /* TPU_MEMRING_SQE_*                  */
    uint16_t dstTier;             /* UvmTier for MIGRATE/EVICT/ADVISE   */
    uint32_t devInst;             /* HBM target / faulting device /
                                   * PEER_COPY local device             */
    uint64_t addr;                /* managed VA; PEER_COPY: local HBM
                                   * arena offset                       */
    uint64_t len;                 /* bytes                              */
    uint64_t userData;            /* echoed in the CQE                  */
    uint32_t peerInst;            /* PEER_COPY remote device            */
    uint32_t arg0;                /* ADVISE subcode / PEER direction    */
    uint64_t peerOff;             /* PEER_COPY peer HBM arena offset    */
    uint64_t arg1;                /* ADVISE READ_DUP on/off; NOP: an
                                   * execution delay in ns (test/pacing
                                   * knob for the hung-op machinery)    */
    uint64_t deadlineNs;          /* 0 = none; absolute tpuNowNs
                                   * deadline — an op claimed past it
                                   * posts TPU_ERR_RETRY_EXHAUSTED
                                   * without executing (counted
                                   * memring_deadline_expired).  The
                                   * hung-op watchdog (tpurm/reset.h)
                                   * escalates ops stuck in flight.    */
    /* --- second cacheline: the dependency set (tracker semantics) --- */
    uint64_t deps[TPU_MEMRING_SQE_NDEPS]; /* TPU_MEMRING_DEP handles;
                                   * write via tpurmMemringSqeDep      */
    uint32_t depCount;            /* valid deps[] entries (<= NDEPS)   */
    uint32_t rsvd0;
    uint64_t seq;                 /* OUT: submission seq assigned by
                                   * prep (input ignored) — the handle
                                   * later SQEs name this op by        */
    uint64_t flowId;              /* tpuflow request identity
                                   * (tpurm/flow.h: tenant<<48 |
                                   * request<<16 | hop; 0 = none).
                                   * Workers set the thread flow
                                   * context from it around execution,
                                   * so nested engine spans (ce
                                   * stripes, fault service, ICI hops)
                                   * inherit the identity, and the
                                   * exec layer accounts the op's
                                   * wall into the flow's copy/ici
                                   * blame bucket.  Lived in the
                                   * reserved spare bytes: the 128-B
                                   * SQE ABI is unchanged.            */
    uint64_t rsvd1;
} TpuMemringSqe;

/* Completion entry — exactly one cacheline. */
typedef struct {
    uint64_t userData;            /* cookie from the SQE                */
    uint32_t status;              /* TpuStatus (TPU_OK on success)      */
    uint32_t opcode;              /* the completed op                   */
    uint64_t bytes;               /* bytes the op moved                 */
    uint64_t seq;                 /* pop order (FIFO submission order)  */
    uint64_t startNs, endNs;      /* execution window, tpuNowNs clock   */
    uint64_t pad[2];
} TpuMemringCqe;

/* Shared-memory header (page 0 of the ring memfd).  The producer owns
 * sqTail (release-published), the worker pool owns sqHead and cqTail,
 * the consumer owns cqHead.  doorbell / cqReady are futex words bumped
 * on submit / CQE post. */
#ifdef __cplusplus
#define TPU_MEMRING_ATOMIC_U32 uint32_t
#define TPU_MEMRING_ATOMIC_U64 uint64_t
#else
#define TPU_MEMRING_ATOMIC_U32 _Atomic uint32_t
#define TPU_MEMRING_ATOMIC_U64 _Atomic uint64_t
#endif
typedef struct {
    TPU_MEMRING_ATOMIC_U32 sqHead;
    TPU_MEMRING_ATOMIC_U32 sqTail;
    TPU_MEMRING_ATOMIC_U32 cqHead;
    TPU_MEMRING_ATOMIC_U32 cqTail;
    uint32_t sqEntries;           /* power of two                       */
    uint32_t cqEntries;           /* power of two (2x sqEntries)        */
    uint32_t sqeSize, cqeSize;    /* ABI sanity for mapped consumers    */
    TPU_MEMRING_ATOMIC_U32 doorbell;
    TPU_MEMRING_ATOMIC_U32 cqReady;
    /* Consumers parked (or about to park) on cqReady.  Workers wake
     * the futex only when nonzero (io_uring's SQ_NEED_WAKEUP shape),
     * so the per-CQE post path costs no syscall without a waiter. */
    TPU_MEMRING_ATOMIC_U32 cqWaiters;
    TPU_MEMRING_ATOMIC_U64 submitted;    /* SQEs ever published         */
    TPU_MEMRING_ATOMIC_U64 completed;    /* CQEs ever posted            */
    TPU_MEMRING_ATOMIC_U64 errorCqes;    /* CQEs with status != TPU_OK  */
    TPU_MEMRING_ATOMIC_U64 cqOverflows;  /* CQEs dropped, CQ full       */
    /* SQPOLL: workers currently busy-polling sqTail.  Nonzero lets the
     * submit path skip the doorbell FUTEX_WAKE syscall entirely (the
     * inverse of io_uring's SQ_NEED_WAKEUP bit).  Appended after the
     * original header fields so pre-SQPOLL external mappers keep their
     * offsets. */
    TPU_MEMRING_ATOMIC_U32 sqPollers;
    /* Dependency-tracker fields (appended, same offset-stability
     * argument).  seqRetired is the RETIREMENT FRONTIER: every
     * submission seq < seqRetired has posted its completion.  Holes
     * above it (out-of-order retirement) live in a ring-private
     * done-set; dep checks read the watermark with one acquire load. */
    uint32_t ringId;              /* this ring's dep-handle identity    */
    uint32_t rsvdHdr;
    TPU_MEMRING_ATOMIC_U64 seqRetired;
} TpuMemringHdr;

#define TPU_MEMRING_SQ_OFFSET 4096

/* ----------------------------------------------------------------- API */

typedef struct TpuMemring TpuMemring;

/* Create a ring bound to `vs` (the VA space MIGRATE/PREFETCH/EVICT/
 * ADVISE execute against; PEER_COPY and NOP/FENCE work with vs == NULL).
 * sqEntries is rounded up to a power of two (default 256 when 0); the
 * CQ holds 2x.  workers == 0 takes registry "memring_workers"
 * (default 2).  The ring pins `vs`: destroy the ring before the space. */
TpuStatus tpurmMemringCreate(struct UvmVaSpace *vs, uint32_t sqEntries,
                             uint32_t workers, TpuMemring **out);
void      tpurmMemringDestroy(TpuMemring *r);

/* Stage one SQE into the next free SQ slot (NOT yet visible to the
 * workers).  TPU_ERR_INSUFFICIENT_RESOURCES when the SQ is full — or
 * when the retirement frontier lags too far behind the staged tail
 * (the done-set window is finite) — submit and reap first either way.
 * Writes the assigned submission seq into sqe->seq (and rewrites any
 * BATCH-relative deps to absolute handles); a BATCH dep that points
 * at or past this SQE fails with TPU_ERR_INVALID_ARGUMENT. */
TpuStatus tpurmMemringPrep(TpuMemring *r, TpuMemringSqe *sqe);

/* Append one dependency handle to a not-yet-prepped SQE.  The ONLY
 * sanctioned writer of sqe->deps[] (check-spine lints raw writes):
 * deps staged here are published by prep's copy into the SQ plus
 * submit's sqTail release store.  TPU_ERR_INVALID_LIMIT once the
 * fixed set is full — join wider through an ORDERED dep or a FENCE. */
TpuStatus tpurmMemringSqeDep(TpuMemringSqe *sqe, uint64_t dep);

/* This ring's dep-handle identity (TPU_MEMRING_DEP ring id). */
uint32_t tpurmMemringId(TpuMemring *r);

/* The submission seq the NEXT tpurmMemringPrep on this ring will
 * assign (producer-side; producers are single-threaded per ring). */
uint64_t tpurmMemringNextSeq(TpuMemring *r);

/* Publish every staged SQE (one release store + doorbell futex wake);
 * returns the number newly submitted. */
uint32_t  tpurmMemringSubmit(TpuMemring *r);

/* Submit, then block until at least waitFor CQEs are reapable
 * (waitFor == 0: no wait).  Returns the number submitted.  The wait's
 * status lands in *waitStatus when non-NULL (TPU_OK, or the timeout /
 * CQ-overflow bail from tpurmMemringWait — the Python surface raises
 * on it); passing NULL keeps the old discard-the-status convenience
 * for reap-everything callers. */
uint32_t  tpurmMemringSubmitAndWait(TpuMemring *r, uint32_t waitFor,
                                    TpuStatus *waitStatus);

/* Reap up to max completions into out; returns the count reaped. */
uint32_t  tpurmMemringReap(TpuMemring *r, TpuMemringCqe *out, uint32_t max);

/* Park until at least n CQEs are reapable or timeoutNs elapses
 * (timeoutNs == 0: wait forever).  TPU_OK when n are reapable;
 * TPU_ERR_RETRY_EXHAUSTED on timeout;
 * TPU_ERR_INSUFFICIENT_RESOURCES when the wait can never be satisfied
 * because CQEs were dropped on CQ overflow (nothing left in flight). */
TpuStatus tpurmMemringWait(TpuMemring *r, uint32_t n, uint64_t timeoutNs);

/* Park until EVERY SQE submitted so far has posted its CQE
 * (completed == submitted) or timeoutNs elapses (0: wait forever).
 * Unlike tpurmMemringWait this keys off completion COUNTS, not
 * reapable CQEs, so unreaped backlog can't satisfy it early and CQ
 * overflow can't starve it.  TPU_OK on drain;
 * TPU_ERR_RETRY_EXHAUSTED on timeout. */
TpuStatus tpurmMemringWaitDrain(TpuMemring *r, uint64_t timeoutNs);

/* Free SQ slots available for tpurmMemringPrep. */
uint32_t  tpurmMemringSqSpace(TpuMemring *r);

/* Lifetime accounting (also visible in the shared header). */
void tpurmMemringCounts(TpuMemring *r, uint64_t *submitted,
                        uint64_t *completed, uint64_t *errorCqes,
                        uint64_t *cqOverflows);

/* The memfd backing the ring region (header + SQ + CQ): map it for
 * external observation; dup before shipping cross-process. */
int tpurmMemringShmFd(TpuMemring *r);

/* ------------------------------------------------ kernel-internal spine */

/* Per-subsystem accounting tags for internal submissions (scoped
 * counters memring_internal_sqes[<tag>]). */
enum {
    TPU_MEMRING_SUBSYS_FAULT = 0,   /* fault-service chains           */
    TPU_MEMRING_SUBSYS_TIER,        /* tier evict / fused evict half  */
    TPU_MEMRING_SUBSYS_ICI,         /* ICI peer transfers             */
    TPU_MEMRING_SUBSYS_MIGRATE,     /* explicit uvmMigrate traffic    */
    TPU_MEMRING_SUBSYS_COUNT
};

/* Publish sqes[0..n) on the process-global internal ring as ONE batch
 * (LINK flags inside the batch are honored; the final entry's LINK is
 * cleared — the batch is the publication boundary.  BATCH-relative
 * deps are rewritten to absolute handles against the seqs the batch's
 * ops are assigned at stage time, so producers express intra-batch
 * DAGs by index — TPU_MEMRING_DEP(TPU_MEMRING_DEP_BATCH, i) — without
 * knowing the ring's seq counter) and block until all
 * n ops complete.  `vs` is the VA space the batch's MIGRATE/PREFETCH/
 * EVICT/ADVISE/TIER_EVICT ops execute against (rides a per-op side
 * slot, so batches from different spaces interleave on the one ring);
 * OP_FAULT carries its entry pointer in sqe.addr and ignores vs of
 * other subsystems' runs when coalescing.  stOut, when non-NULL, takes
 * n per-op statuses (chain-cancelled ops report
 * TPU_ERR_INVALID_STATE).  Returns the first non-OK status in the
 * batch, TPU_OK otherwise.
 *
 * Execution: with zero internal workers (default) the CALLER drains
 * the ring until its group completes (submit-and-help); with SQPOLL or
 * "memring_internal_workers" > 0 dedicated workers drain it.  Called
 * from inside a memring worker (a dependent submission) or while the
 * pools are reset-parked, the batch executes INLINE on the caller —
 * still counted (memring_internal_inline) — so dependent work can
 * never deadlock the pool and quiesce is never bypassed by a queued
 * ghost. */
TpuStatus tpurmMemringSubmitInternal(struct UvmVaSpace *vs,
                                     const TpuMemringSqe *sqes, uint32_t n,
                                     TpuStatus *stOut, uint32_t subsys);

#ifdef __cplusplus
}
#endif

#endif /* TPURM_MEMRING_H */
