/*
 * tpurm UVM — the managed-memory engine (TPU re-design of nvidia-uvm).
 *
 * Capability surface reproduced from the reference (SURVEY.md §2.2):
 *   - per-fd VA space with registered devices and a VA range tree
 *     (reference: kernel-open/nvidia-uvm/uvm_va_space.c),
 *   - 2 MB VA blocks with per-page residency masks across three tiers —
 *     HOST, device HBM, CXL (reference: uvm_va_block.c, per-page residency
 *     state machine around uvm_va_block_make_resident:5086),
 *   - PMM chunk allocator with eviction for oversubscription (reference:
 *     uvm_pmm_gpu.c, chunk sizes uvm_pmm_gpu.h:60-85),
 *   - fault-driven migration with a batched service loop (reference:
 *     uvm_gpu_replayable_faults.c:2906 — fetch/coalesce/preprocess/
 *     service/replay), here driven by software faults (SIGSEGV + futex
 *     handoff for CPU accesses; explicit device-access notifications for
 *     DMA traffic — TPUs expose no replayable-fault HW buffer, SURVEY.md
 *     §7 step 4),
 *   - migration policies: preferred location, accessed-by, read
 *     duplication, range groups (reference: uvm_va_policy.c,
 *     uvm_range_group.c),
 *   - perf heuristics: prefetch region growth and thrashing detection
 *     (reference: uvm_perf_prefetch.c, uvm_perf_thrashing.h:33-46),
 *   - tools event queues + counters (reference: uvm_tools.c:54-70),
 *   - an in-module test framework dispatched by UVM_RUN_TEST (reference:
 *     uvm_test.c:241-312).
 *
 * ABI: the UVM_* ioctl numbers and param layouts below are the reference's
 * stable userspace ABI (kernel-open/nvidia-uvm/uvm_ioctl.h,
 * uvm_linux_ioctl.h:32-40) so reference userspace runs unchanged against
 * the tpurm escape surface ("/dev/nvidia-uvm" via tpurm_open/tpurm_ioctl).
 * The direct C API (uvm* functions) is the TPU-native in-process surface
 * the Python runtime binds.
 */
#ifndef TPURM_UVM_H
#define TPURM_UVM_H

#include <stdbool.h>
#include <stddef.h>
#include <stdint.h>

#include "status.h"
#include "tpurm.h"

#ifdef __cplusplus
extern "C" {
#endif

/* ============================== ABI (reference uvm_ioctl.h numbers) ===== */

#define UVM_INITIALIZE                    0x30000001
#define UVM_DEINITIALIZE                  0x30000002
#define UVM_RUN_TEST                      9
#define UVM_CREATE_RANGE_GROUP            23
#define UVM_DESTROY_RANGE_GROUP           24
#define UVM_SET_RANGE_GROUP               31
#define UVM_FREE                          34
#define UVM_REGISTER_GPU                  37
#define UVM_UNREGISTER_GPU                38
#define UVM_PAGEABLE_MEM_ACCESS           39
#define UVM_PREVENT_MIGRATION_RANGE_GROUPS 40
#define UVM_ALLOW_MIGRATION_RANGE_GROUPS  41
#define UVM_SET_PREFERRED_LOCATION        42
#define UVM_UNSET_PREFERRED_LOCATION      43
#define UVM_ENABLE_READ_DUPLICATION       44
#define UVM_DISABLE_READ_DUPLICATION      45
#define UVM_SET_ACCESSED_BY               46
#define UVM_UNSET_ACCESSED_BY             47
#define UVM_MIGRATE                       51
#define UVM_TOOLS_INIT_EVENT_TRACKER      56
#define UVM_TOOLS_SET_NOTIFICATION_THRESHOLD 57
#define UVM_TOOLS_EVENT_QUEUE_ENABLE_EVENTS  58
#define UVM_TOOLS_EVENT_QUEUE_DISABLE_EVENTS 59
#define UVM_TOOLS_ENABLE_COUNTERS         60
#define UVM_TOOLS_DISABLE_COUNTERS        61
#define UVM_MAP_EXTERNAL_ALLOCATION       33
#define UVM_TOOLS_GET_PROCESSOR_UUID_TABLE 64
#define UVM_UNMAP_EXTERNAL                66
#define UVM_TOOLS_FLUSH_EVENTS            67
#define UVM_CREATE_EXTERNAL_RANGE         73

/* TPU extensions (outside the reference's number space, documented): the
 * reference creates managed ranges via mmap of the uvm fd; the tpurm escape
 * surface has no kernel mmap hook, so managed alloc/free are explicit. */
#define UVM_TPU_ALLOC_MANAGED             1001
#define UVM_TPU_DEVICE_ACCESS             1002
#define UVM_TPU_RESIDENCY_INFO            1003
#define UVM_TPU_ADOPT_PAGEABLE            1004
#define UVM_TPU_SET_COMPRESSIBLE          1005
#define UVM_TPU_SET_TENANT                1006

/* UVM_ADVISE_COMPRESSIBLE values (UvmTpuSetCompressibleParams.format,
 * uvmSetCompressible, memring ADVISE subcode COMPRESSIBLE).  Numeric
 * values match ce.h TPU_CE_COMP_* formats. */
#define UVM_ADVISE_COMPRESSIBLE_OFF       0   /* lossless (default)     */
#define UVM_ADVISE_COMPRESSIBLE_FP8       1   /* fp8 e4m3 quantization  */
#define UVM_ADVISE_COMPRESSIBLE_INT8      2   /* int8, per-stripe scale */

#define UVM_MIGRATE_FLAG_ASYNC            0x00000001

/* Processor addressing (reference: NvProcessorUuid).  CPU = all zeros;
 * TPU device i = "TPU\0" + LE32(inst); CXL tier = "CXL\0". */
typedef struct {
    uint8_t uuid[16];
} UvmProcessorUuid;

typedef struct {
    uint64_t flags;
    TpuStatus rmStatus;
} UvmInitializeParams;

typedef struct {
    UvmProcessorUuid gpuUuid;       /* IN/OUT */
    uint8_t  numaEnabled;           /* OUT */
    int32_t  numaNodeId;            /* OUT */
    int32_t  rmCtrlFd;              /* IN (unused here) */
    uint32_t hClient;               /* IN (unused here) */
    uint32_t hSmcPartRef;           /* IN (unused here) */
    TpuStatus rmStatus;             /* OUT */
} UvmRegisterGpuParams;

typedef struct {
    UvmProcessorUuid gpuUuid;
    TpuStatus rmStatus;
} UvmUnregisterGpuParams;

typedef struct {
    uint64_t base       __attribute__((aligned(8)));
    uint64_t length     __attribute__((aligned(8)));
    UvmProcessorUuid destinationUuid;
    uint32_t flags;
    uint64_t semaphoreAddress __attribute__((aligned(8)));
    uint32_t semaphorePayload;
    int32_t  cpuNumaNode;
    uint64_t userSpaceStart   __attribute__((aligned(8)));
    uint64_t userSpaceLength  __attribute__((aligned(8)));
    TpuStatus rmStatus;
} UvmMigrateParams;

typedef struct {
    uint64_t requestedBase __attribute__((aligned(8)));
    uint64_t length        __attribute__((aligned(8)));
    UvmProcessorUuid preferredLocation;
    int32_t  preferredCpuNumaNode;
    TpuStatus rmStatus;
} UvmSetPreferredLocationParams;

typedef struct {
    uint64_t requestedBase __attribute__((aligned(8)));
    uint64_t length        __attribute__((aligned(8)));
    TpuStatus rmStatus;
} UvmRangeOpParams;        /* UNSET_PREFERRED_LOCATION, {EN,DIS}ABLE_READ_DUPLICATION */

typedef struct {
    uint64_t requestedBase __attribute__((aligned(8)));
    uint64_t length        __attribute__((aligned(8)));
    UvmProcessorUuid accessedByUuid;
    TpuStatus rmStatus;
} UvmAccessedByParams;

typedef struct {
    uint64_t rangeGroupId  __attribute__((aligned(8)));   /* OUT (create) / IN */
    TpuStatus rmStatus;
} UvmRangeGroupParams;

typedef struct {
    uint64_t rangeGroupId  __attribute__((aligned(8)));
    uint64_t requestedBase __attribute__((aligned(8)));
    uint64_t length        __attribute__((aligned(8)));
    TpuStatus rmStatus;
} UvmSetRangeGroupParams;

typedef struct {
    uint64_t rangeGroupIds __attribute__((aligned(8)));   /* user ptr to u64[] */
    uint64_t numGroupIds   __attribute__((aligned(8)));
    TpuStatus rmStatus;
} UvmRangeGroupMigrationParams;  /* PREVENT/ALLOW_MIGRATION_RANGE_GROUPS */

typedef struct {
    uint64_t base __attribute__((aligned(8)));
    TpuStatus rmStatus;
} UvmFreeParams;

typedef struct {
    uint64_t length __attribute__((aligned(8)));          /* IN */
    uint64_t base   __attribute__((aligned(8)));          /* OUT */
    TpuStatus rmStatus;
} UvmTpuAllocManagedParams;

typedef struct {
    uint64_t base   __attribute__((aligned(8)));
    uint64_t length __attribute__((aligned(8)));
    UvmProcessorUuid processorUuid;  /* which device touches the range */
    uint32_t isWrite;
    TpuStatus rmStatus;
} UvmTpuDeviceAccessParams;

typedef struct {
    uint64_t address __attribute__((aligned(8)));         /* IN */
    /* OUT: residency of the page containing address, one flag per tier. */
    uint32_t residentHost;
    uint32_t residentHbm;
    uint32_t residentCxl;
    uint32_t residentRemote;  /* replica leased on a lender chip's HBM */
    uint32_t remoteLenderInst;
    uint32_t hbmDeviceInst;
    uint32_t cpuMapped;       /* host PTE currently valid (RW) */
    uint32_t pinnedTier;      /* thrashing pin, (uint32_t)-1 if none */
    uint64_t hbmOffset __attribute__((aligned(8)));  /* arena offset when
                                                      * residentHbm */
    TpuStatus rmStatus;
} UvmTpuResidencyInfoParams;

typedef struct {
    uint32_t testCmd;
    TpuStatus rmStatus;
} UvmRunTestParams;

typedef struct {
    uint64_t base   __attribute__((aligned(8)));   /* IN */
    uint64_t length __attribute__((aligned(8)));   /* IN */
    TpuStatus rmStatus;                            /* OUT */
} UvmAdoptPageableParams;

/* UVM_TPU_SET_COMPRESSIBLE: opt a span into (or out of) the tpuce
 * page-compression stage.  format is UVM_ADVISE_COMPRESSIBLE_*. */
typedef struct {
    uint64_t base   __attribute__((aligned(8)));   /* IN */
    uint64_t length __attribute__((aligned(8)));   /* IN */
    uint32_t format;                               /* IN */
    TpuStatus rmStatus;                            /* OUT */
} UvmTpuSetCompressibleParams;

/* UVM_TPU_SET_TENANT: configure tenant `tenantId` (priority + per-tier
 * page quotas) and bind the calling VA space to it.  The serving
 * scheduler's per-client QoS hook: quotas steer SLO-aware eviction
 * (over-quota tenants' cold blocks are victimized first), priority
 * orders victims among quota-compliant tenants (lower = evicted
 * earlier).  quota 0 = unlimited. */
typedef struct {
    uint32_t tenantId;                             /* IN (0 = default) */
    uint32_t priority;                             /* IN */
    uint64_t hbmQuotaPages __attribute__((aligned(8)));  /* IN */
    uint64_t cxlQuotaPages __attribute__((aligned(8)));  /* IN */
    TpuStatus rmStatus;                            /* OUT */
} UvmTpuSetTenantParams;

/* External ranges (reference: UVM_CREATE_EXTERNAL_RANGE_PARAMS,
 * uvm_ioctl.h:1042; UVM_UNMAP_EXTERNAL_PARAMS:935 — ours omits gpuUuid
 * because the mapped window is a CPU-visible alias, not a per-GPU VA). */
typedef struct {
    uint64_t base   __attribute__((aligned(8)));   /* IN */
    uint64_t length __attribute__((aligned(8)));   /* IN */
    TpuStatus rmStatus;                            /* OUT */
} UvmExternalRangeParams;

/* Map a dmabuf window into an external range (reference:
 * UVM_MAP_EXTERNAL_ALLOCATION_PARAMS, uvm_ioctl.h:491 — rmCtrlFd/
 * hClient/hMemory collapse to the dmabuf handle from tpuDmabufExport). */
typedef struct {
    uint64_t base         __attribute__((aligned(8)));  /* IN */
    uint64_t length       __attribute__((aligned(8)));  /* IN */
    uint64_t offset       __attribute__((aligned(8)));  /* IN: into buf */
    uint64_t dmabufHandle __attribute__((aligned(8)));  /* IN */
    TpuStatus rmStatus;                                 /* OUT */
} UvmMapExternalAllocationParams;

/* Processor UUID table (reference: uvm_ioctl.h:913): entry 0 = CPU,
 * then one per registered-visible device, then the CXL tier. */
typedef struct {
    uint64_t tablePtr __attribute__((aligned(8)));  /* IN: UvmProcessorUuid[] */
    uint64_t count    __attribute__((aligned(8)));  /* IN: capacity, OUT: n */
    TpuStatus rmStatus;                             /* OUT */
} UvmToolsGetProcessorUuidTableParams;

/* UVM_TOOLS_* param blocks (reference shapes, uvm_ioctl.h:822-948,
 * trimmed to the in-process session model: the reference's user-supplied
 * mmap'd queue buffers are replaced by the session ring, so the buffer
 * pointers are accepted but unused). */
typedef struct {
    uint64_t queueBuffer      __attribute__((aligned(8)));  /* unused */
    uint64_t queueBufferSize  __attribute__((aligned(8)));
    uint64_t controlBuffer    __attribute__((aligned(8)));  /* unused */
    UvmProcessorUuid processor;
    uint32_t allProcessors;
    uint32_t uvmFd;
    TpuStatus rmStatus;
} UvmToolsInitEventTrackerParams;

typedef struct {
    uint32_t notificationThreshold;
    TpuStatus rmStatus;
} UvmToolsSetNotificationThresholdParams;

typedef struct {
    uint64_t eventTypeFlags   __attribute__((aligned(8)));  /* bit per UvmEventType */
    TpuStatus rmStatus;
} UvmToolsEventControlParams;

typedef struct {
    uint64_t counterTypeFlags __attribute__((aligned(8)));  /* all-or-nothing */
    TpuStatus rmStatus;
} UvmToolsCountersParams;

typedef struct {
    TpuStatus rmStatus;
} UvmToolsFlushEventsParams;

/* ================================ direct C API (TPU-native surface) ===== */

typedef struct UvmVaSpace UvmVaSpace;

/* Memory tiers.  HOST/HBM/CXL mirror TpuAperture order (internal.h) so
 * those values convert 1:1; HBM is per-device, HOST/CXL are global.
 * REMOTE is the far rung BELOW local HBM: a lease on a healthy lender
 * chip's HBM arena holding a write-through REPLICA of the HOST copy
 * (tpusplit).  It has no aperture of its own — all data movement is
 * PEER_COPY SQEs on the submission spine — and never converts to a
 * TpuAperture. */
typedef enum {
    UVM_TIER_HOST = 0,
    UVM_TIER_HBM  = 1,
    UVM_TIER_CXL  = 2,
    UVM_TIER_REMOTE = 3,
    UVM_TIER_COUNT = 4,
} UvmTier;

typedef struct {
    UvmTier tier;
    uint32_t devInst;          /* meaningful for UVM_TIER_HBM */
} UvmLocation;

TpuStatus uvmVaSpaceCreate(UvmVaSpace **out);
void      uvmVaSpaceDestroy(UvmVaSpace *vs);

TpuStatus uvmRegisterDevice(UvmVaSpace *vs, uint32_t devInst);
TpuStatus uvmUnregisterDevice(UvmVaSpace *vs, uint32_t devInst);

/* Managed allocation: 2 MB-aligned VA, fault-populated on first touch. */
TpuStatus uvmMemAlloc(UvmVaSpace *vs, uint64_t size, void **outPtr);
TpuStatus uvmMemFree(UvmVaSpace *vs, void *ptr);

/* Explicit migration of [base, base+len) to dst (UvmMigrate analog).
 * SUBMISSION SPINE: this is a thin wrapper that publishes the span as
 * a MIGRATE SQE on the process-global internal memring (prefixed by a
 * fused TIER_EVICT when the destination arena is under pressure —
 * registry "memring_fused_evict", default on) and waits for the
 * completion, so every migration rides the one dispatch path where
 * batching/coalescing happen.  Semantics are unchanged: synchronous,
 * same status surface. */
TpuStatus uvmMigrate(UvmVaSpace *vs, void *base, uint64_t len,
                     UvmLocation dst, uint32_t flags);

/* The synchronous migration ENGINE entry.  Only the memring spine
 * workers may call this (enforced by `make -C native check-spine`);
 * everyone else goes through uvmMigrate. */
TpuStatus uvmMigrateExec(UvmVaSpace *vs, void *base, uint64_t len,
                         UvmLocation dst, uint32_t flags);

/* Spine hook: execute one pending fault entry (opaque UvmFaultEntry
 * pointer from the fault engine's OP_FAULT chains).  Runs the bounded
 * retry + cancel/quarantine pipeline and records the service
 * histograms; returns the entry's final service status. */
TpuStatus uvmFaultServiceExec(void *entry);

/* Spine hook (OP_TIER_EVICT): best-effort LRU eviction from the
 * (tier, devInst) arena until it can take `bytes` more, the fused
 * evict half of an EVICT->MIGRATE chain.  Returns bytes' worth of
 * arena space now free (0 when the tier has no arena). */
uint64_t uvmTierEvictBytes(uint32_t tier, uint32_t devInst,
                           uint64_t bytes);

/* Policy (uvm_va_policy.c analogs). */
TpuStatus uvmSetPreferredLocation(UvmVaSpace *vs, void *base, uint64_t len,
                                  UvmLocation loc);
TpuStatus uvmUnsetPreferredLocation(UvmVaSpace *vs, void *base, uint64_t len);
TpuStatus uvmSetAccessedBy(UvmVaSpace *vs, void *base, uint64_t len,
                           uint32_t devInst);
TpuStatus uvmUnsetAccessedBy(UvmVaSpace *vs, void *base, uint64_t len,
                             uint32_t devInst);
TpuStatus uvmSetReadDuplication(UvmVaSpace *vs, void *base, uint64_t len,
                                int enable);
/* UVM_ADVISE_COMPRESSIBLE: route host<->HBM copies of the span through
 * the tpuce quantize stage (format = UVM_ADVISE_COMPRESSIBLE_*; OFF
 * restores lossless).  A precision contract, not a hint: the span's
 * data will round-trip through fp8/int8 — only KV-cache-like payloads
 * that tolerate it may opt in. */
TpuStatus uvmSetCompressible(UvmVaSpace *vs, void *base, uint64_t len,
                             uint32_t format);

/* Range groups (uvm_range_group.c analog). */
TpuStatus uvmRangeGroupCreate(UvmVaSpace *vs, uint64_t *outId);
TpuStatus uvmRangeGroupDestroy(UvmVaSpace *vs, uint64_t id);
TpuStatus uvmRangeGroupSet(UvmVaSpace *vs, uint64_t id, void *base,
                           uint64_t len);
TpuStatus uvmRangeGroupSetMigratable(UvmVaSpace *vs, uint64_t id,
                                     int migratable);

/* Device access notification — the device-side fault source.  Ensures
 * [base, base+len) is resident in the device's HBM (faulting + migrating
 * non-resident pages through the batch service loop) and then returns.
 * This is what the DMA/copy paths call before touching managed memory. */
TpuStatus uvmDeviceAccess(UvmVaSpace *vs, uint32_t devInst, void *base,
                          uint64_t len, int isWrite);

/* Device-wrote invalidation (chip->host write side): a jitted
 * computation wrote HBM arena [off, off+bytes) on devInst — drop every
 * stale CPU/CXL duplicate of managed pages backed by the span and
 * revoke their user PTEs so the next CPU touch faults the chip truth
 * back.  Caller must have marked the span chip-dirty first
 * (tpurmHbmMarkChipDirty).  Returns pages invalidated. */
uint64_t uvmHbmDeviceWroteRange(uint32_t devInst, uint64_t off,
                                uint64_t bytes);

/* Introspection (UVM_TEST_VA_RESIDENCY_INFO analog, uvm_test.c:288). */
typedef struct {
    uint8_t residentHost, residentHbm, residentCxl;
    uint32_t hbmDeviceInst;
    uint8_t cpuMapped;
    uint8_t devMapped;        /* accessed-by device mapping established */
    uint8_t cancelled;        /* page detached by precise fault cancel */
    int32_t pinnedTier;       /* -1 if not pinned by thrashing mitigation */
    /* Arena offset of the page's HBM backing (valid when residentHbm):
     * lets real-arena clients address the same bytes on-chip. */
    uint64_t hbmOffset;
    /* REMOTE tier: page has a leased replica in a lender chip's HBM. */
    uint8_t residentRemote;
    uint32_t remoteLenderInst;    /* valid when residentRemote */
} UvmResidencyInfo;
TpuStatus uvmResidencyInfo(UvmVaSpace *vs, void *addr, UvmResidencyInfo *out);

/* ------------------------------------------- multi-process managed memory
 * A second process (a broker client) attaches a WINDOW onto the engine
 * host's managed range: the window maps the owner range's host-backing
 * memfd (shipped over SCM_RIGHTS), starts PROT_NONE, and CPU faults
 * forward over the broker to the owner engine — which services them in
 * the owner's VA space (migrating device-resident pages home into the
 * shared backing) — before the local protection opens.  Stance
 * (documented contract): coherence is enforced at FAULT granularity
 * while pages are host-resident; once the child holds an open window
 * page, a LATER owner-side migration device-ward does not revoke it
 * (no cross-process PTE shootdown from userspace) — detach/re-attach
 * re-validates.  Reference: per-fd VA spaces (uvm.c:144,792); the
 * share itself is the CUDA-IPC model, not fork inheritance. */
TpuStatus uvmRemoteAttach(UvmVaSpace *vs, uint64_t ownerAddr,
                          void **outLocalBase, uint64_t *outSize);
TpuStatus uvmRemoteDetach(UvmVaSpace *vs, void *localBase);

/* ------------------------------------------------------------- fault API */

typedef struct {
    uint64_t faultsCpu;        /* CPU (SIGSEGV) faults serviced */
    uint64_t faultsDevice;     /* device-access faults serviced */
    uint64_t batches;          /* service-loop batches */
    uint64_t migratedBytes;    /* bytes moved by fault servicing */
    uint64_t evictions;        /* block evictions (oversubscription) */
    /* Service-latency percentiles, derived from the tputrace
     * log-linear histograms (trace.h; ~1% relative error, full
     * history — formerly a bounded 4096-sample window).  Struct
     * layout is unchanged: histogram adoption is ABI-compatible. */
    uint64_t serviceNsP50;
    uint64_t serviceNsP95;
    /* Phase decomposition of the headline latency: wake = enqueue ->
     * batch pop (futex + scheduler), svcOne = one service_one call
     * (engine work).  headline ~= wake + svcOne (+ batch-mates). */
    uint64_t wakeNsP50;
    uint64_t wakeNsP95;
    uint64_t svcOneNsP50;
    uint64_t svcOneNsP95;
} UvmFaultStats;
void uvmFaultStatsGet(UvmFaultStats *out);
/* Restart the percentile histograms (not the counters): resets the
 * three fault-latency trace histograms only. */
void uvmFaultStatsResetWindows(void);

/* Pageable memory (HMM analog, reference uvm_hmm.c): adopt an existing
 * anonymous mapping into a managed range IN PLACE, preserving contents
 * — device faults, tiering, policies and eviction then apply to memory
 * the engine did not allocate.  2 MB block alignment required; freeing
 * the range restores a plain anonymous mapping with current contents.
 * Device accesses to non-managed pageable VAs are serviced in place
 * (ATS analog) when HMM is enabled (registry uvm_disable_hmm=0). */
TpuStatus uvmPageableAdopt(UvmVaSpace *vs, void *base, uint64_t len);

/* ------------------------------------------------- external mappings */

/* External VA ranges (reference: uvm_map_external.c; ioctls 73/33/66).
 * The caller reserves VA (mmap PROT_NONE) and registers [base, base+
 * length) as an EXTERNAL range — no managed semantics, no fault
 * servicing.  uvmMapExternal then maps a dmabuf window (device HBM
 * exported via tpuDmabufExport) into a span of the range: the span
 * becomes a CPU-visible alias of the same arena bytes the channels
 * DMA (memfd-backed arena).  Freeing the range (uvmMemFree on base)
 * unmaps every window and restores the caller's PROT_NONE reservation.
 * uvmExternalFlush publishes CPU writes through the alias to the
 * real-arena mirror stream (writes through an alias bypass the channel
 * executors that normally notify). */
struct TpuDmabuf;
TpuStatus uvmExternalRangeCreate(UvmVaSpace *vs, void *base,
                                 uint64_t length);
TpuStatus uvmMapExternal(UvmVaSpace *vs, void *base, uint64_t length,
                         struct TpuDmabuf *buf, uint64_t bufOffset);
TpuStatus uvmUnmapExternal(UvmVaSpace *vs, void *base, uint64_t length);
TpuStatus uvmExternalFlush(UvmVaSpace *vs, void *base, uint64_t length);

/* ------------------------------------------------- external HBM chunks */

/* Allocate a chunk of device HBM from the tier's PMM for pools that
 * live outside the managed-VA world (ICI peer-mapped KV pool, peermem
 * exports) — sharing the allocator with the fault engine instead of
 * carving arena bytes privately.  size is rounded up to a power-of-two
 * chunk (max 2 MB).  Reference analog: PMA serving both UVM and RM
 * (uvm_pmm_gpu.h:27-47). */
TpuStatus uvmHbmChunkAllocSized(uint32_t devInst, uint64_t size,
                                uint64_t *outOffset, uint64_t *outSize,
                                void **outHandle);
TpuStatus uvmHbmChunkAlloc(uint32_t devInst, uint64_t size,
                           uint64_t *outOffset, void **outHandle);
TpuStatus uvmHbmChunkFree(uint32_t devInst, void *handle);
/* Arena occupancy: free/total bytes of a device's HBM tier PMM (tpuvac
 * evacuation-target headroom; capacity dashboards).  Bytes the device
 * has LENT to peers' REMOTE tiers are excluded from `used` — borrowed
 * pages are reclaimable on demand (lease drop falls back to HOST), so
 * counting them would double-charge the lender in vac target picking. */
TpuStatus uvmHbmArenaUsage(uint32_t devInst, uint64_t *freeBytes,
                           uint64_t *totalBytes);

/* ------------------------------------------------- REMOTE tier (tpusplit)
 *
 * A neighbor chip's HBM as another chip's far memory.  Gated by the
 * registry knob "remote_tier" (default off); lenders are picked by the
 * tpuvac health/headroom scorer and must keep "remote_headroom_pct"
 * free HBM after the lease.  Replicas are write-through (HOST keeps
 * the durable copy) and generation-fenced: any device reset or an
 * unhealthy lender invalidates the lease and the span falls back to
 * HOST.  Data moves ONLY as PEER_COPY SQEs on the submission spine. */

/* Borrower/lender accounting for one device: pages it has parked
 * remotely (borrower side) and bytes of its own HBM lent out. */
TpuStatus uvmTierRemoteStats(uint32_t devInst, uint64_t *borrowedPages,
                             uint64_t *lentBytes);
/* Drop every lease on `lenderInst` (evacuation/teardown): borrowers
 * fall back to their HOST copies lazily; the gauge drains as blocks
 * are touched.  Returns leases marked for revocation. */
uint64_t uvmTierRemoteRevokeLender(uint32_t lenderInst);

/* ------------------------------------------------------- tenant QoS API
 *
 * Per-client (tenant) HBM/CXL page quotas + eviction priority, the
 * policy substrate under the tpusched serving scheduler.  Tenants are
 * process-global (id 0 is the implicit default tenant every VA space
 * starts in: unlimited quota, priority UVM_TENANT_PRIO_DEFAULT).  A VA
 * space binds to one tenant; every backing page its blocks hold in an
 * HBM/CXL arena is charged to that tenant.  Enforcement is eviction
 * pressure, not allocation failure: when an arena needs a victim, the
 * LRU walk becomes SLO-aware — cold blocks of over-quota tenants go
 * first, then lower-priority tenants, then plain LRU order — so an
 * over-quota tenant preempts itself under pressure while compliant
 * higher-priority tenants keep their residency.  Usage/quotas render
 * as tpurm_tenant_pages gauges in the Prometheus exposition and in
 * /proc/driver/tpurm/tenants. */

#define UVM_TENANT_PRIO_DEFAULT 100

typedef struct {
    uint32_t priority;
    uint64_t hbmQuotaPages;    /* 0 = unlimited */
    uint64_t cxlQuotaPages;
    uint64_t hbmPages;         /* OUT: current charged usage */
    uint64_t cxlPages;
} UvmTenantInfo;

/* Create-or-update a tenant.  Safe while traffic runs (usage counters
 * survive reconfiguration). */
TpuStatus uvmTenantConfigure(uint32_t tenantId, uint32_t priority,
                             uint64_t hbmQuotaPages,
                             uint64_t cxlQuotaPages);
/* OBJECT_NOT_FOUND for an id never configured (except 0: the default
 * tenant always exists). */
TpuStatus uvmTenantInfoGet(uint32_t tenantId, UvmTenantInfo *out);
/* Bind vs (and the pages its blocks already hold) to tenantId; the
 * tenant must exist.  Re-binding moves the existing charge. */
TpuStatus uvmVaSpaceBindTenant(UvmVaSpace *vs, uint32_t tenantId);
/* Per-DEVICE HBM charge (tpuvac): pools that place a tenant's pages on
 * a specific chip (the ICI KV pool) charge that chip's column here;
 * a live migration REBINDS the charge from the source chip to the
 * target in one move (per-tier totals untouched, counted
 * tpurm_tenant_rebinds).  Rendered as tpurm_tenant_dev_pages{tenant,
 * dev} gauges and in /proc/driver/tpurm/tenants. */
void uvmTenantDevCharge(uint32_t tenantId, uint32_t devInst,
                        int64_t pages);
TpuStatus uvmTenantRebindDevicePages(uint32_t tenantId, uint32_t fromDev,
                                     uint32_t toDev, uint64_t pages);
uint64_t uvmTenantDevPages(uint32_t tenantId, uint32_t devInst);

/* -------------------------------------------------------- suspend/resume */

/* Global PM quiesce + device-arena save/restore (reference: fbsr.c FB
 * save + uvm_suspend's global PM lock, uvm_lock.h:43-49).  uvmSuspend
 * blocks every entry point (alloc/free/migrate/device-access), drains
 * the fault ring, and saves all HBM/CXL residency to host — after it
 * returns the arenas hold no live data.  uvmResume restores the saved
 * spans (eagerly by default; registry uvm_resume_restore=0 for lazy
 * first-fault restore) and reopens the gate. */
TpuStatus uvmSuspend(void);
TpuStatus uvmResume(void);

/* ------------------------------------------------------------- tools API */

/* Event record (reference: UvmEventEntry, uvm_tools.c mmap'd queues). */
typedef enum {
    UVM_EVENT_CPU_FAULT = 0,
    UVM_EVENT_GPU_FAULT = 1,
    UVM_EVENT_MIGRATION = 2,
    UVM_EVENT_EVICTION = 3,
    UVM_EVENT_THRASHING = 4,
    UVM_EVENT_PREFETCH = 5,
    UVM_EVENT_READ_DUP = 6,
    UVM_EVENT_ACCESS_COUNTER = 7,
    UVM_EVENT_FATAL_FAULT = 8,
    /* Lifecycle/infra events (reference vocabulary: GPU_FAULT_REPLAY,
     * FAULT_BUFFER_FLUSH, MAP_REMOTE, READ_DUPLICATE_INVALIDATE, ...). */
    UVM_EVENT_GPU_FAULT_REPLAY = 9,
    UVM_EVENT_FAULT_BUFFER_FLUSH = 10,
    UVM_EVENT_MAP_REMOTE = 11,
    UVM_EVENT_READ_DUP_INVALIDATE = 12,
    UVM_EVENT_PTE_UPDATE = 13,
    UVM_EVENT_TLB_INVALIDATE = 14,
    UVM_EVENT_CHANNEL_RC = 15,
    UVM_EVENT_WATCHDOG = 16,
    UVM_EVENT_PM_SUSPEND = 17,
    UVM_EVENT_PM_RESUME = 18,
    UVM_EVENT_EXTERNAL_MAP = 19,
    UVM_EVENT_EXTERNAL_UNMAP = 20,
    UVM_EVENT_HMM_ADOPT = 21,
    UVM_EVENT_ATS_ACCESS = 22,
    UVM_EVENT_COUNT = 23,
} UvmEventType;

typedef struct {
    uint32_t type;             /* UvmEventType */
    uint32_t srcTier, dstTier; /* migration-ish events */
    uint32_t devInst;
    uint64_t address;
    uint64_t bytes;
    uint64_t timestampNs;
} UvmEvent;

typedef struct UvmToolsSession UvmToolsSession;

/* Layout of a tools queue mapping (reference: user-mmap'd lock-free
 * event queues, uvm_tools.c:54-70): page 0 is this header, events
 * follow at offset 4096.  The producer owns widx (release-published
 * after the event is written); the consumer owns ridx; when the ring
 * is full NEW events are dropped and counted (reference queue-full
 * accounting) so an external consumer's ridx is never stolen. */
#ifdef __cplusplus
/* C++ has no _Atomic; the fields are plain integers of identical layout
 * (consumers load/store them with std::atomic_ref or equivalent). */
#define UVM_TOOLS_ATOMIC_U64 uint64_t
#else
#define UVM_TOOLS_ATOMIC_U64 _Atomic uint64_t
#endif
typedef struct {
    UVM_TOOLS_ATOMIC_U64 widx;    /* producer-owned, monotonic */
    UVM_TOOLS_ATOMIC_U64 ridx;    /* consumer-owned, monotonic */
    UVM_TOOLS_ATOMIC_U64 dropped; /* events dropped while full  */
    uint32_t capacity;            /* ring entries (power of two) */
    uint32_t eventSize;           /* sizeof(UvmEvent) sanity     */
} UvmToolsQueueHeader;

#define UVM_TOOLS_QUEUE_RING_OFFSET 4096

/* The memfd backing a session's queue: map it (header + ring) for
 * zero-copy event consumption, exactly the reference's mmap contract.
 * ONE consumer per session: ridx has a single owner — mix the mapped
 * consumer with uvmToolsReadEvents and they rewind each other. */
int uvmToolsSessionQueueFd(UvmToolsSession *s);
TpuStatus uvmToolsSessionCreate(UvmVaSpace *vs, uint32_t capacity,
                                UvmToolsSession **out);
void      uvmToolsSessionDestroy(UvmToolsSession *s);
void      uvmToolsEnableEvents(UvmToolsSession *s, uint64_t typeMask);
/* Incremental per-type set/clear (reference ENABLE/DISABLE_EVENTS). */
void      uvmToolsEnableEventTypes(UvmToolsSession *s, uint64_t typeMask);
void      uvmToolsDisableEventTypes(UvmToolsSession *s, uint64_t typeMask);
/* Counter subscription: uvmToolsCounterGet returns false until enabled. */
void      uvmToolsSetCountersEnabled(UvmToolsSession *s, bool enabled);
bool      uvmToolsCounterGet(UvmToolsSession *s, const char *name,
                             uint64_t *out);
/* Queue-depth notification threshold (0 disables); notifications counts
 * threshold crossings since session creation. */
void      uvmToolsSetNotificationThreshold(UvmToolsSession *s,
                                           uint64_t threshold);
uint64_t  uvmToolsPendingEvents(UvmToolsSession *s);
uint64_t  uvmToolsNotificationCount(UvmToolsSession *s);
/* Drains up to max events; returns count.  Lock-free ring; drops oldest
 * on overflow and counts drops ("uvm_tools_events_dropped"). */
size_t    uvmToolsReadEvents(UvmToolsSession *s, UvmEvent *buf, size_t max);

/* --------------------------------------------------- in-module test API */

/* Test commands (uvm_test.c:241-312 pattern; numbers are tpurm's own). */
enum {
    UVM_TPU_TEST_RANGE_TREE_DIRECTED  = 1,
    UVM_TPU_TEST_RANGE_TREE_RANDOM    = 2,
    UVM_TPU_TEST_PMM_BASIC            = 3,
    UVM_TPU_TEST_PMM_EVICTION         = 4,
    UVM_TPU_TEST_VA_BLOCK             = 5,
    UVM_TPU_TEST_LOCK_SANITY          = 6,
    UVM_TPU_TEST_FAULT_INJECT         = 7,
    UVM_TPU_TEST_ACCESSED_BY          = 8,
    UVM_TPU_TEST_TOOLS                = 9,
    UVM_TPU_TEST_ACCESS_COUNTERS      = 10,
    UVM_TPU_TEST_REPLAY_CANCEL        = 11,
    UVM_TPU_TEST_SUSPEND_RESUME       = 12,
    UVM_TPU_TEST_EXTERNAL_RANGE       = 13,
    UVM_TPU_TEST_RANGE_SPLIT          = 14,
    UVM_TPU_TEST_HMM_PAGEABLE         = 15,
    UVM_TPU_TEST_DEV_MMU              = 16,
    UVM_TPU_TEST_MULTI_WORKER         = 17,
};
TpuStatus uvmRunTest(UvmVaSpace *vs, uint32_t testCmd);

#ifdef __cplusplus
}
#endif

#endif /* TPURM_UVM_H */
