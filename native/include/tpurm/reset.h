/*
 * tpureset — coordinated full-device reset, hung-op watchdog
 * escalation, and the device-wide generation fence.
 *
 * The per-channel half of "surviving the hardware" already exists (rc.c
 * robust-channel recovery: latch, shadow-buffer attribution, bounded
 * reset-and-replay).  tpureset owns the two failures a serving fleet
 * actually sees above that layer:
 *
 *   a WEDGED DEVICE — an engine stops retiring work entirely.  The
 *   recovery is a *full-device reset* (reference: RM fatal-fault
 *   teardown + fbsr save/restore, SURVEY layer 3), structured as three
 *   phases:
 *
 *     quiesce — park the memring worker pools (published-but-unclaimed
 *               SQEs stay queued for replay; claimed ops drain with a
 *               bounded timeout), take the UVM PM gate exclusively and
 *               save device-resident pages to their host backing
 *               (uvmSuspend — the fbsr path), pause the fault-service
 *               loop between batches, and drain every tpuce copy
 *               channel;
 *     reset   — bump the DEVICE-WIDE GENERATION (stale trackers and
 *               completions that cross the bump are rejected with
 *               TPU_ERR_DEVICE_RESET — see the fencing contract
 *               below), clear every latched channel error
 *               (tpuRcRecoverAll), retrain every ICI link, and
 *               re-validate live RDMA MR pins;
 *     resume  — restore saved residency from the backing (uvmResume:
 *               HBM survivors are re-materialized from host truth, the
 *               fbsr semantics), resume fault service, and unpark the
 *               memring pools — pending idempotent SQEs re-issue
 *               against the new generation.
 *
 *   a HUNG OP — work is in flight but never retires.  SQEs and tpuce
 *   batches carry optional DEADLINES (absolute tpuNowNs); expired ops
 *   fail fast instead of waiting forever.  Above that, a watchdog
 *   thread scans for no-progress-with-inflight rings and walks an
 *   ESCALATION LADDER, each rung counted:
 *
 *     rung 1  nudge     — re-ring the doorbells (a lost wake is the
 *                         cheapest wedge)            tpurm_watchdog_nudges
 *     rung 2  RC reset  — channel reset-and-replay   tpurm_watchdog_rc_resets
 *     rung 2.5 EVACUATE — when a device's health state and the fleet
 *                         allow it (a sick chip, a HEALTHY peer with
 *                         HBM headroom — tpurm/health.h), post a live
 *                         tenant evacuation request instead of
 *                         resetting; the serving layer drains tenants
 *                         off the chip inside a grace window
 *                         ("vac_grace_ms").  An expired un-acked
 *                         request falls through to rung 3, so recovery
 *                         never waits on an absent scheduler.
 *                                                    tpurm_watchdog_evacuations
 *     rung 3  device    — full-device reset          tpurm_watchdog_device_resets
 *
 *   The ladder saturates after rung 3 until the ring makes progress
 *   again (no reset storms).
 *
 * Generation fencing contract: every claim records the generation it
 * executed under.  Quiesce waits for in-flight work, so the only ops
 * that can cross a generation bump are ones quiesce TIMED OUT on —
 * genuinely hung or wedged work whose eventual "completion" must not
 * be mistaken for valid post-reset state.  Their CQEs/waits surface
 * TPU_ERR_DEVICE_RESET and are counted (memring_stale_completions /
 * tpuce_stale_completions); the memring caller re-issues, a tpuce
 * batch replays the stripe itself.
 *
 * The reset.device injection site (TPUMEM_INJECT_RESET_DEVICE) is
 * evaluated once per watchdog tick: a hit injects a device-level fatal
 * fault whose recovery IS a full reset (counted tpurm_reset_injected,
 * reconciled exactly: injected hits == tpurm_reset_injected).
 *
 * Observability: /proc/driver/tpurm/reset node; Prometheus series
 * tpurm_reset_total, tpurm_device_generation, tpurm_reset_mttr_ns
 * (cumulative quiesce->resume ns; with tpurm_reset_total this yields
 * the mean, per-reset samples come from TpuResetStats.lastMttrNs), and
 * the three ladder counters above; reset.device / reset.quiesce
 * tputrace spans while tracing is armed.
 *
 * Registry knobs (TPUMEM_*):
 *   reset_watchdog_enable      (1)    master switch for the watchdog
 *   reset_watchdog_period_ms   (100)  scan + inject-evaluation period
 *   reset_hang_timeout_ms      (5000) stall age before the ladder runs
 *   reset_quiesce_timeout_ms   (2000) bounded in-flight drain per phase
 */
#ifndef TPURM_RESET_H
#define TPURM_RESET_H

#include <stdint.h>

#include "status.h"

#ifdef __cplusplus
extern "C" {
#endif

typedef struct {
    uint64_t generation;        /* current device-wide generation (>=1) */
    uint64_t resets;            /* completed full-device resets          */
    uint64_t failedResets;      /* reset attempts that could not run     */
    uint64_t injectedResets;    /* resets forced by the reset.device site */
    uint64_t watchdogNudges;    /* ladder rung 1 */
    uint64_t watchdogRcResets;  /* ladder rung 2 */
    uint64_t watchdogDeviceResets; /* ladder rung 3 */
    uint64_t watchdogEvacuations;  /* ladder rung 2.5 (EVACUATE) */
    uint64_t lastMttrNs;        /* last reset: quiesce -> resume        */
    uint64_t lastQuiesceNs;     /* last reset: quiesce phase alone      */
    uint64_t lastRestoreNs;     /* last reset: reset + resume phases    */
    uint64_t mttrSumNs;         /* cumulative MTTR over all resets      */
    uint64_t staleCompletions;  /* generation-fenced completions (all
                                 * engines: memring + tpuce)            */
} TpuResetStats;

/* The device-wide generation.  Starts at 1; each completed (or
 * force-proceeded) reset bumps it.  Safe from any thread, any time. */
uint64_t tpurmDeviceGeneration(void);

/* Coordinated full-device reset (all devices — the engine's arenas,
 * channel pools and rings are process-global, exactly like the
 * reference RM's per-GPU lock domain collapsed onto one fake chip set).
 * Concurrent callers COALESCE: a reset already in flight absorbs the
 * second request, which returns TPU_OK once that reset completes.
 *
 * Fails with TPU_ERR_INVALID_STATE when the UVM PM gate is already
 * held by an explicit uvmSuspend (the operator owns the suspension;
 * resetting under them would yank the arenas they froze). */
TpuStatus tpurmDeviceReset(void);

/* Snapshot the reset/watchdog statistics. */
void tpurmResetStats(TpuResetStats *out);

/* Start the hung-op watchdog thread (idempotent; also started lazily
 * by tpuRcInit so any process that creates a channel is covered). */
void tpurmResetWatchdogStart(void);

#ifdef __cplusplus
}
#endif

#endif /* TPURM_RESET_H */
