/*
 * tpushield — end-to-end page integrity: CRC sealing, wire checksums,
 * background scrub, and poison containment with page retirement.
 *
 * Every robustness layer before this one reacts to REPORTED errors;
 * nothing in the engine detected silent data corruption — every tier
 * demotion, ICI hop and vac shipping window trusted the bytes.  The
 * reference driver treats integrity as a first-class subsystem (ECC
 * interrupt handling and dynamic page retirement / row remapping in
 * the PMM blacklist path, SURVEY §2.2/§2.6); at serving scale a
 * flipped bit in a cold CXL-parked KV page is a silently-wrong token
 * stream no retry ladder can catch after the fact.
 *
 * Model — per-page integrity metadata (CRC32C seal + seal generation +
 * poison state) stored beside the residency masks in UvmVaBlock:
 *
 *   SEAL    — pages going COLD or crossing a WIRE are sealed: the tier
 *             demote / eviction copy-back path (CRC computed on the
 *             tpuce executor threads as a stripe transform stage, so
 *             sealing overlaps the copy), the fbsr save (rides the
 *             same eviction), ICI PEER_COPY and the multi-hop
 *             store-and-forward pipeline (per-hop CRC so a corrupting
 *             middle hop is attributed to the LINK and feeds
 *             tpurmHealthNote), and tpuvac shipping windows (per-record
 *             CRC verified before tpurmVacCommit).
 *   VERIFY  — sealed pages are verified on the way back hot (promote /
 *             make-resident / restore / first CPU touch) and by the
 *             background scrubber before a demand fault ever sees them.
 *   LADDER  — a verify mismatch runs a bounded re-fetch ladder:
 *             (1) recompute against the sealing source (transient /
 *             in-flight corruption), (2) re-fetch from any
 *             read-duplicated sibling copy (counted refetch_saves),
 *             (3) declare the page POISONED.
 *   POISON  — containment, never a device reset: the OWNING sequence
 *             gets TPU_ERR_PAGE_POISONED (the scheduler retires that
 *             stream with an error status; co-tenants are untouched)
 *             and the backing page is RETIRED into the quarantine list
 *             — its PMM chunk is never freed, so the physical span can
 *             never be re-allocated (tpurm_pages_retired{dev=}).
 *   SCRUB   — a background thread (cadence "shield_scrub_ms", bounded
 *             "shield_scrub_pages" per tick so the fault p50 budget
 *             holds) walks sealed cold pages and catches corruption
 *             before a demand fault does (tpurm_scrub_pages/_hits).
 *
 * Injection: the mem.corrupt site (TPUMEM_INJECT_MEM_CORRUPT) is the
 * first site that CORRUPTS rather than fails — a hit flips one bit in
 * a freshly sealed page / shipped wire buffer.  Exact reconciliation:
 * site hits == shield_detected + shield_inject_misses, and misses stay
 * zero while the seal/verify hooks cover every consumption path.
 *
 * Fast-path discipline: with no sealed pages a block costs ONE pointer
 * load on the fault path (blk->shield == NULL); with the registry
 * knob "shield_enable" 0 nothing seals at all.
 */
#ifndef TPURM_SHIELD_H
#define TPURM_SHIELD_H

#include <stdbool.h>
#include <stdint.h>

#include "status.h"

#ifdef __cplusplus
extern "C" {
#endif

/* Lifetime subsystem statistics (process-global). */
typedef struct TpuShieldStats {
    uint64_t seals;             /* pages sealed (incl. reseals)        */
    uint64_t verifies;          /* page verifications run              */
    uint64_t mismatches;        /* CRC mismatches observed (any cause) */
    uint64_t refetchSaves;      /* ladder recoveries from a sibling /
                                 * the sealing source                  */
    uint64_t pagesPoisoned;     /* pages declared POISONED             */
    uint64_t pagesRetired;      /* backing pages on the retire list    */
    uint64_t scrubTicks;        /* scrubber passes                     */
    uint64_t scrubPages;        /* pages scrubbed                      */
    uint64_t scrubHits;         /* corruption caught by the scrubber   */
    uint64_t injectCorrupts;    /* mem.corrupt flips performed         */
    uint64_t injectDetected;    /* flips caught by a verify            */
    uint64_t injectMisses;      /* flips that escaped every verify
                                 * hook (coverage hole — must be 0)    */
    uint64_t wireVerifies;      /* ICI/vac wire-buffer verifications   */
    uint64_t wireMismatches;    /* wire CRC mismatches                 */
} TpuShieldStats;

/* Registry "shield_enable" (default 1). */
bool tpurmShieldEnabled(void);

/* CRC32C (Castagnoli).  Hardware SSE4.2 path when the CPU has it,
 * slice-by-8 software fallback.  Extend form chains partial buffers
 * (seed crc 0 == tpurmShieldCrc32c). */
uint32_t tpurmShieldCrc32c(const void *data, uint64_t len);
uint32_t tpurmShieldCrc32cExtend(uint32_t crc, const void *data,
                                 uint64_t len);
/* At-load self-test of the CRC dispatch: SW table and (when present)
 * the HW instruction path are verified against the canonical
 * CRC32C("123456789") vector; a HW mismatch journals (shield.selftest)
 * and falls the dispatch back to the table.  Runs automatically in the
 * library constructor; re-callable from tests.  Returns whether the
 * dispatched path verified. */
bool tpurmShieldCrcSelftest(void);

void tpurmShieldStatsGet(TpuShieldStats *out);
void tpurmShieldStatsReset(void);   /* tests */

/* ---- wire-side helpers (ici.c, vac.py over ctypes) ----
 *
 * InjectWire: one mem.corrupt evaluation carrying `scope`; a hit flips
 * one deterministic bit inside [buf, buf+len) and counts the flip.
 * Returns true when it flipped (the caller's verify MUST run either
 * way — that verify is what keeps the reconciliation exact).
 *
 * VerifyWire: CRC-check a shipped buffer against the seal computed at
 * the source.  Counts wire verifies/mismatches and resolves the
 * inject bookkeeping (a flip this verify catches counts detected).
 * Returns TPU_OK or TPU_ERR_INVALID_STATE on mismatch — the caller
 * re-fetches from its intact source (its rung of the ladder). */
bool tpurmShieldInjectWire(void *buf, uint64_t len, uint64_t scope);
TpuStatus tpurmShieldVerifyWire(const void *buf, uint64_t len,
                                uint32_t expectCrc, uint64_t scope);

/* Poisoned pages inside the managed span [addr, addr+len) (0 when the
 * span resolves to no managed range).  The scheduler uses this to
 * attribute a TPU_ERR_PAGE_POISONED round failure to the OWNING
 * sequence (containment: only that stream retires). */
uint32_t tpurmShieldSpanPoisoned(uint64_t addr, uint64_t len);

/* ---- retirement list ---- */

/* Pages currently retired, total or for one device's HBM arena. */
uint64_t tpurmShieldRetiredPages(uint32_t devInst);
uint64_t tpurmShieldRetiredTotal(void);
/* True when [offset, offset+bytes) of the (tier, devInst) arena
 * overlaps a retired span (tests: retired spans never re-allocate). */
bool tpurmShieldSpanRetired(uint32_t tier, uint32_t devInst,
                            uint64_t offset, uint64_t bytes);

/* ---- scrubber ---- */

/* One synchronous scrub pass over at most maxPages sealed pages
 * (tests / bench detection-latency probes; the background thread uses
 * the same walk).  Returns pages scrubbed. */
uint32_t tpurmShieldScrubNow(uint32_t maxPages);

#ifdef __cplusplus
}
#endif

#endif /* TPURM_SHIELD_H */
