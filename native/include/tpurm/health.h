/*
 * tpuvac health — per-device health scoring, evacuation rendezvous,
 * and the transactional migration manifest.
 *
 * The fleet-operations layer above tpureset: where reset.c answers a
 * sick chip with a full-device reset (every tenant blacks out), tpuvac
 * lets the serving layer MOVE tenants off a degrading chip while
 * co-tenants keep decoding.  Three pieces live here:
 *
 *   HEALTH SCORER — every error the engines already count (channel RC
 *     resets, watchdog nudges, ICI link flaps and retrain failures,
 *     page quarantines, generation-fenced stale completions, deadline
 *     expiries, full device resets) is also REPORTED per device via
 *     tpurmHealthNote().  Each event adds a weighted contribution to a
 *     decaying score (half-life registry "vac_health_halflife_ms");
 *     the score drives a hysteretic state machine
 *
 *         HEALTHY -> DEGRADED -> EVACUATING
 *
 *     Promotion is immediate at the threshold ("vac_degrade_score" /
 *     "vac_evac_score"); demotion requires the decayed score to fall
 *     below HALF the threshold AND "vac_health_hold_ms" of quiet since
 *     the last event — so a flapping chip cannot oscillate its state
 *     at event rate (reference analog: nvswitch/nvlink error-rate
 *     thresholds latch a link DOWN rather than tracking instantaneous
 *     errors).
 *
 *   EVACUATION RENDEZVOUS — the native engine cannot move KV pages
 *     itself (sequence state lives in the serving layer), so the
 *     watchdog posts an evacuation REQUEST (source device, suggested
 *     target) that the scheduler polls between decode rounds
 *     (uvm/vac.py).  The request carries a grace window
 *     ("vac_grace_ms"): a hung-op ladder escalation that finds the
 *     window expired un-acked falls through to the full-device reset
 *     rung, so an absent/wedged serving layer never wedges recovery.
 *     Targets are picked healthy-first with HBM headroom
 *     ("vac_headroom_pct" of the arena must be free).
 *
 *   VAC TRANSACTIONS — a migration is transactional: the source's
 *     pages and sequence slots are retained until the target COMMITS a
 *     generation-stamped manifest.  tpurmVacBegin stamps the device
 *     generation and the source/target pair; tpurmVacCommit re-checks
 *     that the generation never moved (a reset under the migration
 *     invalidates in-flight page state), the target is not lost, and
 *     an ACTIVE route still exists — any failure means the caller
 *     ABORTS back to the source with zero corruption (the source copy
 *     was never released).  Reference analog: fbsr.c save/restore
 *     under the PM quiesce lock, pointed at a remote tier instead of
 *     sysmem.
 *
 * Observability: tpurm_device_health{dev=} / _score gauges in the
 * Prometheus exposition, the /proc/driver/tpurm/health node, a
 * health.transition trace instant per state change, and the
 * tpurm_watchdog_evacuations / vac_* counters.
 */
#ifndef TPURM_HEALTH_H
#define TPURM_HEALTH_H

#include <stdbool.h>
#include <stdint.h>

#include "status.h"

#ifdef __cplusplus
extern "C" {
#endif

/* Health states (order matters: promotion walks upward). */
enum {
    TPU_HEALTH_HEALTHY = 0,
    TPU_HEALTH_DEGRADED = 1,
    TPU_HEALTH_EVACUATING = 2,
};

/* Reportable events (keep tpurmHealthEventName in sync). */
typedef enum {
    TPU_HEALTH_EV_RC_RESET = 0,     /* channel RC reset-and-replay     */
    TPU_HEALTH_EV_WD_NUDGE,         /* memring watchdog rung 1 nudge   */
    TPU_HEALTH_EV_LINK_FLAP,        /* ICI link flap / admin failure   */
    TPU_HEALTH_EV_RETRAIN_FAIL,     /* ICI retrain attempt failed      */
    TPU_HEALTH_EV_PAGE_QUARANTINE,  /* page retired onto poison map    */
    TPU_HEALTH_EV_STALE_COMPLETION, /* generation-fenced completion    */
    TPU_HEALTH_EV_DEADLINE_EXPIRED, /* SQE/batch deadline fail-fast    */
    TPU_HEALTH_EV_DEVICE_RESET,     /* full-device reset ran           */
    TPU_HEALTH_EV_COUNT
} TpuHealthEvent;

/* Snapshot of one device's health (tpurmHealthInfo). */
typedef struct {
    uint32_t state;                 /* TPU_HEALTH_*                    */
    uint32_t evacPending;           /* nonzero: a request is posted    */
    uint64_t score;                 /* decayed score, integer points   */
    uint64_t transitions;           /* lifetime state changes          */
    uint64_t lastEventNs;           /* tpuNowNs of the last note       */
    uint64_t events[TPU_HEALTH_EV_COUNT];
    uint32_t evacTarget;            /* valid while evacPending         */
    uint64_t evacReqId;             /* rendezvous token for the ack    */
} TpuHealthInfo;

/* Report one event against a device (hot paths call this; the cost is
 * one mutexless fast path when the device is quiet is NOT attempted —
 * notes are rare by definition, a mutex is fine). */
void tpurmHealthNote(uint32_t devInst, uint32_t event);

uint32_t tpurmDeviceHealthState(uint32_t devInst);
uint64_t tpurmDeviceHealthScore(uint32_t devInst);
TpuStatus tpurmHealthInfo(uint32_t devInst, TpuHealthInfo *out);
const char *tpurmHealthEventName(uint32_t event);
const char *tpurmHealthStateName(uint32_t state);

/* Zero a device's score/state/history (post-evacuation, post-reset
 * recovery, tests).  Pending evacuation requests are cancelled. */
void tpurmHealthClear(uint32_t devInst);

/* ------------------------------------------------- evacuation rendezvous */

/* Post an evacuation request for devInst (operator planned move or the
 * watchdog).  target ~0u = pick one (healthy peer with headroom);
 * OBJECT_NOT_FOUND when no viable target exists, INVALID_STATE when a
 * request is already pending. */
TpuStatus tpurmHealthEvacRequest(uint32_t devInst, uint32_t target);
/* Broker-aware form: forwards over TPURM_BROKER when attached. */
TpuStatus tpurmHealthEvacRequestClient(uint32_t devInst, uint32_t target);

/* Poll: true when an evacuation of devInst is requested and inside its
 * grace window.  Fills the suggested target and the request id the
 * eventual ack must echo. */
bool tpurmHealthEvacPending(uint32_t devInst, uint32_t *targetOut,
                            uint64_t *reqIdOut);

/* Serving-layer completion: success clears the device's health history
 * (the tenant left; old errors no longer predict anything), failure
 * re-arms the ladder (the request is consumed either way).
 * INVALID_ARGUMENT when reqId does not match the pending request. */
TpuStatus tpurmHealthEvacAck(uint32_t devInst, uint64_t reqId,
                             bool success);

/* Healthy peer with HBM headroom ("vac_headroom_pct" free), nearest
 * first (fewest route hops).  OBJECT_NOT_FOUND when none. */
TpuStatus tpurmHealthPickTarget(uint32_t srcInst, uint32_t *targetOut);

/* Watchdog hooks (reset.c): Tick runs once per watchdog period (decay,
 * health-driven evac posting, grace expiry); EvacLadderRung is
 * consulted when the hung-op ladder reaches the device-reset rung and
 * returns true when the EVACUATE rung absorbed the escalation (a
 * request was posted, or one is pending inside its grace window) —
 * false falls through to the full-device reset. */
void tpurmHealthTick(void);
bool tpurmHealthEvacLadderRung(void);

/* ---------------------------------------------------- vac transactions */

/* Begin a migration manifest src -> dst.  Stamps the current device
 * generation; fails when either device is lost or no ACTIVE route
 * exists.  Up to 16 concurrent transactions. */
TpuStatus tpurmVacBegin(uint32_t srcInst, uint32_t dstInst,
                        uint64_t *txnOut);
/* Commit: re-validates generation / target liveness / route.  On any
 * failure the transaction stays open — the caller MUST abort (its
 * source copy is still the truth). */
TpuStatus tpurmVacCommit(uint64_t txn);
/* Abort: release the manifest; the source remains authoritative. */
TpuStatus tpurmVacAbort(uint64_t txn);
/* Open transactions (introspection / leak checks). */
uint32_t tpurmVacActive(void);

#ifdef __cplusplus
}
#endif

#endif /* TPURM_HEALTH_H */
