/*
 * msgq — lockless shared-memory command queue.
 *
 * TPU-native analog of the reference's GSP message queue
 * (reference: src/common/uproc/ msgq library; producers submit via
 * GspMsgQueueSendCommand -> msgqTxSubmitBuffers,
 * src/nvidia/src/kernel/gpu/gsp/message_queue_cpu.c:446,568): commands
 * are written into a ring, then published by a release-store of the
 * write pointer; the consumer side polls/sleeps on the read pointer and
 * publishes completion by a release-store of a completed sequence
 * number.  This queue is the L1 boundary of the build — channel work is
 * *submitted to* the runtime executor through it rather than executed
 * inline, and in real-arena mode the HBM mirror stream to the Python/JAX
 * runtime rides a second instance of the same structure.
 *
 * Concurrency model:
 *   - single consumer always;
 *   - single producer by default; TPU_MSGQ_MPSC serializes producers
 *     with an internal tx mutex (the reference's command queue is also
 *     mutex-guarded on the tx side).
 * Blocking uses futexes directly (doorbell on submit, back-pressure on
 * full, completion waits), so consumers never spin.
 */
#ifndef TPURM_MSGQ_H
#define TPURM_MSGQ_H

#include <stdbool.h>
#include <stdint.h>

#ifdef __cplusplus
extern "C" {
#endif

typedef struct TpuMsgq TpuMsgq;

/* Command opcodes. */
enum {
    TPU_MSGQ_NOP = 0,
    TPU_MSGQ_HBM_MIRROR = 2,   /* shadow[hbmOff..+bytes] is dirty     */
    TPU_MSGQ_FENCE = 3,        /* completion marker only              */
    TPU_MSGQ_CE_PUSH = 5,      /* src = CopySeg methods in a channel
                                * pushbuffer, bytes = method count    */
    TPU_MSGQ_HBM_READBACK = 6, /* chip[dst..+bytes] is newer than the
                                * shadow: consumer must download it
                                * into the shadow before completing   */
};

/* Command flags. */
enum {
    TPU_MSGQ_FLAG_INJECT_ERROR = 0x2, /* fault-injection (tests)      */
};

typedef struct TpuMsgqCmd {
    uint32_t op;
    uint32_t flags;
    uint64_t seq;              /* assigned by tpuMsgqSubmit            */
    uint64_t dst;              /* hbm offset (MIRROR)                  */
    uint64_t src;              /* methods pointer (CE_PUSH)            */
    uint64_t bytes;
    uint32_t devInst;          /* device (MIRROR)                      */
    uint32_t _pad;
    uint64_t pbEnd;            /* pushbuffer chunk to retire (CE_PUSH) */
} TpuMsgqCmd;

enum {
    TPU_MSGQ_MPSC = 0x1,       /* serialize producers with a tx mutex */
};

/* nElems is rounded up to a power of two (min 16). */
TpuMsgq *tpuMsgqCreate(uint32_t nElems, uint32_t flags);
void tpuMsgqDestroy(TpuMsgq *q);

/* Producer: append n commands, assigning consecutive sequence numbers;
 * returns the sequence of the LAST command via outLastSeq (optional).
 * Blocks while the ring lacks space.  Fails only after tpuMsgqShutdown. */
int tpuMsgqSubmit(TpuMsgq *q, TpuMsgqCmd *cmds, uint32_t n,
                  uint64_t *outLastSeq);

/* Non-blocking variant: -EAGAIN when the ring lacks space (callers that
 * must never stall — e.g. the HBM mirror's engine-side notify — degrade
 * to an overflow path instead of waiting). */
int tpuMsgqTrySubmit(TpuMsgq *q, TpuMsgqCmd *cmds, uint32_t n,
                     uint64_t *outLastSeq);

/* Reopen a shut-down queue: discards any unconsumed commands (they count
 * as retired), clears the shutdown latch, and resumes sequence
 * allocation.  Caller must guarantee no concurrent producer/consumer. */
void tpuMsgqReopen(TpuMsgq *q);

/* Consumer: copy up to max pending commands into out.  Blocks until at
 * least one command is available or the queue is shut down (returns 0). */
uint32_t tpuMsgqReceive(TpuMsgq *q, TpuMsgqCmd *out, uint32_t max);

/* Consumer: retire commands through sequence seq (frees ring space,
 * publishes the completed sequence, wakes waiters). */
void tpuMsgqComplete(TpuMsgq *q, uint64_t seq);

/* Highest completed (retired) sequence. */
uint64_t tpuMsgqCompletedSeq(TpuMsgq *q);

/* Block until completedSeq >= seq (or shutdown; returns false then). */
bool tpuMsgqWaitSeq(TpuMsgq *q, uint64_t seq);

/* Unblock all producers/consumers/waiters; subsequent Submit fails and
 * Receive returns 0.  Idempotent. */
void tpuMsgqShutdown(TpuMsgq *q);

/* Introspection (tests/metrics). */
uint64_t tpuMsgqSubmittedSeq(TpuMsgq *q);
uint32_t tpuMsgqDepth(TpuMsgq *q);

#ifdef __cplusplus
}
#endif

#endif /* TPURM_MSGQ_H */
