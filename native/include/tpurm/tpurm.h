/*
 * tpurm — public API of the TPU resource-manager runtime.
 *
 * TPU-native re-design of the reference's RM stack (SURVEY.md §1): where the
 * reference is a kernel driver reached through /dev/nvidiactl ioctls, the TPU
 * runtime is a user-level library (TPU devices are driven from userspace via
 * libtpu/vfio), exposing
 *
 *   1. the same escape ABI (tpurm_open/tpurm_ioctl emulate the char-dev
 *      surface; an LD_PRELOAD shim maps real open()/ioctl() onto these so
 *      reference binaries run unchanged),
 *   2. a direct C API for in-process clients (the Python runtime binds this
 *      via ctypes),
 *   3. the DMA-channel engine (channel/pushbuffer/tracker trio, reference:
 *      kernel-open/nvidia-uvm/uvm_channel.h:33-47, uvm_pushbuffer.h:33-90)
 *      used by the CXL path here and the UVM migration engine on top.
 *
 * Device model: enumerated TPU devices each own an HBM arena.  With no real
 * TPU attached the arena is host memory (the fake-device backend SURVEY.md §4
 * calls for); with a real TPU the arena is a window registered by the Python
 * runtime (JAX owns the true HBM allocator).
 */
#ifndef TPURM_TPURM_H
#define TPURM_TPURM_H

#include <stddef.h>
#include <stdint.h>

#include "abi.h"
#include "status.h"

#ifdef __cplusplus
extern "C" {
#endif

/* ------------------------------------------------------- escape surface */

/* Returns a pseudo-fd (>= 0) or -1 with errno set.  Recognized paths:
 * "/dev/nvidiactl", "/dev/tpuctl" (control node); "/dev/nvidia0",
 * "/dev/accel/tpu0" etc (per-device nodes). */
int tpurm_open(const char *path);
int tpurm_close(int pfd);
/* Emulates ioctl(2) on a pseudo-fd: returns 0 on success (RM status is in
 * the param block), -1 with errno on transport errors. */
int tpurm_ioctl(int pfd, unsigned long request, void *argp);

/* ------------------------------------------------------- direct C API */

TpuStatus tpurmAlloc(TpuRmAllocParams *p);
TpuStatus tpurmControl(TpuRmControlParams *p);
TpuStatus tpurmFree(TpuRmFreeParams *p);

/* --------------------------------------------------------- device model */

typedef struct TpurmDevice TpurmDevice;

uint32_t      tpurmDeviceCount(void);
TpurmDevice  *tpurmDeviceGet(uint32_t inst);
/* The device's HBM arena (fake-device backend: host memory). */
void         *tpurmDeviceHbmBase(TpurmDevice *dev);
uint64_t      tpurmDeviceHbmSize(TpurmDevice *dev);
/* Mark the device lost (error-injection surface; reference:
 * PDB_PROP_GPU_IS_LOST checked in p2p_cxl.c:594). */
void          tpurmDeviceSetLost(TpurmDevice *dev, int lost);

/* -------------------------------------------------------- DMA channels */

typedef struct TpurmChannel TpurmChannel;

/* Copy-engine type tags (channel pools per CE type in the reference). */
typedef enum {
    TPURM_CE_HOST_TO_DEV = 0,
    TPURM_CE_DEV_TO_HOST = 1,
    TPURM_CE_DEV_TO_DEV  = 2,
    TPURM_CE_ANY         = 3,
} TpurmCeType;

TpurmChannel *tpurmChannelCreate(TpurmDevice *dev, TpurmCeType ce,
                                 uint32_t ring_entries /* 0 = registry */);
void          tpurmChannelDestroy(TpurmChannel *ch);

/* Submit an async copy; returns the tracker value that completes it, or 0
 * on failure (ring full is back-pressured internally, not an error). */
uint64_t      tpurmChannelPushCopy(TpurmChannel *ch, void *dst,
                                   const void *src, uint64_t bytes);
/* Tracker semantics (reference: uvm_tracker.c): wait until the channel's
 * completed value >= value. */
TpuStatus     tpurmChannelWait(TpurmChannel *ch, uint64_t value);
uint64_t      tpurmChannelCompletedValue(TpurmChannel *ch);
/* Fault injection: force the next push to fail (reference: UVM error
 * injection ioctls, uvm_test.c:286,308). */
void          tpurmChannelInjectError(TpurmChannel *ch);
/* Robust-channel recovery: clear a latched channel error so new work can
 * proceed (reference: per-channel RC, src/nvidia/src/kernel/gpu/rc/). */
void          tpurmChannelResetError(TpurmChannel *ch);

/* --------------------------------------------------------- diagnostics */

/* Journal ring dump into caller buffer; returns bytes written. */
size_t tpurmJournalDump(char *buf, size_t bufSize);
/* Monotonic named counter read (pinned bytes, pushes, copies...). */
uint64_t tpurmCounterGet(const char *name);

#ifdef __cplusplus
}
#endif

#endif /* TPURM_TPURM_H */
