/*
 * tpurm — public API of the TPU resource-manager runtime.
 *
 * TPU-native re-design of the reference's RM stack (SURVEY.md §1): where the
 * reference is a kernel driver reached through /dev/nvidiactl ioctls, the TPU
 * runtime is a user-level library (TPU devices are driven from userspace via
 * libtpu/vfio), exposing
 *
 *   1. the same escape ABI (tpurm_open/tpurm_ioctl emulate the char-dev
 *      surface; an LD_PRELOAD shim maps real open()/ioctl() onto these so
 *      reference binaries run unchanged),
 *   2. a direct C API for in-process clients (the Python runtime binds this
 *      via ctypes),
 *   3. the DMA-channel engine (channel/pushbuffer/tracker trio, reference:
 *      kernel-open/nvidia-uvm/uvm_channel.h:33-47, uvm_pushbuffer.h:33-90)
 *      used by the CXL path here and the UVM migration engine on top.
 *
 * Device model: enumerated TPU devices each own an HBM arena.  With no real
 * TPU attached the arena is host memory (the fake-device backend SURVEY.md §4
 * calls for); with a real TPU the arena is a window registered by the Python
 * runtime (JAX owns the true HBM allocator).
 */
#ifndef TPURM_TPURM_H
#define TPURM_TPURM_H

#include <stdbool.h>
#include <stddef.h>
#include <stdint.h>

#include "abi.h"
#include "status.h"

#ifdef __cplusplus
extern "C" {
#endif

/* ------------------------------------------------------- escape surface */

/* Returns a pseudo-fd (>= 0) or -1 with errno set.  Recognized paths:
 * "/dev/nvidiactl", "/dev/tpuctl" (control node); "/dev/nvidia0",
 * "/dev/accel/tpu0" etc (per-device nodes). */
int tpurm_open(const char *path);

/* Multi-process RM broker (broker.c): serve this process's engine over
 * a unix socket; other processes attach by setting TPURM_BROKER=<path>
 * before their first open (the rs_server client model — each
 * connection gets an isolated handle namespace). */
TpuStatus tpurmBrokerServe(const char *path);
/* Tenant QoS over the broker (BR_OP_TENANT): configure a per-client
 * tenant (priority + HBM/CXL page quotas, uvm.h uvmTenantConfigure) in
 * the ENGINE HOST's tenant table.  A process with TPURM_BROKER set
 * forwards the op to the brokerd; a process hosting the engine itself
 * applies it locally — callers (the tpusched Python surface) need not
 * care which side they are on. */
TpuStatus tpurmBrokerTenantConfigure(uint32_t tenantId, uint32_t priority,
                                     uint64_t hbmQuotaPages,
                                     uint64_t cxlQuotaPages);
int tpurm_close(int pfd);
/* Emulates ioctl(2) on a pseudo-fd: returns 0 on success (RM status is in
 * the param block), -1 with errno on transport errors. */
int tpurm_ioctl(int pfd, unsigned long request, void *argp);
/* Emulates mmap(2) on the uvm pseudo-fd (reference uvm_mmap, uvm.c:792):
 * allocates a managed range, returns its base or MAP_FAILED.  The
 * companion munmap hook frees the range; it returns 1 when it consumed
 * the call (the interposer then skips the real munmap). */
void *tpurm_mmap(int pfd, size_t length);
int   tpurm_munmap_hook(void *addr, size_t length);

/* ------------------------------------------------------- direct C API */

TpuStatus tpurmAlloc(TpuRmAllocParams *p);
TpuStatus tpurmControl(TpuRmControlParams *p);
TpuStatus tpurmFree(TpuRmFreeParams *p);

/* --------------------------------------------------------- device model */

typedef struct TpurmDevice TpurmDevice;

uint32_t      tpurmDeviceCount(void);
TpurmDevice  *tpurmDeviceGet(uint32_t inst);
/* The device's HBM arena (fake-device backend: host memory). */
void         *tpurmDeviceHbmBase(TpurmDevice *dev);
uint64_t      tpurmDeviceHbmSize(TpurmDevice *dev);
/* Mark the device lost (error-injection surface; reference:
 * PDB_PROP_GPU_IS_LOST checked in p2p_cxl.c:594). */
void          tpurmDeviceSetLost(TpurmDevice *dev, int lost);

/* --------------------------------------------------- real-HBM backend */

/* Switch a device's arena from fake (host-only) to REAL: the host arena
 * becomes the coherent shadow of chip HBM and every engine write to it
 * publishes a dirty range on the device's mirror msgq (msgq.h), which
 * the JAX runtime's drain thread applies to a persistent on-chip buffer.
 * Reads are always served from the shadow (fault service must never
 * synchronously depend on the Python runtime — GIL deadlock otherwise);
 * tpurmHbmFence/tpurmHbmWaitSeq give explicit chip-coherence points.
 * Reference analog: the GSP message queue boundary privileged work
 * crosses to firmware (kernel_gsp.c:372 -> message_queue_cpu.c:446). */
TpuStatus tpurmDeviceRegisterHbm(uint32_t inst);
void      tpurmDeviceUnregisterHbm(uint32_t inst);
int       tpurmDeviceArenaIsReal(uint32_t inst);

struct TpuMsgqCmd;         /* full layout in msgq.h */
uint32_t  tpurmHbmMirrorReceive(uint32_t inst, struct TpuMsgqCmd *outCmds,
                                uint32_t max);
void      tpurmHbmMirrorComplete(uint32_t inst, uint64_t seq);
/* Check-and-clear the overflow latch: 1 means a dirty-range notify was
 * dropped (queue full) and the consumer must resync the WHOLE arena
 * from the shadow before acknowledging any later fence. */
int       tpurmHbmMirrorConsumeOverflow(uint32_t inst);
uint64_t  tpurmHbmFence(uint32_t inst);
TpuStatus tpurmHbmWaitSeq(uint32_t inst, uint64_t seq);
/* 1 when the mirror stream has nothing outstanding (fence would be a
 * no-op); read paths use it to skip the round trip. */
int       tpurmHbmMirrorIdle(uint32_t inst);

/* Chip-dirty tracking — the chip->host direction of the boundary.
 * When a jitted computation writes the on-chip arena, the runtime
 * installs the result and marks the span chip-dirty; engine reads of
 * chip-dirty spans (eviction, CPU-fault service, CE/CXL DMA, RDMA
 * pinning, PM save) first block on a READBACK op that downloads the
 * pages into the shadow.  Mirrors the reference's direction-agnostic
 * copy engine (mem_utils.c:567, ce_utils.c:571) and fbsr.c save
 * semantics: device memory, not a host mirror, is the truth once the
 * device wrote it. */
void      tpurmHbmMarkChipDirty(uint32_t inst, uint64_t off,
                                uint64_t bytes);
int       tpurmHbmChipDirtyTest(uint32_t inst, uint64_t off,
                                uint64_t bytes);
/* First chip-dirty span within [off, end): 1 + [*lo, *hi) on hit. */
int       tpurmHbmChipDirtyNextSpan(uint32_t inst, uint64_t off,
                                    uint64_t end, uint64_t *lo,
                                    uint64_t *hi);
void      tpurmHbmChipDirtyClear(uint32_t inst, uint64_t off,
                                 uint64_t bytes);
uint64_t  tpurmHbmChipDirtyGranule(void);
/* Blocking: submit a READBACK for [off, off+bytes) and wait until the
 * consumer has made the shadow coherent.  TPU_OK immediately when the
 * arena is fake or the span has no chip-dirty pages. */
TpuStatus tpurmHbmReadback(uint32_t inst, uint64_t off, uint64_t bytes);

/* -------------------------------------------------------- DMA channels */

typedef struct TpurmChannel TpurmChannel;

/* Copy-engine type tags (channel pools per CE type in the reference). */
typedef enum {
    TPURM_CE_HOST_TO_DEV = 0,
    TPURM_CE_DEV_TO_HOST = 1,
    TPURM_CE_DEV_TO_DEV  = 2,
    TPURM_CE_ANY         = 3,
} TpurmCeType;

TpurmChannel *tpurmChannelCreate(TpurmDevice *dev, TpurmCeType ce,
                                 uint32_t ring_entries /* 0 = registry */);
void          tpurmChannelDestroy(TpurmChannel *ch);

/* Submit an async copy; returns the tracker value that completes it, or 0
 * on failure (ring full is back-pressured internally, not an error). */
uint64_t      tpurmChannelPushCopy(TpurmChannel *ch, void *dst,
                                   const void *src, uint64_t bytes);
/* Tracker semantics (reference: uvm_tracker.c): wait until the channel's
 * completed value >= value. */
TpuStatus     tpurmChannelWait(TpurmChannel *ch, uint64_t value);
/* Range wait: like tpurmChannelWait but fails ONLY if a push whose
 * tracker value lies in [minValue, value] faulted — failure attribution
 * survives a concurrent RC reset (recovery retry on another thread
 * cannot turn this caller's faulted copy into a silent success).  Used
 * by trackers and the hardened-recovery retry loops. */
TpuStatus     tpurmChannelWaitRange(TpurmChannel *ch, uint64_t minValue,
                                    uint64_t value);
uint64_t      tpurmChannelCompletedValue(TpurmChannel *ch);
/* Fault injection: force the next push to fail (reference: UVM error
 * injection ioctls, uvm_test.c:286,308). */
void          tpurmChannelInjectError(TpurmChannel *ch);
/* Robust-channel recovery: clear a latched channel error so new work can
 * proceed (reference: per-channel RC, src/nvidia/src/kernel/gpu/rc/). */
void          tpurmChannelResetError(TpurmChannel *ch);
/* Non-replayable fault kinds (reference: CE/PBDMA engine faults,
 * uvm_gpu_non_replayable_faults.c; watchdog kernel_rc_watchdog.c). */
enum {
    TPU_RC_CE_FAULT = 1,
    TPU_RC_WATCHDOG_TIMEOUT = 2,
};

/* Per-channel error notifier (reference: error notifiers on every
 * channel): invoked by the RC service for every non-replayable fault
 * attributed to this channel.  Runs under the RC registry lock: the
 * callback must not create or destroy channels. */
typedef void (*TpurmChannelErrorNotifier)(void *ctx, uint64_t value,
                                          uint32_t kind);
void          tpurmChannelSetErrorNotifier(TpurmChannel *ch,
                                           TpurmChannelErrorNotifier cb,
                                           void *ctx);
/* Fault injection: stall the channel executor for ms before its next
 * push (drives the RC watchdog in tests). */
void          tpurmChannelInjectStall(TpurmChannel *ch, uint32_t ms);

/* ------------------------------------------------------------- tracker */

/* Cross-channel completion dependencies (reference: uvm_tracker.c — a
 * set of (channel, value) entries; same-channel entries collapse to the
 * max value; completed entries are pruned on query). */
#define TPU_TRACKER_INLINE 8

typedef struct {
    TpurmChannel *ch;
    uint64_t value;            /* max value added for this channel      */
    uint64_t minValue;         /* min value added (failure attribution
                                * window for tpurmChannelWaitRange)     */
} TpuTrackerEntry;

typedef struct {
    uint32_t count, capacity;
    TpuTrackerEntry *entries;           /* inlineEntries until it grows */
    TpuTrackerEntry inlineEntries[TPU_TRACKER_INLINE];
} TpuTracker;

void      tpuTrackerInit(TpuTracker *t);
void      tpuTrackerDeinit(TpuTracker *t);
TpuStatus tpuTrackerAdd(TpuTracker *t, TpurmChannel *ch, uint64_t value);
TpuStatus tpuTrackerAddTracker(TpuTracker *dst, const TpuTracker *src);
/* Prunes completed entries; true when nothing is outstanding. */
bool      tpuTrackerIsCompleted(TpuTracker *t);
/* Waits every entry (draining failures too), clears the tracker, and
 * returns the first failure status if any entry's channel faulted. */
TpuStatus tpuTrackerWait(TpuTracker *t);

/* ---------------------------------------------------------- pushbuffer */

/* Multi-segment pushes carved from a per-channel pushbuffer ring with
 * cpu_put/gpu_get semantics (reference: uvm_pushbuffer.h:33-90 — space
 * is reclaimed as the consumer's get pointer passes it; reservation
 * back-pressures when the ring is full).  A push's segments execute as
 * one channel entry and complete under one tracker value. */
typedef struct TpuPush {
    TpurmChannel *ch;
    void *segs;                         /* chunk in the pushbuffer */
    uint32_t nsegs, maxSegs;
    uint64_t pbEndOffset;               /* monotonic pb offset after chunk */
} TpuPush;

TpuStatus tpuPushBegin(TpurmChannel *ch, uint32_t maxSegs, TpuPush *p);
TpuStatus tpuPushCopySeg(TpuPush *p, void *dst, const void *src,
                         uint64_t bytes);
/* Segment with an executor-side transform (TPU_CE_COMP_* format from
 * ce.h; 0 = plain copy).  The tpuce compression stage rides this: the
 * executor quantizes+dequantizes the payload in place of memmove. */
TpuStatus tpuPushCopySegEx(TpuPush *p, void *dst, const void *src,
                           uint64_t bytes, uint32_t xform);
/* Segment with executor-side CRC32C sealing (tpushield): after the
 * copy (and any xform) the executor computes one CRC32C per crcStride
 * bytes of the DESTINATION into consecutive crcOut cells — sealing
 * overlaps the copy on the executor thread instead of serializing
 * after it.  bytes must be a multiple of crcStride; crcOut must stay
 * valid until the push's tracker value completes. */
TpuStatus tpuPushCopySegCrc(TpuPush *p, void *dst, const void *src,
                            uint64_t bytes, uint32_t xform,
                            uint32_t *crcOut, uint64_t crcStride);
/* Submit; returns the tracker value (0 on failure).  If t is non-NULL the
 * (channel, value) pair is recorded there.  An empty push (no segments)
 * is submitted as a no-op marker — useful as a completion fence. */
uint64_t  tpuPushEnd(TpuPush *p, TpuTracker *t);
/* Abandon a begun push without submitting: its pushbuffer chunk is
 * released directly (no channel entry is created and no tracker value
 * is produced). */
void      tpuPushAbort(TpuPush *p);

/* --------------------------------------------------------- diagnostics */

/* Journal ring dump into caller buffer; returns bytes written. */
size_t tpurmJournalDump(char *buf, size_t bufSize);
/* Monotonic named counter read (pinned bytes, pushes, copies...). */
uint64_t tpurmCounterGet(const char *name);

/* procfs analog (reference: nv-procfs.c, uvm_procfs.c:36-49): virtual
 * observability nodes rendered on demand.  Paths accept both tpurm and
 * the reference's /proc/driver/nvidia spellings; debug-gated nodes
 * (counters, journal) require registry procfs_debug=1.  The LD_PRELOAD
 * shim serves open("/proc/driver/...") of these nodes via memfd. */
size_t tpurmProcfsRead(const char *path, char *buf, size_t bufSize);
size_t tpurmProcfsList(char *buf, size_t bufSize);
int    tpurmProcfsIsNode(const char *path);

#ifdef __cplusplus
}
#endif

#endif /* TPURM_TPURM_H */
