/*
 * tpurm — TPU resource-manager runtime: status codes.
 *
 * Values are the stable NV_STATUS ABI (reference:
 * src/common/sdk/nvidia/inc/nvstatuscodes.h) so that reference userspace
 * (tests/cxl_p2p_test.c) sees the error codes it expects.  Only the subset
 * the TPU build uses is defined.
 */
#ifndef TPURM_STATUS_H
#define TPURM_STATUS_H

#include <stdint.h>

typedef uint32_t TpuStatus;

#define TPU_OK                            0x00000000u
#define TPU_ERR_GPU_IS_LOST               0x0000000Fu
#define TPU_ERR_INSERT_DUPLICATE_NAME     0x00000019u
#define TPU_ERR_INSUFFICIENT_RESOURCES    0x0000001Au
#define TPU_ERR_INVALID_ADDRESS           0x0000001Eu
#define TPU_ERR_INVALID_ARGUMENT          0x0000001Fu
#define TPU_ERR_INVALID_CLASS             0x00000022u
#define TPU_ERR_INVALID_CLIENT            0x00000023u
#define TPU_ERR_INVALID_COMMAND           0x00000024u
#define TPU_ERR_INVALID_DEVICE            0x00000026u
#define TPU_ERR_INVALID_LIMIT             0x0000002Eu
#define TPU_ERR_INVALID_OBJECT_HANDLE     0x00000033u
#define TPU_ERR_INVALID_OBJECT_PARENT     0x00000036u
#define TPU_ERR_INVALID_PARAM_STRUCT      0x0000003Au
#define TPU_ERR_INVALID_STATE             0x00000040u
#define TPU_ERR_NO_MEMORY                 0x00000051u
#define TPU_ERR_NOT_SUPPORTED             0x00000056u
#define TPU_ERR_OBJECT_NOT_FOUND          0x00000057u
#define TPU_ERR_OPERATING_SYSTEM          0x00000059u
#define TPU_ERR_STATE_IN_USE              0x00000063u

/* Recovery-path error classes (fork-local; outside the reference's
 * nvstatuscodes range so they can never be confused with ABI codes):
 *   PAGE_QUARANTINED — the page faulted fatally through every bounded
 *     retry and has been retired onto a poison mapping;
 *   RETRAIN_FAILED   — an ICI link could not be retrained and no
 *     degraded route exists;
 *   RETRY_EXHAUSTED  — a transient-error recovery loop (copy/fault/
 *     RDMA) ran out of attempts;
 *   DEVICE_RESET     — the op's result is fenced by a full-device
 *     reset generation bump (a stale tracker/completion crossed a
 *     tpurmDeviceReset; the caller must re-issue against the new
 *     generation);
 *   PAGE_POISONED    — tpushield verified a sealed page against its
 *     CRC, the re-fetch ladder found no recovery source, and the page
 *     was poisoned + its backing retired.  Containment: only the
 *     OWNING sequence sees this status (the scheduler retires that
 *     stream with an error); co-tenants are untouched and no device
 *     reset runs. */
#define TPU_ERR_PAGE_QUARANTINED          0x00000070u
#define TPU_ERR_RETRAIN_FAILED            0x00000071u
#define TPU_ERR_RETRY_EXHAUSTED           0x00000072u
#define TPU_ERR_DEVICE_RESET              0x00000073u
#define TPU_ERR_PAGE_POISONED             0x00000074u

const char *tpuStatusToString(TpuStatus status);

#endif /* TPURM_STATUS_H */
