/*
 * tpurm — wire ABI: ioctl numbers, object classes, control commands, and
 * parameter struct layouts.
 *
 * This is the *stable userspace ABI* the reference exposes and which this
 * framework preserves bit-exactly so reference userspace runs unchanged
 * (north star, BASELINE.json).  Layout facts verified against:
 *   - ioctl escapes:      reference tests/cxl_p2p_test.c:28-31,
 *                         kernel-open/common/inc/nv-ioctl-numbers.h
 *   - NVOS21/54/00:       reference tests/cxl_p2p_test.c:70-95 (8-byte
 *                         alignment traps noted at :147-149)
 *   - CXL control cmds:   src/common/sdk/nvidia/inc/ctrl/ctrl2080/
 *                         ctrl2080bus.h:1430-1549 (cmds 0x20801833-36)
 *   - class ids:          NV01_ROOT/NV01_DEVICE_0/NV20_SUBDEVICE_0
 *
 * Everything else in tpurm is TPU-native design; only this header is
 * ABI-constrained.
 */
#ifndef TPURM_ABI_H
#define TPURM_ABI_H

#include <stdint.h>
#include <sys/ioctl.h>

#ifdef __cplusplus
extern "C" {
#endif

/* ---------------------------------------------------------------- escapes */

#define TPU_IOCTL_MAGIC        'F'
#define TPU_ESC_RM_FREE        0x29
#define TPU_ESC_RM_CONTROL     0x2a
#define TPU_ESC_RM_ALLOC       0x2b

/* ----------------------------------------------------------- object model */

#define TPU_CLASS_ROOT         0x00000000u  /* NV01_ROOT: client           */
#define TPU_CLASS_DEVICE       0x00000080u  /* NV01_DEVICE_0               */
#define TPU_CLASS_SUBDEVICE    0x00002080u  /* NV20_SUBDEVICE_0            */

/* ------------------------------------------------------ NVOS param blocks */

/* NV_ESC_RM_ALLOC payload (NVOS21_PARAMETERS layout). */
typedef struct {
    uint32_t hRoot;
    uint32_t hObjectParent;
    uint32_t hObjectNew;
    uint32_t hClass;
    uint64_t pAllocParms;       /* user pointer to class-specific params */
    uint32_t paramsSize;
    uint32_t status;
} TpuRmAllocParams;

/* NV_ESC_RM_CONTROL payload (NVOS54_PARAMETERS layout). */
typedef struct {
    uint32_t hClient;
    uint32_t hObject;
    uint32_t cmd;
    uint32_t flags;
    uint64_t params;            /* user pointer, 8-byte aligned slot */
    uint32_t paramsSize;
    uint32_t status;
} TpuRmControlParams;

/* NV_ESC_RM_FREE payload (NVOS00_PARAMETERS layout). */
typedef struct {
    uint32_t hRoot;
    uint32_t hObjectParent;
    uint32_t hObjectOld;
    uint32_t status;
} TpuRmFreeParams;

#define TPU_ESC_RM_FREE_IOCTL    _IOWR(TPU_IOCTL_MAGIC, TPU_ESC_RM_FREE,    TpuRmFreeParams)
#define TPU_ESC_RM_CONTROL_IOCTL _IOWR(TPU_IOCTL_MAGIC, TPU_ESC_RM_CONTROL, TpuRmControlParams)
#define TPU_ESC_RM_ALLOC_IOCTL   _IOWR(TPU_IOCTL_MAGIC, TPU_ESC_RM_ALLOC,   TpuRmAllocParams)

/* -------------------------------------------- class-specific alloc params */

/* NV01_DEVICE_0 alloc params (NV0080_ALLOC_PARAMETERS layout; the aligned(8)
 * attributes reproduce the reference's explicit alignment). */
typedef struct {
    uint32_t deviceId;
    uint32_t hClientShare;
    uint32_t hTargetClient;
    uint32_t hTargetDevice;
    uint32_t flags;
    uint64_t vaSpaceSize      __attribute__((aligned(8)));
    uint64_t vaStartInternal  __attribute__((aligned(8)));
    uint64_t vaLimitInternal  __attribute__((aligned(8)));
    uint32_t vaMode;
} TpuDeviceAllocParams;

/* NV20_SUBDEVICE_0 alloc params. */
typedef struct {
    uint32_t subDeviceId;
} TpuSubdeviceAllocParams;

/* ----------------------------------------------- NV0000 (client) controls */

#define TPU_CTRL_CMD_SYSTEM_GET_P2P_CAPS_V2   0x00000127u
#define TPU_CTRL_CMD_GPU_GET_ATTACHED_IDS     0x00000201u
#define TPU_CTRL_CMD_GPU_GET_PROBED_IDS       0x00000214u
#define TPU_CTRL_CMD_GPU_ATTACH_IDS           0x00000215u

#define TPU_CTRL_MAX_PROBED_DEVICES   32
#define TPU_CTRL_MAX_ATTACHED_DEVICES 32
#define TPU_CTRL_ATTACH_ALL_PROBED    0x0000ffffu
#define TPU_CTRL_INVALID_DEVICE_ID    0xffffffffu

typedef struct {
    uint32_t gpuIds[TPU_CTRL_MAX_PROBED_DEVICES];
    uint32_t excludedGpuIds[TPU_CTRL_MAX_PROBED_DEVICES];
} TpuCtrlGetProbedIdsParams;

typedef struct {
    uint32_t gpuIds[TPU_CTRL_MAX_PROBED_DEVICES];
    uint32_t failedId;
} TpuCtrlAttachIdsParams;

typedef struct {
    uint32_t gpuIds[TPU_CTRL_MAX_ATTACHED_DEVICES];
} TpuCtrlGetAttachedIdsParams;

/* NV0000_CTRL_CMD_SYSTEM_GET_P2P_CAPS_V2 param subset.  Caps bits mirror
 * the reference's p2p caps (platform/p2p/p2p_caps.c), including the
 * fork-added CXL connectivity (client_resource.c:597-616); ICI plays the
 * NVLINK role (SURVEY.md §2.7). */
#define TPU_P2P_CAPS_READS_SUPPORTED   0x1u
#define TPU_P2P_CAPS_WRITES_SUPPORTED  0x2u
#define TPU_P2P_CAPS_ICI_SUPPORTED     0x4u   /* NVLINK analog */
#define TPU_P2P_CAPS_ATOMICS_SUPPORTED 0x8u
#define TPU_P2P_CAPS_CXL_SUPPORTED     0x10u  /* fork delta */

#define TPU_CTRL_P2P_MAX_GPUS 8

typedef struct {
    uint32_t gpuIds[TPU_CTRL_P2P_MAX_GPUS];   /* IN: wire ids */
    uint32_t gpuCount;                        /* IN */
    uint32_t p2pCaps;                         /* OUT: common caps mask */
    uint32_t busPeerIds[TPU_CTRL_P2P_MAX_GPUS * TPU_CTRL_P2P_MAX_GPUS];
                                              /* OUT: hop counts, ~0 = none */
} TpuCtrlGetP2pCapsV2Params;

/* -------------------------------------- NV2080 (subdevice) CXL controls
 * The four fork-added commands (ctrl2080bus.h:1430-1549). */

#define TPU_CTRL_CMD_BUS_GET_CXL_INFO           0x20801833u
#define TPU_CTRL_CMD_BUS_CXL_P2P_DMA_REQUEST    0x20801834u
#define TPU_CTRL_CMD_BUS_REGISTER_CXL_BUFFER    0x20801835u
#define TPU_CTRL_CMD_BUS_UNREGISTER_CXL_BUFFER  0x20801836u

typedef struct {
    uint8_t  bIsLinkUp;
    uint8_t  bMemoryExpander;
    uint32_t nrLinks;
    uint32_t maxNrLinks;
    uint32_t linkMask;
    uint32_t perLinkBwMBps;
    uint32_t cxlVersion;
    uint32_t remoteType;
} TpuCtrlGetCxlInfoParams;

#define TPU_CXL_REMOTE_TYPE_CPU 1

typedef struct {
    uint64_t baseAddress;
    uint64_t size;
    uint32_t cxlVersion;
    uint64_t bufferHandle;      /* out */
} TpuCtrlRegisterCxlBufferParams;

typedef struct {
    uint64_t bufferHandle;
} TpuCtrlUnregisterCxlBufferParams;

typedef struct {
    uint64_t cxlBufferHandle;
    uint64_t gpuOffset;
    uint64_t cxlOffset;
    uint64_t size;
    uint32_t flags;
    uint32_t transferId;        /* out */
} TpuCtrlCxlP2pDmaRequestParams;

/* DMA flags: bit 0 = direction (0: device->CXL, 1: CXL->device), bit 1 =
 * async (ctrl2080bus.h DRF _DIRECTION 0:0, _ASYNC 1:1). */
#define TPU_CXL_DMA_FLAG_DEV_TO_CXL 0x0u
#define TPU_CXL_DMA_FLAG_CXL_TO_DEV 0x1u
#define TPU_CXL_DMA_FLAG_ASYNC      0x2u

/* Limits (reference: p2p_cxl.c:137,140; nv-p2p.c:1173). */
#define TPU_CXL_MAX_BUFFER_BYTES    (1ull << 40)
#define TPU_CXL_MAX_BUFFERS         256
#define TPU_CXL_MAX_PIN_PAGES       (1u << 28)
#define TPU_CXL_PAGE_SIZE_4K        4096ull
#define TPU_CXL_PAGE_SIZE_2M        (2ull * 1024 * 1024)
/* Single-copy clamp (reference: p2p_cxl.c:617-621). */
#define TPU_CE_COPY_CLAMP           0xFFFFF000ull

/* ------------------------------------------------ FB memory + BAR mapping
 * NV01_MEMORY_LOCAL_USER (cl0040.h:34) + NVOS33/NVOS34 map/unmap
 * escapes (nv_escape.h:42-43, nvos.h NVOS33_PARAMETERS).  The device
 * arena is the BAR1 analog: a memory object is a PMM chunk of the
 * arena, and mapping returns a CPU pointer into the coherent shadow.
 * Writes through the mapping reach chip HBM at unmap (or any fence) —
 * the write-combining flush analog. */

#define TPU_CLASS_MEMORY_LOCAL 0x00000040u  /* NV01_MEMORY_LOCAL_USER */

/* NV_MEMORY_ALLOCATION_PARAMS subset (nvos.h:1591-1625): the fields the
 * vidmem path consumes; surface/layout fields are display-domain and
 * designed out (SURVEY §7).  size is IN/OUT: the PMM rounds up to its
 * power-of-two chunk ladder and allocations are capped at the 2 MB VA
 * block granularity (reference chunk ceiling, uvm_pmm_gpu.h:60-85) —
 * larger surfaces compose multiple objects. */
typedef struct {
    uint32_t owner;
    uint32_t type;
    uint32_t flags;
    uint64_t size      __attribute__((aligned(8)));  /* IN/OUT */
    uint64_t alignment __attribute__((aligned(8)));
    uint64_t offset    __attribute__((aligned(8)));  /* OUT: FB offset */
} TpuMemoryAllocParams;

#define TPU_ESC_RM_MAP_MEMORY   0x4E
#define TPU_ESC_RM_UNMAP_MEMORY 0x4F

/* NVOS33_PARAMETERS (nvos.h:1827-1837). */
typedef struct {
    uint32_t hClient;
    uint32_t hDevice;
    uint32_t hMemory;
    uint64_t offset         __attribute__((aligned(8)));
    uint64_t length         __attribute__((aligned(8)));
    uint64_t pLinearAddress __attribute__((aligned(8)));  /* OUT */
    uint32_t status;
    uint32_t flags;
} TpuMapMemoryParams;

/* NVOS34_PARAMETERS (nvos.h:1844-1852 subset). */
typedef struct {
    uint32_t hClient;
    uint32_t hDevice;
    uint32_t hMemory;
    uint64_t pLinearAddress __attribute__((aligned(8)));
    uint32_t status;
    uint32_t flags;
} TpuUnmapMemoryParams;

/* --------------------------------------------------- RM event notification
 * NV01_EVENT_OS_EVENT analog (reference: cl0005.h:35-47 alloc params;
 * event_notification.c delivery; nvgputypes.h:57-64 NvNotification).
 * The reference signals an OS event handle passed in `data`; the tpurm
 * userspace redesign points `data` at a TpuOsEvent in client memory —
 * `signaled` is a futex word the engine increments and FUTEX_WAKEs, and
 * the notification record is filled in the reference's documented order
 * (timeStamp, info32, info16, status last). */

#define TPU_CLASS_EVENT_OS     0x00000079u  /* NV01_EVENT_OS_EVENT */

typedef struct {
    uint32_t hParentClient;
    uint32_t hSrcResource;
    uint32_t hClass;
    uint32_t notifyIndex;
    uint64_t data __attribute__((aligned(8)));  /* TpuOsEvent* */
} TpuEventAllocParams;

/* NvNotification layout, byte-exact (nvgputypes.h:57-64: 16 bytes). */
typedef struct {
    uint32_t timeStampNanoseconds[2];
    uint32_t info32;
    uint16_t info16;
    uint16_t status;
} TpuNvNotification;

typedef struct {
    uint32_t signaled;          /* futex word; incremented per delivery */
    uint32_t reserved;
    TpuNvNotification rec;
} TpuOsEvent;

#define TPU_NOTIFICATION_STATUS_IN_PROGRESS  0x8000u
#define TPU_NOTIFICATION_STATUS_DONE_SUCCESS 0x0000u

/* NV2080_CTRL_CMD_EVENT_SET_NOTIFICATION (ctrl2080event.h:79-94). */
#define TPU_CTRL_CMD_EVENT_SET_NOTIFICATION 0x20800301u
#define TPU_EVENT_ACTION_DISABLE 0x0u
#define TPU_EVENT_ACTION_SINGLE  0x1u
#define TPU_EVENT_ACTION_REPEAT  0x2u

typedef struct {
    uint32_t event;             /* notifier index */
    uint32_t action;
    uint8_t  bNotifyState;
    uint32_t info32;
    uint16_t info16;
} TpuCtrlEventSetNotificationParams;

/* Notifier indices (cl2080_notification.h vocabulary).  CXL DMA
 * completion is a fork-space index: the reference's CXL fork exposes
 * completion only via the async tracker; tpurm also delivers it as an
 * RM event so clients need not poll.
 *
 * TPU_NOTIFIER_CXL_DMA delivery contract (per-hClient scoping):
 * completion events are SCOPED to the client that issued the DMA
 * request — when two clients arm identical listeners on this index,
 * each hears only its own transfers complete (a completion is a
 * statement about the requesting client's ordering, not a device-wide
 * broadcast; a concurrent client's copy-back discipline must not
 * trigger on someone else's DMA).  Fallback: when the REQUESTING
 * client holds no armed listener at this index, the completion is
 * delivered BROADCAST (scope 0) so pure observers — monitoring
 * clients armed on the notifier without issuing DMA themselves —
 * still hear it rather than the event being silently dropped.
 * Corollary: a DMA-issuing client MUST arm its own listener to keep
 * scoped delivery in force; if any issuer skips arming, its
 * completions fall back to broadcast and other armed clients will
 * hear them. */
#define TPU_NOTIFIER_SW        0u    /* NV2080_NOTIFIERS_SW */
#define TPU_NOTIFIER_RC_ERROR  37u   /* NV2080_NOTIFIERS_RC_ERROR */
#define TPU_NOTIFIER_CXL_DMA   180u  /* fork: async CXL DMA completion */

/* UVM_ADVISE_COMPRESSIBLE contract (uvm.h UVM_TPU_SET_COMPRESSIBLE /
 * uvmSetCompressible / memring ADVISE subcode COMPRESSIBLE).
 *
 * The advise opts a VA span into the tpuce page-compression stage:
 * host->HBM uploads quantize the payload (fp8 e4m3 or int8 with a
 * per-stripe absmax scale, payload treated as float32) and HBM->host
 * downloads dequantize it; the wire carries ~1/4 the raw bytes
 * (tpuce_compressed_bytes_in/out counters).  This is a PRECISION
 * CONTRACT, not a hint: data in an advised span round-trips lossily
 * (<= 1/16 relative error per element for fp8; <= absmax/254 absolute
 * for int8).  It is safe exactly when the payload is float data that
 * tolerates reduced precision — KV-cache pages are the intended user
 * — and UNSAFE for integers, pointers, packed structs, or any
 * bit-exact data; those ranges must keep the default (OFF).
 * Non-finite elements pass through bit-exact, the advise splits
 * ranges at the span edges like every other policy (a sub-span of an
 * allocation carries its own setting, inherited across splits), and
 * a compressed stripe that exhausts its copy retries falls back to
 * the lossless path rather than corrupting the destination. */

#ifdef __cplusplus
}
#endif

#endif /* TPURM_ABI_H */
