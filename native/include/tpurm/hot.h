/*
 * tpuhot — hotness-driven placement: access counters, tree-density
 * prefetch governance, and thrashing PIN/THROTTLE hints.
 *
 * The perf-policy subsystem the reference ships as three cooperating
 * modules (uvm_gpu_access_counters.c — "whether access counters will
 * trigger migrations"; uvm_perf_thrashing.h:33-46 — PIN/THROTTLE
 * hints; uvm_perf_prefetch.c — tree-based prefetch region growth),
 * rebuilt over this engine's fault service path.  Three policies hang
 * off one per-VA-block tracker:
 *
 *   TRACKER — every fault service (CPU demand faults and device-access
 *     spans both land in service_one) feeds the faulted block's access
 *     counter with ONE relaxed atomic add; recency and a decaying
 *     score (half-life registry "hot_decay_ms") are folded lazily at
 *     policy evaluation points, so the fault hot path pays a single
 *     uncontended RMW and nothing else.
 *
 *   PREFETCH GOVERNOR — speculative region growth around a fault is
 *     governed twice: bottom-up TREE DENSITY (the candidate region
 *     doubles only while the enclosing aligned region's recently-
 *     accessed page density stays above "hot_prefetch_density_pct" —
 *     the reference's bitmap-tree shape) and MEASURED PRECISION (the
 *     per-block speculation cap grows where hits/(hits+useless) from
 *     the PR-7 effectiveness counters stays above
 *     "hot_prefetch_min_precision" percent, and shrinks where it
 *     decays).  This replaces the fixed fault-count lookahead: a
 *     block whose speculation keeps getting evicted untouched stops
 *     speculating; a streaming block escalates to whole-block staging.
 *
 *   THRASH DETECTOR — a block whose pages migrate HBM<->host in
 *     alternating directions more than "hot_thrash_count" times inside
 *     "hot_thrash_window_ms" gets a PIN hint (resident device-side,
 *     exempt from uvmLruPopVictim and therefore uvmTierEvictBytes
 *     until the pin lapses after "hot_pin_ms"; CPU reads duplicate
 *     against the pinned copy) — or, when the device arena has less
 *     than "hot_pin_headroom_pct" free, a THROTTLE hint: the faulting
 *     stream's services on that block are each delayed
 *     "hot_throttle_us" for "hot_throttle_ms", so the resident side
 *     keeps its working set instead of losing a pin fight it cannot
 *     win.
 *
 *   VICTIM SCORER — eviction consumes the same coldness signal:
 *     uvmLruPopVictim's SLO walk breaks (over-quota, priority) ties by
 *     decayed hotness instead of raw list position, and the plain LRU
 *     path runs a bounded coldness scan ("hot_victim_scan" candidates)
 *     so a released-but-hot block near the cold end is not the next
 *     victim merely because of its list position.  tpusched's
 *     preempt-victim choice reads the same scores over the candidate
 *     sequence's backing span (tpurmHotSpanScore).
 *
 * Every policy decision (pin-or-throttle, governor cap adjust, victim
 * reorder) is evaluated under the hot.decide inject site with bounded
 * degrade-to-no-op: an injected hit skips exactly that decision and
 * counts hot_inject_skips — the EXACT reconciliation invariant is
 * hits == hot_inject_skips.  PINs always lapse (pinExpiryNs), so an
 * armed site can delay placement policy but never wedge forward
 * progress.
 *
 * Observability: tpurm_hot_* counters and per-device
 * tpurm_hot_device_score gauges in the Prometheus exposition,
 * /proc/driver/tpurm/hotness (top-K hot blocks with pin/throttle
 * state), hot.pin / hot.throttle trace instants.
 */
#ifndef TPURM_HOT_H
#define TPURM_HOT_H

#include <stdbool.h>
#include <stdint.h>

#include "status.h"

#ifdef __cplusplus
extern "C" {
#endif

/* Lifetime policy/engine statistics (process-global). */
typedef struct TpuHotStats {
    uint64_t pins;              /* PIN decisions taken                 */
    uint64_t throttles;         /* THROTTLE decisions taken            */
    uint64_t throttleDelays;    /* services actually delayed           */
    uint64_t thrashPages;       /* pages crossing the thrash threshold */
    uint64_t prefetchGrown;     /* governor cap doublings              */
    uint64_t prefetchShrunk;    /* governor cap halvings               */
    uint64_t victimReorders;    /* coldness-scan victim swaps          */
    uint64_t injectSkips;       /* hot.decide hits degraded to no-op   */
    uint64_t decisions;         /* policy decisions evaluated          */
} TpuHotStats;

void tpurmHotStatsGet(TpuHotStats *out);

/* Decayed per-device hotness gauge: access pressure recently fed to
 * blocks homed on devInst (integer fixed-point, 1024 per page touch;
 * half-life "hot_decay_ms").  The Prometheus exposition renders the
 * same value as tpurm_hot_device_score{dev=}. */
uint64_t tpurmHotDeviceScore(uint32_t devInst);

/* Decayed hotness of the managed blocks covering [addr, addr+len):
 * the mean per-block score, 0 when the span resolves to no managed
 * range.  This is the coldness signal tpusched's preempt-victim
 * choice consumes (uvm/hot.py span_score). */
uint64_t tpurmHotSpanScore(uint64_t addr, uint64_t len);

/* Zero the process-global policy stats and per-device gauges (tests;
 * per-block tracker state lives with the blocks and decays on its
 * own). */
void tpurmHotStatsReset(void);

#ifdef __cplusplus
}
#endif

#endif /* TPURM_HOT_H */
