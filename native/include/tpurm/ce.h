/*
 * tpuce — the multi-channel copy-engine subsystem.
 *
 * One scheduled path for every bulk byte the stack moves (block
 * migration, tier evict/promote, memring coalesced runs, ICI peer
 * copies, memdesc transfers).  Reference analog: the mem_mgr CE utils
 * layer striping work across parallel FIFO channels with per-channel
 * trackers (SURVEY layer 3; ce_utils.c / channel pools per CE type in
 * uvm_channel.c).
 *
 * Structure:
 *
 *   manager   — one per device (lazy), owning N logical copy channels
 *               (registry "tpuce_channels", default 4 capped at the
 *               online CPUs — each channel is an executor thread; the
 *               manager grows the device's CE pool to N so RC
 *               reset-and-replay covers every channel it schedules).  Each channel
 *               carries its own submission queue (the underlying DMA
 *               channel's GPFIFO), completion tracker values, and
 *               busy/bytes accounting exported as tpuce_ch{N}_bytes /
 *               tpuce_ch{N}_busy_ns counters.
 *   scheduler — block-granular copies split into stripes (registry
 *               "tpuce_stripe_bytes", default 512 KB) and each stripe
 *               lands on the channel with the fewest outstanding
 *               bytes (load balance by queue depth, not round robin).
 *               Splits are counted (tpuce_stripe_splits).
 *   batch     — the submission object: copies striped across the
 *               manager pipeline freely; tpuCeBatchWait() fences them
 *               all with PER-STRIPE recovery — a failed stripe is
 *               retried (bounded, RC reset-and-replay + backoff) or,
 *               when compressed, re-sent through the lossless path, so
 *               a stripe failure never corrupts the destination.
 *
 * Compression: an opt-in quantize-on-upload / dequantize-on-download
 * stage on the host<->HBM path for ranges advised COMPRESSIBLE (KV
 * cache pages tolerate reduced precision; exact ranges stay lossless).
 * The stripe payload is treated as float32 and quantized to fp8-e4m3
 * or int8 (per-stripe absmax scale); the destination receives the
 * DEQUANTIZED working copy at full stride — device compute always
 * sees valid float data — while the transport-layer saving is modeled
 * by accounting stripe wire bytes at the compressed size
 * (tpuce_compressed_bytes_in/out vs tpuce_compressed_bytes_raw).
 * Non-finite elements pass through bit-exact (never quantized), and a
 * stripe that exhausts its retries compressed falls back to the
 * lossless path (tpuce_lossless_fallbacks).
 *
 * Failure injection: the "ce.copy" site (TPUMEM_INJECT_CE_COPY) fires
 * per stripe-submission attempt.  Exact accounting invariant
 * (test-checked): every ce.copy hit bumps exactly one of
 * tpuce_inject_retries / tpuce_inject_errors; the general
 * tpuce_retries / tpuce_stripe_errors counters cover injected and
 * real failures alike.
 */
#ifndef TPURM_CE_H
#define TPURM_CE_H

#include <stdbool.h>
#include <stdint.h>

#include "status.h"
#include "tpurm.h"

#ifdef __cplusplus
extern "C" {
#endif

/* Compression formats (low bits) + direction flag.  The direction only
 * steers wire-byte accounting (bytes_in = toward HBM, bytes_out = back
 * toward host); the transform itself is direction-agnostic. */
enum {
    TPU_CE_COMP_NONE = 0,
    TPU_CE_COMP_FP8 = 1,          /* e4m3: 3 mantissa bits, max 448   */
    TPU_CE_COMP_INT8 = 2,         /* symmetric, per-stripe absmax     */
    TPU_CE_COMP_FMT_MASK = 0x0F,
    TPU_CE_COMP_DOWNLOAD = 0x10,  /* accounting: HBM -> host direction */
};

#define TPUCE_MAX_CHANNELS 8
#define TPUCE_BATCH_STRIPES 64
#define TPUCE_GATHER_SEGS 32

typedef struct TpuCeMgr TpuCeMgr;

/* The per-device manager (lazy; NULL when the device does not exist or
 * its channel pool could not be built). */
TpuCeMgr *tpuCeMgrGet(uint32_t devInst);

/* Channels currently schedulable (registry tpuce_channels, re-read per
 * copy through a generation cache so tests/bench can flip it with
 * tpuRegistryBump; clamped to what the manager could create). */
uint32_t tpuCeMgrChannels(TpuCeMgr *m);

/* Per-channel accounting snapshot: bytes executed, busy-ns in the
 * executor, and bytes submitted-but-not-retired.  Any of the out
 * pointers may be NULL. */
TpuStatus tpuCeChannelStats(TpuCeMgr *m, uint32_t ch, uint64_t *bytes,
                            uint64_t *busyNs, uint64_t *outstanding);

/* One discontiguous copy segment (gather submission). */
typedef struct {
    void *dst;
    const void *src;
    uint64_t len;
} TpuCeSeg;

/* One stripe in flight (internal layout exposed so batches can live on
 * the caller's stack; treat as opaque).  A stripe is either one
 * contiguous span (nsegs == 0: dst/src/len) or a GATHER of up to
 * TPUCE_GATHER_SEGS discontiguous segments riding one push — one
 * channel, one submission, one recovery domain (restores the old
 * 64-segs-per-push economy for fragmented memdesc copies). */
typedef struct {
    TpurmChannel *ch;
    uint32_t chIdx;
    uint32_t comp;
    uint32_t attempts;
    bool injected;                /* current failure came from ce.copy */
    uint64_t val;                 /* tracker value (0: not in flight)  */
    TpuStatus subSt;              /* submission status when val == 0   */
    uint64_t gen;                 /* device generation at submission:
                                   * a completion that crosses a full-
                                   * device reset is STALE — the wait
                                   * rejects it (tpuce_stale_
                                   * completions) and replays the
                                   * stripe against the new generation */
    void *dst;
    const void *src;
    uint64_t len;                 /* contiguous span / gather total    */
    /* tpushield seal stage: per-crcStride CRC32C of the destination,
     * computed on the executor thread (channel.c CopySeg contract);
     * survives stripe retry / lossless fallback so a re-sent stripe
     * reseals what it actually stored. */
    uint32_t *crcOut;
    uint64_t crcStride;
    uint32_t nsegs;               /* 0: contiguous; else gather count  */
    TpuCeSeg segs[TPUCE_GATHER_SEGS];
} TpuCeStripe;

/* A submission batch: stripes pipeline across the channel pool until
 * the batch is waited.  Completion is a DEP-JOIN over the stripes'
 * (channel, value) tracker pairs, not a submission-order barrier:
 * tpuCeBatchWait completes stripes in RETIREMENT order (ready ones
 * reap without blocking, counted tpuce_ooo_completions), and when the
 * stripe table fills mid-copy the staging path reaps what already
 * retired — blocking on the OLDEST stripe only if nothing has
 * (tpuce_dep_join_waits) — instead of draining the whole batch, so
 * stripes from different copies keep interleaving on the channels. */
typedef struct {
    TpuCeMgr *m;
    uint32_t n;
    TpuStatus st;                 /* sticky first terminal error */
    uint64_t deadlineNs;          /* 0 = none; absolute tpuNowNs bound:
                                   * once past it, stripe recovery stops
                                   * retrying and fails fast (counted
                                   * tpuce_deadline_expired) — the hung-
                                   * op ladder's fail-fast floor        */
    uint8_t done[TPUCE_BATCH_STRIPES];  /* reaped out of order         */
    TpuCeStripe stripes[TPUCE_BATCH_STRIPES];
} TpuCeBatch;

TpuStatus tpuCeBatchBegin(TpuCeMgr *m, TpuCeBatch *b);

/* Arm a completion deadline on the batch (applies to every stripe wait
 * from now on; 0 clears). */
void tpuCeBatchSetDeadline(TpuCeBatch *b, uint64_t deadlineNs);

/* Stripe [src, src+len) -> dst across the pool.  comp is a
 * TPU_CE_COMP_* format (|DOWNLOAD for accounting); ineligible payloads
 * (unaligned / tiny) silently degrade to lossless. */
TpuStatus tpuCeBatchCopy(TpuCeBatch *b, void *dst, const void *src,
                         uint64_t len, uint32_t comp);

/* Copy with the tpushield seal stage: the executor threads compute one
 * CRC32C per crcStride bytes of the destination into consecutive
 * crcOut cells (cell k covers dst[k*crcStride, (k+1)*crcStride)) while
 * the stripes retire — sealing overlaps the copy.  len must be a
 * multiple of crcStride; crcOut must stay valid until the batch
 * fences.  Compression composes: the CRC covers the DEQUANTIZED bytes
 * the destination actually holds. */
TpuStatus tpuCeBatchCopyCrc(TpuCeBatch *b, void *dst, const void *src,
                            uint64_t len, uint32_t comp,
                            uint32_t *crcOut, uint64_t crcStride);

/* Gather submission: n (<= TPUCE_GATHER_SEGS) discontiguous segments
 * as ONE stripe on the least-loaded channel — one push, one recovery
 * domain.  Lossless only (fragmented payloads never compress). */
TpuStatus tpuCeBatchCopySegs(TpuCeBatch *b, const TpuCeSeg *segs,
                             uint32_t n);

/* Fence the batch: waits every stripe, running per-stripe recovery
 * (bounded retry, lossless fallback).  Idempotent; returns the first
 * terminal error.  In-flight stripes are always drained before return
 * (the caller may free the surfaces on error). */
TpuStatus tpuCeBatchWait(TpuCeBatch *b);

/* Async handoff: move the batch's completion dependencies into the
 * caller's tracker instead of waiting.  Per-stripe retry does NOT run
 * on this path — failures surface at the caller's tracker wait
 * (range-checked), exactly like a raw channel dependency. */
TpuStatus tpuCeBatchHandoff(TpuCeBatch *b, TpuTracker *t);

/* Convenience: Begin + Copy + Wait. */
TpuStatus tpuCeCopySync(TpuCeMgr *m, void *dst, const void *src,
                        uint64_t len, uint32_t comp);

/* Drain every channel the manager schedules (fence semantics for
 * concurrent submitters: all work submitted before the call completes
 * before it returns). */
TpuStatus tpuCeMgrDrain(TpuCeMgr *m);

#ifdef __cplusplus
}
#endif

#endif /* TPURM_CE_H */
