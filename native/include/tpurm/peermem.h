/*
 * tpurm peermem — TPU-direct RDMA: expose device-resident managed memory
 * to third-party DMA engines (RDMA NICs).
 *
 * Re-design of the reference's P2P export API + peermem module
 * (kernel-open/nvidia/nv-p2p.c:646 nvidia_p2p_get_pages / dma_map_pages /
 * put_pages; kernel-open/nvidia-peermem/nvidia-peermem.c acquire:198,
 * get_pages:216, dma_map:245, free-callback revoke:134).  Flow parity:
 *
 *   tpuP2pGetPages     — pin a managed VA range's pages device-side
 *                        (migrates to HBM, pins against eviction) and
 *                        return their bus addresses,
 *   tpuP2pDmaMapPages  — per-NIC IOVA mapping of a page table,
 *   tpuP2pPutPages     — unpin + release,
 *   free callback      — invoked when the underlying range is freed
 *                        (uvmMemFree/VaSpaceDestroy) so the RDMA consumer
 *                        revokes its MR, exactly the reference's
 *                        invalidation contract.
 *
 * TPU shape: "bus addresses" are offsets into the device HBM window (the
 * window a NIC would BAR-map); the fake-device backend resolves them to
 * host pointers so the loopback RDMA test can actually move bytes.
 */
#ifndef TPURM_PEERMEM_H
#define TPURM_PEERMEM_H

#include <stddef.h>
#include <stdint.h>

#include "status.h"
#include "uvm.h"

#ifdef __cplusplus
extern "C" {
#endif

#define TPU_P2P_PAGE_TABLE_VERSION 0x10001
#define TPU_P2P_PAGE_SIZE_DEFAULT  (64 * 1024)

typedef struct {
    uint64_t busAddress;        /* offset into the device HBM window */
} TpuP2pPage;

typedef struct {
    uint32_t version;
    uint32_t pageSize;
    uint32_t devInst;
    uint32_t entries;
    TpuP2pPage *pages;
} TpuP2pPageTable;

typedef struct {
    uint32_t version;
    uint32_t nicId;
    uint32_t entries;
    uint64_t *iova;             /* per-page NIC-visible addresses */
} TpuP2pDmaMapping;

/* Invalidation callback (reference: free-callback at nv-p2p.c get_pages):
 * called when the underlying managed range goes away. */
typedef void (*TpuP2pFreeCallback)(void *data);

/* Pin [va, va+size) of vs device-side and build a page table.  The range
 * is migrated to the device's HBM tier and pinned against eviction until
 * tpuP2pPutPages. */
TpuStatus tpuP2pGetPages(UvmVaSpace *vs, uint32_t devInst, uint64_t va,
                         uint64_t size, TpuP2pPageTable **out,
                         TpuP2pFreeCallback cb, void *cbData);
TpuStatus tpuP2pDmaMapPages(TpuP2pPageTable *pt, uint32_t nicId,
                            TpuP2pDmaMapping **out);
TpuStatus tpuP2pDmaUnmapPages(TpuP2pDmaMapping *map);
TpuStatus tpuP2pPutPages(TpuP2pPageTable *pt);

/* Fake-backend resolution for loopback tests: host pointer for a bus
 * address (NULL when out of range). */
void *tpuP2pBusToPtr(uint32_t devInst, uint64_t busAddress);

/* ------------------------------------------------------ dma-buf analog */

/* Export a device HBM window as a refcounted handle another subsystem
 * can import (reference: nv-dmabuf.c exporting GPU memory as dma-buf). */
typedef struct TpuDmabuf TpuDmabuf;

TpuStatus  tpuDmabufExport(uint32_t devInst, uint64_t offset, uint64_t size,
                           TpuDmabuf **out);
TpuStatus  tpuDmabufImport(TpuDmabuf *buf, void **ptr, uint64_t *size);
TpuStatus  tpuDmabufInfo(TpuDmabuf *buf, uint32_t *devInst,
                         uint64_t *offset, uint64_t *size);
void       tpuDmabufPut(TpuDmabuf *buf);   /* drop one reference */
TpuDmabuf *tpuDmabufGet(TpuDmabuf *buf);   /* take one reference */

#ifdef __cplusplus
}
#endif

#endif /* TPURM_PEERMEM_H */
