/*
 * tpuflow test: flow-id ABI arithmetic, open/account/close ledger
 * semantics (hop masking, unmatched drops, bucket-sum <= wall),
 * per-tenant SLO histograms (batched feed, quantiles, counts), the
 * blame-ordered report, and the Prometheus/proc render shapes.
 */
#define _GNU_SOURCE
#include <stdint.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>
#include <time.h>

#include "tpurm/flow.h"

extern uint64_t tpurmCounterGet(const char *name);
extern size_t tpurmProcfsRead(const char *path, char *buf, size_t n);

#define CHECK(cond) do { \
    if (!(cond)) { \
        fprintf(stderr, "FAIL %s:%d: %s\n", __FILE__, __LINE__, #cond); \
        return 1; \
    } } while (0)

static int test_flow_id_abi(void)
{
    uint64_t f = tpurmFlowMint(7, 0xABCD1234u);
    CHECK(TPU_FLOW_TENANT(f) == 7);
    CHECK(TPU_FLOW_REQUEST(f) == 0xABCD1234u);
    CHECK(TPU_FLOW_HOP(f) == 0);
    uint64_t h3 = TPU_FLOW_WITH_HOP(f, 3);
    CHECK(TPU_FLOW_HOP(h3) == 3);
    CHECK(TPU_FLOW_KEY(h3) == TPU_FLOW_KEY(f));
    CHECK(TPU_FLOW_TENANT(h3) == 7);
    /* Tenant/request saturate into their fields, never bleed. */
    uint64_t g = tpurmFlowMint(0x1FFFF, 0);
    CHECK(TPU_FLOW_TENANT(g) == 0xFFFF);
    CHECK(TPU_FLOW_REQUEST(g) == 0);
    return 0;
}

static int test_ledger_and_blame(void)
{
    tpurmFlowResetAll();
    uint64_t f = tpurmFlowMint(3, 42);
    CHECK(tpurmFlowOpen(f) == TPU_OK);
    CHECK(tpurmFlowOpen(f) == TPU_OK);            /* idempotent */

    /* Accounting via a HOPPED id lands on the same ledger. */
    tpurmFlowAccount(f, TPU_FLOW_B_QUEUED, 1000000);
    tpurmFlowAccount(TPU_FLOW_WITH_HOP(f, 2), TPU_FLOW_B_ICI, 500000);
    tpurmFlowAccount(f, TPU_FLOW_B_COPY, 250000);
    tpurmFlowTokens(f, 16);

    /* Unmatched keys drop, never invent ledger entries. */
    uint64_t before = tpurmCounterGet("tpurm_flows_opened");
    tpurmFlowAccount(tpurmFlowMint(9, 999), TPU_FLOW_B_COPY, 777);
    CHECK(tpurmCounterGet("tpurm_flows_opened") == before);

    struct timespec ts = { 0, 2000000 };          /* ensure wall > 0 */
    nanosleep(&ts, NULL);
    uint64_t wall = 0;
    CHECK(tpurmFlowClose(f, &wall) == TPU_OK);
    CHECK(wall > 0);

    TpuFlowRec recs[8];
    uint32_t n = tpurmFlowReport(recs, 8);
    CHECK(n == 1);
    CHECK(recs[0].flow == TPU_FLOW_KEY(f));
    CHECK(recs[0].tenant == 3);
    CHECK(recs[0].state == 2);
    CHECK(recs[0].tokens == 16);
    CHECK(recs[0].bucketNs[TPU_FLOW_B_QUEUED] == 1000000);
    CHECK(recs[0].bucketNs[TPU_FLOW_B_ICI] == 500000);
    CHECK(recs[0].bucketNs[TPU_FLOW_B_COPY] == 250000);
    CHECK(recs[0].wallNs == wall);
    /* Soundness: what this test accounted fits inside the wall. */
    uint64_t bucketSum = 0;
    for (uint32_t b = 0; b < TPU_FLOW_B_COUNT; b++)
        bucketSum += recs[0].bucketNs[b];
    CHECK(bucketSum <= recs[0].wallNs);

    /* Per-tenant blame mirrors the bucket adds. */
    CHECK(tpurmSloBlameNs(3, TPU_FLOW_B_QUEUED) == 1000000);
    CHECK(tpurmSloBlameNs(3, TPU_FLOW_B_ICI) == 500000);
    return 0;
}

static int test_report_ordering(void)
{
    tpurmFlowResetAll();
    for (uint32_t i = 0; i < 5; i++) {
        uint64_t f = tpurmFlowMint(1, 100 + i);
        CHECK(tpurmFlowOpen(f) == TPU_OK);
        /* Blame grows with i: the report must come back descending. */
        tpurmFlowAccount(f, TPU_FLOW_B_PREEMPTED, (i + 1) * 10000ull);
    }
    TpuFlowRec recs[8];
    uint32_t n = tpurmFlowReport(recs, 8);
    CHECK(n == 5);
    for (uint32_t i = 1; i < n; i++) {
        uint64_t prev = recs[i - 1].bucketNs[TPU_FLOW_B_PREEMPTED];
        uint64_t cur = recs[i].bucketNs[TPU_FLOW_B_PREEMPTED];
        CHECK(prev >= cur);
    }
    CHECK(recs[0].bucketNs[TPU_FLOW_B_PREEMPTED] == 50000);
    /* max smaller than the population truncates, keeping the top. */
    TpuFlowRec top2[2];
    CHECK(tpurmFlowReport(top2, 2) == 2);
    CHECK(top2[0].bucketNs[TPU_FLOW_B_PREEMPTED] == 50000);
    CHECK(top2[1].bucketNs[TPU_FLOW_B_PREEMPTED] == 40000);
    return 0;
}

static int test_slo_hists(void)
{
    tpurmFlowResetAll();
    /* Batched feed: 100 samples at 2ms + a 5-sample tail at 100ms. */
    tpurmSloRecordN(5, TPU_SLO_ITL, 2000000, 100);
    tpurmSloRecordN(5, TPU_SLO_ITL, 100000000, 5);
    tpurmSloRecord(5, TPU_SLO_TTFT, 30000000);
    CHECK(tpurmSloCount(5, TPU_SLO_ITL) == 105);
    CHECK(tpurmSloCount(5, TPU_SLO_TTFT) == 1);
    uint64_t p50 = tpurmSloQuantileNs(5, TPU_SLO_ITL, 0.50);
    CHECK(p50 > 1900000 && p50 < 2100000);
    uint64_t p99 = tpurmSloQuantileNs(5, TPU_SLO_ITL, 0.99);
    CHECK(p99 > 90000000);
    /* Other tenants stay empty (per-tenant isolation). */
    CHECK(tpurmSloCount(6, TPU_SLO_ITL) == 0);
    return 0;
}

static int test_renders(void)
{
    tpurmFlowResetAll();
    uint64_t f = tpurmFlowMint(2, 7);
    CHECK(tpurmFlowOpen(f) == TPU_OK);
    tpurmFlowAccount(f, TPU_FLOW_B_FAULT, 123456);
    tpurmFlowTokens(f, 4);
    tpurmSloRecordN(2, TPU_SLO_ITL, 3000000, 4);
    tpurmSloRecord(2, TPU_SLO_TTFT, 8000000);

    enum { CAP = 1 << 20 };
    char *buf = malloc(CAP);
    CHECK(buf);

    size_t n = tpurmProcfsRead("/proc/driver/tpurm/metrics", buf, CAP);
    CHECK(n > 0);
    buf[n] = '\0';
    CHECK(strstr(buf, "# TYPE tpurm_slo_ttft_ns histogram"));
    CHECK(strstr(buf, "# TYPE tpurm_slo_itl_ns histogram"));
    CHECK(strstr(buf, "tpurm_slo_itl_ns_count{tenant=\"2\"} 4"));
    CHECK(strstr(buf, "tpurm_slo_ttft_ns_count{tenant=\"2\"} 1"));
    CHECK(strstr(buf, "tpurm_slo_itl_ns_bucket{tenant=\"2\",le=\"+Inf\"} 4"));
    CHECK(strstr(buf,
                 "tpurm_slo_blame_ns{tenant=\"2\",bucket=\"fault\"} 123456"));
    CHECK(strstr(buf, "tpurm_flows_open 1"));

    n = tpurmProcfsRead("/proc/driver/tpurm/flows", buf, CAP);
    CHECK(n > 0);
    buf[n] = '\0';
    CHECK(strstr(buf, "tenant"));
    CHECK(strstr(buf, "queued"));
    CHECK(strstr(buf, "0x"));                     /* the flow row */
    free(buf);
    return 0;
}

static int test_bucket_names(void)
{
    const char *seen[TPU_FLOW_B_COUNT];
    for (uint32_t b = 0; b < TPU_FLOW_B_COUNT; b++) {
        const char *nm = tpurmFlowBucketName(b);
        CHECK(nm && nm[0]);
        for (uint32_t j = 0; j < b; j++)
            CHECK(strcmp(seen[j], nm) != 0);
        seen[b] = nm;
    }
    CHECK(tpurmFlowBucketName(TPU_FLOW_B_COUNT) == NULL);
    return 0;
}

int main(void)
{
    if (test_flow_id_abi())
        return 1;
    if (test_ledger_and_blame())
        return 1;
    if (test_report_ordering())
        return 1;
    if (test_slo_hists())
        return 1;
    if (test_renders())
        return 1;
    if (test_bucket_names())
        return 1;
    tpurmFlowResetAll();
    printf("flow_test OK\n");
    return 0;
}
