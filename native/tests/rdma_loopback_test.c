/*
 * RDMA loopback that LEAVES THE PROCESS (VERDICT r2 task 8).
 *
 * Parent = the host with the TPU engine: registers managed memory as an
 * MR through the ib-core analog (acquire -> get_pages -> dma_map,
 * reference nvidia-peermem.c:198,245,515).  Child = the emulated NIC:
 * a forked process that receives the device arena memfd + control memfd
 * + IOVA list over a unix socket (SCM_RIGHTS), maps the "BAR", and
 * does DMA reads/writes at the IOVAs.  The mid-MR free fires the
 * free-callback chain (reference :134): the core revokes the MR and the
 * child observes `revoked` in the shared control page and stops.
 *
 * The child only touches received fds and raw memory — no engine calls
 * — so forking from the threaded parent is safe.
 */
#define _GNU_SOURCE
#include <errno.h>
#include <stdatomic.h>
#include <stdint.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>
#include <sys/mman.h>
#include <sys/socket.h>
#include <sys/wait.h>
#include <unistd.h>

#include "tpurm/peermem.h"
#include "tpurm/rdma.h"
#include "tpurm/tpurm.h"
#include "tpurm/uvm.h"

#define CHECK(cond)                                                     \
    do {                                                                \
        if (!(cond)) {                                                  \
            fprintf(stderr, "FAIL %s:%d: %s (errno %d)\n", __FILE__,    \
                    __LINE__, #cond, errno);                            \
            exit(1);                                                    \
        }                                                               \
    } while (0)

enum { MAX_PAGES = 64 };

typedef struct {
    uint64_t arenaSize;
    uint32_t pageSize;
    uint32_t entries;
    uint64_t iova[MAX_PAGES];
} MrWire;

/* Send a description + two fds over the socket. */
static void send_mr(int sock, const MrWire *w, int arenaFd, int ctrlFd)
{
    struct iovec iov = { (void *)w, sizeof(*w) };
    char cbuf[CMSG_SPACE(2 * sizeof(int))];
    struct msghdr msg = { .msg_iov = &iov, .msg_iovlen = 1,
                          .msg_control = cbuf,
                          .msg_controllen = sizeof(cbuf) };
    struct cmsghdr *cm = CMSG_FIRSTHDR(&msg);
    cm->cmsg_level = SOL_SOCKET;
    cm->cmsg_type = SCM_RIGHTS;
    cm->cmsg_len = CMSG_LEN(2 * sizeof(int));
    int fds[2] = { arenaFd, ctrlFd };
    memcpy(CMSG_DATA(cm), fds, sizeof(fds));
    CHECK(sendmsg(sock, &msg, 0) == (ssize_t)sizeof(*w));
}

static void recv_mr(int sock, MrWire *w, int *arenaFd, int *ctrlFd)
{
    struct iovec iov = { w, sizeof(*w) };
    char cbuf[CMSG_SPACE(2 * sizeof(int))];
    struct msghdr msg = { .msg_iov = &iov, .msg_iovlen = 1,
                          .msg_control = cbuf,
                          .msg_controllen = sizeof(cbuf) };
    CHECK(recvmsg(sock, &msg, 0) == (ssize_t)sizeof(*w));
    struct cmsghdr *cm = CMSG_FIRSTHDR(&msg);
    CHECK(cm && cm->cmsg_type == SCM_RIGHTS);
    int fds[2];
    memcpy(fds, CMSG_DATA(cm), sizeof(fds));
    *arenaFd = fds[0];
    *ctrlFd = fds[1];
}

/* --------------------------------------------------- the emulated NIC */

static int nic_process(int sock)
{
    MrWire w;
    int arenaFd, ctrlFd;
    recv_mr(sock, &w, &arenaFd, &ctrlFd);

    uint8_t *bar = mmap(NULL, w.arenaSize, PROT_READ | PROT_WRITE,
                        MAP_SHARED, arenaFd, 0);
    if (bar == MAP_FAILED)
        return 10;
    TpuIbMrControl *ctrl = mmap(NULL, 4096, PROT_READ | PROT_WRITE,
                                MAP_SHARED, ctrlFd, 0);
    if (ctrl == MAP_FAILED)
        return 11;

    /* RDMA READ of page 0 (the host seeded 0xA7): echo the byte back. */
    uint64_t off0 = w.iova[0] & TPU_IB_IOVA_OFFSET_MASK;
    uint8_t readBack = bar[off0 + 5];
    /* RDMA WRITE into page 1. */
    uint64_t off1 = w.iova[1] & TPU_IB_IOVA_OFFSET_MASK;
    memset(bar + off1, 0x1C, w.pageSize);

    /* Report phase-1 results. */
    uint8_t report[2] = { 1, readBack };
    if (write(sock, report, sizeof(report)) != (ssize_t)sizeof(report))
        return 12;

    /* Spin-wait (bounded) for mid-MR revocation from the host side. */
    for (int i = 0; i < 20000; i++) {
        if (atomic_load(&ctrl->revoked))
            break;
        usleep(1000);
    }
    if (!atomic_load(&ctrl->revoked))
        return 13;
    atomic_store(&ctrl->consumerAck, 1);
    return 0;
}

/* ------------------------------------------------------------- host */

int main(void)
{
    /* Managed buffer through the uvm surface. */
    int fd = tpurm_open("/dev/nvidia-uvm");
    CHECK(fd >= 0);
    UvmInitializeParams init = { 0, 0 };
    CHECK(tpurm_ioctl(fd, UVM_INITIALIZE, &init) == 0 &&
          init.rmStatus == TPU_OK);
    UvmRegisterGpuParams reg = { 0 };
    CHECK(tpurm_ioctl(fd, UVM_REGISTER_GPU, &reg) == 0 &&
          reg.rmStatus == TPU_OK);
    UvmTpuAllocManagedParams alloc = { .length = 4 << 20 };
    CHECK(tpurm_ioctl(fd, UVM_TPU_ALLOC_MANAGED, &alloc) == 0 &&
          alloc.rmStatus == TPU_OK);
    volatile uint8_t *buf = (volatile uint8_t *)(uintptr_t)alloc.base;
    for (uint64_t i = 0; i < (4 << 20); i += 4096)
        buf[i + 5] = 0xA7;       /* seed, incl. page 0 byte 5 */

    /* Register the MR: pins the span into device HBM. */
    TpuIbMr *mr = NULL;
    CHECK(tpuIbRegMr(alloc.base, 4 << 20, /*nicId=*/3, &mr) == TPU_OK);
    CHECK(tpuIbMrValid(mr) == 1);

    int arenaFd, ctrlFd;
    MrWire w = { 0 };
    const uint64_t *iova;
    CHECK(tpuIbMrDescribe(mr, &arenaFd, &ctrlFd, &w.pageSize, &w.entries,
                          &iova) == TPU_OK);
    CHECK(w.entries >= 2);
    if (w.entries > MAX_PAGES)
        w.entries = MAX_PAGES;
    memcpy(w.iova, iova, w.entries * sizeof(uint64_t));
    /* IOVAs carry the NIC tag in the top byte. */
    CHECK((w.iova[0] >> 56) == 3);
    TpurmDevice *dev = tpurmDeviceGet(0);
    w.arenaSize = tpurmDeviceHbmSize(dev);

    int socks[2];
    CHECK(socketpair(AF_UNIX, SOCK_STREAM, 0, socks) == 0);
    pid_t pid = fork();
    CHECK(pid >= 0);
    if (pid == 0) {
        close(socks[0]);
        _exit(nic_process(socks[1]));
    }
    close(socks[1]);
    send_mr(socks[0], &w, arenaFd, ctrlFd);

    /* Phase 1: the NIC read the seeded byte and wrote page 1. */
    uint8_t report[2] = { 0, 0 };
    CHECK(read(socks[0], report, sizeof(report)) == (ssize_t)2);
    CHECK(report[0] == 1);
    CHECK(report[1] == 0xA7);            /* RDMA READ saw device bytes */

    /* The NIC's RDMA WRITE is visible to the engine: CPU-fault the
     * second page home and check the bytes. */
    CHECK(buf[w.pageSize + 17] == (0x1C));

    /* Mid-MR invalidation: free the allocation UNDER the live MR. */
    UvmFreeParams fr = { .base = alloc.base, .rmStatus = 0xFFFFFFFFu };
    CHECK(tpurm_ioctl(fd, UVM_FREE, &fr) == 0 && fr.rmStatus == TPU_OK);
    CHECK(tpuIbMrValid(mr) == 0);        /* revoked via free callback */

    int status = 0;
    CHECK(waitpid(pid, &status, 0) == pid);
    CHECK(WIFEXITED(status) && WEXITSTATUS(status) == 0);

    /* The consumer acknowledged the revocation before exiting. */
    TpuIbMrControl *ctrl = mmap(NULL, 4096, PROT_READ, MAP_SHARED,
                                ctrlFd, 0);
    CHECK(ctrl != MAP_FAILED);
    CHECK(atomic_load(&ctrl->consumerAck) == 1);
    munmap((void *)ctrl, 4096);

    CHECK(tpuIbDeregMr(mr) == TPU_OK);
    close(socks[0]);
    CHECK(tpurm_close(fd) == 0);
    printf("rdma_loopback_test OK\n");
    return 0;
}
