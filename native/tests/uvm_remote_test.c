/*
 * Multi-process managed memory: a SECOND process attaches a window onto
 * the engine host's managed range and faults pages the owner migrated
 * to HBM and CXL — the last structural piece of the per-fd VA-space
 * model (reference: any process opens /dev/nvidia-uvm and gets its own
 * VA space, uvm.c:144,792; the cross-process share itself follows the
 * CUDA-IPC model, not fork inheritance).
 *
 * Flow:
 *   parent (engine host): serves the broker in-process, allocates a
 *     managed range, writes a pattern, migrates spans to HBM and CXL
 *     (host backing now stale for those spans), spawns the child.
 *   child (fresh exec): attaches its own VA space, maps the owner
 *     range's backing via uvmRemoteAttach, and READS the migrated
 *     spans — each CPU fault forwards over the broker, the owner
 *     services it (migrating device-resident pages home into the
 *     shared backing), and only then does the child's window open.
 *     The child then WRITES a byte (write fault -> host-exclusive in
 *     the owner) and checks its own tools queue saw its fault events.
 *   parent: waits, then verifies the child's write through its own
 *     mapping and that its own tools queue saw its own (migration)
 *     events.
 */
#define _GNU_SOURCE
#include <stdio.h>
#include <stdlib.h>
#include <string.h>
#include <sys/wait.h>
#include <unistd.h>

#include "tpurm/tpurm.h"
#include "tpurm/uvm.h"

#define CHECKR(cond) do { \
    if (!(cond)) { \
        fprintf(stderr, "FAIL %s:%d: %s\n", __FILE__, __LINE__, #cond); \
        return 1; \
    } } while (0)

#define RANGE_BYTES (4ull << 20)
#define HBM_SPAN    (2ull << 20)          /* [0, 2M) -> HBM */
#define CXL_SPAN    (1ull << 20)          /* [2M, 3M) -> CXL */
#define WRITE_OFF   (64 * 1024 + 17)      /* inside the HBM span */

static uint8_t pat(uint64_t off)
{
    return (uint8_t)((off * 7 + 3) & 0xFF);
}

static int child_main(const char *sock, uint64_t ownerBase)
{
    setenv("TPURM_BROKER", sock, 1);

    UvmVaSpace *vs = NULL;
    CHECKR(uvmVaSpaceCreate(&vs) == TPU_OK);
    UvmToolsSession *ts = NULL;
    CHECKR(uvmToolsSessionCreate(vs, 256, &ts) == TPU_OK);
    uvmToolsEnableEvents(ts, ~0ull);

    void *base = NULL;
    uint64_t size = 0;
    CHECKR(uvmRemoteAttach(vs, ownerBase, &base, &size) == TPU_OK);
    CHECKR(size == RANGE_BYTES);

    /* Faulting reads across all three residencies the owner set up:
     * HBM span, CXL span, host tail.  Every access below SIGSEGVs
     * locally, forwards over the broker, and must read OWNER truth. */
    const volatile uint8_t *p = base;
    uint64_t offs[] = { 0, 4096, HBM_SPAN - 1,            /* HBM span */
                        HBM_SPAN, HBM_SPAN + CXL_SPAN - 1,/* CXL span */
                        HBM_SPAN + CXL_SPAN,              /* host tail */
                        RANGE_BYTES - 1 };
    for (size_t i = 0; i < sizeof(offs) / sizeof(offs[0]); i++) {
        uint8_t got = p[offs[i]];
        if (got != pat(offs[i])) {
            fprintf(stderr, "FAIL: off %llu got 0x%02x want 0x%02x\n",
                    (unsigned long long)offs[i], got, pat(offs[i]));
            return 1;
        }
    }

    /* Read-then-write on the same page: the read opens the window
     * READ-ONLY, so the write must RE-FAULT and forward as a write
     * (owner goes host-exclusive) before the store lands in the
     * SHARED backing, visible to the owner. */
    CHECKR(p[WRITE_OFF] == pat(WRITE_OFF));
    ((volatile uint8_t *)base)[WRITE_OFF] = 0x5A;
    CHECKR(p[WRITE_OFF] == 0x5A);

    /* The child's OWN tools queue saw the child's fault events. */
    UvmEvent evs[64];
    size_t n = uvmToolsReadEvents(ts, evs, 64);
    size_t cpuFaults = 0;
    for (size_t i = 0; i < n; i++)
        if (evs[i].type == UVM_EVENT_CPU_FAULT)
            cpuFaults++;
    CHECKR(cpuFaults >= 3);

    CHECKR(uvmRemoteDetach(vs, base) == TPU_OK);
    uvmToolsSessionDestroy(ts);
    uvmVaSpaceDestroy(vs);
    printf("uvm_remote child OK (%zu cpu-fault events)\n", cpuFaults);
    return 0;
}

int main(int argc, char **argv)
{
    if (argc == 4 && strcmp(argv[1], "--child") == 0)
        return child_main(argv[2], strtoull(argv[3], NULL, 0));

    unsetenv("TPURM_BROKER");       /* parent IS the engine host */
    char sock[64];
    snprintf(sock, sizeof(sock), "/tmp/tpurm_uvmr_%d.sock", getpid());
    CHECKR(tpurmBrokerServe(sock) == TPU_OK);

    UvmVaSpace *vs = NULL;
    CHECKR(uvmVaSpaceCreate(&vs) == TPU_OK);
    CHECKR(uvmRegisterDevice(vs, 0) == TPU_OK);
    UvmToolsSession *ts = NULL;
    CHECKR(uvmToolsSessionCreate(vs, 256, &ts) == TPU_OK);
    uvmToolsEnableEvents(ts, ~0ull);

    void *base = NULL;
    CHECKR(uvmMemAlloc(vs, RANGE_BYTES, &base) == TPU_OK);
    uint8_t *b = base;
    for (uint64_t i = 0; i < RANGE_BYTES; i++)
        b[i] = pat(i);

    /* Owner moves spans device-ward: the host backing goes STALE for
     * them (and PROT_NONE in the owner) until a fault migrates them
     * home. */
    UvmLocation hbm = { .tier = UVM_TIER_HBM, .devInst = 0 };
    UvmLocation cxl = { .tier = UVM_TIER_CXL, .devInst = 0 };
    CHECKR(uvmMigrate(vs, b, HBM_SPAN, hbm, 0) == TPU_OK);
    CHECKR(uvmMigrate(vs, b + HBM_SPAN, CXL_SPAN, cxl, 0) == TPU_OK);
    UvmResidencyInfo ri;
    CHECKR(uvmResidencyInfo(vs, b, &ri) == TPU_OK);
    CHECKR(ri.residentHbm && !ri.residentHost);
    CHECKR(uvmResidencyInfo(vs, b + HBM_SPAN, &ri) == TPU_OK);
    CHECKR(ri.residentCxl && !ri.residentHost);

    char addrArg[32];
    snprintf(addrArg, sizeof(addrArg), "0x%llx",
             (unsigned long long)(uintptr_t)base);
    pid_t c = fork();
    if (c == 0) {
        execl(argv[0], argv[0], "--child", sock, addrArg, (char *)NULL);
        perror("execl");
        _exit(127);
    }
    int st = -1;
    waitpid(c, &st, 0);
    CHECKR(WIFEXITED(st) && WEXITSTATUS(st) == 0);

    /* The child's faults migrated the spans home and its write landed
     * in the shared backing: the owner reads it directly. */
    CHECKR(uvmResidencyInfo(vs, b, &ri) == TPU_OK);
    CHECKR(ri.residentHost);
    CHECKR(b[WRITE_OFF] == 0x5A);
    CHECKR(b[0] == pat(0));
    CHECKR(b[HBM_SPAN + 5] == pat(HBM_SPAN + 5));

    /* The parent's OWN tools queue saw the parent's events. */
    UvmEvent evs[128];
    size_t n = uvmToolsReadEvents(ts, evs, 128);
    size_t migrations = 0;
    for (size_t i = 0; i < n; i++)
        if (evs[i].type == UVM_EVENT_MIGRATION)
            migrations++;
    CHECKR(migrations >= 2);

    CHECKR(uvmMemFree(vs, base) == TPU_OK);
    uvmToolsSessionDestroy(ts);
    uvmVaSpaceDestroy(vs);
    unlink(sock);
    printf("uvm_remote_test OK (child faulted HBM/CXL pages home, "
           "%zu parent migration events)\n", migrations);
    return 0;
}
