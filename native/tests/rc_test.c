/*
 * Robust-channel recovery: non-replayable fault attribution through the
 * shadow buffer (CE faults -> notifier), the watchdog detecting a stuck
 * channel, and the auto-reset recovery policy.
 *
 * Reference analogs: uvm_gpu_non_replayable_faults.c (shadow-buffer
 * delivery + service), kernel_rc_watchdog.c (timeout detection),
 * per-channel error notifiers.
 */
#define _GNU_SOURCE
#include <stdatomic.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>
#include <time.h>
#include <unistd.h>

#include "tpurm/tpurm.h"

#define CHECK(cond)                                                     \
    do {                                                                \
        if (!(cond)) {                                                  \
            fprintf(stderr, "FAIL %s:%d: %s\n", __FILE__, __LINE__,     \
                    #cond);                                             \
            exit(1);                                                    \
        }                                                               \
    } while (0)

static _Atomic uint64_t g_notifiedValue;
static _Atomic uint32_t g_notifiedKind;
static _Atomic uint32_t g_notifyCount;

static void notifier(void *ctx, uint64_t value, uint32_t kind)
{
    (void)ctx;
    atomic_store(&g_notifiedValue, value);
    atomic_store(&g_notifiedKind, kind);
    atomic_fetch_add(&g_notifyCount, 1);
}

static void wait_notify_count(uint32_t want)
{
    for (int i = 0; i < 5000; i++) {
        if (atomic_load(&g_notifyCount) >= want)
            return;
        usleep(1000);
    }
    CHECK(!"notifier never fired");
}

int main(void)
{
    TpurmDevice *dev = tpurmDeviceGet(0);
    CHECK(dev != NULL);

    /* ---- CE fault -> shadow buffer -> notifier ---- */
    TpurmChannel *ch = tpurmChannelCreate(dev, TPURM_CE_ANY, 64);
    CHECK(ch != NULL);
    tpurmChannelSetErrorNotifier(ch, notifier, NULL);

    int autoReset = getenv("TPUMEM_RC_POLICY") &&
                    strcmp(getenv("TPUMEM_RC_POLICY"), "1") == 0;

    uint8_t src = 1, dst = 0;
    tpurmChannelInjectError(ch);
    uint64_t v = tpurmChannelPushCopy(ch, &dst, &src, 1);
    CHECK(v != 0);
    /* Latch is synchronous — but under auto-reset policy the RC service
     * may clear it before this wait observes it (that IS the policy:
     * the client never sees a recovered fault). */
    TpuStatus ws = tpurmChannelWait(ch, v);
    if (!autoReset)
        CHECK(ws != TPU_OK);
    wait_notify_count(1);
    CHECK(atomic_load(&g_notifiedValue) == v);
    CHECK(atomic_load(&g_notifiedKind) == TPU_RC_CE_FAULT);
    CHECK(tpurmCounterGet("rc_nonreplayable_faults") >= 1);

    /* ---- watchdog: a stalled channel with pending work barks ---- */
    uint64_t barksBefore = tpurmCounterGet("rc_watchdog_timeouts");
    tpurmChannelResetError(ch);
    tpurmChannelInjectStall(ch, 1200);     /* > rc_watchdog_timeout_ms */
    uint64_t v2 = tpurmChannelPushCopy(ch, &dst, &src, 1);
    CHECK(v2 != 0);
    /* The env (set by the Makefile run) pins period=50ms timeout=300ms:
     * the stall holds the fifo non-empty with no progress long enough. */
    for (int i = 0; i < 5000; i++) {
        if (tpurmCounterGet("rc_watchdog_timeouts") > barksBefore)
            break;
        usleep(1000);
    }
    CHECK(tpurmCounterGet("rc_watchdog_timeouts") > barksBefore);
    wait_notify_count(2);
    CHECK(atomic_load(&g_notifiedKind) == TPU_RC_WATCHDOG_TIMEOUT);
    /* The stalled push still completes once the stall expires. */
    CHECK(tpurmChannelWait(ch, v2) == TPU_OK);
    CHECK(dst == 1);

    tpurmChannelDestroy(ch);

    /* ---- rc_policy=1: auto-reset lets work flow after a CE fault ----
     * (policy read per delivery, so flipping the env var mid-process
     * has no effect; this binary is run with TPUMEM_RC_POLICY=1 by a
     * second Makefile invocation.) */
    if (autoReset) {
        TpurmChannel *ch2 = tpurmChannelCreate(dev, TPURM_CE_ANY, 64);
        CHECK(ch2 != NULL);
        uint64_t resetsBefore = tpurmCounterGet("rc_auto_resets");
        tpurmChannelInjectError(ch2);
        uint64_t v3 = tpurmChannelPushCopy(ch2, &dst, &src, 1);
        CHECK(v3 != 0);
        tpurmChannelWait(ch2, v3);   /* outcome depends on reset timing */
        /* RC service auto-resets THIS fault: new work succeeds WITHOUT
         * an explicit ResetError from the client. */
        for (int i = 0; i < 5000; i++) {
            if (tpurmCounterGet("rc_auto_resets") > resetsBefore)
                break;
            usleep(1000);
        }
        CHECK(tpurmCounterGet("rc_auto_resets") > resetsBefore);
        uint8_t d2 = 0, s2 = 9;
        uint64_t v4 = tpurmChannelPushCopy(ch2, &d2, &s2, 1);
        CHECK(v4 != 0 && tpurmChannelWait(ch2, v4) == TPU_OK);
        CHECK(d2 == 9);
        tpurmChannelDestroy(ch2);
        printf("rc_test OK (policy=auto-reset)\n");
        return 0;
    }

    printf("rc_test OK\n");
    return 0;
}
