/*
 * Channel/tracker/transfer-engine test.
 *
 * Native analog of the reference's uvm_channel_test.c (incl. the stress
 * shape of UVM_TEST_CHANNEL_STRESS) and uvm_ce_test.c: ring back-pressure,
 * tracker ordering, extent-split copies, error injection and latching.
 */
#include <pthread.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>

#include "tpurm/tpurm.h"

#define CHECK(cond) do { \
    if (!(cond)) { \
        fprintf(stderr, "FAIL %s:%d: %s\n", __FILE__, __LINE__, #cond); \
        return 1; \
    } } while (0)

int main(void)
{
    TpurmDevice *dev = tpurmDeviceGet(0);
    CHECK(dev != NULL);
    CHECK(tpurmDeviceHbmSize(dev) >= 64 * 1024 * 1024);

    /* Small ring to force back-pressure (min clamps to 32). */
    TpurmChannel *ch = tpurmChannelCreate(dev, TPURM_CE_ANY, 32);
    CHECK(ch != NULL);

    /* Stress: 10k pushes through a 32-deep ring, strict tracker order. */
    enum { N = 10000, BUF = 4096 };
    static char src[BUF], dst[BUF];
    uint64_t last = 0;
    for (int i = 0; i < N; i++) {
        memset(src, i & 0xff, BUF);
        uint64_t v = tpurmChannelPushCopy(ch, dst, src, BUF);
        CHECK(v == last + 1);
        last = v;
        if ((i & 1023) == 0) {
            CHECK(tpurmChannelWait(ch, v) == TPU_OK);
            CHECK(dst[0] == (char)(i & 0xff));
        }
    }
    CHECK(tpurmChannelWait(ch, last) == TPU_OK);
    CHECK(tpurmChannelCompletedValue(ch) == last);

    /* Error injection latches the channel. */
    tpurmChannelInjectError(ch);
    uint64_t bad = tpurmChannelPushCopy(ch, dst, src, BUF);
    CHECK(bad != 0);
    CHECK(tpurmChannelWait(ch, bad) == TPU_ERR_INVALID_STATE);
    tpurmChannelDestroy(ch);

    /* Transfer engine: extent-split copy through a paged memdesc. */
    /* Build a deliberately non-contiguous source: 8 pages alternating from
     * two separate arenas, so coalescing yields multiple extents. */
    enum { PG = 4096, PAGES = 8 };
    char *arenaA = aligned_alloc(PG, PG * PAGES);
    char *arenaB = aligned_alloc(PG, PG * PAGES);
    CHECK(arenaA && arenaB);
    uint64_t pageAddrs[PAGES];
    for (int i = 0; i < PAGES; i++) {
        char *page = (i % 2 == 0 ? arenaA : arenaB) + (uint64_t)(i / 2) * PG;
        memset(page, 0x10 + i, PG);
        pageAddrs[i] = (uint64_t)(uintptr_t)page;
    }

    /* This exercises the internal transfer engine through the CXL DMA path
     * instead of private headers: register buffer, DMA to device, readback. */
    /* (Direct tpuMemCopy is internal; the public route is the control op —
     *  covered in cxl_conformance_test. Here: device HBM arena copy via
     *  channel public API only.) */
    char *hbm = tpurmDeviceHbmBase(dev);
    TpurmChannel *ce = tpurmChannelCreate(dev, TPURM_CE_ANY, 0);
    CHECK(ce != NULL);
    for (int i = 0; i < PAGES; i++) {
        uint64_t v = tpurmChannelPushCopy(ce, hbm + (uint64_t)i * PG,
                                          (void *)(uintptr_t)pageAddrs[i], PG);
        CHECK(v > 0);
        last = v;
    }
    CHECK(tpurmChannelWait(ce, last) == TPU_OK);
    for (int i = 0; i < PAGES; i++)
        CHECK(hbm[(uint64_t)i * PG] == (char)(0x10 + i));
    tpurmChannelDestroy(ce);

    /* ---- tracker: cross-channel completion dependencies ---- */
    {
        TpurmChannel *c1 = tpurmChannelCreate(dev, TPURM_CE_ANY, 32);
        TpurmChannel *c2 = tpurmChannelCreate(dev, TPURM_CE_ANY, 32);
        CHECK(c1 && c2);
        static char t_src[PG], t_dst1[PG], t_dst2[PG];
        memset(t_src, 0x3C, PG);

        TpuTracker t;
        tpuTrackerInit(&t);
        uint64_t v1 = tpurmChannelPushCopy(c1, t_dst1, t_src, PG);
        uint64_t v2 = tpurmChannelPushCopy(c2, t_dst2, t_src, PG);
        CHECK(v1 && v2);
        CHECK(tpuTrackerAdd(&t, c1, v1) == TPU_OK);
        CHECK(tpuTrackerAdd(&t, c2, v2) == TPU_OK);
        /* Same-channel entries collapse to the max value. */
        uint64_t v1b = tpurmChannelPushCopy(c1, t_dst1, t_src, PG);
        CHECK(tpuTrackerAdd(&t, c1, v1b) == TPU_OK);
        CHECK(t.count == 2);
        CHECK(tpuTrackerWait(&t) == TPU_OK);
        CHECK(t.count == 0);
        CHECK(t_dst1[7] == 0x3C && t_dst2[7] == 0x3C);

        /* IsCompleted prunes as channels catch up. */
        uint64_t v3 = tpurmChannelPushCopy(c1, t_dst1, t_src, PG);
        tpuTrackerAdd(&t, c1, v3);
        while (!tpuTrackerIsCompleted(&t))
            ;
        CHECK(t.count == 0);

        /* A faulted channel propagates its error through the tracker,
         * and the other channel is still drained. */
        tpurmChannelInjectError(c1);
        uint64_t vb = tpurmChannelPushCopy(c1, t_dst1, t_src, PG);
        uint64_t vg = tpurmChannelPushCopy(c2, t_dst2, t_src, PG);
        tpuTrackerAdd(&t, c1, vb);
        tpuTrackerAdd(&t, c2, vg);
        CHECK(tpuTrackerWait(&t) == TPU_ERR_INVALID_STATE);
        CHECK(tpurmChannelCompletedValue(c2) >= vg);
        tpurmChannelResetError(c1);

        /* Growth past the inline capacity (dedup off: distinct channels). */
        TpurmChannel *many[TPU_TRACKER_INLINE + 4];
        static char many_dst[TPU_TRACKER_INLINE + 4][PG];
        for (unsigned i = 0; i < TPU_TRACKER_INLINE + 4; i++) {
            many[i] = tpurmChannelCreate(dev, TPURM_CE_ANY, 32);
            CHECK(many[i]);
            uint64_t v = tpurmChannelPushCopy(many[i], many_dst[i], t_src,
                                              PG);
            CHECK(tpuTrackerAdd(&t, many[i], v) == TPU_OK);
        }
        CHECK(t.count == TPU_TRACKER_INLINE + 4);
        CHECK(tpuTrackerWait(&t) == TPU_OK);
        for (unsigned i = 0; i < TPU_TRACKER_INLINE + 4; i++)
            tpurmChannelDestroy(many[i]);
        tpuTrackerDeinit(&t);
        tpurmChannelDestroy(c1);
        tpurmChannelDestroy(c2);
    }

    /* ---- pushbuffer: multi-segment pushes, wrap, back-pressure ---- */
    {
        /* Tiny pushbuffer forces wrap-around + reservation waits. */
        setenv("TPUMEM_PUSHBUFFER_SIZE_BYTES", "4096", 1);
        TpurmChannel *pc = tpurmChannelCreate(dev, TPURM_CE_ANY, 32);
        CHECK(pc != NULL);
        unsetenv("TPUMEM_PUSHBUFFER_SIZE_BYTES");

        /* DEPTH rotating buffer sets keep pipelining without racing a
         * worker still reading a buffer being rewritten: round r reuses
         * set r%DEPTH only after round r-DEPTH completed. */
        enum { ROUNDS = 512, SEGS = 16, DEPTH = 8 };
        static char p_src[DEPTH][SEGS][64], p_dst[DEPTH][SEGS][64];
        uint64_t lastv = 0, rvals[DEPTH] = { 0 };
        for (int r = 0; r < ROUNDS; r++) {
            int slot = r % DEPTH;
            if (rvals[slot])
                CHECK(tpurmChannelWait(pc, rvals[slot]) == TPU_OK);
            TpuPush push;
            CHECK(tpuPushBegin(pc, SEGS, &push) == TPU_OK);
            for (int s = 0; s < SEGS; s++) {
                memset(p_src[slot][s], (r + s) & 0xff, 64);
                CHECK(tpuPushCopySeg(&push, p_dst[slot][s],
                                     p_src[slot][s], 64) == TPU_OK);
            }
            uint64_t v = tpuPushEnd(&push, NULL);
            CHECK(v == lastv + 1);      /* one value per multi-seg push */
            lastv = v;
            rvals[slot] = v;
        }
        CHECK(tpurmChannelWait(pc, lastv) == TPU_OK);
        int lastSlot = (ROUNDS - 1) % DEPTH;
        for (int s = 0; s < SEGS; s++)
            CHECK(p_dst[lastSlot][s][63] == (char)((ROUNDS - 1 + s) & 0xff));

        /* Abort releases reserved space (no deadlock on refill). */
        TpuPush ab;
        CHECK(tpuPushBegin(pc, SEGS, &ab) == TPU_OK);
        tpuPushAbort(&ab);
        for (int r = 0; r < 8; r++) {
            TpuPush push;
            CHECK(tpuPushBegin(pc, SEGS, &push) == TPU_OK);
            CHECK(tpuPushCopySeg(&push, p_dst[0][0], p_src[0][0], 64) ==
                  TPU_OK);
            CHECK(tpuPushEnd(&push, NULL) != 0);
        }
        /* Empty push = completion fence. */
        TpuPush fence;
        CHECK(tpuPushBegin(pc, 1, &fence) == TPU_OK);
        uint64_t fv = tpuPushEnd(&fence, NULL);
        CHECK(fv != 0);
        CHECK(tpurmChannelWait(pc, fv) == TPU_OK);
        tpurmChannelDestroy(pc);
    }

    /* Counters moved. */
    CHECK(tpurmCounterGet("channel_pushes") >= N + PAGES);
    CHECK(tpurmCounterGet("channel_bytes_copied") >= (uint64_t)N * BUF);

    /* Journal captured the injected fault. */
    char buf[8192];
    size_t n = tpurmJournalDump(buf, sizeof(buf));
    CHECK(n > 0);
    CHECK(strstr(buf, "injected CE fault") != NULL);

    free(arenaA);
    free(arenaB);
    printf("channel_test OK\n");
    return 0;
}
