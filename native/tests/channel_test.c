/*
 * Channel/tracker/transfer-engine test.
 *
 * Native analog of the reference's uvm_channel_test.c (incl. the stress
 * shape of UVM_TEST_CHANNEL_STRESS) and uvm_ce_test.c: ring back-pressure,
 * tracker ordering, extent-split copies, error injection and latching.
 */
#include <pthread.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>

#include "tpurm/tpurm.h"

#define CHECK(cond) do { \
    if (!(cond)) { \
        fprintf(stderr, "FAIL %s:%d: %s\n", __FILE__, __LINE__, #cond); \
        return 1; \
    } } while (0)

int main(void)
{
    TpurmDevice *dev = tpurmDeviceGet(0);
    CHECK(dev != NULL);
    CHECK(tpurmDeviceHbmSize(dev) >= 64 * 1024 * 1024);

    /* Small ring to force back-pressure (min clamps to 32). */
    TpurmChannel *ch = tpurmChannelCreate(dev, TPURM_CE_ANY, 32);
    CHECK(ch != NULL);

    /* Stress: 10k pushes through a 32-deep ring, strict tracker order. */
    enum { N = 10000, BUF = 4096 };
    static char src[BUF], dst[BUF];
    uint64_t last = 0;
    for (int i = 0; i < N; i++) {
        memset(src, i & 0xff, BUF);
        uint64_t v = tpurmChannelPushCopy(ch, dst, src, BUF);
        CHECK(v == last + 1);
        last = v;
        if ((i & 1023) == 0) {
            CHECK(tpurmChannelWait(ch, v) == TPU_OK);
            CHECK(dst[0] == (char)(i & 0xff));
        }
    }
    CHECK(tpurmChannelWait(ch, last) == TPU_OK);
    CHECK(tpurmChannelCompletedValue(ch) == last);

    /* Error injection latches the channel. */
    tpurmChannelInjectError(ch);
    uint64_t bad = tpurmChannelPushCopy(ch, dst, src, BUF);
    CHECK(bad != 0);
    CHECK(tpurmChannelWait(ch, bad) == TPU_ERR_INVALID_STATE);
    tpurmChannelDestroy(ch);

    /* Transfer engine: extent-split copy through a paged memdesc. */
    /* Build a deliberately non-contiguous source: 8 pages alternating from
     * two separate arenas, so coalescing yields multiple extents. */
    enum { PG = 4096, PAGES = 8 };
    char *arenaA = aligned_alloc(PG, PG * PAGES);
    char *arenaB = aligned_alloc(PG, PG * PAGES);
    CHECK(arenaA && arenaB);
    uint64_t pageAddrs[PAGES];
    for (int i = 0; i < PAGES; i++) {
        char *page = (i % 2 == 0 ? arenaA : arenaB) + (uint64_t)(i / 2) * PG;
        memset(page, 0x10 + i, PG);
        pageAddrs[i] = (uint64_t)(uintptr_t)page;
    }

    /* This exercises the internal transfer engine through the CXL DMA path
     * instead of private headers: register buffer, DMA to device, readback. */
    /* (Direct tpuMemCopy is internal; the public route is the control op —
     *  covered in cxl_conformance_test. Here: device HBM arena copy via
     *  channel public API only.) */
    char *hbm = tpurmDeviceHbmBase(dev);
    TpurmChannel *ce = tpurmChannelCreate(dev, TPURM_CE_ANY, 0);
    CHECK(ce != NULL);
    for (int i = 0; i < PAGES; i++) {
        uint64_t v = tpurmChannelPushCopy(ce, hbm + (uint64_t)i * PG,
                                          (void *)(uintptr_t)pageAddrs[i], PG);
        CHECK(v > 0);
        last = v;
    }
    CHECK(tpurmChannelWait(ce, last) == TPU_OK);
    for (int i = 0; i < PAGES; i++)
        CHECK(hbm[(uint64_t)i * PG] == (char)(0x10 + i));
    tpurmChannelDestroy(ce);

    /* Counters moved. */
    CHECK(tpurmCounterGet("channel_pushes") >= N + PAGES);
    CHECK(tpurmCounterGet("channel_bytes_copied") >= (uint64_t)N * BUF);

    /* Journal captured the injected fault. */
    char buf[8192];
    size_t n = tpurmJournalDump(buf, sizeof(buf));
    CHECK(n > 0);
    CHECK(strstr(buf, "injected CE fault") != NULL);

    free(arenaA);
    free(arenaB);
    printf("channel_test OK\n");
    return 0;
}
