/*
 * REMOTE tier (tpusplit) test: a neighbor chip's HBM as far memory.
 *
 *   1. demote/promote round trip — eviction replicates the span onto a
 *      lender chip, the promote fetches it back over ICI, the pattern
 *      survives, and every ledger (borrowed pages, lent bytes, gauge)
 *      returns to zero when the lease dies.
 *   2. lender-side arena accounting — bytes lent to a borrower are
 *      EXCLUDED from the lender's uvmHbmArenaUsage (vac target picking
 *      must not double-count reclaimable leases).
 *   3. generation fence — a full-device reset between demote and
 *      promote invalidates the lease; the span falls back to HOST with
 *      the pattern intact.
 *   4. peer death mid-read — the lender dies while a borrower promote
 *      is in flight: the dep-chained window cancels, the lease drops,
 *      HOST serves, and zero corrupt bytes reach the completed read.
 *
 * Run with TPUMEM_FAKE_TPU_COUNT=4 (the Makefile does): lender picking
 * needs peers.
 */
#define _GNU_SOURCE
#include <pthread.h>
#include <stdatomic.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>
#include <unistd.h>

#include "tpurm/health.h"
#include "tpurm/reset.h"
#include "tpurm/status.h"
#include "tpurm/tpurm.h"
#include "tpurm/uvm.h"

#define CHECK(cond) do { \
    if (!(cond)) { \
        fprintf(stderr, "FAIL %s:%d: %s\n", __FILE__, __LINE__, #cond); \
        return 1; \
    } } while (0)

/* Internal surfaces (internal.h): registry flips + counter cells. */
void tpuRegistrySet(const char *key, const char *value);
_Atomic uint64_t *tpuCounterRef(const char *name);

#define BUF_BYTES (1u << 20)

static void fill_pattern(uint8_t *p, uint64_t n, uint32_t seed)
{
    for (uint64_t i = 0; i < n; i++)
        p[i] = (uint8_t)((i * 2654435761u + seed) >> 16);
}

static int check_pattern(const uint8_t *p, uint64_t n, uint32_t seed)
{
    for (uint64_t i = 0; i < n; i++)
        if (p[i] != (uint8_t)((i * 2654435761u + seed) >> 16))
            return 0;
    return 1;
}

static uint64_t ctr(const char *name)
{
    return atomic_load(tpuCounterRef(name));
}

/* Migrate to HBM dev 0 and evict the arena so the span demotes through
 * the REMOTE replicate hook.  Returns nonzero on CHECK failure. */
static int demote(UvmVaSpace *vs, uint8_t *buf)
{
    UvmLocation hbm = { .tier = UVM_TIER_HBM, .devInst = 0 };
    CHECK(uvmMigrate(vs, buf, BUF_BYTES, hbm, 0) == TPU_OK);
    UvmResidencyInfo ri;
    CHECK(uvmResidencyInfo(vs, buf, &ri) == TPU_OK);
    CHECK(ri.residentHbm);
    uvmTierEvictBytes(UVM_TIER_HBM, 0, ~0ull >> 1);
    CHECK(uvmResidencyInfo(vs, buf, &ri) == TPU_OK);
    CHECK(!ri.residentHbm);
    CHECK(ri.residentHost);
    return 0;
}

/* ---- 1 + 2: round trip and lender accounting ----------------------- */

static int test_roundtrip(UvmVaSpace *vs)
{
    uint8_t *buf = NULL;
    CHECK(uvmMemAlloc(vs, BUF_BYTES, (void **)&buf) == TPU_OK);
    fill_pattern(buf, BUF_BYTES, 0x5EED);

    uint64_t demotes0 = ctr("tier_remote_demotes");
    uint64_t promotes0 = ctr("tier_remote_promotes");
    CHECK(demote(vs, buf) == 0);

    UvmResidencyInfo ri;
    CHECK(uvmResidencyInfo(vs, buf, &ri) == TPU_OK);
    CHECK(ri.residentRemote);
    uint32_t lender = ri.remoteLenderInst;
    CHECK(lender != 0 && lender < tpurmDeviceCount());
    CHECK(ctr("tier_remote_demotes") > demotes0);
    CHECK(ctr("tier_remote_demote_bytes") >= BUF_BYTES);

    uint64_t borrowed = 0, lent = 0;
    CHECK(uvmTierRemoteStats(0, &borrowed, NULL) == TPU_OK);
    CHECK(borrowed > 0);
    CHECK(uvmTierRemoteStats(lender, NULL, &lent) == TPU_OK);
    CHECK(lent >= BUF_BYTES);

    /* Lender accounting: the lease must NOT shrink the lender's
     * reported free HBM (leases are reclaimable on demand, so vac
     * target picking sees through them). */
    uint64_t freeB = 0, totalB = 0;
    CHECK(uvmHbmArenaUsage(lender, &freeB, &totalB) == TPU_OK);
    CHECK(totalB - freeB < BUF_BYTES);  /* lease alone would exceed it */

    /* Promote: the fetch rides ICI; exclusivity then drops the lease
     * and every ledger drains. */
    UvmLocation hbm = { .tier = UVM_TIER_HBM, .devInst = 0 };
    CHECK(uvmMigrate(vs, buf, BUF_BYTES, hbm, 0) == TPU_OK);
    CHECK(ctr("tier_remote_promotes") > promotes0);
    CHECK(ctr("tier_remote_promote_bytes") >= BUF_BYTES);
    CHECK(uvmResidencyInfo(vs, buf, &ri) == TPU_OK);
    CHECK(ri.residentHbm && !ri.residentRemote);
    CHECK(uvmTierRemoteStats(0, &borrowed, NULL) == TPU_OK);
    CHECK(borrowed == 0);
    CHECK(uvmTierRemoteStats(lender, NULL, &lent) == TPU_OK);
    CHECK(lent == 0);

    UvmLocation host = { .tier = UVM_TIER_HOST, .devInst = 0 };
    CHECK(uvmMigrate(vs, buf, BUF_BYTES, host, 0) == TPU_OK);
    CHECK(check_pattern(buf, BUF_BYTES, 0x5EED));

    CHECK(uvmMemFree(vs, buf) == TPU_OK);
    printf("  roundtrip + lender accounting          ok\n");
    return 0;
}

/* ---- 3: generation fence ------------------------------------------- */

static int test_generation_fence(UvmVaSpace *vs)
{
    uint8_t *buf = NULL;
    CHECK(uvmMemAlloc(vs, BUF_BYTES, (void **)&buf) == TPU_OK);
    fill_pattern(buf, BUF_BYTES, 0xFE4CE);
    CHECK(demote(vs, buf) == 0);
    UvmResidencyInfo ri;
    CHECK(uvmResidencyInfo(vs, buf, &ri) == TPU_OK);
    CHECK(ri.residentRemote);

    /* Reset bumps the process-wide generation: every lease is stale. */
    uint64_t aborts0 = ctr("tier_remote_fence_aborts");
    CHECK(tpurmDeviceReset() == TPU_OK);

    UvmLocation hbm = { .tier = UVM_TIER_HBM, .devInst = 0 };
    CHECK(uvmMigrate(vs, buf, BUF_BYTES, hbm, 0) == TPU_OK);
    CHECK(ctr("tier_remote_fence_aborts") > aborts0);
    CHECK(uvmResidencyInfo(vs, buf, &ri) == TPU_OK);
    CHECK(ri.residentHbm && !ri.residentRemote);

    uint64_t borrowed = ~0ull;
    CHECK(uvmTierRemoteStats(0, &borrowed, NULL) == TPU_OK);
    CHECK(borrowed == 0);

    UvmLocation host = { .tier = UVM_TIER_HOST, .devInst = 0 };
    CHECK(uvmMigrate(vs, buf, BUF_BYTES, host, 0) == TPU_OK);
    CHECK(check_pattern(buf, BUF_BYTES, 0xFE4CE));
    CHECK(uvmMemFree(vs, buf) == TPU_OK);
    printf("  generation fence -> HOST fallback      ok\n");
    return 0;
}

/* ---- 4: peer death mid-read ----------------------------------------

 * The lender chip dies while the borrower's promote is being serviced.
 * Two shapes:
 *   (a) deterministic — mark the lender LOST before the promote: every
 *       PEER_COPY in the window fails/cancels, the fetch aborts, the
 *       HOST copy serves, the read completes with zero corrupt bytes.
 *   (b) racing — a faulting thread hammers demote/promote cycles while
 *       the main thread fires a full-device reset mid-stream; the
 *       pattern must survive every cycle. */

static int test_peer_death(UvmVaSpace *vs)
{
    uint8_t *buf = NULL;
    CHECK(uvmMemAlloc(vs, BUF_BYTES, (void **)&buf) == TPU_OK);
    fill_pattern(buf, BUF_BYTES, 0xDEAD);
    CHECK(demote(vs, buf) == 0);
    UvmResidencyInfo ri;
    CHECK(uvmResidencyInfo(vs, buf, &ri) == TPU_OK);
    CHECK(ri.residentRemote);
    uint32_t lender = ri.remoteLenderInst;

    uint64_t aborts0 = ctr("tier_remote_fence_aborts");
    TpurmDevice *ldev = tpurmDeviceGet(lender);
    CHECK(ldev != NULL);
    tpurmDeviceSetLost(ldev, 1);

    /* Borrower fault in flight against a dead lender: the dep-chained
     * window cancels, the lease drops, HOST serves. */
    UvmLocation hbm = { .tier = UVM_TIER_HBM, .devInst = 0 };
    CHECK(uvmMigrate(vs, buf, BUF_BYTES, hbm, 0) == TPU_OK);
    CHECK(ctr("tier_remote_fence_aborts") > aborts0);
    CHECK(uvmResidencyInfo(vs, buf, &ri) == TPU_OK);
    CHECK(ri.residentHbm && !ri.residentRemote);

    UvmLocation host = { .tier = UVM_TIER_HOST, .devInst = 0 };
    CHECK(uvmMigrate(vs, buf, BUF_BYTES, host, 0) == TPU_OK);
    CHECK(check_pattern(buf, BUF_BYTES, 0xDEAD));   /* zero corrupt bytes */

    tpurmDeviceSetLost(ldev, 0);
    CHECK(uvmMemFree(vs, buf) == TPU_OK);
    printf("  lender lost mid-read -> HOST fallback  ok\n");
    return 0;
}

struct churn_arg {
    UvmVaSpace *vs;
    uint8_t *buf;
    _Atomic int stop;
    _Atomic int failures;
    _Atomic int cycles;
};

static void *churn_thread(void *opaque)
{
    struct churn_arg *a = opaque;
    UvmLocation hbm = { .tier = UVM_TIER_HBM, .devInst = 0 };
    UvmLocation host = { .tier = UVM_TIER_HOST, .devInst = 0 };
    while (!atomic_load(&a->stop)) {
        /* Reset windows can refuse services transiently; only the data
         * integrity check is load-bearing. */
        (void)uvmMigrate(a->vs, a->buf, BUF_BYTES, hbm, 0);
        uvmTierEvictBytes(UVM_TIER_HBM, 0, ~0ull >> 1);
        (void)uvmMigrate(a->vs, a->buf, BUF_BYTES, hbm, 0);
        if (uvmMigrate(a->vs, a->buf, BUF_BYTES, host, 0) == TPU_OK &&
            !check_pattern(a->buf, BUF_BYTES, 0xC0FFEE))
            atomic_fetch_add(&a->failures, 1);
        atomic_fetch_add(&a->cycles, 1);
    }
    return NULL;
}

static int test_reset_race(UvmVaSpace *vs)
{
    struct churn_arg a = { .vs = vs };
    CHECK(uvmMemAlloc(vs, BUF_BYTES, (void **)&a.buf) == TPU_OK);
    fill_pattern(a.buf, BUF_BYTES, 0xC0FFEE);

    pthread_t th;
    CHECK(pthread_create(&th, NULL, churn_thread, &a) == 0);
    /* Two mid-stream full-device resets while the churn is faulting
     * through demote/promote windows. */
    for (int i = 0; i < 2; i++) {
        while (atomic_load(&a.cycles) < (i + 1) * 2)
            usleep(1000);
        (void)tpurmDeviceReset();
    }
    atomic_store(&a.stop, 1);
    pthread_join(th, NULL);
    CHECK(atomic_load(&a.failures) == 0);

    UvmLocation host = { .tier = UVM_TIER_HOST, .devInst = 0 };
    CHECK(uvmMigrate(vs, a.buf, BUF_BYTES, host, 0) == TPU_OK);
    CHECK(check_pattern(a.buf, BUF_BYTES, 0xC0FFEE));
    CHECK(uvmMemFree(vs, a.buf) == TPU_OK);
    printf("  reset race under churn (%d cycles)      ok\n",
           atomic_load(&a.cycles));
    return 0;
}

int main(void)
{
    if (tpurmDeviceCount() < 2) {
        fprintf(stderr, "remote_tier_test: needs TPUMEM_FAKE_TPU_COUNT>=2\n");
        return 1;
    }
    tpuRegistrySet("TPUMEM_REMOTE_TIER", "1");
    /* The fake arenas are small and equally sized: no headroom refusals
     * in the way of the deterministic assertions. */
    tpuRegistrySet("TPUMEM_REMOTE_HEADROOM_PCT", "0");

    UvmVaSpace *vs = NULL;
    if (uvmVaSpaceCreate(&vs) != TPU_OK) {
        fprintf(stderr, "va space create failed\n");
        return 1;
    }
    for (uint32_t d = 0; d < tpurmDeviceCount(); d++)
        uvmRegisterDevice(vs, d);

    int rc = 0;
    rc |= test_roundtrip(vs);
    rc |= test_generation_fence(vs);
    rc |= test_peer_death(vs);
    rc |= test_reset_race(vs);

    uvmVaSpaceDestroy(vs);
    printf(rc ? "remote_tier_test: FAIL\n" : "remote_tier_test: ok\n");
    return rc;
}
