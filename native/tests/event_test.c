/*
 * RM event notification test (NV0005 analog).
 *
 * Walker-style flow against the reference's async event semantics
 * (rmapi/event_notification.c): allocate an NV01_EVENT_OS_EVENT under
 * the subdevice, arm it with NV2080_CTRL_CMD_EVENT_SET_NOTIFICATION,
 * fire an ASYNC CXL DMA, and observe completion by futex-waiting the
 * OS-event word — never polling the transfer tracker.  Also covers
 * SINGLE-shot disarm, validation errors, and teardown.
 */
#include <assert.h>
#include <errno.h>
#include <linux/futex.h>
#include <stdio.h>
#include <string.h>
#include <sys/mman.h>
#include <sys/syscall.h>
#include <time.h>
#include <unistd.h>

#include "tpurm/tpurm.h"

#define CHECK(cond) do { \
    if (!(cond)) { \
        fprintf(stderr, "FAIL %s:%d: %s\n", __FILE__, __LINE__, #cond); \
        return 1; \
    } } while (0)

#define BUF_SIZE (4u * 1024 * 1024)

static TpuStatus rm_alloc(uint32_t hRoot, uint32_t hParent, uint32_t hNew,
                          uint32_t hClass, void *params, uint32_t size)
{
    TpuRmAllocParams p;
    memset(&p, 0, sizeof(p));
    p.hRoot = hClass == TPU_CLASS_ROOT ? hNew : hRoot;
    p.hObjectParent = hClass == TPU_CLASS_ROOT ? hNew : hParent;
    p.hObjectNew = hNew;
    p.hClass = hClass;
    p.pAllocParms = (uint64_t)(uintptr_t)params;
    p.paramsSize = size;
    return tpurmAlloc(&p);
}

static TpuStatus rm_control(uint32_t hClient, uint32_t hObject, uint32_t cmd,
                            void *params, uint32_t size)
{
    TpuRmControlParams p;
    memset(&p, 0, sizeof(p));
    p.hClient = hClient;
    p.hObject = hObject;
    p.cmd = cmd;
    p.params = (uint64_t)(uintptr_t)params;
    p.paramsSize = size;
    return tpurmControl(&p);
}

/* Futex-wait until *word != seen (with a deadline) — the client-side
 * half of the OS-event protocol.  Returns 0 on wake, -1 on timeout. */
static int os_event_wait(TpuOsEvent *ev, uint32_t seen, int timeout_s)
{
    struct timespec deadline, now;
    clock_gettime(CLOCK_REALTIME, &deadline);
    deadline.tv_sec += timeout_s;
    for (;;) {
        uint32_t cur = __atomic_load_n(&ev->signaled, __ATOMIC_ACQUIRE);
        if (cur != seen)
            return 0;
        clock_gettime(CLOCK_REALTIME, &now);
        if (now.tv_sec >= deadline.tv_sec)
            return -1;
        struct timespec rel = { .tv_sec = 1, .tv_nsec = 0 };
        syscall(SYS_futex, &ev->signaled, FUTEX_WAIT, cur, &rel, NULL, 0);
    }
}

int main(void)
{
    const uint32_t hClient = 0xeeee0001, hDevice = 0xeeee0002,
                   hSubdev = 0xeeee0003, hEvent = 0xeeee0004;

    CHECK(rm_alloc(0, 0, hClient, TPU_CLASS_ROOT, NULL, 0) == TPU_OK);
    TpuCtrlAttachIdsParams attach;
    memset(&attach, 0, sizeof(attach));
    attach.gpuIds[0] = TPU_CTRL_ATTACH_ALL_PROBED;
    CHECK(rm_control(hClient, hClient, TPU_CTRL_CMD_GPU_ATTACH_IDS, &attach,
                     sizeof(attach)) == TPU_OK);
    TpuDeviceAllocParams devParams;
    memset(&devParams, 0, sizeof(devParams));
    CHECK(rm_alloc(hClient, hClient, hDevice, TPU_CLASS_DEVICE, &devParams,
                   sizeof(devParams)) == TPU_OK);
    TpuSubdeviceAllocParams subParams = { .subDeviceId = 0 };
    CHECK(rm_alloc(hClient, hDevice, hSubdev, TPU_CLASS_SUBDEVICE,
                   &subParams, sizeof(subParams)) == TPU_OK);

    /* ---- event alloc validation ---- */
    TpuOsEvent os;
    memset(&os, 0, sizeof(os));
    os.rec.status = TPU_NOTIFICATION_STATUS_IN_PROGRESS;
    TpuEventAllocParams ep;
    memset(&ep, 0, sizeof(ep));
    ep.hParentClient = hClient;
    ep.hSrcResource = hSubdev;
    ep.hClass = TPU_CLASS_EVENT_OS;
    ep.notifyIndex = TPU_NOTIFIER_CXL_DMA;
    ep.data = (uint64_t)(uintptr_t)&os;
    /* Wrong size. */
    CHECK(rm_alloc(hClient, hSubdev, hEvent, TPU_CLASS_EVENT_OS, &ep, 4) ==
          TPU_ERR_INVALID_PARAM_STRUCT);
    /* Parent must resolve to a device-backed object. */
    CHECK(rm_alloc(hClient, hClient, hEvent, TPU_CLASS_EVENT_OS, &ep,
                   sizeof(ep)) == TPU_ERR_INVALID_OBJECT_PARENT);
    CHECK(rm_alloc(hClient, hSubdev, hEvent, TPU_CLASS_EVENT_OS, &ep,
                   sizeof(ep)) == TPU_OK);

    /* Unarmed events never fire.  Arm: unknown index is OBJECT_NOT_FOUND,
     * then arm REPEAT for real. */
    TpuCtrlEventSetNotificationParams sn;
    memset(&sn, 0, sizeof(sn));
    sn.event = 77;
    sn.action = TPU_EVENT_ACTION_REPEAT;
    CHECK(rm_control(hClient, hSubdev, TPU_CTRL_CMD_EVENT_SET_NOTIFICATION,
                     &sn, sizeof(sn)) == TPU_ERR_OBJECT_NOT_FOUND);
    sn.event = TPU_NOTIFIER_CXL_DMA;
    sn.action = 99;     /* invalid action */
    CHECK(rm_control(hClient, hSubdev, TPU_CTRL_CMD_EVENT_SET_NOTIFICATION,
                     &sn, sizeof(sn)) == TPU_ERR_INVALID_ARGUMENT);
    sn.action = TPU_EVENT_ACTION_REPEAT;
    CHECK(rm_control(hClient, hSubdev, TPU_CTRL_CMD_EVENT_SET_NOTIFICATION,
                     &sn, sizeof(sn)) == TPU_OK);

    /* ---- async CXL DMA completes the event, no polling ---- */
    uint8_t *buf = mmap(NULL, BUF_SIZE, PROT_READ | PROT_WRITE,
                        MAP_PRIVATE | MAP_ANONYMOUS, -1, 0);
    CHECK(buf != MAP_FAILED);
    memset(buf, 0x5a, BUF_SIZE);
    TpuCtrlRegisterCxlBufferParams reg;
    memset(&reg, 0, sizeof(reg));
    reg.baseAddress = (uint64_t)(uintptr_t)buf;
    reg.size = BUF_SIZE;
    reg.cxlVersion = 2;
    CHECK(rm_control(hClient, hSubdev, TPU_CTRL_CMD_BUS_REGISTER_CXL_BUFFER,
                     &reg, sizeof(reg)) == TPU_OK);

    TpuCtrlCxlP2pDmaRequestParams dma;
    memset(&dma, 0, sizeof(dma));
    dma.cxlBufferHandle = reg.bufferHandle;
    dma.size = BUF_SIZE;
    dma.flags = TPU_CXL_DMA_FLAG_CXL_TO_DEV | TPU_CXL_DMA_FLAG_ASYNC;
    CHECK(rm_control(hClient, hSubdev, TPU_CTRL_CMD_BUS_CXL_P2P_DMA_REQUEST,
                     &dma, sizeof(dma)) == TPU_OK);

    /* Completion arrives via futex wake; the notification record is
     * filled timestamp/info32/info16 then status (release-ordered). */
    CHECK(os_event_wait(&os, 0, 10) == 0);
    CHECK(__atomic_load_n(&os.rec.status, __ATOMIC_ACQUIRE) ==
          TPU_NOTIFICATION_STATUS_DONE_SUCCESS);
    CHECK(os.rec.info32 == 1);
    CHECK(os.rec.timeStampNanoseconds[0] != 0 ||
          os.rec.timeStampNanoseconds[1] != 0);
    uint32_t fired = os.signaled;
    CHECK(fired >= 1);

    /* ---- SINGLE action disarms after one delivery ---- */
    sn.action = TPU_EVENT_ACTION_SINGLE;
    CHECK(rm_control(hClient, hSubdev, TPU_CTRL_CMD_EVENT_SET_NOTIFICATION,
                     &sn, sizeof(sn)) == TPU_OK);
    CHECK(rm_control(hClient, hSubdev, TPU_CTRL_CMD_BUS_CXL_P2P_DMA_REQUEST,
                     &dma, sizeof(dma)) == TPU_OK);
    CHECK(os_event_wait(&os, fired, 10) == 0);
    uint32_t after_single = os.signaled;
    /* Now disarmed: another DMA must NOT signal. */
    CHECK(rm_control(hClient, hSubdev, TPU_CTRL_CMD_BUS_CXL_P2P_DMA_REQUEST,
                     &dma, sizeof(dma)) == TPU_OK);
    CHECK(os_event_wait(&os, after_single, 2) == -1);

    /* ---- teardown: freeing the event object unregisters it ---- */
    sn.action = TPU_EVENT_ACTION_REPEAT;
    CHECK(rm_control(hClient, hSubdev, TPU_CTRL_CMD_EVENT_SET_NOTIFICATION,
                     &sn, sizeof(sn)) == TPU_OK);
    TpuRmFreeParams fp;
    memset(&fp, 0, sizeof(fp));
    fp.hRoot = hClient;
    fp.hObjectParent = hSubdev;
    fp.hObjectOld = hEvent;
    CHECK(tpurmFree(&fp) == TPU_OK);
    uint32_t before = os.signaled;
    CHECK(rm_control(hClient, hSubdev, TPU_CTRL_CMD_BUS_CXL_P2P_DMA_REQUEST,
                     &dma, sizeof(dma)) == TPU_OK);
    CHECK(os_event_wait(&os, before, 2) == -1);

    TpuCtrlUnregisterCxlBufferParams unreg = { .bufferHandle =
                                                   reg.bufferHandle };
    CHECK(rm_control(hClient, hSubdev,
                     TPU_CTRL_CMD_BUS_UNREGISTER_CXL_BUFFER, &unreg,
                     sizeof(unreg)) == TPU_OK);
    memset(&fp, 0, sizeof(fp));
    fp.hRoot = hClient;
    fp.hObjectOld = hClient;
    CHECK(tpurmFree(&fp) == TPU_OK);
    munmap(buf, BUF_SIZE);
    printf("event_test: all assertions passed\n");
    return 0;
}
