/*
 * tpuhot test: tracker decay, thrash PIN exemption from BOTH eviction
 * paths (allocation-pressure uvmLruPopVictim and the spine's
 * byte-target uvmTierEvictBytes), pin lapse, THROTTLE boundedness,
 * precision-gated prefetch growth/shrink, hotness-fed victim
 * reordering, and the hot.decide inject site's EXACT reconciliation
 * (hits == hot_inject_skips).
 *
 * Single fake device with a 16 MB arena (set below before the engine
 * initializes) so eviction pressure is cheap to create.
 */
#define _GNU_SOURCE
#include <stdatomic.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>
#include <time.h>
#include <unistd.h>

#include "tpurm/hot.h"
#include "tpurm/inject.h"
#include "tpurm/status.h"
#include "tpurm/tpurm.h"
#include "tpurm/uvm.h"

#define CHECK(cond) do { \
    if (!(cond)) { \
        fprintf(stderr, "FAIL %s:%d: %s\n", __FILE__, __LINE__, #cond); \
        return 1; \
    } } while (0)

#define MB (1024ull * 1024)
#define BLOCK (2 * MB)

/* Internal surfaces the test drives directly (exported symbols;
 * declared by hand like the other native tests do). */
void tpuRegistrySet(const char *key, const char *value);
uint64_t uvmTierEvictBytes(uint32_t tier, uint32_t devInst,
                           uint64_t bytes);

/* Byte target that evicts roughly ONE block: current free + one block
 * (uvmTierEvictBytes stops as soon as the arena can take the target). */
static uint64_t one_block_target(void)
{
    uint64_t freeB = 0, total = 0;
    if (uvmHbmArenaUsage(0, &freeB, &total) != TPU_OK)
        return BLOCK;
    return freeB + BLOCK;
}

static void sleep_ms(unsigned ms)
{
    struct timespec ts = { .tv_sec = ms / 1000,
                           .tv_nsec = (long)(ms % 1000) * 1000000L };
    nanosleep(&ts, NULL);
}

static uint64_t now_ns(void)
{
    struct timespec ts;
    clock_gettime(CLOCK_MONOTONIC, &ts);
    return (uint64_t)ts.tv_sec * 1000000000ull + (uint64_t)ts.tv_nsec;
}

static const UvmLocation HBM0 = { UVM_TIER_HBM, 0 };
static const UvmLocation HOSTLOC = { UVM_TIER_HOST, 0 };

/* Trip the thrash detector on [p, p+len): deviceward, hostward,
 * deviceward — two direction alternations (hot_thrash_count=2 below). */
static int thrash(UvmVaSpace *vs, void *p, uint64_t len)
{
    CHECK(uvmMigrate(vs, p, len, HBM0, 0) == TPU_OK);
    CHECK(uvmMigrate(vs, p, len, HOSTLOC, 0) == TPU_OK);
    CHECK(uvmMigrate(vs, p, len, HBM0, 0) == TPU_OK);
    return 0;
}

/* ---- 1. tracker feed + decay -------------------------------------- */

static int test_tracker_decay(UvmVaSpace *vs)
{
    tpuRegistrySet("TPUMEM_HOT_DECAY_MS", "50");
    void *p;
    CHECK(uvmMemAlloc(vs, BLOCK, &p) == TPU_OK);
    memset(p, 0xA1, BLOCK);                       /* CPU-fault feed */
    CHECK(uvmDeviceAccess(vs, 0, p, BLOCK, 0) == TPU_OK);
    uint64_t hot = tpurmHotSpanScore((uint64_t)(uintptr_t)p, BLOCK);
    CHECK(hot > 0);
    CHECK(tpurmHotDeviceScore(0) > 0);
    /* Four half-lives: the decayed score must drop to <= 1/8. */
    sleep_ms(210);
    uint64_t cold = tpurmHotSpanScore((uint64_t)(uintptr_t)p, BLOCK);
    CHECK(cold <= hot / 8);
    CHECK(uvmMemFree(vs, p) == TPU_OK);
    tpuRegistrySet("TPUMEM_HOT_DECAY_MS", "250");
    return 0;
}

/* ---- 2. thrash PIN + exemption from both eviction paths ----------- */

static int test_pin_exemption(UvmVaSpace *vs)
{
    tpuRegistrySet("TPUMEM_HOT_THRASH_COUNT", "2");
    tpuRegistrySet("TPUMEM_HOT_THRASH_WINDOW_MS", "10000");
    tpuRegistrySet("TPUMEM_HOT_PIN_MS", "60000");

    uint64_t pins0 = tpurmCounterGet("tpurm_hot_pins");
    void *a;
    CHECK(uvmMemAlloc(vs, BLOCK, &a) == TPU_OK);
    memset(a, 0x5A, BLOCK);
    CHECK(thrash(vs, a, BLOCK) == 0);
    CHECK(tpurmCounterGet("tpurm_hot_pins") == pins0 + 1);
    CHECK(tpurmCounterGet("tpurm_hot_thrash_pages") > 0);
    UvmResidencyInfo info;
    CHECK(uvmResidencyInfo(vs, a, &info) == TPU_OK);
    CHECK(info.pinnedTier == (int32_t)UVM_TIER_HBM);
    CHECK(info.residentHbm);

    /* Path 1 — allocation-pressure eviction (uvmLruPopVictim via the
     * arena walk): flood the 16 MB arena; the pinned block must keep
     * its residency while the flood evicts itself. */
    void *flood;
    CHECK(uvmMemAlloc(vs, 16 * MB, &flood) == TPU_OK);
    for (uint64_t off = 0; off < 16 * MB; off += BLOCK)
        CHECK(uvmMigrate(vs, (char *)flood + off, BLOCK, HBM0, 0) ==
              TPU_OK);
    CHECK(uvmResidencyInfo(vs, a, &info) == TPU_OK);
    CHECK(info.residentHbm);          /* pinned: never evicted */
    CHECK(info.pinnedTier == (int32_t)UVM_TIER_HBM);

    /* Path 2 — the spine's byte-target evictor (OP_TIER_EVICT body):
     * ask for the whole arena; everything unpinned goes, the pinned
     * block stays. */
    uvmTierEvictBytes(UVM_TIER_HBM, 0, 16 * MB);
    CHECK(uvmResidencyInfo(vs, a, &info) == TPU_OK);
    CHECK(info.residentHbm);
    UvmResidencyInfo finfo;
    CHECK(uvmResidencyInfo(vs, flood, &finfo) == TPU_OK);
    CHECK(!finfo.residentHbm);        /* unpinned flood was evictable */

    CHECK(uvmMemFree(vs, flood) == TPU_OK);
    CHECK(uvmMemFree(vs, a) == TPU_OK);
    return 0;
}

/* ---- 3. pin lapse -------------------------------------------------- */

static int test_pin_lapse(UvmVaSpace *vs)
{
    tpuRegistrySet("TPUMEM_HOT_PIN_MS", "80");
    void *c;
    CHECK(uvmMemAlloc(vs, BLOCK, &c) == TPU_OK);
    memset(c, 0xC3, BLOCK);
    CHECK(thrash(vs, c, BLOCK) == 0);
    UvmResidencyInfo info;
    CHECK(uvmResidencyInfo(vs, c, &info) == TPU_OK);
    CHECK(info.pinnedTier == (int32_t)UVM_TIER_HBM);

    sleep_ms(120);                    /* pin lapses: no wedge possible */
    CHECK(uvmResidencyInfo(vs, c, &info) == TPU_OK);
    CHECK(info.pinnedTier == -1);
    /* And the block is evictable again. */
    uvmTierEvictBytes(UVM_TIER_HBM, 0, 16 * MB);
    CHECK(uvmResidencyInfo(vs, c, &info) == TPU_OK);
    CHECK(!info.residentHbm);
    /* Data integrity across pin + eviction. */
    CHECK(((volatile unsigned char *)c)[123] == 0xC3);
    CHECK(uvmMemFree(vs, c) == TPU_OK);
    tpuRegistrySet("TPUMEM_HOT_PIN_MS", "300");
    return 0;
}

/* ---- 4. THROTTLE: decided without headroom, bounded, expires ------ */

static int test_throttle(UvmVaSpace *vs)
{
    tpuRegistrySet("TPUMEM_HOT_PIN", "0");      /* force THROTTLE arm */
    tpuRegistrySet("TPUMEM_HOT_THROTTLE_US", "20000");
    tpuRegistrySet("TPUMEM_HOT_THROTTLE_MS", "400");

    uint64_t th0 = tpurmCounterGet("tpurm_hot_throttles");
    void *d;
    CHECK(uvmMemAlloc(vs, BLOCK, &d) == TPU_OK);
    memset(d, 0xD4, BLOCK);
    CHECK(thrash(vs, d, BLOCK) == 0);
    CHECK(tpurmCounterGet("tpurm_hot_throttles") == th0 + 1);
    UvmResidencyInfo info;
    CHECK(uvmResidencyInfo(vs, d, &info) == TPU_OK);
    CHECK(info.pinnedTier == -1);     /* throttle, not pin */

    /* A fault service on the throttled block is delayed (counted) but
     * BOUNDED: it completes, and well under a second. */
    uint64_t delays0 = tpurmCounterGet("tpurm_hot_throttle_delays");
    uint64_t t0 = now_ns();
    ((volatile char *)d)[0] = 1;      /* CPU write fault (block on HBM) */
    uint64_t dt = now_ns() - t0;
    CHECK(tpurmCounterGet("tpurm_hot_throttle_delays") > delays0);
    CHECK(dt < 2000000000ull);        /* bounded: no wedge */

    /* The hint expires on its own: past hot_throttle_ms no further
     * service is delayed.  Raise the detector threshold first — the
     * CPU fault above plus the re-migration below are themselves
     * direction alternations and would legitimately re-trip it. */
    tpuRegistrySet("TPUMEM_HOT_THRASH_COUNT", "100");
    sleep_ms(450);
    CHECK(uvmMigrate(vs, d, BLOCK, HBM0, 0) == TPU_OK);
    uint64_t delays1 = tpurmCounterGet("tpurm_hot_throttle_delays");
    ((volatile char *)d)[4096] = 2;
    CHECK(tpurmCounterGet("tpurm_hot_throttle_delays") == delays1);

    CHECK(uvmMemFree(vs, d) == TPU_OK);
    tpuRegistrySet("TPUMEM_HOT_PIN", "1");
    tpuRegistrySet("TPUMEM_HOT_THRASH_COUNT", "2");
    return 0;
}

/* ---- 5. precision-gated prefetch growth and shrink ----------------- */

static int test_prefetch_governor(UvmVaSpace *vs)
{
    tpuRegistrySet("TPUMEM_HOT_PREFETCH_MIN_SAMPLES", "4");
    tpuRegistrySet("TPUMEM_HOT_PREFETCH_START", "4");
    uint64_t ps = 64 * 1024;          /* uvm_page_size default */

    /* GROW: sequential single-page device accesses — speculation lands
     * just ahead of the stream, the next access hits it, precision
     * stays high, the cap doubles. */
    uint64_t grown0 = tpurmCounterGet("tpurm_hot_prefetch_grown");
    void *g;
    CHECK(uvmMemAlloc(vs, BLOCK, &g) == TPU_OK);
    memset(g, 0x11, BLOCK);
    for (uint64_t off = 0; off < BLOCK; off += ps)
        CHECK(uvmDeviceAccess(vs, 0, (char *)g + off, ps, 0) == TPU_OK);
    CHECK(tpurmCounterGet("uvm_prefetch_hits") > 0);
    CHECK(tpurmCounterGet("tpurm_hot_prefetch_grown") > grown0);
    CHECK(uvmMemFree(vs, g) == TPU_OK);

    /* SHRINK: strided accesses speculate pages nothing ever touches;
     * evicting them untouched counts useless, precision collapses, the
     * cap halves. */
    uint64_t shrunk0 = tpurmCounterGet("tpurm_hot_prefetch_shrunk");
    void *s;
    CHECK(uvmMemAlloc(vs, 4 * BLOCK, &s) == TPU_OK);
    memset(s, 0x22, 4 * BLOCK);
    for (int round = 0; round < 4; round++) {
        for (uint64_t off = 0; off < 4 * BLOCK; off += 8 * ps)
            CHECK(uvmDeviceAccess(vs, 0, (char *)s + off, ps, 0) ==
                  TPU_OK);
        uvmTierEvictBytes(UVM_TIER_HBM, 0, 16 * MB);
    }
    CHECK(tpurmCounterGet("uvm_prefetch_useless") > 0);
    CHECK(tpurmCounterGet("tpurm_hot_prefetch_shrunk") > shrunk0);
    CHECK(uvmMemFree(vs, s) == TPU_OK);
    return 0;
}

/* ---- 6. hotness-fed victim reordering ------------------------------ */

static int test_victim_coldness(UvmVaSpace *vs)
{
    uvmTierEvictBytes(UVM_TIER_HBM, 0, 16 * MB);   /* clean slate */
    void *hot, *cold;
    CHECK(uvmMemAlloc(vs, BLOCK, &hot) == TPU_OK);
    CHECK(uvmMemAlloc(vs, BLOCK, &cold) == TPU_OK);
    memset(hot, 0x33, BLOCK);
    memset(cold, 0x44, BLOCK);
    CHECK(uvmMigrate(vs, hot, BLOCK, HBM0, 0) == TPU_OK);
    CHECK(uvmMigrate(vs, cold, BLOCK, HBM0, 0) == TPU_OK);
    /* Heat the OLDER block hard, then give the newer one a single
     * light touch so it sits at the LRU's WARM end: positionally the
     * hot block is now the next victim — only the coldness scan saves
     * it. */
    for (int i = 0; i < 16; i++)
        CHECK(uvmDeviceAccess(vs, 0, hot, BLOCK, 0) == TPU_OK);
    CHECK(uvmDeviceAccess(vs, 0, cold, 64 * 1024, 0) == TPU_OK);

    uint64_t reorders0 = tpurmCounterGet("tier_hot_victim_reorders");
    uvmTierEvictBytes(UVM_TIER_HBM, 0, one_block_target());
    UvmResidencyInfo hi, ci;
    CHECK(uvmResidencyInfo(vs, hot, &hi) == TPU_OK);
    CHECK(uvmResidencyInfo(vs, cold, &ci) == TPU_OK);
    CHECK(hi.residentHbm);            /* hot survived its position */
    CHECK(!ci.residentHbm);           /* genuinely-cold block evicted */
    CHECK(tpurmCounterGet("tier_hot_victim_reorders") > reorders0);

    /* Scorer off (hot_victim_scan=0): byte-for-byte positional LRU —
     * the same shape (hot block at the LRU head by position, cold at
     * the tail) now evicts the HOT block first. */
    tpuRegistrySet("TPUMEM_HOT_VICTIM_SCAN", "0");
    CHECK(uvmMigrate(vs, cold, BLOCK, HBM0, 0) == TPU_OK);
    for (int i = 0; i < 16; i++)
        CHECK(uvmDeviceAccess(vs, 0, hot, BLOCK, 0) == TPU_OK);
    CHECK(uvmDeviceAccess(vs, 0, cold, 64 * 1024, 0) == TPU_OK);
    uvmTierEvictBytes(UVM_TIER_HBM, 0, one_block_target());
    CHECK(uvmResidencyInfo(vs, hot, &hi) == TPU_OK);
    CHECK(!hi.residentHbm);           /* positional order honored */
    tpuRegistrySet("TPUMEM_HOT_VICTIM_SCAN", "8");

    CHECK(uvmMemFree(vs, hot) == TPU_OK);
    CHECK(uvmMemFree(vs, cold) == TPU_OK);
    return 0;
}

/* ---- 7. hot.decide inject: degrade-to-no-op + EXACT invariant ------ */

static int test_inject_decide(UvmVaSpace *vs)
{
    tpuRegistrySet("TPUMEM_HOT_THRASH_COUNT", "2");
    uint64_t pins0 = tpurmCounterGet("tpurm_hot_pins");
    uint64_t th0 = tpurmCounterGet("tpurm_hot_throttles");

    CHECK(tpurmInjectConfigure(TPU_INJECT_SITE_HOT_DECIDE,
                               TPU_INJECT_NTH, 1, 1, 0) == TPU_OK);
    void *p;
    CHECK(uvmMemAlloc(vs, BLOCK, &p) == TPU_OK);
    memset(p, 0x77, BLOCK);
    CHECK(thrash(vs, p, BLOCK) == 0); /* decision skipped: no hint */
    UvmResidencyInfo info;
    CHECK(uvmResidencyInfo(vs, p, &info) == TPU_OK);
    CHECK(info.pinnedTier == -1);
    CHECK(tpurmCounterGet("tpurm_hot_pins") == pins0);
    CHECK(tpurmCounterGet("tpurm_hot_throttles") == th0);
    /* Forward progress under a 100%-hit site: services still complete
     * (degrade-to-no-op, nothing retries, nothing wedges). */
    ((volatile char *)p)[0] = 1;
    tpurmInjectDisable(TPU_INJECT_SITE_HOT_DECIDE);
    CHECK(uvmMemFree(vs, p) == TPU_OK);
    return 0;
}

int main(void)
{
    /* Small arena BEFORE the engine initializes: eviction pressure is
     * the whole test.  Policies under test get fast windows. */
    setenv("TPUMEM_FAKE_HBM_MB", "16", 1);
    setenv("TPUMEM_HOT_THRASH_COUNT", "2", 1);
    setenv("TPUMEM_HOT_THRASH_WINDOW_MS", "10000", 1);

    UvmVaSpace *vs;
    if (uvmVaSpaceCreate(&vs) != TPU_OK) {
        fprintf(stderr, "vaspace create failed\n");
        return 1;
    }
    if (uvmRegisterDevice(vs, 0) != TPU_OK) {
        fprintf(stderr, "no fake device 0\n");
        return 1;
    }

    struct { const char *name; int (*fn)(UvmVaSpace *); } tests[] = {
        { "tracker_decay", test_tracker_decay },
        { "pin_exemption", test_pin_exemption },
        { "pin_lapse", test_pin_lapse },
        { "throttle_bounded", test_throttle },
        { "prefetch_governor", test_prefetch_governor },
        { "victim_coldness", test_victim_coldness },
        { "inject_decide", test_inject_decide },
    };
    for (size_t i = 0; i < sizeof(tests) / sizeof(tests[0]); i++) {
        if (tests[i].fn(vs) != 0) {
            fprintf(stderr, "hot_test: %s FAILED\n", tests[i].name);
            return 1;
        }
        printf("  hot test %-24s ok\n", tests[i].name);
    }

    /* EXACT reconciliation: every hot.decide hit degraded exactly one
     * decision to a no-op — across the WHOLE run. */
    uint64_t evals = 0, hits = 0;
    tpurmInjectCounts(TPU_INJECT_SITE_HOT_DECIDE, &evals, &hits);
    TpuHotStats st;
    tpurmHotStatsGet(&st);
    if (hits != st.injectSkips ||
        hits != tpurmCounterGet("hot_inject_skips")) {
        fprintf(stderr,
                "hot.decide reconciliation: hits=%llu skips=%llu "
                "counter=%llu\n",
                (unsigned long long)hits,
                (unsigned long long)st.injectSkips,
                (unsigned long long)tpurmCounterGet("hot_inject_skips"));
        return 1;
    }
    if (hits == 0) {
        fprintf(stderr, "hot.decide never hit (armed window inert)\n");
        return 1;
    }
    printf("  hot test %-24s ok (hits=%llu == skips)\n",
           "inject_reconciliation", (unsigned long long)hits);

    /* Render smoke: the hotness node serves and carries the stats. */
    char buf[16384];
    size_t n = tpurmProcfsRead("driver/tpurm/hotness", buf,
                               sizeof(buf) - 1);
    buf[n] = 0;
    if (n == 0 || !strstr(buf, "pins:") || !strstr(buf, "dev0_score:")) {
        fprintf(stderr, "hotness node render broken:\n%s\n", buf);
        return 1;
    }

    uvmVaSpaceDestroy(vs);
    printf("hot_test: all ok\n");
    return 0;
}
